#!/usr/bin/env python
"""Static durability-coverage check (tier-1).

The checkpoint layer is only as durable as its WEAKEST state writer: a
new module that calls ``os.replace``/``os.rename`` directly looks
atomic in code review but ships the classic torn-file bug — without an
fsync of the tmp file the rename can land before the data blocks do,
and without an fsync of the parent directory the rename itself may not
survive a crash.  ``pwasm_tpu/utils/fsio.py`` holds the one audited
fsync-then-replace implementation; this check greps ``pwasm_tpu/``,
``qa/`` and ``bench.py`` for rename-publish entry points and fails
when any hit lives outside that module (or a registered, justified
exemption) — forcing the author of a new state writer to route through
``fsio.replace_durable``/``write_durable_*`` or to argue the exemption
in the registry below.

Registry semantics, per module (repo-relative path):

- ``impl:<why>``    the audited implementation itself (must contain
                    both ``os.fsync`` and ``os.replace`` — verified);
- ``exempt:<why>``  deliberately undurable (scratch files whose loss
                    costs a rebuild, not correctness) — the
                    justification is the registry entry itself.

Run standalone (``python qa/check_durability.py``, exit 1 on
violations) or through ``tests/test_durability.py``.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# rename-publish entry points: the calls that make a tmp file LOOK
# atomically published.  shutil.move is included because it degrades
# to copy+rename across filesystems — same review trap, worse window.
PATTERNS = re.compile(
    r"os\.replace\s*\(|os\.rename\s*\(|shutil\.move\s*\(")

# raw fsync call sites (ISSUE 9): the journal/spool rule.  An append
# log someone hand-rolls with its own os.fsync looks durable in review
# but typically misses the directory-entry fsync on creation and the
# torn-tail read contract; ``fsio.DurableAppender`` is the audited
# appender, so a bare fsync outside fsio.py needs the same registry
# argument a bare replace does.
FSYNC_PATTERNS = re.compile(r"os\.fsync\s*\(")

_FSIO = "pwasm_tpu/utils/fsio.py"

# module -> justification (see module docstring for the grammar)
REGISTRY = {
    _FSIO: "impl: the one audited fsync-then-replace "
           "(write tmp -> fsync tmp -> os.replace -> fsync parent dir)",
    "tests/test_stream.py":
        "exempt: simulates an EXTERNAL writer's log rotation "
        "(logrotate-style replace of the tailed PAF) to exercise "
        "FollowReader's inode tracking — deliberately not a durable "
        "publish of repo state",
    "pwasm_tpu/obs/events.py":
        "exempt: --log-json-max-bytes rotation renames the CURRENT "
        "event log aside (FILE -> FILE.1) inside the never-raises "
        "emit path — best-effort observability whose loss costs log "
        "lines, not correctness; an fsync here would put disk-flush "
        "stalls on the signal-drain emit path",
}

# fsync registry: modules allowed a raw os.fsync.  fsio.py is the impl
# (replace pattern + DurableAppender); the two exemptions fsync LIVE
# file handles they own — in-place durability points, not publishes —
# where the replace pattern structurally cannot apply.
FSYNC_REGISTRY = {
    _FSIO: "impl: write_durable_* tmp fsync, truncate_durable, and "
           "DurableAppender (the audited fsync-per-record appender "
           "journal writers must route through)",
    "pwasm_tpu/cli.py":
        "exempt: the ckpt prelude fsyncs the OPEN report stream in "
        "place before recording its byte offset — an append-stream "
        "durability point on a handle the run owns, not a publish",
    "pwasm_tpu/native/__init__.py":
        "exempt: fsyncs the freshly compiled tmp artifact on its own "
        "fd before fsio.replace_durable (replace_durable's documented "
        "caller-owns-the-tmp-fsync contract)",
}

# directories scanned, relative to the repo root
SCAN_ROOTS = ("pwasm_tpu", "qa", "tests")
SCAN_FILES = ("bench.py", "tpu_smoke.py")


def _iter_py(root: str):
    for base in SCAN_ROOTS:
        top = os.path.join(root, base)
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)
    for fn in SCAN_FILES:
        path = os.path.join(root, fn)
        if os.path.exists(path):
            yield path


def find_hits(root: str = REPO) -> list[tuple[str, int, str]]:
    """Every (relpath, lineno, line) matching PATTERNS, comment-only
    lines skipped."""
    hits = []
    for path in _iter_py(root):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f, 1):
                if line.lstrip().startswith("#"):
                    continue
                if PATTERNS.search(line):
                    hits.append((rel, i, line.strip()))
    return hits


def find_fsync_hits(root: str = REPO) -> list[tuple[str, int, str]]:
    """Every (relpath, lineno, line) with a raw ``os.fsync`` call,
    comment-only lines skipped."""
    hits = []
    for path in _iter_py(root):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f, 1):
                if line.lstrip().startswith("#"):
                    continue
                if FSYNC_PATTERNS.search(line):
                    hits.append((rel, i, line.strip()))
    return hits


def find_unregistered(root: str = REPO) -> list[str]:
    """Human-readable violation lines; empty = covered."""
    out = []
    for rel, lineno, line in find_hits(root):
        if rel not in REGISTRY:
            out.append(f"{rel}:{lineno}: rename-publish outside the "
                       f"durable-write module ({_FSIO}): {line}")
    for rel, lineno, line in find_fsync_hits(root):
        if rel not in FSYNC_REGISTRY:
            out.append(f"{rel}:{lineno}: raw os.fsync outside the "
                       f"durable-write module ({_FSIO}) — journal/"
                       "spool writers route through fsio "
                       "(DurableAppender / write_durable_*): "
                       f"{line}")
    return out


def stale_registry_entries(root: str = REPO) -> list[str]:
    """Registry rows whose module no longer has any hit (or vanished)."""
    live = {rel for rel, _l, _s in find_hits(root)}
    out = [rel for rel in REGISTRY if rel not in live]
    live_f = {rel for rel, _l, _s in find_fsync_hits(root)}
    out += [f"{rel} (fsync)" for rel in FSYNC_REGISTRY
            if rel not in live_f]
    return out


def impl_self_check(root: str = REPO) -> list[str]:
    """The registered implementation must actually contain the fsync —
    a refactor that drops it would otherwise pass the gate."""
    path = os.path.join(root, _FSIO)
    try:
        with open(path, encoding="utf-8") as f:
            src = f.read()
    except OSError:
        return [f"{_FSIO}: missing (the durable-write module itself)"]
    out = []
    for needle in ("os.fsync", "os.replace"):
        if needle not in src:
            out.append(f"{_FSIO}: no {needle} call — the audited "
                       "pattern is gone")
    if "class DurableAppender" not in src:
        out.append(f"{_FSIO}: no DurableAppender — the audited "
                   "fsync-per-record appender (journal writers' "
                   "route) is gone")
    return out


def main() -> int:
    bad = find_unregistered()
    stale = stale_registry_entries()
    broken = impl_self_check()
    for line in bad + broken:
        print(line, file=sys.stderr)
    for rel in stale:
        print(f"{rel}: stale registry entry (no rename-publish left — "
              "remove it)", file=sys.stderr)
    if bad:
        print(f"\n{len(bad)} rename-publish call(s) outside "
              "pwasm_tpu/utils/fsio.py.  Route state writes through "
              "fsio.replace_durable / write_durable_* (fsync tmp, "
              "replace, fsync dir) or register a justified exemption "
              "in qa/check_durability.py.", file=sys.stderr)
    return 1 if (bad or stale or broken) else 0


if __name__ == "__main__":
    sys.exit(main())
