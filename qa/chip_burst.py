#!/usr/bin/env python
"""One-shot chip burst: run every chip-gated validation/measurement in
priority order the moment the tunnel is healthy, so a short window is
never wasted (the tunnel goes down for multi-hour stretches — see
ROUND4.md).  Results land in ``chip_burst/`` as JSONL + logs; the
driver-style artifacts (BENCH_ALL.json, TPU_SMOKE.json) are refreshed
by the full bench step exactly as a bare ``python bench.py`` would.

Order: smoke (gate) -> full bench table -> cfg4 column-tile sweep ->
cfg2 Iy-chain A/B -> cfg7 on chip -> cfg4 profiled launch.  Exit 3 =
backend down or not a real TPU (nothing ran); exit 130 = interrupted
(Ctrl-C while blocking on ``--wait`` — the conventional 128+SIGINT
status, not a traceback); exit 0 = burst completed (individual steps
may still record failures in the JSONL).

``--wait[=S]``: instead of exiting 3 on a down tunnel, block (bounded
by S seconds, default 3600) on the resilience layer's capped-
exponential re-probe schedule (``resilience.health.wait_for_backend``)
and fire the burst in the FIRST healthy window — the mode a cron
driver wants during a flapping-tunnel stretch.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "chip_burst")
sys.path.insert(0, REPO)

from bench import _json_rows  # noqa: E402  (one shared stdout parser)


# probe TUNING passes through the scrub: these bound the health checks
# (how long a probe may take / how long a healthy verdict is cached) —
# they change no run behavior or result bytes, and on a slow tunnel the
# operator's raised PWASM_DEVICE_PROBE_TIMEOUT is the difference
# between a burst firing and a spurious exit 3.  NB PWASM_DEVICE_PROBE
# itself (=0 disables probing entirely) IS run behavior and stays
# scrubbed.
_SCRUB_KEEP = ("PWASM_DEVICE_PROBE_TIMEOUT", "PWASM_DEVICE_PROBE_TTL",
               "PWASM_BENCH_PROBE_TIMEOUT")


def _scrub_env(environ) -> dict:
    """Each step fully controls its PWASM knobs: ANY run-behavior
    ``PWASM_*`` value lingering in the operator's shell — a
    ``PWASM_INJECT_FAULTS`` left armed after a chaos session, a
    ``PWASM_HOST_COLUMNAR=0`` escape hatch, a ``PWASM_BENCH_CONFIG``
    pin — would silently poison every burst step, so the scrub strips
    the whole ``PWASM_`` namespace except the probe-tuning allowlist
    (steps re-add exactly what they need via ``env_extra``).
    Backend-selecting vars (``JAX_*``, ``PALLAS_*``) pass through:
    they are what point the burst at the chip."""
    return {k: v for k, v in environ.items()
            if not k.startswith("PWASM_") or k in _SCRUB_KEEP}


def _run(name: str, env_extra: dict, args: list[str], timeout: float,
         log: list) -> dict:
    env = _scrub_env(os.environ)
    env.update({k: str(v) for k, v in env_extra.items()})
    t0 = time.time()
    try:
        r = subprocess.run([sys.executable] + args, env=env, cwd=REPO,
                           capture_output=True, text=True,
                           timeout=timeout)
        rec = {"step": name, "rc": r.returncode,
               "rows": _json_rows(r.stdout),
               "wall_s": round(time.time() - t0, 1)}
        with open(os.path.join(OUT, f"{name}.stderr"), "w") as f:
            f.write(r.stderr)
    except subprocess.TimeoutExpired as e:
        rec = {"step": name, "rc": None, "rows": [],
               "wall_s": round(time.time() - t0, 1), "timeout": True}
        with open(os.path.join(OUT, f"{name}.stderr"), "w") as f:
            for part in (e.stdout, e.stderr):  # partial output is the
                if part:                       # only hang diagnostic
                    f.write(part if isinstance(part, str)
                            else part.decode("utf-8", "replace"))
    except Exception as e:
        # a spawn failure must cost one step record, never the rest of
        # a scarce healthy-tunnel window
        rec = {"step": name, "rc": None, "rows": [],
               "wall_s": round(time.time() - t0, 1),
               "error": f"{type(e).__name__}: {e}"}
    log.append(rec)
    print(json.dumps(rec), flush=True)
    with open(os.path.join(OUT, "burst.jsonl"), "a") as f:
        f.write(json.dumps(rec) + "\n")
    return rec


def _parse_wait(argv: list[str]) -> float | None:
    """``--wait`` / ``--wait=S`` -> wait budget in seconds (default
    3600); None when not asked to wait.  Raises SystemExit(2) on a
    malformed value — a silent typo must not turn a bounded wait into
    an immediate exit 3."""
    for a in argv:
        if a == "--wait":
            return 3600.0
        if a.startswith("--wait="):
            try:
                s = float(a.split("=", 1)[1])
                if s < 0 or s != s:
                    raise ValueError
            except ValueError:
                print(f"[burst] bad --wait value: {a!r}",
                      file=sys.stderr)
                raise SystemExit(2)
            return s
    return None


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    os.makedirs(OUT, exist_ok=True)
    log: list = []

    wait_s = _parse_wait(argv)
    if wait_s is not None:
        # block (bounded) for the first healthy tunnel window instead
        # of burning the invocation on a down backend — the re-probe
        # schedule and its bounded subprocess probe come from the
        # resilience layer (ROADMAP: "the first healthy chip window")
        from pwasm_tpu.resilience.health import wait_for_backend
        print(f"[burst] --wait: probing for a healthy backend "
              f"(budget {wait_s:.0f}s)", file=sys.stderr)
        t0 = time.time()
        try:
            healthy = wait_for_backend(wait_s)
        except KeyboardInterrupt:
            # an operator's Ctrl-C during the (potentially hour-long)
            # block is a normal way to end a wait — it gets the
            # documented interrupted status, not a traceback that
            # reads like a crash in a cron log
            print(f"[burst] interrupted after {time.time() - t0:.0f}s "
                  "waiting for a healthy backend; exiting 130",
                  file=sys.stderr)
            return 130
        if not healthy:
            print(f"[burst] backend still down after "
                  f"{time.time() - t0:.0f}s; giving up", file=sys.stderr)
            return 3
        print(f"[burst] backend healthy after {time.time() - t0:.0f}s; "
              "firing burst", file=sys.stderr)

    smoke = _run("smoke", {}, ["tpu_smoke.py"], 700, log)
    verdict = smoke["rows"][-1] if smoke["rows"] else {}
    if verdict.get("backend_down") or not verdict.get("ok") \
            or verdict.get("backend") != "tpu":
        # a healthy-but-CPU backend must not burn the burst budget on
        # TPU-sized workloads (or overwrite the driver artifacts with
        # non-chip numbers)
        print("[burst] backend down, smoke failed, or not a real TPU; "
              "aborting", file=sys.stderr)
        return 3

    # 1. the driver-style full table (writes BENCH_ALL.json/TPU_SMOKE.json)
    _run("bench_all", {}, ["bench.py"], 5400, log)

    # 2. cfg4 column-tile sweep with the chunk-wise kernel
    for t in (2048, 4096, 8192):
        _run(f"cfg4_ctile{t}",
             {"PWASM_BENCH_CONFIG": "4", "PWASM_BENCH_CTILE": t},
             ["bench.py"], 1200, log)

    # 3. cfg2 Iy-chain A/B
    for chain in ("log", "two_level"):
        _run(f"cfg2_iy_{chain}",
             {"PWASM_BENCH_CONFIG": "2", "PWASM_DP_IYCHAIN": chain},
             ["bench.py"], 1200, log)

    # 4. cfg7 device clip refinement on chip
    _run("cfg7_chip", {"PWASM_BENCH_CONFIG": "7"}, ["bench.py"], 1200,
         log)

    # 5. one profiled cfg4 launch for the roofline-gap analysis
    _run("cfg4_profile",
         {"PWASM_BENCH_CONFIG": "4",
          "PWASM_BENCH_PROFILE": os.path.join(OUT, "cfg4_trace")},
         ["bench.py"], 1800, log)

    # 6. realistic-scale CLI on chip (BASELINE.md's device wall is
    # currently cpu-jax class; this replaces it with an on-chip
    # number — the script's --device=tpu run reaches the chip through
    # the same health gate as any user CLI run)
    _run("realistic_scale", {}, ["qa/realistic_scale.py"], 1800, log)

    print(f"[burst] complete: {len(log)} steps, results in {OUT}",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
