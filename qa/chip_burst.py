#!/usr/bin/env python
"""One-shot chip burst: run every chip-gated validation/measurement in
priority order the moment the tunnel is healthy, so a short window is
never wasted (the tunnel goes down for multi-hour stretches — see
ROUND4.md).  Results land in ``chip_burst/`` as JSONL + logs; the
driver-style artifacts (BENCH_ALL.json, TPU_SMOKE.json) are refreshed
by the full bench step exactly as a bare ``python bench.py`` would.

Order: smoke (gate) -> full bench table -> cfg4 column-tile sweep ->
cfg2 Iy-chain A/B -> cfg7 on chip -> cfg4 profiled launch.  Exit 3 =
backend down or not a real TPU (nothing ran); exit 0 = burst completed
(individual steps may still record failures in the JSONL).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "chip_burst")
sys.path.insert(0, REPO)

from bench import _json_rows  # noqa: E402  (one shared stdout parser)


def _run(name: str, env_extra: dict, args: list[str], timeout: float,
         log: list) -> dict:
    # each step fully controls its PWASM knobs: stray operator-shell
    # values (a lingering PWASM_BENCH_CONFIG pin, a profile dir, ...)
    # must not leak into the children
    env = {k: v for k, v in os.environ.items()
           if not (k.startswith("PWASM_BENCH_")
                   or k.startswith("PWASM_DP_"))}
    env.update({k: str(v) for k, v in env_extra.items()})
    t0 = time.time()
    try:
        r = subprocess.run([sys.executable] + args, env=env, cwd=REPO,
                           capture_output=True, text=True,
                           timeout=timeout)
        rec = {"step": name, "rc": r.returncode,
               "rows": _json_rows(r.stdout),
               "wall_s": round(time.time() - t0, 1)}
        with open(os.path.join(OUT, f"{name}.stderr"), "w") as f:
            f.write(r.stderr)
    except subprocess.TimeoutExpired as e:
        rec = {"step": name, "rc": None, "rows": [],
               "wall_s": round(time.time() - t0, 1), "timeout": True}
        with open(os.path.join(OUT, f"{name}.stderr"), "w") as f:
            for part in (e.stdout, e.stderr):  # partial output is the
                if part:                       # only hang diagnostic
                    f.write(part if isinstance(part, str)
                            else part.decode("utf-8", "replace"))
    except Exception as e:
        # a spawn failure must cost one step record, never the rest of
        # a scarce healthy-tunnel window
        rec = {"step": name, "rc": None, "rows": [],
               "wall_s": round(time.time() - t0, 1),
               "error": f"{type(e).__name__}: {e}"}
    log.append(rec)
    print(json.dumps(rec), flush=True)
    with open(os.path.join(OUT, "burst.jsonl"), "a") as f:
        f.write(json.dumps(rec) + "\n")
    return rec


def main() -> int:
    os.makedirs(OUT, exist_ok=True)
    log: list = []

    smoke = _run("smoke", {}, ["tpu_smoke.py"], 700, log)
    verdict = smoke["rows"][-1] if smoke["rows"] else {}
    if verdict.get("backend_down") or not verdict.get("ok") \
            or verdict.get("backend") != "tpu":
        # a healthy-but-CPU backend must not burn the burst budget on
        # TPU-sized workloads (or overwrite the driver artifacts with
        # non-chip numbers)
        print("[burst] backend down, smoke failed, or not a real TPU; "
              "aborting", file=sys.stderr)
        return 3

    # 1. the driver-style full table (writes BENCH_ALL.json/TPU_SMOKE.json)
    _run("bench_all", {}, ["bench.py"], 5400, log)

    # 2. cfg4 column-tile sweep with the chunk-wise kernel
    for t in (2048, 4096, 8192):
        _run(f"cfg4_ctile{t}",
             {"PWASM_BENCH_CONFIG": "4", "PWASM_BENCH_CTILE": t},
             ["bench.py"], 1200, log)

    # 3. cfg2 Iy-chain A/B
    for chain in ("log", "two_level"):
        _run(f"cfg2_iy_{chain}",
             {"PWASM_BENCH_CONFIG": "2", "PWASM_DP_IYCHAIN": chain},
             ["bench.py"], 1200, log)

    # 4. cfg7 device clip refinement on chip
    _run("cfg7_chip", {"PWASM_BENCH_CONFIG": "7"}, ["bench.py"], 1200,
         log)

    # 5. one profiled cfg4 launch for the roofline-gap analysis
    _run("cfg4_profile",
         {"PWASM_BENCH_CONFIG": "4",
          "PWASM_BENCH_PROFILE": os.path.join(OUT, "cfg4_trace")},
         ["bench.py"], 1800, log)

    # 6. realistic-scale CLI on chip (BASELINE.md's device wall is
    # currently cpu-jax class; this replaces it with an on-chip
    # number — the script's --device=tpu run reaches the chip through
    # the same health gate as any user CLI run)
    _run("realistic_scale", {}, ["qa/realistic_scale.py"], 1800, log)

    print(f"[burst] complete: {len(log)} steps, results in {OUT}",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
