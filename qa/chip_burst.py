#!/usr/bin/env python
"""One-shot chip burst: run every chip-gated validation/measurement in
priority order the moment the tunnel is healthy, so a short window is
never wasted (the tunnel goes down for multi-hour stretches — see
ROUND4.md).  Results land in ``chip_burst/`` as JSONL + logs; the
driver-style artifacts (BENCH_ALL.json, TPU_SMOKE.json) are refreshed
by the full bench step exactly as a bare ``python bench.py`` would.

Order: smoke (gate) -> full bench table -> cfg4 column-tile sweep ->
cfg2 Iy-chain A/B -> cfg7 on chip -> cfg4 profiled launch.  Exit 3 =
backend down or not a real TPU (nothing ran); exit 130 = interrupted
(Ctrl-C while blocking on ``--wait`` — the conventional 128+SIGINT
status, not a traceback); exit 0 = burst completed (individual steps
may still record failures in the JSONL).

``--wait[=S]``: instead of exiting 3 on a down tunnel, block (bounded
by S seconds, default 3600) on the resilience layer's capped-
exponential re-probe schedule (``resilience.health.wait_for_backend``)
and fire the burst in the FIRST healthy window — the mode a cron
driver wants during a flapping-tunnel stretch.

``--multichip``: the scale-out throughput sweep (ISSUE 8): run the
full sharded pipeline step (banded DP + psum'd consensus vote, the
``dryrun_multichip`` program) at a FIXED workload over 1, 2, 4, ...
devices and stamp the per-chip-count throughput table into the latest
``MULTICHIP_r*.json``.  On a real TPU mesh the sweep uses the chips;
anywhere else it degrades to the cpu-like twin (virtual host devices
via ``--xla_force_host_platform_device_count``, the same twin
``cpu_like_mesh`` builds) so CI can always run it — the stamped table
then carries ``cpu_fallback: true``.  This is the leg that certifies
the K-lane scale-up claim (jobs/s at K lanes >= ~K*0.8x single-lane)
on real silicon; the bench's cpu-twin lanes leg only certifies the
no-lost-throughput floor.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "chip_burst")
sys.path.insert(0, REPO)

from bench import _json_rows  # noqa: E402  (one shared stdout parser)


# probe TUNING passes through the scrub: these bound the health checks
# (how long a probe may take / how long a healthy verdict is cached) —
# they change no run behavior or result bytes, and on a slow tunnel the
# operator's raised PWASM_DEVICE_PROBE_TIMEOUT is the difference
# between a burst firing and a spurious exit 3.  NB PWASM_DEVICE_PROBE
# itself (=0 disables probing entirely) IS run behavior and stays
# scrubbed.
_SCRUB_KEEP = ("PWASM_DEVICE_PROBE_TIMEOUT", "PWASM_DEVICE_PROBE_TTL",
               "PWASM_BENCH_PROBE_TIMEOUT")


def _scrub_env(environ) -> dict:
    """Each step fully controls its PWASM knobs: ANY run-behavior
    ``PWASM_*`` value lingering in the operator's shell — a
    ``PWASM_INJECT_FAULTS`` left armed after a chaos session, a
    ``PWASM_HOST_COLUMNAR=0`` escape hatch, a ``PWASM_BENCH_CONFIG``
    pin — would silently poison every burst step, so the scrub strips
    the whole ``PWASM_`` namespace except the probe-tuning allowlist
    (steps re-add exactly what they need via ``env_extra``).
    Backend-selecting vars (``JAX_*``, ``PALLAS_*``) pass through:
    they are what point the burst at the chip."""
    return {k: v for k, v in environ.items()
            if not k.startswith("PWASM_") or k in _SCRUB_KEEP}


def _run(name: str, env_extra: dict, args: list[str], timeout: float,
         log: list) -> dict:
    env = _scrub_env(os.environ)
    env.update({k: str(v) for k, v in env_extra.items()})
    t0 = time.time()
    try:
        r = subprocess.run([sys.executable] + args, env=env, cwd=REPO,
                           capture_output=True, text=True,
                           timeout=timeout)
        rec = {"step": name, "rc": r.returncode,
               "rows": _json_rows(r.stdout),
               "wall_s": round(time.time() - t0, 1)}
        with open(os.path.join(OUT, f"{name}.stderr"), "w") as f:
            f.write(r.stderr)
    except subprocess.TimeoutExpired as e:
        rec = {"step": name, "rc": None, "rows": [],
               "wall_s": round(time.time() - t0, 1), "timeout": True}
        with open(os.path.join(OUT, f"{name}.stderr"), "w") as f:
            for part in (e.stdout, e.stderr):  # partial output is the
                if part:                       # only hang diagnostic
                    f.write(part if isinstance(part, str)
                            else part.decode("utf-8", "replace"))
    except Exception as e:
        # a spawn failure must cost one step record, never the rest of
        # a scarce healthy-tunnel window
        rec = {"step": name, "rc": None, "rows": [],
               "wall_s": round(time.time() - t0, 1),
               "error": f"{type(e).__name__}: {e}"}
    log.append(rec)
    print(json.dumps(rec), flush=True)
    with open(os.path.join(OUT, "burst.jsonl"), "a") as f:
        f.write(json.dumps(rec) + "\n")
    return rec


# ---------------------------------------------------------------------------
# --multichip: per-chip-count throughput sweep (ISSUE 8 satellite)
# ---------------------------------------------------------------------------
def _multichip_counts(n_max: int) -> list[int]:
    """1, 2, 4, ... up to the device inventory (pow2 so the 2-D mesh
    factorization exercises both axes at every point)."""
    counts, k = [], 1
    while k <= max(1, n_max):
        counts.append(k)
        k *= 2
    return counts


def _multichip_child(n: int) -> int:
    """Measure ONE chip count in a fresh backend: jit the sharded
    pipeline step (DP + depth-psum consensus) over an n-device mesh at
    a fixed workload, assert bit-parity vs the single-device program,
    and print the throughput row as the last stdout line."""
    import numpy as np

    import jax

    if len(jax.devices()) < n:
        print(json.dumps({"n_devices": n, "error":
                          f"only {len(jax.devices())} devices"}))
        return 1
    from pwasm_tpu.ops.banded_dp import banded_scores_batch
    from pwasm_tpu.ops.consensus import consensus_votes
    from pwasm_tpu.parallel.mesh import make_mesh, make_pipeline_step

    # fixed TOTAL workload for every chip count (so rows compare):
    # 32 targets x 1024-base query, band 64 (dryrun_multichip's
    # realistic shapes — the 48-diagonal m/n spread fits the band);
    # 64-deep pileup, 4096 cols
    T, m, nlen, band, depth, cols = 32, 1024, 1072, 64, 64, 4096
    rng = np.random.default_rng(5)
    q = rng.integers(0, 4, m).astype(np.int8)
    ts = np.full((T, nlen), 127, dtype=np.int8)
    t_lens = np.full(T, nlen - 16, dtype=np.int32)
    for k in range(T):
        ts[k, :t_lens[k]] = rng.integers(0, 4, t_lens[k])
    pileup = rng.integers(0, 7, size=(depth, cols)).astype(np.int8)
    mesh = make_mesh(n)
    step = make_pipeline_step(mesh, band=band)
    scores, votes = step(q, ts, t_lens, pileup)   # compile + warm
    scores.block_until_ready()
    votes.block_until_ready()
    parity = (np.array_equal(
        np.asarray(scores),
        np.asarray(banded_scores_batch(q, ts, t_lens, band=band)))
        and np.array_equal(np.asarray(votes),
                           np.asarray(consensus_votes(pileup))))
    walls = []
    for _ in range(3):
        t0 = time.perf_counter()
        s2, v2 = step(q, ts, t_lens, pileup)
        s2.block_until_ready()
        v2.block_until_ready()
        walls.append(time.perf_counter() - t0)
    wall = min(walls)
    print(json.dumps({
        "n_devices": n, "mesh": dict(mesh.shape),
        "backend": jax.default_backend(), "parity_ok": parity,
        "wall_s": round(wall, 6),
        "steps_per_s": round(1.0 / wall, 3),
        "dp_cells_per_s": round(T * m * band / wall, 1),
        "consensus_cols_per_s": round(cols / wall, 1)}))
    return 0 if parity else 1


def stamp_multichip(rows: list[dict], cpu_fallback: bool,
                    repo: str = REPO) -> str:
    """Merge the sweep's ``throughput`` table into the LATEST
    ``MULTICHIP_r*.json`` (the driver's dryrun artifact — the stamp
    rides the round it measured), creating ``MULTICHIP_r01.json`` when
    no round artifact exists yet.  Durable fsync-then-replace write:
    a crash mid-stamp must not tear the driver's artifact."""
    import glob

    from pwasm_tpu.utils.fsio import write_durable_text

    cands = sorted(glob.glob(os.path.join(repo, "MULTICHIP_r*.json")))
    path = cands[-1] if cands \
        else os.path.join(repo, "MULTICHIP_r01.json")
    doc: dict = {}
    try:
        with open(path) as f:
            loaded = json.load(f)
        doc = loaded if isinstance(loaded, dict) else {"rows": loaded}
    except (OSError, ValueError):
        pass
    doc["throughput"] = {"cpu_fallback": bool(cpu_fallback),
                         "stamped_unix": int(time.time()),
                         "rows": rows}
    write_durable_text(path, json.dumps(doc, indent=1) + "\n")
    return path


def run_multichip() -> int:
    """The --multichip mode: probe for a real mesh (bounded — a dead
    tunnel costs the timeout, not a hang), sweep chip counts in fresh
    child backends, stamp the table."""
    os.makedirs(OUT, exist_ok=True)
    env0 = _scrub_env(os.environ)
    real, ndev = False, 0
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import json, jax; print(json.dumps("
             "{'backend': jax.default_backend(),"
             " 'n': len(jax.devices())}))"],
            env=env0, capture_output=True, text=True, timeout=120)
        if r.returncode == 0 and r.stdout.strip():
            info = json.loads(r.stdout.strip().splitlines()[-1])
            ndev = int(info.get("n", 0))
            real = info.get("backend") == "tpu" and ndev >= 2
    except Exception:
        pass
    if not real:
        ndev = 8   # the cpu-like twin mirrors a v5e-8
        print("[multichip] no real TPU mesh; sweeping the cpu-like "
              f"twin ({ndev} virtual host devices)", file=sys.stderr)
    rows = []
    for n in _multichip_counts(ndev):
        env = dict(env0)
        if not real:
            env["JAX_PLATFORMS"] = "cpu"
            flags = " ".join(
                t for t in env.get("XLA_FLAGS", "").split()
                if "xla_force_host_platform_device_count" not in t)
            env["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={n}"
            ).strip()
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 f"--multichip-child={n}"],
                env=env, cwd=REPO, capture_output=True, text=True,
                timeout=900)
            row = json.loads(r.stdout.strip().splitlines()[-1]) \
                if r.stdout.strip() else {"n_devices": n,
                                          "error": "no output"}
            if r.returncode != 0 and "error" not in row:
                row["error"] = f"rc {r.returncode}"
                sys.stderr.write(r.stderr[-1000:])
        except Exception as e:
            row = {"n_devices": n,
                   "error": f"{type(e).__name__}: {e}"}
        rows.append(row)
        print(json.dumps(row), flush=True)
    path = stamp_multichip(rows, cpu_fallback=not real)
    ok = all("error" not in r and r.get("parity_ok") for r in rows)
    print(f"[multichip] stamped {len(rows)} row(s) into {path}"
          + ("" if ok else " (with failures)"), file=sys.stderr)
    return 0 if ok else 1


def _parse_wait(argv: list[str]) -> float | None:
    """``--wait`` / ``--wait=S`` -> wait budget in seconds (default
    3600); None when not asked to wait.  Raises SystemExit(2) on a
    malformed value — a silent typo must not turn a bounded wait into
    an immediate exit 3."""
    for a in argv:
        if a == "--wait":
            return 3600.0
        if a.startswith("--wait="):
            try:
                s = float(a.split("=", 1)[1])
                if s < 0 or s != s:
                    raise ValueError
            except ValueError:
                print(f"[burst] bad --wait value: {a!r}",
                      file=sys.stderr)
                raise SystemExit(2)
            return s
    return None


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    for a in argv:
        if a.startswith("--multichip-child="):
            try:
                n = int(a.split("=", 1)[1])
                if n < 1:
                    raise ValueError
            except ValueError:
                print(f"[burst] bad --multichip-child value: {a!r}",
                      file=sys.stderr)
                return 2
            return _multichip_child(n)
    if "--multichip" in argv:
        return run_multichip()
    os.makedirs(OUT, exist_ok=True)
    log: list = []

    wait_s = _parse_wait(argv)
    if wait_s is not None:
        # block (bounded) for the first healthy tunnel window instead
        # of burning the invocation on a down backend — the re-probe
        # schedule and its bounded subprocess probe come from the
        # resilience layer (ROADMAP: "the first healthy chip window")
        from pwasm_tpu.resilience.health import wait_for_backend
        print(f"[burst] --wait: probing for a healthy backend "
              f"(budget {wait_s:.0f}s)", file=sys.stderr)
        t0 = time.time()
        try:
            healthy = wait_for_backend(wait_s)
        except KeyboardInterrupt:
            # an operator's Ctrl-C during the (potentially hour-long)
            # block is a normal way to end a wait — it gets the
            # documented interrupted status, not a traceback that
            # reads like a crash in a cron log
            print(f"[burst] interrupted after {time.time() - t0:.0f}s "
                  "waiting for a healthy backend; exiting 130",
                  file=sys.stderr)
            return 130
        if not healthy:
            print(f"[burst] backend still down after "
                  f"{time.time() - t0:.0f}s; giving up", file=sys.stderr)
            return 3
        print(f"[burst] backend healthy after {time.time() - t0:.0f}s; "
              "firing burst", file=sys.stderr)

    smoke = _run("smoke", {}, ["tpu_smoke.py"], 700, log)
    verdict = smoke["rows"][-1] if smoke["rows"] else {}
    if verdict.get("backend_down") or not verdict.get("ok") \
            or verdict.get("backend") != "tpu":
        # a healthy-but-CPU backend must not burn the burst budget on
        # TPU-sized workloads (or overwrite the driver artifacts with
        # non-chip numbers)
        print("[burst] backend down, smoke failed, or not a real TPU; "
              "aborting", file=sys.stderr)
        return 3

    # 1. the driver-style full table (writes BENCH_ALL.json/TPU_SMOKE.json)
    _run("bench_all", {}, ["bench.py"], 5400, log)

    # 1b. per-chip-count scale-out throughput (ISSUE 8): the real-mesh
    # numbers the lease scheduler's K-lane scaling claim rests on
    _run("multichip", {}, ["qa/chip_burst.py", "--multichip"], 1800,
         log)

    # 2. cfg4 column-tile sweep with the chunk-wise kernel
    for t in (2048, 4096, 8192):
        _run(f"cfg4_ctile{t}",
             {"PWASM_BENCH_CONFIG": "4", "PWASM_BENCH_CTILE": t},
             ["bench.py"], 1200, log)

    # 3. cfg2 Iy-chain A/B
    for chain in ("log", "two_level"):
        _run(f"cfg2_iy_{chain}",
             {"PWASM_BENCH_CONFIG": "2", "PWASM_DP_IYCHAIN": chain},
             ["bench.py"], 1200, log)

    # 4. cfg7 device clip refinement on chip
    _run("cfg7_chip", {"PWASM_BENCH_CONFIG": "7"}, ["bench.py"], 1200,
         log)

    # 5. one profiled cfg4 launch for the roofline-gap analysis
    _run("cfg4_profile",
         {"PWASM_BENCH_CONFIG": "4",
          "PWASM_BENCH_PROFILE": os.path.join(OUT, "cfg4_trace")},
         ["bench.py"], 1800, log)

    # 6. realistic-scale CLI on chip (BASELINE.md's device wall is
    # currently cpu-jax class; this replaces it with an on-chip
    # number — the script's --device=tpu run reaches the chip through
    # the same health gate as any user CLI run)
    _run("realistic_scale", {}, ["qa/realistic_scale.py"], 1800, log)

    print(f"[burst] complete: {len(log)} steps, results in {OUT}",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
