"""Realistic-scale CLI wall-clock capture (VERDICT r4 item 5).

Runs the Nanopore-like corpus from tests/test_realistic_scale.py
through the full CLI (report + summary + MSA + consensus) on
--device=cpu and --device=tpu, printing wall times and the RunStats
routing + dispatch-budget counters as one JSON line each — the numbers
BASELINE.md's "realistic scale" section records.  Usage:

    python qa/realistic_scale.py [n_aln] [fault_spec]

With ``fault_spec`` (e.g. ``seed=7,rate=0.3,kinds=raise+nan+corrupt``)
a third CHAOS leg runs the device path under seeded fault injection at
the same scale and asserts its output stays byte-identical to the
clean device run (ROADMAP PR-1 follow-up: resilience exercised at
realistic scale, not just in unit fixtures).
"""

import io
import json
import os
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "tests"))


def main() -> int:
    n_aln = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    fault_spec = sys.argv[2] if len(sys.argv) > 2 else ""
    from test_realistic_scale import make_corpus

    from pwasm_tpu.cli import run

    t0 = time.perf_counter()
    qseq, lines = make_corpus(n_aln=n_aln)
    gen_s = time.perf_counter() - t0
    with tempfile.TemporaryDirectory() as d:
        fa = os.path.join(d, "cds.fa")
        with open(fa, "w") as f:
            f.write(f">cds1\n{qseq}\n")
        paf = os.path.join(d, "in.paf")
        with open(paf, "w") as f:
            f.write("".join(l + "\n" for l in lines))
        paf_mb = os.path.getsize(paf) / 1e6
        legs = [("cpu", []), ("tpu", [])]
        if fault_spec:
            # --batch=16: the dispatch-lean pipeline leaves only ~2
            # supervised round-trips per run at the default batch, too
            # few draw opportunities for the fault plan (see
            # docs/RESILIENCE.md) — and batch size never changes bytes
            legs.append(("chaos", ["--batch=16",
                                   f"--inject-faults={fault_spec}",
                                   "--max-retries=4"]))
        body = {}
        for dev, extra in legs:
            plat = "tpu" if dev == "chaos" else dev
            outs = {k: os.path.join(d, f"{dev}.{k}")
                    for k in ("dfa", "sum", "mfa", "cons", "stats")}
            err = io.StringIO()
            t0 = time.perf_counter()
            rc = run([paf, "-r", fa, "-o", outs["dfa"],
                      "-s", outs["sum"], "-w", outs["mfa"],
                      f"--cons={outs['cons']}", f"--device={plat}",
                      f"--stats={outs['stats']}"] + extra, stderr=err)
            wall = time.perf_counter() - t0
            st = json.loads(open(outs["stats"]).read()) if rc == 0 \
                else {}
            body[dev] = b"".join(
                open(outs[k], "rb").read()
                for k in ("dfa", "sum", "mfa", "cons")) if rc == 0 \
                else None
            if dev == "chaos":
                chaos_res = st.get("resilience", {})
            print(json.dumps({
                "corpus": {"n_aln": n_aln, "paf_mb": round(paf_mb, 2),
                           "gen_s": round(gen_s, 2)},
                "device": dev, "rc": rc,
                "wall_s": round(wall, 3),
                "aligned_bases": st.get("aligned_bases"),
                "events": st.get("events"),
                "device_events": st.get("device_events"),
                "scalar_events": st.get("scalar_events"),
                "fallback_batches": st.get("fallback_batches"),
                "engine_fallbacks": st.get("engine_fallbacks"),
                "device_dispatch": st.get("device"),
                "resilience": st.get("resilience") if dev == "chaos"
                else None,
                "bases_per_s": round(
                    st.get("aligned_bases", 0) / wall) if rc == 0
                else None,
            }))
            if rc != 0:
                sys.stderr.write(err.getvalue()[-1000:])
                return rc
        if fault_spec:
            ok = body["chaos"] == body["tpu"]
            injected = chaos_res.get("injected_faults", 0)
            print(json.dumps({"chaos_byte_identical": ok,
                              "chaos_injected_faults": injected}))
            if injected == 0:
                print("warning: the fault plan never fired — raise "
                      "rate= or lower --batch further",
                      file=sys.stderr)
            if not ok:
                return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
