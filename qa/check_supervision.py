#!/usr/bin/env python
"""Static supervision-coverage check (tier-1).

The resilience layer only protects device work that is ROUTED THROUGH
it: a new module that calls ``jax.jit`` / ``jax.device_put`` /
``.block_until_ready`` directly, outside a ``BatchSupervisor.run``
site, silently re-opens the fail-fast hole PR 1 closed (no retries, no
breaker, no fallback policy, no counters).  This check greps
``pwasm_tpu/`` for device round-trip entry points and fails when any
hit lives in a module that is not in the REGISTRY below — forcing the
author of new device code to either thread it through a supervised
site or register (and justify) the exemption.

Registry semantics, per module (repo-relative path):

- ``site:<name>``   the module's device work is reached only through a
                    ``BatchSupervisor.run`` call at that site (the
                    supervised callers are listed in
                    docs/RESILIENCE.md);
- ``exempt:<why>``  deliberately unsupervised (probes, one-shot debug
                    tools, the compat shim) — the justification is the
                    registry entry itself.

Run standalone (``python qa/check_supervision.py``, exit 1 on
violations) or through ``tests/test_supervision_coverage.py``.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# device round-trip entry points: program definitions (jit) and
# explicit host<->device transfers.  ``np.asarray``/``jnp.asarray`` are
# deliberately NOT patterns — they are ubiquitous and ambiguous; every
# blocking fetch in this codebase happens inside a function built
# around one of these markers.
PATTERNS = re.compile(
    r"jax\.jit\s*\(|@jax\.jit\b|partial\s*\(\s*jax\.jit"
    r"|jax\.device_put\s*\(|jax\.device_get\s*\("
    r"|\.block_until_ready\s*\(")

# module -> justification (see module docstring for the grammar)
REGISTRY = {
    # jitted device programs, reached only via supervised call sites
    "pwasm_tpu/ops/pack.py": "site:ctx_scan",
    "pwasm_tpu/ops/ctx_scan.py": "site:ctx_scan",
    "pwasm_tpu/report/device_report.py": "site:ctx_scan",
    "pwasm_tpu/ops/banded_dp.py": "site:realign",
    "pwasm_tpu/ops/realign.py": "site:realign",
    "pwasm_tpu/ops/consensus.py": "site:consensus",
    "pwasm_tpu/ops/refine_clip.py": "site:refine",
    "pwasm_tpu/parallel/many2many.py": "site:many2many",
    "pwasm_tpu/parallel/mesh.py":
        "site:consensus+refine (sharded twins of supervised programs)",
    "pwasm_tpu/parallel/wavefront_sp.py":
        "exempt:bench-only long-read kernel (no CLI entry point; "
        "bench.py owns its bounded subprocess)",
}

# The service layer (pwasm_tpu/service/: the warm-pool daemon, ISSUE
# 5) is held to a STRICTER rule than the registry: it must not touch
# jax AT ALL — not even an import.  Every served job reaches the
# device exclusively through cli.run's supervised sites, so any direct
# jax use in service code would be a device entry point outside BOTH
# the supervision contract and the per-job fault-injection/guardrail
# machinery.  (The generic PATTERNS above still apply to service
# modules too; this adds the import-level tripwire.)  The same rule
# covers pwasm_tpu/obs/ (ISSUE 6): the observability layer runs on
# the plain-CPU path, inside signal-handler-adjacent code and in the
# jax-free daemon — an obs module importing jax would smuggle backend
# init into all three.
SERVICE_DIR = "pwasm_tpu/service"
OBS_DIR = "pwasm_tpu/obs"
# pwasm_tpu/stream/ (ISSUE 10) is held to the same jax-free rule: the
# streaming ingestion readers run inside the daemon and around signal
# handling, and the multi-CDS driver is a HOST driver — its device
# work is reached only through the supervised many2many site in
# pwasm_tpu/parallel/ (imported lazily, like cli._main_loop does).
STREAM_DIR = "pwasm_tpu/stream"
# pwasm_tpu/fleet/ (ISSUE 13) too: the router and the TCP transport
# move protocol frames and read journals/spools — a fleet module
# importing jax would smuggle backend init into a process that must
# stay device-free by design (the router fronts N daemons that each
# own their devices).
FLEET_DIR = "pwasm_tpu/fleet"
# pwasm_tpu/surveil/ (ISSUE 20): the continuous-m2m coordination
# layer — stream partitioning, fragment merge, the session driver —
# runs inside the daemon and the (device-free) router.  Its only
# device reach is the lazy supervised many2many site in parallel/.
SURVEIL_DIR = "pwasm_tpu/surveil"
SURVEIL_FILES = ("pwasm_tpu/surveil/__init__.py",
                 "pwasm_tpu/surveil/records.py",
                 "pwasm_tpu/surveil/partition.py",
                 "pwasm_tpu/surveil/session.py")
SERVICE_PATTERNS = re.compile(
    r"^\s*(?:import\s+jax\b|from\s+jax[.\s])|jax\.jit|jax\.device_put"
    r"|jax\.device_get|\.block_until_ready\s*\(")

# ---- sharding-API routing gate (ISSUE 8 satellite) --------------------
# Every sharding/collective surface the repo touches is shimmed in
# utils/jaxcompat.py (shard_map + check_vma/check_rep, psum, ppermute,
# pcast): the baseline container's jax pin change took out every
# parallel/ test before the shim existed, so a NEW module importing
# jax's shard_map directly — or calling jax.lax.psum/ppermute/pcast
# bare — re-opens exactly that hole.  This gate fails any such use in
# pwasm_tpu/ outside the shim itself.
JAXCOMPAT = "pwasm_tpu/utils/jaxcompat.py"
SHARDING_PATTERNS = re.compile(
    r"from\s+jax\.experimental\.shard_map"           # old import path
    r"|from\s+jax\.experimental\s+import\s+[^#\n]*"  # module-import
    r"\bshard_map\b"                                 #   spelling
    r"|from\s+jax\s+import\s+[^#\n]*\bshard_map\b"   # new import path
    r"|jax\.shard_map\s*\("
    r"|(?:jax\.)?lax\.(?:psum|ppermute|pcast)\s*\(")

# ---- metric-name lint (ISSUE 6 satellite) -----------------------------
# Every metric registration (registry.counter/gauge/histogram) in
# pwasm_tpu/ must live in obs/catalog.py — the catalog IS the metric
# namespace, so an operator reading docs/OBSERVABILITY.md sees every
# series that can exist.  Within the catalog, names must be snake_case
# with the pwasm_ prefix and appear exactly once (a duplicate would
# alias two meanings onto one time series; the registry also raises at
# runtime, but the lint fails at review time).
METRIC_CATALOG = "pwasm_tpu/obs/catalog.py"
METRIC_REGISTER_RE = re.compile(
    r"\.\s*(?:counter|gauge|histogram)\s*\(")
METRIC_NAME_RE = re.compile(r"^pwasm_[a-z0-9]+(_[a-z0-9]+)*$")
METRIC_LITERAL_RE = re.compile(r"""["'](pwasm_[A-Za-z0-9_]*)["']""")

# the registration region of the catalog ends at this sentinel line:
# everything below it REFERENCES registered families (the default SLO
# rule expressions, ISSUE 14), so the uniqueness scan must not read a
# rule's metric reference as a second registration
CATALOG_END_SENTINEL = "metric-name-lint: end-of-registrations"

# ---- metric doc-drift rule (ISSUE 11 satellite) -----------------------
# docs/OBSERVABILITY.md is the operator's catalog of record: a metric
# family registered in obs/catalog.py but absent from the doc is a
# series an operator cannot know to alert on.  This rule fails any
# catalog name literal the doc never mentions (substring match — the
# doc tables and prose both count).
METRIC_DOC = "docs/OBSERVABILITY.md"

# ---- self-monitoring gates (ISSUE 14 satellite) -----------------------
# The SLO engine and the canary run INSIDE the daemon's accept loop
# and worker threads: they are held to the same jax-free rule as the
# rest of service/obs (the directory walks already cover them), and
# additionally they must EXIST — a refactor that drops either silently
# removes the self-monitoring surface the fleet verdict depends on.
SLO_FILES = ("pwasm_tpu/obs/slo.py", "pwasm_tpu/service/canary.py")

# ---- result-cache gate (ISSUE 15 satellite) ---------------------------
# The content-addressed result cache sits on EVERY serving tier's hot
# path (CLI populate, daemon admission, router edge + affinity) and
# runs inside connection threads: it must EXIST (a refactor dropping
# it silently removes the ≥100x repeat-traffic path every tier leans
# on) and stay jax-free like the rest of service/ — its only jobs are
# hashing, fsio writes, and file serves.
CACHE_FILES = ("pwasm_tpu/service/cache.py",)

# Incremental-compute surface (ISSUE 17): the delta machinery lives
# inside the cache module and every serving tier leans on it — a
# refactor that drops one of these entry points silently turns all
# near-miss traffic back into cold recomputes.  Checked by
# find_cache_violations alongside the jax-freeness scan.
CACHE_DELTA_SYMBOLS = ("def delta_lookup", "def note_delta",
                       "def m2m_scan", "def prefetch",
                       "def contains_family", "def paf_line_digests",
                       "def family_key", "def m2m_family_key")

# ---- fencing-invariant gate (ISSUE 16 satellite) ----------------------
# Failover re-admission is where split-brain corruption happens: an
# orchestrator that re-admits a started job as a ``--resume``
# continuation on a SIBLING member must first route the job's
# placement epoch through fencing.readmit_epoch_guard — otherwise a
# stale router incarnation can re-place work a newer incarnation
# already owns, and two writers share one report file.  This gate
# finds every line in pwasm_tpu/ that APPENDS the literal
# ``--resume`` to an argv (the re-admission signature) and fails
# unless the site is registered below.  Registry grammar, per module:
#
# - ``guard``         the site must reference FENCING_GUARD earlier
#                     in the SAME function (the epoch check happens
#                     before the job is re-placed);
# - ``exempt:<why>``  deliberately unguarded — the justification is
#                     the registry entry itself.
FENCING_FILE = "pwasm_tpu/fleet/fencing.py"
FENCING_GUARD = "readmit_epoch_guard"
RESUME_APPEND_RE = re.compile(
    r"""(?:append|extend)\s*\(\s*\[?\s*["']--resume["']"""
    r"""|\+\s*\[\s*["']--resume["']""")
FENCING_REGISTRY = {
    # the daemon re-admits its OWN journal into its OWN queue at
    # startup — one process, one writer, no sibling to race; the
    # fleet epoch does not exist at this layer
    "pwasm_tpu/service/daemon.py":
        "exempt:single-process self-replay (the daemon re-admits its "
        "own journal at startup; no sibling writer exists to fence)",
    # the router re-places jobs on SIBLINGS after a member death —
    # the epoch guard is mandatory here
    "pwasm_tpu/fleet/router.py": "guard",
}

# default SLO rule names are declared in the catalog's rules region
# (below the sentinel) as {"name": "..."} literals; each must appear
# in docs/OBSERVABILITY.md — an undocumented rule is an alert an
# operator cannot know to act on
RULE_NAME_RE = re.compile(r"""["']name["']\s*:\s*["']([a-z0-9_]+)["']""")


def find_hits(root: str = REPO) -> list[tuple[str, int, str]]:
    """Every (relpath, lineno, line) in pwasm_tpu/ matching PATTERNS,
    comment-only lines skipped."""
    hits = []
    pkg = os.path.join(root, "pwasm_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                for i, line in enumerate(f, 1):
                    if line.lstrip().startswith("#"):
                        continue
                    if PATTERNS.search(line):
                        hits.append((rel, i, line.strip()))
    return hits


def find_unregistered(root: str = REPO) -> list[str]:
    """Human-readable violation lines; empty = covered."""
    out = []
    for rel, lineno, line in find_hits(root):
        if rel not in REGISTRY:
            out.append(f"{rel}:{lineno}: unsupervised device entry "
                       f"point: {line}")
    return out


def _find_jaxfree_violations(root: str, subdir: str,
                             what: str) -> list[str]:
    out = []
    top = os.path.join(root, *subdir.split("/"))
    if not os.path.isdir(top):
        return out
    for dirpath, dirnames, filenames in os.walk(top):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                for i, line in enumerate(f, 1):
                    if line.lstrip().startswith("#"):
                        continue
                    if SERVICE_PATTERNS.search(line):
                        out.append(
                            f"{rel}:{i}: {what} module touches jax "
                            f"directly: {line.strip()} — route device "
                            "work through cli.run's supervised sites")
    return out


def find_service_violations(root: str = REPO) -> list[str]:
    """Service-side device entry points (see SERVICE_PATTERNS): the
    daemon/client/queue/protocol modules must stay jax-free — device
    work belongs behind cli.run's BatchSupervisor sites."""
    return _find_jaxfree_violations(root, SERVICE_DIR, "service")


def find_obs_violations(root: str = REPO) -> list[str]:
    """Observability-side jax use (ISSUE 6): pwasm_tpu/obs/ must stay
    jax-free — it runs on the plain-CPU path, in the daemon, and in
    signal-handler-adjacent code."""
    return _find_jaxfree_violations(root, OBS_DIR, "obs")


def find_stream_violations(root: str = REPO) -> list[str]:
    """Streaming-layer jax use (ISSUE 10): pwasm_tpu/stream/ must stay
    jax-free — device work belongs behind the supervised sites in
    pwasm_tpu/parallel/, reached via lazy imports."""
    return _find_jaxfree_violations(root, STREAM_DIR, "stream")


def find_fleet_violations(root: str = REPO) -> list[str]:
    """Fleet-layer jax use (ISSUE 13): pwasm_tpu/fleet/ must stay
    jax-free — the router/transport/ledger move frames and files;
    every device touch in the fleet happens inside a member daemon's
    cli.run, behind the supervised sites."""
    return _find_jaxfree_violations(root, FLEET_DIR, "fleet")


def find_surveil_violations(root: str = REPO) -> list[str]:
    """Surveillance-pipeline gate (ISSUE 20): pwasm_tpu/surveil/ must
    exist AND stay jax-free — the stream partitioner, fragment
    merger and session driver run inside the daemon and the
    device-free router; device work is reached only through the
    lazy supervised many2many site in pwasm_tpu/parallel/.
    ``_find_jaxfree_violations`` returns [] for a missing directory,
    so the existence of the core modules is asserted first."""
    out: list[str] = []
    for rel in SURVEIL_FILES:
        path = os.path.join(root, *rel.split("/"))
        if not os.path.isfile(path):
            out.append(f"{rel}: surveillance-pipeline module missing "
                       "— the continuous-m2m coordination layer the "
                       "--m2m-stream job type and the fleet scatter "
                       "path depend on")
    out.extend(_find_jaxfree_violations(root, SURVEIL_DIR, "surveil"))
    return out


def find_sharding_violations(root: str = REPO) -> list[str]:
    """Bare sharding/collective API use outside the jaxcompat shim
    (module docstring: the ISSUE 8 routing rule)."""
    out: list[str] = []
    pkg = os.path.join(root, "pwasm_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if rel == JAXCOMPAT:
                continue
            with open(path, encoding="utf-8") as f:
                for i, line in enumerate(f, 1):
                    if line.lstrip().startswith("#"):
                        continue
                    if SHARDING_PATTERNS.search(line):
                        out.append(
                            f"{rel}:{i}: bare sharding/collective API "
                            f"use: {line.strip()} — route it through "
                            f"{JAXCOMPAT}")
    return out


def find_metric_lint(root: str = REPO) -> list[str]:
    """The metric-name lint (module docstring): registrations only in
    the catalog; catalog names snake_case, ``pwasm_``-prefixed, unique."""
    out: list[str] = []
    pkg = os.path.join(root, "pwasm_tpu")
    catalog_path = os.path.join(root, *METRIC_CATALOG.split("/"))
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if rel == METRIC_CATALOG \
                    or rel == OBS_DIR + "/metrics.py":
                continue   # the catalog itself + the registry impl
            with open(path, encoding="utf-8") as f:
                for i, line in enumerate(f, 1):
                    if line.lstrip().startswith("#"):
                        continue
                    # the CALL alone is the violation — requiring the
                    # name literal on the same line would let any
                    # multi-line registration (the repo's normal
                    # style) slip past the lint
                    if METRIC_REGISTER_RE.search(line):
                        out.append(
                            f"{rel}:{i}: metric registered outside "
                            f"the catalog: {line.strip()} — move the "
                            f"registration to {METRIC_CATALOG}")
    if not os.path.isfile(catalog_path):
        return out
    seen: dict[str, int] = {}
    with open(catalog_path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            if CATALOG_END_SENTINEL in line:
                break   # below: rule metric REFERENCES, not
                #         registrations (see the sentinel comment)
            if line.lstrip().startswith("#"):
                continue
            for name in METRIC_LITERAL_RE.findall(line):
                if not METRIC_NAME_RE.match(name):
                    out.append(
                        f"{METRIC_CATALOG}:{i}: metric name {name!r} "
                        "violates the grammar (snake_case, pwasm_ "
                        "prefix)")
                if name in seen:
                    out.append(
                        f"{METRIC_CATALOG}:{i}: duplicate metric "
                        f"name {name!r} (first at line {seen[name]})")
                else:
                    seen[name] = i
    return out


def catalog_metric_names(root: str = REPO) -> dict[str, int]:
    """Every valid-grammar metric name literal in the catalog, with
    its first line number (the doc-drift rule's registration side)."""
    catalog_path = os.path.join(root, *METRIC_CATALOG.split("/"))
    names: dict[str, int] = {}
    if not os.path.isfile(catalog_path):
        return names
    with open(catalog_path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            if CATALOG_END_SENTINEL in line:
                break
            if line.lstrip().startswith("#"):
                continue
            for name in METRIC_LITERAL_RE.findall(line):
                if METRIC_NAME_RE.match(name):
                    names.setdefault(name, i)
    return names


def catalog_rule_names(root: str = REPO) -> dict[str, int]:
    """Every default SLO rule name declared in the catalog's rules
    region (after the sentinel), with its line number — the
    registration side of the rule doc-drift check (ISSUE 14)."""
    catalog_path = os.path.join(root, *METRIC_CATALOG.split("/"))
    names: dict[str, int] = {}
    if not os.path.isfile(catalog_path):
        return names
    in_rules = False
    with open(catalog_path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            if CATALOG_END_SENTINEL in line:
                in_rules = True
                continue
            if not in_rules or line.lstrip().startswith("#"):
                continue
            for name in RULE_NAME_RE.findall(line):
                names.setdefault(name, i)
    return names


def find_slo_violations(root: str = REPO) -> list[str]:
    """Self-monitoring gate (ISSUE 14 satellite): obs/slo.py and
    service/canary.py must exist AND stay jax-free — the engine and
    the canary run inside the daemon's accept loop and worker
    threads, tier-1 like the rest of service/obs/stream/fleet."""
    out: list[str] = []
    for rel in SLO_FILES:
        path = os.path.join(root, *rel.split("/"))
        if not os.path.isfile(path):
            out.append(f"{rel}: self-monitoring module missing — the "
                       "SLO engine / canary surface the fleet health "
                       "verdict depends on")
            continue
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f, 1):
                if line.lstrip().startswith("#"):
                    continue
                if SERVICE_PATTERNS.search(line):
                    out.append(
                        f"{rel}:{i}: self-monitoring module touches "
                        f"jax directly: {line.strip()} — the engine "
                        "and canary must stay jax-free (device work "
                        "goes through the injected runner)")
    return out


def find_cache_violations(root: str = REPO) -> list[str]:
    """Result-cache gate (ISSUE 15 satellite): service/cache.py must
    exist AND stay jax-free — the cache runs in admission/connection
    threads on every serving tier, and a jax import there would
    smuggle backend init into all of them."""
    out: list[str] = []
    for rel in CACHE_FILES:
        path = os.path.join(root, *rel.split("/"))
        if not os.path.isfile(path):
            out.append(f"{rel}: result-cache module missing — the "
                       "content-addressed serving path every tier "
                       "(CLI/daemon/router) depends on")
            continue
        with open(path, encoding="utf-8") as f:
            text = f.read()
        for i, line in enumerate(text.splitlines(), 1):
            if line.lstrip().startswith("#"):
                continue
            if SERVICE_PATTERNS.search(line):
                out.append(
                    f"{rel}:{i}: result-cache module touches "
                    f"jax directly: {line.strip()} — the cache "
                    "hashes and serves bytes; device work stays "
                    "behind cli.run's supervised sites")
        for sym in CACHE_DELTA_SYMBOLS:
            if sym not in text:
                out.append(
                    f"{rel}: missing `{sym}` — the incremental-"
                    "compute (delta-serving) surface every tier's "
                    "near-miss path depends on (ISSUE 17)")
    return out


def _enclosing_def_start(lines: list[str], hit_idx: int) -> int:
    """0-based index of the ``def`` line opening the function that
    contains ``lines[hit_idx]`` (nearest preceding def at strictly
    lower indentation), or 0 when the hit is at module level."""
    hit = lines[hit_idx]
    hit_indent = len(hit) - len(hit.lstrip())
    for j in range(hit_idx - 1, -1, -1):
        stripped = lines[j].lstrip()
        if not stripped:
            continue
        indent = len(lines[j]) - len(stripped)
        if stripped.startswith("def ") and indent < hit_indent:
            return j
    return 0


def find_fencing_violations(root: str = REPO) -> list[str]:
    """Fencing-invariant gate (ISSUE 16 satellite): fleet/fencing.py
    must exist, and every ``--resume`` re-admission site in pwasm_tpu/
    must be registered in FENCING_REGISTRY — ``guard`` sites must
    reference ``readmit_epoch_guard`` earlier in the same function,
    so no failover path can re-place a job without the epoch check."""
    out: list[str] = []
    fpath = os.path.join(root, *FENCING_FILE.split("/"))
    if not os.path.isfile(fpath):
        out.append(f"{FENCING_FILE}: fencing module missing — the "
                   "epoch-lease surface every failover re-admission "
                   "path depends on")
    pkg = os.path.join(root, "pwasm_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if rel == FENCING_FILE:
                continue
            with open(path, encoding="utf-8") as f:
                lines = f.readlines()
            for i, line in enumerate(lines):
                if line.lstrip().startswith("#"):
                    continue
                if not RESUME_APPEND_RE.search(line):
                    continue
                entry = FENCING_REGISTRY.get(rel)
                if entry is None:
                    out.append(
                        f"{rel}:{i + 1}: unregistered --resume "
                        f"re-admission site: {line.strip()} — route "
                        f"the job's epoch through "
                        f"{FENCING_FILE}::{FENCING_GUARD} and "
                        "register the site in "
                        "qa/check_supervision.py::FENCING_REGISTRY")
                elif entry == "guard":
                    start = _enclosing_def_start(lines, i)
                    if FENCING_GUARD not in "".join(lines[start:i]):
                        out.append(
                            f"{rel}:{i + 1}: --resume re-admission "
                            f"without the epoch fence: call "
                            f"{FENCING_GUARD} earlier in the same "
                            "function, before the job is re-placed")
    return out


# ── monotonic-clock audit (ISSUE 18 satellite) ──
# Durations and intervals must come from time.monotonic(): an NTP
# step or a suspended laptop warps time.time() arithmetic, and the
# places this codebase subtracts timestamps are exactly the places
# that decide deadlines, uptimes and queue waits — a backwards wall
# clock there turns into a spurious deadline_exceeded or a negative
# queue_wait.  The lint is line-level: any `time.time() - x` /
# `x - time.time()` subtraction outside the allowlist fails tier-1.
# Wall-clock TIMESTAMPS (journal `t=` fields, submitted_s sort keys)
# are fine — they are recorded, not subtracted.
CLOCK_SUB_RE = re.compile(r"time\.time\(\)\s*-|-\s*time\.time\(\)")

# path -> justification for a genuine wall-clock duration: values
# PERSISTED across processes (a cache manifest's created stamp must
# be comparable after a restart, which monotonic time is not)
CLOCK_ALLOWLIST = {
    "pwasm_tpu/service/cache.py":
        "TTL over manifest `created` stamps persisted across "
        "processes — monotonic clocks don't survive a restart",
}


def find_clock_violations(root: str = REPO) -> list[str]:
    """Wall-clock duration arithmetic (CLOCK_SUB_RE) in pwasm_tpu/
    outside CLOCK_ALLOWLIST — durations belong to time.monotonic()."""
    out: list[str] = []
    pkg = os.path.join(root, "pwasm_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if rel in CLOCK_ALLOWLIST:
                continue
            with open(path, encoding="utf-8") as f:
                for i, line in enumerate(f, 1):
                    if line.lstrip().startswith("#"):
                        continue
                    if CLOCK_SUB_RE.search(line):
                        out.append(
                            f"{rel}:{i}: wall-clock duration "
                            f"arithmetic: {line.strip()} — use "
                            "time.monotonic() (or register a "
                            "justified allowlist entry in "
                            "qa/check_supervision.py::"
                            "CLOCK_ALLOWLIST)")
    return out


def stale_clock_allowlist(root: str = REPO) -> list[str]:
    """Allowlist rows whose file no longer subtracts time.time() —
    same accuracy rule as the supervision registry."""
    out = []
    for rel in CLOCK_ALLOWLIST:
        path = os.path.join(root, *rel.split("/"))
        if not os.path.isfile(path):
            out.append(rel)
            continue
        with open(path, encoding="utf-8") as f:
            if not any(CLOCK_SUB_RE.search(l) for l in f
                       if not l.lstrip().startswith("#")):
                out.append(rel)
    return out


# ── protocol error-vocabulary coverage (ISSUE 18 satellite) ──
# Every ERR_* code protocol.py can put on the wire is a behaviour a
# client will branch on; an error code no test exercises is a
# contract nobody is holding.  The gate fails when a code's constant
# name AND its wire string are both absent from tests/ — adding a new
# code to the vocabulary forces adding the test that emits it.
PROTOCOL_FILE = "pwasm_tpu/service/protocol.py"
ERR_DEF_RE = re.compile(r'^(ERR_[A-Z_]+)\s*=\s*"([a-z_]+)"')


def protocol_error_codes(root: str = REPO) -> dict[str, tuple]:
    """``{ERR_NAME: (lineno, wire_string)}`` parsed from the
    top-level assignments in service/protocol.py."""
    out: dict[str, tuple] = {}
    path = os.path.join(root, *PROTOCOL_FILE.split("/"))
    if not os.path.isfile(path):
        return out
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            m = ERR_DEF_RE.match(line)
            if m:
                out[m.group(1)] = (i, m.group(2))
    return out


def find_error_vocab_gaps(root: str = REPO) -> list[str]:
    """Protocol error codes exercised by no test: neither the ERR_*
    constant nor its wire string appears anywhere under tests/."""
    codes = protocol_error_codes(root)
    if not codes:
        return [f"{PROTOCOL_FILE}: missing or defines no ERR_* "
                "codes — the protocol error vocabulary is gone"]
    tests_dir = os.path.join(root, "tests")
    corpus: list[str] = []
    if os.path.isdir(tests_dir):
        for dirpath, dirnames, filenames in os.walk(tests_dir):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    with open(os.path.join(dirpath, fn),
                              encoding="utf-8") as f:
                        corpus.append(f.read())
    text = "\n".join(corpus)
    out = []
    for name, (lineno, wire) in sorted(codes.items(),
                                       key=lambda kv: kv[1][0]):
        if name not in text and wire not in text:
            out.append(
                f"{PROTOCOL_FILE}:{lineno}: error code {name} "
                f"({wire!r}) is exercised by no test under tests/ — "
                "an error code nobody tests is a contract nobody "
                "holds; add a test that provokes it")
    return out


# ── transport-confinement gate (ISSUE 19 tentpole) ──
# fleet/transport.py is the ONE place sockets are minted and TLS is
# configured: the TLS floor (1.2+), the mTLS peer-CN extraction, the
# handshake-failure accounting and the unix-socket 0600 chmod all live
# there, so a module that constructs its own socket.socket or touches
# ssl directly ships a listener/dialer OUTSIDE the zero-trust surface
# — no TLS upgrade path, no handshake metric, no permission contract.
# Line-level, pwasm_tpu/ only: qa/fleet_chaos.py's ChaosProxy and the
# fuzzer are deliberate ATTACKER tooling and stay out of scope.
TRANSPORT_FILE = "pwasm_tpu/fleet/transport.py"
TLS_PATTERNS = re.compile(
    r"socket\.socket\s*\(|socket\.create_connection\s*\("
    r"|socket\.socketpair\s*\(|socket\.fromfd\s*\("
    r"|^\s*import\s+ssl\b|^\s*from\s+ssl\s+import\b|\bssl\.")


def find_tls_violations(root: str = REPO) -> list[str]:
    """Raw socket construction or ssl use outside fleet/transport.py
    (module comment above: the transport module is the zero-trust
    choke point; everything else dials/binds through it)."""
    out: list[str] = []
    pkg = os.path.join(root, "pwasm_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if rel == TRANSPORT_FILE:
                continue
            with open(path, encoding="utf-8") as f:
                for i, line in enumerate(f, 1):
                    if line.lstrip().startswith("#"):
                        continue
                    if TLS_PATTERNS.search(line):
                        out.append(
                            f"{rel}:{i}: socket/ssl use outside the "
                            f"transport module: {line.strip()} — "
                            f"mint connections and listeners through "
                            f"{TRANSPORT_FILE} so TLS, mTLS identity "
                            "and the 0600 socket contract cannot be "
                            "bypassed")
    return out


# ── private-directory gate (ISSUE 19 satellite) ──
# State directories (result spool, result cache, journals, compile
# cache) hold job payloads and capability material; a bare
# os.makedirs ships them default-umask world-readable.  Every
# directory-creation site in pwasm_tpu/ goes through
# utils/fsio.py::ensure_private_dir (0700 at creation) or registers a
# justified allowlist entry here.
FSIO_FILE = "pwasm_tpu/utils/fsio.py"
MAKEDIRS_RE = re.compile(r"\bos\.makedirs\s*\(|\bos\.mkdir\s*\(")

# path -> justification for a bare makedirs
PERM_ALLOWLIST = {
    "pwasm_tpu/utils/backend.py":
        "already makedirs(mode=0o700) WITH an owner/squat check — "
        "the probe-cache dir predates ensure_private_dir and needs "
        "the lstat validation inline",
}


def find_perm_violations(root: str = REPO) -> list[str]:
    """Bare os.makedirs/os.mkdir in pwasm_tpu/ outside fsio.py and
    PERM_ALLOWLIST — state dirs are created 0700 via
    ensure_private_dir."""
    out: list[str] = []
    pkg = os.path.join(root, "pwasm_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            if rel == FSIO_FILE or rel in PERM_ALLOWLIST:
                continue
            with open(path, encoding="utf-8") as f:
                for i, line in enumerate(f, 1):
                    if line.lstrip().startswith("#"):
                        continue
                    if MAKEDIRS_RE.search(line):
                        out.append(
                            f"{rel}:{i}: bare directory creation: "
                            f"{line.strip()} — use {FSIO_FILE}::"
                            "ensure_private_dir (0700) or register "
                            "a justified PERM_ALLOWLIST entry")
    return out


def stale_perm_allowlist(root: str = REPO) -> list[str]:
    """PERM_ALLOWLIST rows whose file no longer creates directories —
    same accuracy rule as the other registries."""
    out = []
    for rel in PERM_ALLOWLIST:
        path = os.path.join(root, *rel.split("/"))
        if not os.path.isfile(path):
            out.append(rel)
            continue
        with open(path, encoding="utf-8") as f:
            if not any(MAKEDIRS_RE.search(l) for l in f
                       if not l.lstrip().startswith("#")):
                out.append(rel)
    return out


def find_doc_drift(root: str = REPO) -> list[str]:
    """Catalog families missing from docs/OBSERVABILITY.md (module
    comment: the doc is the operator's catalog of record, so every
    registered family must appear there)."""
    doc_path = os.path.join(root, *METRIC_DOC.split("/"))
    if not os.path.isfile(doc_path):
        # no doc at all: every catalog name is undocumented
        doc_text = ""
    else:
        with open(doc_path, encoding="utf-8") as f:
            doc_text = f.read()
    out = []
    for name, lineno in sorted(catalog_metric_names(root).items(),
                               key=lambda kv: kv[1]):
        if name not in doc_text:
            out.append(
                f"{METRIC_CATALOG}:{lineno}: metric {name!r} is "
                f"registered but undocumented — add it to "
                f"{METRIC_DOC}")
    # the rule-name half (ISSUE 14 satellite): every default SLO rule
    # must appear in the doc's rule catalog — `health` says a rule
    # name to an operator, the doc owes them its meaning + runbook
    for name, lineno in sorted(catalog_rule_names(root).items(),
                               key=lambda kv: kv[1]):
        if name not in doc_text:
            out.append(
                f"{METRIC_CATALOG}:{lineno}: SLO rule {name!r} is "
                f"shipped as a default but undocumented — add it to "
                f"the rule table in {METRIC_DOC}")
    return out


def stale_registry_entries(root: str = REPO) -> list[str]:
    """Registry rows whose module no longer has any hit (or vanished) —
    kept accurate so the registry stays a map, not a fossil."""
    live = {rel for rel, _l, _s in find_hits(root)}
    return [rel for rel in REGISTRY if rel not in live]


def main() -> int:
    bad = find_unregistered()
    stale = stale_registry_entries()
    svc = find_service_violations()
    obs = find_obs_violations()
    stream = find_stream_violations()
    fleet = find_fleet_violations()
    surveil = find_surveil_violations()
    metric = find_metric_lint()
    doc_drift = find_doc_drift()
    sharding = find_sharding_violations()
    slo = find_slo_violations()
    cachev = find_cache_violations()
    fencing = find_fencing_violations()
    clock = find_clock_violations() + [
        f"{rel}: stale CLOCK_ALLOWLIST entry (no wall-clock "
        "subtraction left — remove it)"
        for rel in stale_clock_allowlist()]
    errvocab = find_error_vocab_gaps()
    tlsv = find_tls_violations()
    perm = find_perm_violations() + [
        f"{rel}: stale PERM_ALLOWLIST entry (no directory creation "
        "left — remove it)" for rel in stale_perm_allowlist()]
    for line in bad:
        print(line, file=sys.stderr)
    for rel in stale:
        print(f"{rel}: stale registry entry (no device entry points "
              "left — remove it)", file=sys.stderr)
    for line in svc + obs + stream + fleet + surveil + metric \
            + doc_drift + sharding + slo + cachev + fencing + clock \
            + errvocab + tlsv + perm:
        print(line, file=sys.stderr)
    if bad:
        print(f"\n{len(bad)} device entry point(s) outside the "
              "BatchSupervisor site registry.  Either route the work "
              "through a supervised site (resilience/supervisor.py) or "
              "register the module in qa/check_supervision.py with a "
              "justification.", file=sys.stderr)
    if svc or obs or stream or fleet:
        print(f"\n{len(svc) + len(obs) + len(stream) + len(fleet)} "
              "direct jax use(s) in pwasm_tpu/service/, "
              "pwasm_tpu/obs/, pwasm_tpu/stream/ or pwasm_tpu/fleet/."
              "  These layers reach the device only through "
              "supervised sites — move the device work there.",
              file=sys.stderr)
    if surveil:
        print(f"\n{len(surveil)} surveillance-pipeline gate "
              "failure(s): pwasm_tpu/surveil/ must exist and stay "
              "jax-free (ISSUE 20).", file=sys.stderr)
    if metric:
        print(f"\n{len(metric)} metric-name lint failure(s): all "
              "registrations live in pwasm_tpu/obs/catalog.py with "
              "snake_case pwasm_-prefixed unique names.",
              file=sys.stderr)
    if doc_drift:
        print(f"\n{len(doc_drift)} doc-drift failure(s): every "
              f"family registered in {METRIC_CATALOG} must appear in "
              f"{METRIC_DOC} (the operator's catalog of record).",
              file=sys.stderr)
    if sharding:
        print(f"\n{len(sharding)} bare sharding/collective API "
              f"use(s): import shard_map/psum/ppermute/pcast from "
              f"{JAXCOMPAT} instead, so a jax pin change costs one "
              "edit there.", file=sys.stderr)
    if slo:
        print(f"\n{len(slo)} self-monitoring gate failure(s): "
              "obs/slo.py and service/canary.py must exist and stay "
              "jax-free (ISSUE 14).", file=sys.stderr)
    if cachev:
        print(f"\n{len(cachev)} result-cache gate failure(s): "
              "service/cache.py must exist and stay jax-free "
              "(ISSUE 15).", file=sys.stderr)
    if fencing:
        print(f"\n{len(fencing)} fencing-invariant failure(s): "
              "every --resume re-admission path must route the "
              "job's epoch through fleet/fencing.py::"
              "readmit_epoch_guard (ISSUE 16).", file=sys.stderr)
    if clock:
        print(f"\n{len(clock)} monotonic-clock failure(s): durations "
              "come from time.monotonic(); time.time() subtraction "
              "is only legal on the CLOCK_ALLOWLIST (ISSUE 18).",
              file=sys.stderr)
    if errvocab:
        print(f"\n{len(errvocab)} error-vocabulary coverage "
              "failure(s): every protocol ERR_* code needs at least "
              "one test that provokes it (ISSUE 18).",
              file=sys.stderr)
    if tlsv:
        print(f"\n{len(tlsv)} transport-confinement failure(s): "
              f"sockets and ssl are minted only in {TRANSPORT_FILE} "
              "(ISSUE 19).", file=sys.stderr)
    if perm:
        print(f"\n{len(perm)} private-directory failure(s): state "
              "dirs are created 0700 via "
              "utils/fsio.py::ensure_private_dir (ISSUE 19).",
              file=sys.stderr)
    return 1 if (bad or stale or svc or obs or stream or fleet
                 or surveil or metric or doc_drift or sharding
                 or slo or cachev or fencing or clock or errvocab
                 or tlsv or perm) else 0


if __name__ == "__main__":
    sys.exit(main())
