#!/usr/bin/env python
"""Protocol fuzz harness (ISSUE 19 tentpole): seeded frame-level
mutation against a LIVE daemon and router.

The NDJSON protocol's whole attack surface is one line-framed reader
(``service/protocol.py::read_frame``) shared by every tier, so the
fuzzer's job is narrow and deep: throw every shape of hostile bytes
at a real accept loop — bit flips of valid frames, truncations,
length lies (lines past the server's frame ceiling), NUL and
UTF-8-invalid garbage, JSON non-objects, JSON bombs, pipelined
batches, mid-handshake aborts, slow-loris partial frames — and hold
the server to three survival contracts:

1. **liveness**: a control ``ping`` on a fresh connection answers
   ``ok`` after every mutation batch (and concurrently DURING the
   slow-loris hold — one wedged reader thread must never wedge the
   accept loop);
2. **truthful rejection**: every in-band answer to a hostile frame is
   a well-formed JSON error frame whose code is in the DOCUMENTED
   error vocabulary (protocol.py ``ERR_*``) — never a traceback,
   never a half-written line;
3. **no leaks**: file descriptors (``/proc/self/fd``) and thread
   counts return to their pre-campaign census (slack for the
   momentary accept) once the connections close.

Everything is DETERMINISTIC: a campaign is a pure function of
``(seed, n)`` via ``random.Random`` — a failure reproduces exactly.

Library use (tier-1 smoke, ``tests/test_protocol_fuzz.py``)::

    stats = fuzz_target(sock_path, n=500, seed=7)

``python qa/protocol_fuzz.py [--n=N] [--seed=S]`` runs the long
self-contained campaign (in-process daemon over unix AND tcp + an
in-process router, stub runners, no jax) and prints the stats as
JSON; ``qa/fleet_chaos.py --fuzz`` invokes the same entry point.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from random import Random

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from pwasm_tpu.fleet.transport import connect  # noqa: E402
from pwasm_tpu.service import protocol  # noqa: E402

# the documented rejection vocabulary: every in-band answer to a
# hostile frame must carry one of these codes (survival contract 2)
ERROR_VOCAB = frozenset(
    v for k, v in vars(protocol).items()
    if k.startswith("ERR_") and isinstance(v, str))

# valid baseline frames the mutators start from — a mix of open verbs
# and verbs that hit admission/auth/lookup paths
BASE_FRAMES = (
    b'{"cmd":"ping"}',
    b'{"cmd":"stats"}',
    b'{"cmd":"status","id":"fz-0"}',
    b'{"cmd":"result","id":"fz-0"}',
    b'{"cmd":"cancel","id":"fz-0"}',
    b'{"cmd":"inspect","id":"fz-0"}',
    b'{"cmd":"submit","argv":["x"],"client":"fz"}',
    b'{"cmd":"health"}',
    b'{"cmd":"nonesuch"}',
)

# a mutation is (payload_bytes, expect_read): expect_read=False means
# the mutator deliberately aborts mid-frame (truncation/slow-loris
# seed) and no answer is owed
N_MUTATION_KINDS = 9


def mutate(rng: Random, ceiling: int) -> tuple[bytes, bool]:
    """One deterministic hostile payload.  ``ceiling`` is the
    server's frame limit, so length-lie mutations can overshoot it
    cheaply (the harness runs servers with a small ceiling)."""
    kind = rng.randrange(N_MUTATION_KINDS)
    base = bytearray(rng.choice(BASE_FRAMES))
    if kind == 0:                     # bit flips in a valid frame
        for _ in range(rng.randrange(1, 9)):
            i = rng.randrange(len(base))
            base[i] ^= 1 << rng.randrange(8)
        return bytes(base).replace(b"\n", b" ") + b"\n", True
    if kind == 1:                     # truncation: abort mid-frame
        return bytes(base[: rng.randrange(1, len(base))]), False
    if kind == 2:                     # length lie: past the ceiling
        pad = b"A" * (ceiling + rng.randrange(1, 4096))
        return b'{"cmd":"ping","pad":"' + pad + b'"}\n', True
    if kind == 3:                     # NUL-riddled garbage
        raw = bytes(rng.randrange(256)
                    for _ in range(rng.randrange(1, 200)))
        return raw.replace(b"\n", b"\x00") + b"\n", True
    if kind == 4:                     # UTF-8-invalid JSON-ish line
        return (b'{"cmd":"\xff\xfe\xc0' +
                bytes([rng.randrange(0x80, 0x100)]) + b'"}\n'), True
    if kind == 5:                     # valid JSON, not an object
        return rng.choice(
            (b"[1,2,3]\n", b'"frame"\n', b"42\n", b"null\n",
             b"true\n")), True
    if kind == 6:                     # hostile field types
        return rng.choice((
            b'{"cmd":123}\n',
            b'{"cmd":["ping"]}\n',
            b'{"cmd":"submit","argv":"not-a-list"}\n',
            b'{"cmd":"status","id":{}}\n',
            b'{"cmd":"submit","argv":[],"deadline_ms":"soon"}\n',
            b'{"cmd":"logs","limit":-5}\n',
        )), True
    if kind == 7:                     # JSON bomb: deep nesting
        depth = rng.randrange(64, 2048)
        return (b'{"cmd":"ping","b":' + b"[" * depth
                + b"0" + b"]" * depth + b"}\n"), True
    # kind == 8: pipelined batch — several frames in one write, some
    # broken; the reader must stay line-synced across them
    parts = []
    for _ in range(rng.randrange(2, 6)):
        f = bytearray(rng.choice(BASE_FRAMES))
        if rng.random() < 0.5 and f:
            f[rng.randrange(len(f))] ^= 0xFF
        parts.append(bytes(f).replace(b"\n", b" "))
    return b"\n".join(parts) + b"\n", True


def fd_count() -> int:
    return len(os.listdir("/proc/self/fd"))


def census() -> tuple[int, int]:
    """(open fds, live threads) for the CURRENT process — the drill
    harnesses run their servers in-process, so a leaked server-side
    conn/thread shows up here too."""
    return fd_count(), threading.active_count()


def settle(before: tuple[int, int], slack: int = 4,
           timeout_s: float = 10.0) -> tuple[int, int]:
    """Wait for the census to return to within ``slack`` of
    ``before`` (connection threads exit asynchronously after close)
    and return the final census."""
    deadline = time.monotonic() + timeout_s
    now = census()
    while time.monotonic() < deadline:
        now = census()
        if now[0] <= before[0] + slack and now[1] <= before[1] + slack:
            break
        time.sleep(0.05)
    return now


def ping_ok(target: str, tls=None, timeout: float = 5.0) -> bool:
    """One control ping on a fresh connection (liveness contract)."""
    try:
        conn = connect(target, timeout=timeout, tls=tls)
    except OSError:
        return False
    try:
        conn.sendall(b'{"cmd":"ping"}\n')
        line = conn.makefile("rb").readline(1 << 16)
        return bool(line) and json.loads(line).get("ok") is True
    except (OSError, ValueError):
        return False
    finally:
        try:
            conn.close()
        except OSError:
            pass


def fuzz_target(target: str, n: int = 500, seed: int = 0,
                tls=None, ceiling: int = protocol.MAX_FRAME_BYTES,
                control_every: int = 50) -> dict:
    """Run ``n`` seeded mutations against ``target`` and return the
    measured facts; raises AssertionError the moment a survival
    contract breaks (with the seed in the message — reproduce with
    it).  ``tls`` is a transport ClientTLS for TLS targets."""
    rng = Random(seed)
    before = census()
    stats = {"target": target, "n": n, "seed": seed,
             "responses": 0, "aborts": 0, "closes": 0,
             "codes": {}, "control_pings": 0}
    assert ping_ok(target, tls), \
        f"target {target} not answering ping before the campaign"
    for i in range(n):
        payload, expect_read = mutate(rng, ceiling)
        try:
            conn = connect(target, timeout=5.0, tls=tls)
        except OSError as e:
            raise AssertionError(
                f"[seed={seed} mutation={i}] connect refused mid-"
                f"campaign: {e} — accept loop wedged or dead")
        try:
            try:
                conn.sendall(payload)
            except OSError:
                # server closed on us mid-send (fatal frame on a
                # pipelined batch): a loud close is a legal answer
                stats["closes"] += 1
                continue
            if not expect_read:
                stats["aborts"] += 1
                continue
            conn.settimeout(5.0)
            try:
                line = conn.makefile("rb").readline(1 << 16)
            except OSError:
                stats["closes"] += 1
                continue
            if not line:
                stats["closes"] += 1    # loud close: legal
                continue
            try:
                resp = json.loads(line)
            except ValueError:
                raise AssertionError(
                    f"[seed={seed} mutation={i}] non-JSON answer "
                    f"to a hostile frame: {line[:200]!r}")
            assert isinstance(resp, dict) and resp.get("ok") in \
                (True, False), \
                f"[seed={seed} mutation={i}] malformed frame {resp!r}"
            stats["responses"] += 1
            if resp.get("ok") is False:
                code = resp.get("error")
                assert code in ERROR_VOCAB, \
                    (f"[seed={seed} mutation={i}] undocumented "
                     f"error code {code!r} (vocabulary: "
                     f"{sorted(ERROR_VOCAB)})")
                stats["codes"][code] = stats["codes"].get(code, 0) + 1
        finally:
            try:
                conn.close()
            except OSError:
                pass
        if (i + 1) % control_every == 0:
            assert ping_ok(target, tls), \
                (f"[seed={seed} mutation={i}] control ping failed "
                 "mid-campaign — server wedged")
            stats["control_pings"] += 1
    assert ping_ok(target, tls), \
        f"[seed={seed}] target dead after the campaign"
    stats["control_pings"] += 1
    after = settle(before)
    assert after[0] <= before[0] + 4, \
        (f"[seed={seed}] fd leak: {before[0]} -> {after[0]} "
         "open descriptors after the campaign settled")
    assert after[1] <= before[1] + 4, \
        (f"[seed={seed}] thread leak: {before[1]} -> {after[1]} "
         "live threads after the campaign settled")
    stats["fd_before"], stats["fd_after"] = before[0], after[0]
    stats["threads_before"] = before[1]
    stats["threads_after"] = after[1]
    return stats


def slow_loris_drill(target: str, tls=None, holders: int = 6,
                     hold_s: float = 1.0) -> dict:
    """Open ``holders`` connections, send HALF a frame on each, hold
    them open, and prove fresh control pings answer concurrently —
    a parked reader thread must cost one thread, not the accept
    loop.  Returns measured facts."""
    before = census()
    held = []
    try:
        for _ in range(holders):
            c = connect(target, timeout=5.0, tls=tls)
            c.sendall(b'{"cmd":"ping","slow":"lo')   # no newline
            held.append(c)
        t0 = time.monotonic()
        alive = ping_ok(target, tls)
        ping_latency = time.monotonic() - t0
        time.sleep(hold_s)
        alive_after_hold = ping_ok(target, tls)
    finally:
        for c in held:
            try:
                c.close()
            except OSError:
                pass
    after = settle(before)
    return {"holders": holders, "alive_during_hold": alive,
            "alive_after_hold": alive_after_hold,
            "ping_latency_s": round(ping_latency, 3),
            "fd_before": before[0], "fd_after": after[0],
            "threads_before": before[1], "threads_after": after[1]}


def tls_garbage_drill(target: str, n: int = 50, seed: int = 0) -> dict:
    """Plaintext/garbage probes against a TLS port: dial WITHOUT tls,
    send seeded garbage (or nothing — a mid-handshake abort), and
    require a loud close, never a hang.  The server counts each as a
    handshake failure, not a crash."""
    rng = Random(seed)
    closed = 0
    for i in range(n):
        conn = connect(target, timeout=5.0, tls=None)
        try:
            if rng.random() < 0.3:
                pass                        # connect-then-abort
            else:
                conn.sendall(bytes(rng.randrange(256) for _ in
                                   range(rng.randrange(1, 128))))
            conn.settimeout(5.0)
            try:
                data = conn.recv(4096)
            except OSError:
                data = b""                  # reset: loud enough
            # a TLS server answers a plaintext probe with at most an
            # alert record then closes — crucially, recv() RETURNS
            # instead of hanging until the client gives up
            closed += 1 if len(data) < 4096 else 0
        finally:
            try:
                conn.close()
            except OSError:
                pass
    return {"probes": n, "loud_closes": closed}


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    n, seed = 2000, 0
    for a in argv:
        if a.startswith("--n="):
            n = int(a.split("=", 1)[1])
        elif a.startswith("--seed="):
            seed = int(a.split("=", 1)[1])
        else:
            print(__doc__, file=sys.stderr)
            return 2
    sys.path.insert(0, os.path.join(ROOT, "tests"))
    import io
    import shutil
    import tempfile
    from contextlib import ExitStack

    from test_fleet import _daemon, _stub_runner

    from pwasm_tpu.fleet.router import Router
    from pwasm_tpu.service.client import wait_for_socket

    ceiling = 4096
    out = {}
    with ExitStack() as stack:
        m = stack.enter_context(_daemon(
            runner=_stub_runner(), listen="127.0.0.1:0",
            max_frame_bytes=ceiling))
        rdir = tempfile.mkdtemp(prefix="pwfuzz")
        stack.callback(shutil.rmtree, rdir, True)
        rsock = os.path.join(rdir, "router.sock")
        err = io.StringIO()
        r = Router([m.sock], socket_path=rsock, stderr=err,
                   poll_interval=0.1, max_frame_bytes=ceiling)
        t = threading.Thread(target=r.serve, daemon=True)
        t.start()
        stack.callback(lambda: (r.drain.request("fuzz done"),
                                t.join(20)))
        if not wait_for_socket(rsock, 15):
            print(err.getvalue(), file=sys.stderr)
            return 1
        tcp = f"127.0.0.1:{m.daemon.tcp_port}"
        out["daemon_unix"] = fuzz_target(m.sock, n=n, seed=seed,
                                         ceiling=ceiling)
        out["daemon_tcp"] = fuzz_target(tcp, n=n, seed=seed + 1,
                                        ceiling=ceiling)
        out["router_unix"] = fuzz_target(rsock, n=n, seed=seed + 2,
                                         ceiling=ceiling)
        out["slow_loris"] = slow_loris_drill(m.sock)
    print(json.dumps(out, indent=2))
    ok = all(v.get("control_pings", 1) > 0 for v in out.values()) \
        and out["slow_loris"]["alive_during_hold"] \
        and out["slow_loris"]["alive_after_hold"]
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
