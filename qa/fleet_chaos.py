#!/usr/bin/env python
"""Fleet chaos harness (ISSUE 18): gray-failure fault injection at
the FLEET tier, plus the drills tier-1 tests and bench cfg8 gate.

Unit fault injection (``resilience/faults.py``) poisons device
batches inside one process; this module poisons the FLEET around
perfectly healthy members — the failures that pass every liveness
check while dragging the fleet's tail latency down:

- ``ChaosProxy``: an in-process TCP proxy in front of one member.
  ``delay_s`` makes the member a latency outlier without killing it
  (the canonical gray failure: every poll still succeeds, slowly);
  ``blackhole`` swallows bytes without ever answering (a
  half-partition — the connection opens, the reply never comes);
  ``truncate_after`` forwards N reply bytes then closes the wire
  (a torn NDJSON frame).
- ``StopWindows``: a SIGSTOP/SIGCONT duty cycle on a subprocess
  member — alive and heartbeating between stops, pathologically
  slow under them.
- ``deny_writes``: flips a journal/spool/cache dir unwritable so
  every durable append fails with the OSError class ENOSPC raises —
  the full-disk degradation path, exercised without filling a disk.

Drills (each RETURNS measured facts; the caller asserts):

- ``gray_drill``: watch a router until the named member is
  quarantined, call ``relieve()``, watch until probation-exit.
- ``deadline_drill``: submit through any tier (daemon or router)
  with an end-to-end budget and report the truthful verdict —
  refused-at-admission, expired-resumable (rc 75), or completed.

``python qa/fleet_chaos.py`` runs a self-contained in-process drill
(stub runners, no jax, no corpus): three members, one behind a delay
proxy, and prints the measured quarantine/recovery timings as JSON —
exit 0 only if the slow member was quarantined AND probation-exited.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import sys
import threading
import time
from contextlib import contextmanager

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)


# ---------------------------------------------------------------------------
# fault injectors
# ---------------------------------------------------------------------------
class ChaosProxy:
    """TCP proxy in front of one member socket (unix or host:port).

    The router is pointed at ``start()``'s returned ``host:port``
    instead of the member itself; every byte then crosses this proxy,
    and the knobs below are flipped at runtime (thread-safe — they
    apply to the next chunk pumped):

    - ``delay_s``: sleep before forwarding each client->member chunk
      (request latency without request loss);
    - ``blackhole``: read and DISCARD client bytes, forward nothing,
      answer nothing — the caller's timeout is the only way out;
    - ``truncate_after``: forward only the first N member->client
      bytes of each connection, then close both sides (torn frame).
    """

    def __init__(self, target: str, delay_s: float = 0.0):
        self.target = target
        self.delay_s = float(delay_s)
        self.blackhole = False
        self.truncate_after: int | None = None
        self._lsock: socket.socket | None = None
        self._closing = threading.Event()
        self._threads: list[threading.Thread] = []
        self.conns = 0

    # -- lifecycle --
    def start(self) -> str:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        s.listen(16)
        s.settimeout(0.2)
        self._lsock = s
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="chaos-proxy-accept")
        t.start()
        self._threads.append(t)
        return f"127.0.0.1:{s.getsockname()[1]}"

    def stop(self) -> None:
        self._closing.set()
        if self._lsock is not None:
            try:
                self._lsock.close()
            except OSError:
                pass

    # -- plumbing --
    def _upstream(self) -> socket.socket:
        if ":" in self.target and not os.path.exists(self.target):
            host, port = self.target.rsplit(":", 1)
            return socket.create_connection((host, int(port)),
                                            timeout=10)
        u = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        u.settimeout(10)
        u.connect(self.target)
        return u

    def _accept_loop(self) -> None:
        while not self._closing.is_set():
            try:
                conn, _ = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            self.conns += 1
            try:
                up = self._upstream()
            except OSError:
                conn.close()
                continue
            for src, dst, to_member in ((conn, up, True),
                                        (up, conn, False)):
                t = threading.Thread(
                    target=self._pump, args=(src, dst, to_member),
                    daemon=True, name="chaos-proxy-pump")
                t.start()
                self._threads.append(t)

    def _pump(self, src: socket.socket, dst: socket.socket,
              to_member: bool) -> None:
        sent = 0
        try:
            while not self._closing.is_set():
                try:
                    chunk = src.recv(65536)
                except OSError:
                    break
                if not chunk:
                    break
                if to_member:
                    if self.blackhole:
                        continue        # swallowed, never answered
                    d = self.delay_s
                    if d > 0:
                        time.sleep(d)
                else:
                    cut = self.truncate_after
                    if cut is not None:
                        chunk = chunk[:max(0, cut - sent)]
                        if not chunk:
                            break       # torn frame: close both ends
                try:
                    dst.sendall(chunk)
                    sent += len(chunk)
                except OSError:
                    break
                if not to_member and self.truncate_after is not None \
                        and sent >= self.truncate_after:
                    # the budget is spent THIS chunk: close both ends
                    # now rather than blocking on a reply that will
                    # never come (the member already answered whole)
                    break
        finally:
            # shutdown BEFORE close: the sibling pump thread is
            # blocked in recv() on these same sockets, and a bare
            # close() neither wakes it nor sends the FIN the far end
            # is waiting for — the torn frame must be promptly torn
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass


class StopWindows:
    """SIGSTOP/SIGCONT duty cycle on a subprocess member: the process
    is alive (its socket accepts, its journal exists, its parent sees
    no exit) but runs only ``run_s`` out of every
    ``stop_s + run_s`` — a gray member, not a dead one.  ``stop()``
    always leaves the victim SIGCONT'd."""

    def __init__(self, pid: int, stop_s: float = 0.3,
                 run_s: float = 0.1):
        self.pid = pid
        self.stop_s = float(stop_s)
        self.run_s = float(run_s)
        self._closing = threading.Event()
        self._thread: threading.Thread | None = None
        self.windows = 0

    def start(self) -> "StopWindows":
        self._thread = threading.Thread(target=self._loop,
                                        daemon=True,
                                        name="chaos-stop-windows")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._closing.is_set():
            try:
                os.kill(self.pid, signal.SIGSTOP)
            except OSError:
                return                # victim gone: nothing to chaos
            self.windows += 1
            self._closing.wait(self.stop_s)
            try:
                os.kill(self.pid, signal.SIGCONT)
            except OSError:
                return
            self._closing.wait(self.run_s)

    def stop(self) -> None:
        self._closing.set()
        if self._thread is not None:
            self._thread.join(5)
        try:
            os.kill(self.pid, signal.SIGCONT)
        except OSError:
            pass


@contextmanager
def deny_writes(path: str):
    """Make ``path`` (a journal/spool/cache dir) unwritable for the
    duration — every durable append inside fails with the OSError
    class a full disk raises, which is exactly the degradation
    surface ISSUE 18's ENOSPC satellite gates.  Restores the original
    mode on exit.  No-op (yields False) when running as root, where
    mode bits don't bind — callers skip the assertion then."""
    st_mode = os.stat(path).st_mode
    os.chmod(path, 0o500)
    effective = not os.access(path, os.W_OK)
    try:
        yield effective
    finally:
        os.chmod(path, st_mode)


# ---------------------------------------------------------------------------
# drill helpers
# ---------------------------------------------------------------------------
def wait_until(pred, timeout_s: float, interval: float = 0.05):
    """Poll ``pred()`` until truthy or the budget runs out; returns
    the last value (truthy = success)."""
    deadline = time.monotonic() + timeout_s
    val = pred()
    while not val and time.monotonic() < deadline:
        time.sleep(interval)
        val = pred()
    return val


def member_row(stats: dict, name: str) -> dict | None:
    """The named member's row from a router ``stats`` payload."""
    for row in (stats.get("fleet") or {}).get("members") or []:
        if row.get("name") == name:
            return row
    return None


def gray_drill(router_sock: str, member_name: str, relieve,
               detect_timeout_s: float = 30.0,
               recover_timeout_s: float = 30.0) -> dict:
    """THE gray-failure drill: with a fault already active on
    ``member_name`` (delay proxy / stop windows — the caller armed
    it), watch the router until the member is QUARANTINED, then call
    ``relieve()`` and watch until probation-exit.  Returns measured
    facts only; the caller owns the assertions:

    ``{"quarantined", "t_detect_s", "recovered", "t_recover_s",
       "quarantines_total", "eligible_floor_held"}``

    ``eligible_floor_held`` is True when at every observed sample at
    least one alive member remained unquarantined — the never-wedge
    property the router must keep even mid-chaos."""
    from pwasm_tpu.service.client import ServiceClient
    floor_held = True

    def _sample(c):
        nonlocal floor_held
        st = c.request({"cmd": "stats"})["stats"]
        rows = (st.get("fleet") or {}).get("members") or []
        if not any(r.get("alive") and not r.get("quarantined")
                   for r in rows):
            floor_held = False
        return member_row(st, member_name) or {}

    with ServiceClient(router_sock, timeout=10.0) as c:
        t0 = time.monotonic()
        quarantined = bool(wait_until(
            lambda: _sample(c).get("quarantined"),
            detect_timeout_s))
        t_detect = time.monotonic() - t0
        relieve()
        t1 = time.monotonic()
        recovered = quarantined and bool(wait_until(
            lambda: not _sample(c).get("quarantined"),
            recover_timeout_s))
        t_recover = time.monotonic() - t1
        row = _sample(c)
    return {"quarantined": quarantined,
            "t_detect_s": round(t_detect, 3),
            "recovered": recovered,
            "t_recover_s": round(t_recover, 3),
            "quarantines_total": int(row.get("quarantines") or 0),
            "eligible_floor_held": floor_held}


def deadline_drill(target: str, args: list, cwd: str,
                   deadline_s: float,
                   result_timeout_s: float = 120.0) -> dict:
    """Submit ``args`` through ``target`` (daemon or router socket)
    with an end-to-end budget and report the truthful outcome:

    - ``refused``: the budget was spent before admission
      (``deadline_exceeded`` at submit, nothing ran);
    - ``expired``: admitted, stopped at a batch boundary — state
      preempted, rc 75, detail says deadline_exceeded (resumable);
    - ``done``: completed inside the budget (rc 0).
    """
    from pwasm_tpu.service.client import ServiceClient
    out: dict = {"refused": False, "expired": False, "done": False,
                 "rc": None, "detail": ""}
    with ServiceClient(target, deadline_s=deadline_s,
                       timeout=60.0) as c:
        sub = c.submit(args, cwd=cwd)
        if not sub.get("ok"):
            out["refused"] = sub.get("error") == "deadline_exceeded"
            out["detail"] = str(sub.get("detail") or "")
            return out
        res = c.result(sub["job_id"], timeout=result_timeout_s)
        job = res.get("job") or {}
        out["rc"] = res.get("rc")
        out["detail"] = str(job.get("detail") or "")
        out["done"] = res.get("rc") == 0
        out["expired"] = (job.get("state") == "preempted"
                          and res.get("rc") == 75
                          and "deadline_exceeded" in out["detail"])
    return out


# ---------------------------------------------------------------------------
# standalone: the in-process gray drill (stub runners, no jax)
# ---------------------------------------------------------------------------
def main() -> int:
    if "--fuzz" in sys.argv[1:]:
        # the long protocol-fuzz campaign (ISSUE 19): same in-process
        # fleet shape, hostile bytes instead of latency faults —
        # qa/protocol_fuzz.py owns the mutation engine and the
        # survival contracts; extra --n=/--seed= flags pass through
        if HERE not in sys.path:
            sys.path.insert(0, HERE)
        from protocol_fuzz import main as fuzz_main
        return fuzz_main([a for a in sys.argv[1:] if a != "--fuzz"])
    sys.path.insert(0, os.path.join(ROOT, "tests"))
    import io
    import shutil
    import tempfile
    from contextlib import ExitStack

    from test_fleet import _daemon, _stub_runner

    from pwasm_tpu.fleet.router import Router
    from pwasm_tpu.fleet.transport import target_name
    from pwasm_tpu.service.client import wait_for_socket

    poll = 0.1
    with ExitStack() as stack:
        members = [stack.enter_context(
            _daemon(runner=_stub_runner(sleep=0.01)))
            for _ in range(3)]
        proxy = ChaosProxy(members[2].sock)
        addr = proxy.start()
        stack.callback(proxy.stop)
        rdir = tempfile.mkdtemp(prefix="pwchaos")
        stack.callback(shutil.rmtree, rdir, True)
        rsock = os.path.join(rdir, "router.sock")
        err = io.StringIO()
        r = Router([members[0].sock, members[1].sock, addr],
                   socket_path=rsock, stderr=err,
                   poll_interval=poll, quarantine_x=3.0)
        t = threading.Thread(target=r.serve, daemon=True)
        t.start()
        stack.callback(lambda: (r.drain.request("chaos drill done"),
                                t.join(20)))
        if not wait_for_socket(rsock, 15):
            print(err.getvalue(), file=sys.stderr)
            return 1
        # let the healthy EWMAs converge before injecting the fault,
        # then make member 2 a latency outlier (alive, never down)
        time.sleep(6 * poll)
        proxy.delay_s = 0.5
        res = gray_drill(rsock, target_name(addr),
                         relieve=lambda: setattr(proxy, "delay_s",
                                                 0.0))
        res["poll_interval_s"] = poll
    print(json.dumps(res, indent=2))
    ok = (res["quarantined"] and res["recovered"]
          and res["eligible_floor_held"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
