#!/usr/bin/env python
"""Bench regression gate (ROADMAP item 4: "make the bench a
regression gate").

Compares a FRESH bench run's config legs against the committed
trajectory (``BENCH_ALL.json``) and fails — exit 1 — on any
unexplained regression beyond a tolerance:

- wall-clock metrics (unit ``s`` or ``ms``): regression = new wall
  slower than ``old * (1 + tolerance)``;
- ratio metrics (unit ``x``, lower-is-better multipliers like
  ``realistic_pycli_vs_native_ratio``): same rule as walls;
- rate metrics (unit ending in ``/s``): regression = new rate below
  ``old * (1 - tolerance)``;
- boolean/parity legs (unit ``bool``): regression = a leg that WAS
  passing (truthy) now failing — a gained capability (0 -> 1) never
  regresses the gate.

Metrics present on only one side are reported as informational skips,
never failures: a new bench leg must be able to land before its first
trajectory entry exists, and a retired leg must not wedge the gate
forever.  ``--allow=metric1,metric2`` waives named metrics for one run
(an EXPLAINED slowdown — e.g. a deliberate precision/throughput trade
— is waived explicitly, in the PR that explains it, not silently
absorbed by a looser tolerance).

Usage:
    python bench.py ... > /tmp/fresh.json   # or PWASM_BENCH_OUT
    python qa/bench_gate.py NEW.json [--baseline=BENCH_ALL.json]
        [--tolerance=0.25] [--allow=metric_a,metric_b]

``NEW.json`` may be either the aggregate array (BENCH_ALL.json shape)
or a stream of one-JSON-object lines (bench.py stdout shape); rows
need ``metric``/``value``/``unit``.
"""

from __future__ import annotations

import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
DEFAULT_BASELINE = os.path.join(ROOT, "BENCH_ALL.json")
DEFAULT_TOLERANCE = 0.25   # bench walls on shared CPU runners are
#   noisy at the ±10-15% level; 25% is past noise but well under the
#   2x-class regressions the gate exists to catch


def load_rows(path: str) -> list[dict]:
    """Load bench rows from an aggregate JSON array or an NDJSON
    stream of per-leg objects (both shapes bench.py produces)."""
    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
        if isinstance(data, dict):
            data = [data]
        return [r for r in data if isinstance(r, dict)]
    except ValueError:
        rows = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict) and "metric" in obj:
                rows.append(obj)
        return rows


def index_rows(rows: list[dict]) -> dict[str, dict]:
    out = {}
    for r in rows:
        name = r.get("metric")
        if isinstance(name, str) and isinstance(
                r.get("value"), (int, float)):
            out[name] = r   # last occurrence wins (latest leg)
    return out


def _direction(unit: str) -> str:
    """lower = lower-is-better (walls, ratio multipliers), higher =
    higher-is-better (rates), bool = pass/fail leg, none = ungated
    (counts, ids)."""
    if unit in ("s", "ms", "x"):
        return "lower"
    if unit.endswith("/s"):
        return "higher"
    if unit == "bool":
        return "bool"
    return "none"


def compare(new_rows: list[dict], base_rows: list[dict],
            tolerance: float = DEFAULT_TOLERANCE,
            allow: frozenset[str] | set[str] = frozenset()) -> dict:
    """Pure comparison (the testable core): returns
    ``{"regressions": [...], "waived": [...], "improved": [...],
    "skipped": [...], "checked": N}`` where each entry is a dict with
    metric/unit/old/new/ratio."""
    new = index_rows(new_rows)
    base = index_rows(base_rows)
    regressions, waived, improved, skipped = [], [], [], []
    checked = 0
    for name in sorted(set(new) | set(base)):
        if name not in new or name not in base:
            skipped.append({"metric": name,
                            "why": "missing from "
                            + ("baseline" if name in new else "run")})
            continue
        unit = str(base[name].get("unit", ""))
        d = _direction(unit)
        if d == "none":
            continue
        old_v, new_v = base[name]["value"], new[name]["value"]
        checked += 1
        entry = {"metric": name, "unit": unit, "old": old_v,
                 "new": new_v}
        bad = False
        if d == "bool":
            bad = bool(old_v) and not bool(new_v)
        elif old_v <= 0:
            skipped.append({"metric": name,
                            "why": f"non-positive baseline {old_v}"})
            checked -= 1
            continue
        elif d == "lower":
            entry["ratio"] = round(new_v / old_v, 4)
            bad = new_v > old_v * (1.0 + tolerance)
            if new_v < old_v:
                improved.append(entry)
        else:
            entry["ratio"] = round(new_v / old_v, 4)
            bad = new_v < old_v * (1.0 - tolerance)
            if new_v > old_v:
                improved.append(entry)
        if bad:
            (waived if name in allow else regressions).append(entry)
    return {"regressions": regressions, "waived": waived,
            "improved": improved, "skipped": skipped,
            "checked": checked}


def main(argv: list[str]) -> int:
    new_path = None
    baseline = DEFAULT_BASELINE
    tolerance = DEFAULT_TOLERANCE
    allow: set[str] = set()
    for a in argv:
        if a.startswith("--baseline="):
            baseline = a.split("=", 1)[1]
        elif a.startswith("--tolerance="):
            import math
            try:
                tolerance = float(a.split("=", 1)[1])
                # nan/inf would make every comparison False — a gate
                # silently disabled by a CI templating typo
                if tolerance < 0 or not math.isfinite(tolerance):
                    raise ValueError
            except ValueError:
                print(f"bench_gate: bad --tolerance: {a}",
                      file=sys.stderr)
                return 2
        elif a.startswith("--allow="):
            allow |= {s for s in a.split("=", 1)[1].split(",") if s}
        elif a.startswith("-"):
            print(__doc__, file=sys.stderr)
            return 2
        elif new_path is None:
            new_path = a
        else:
            print(__doc__, file=sys.stderr)
            return 2
    if new_path is None:
        print(__doc__, file=sys.stderr)
        return 2
    try:
        new_rows = load_rows(new_path)
        base_rows = load_rows(baseline)
    except OSError as e:
        print(f"bench_gate: {e}", file=sys.stderr)
        return 2
    res = compare(new_rows, base_rows, tolerance, frozenset(allow))
    for e in res["skipped"]:
        print(f"bench_gate: skip {e['metric']} ({e['why']})")
    for e in res["improved"]:
        print(f"bench_gate: improved {e['metric']}: {e['old']} -> "
              f"{e['new']} {e['unit']}")
    for e in res["waived"]:
        print(f"bench_gate: WAIVED regression {e['metric']}: "
              f"{e['old']} -> {e['new']} {e['unit']} (--allow)")
    for e in res["regressions"]:
        print(f"bench_gate: REGRESSION {e['metric']}: {e['old']} -> "
              f"{e['new']} {e['unit']} "
              f"(ratio {e.get('ratio', 'n/a')}, tolerance "
              f"{tolerance:g})", file=sys.stderr)
    n = len(res["regressions"])
    print(f"bench_gate: {res['checked']} metric(s) checked, "
          f"{n} regression(s), {len(res['waived'])} waived")
    return 1 if n else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
