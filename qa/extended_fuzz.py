#!/usr/bin/env python
"""Extended QA sweeps — heavier than the CI suite, run ad hoc per round.

Five independent adversarial sweeps over the surfaces the test suite
fuzzes lightly.  Each prints one PASS/FAIL line; exit 0 iff all pass.
Run on CPU (JAX_PLATFORMS=cpu, 8 virtual devices recommended) or
against a real chip.  Round-3 findings credited to these sweeps: a
native process abort on inverted alignment spans (fixed: shared
coordinate validation) and the --skip-bad-lines gap at MSA insertion.

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python qa/extended_fuzz.py
"""

from __future__ import annotations

import contextlib
import io
import os
import random
import sys
import tempfile

# Pin jax to host CPU exactly like tests/conftest.py: this environment's
# site hook registers a TPU-tunnel backend that overrides even
# JAX_PLATFORMS=cpu, and a downed tunnel would block the --device=tpu
# sweeps forever.  (Run against the real chip by exporting
# PWASM_QA_REAL_CHIP=1 first.)
if os.environ.get("PWASM_QA_REAL_CHIP", "") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    # like tests/conftest.py: sweeps must not arm the process-global
    # persistent compilation cache (hundreds of one-off oracle shapes
    # would pollute the production cache dir)
    os.environ.setdefault("PWASM_JAX_CACHE", "0")
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        import jax._src.xla_bridge as _xb

        getattr(_xb, "_backend_factories", {}).pop("axon", None)
    except Exception:
        pass

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))


def sweep_refine_batch(seeds: int = 40) -> bool:
    """Batched X-drop refinement vs the scalar reference transliteration
    AND the device phase program (ops/refine_clip.py), all
    (skip_dels x with_dels) regimes — three-way bit-exactness."""
    from test_gapseq_refine import _clone, _random_gapseq

    from pwasm_tpu.align.gapseq import refine_clipping_batch

    bad = total = 0
    for seed in range(seeds):
        rng = np.random.default_rng(1000 + seed)
        for skip_dels in (False, True):
            for with_dels in (False, True):
                seqs, clones, dev, cposes = [], [], [], []
                for _ in range(16):
                    s = _random_gapseq(rng, with_dels=with_dels)
                    seqs.append(s)
                    clones.append(_clone(s))
                    dev.append(_clone(s))
                    cposes.append(int(rng.integers(0, 6)))
                gm = max(s.seqlen + s.numgaps + 8 for s in seqs)
                cons = rng.choice(list(b"ACGT*"), gm + 10).astype("uint8").tobytes()
                eh, ed = io.StringIO(), io.StringIO()
                with contextlib.redirect_stderr(eh):
                    refine_clipping_batch(seqs, cons, cposes,
                                          skip_dels=skip_dels)
                with contextlib.redirect_stderr(io.StringIO()):
                    for c, cp in zip(clones, cposes):
                        c.refine_clipping_scalar(cons, cp,
                                                 skip_dels=skip_dels)
                with contextlib.redirect_stderr(ed):
                    demoted = refine_clipping_batch(
                        dev, cons, cposes, skip_dels=skip_dels,
                        device=True)
                if demoted or eh.getvalue() != ed.getvalue():
                    bad += 1
                for s, c, v in zip(seqs, clones, dev):
                    total += 1
                    if (s.clp5, s.clp3) != (c.clp5, c.clp3) \
                            or (s.clp5, s.clp3) != (v.clp5, v.clp3):
                        bad += 1
    print(f"[{'PASS' if not bad else 'FAIL'}] refine "
          f"batch-vs-scalar-vs-device: {bad} mismatches / {total}")
    return bad == 0


def sweep_realign_oracle(seeds: int = 25) -> bool:
    """Row-walk re-aligner (auto kernel) vs the full-Gotoh oracle with a
    band covering the whole matrix — scores AND op strings."""
    from pwasm_tpu.ops.realign import (banded_realign_rows,
                                       full_gotoh_traceback,
                                       rows_to_ops_fwd)

    bad = total = 0
    for seed in range(seeds):
        rng = np.random.default_rng(2000 + seed)
        T, m_max, n_max = 12, 70, 90
        qs = np.full((T, m_max), 127, np.int8)
        ts = np.full((T, n_max), 127, np.int8)
        qls = np.zeros(T, np.int32)
        tls = np.zeros(T, np.int32)
        oracle = []
        for k in range(T):
            m = int(rng.integers(5, m_max + 1))
            q = rng.integers(0, 4, m).astype(np.int8)
            t = list(q)
            for _ in range(int(rng.integers(0, 12))):
                p = int(rng.integers(0, max(1, len(t) - 1)))
                r = rng.random()
                if r < 0.4:
                    t[p] = int(rng.integers(0, 4))
                elif r < 0.7:
                    t.insert(p, int(rng.integers(0, 4)))
                elif len(t) > 2:
                    del t[p]
            t = np.array(t[:n_max], np.int8)
            oracle.append(full_gotoh_traceback(q, t))
            qs[k, :m] = q
            ts[k, :len(t)] = t
            qls[k] = m
            tls[k] = len(t)
        sc, leads, iy, ops, ok = (np.asarray(x) for x in
                                  banded_realign_rows(qs, ts, qls, tls,
                                                      band=256))
        for k in range(T):
            want_s, want_o = oracle[k]
            total += 1
            got = rows_to_ops_fwd(int(leads[k]), iy[k], ops[k],
                                  int(qls[k]))
            if not ok[k] or sc[k] != want_s \
                    or not np.array_equal(got, want_o):
                bad += 1
    print(f"[{'PASS' if not bad else 'FAIL'}] realign-vs-oracle: "
          f"{bad} mismatches / {total}")
    return bad == 0


def sweep_fai_roundtrip(trials: int = 120) -> bool:
    """.fai sidecar: random record shapes (uniform/irregular/CRLF/blank
    lines/interior whitespace/no final newline) — reload must fetch
    identically whether the sidecar persisted or a rescan ran."""
    from pwasm_tpu.core.fasta import FastaFile

    rng = np.random.default_rng(7)
    bad = checked = 0
    for _ in range(trials):
        with tempfile.TemporaryDirectory() as td:
            p = os.path.join(td, "f.fa")
            recs, body = [], []
            for r in range(int(rng.integers(1, 6))):
                name = f"s{r}"
                L = int(rng.integers(1, 200))
                seq = "".join("ACGT"[i] for i in rng.integers(0, 4, L))
                style = rng.integers(0, 5)
                if style == 0:
                    w = int(rng.integers(1, 80))
                    lines = [seq[i:i + w] for i in range(0, L, w)]
                elif style == 1:
                    lines, i = [], 0
                    while i < L:
                        w = int(rng.integers(1, 30))
                        lines.append(seq[i:i + w])
                        i += w
                elif style == 2:
                    w = int(rng.integers(1, 60))
                    lines = [seq[i:i + w] + "\r"
                             for i in range(0, L, w)]
                elif style == 3:
                    w = max(1, L // 2)
                    lines = [seq[:w], "", seq[w:]]
                else:
                    lines = [seq[:L // 2] + " " + seq[L // 2:]]
                body.append(f">{name}\n" + "\n".join(lines) + "\n")
                recs.append((name, seq.replace(" ", "").encode()))
            text = "".join(body)
            if rng.random() < 0.2:
                text = text.rstrip("\n")
            with open(p, "w") as f:
                f.write(text)
            fa1 = FastaFile(p)
            fa2 = FastaFile(p)
            for name, seq in recs:
                checked += 1
                if fa1.fetch(name) != seq or fa2.fetch(name) != seq:
                    bad += 1
    print(f"[{'PASS' if not bad else 'FAIL'}] .fai roundtrip: "
          f"{bad} bad fetches / {checked}")
    return bad == 0


def sweep_paf_corruption(trials: int = 20000) -> bool:
    """Random corruptions of valid PAF lines: every outcome must be a
    clean accept or PwasmError — never a crash (this sweep found the
    native std::length_error abort in round 3)."""
    from helpers import make_paf_line

    from pwasm_tpu.core.dna import revcomp
    from pwasm_tpu.core.errors import PwasmError
    from pwasm_tpu.core.events import extract_alignment
    from pwasm_tpu.core.paf import parse_paf_line

    rng = np.random.default_rng(99)
    random.seed(99)
    Q = "".join("ACGT"[i] for i in rng.integers(0, 4, 90))
    base_lines = []
    for strand in "+-":
        for ops in ([("=", 90)],
                    [("=", 30), ("ins", "ttg"), ("=", 60)],
                    [("=", 40), ("del", 5), ("=", 45)]):
            base_lines.append(
                make_paf_line("q", Q, "t0", strand, ops)[0])
    alpha = "ACGTacgt:*+-~0123456789\tNnXx"
    ok = err = 0
    for _ in range(trials):
        s = list(random.choice(base_lines))
        for _ in range(random.randint(1, 5)):
            p = random.randrange(len(s))
            r = random.random()
            if r < 0.5:
                s[p] = random.choice(alpha)
            elif r < 0.8:
                s.insert(p, random.choice(alpha))
            elif len(s) > 2:
                del s[p]
        try:
            rec = parse_paf_line("".join(s))
            al = rec.alninfo
            refseq = Q.encode()
            if al.r_len != len(refseq):
                raise PwasmError("len mismatch\n")
            refseq_aln = revcomp(refseq) if al.reverse else refseq
            extract_alignment(rec, refseq_aln)
            ok += 1
        except PwasmError:
            err += 1
    print(f"[PASS] paf corruption: {ok} accepted, {err} rejected "
          f"cleanly, 0 crashes / {trials}")
    return True


def sweep_cli_parity(trials: int = 15) -> bool:
    """Random anchored alignment sets through the full CLI: cpu, tpu and
    tpu+shard outputs (.dfa/.ace/.mfa/.info) must be byte-identical."""
    from helpers import make_paf_line

    from pwasm_tpu.cli import run
    from pwasm_tpu.core.dna import revcomp
    from pwasm_tpu.core.fasta import write_fasta

    rng = np.random.default_rng(11)
    bad = 0
    for trial in range(trials):
        with tempfile.TemporaryDirectory() as td:
            L = int(rng.integers(60, 240))
            Q = "".join("ACGT"[i] for i in rng.integers(0, 4, L))
            fa = os.path.join(td, "q.fa")
            write_fasta(fa, [("q", Q.encode())])
            lines = []
            for k in range(int(rng.integers(2, 14))):
                strand = "-" if rng.random() < 0.3 else "+"
                q_aln = revcomp(Q.encode()).decode() \
                    if strand == "-" else Q
                head = int(rng.integers(3, 10))
                tail = int(rng.integers(3, 10))
                ops = [("=", head)]
                pos = head
                while pos < L - tail:
                    r = rng.random()
                    span = int(rng.integers(1, L - tail - pos + 1))
                    if r < 0.55:
                        ops.append(("=", span))
                        pos += span
                    elif r < 0.7:
                        qb = q_aln[pos]
                        tb = "ACGT"[("ACGT".index(qb) + 1) % 4]
                        ops.append(("*", tb.lower(), qb.lower()))
                        pos += 1
                    elif r < 0.85:
                        ins = "".join(
                            "acgt"[i] for i in rng.integers(
                                0, 4, int(rng.integers(1, 6))))
                        ops.append(("ins", ins))
                    else:
                        d = min(int(rng.integers(1, 6)),
                                L - tail - pos)
                        if d > 0:
                            ops.append(("del", d))
                            pos += d
                ops.append(("=", L - pos))
                lines.append(
                    make_paf_line("q", Q, f"t{k:02d}", strand, ops)[0])
            paf = os.path.join(td, "in.paf")
            with open(paf, "w") as f:
                f.write("".join(l + "\n" for l in lines))
            # parity is judged WITHIN each feature-flag variant: devices
            # must agree byte-for-byte whatever the pipeline does
            for vname, vflags in (("base", []),
                                  ("realign", ["--realign"]),
                                  ("rcg", ["--remove-cons-gaps"])):
                outs = {}
                for mode, extra in (("cpu", ["--device=cpu"]),
                                    ("tpu", ["--device=tpu"]),
                                    ("shard", ["--device=tpu",
                                               "--shard"])):
                    tag = f"{vname}_{mode}"
                    rc = run([paf, "-r", fa,
                              "-o", os.path.join(td, f"{tag}.dfa"),
                              f"--ace={os.path.join(td, tag + '.ace')}",
                              "-w", os.path.join(td, f"{tag}.mfa"),
                              f"--info={os.path.join(td, tag)}.info"]
                             + vflags + extra, stderr=io.StringIO())
                    if rc != 0:
                        bad += 1
                        continue
                    outs[mode] = "".join(
                        open(os.path.join(td, f"{tag}.{e}")).read()
                        for e in ("dfa", "ace", "mfa", "info"))
                if len(set(outs.values())) != 1:
                    bad += 1
    print(f"[{'PASS' if not bad else 'FAIL'}] CLI parity "
          f"(cpu/tpu/shard): {bad} divergent trials / {trials}")
    return bad == 0


def sweep_native_cli_parity(trials: int = 25) -> bool:
    """Random anchored alignment sets through BOTH front ends: the
    standalone C++ binary's outputs (.dfa/.mfa/.ace/.info/.cons +
    summary + stderr) must be byte-identical to the Python CLI's CPU
    path, across the refinement-flag variants (and both Python-side
    MSA engines — trials alternate the native-engine delegation)."""
    from pwasm_tpu.native import native_cli_path

    cli = native_cli_path()
    if cli is None:
        print("[SKIP] native CLI parity: no toolchain")
        return True
    rng = np.random.default_rng(13)
    saved_delegation = os.environ.get("PWASM_NATIVE_MSA")
    try:
        bad = _native_cli_parity_trials(cli, rng, trials)
    finally:
        if saved_delegation is None:
            os.environ.pop("PWASM_NATIVE_MSA", None)
        else:
            os.environ["PWASM_NATIVE_MSA"] = saved_delegation
    print(f"[{'PASS' if not bad else 'FAIL'}] native-binary CLI parity: "
          f"{bad} divergent trials / {trials}")
    return bad == 0


def _native_cli_parity_trials(cli, rng, trials) -> int:
    import subprocess

    from helpers import make_paf_line

    from pwasm_tpu.cli import run
    from pwasm_tpu.core.dna import revcomp
    from pwasm_tpu.core.fasta import write_fasta

    bad = 0
    for trial in range(trials):
        with tempfile.TemporaryDirectory() as td:
            L = int(rng.integers(60, 240))
            Q = "".join("ACGT"[i] for i in rng.integers(0, 4, L))
            fa = os.path.join(td, "q.fa")
            write_fasta(fa, [("q", Q.encode())])
            lines = []
            for k in range(int(rng.integers(2, 14))):
                strand = "-" if rng.random() < 0.3 else "+"
                q_aln = revcomp(Q.encode()).decode() \
                    if strand == "-" else Q
                head = int(rng.integers(3, 10))
                tail = int(rng.integers(3, 10))
                ops = [("=", head)]
                pos = head
                while pos < L - tail:
                    r = rng.random()
                    span = int(rng.integers(1, L - tail - pos + 1))
                    if r < 0.55:
                        ops.append(("=", span))
                        pos += span
                    elif r < 0.7:
                        qb = q_aln[pos]
                        tb = "ACGT"[("ACGT".index(qb) + 1) % 4]
                        ops.append(("*", tb.lower(), qb.lower()))
                        pos += 1
                    elif r < 0.85:
                        ins = "".join(
                            "acgt"[i] for i in rng.integers(
                                0, 4, int(rng.integers(1, 6))))
                        ops.append(("ins", ins))
                    else:
                        d = min(int(rng.integers(1, 6)),
                                L - tail - pos)
                        if d > 0:
                            ops.append(("del", d))
                            pos += d
                ops.append(("=", L - pos))
                lines.append(
                    make_paf_line("q", Q, f"t{k:02d}", strand, ops)[0])
            # sprinkle duplicates and self-alignments for the warning
            # paths (both must be byte-identical on stderr too)
            if lines and rng.random() < 0.5:
                lines.append(lines[0])
            if rng.random() < 0.5:
                lines.append(make_paf_line("q", Q, "q", "+",
                                           [("=", L)])[0])
            paf = os.path.join(td, "in.paf")
            with open(paf, "w") as f:
                f.write("".join(l + "\n" for l in lines))
            # alternate (per trial) the Python CLI between the delegated
            # native MSA engine and the Python engine, so BOTH stay
            # byte-locked to the standalone binary
            os.environ["PWASM_NATIVE_MSA"] = "0" if trial % 2 else "1"
            for vname, vflags in (("base", []),
                                  ("rcg", ["--remove-cons-gaps"]),
                                  ("norc", ["--no-refine-clip"])):
                exts = ("dfa", "mfa", "ace", "info", "cons", "sum")
                def outset(tag):
                    return [
                        "-o", os.path.join(td, f"{tag}.dfa"),
                        "-w", os.path.join(td, f"{tag}.mfa"),
                        f"--ace={os.path.join(td, tag + '.ace')}",
                        f"--info={os.path.join(td, tag + '.info')}",
                        f"--cons={os.path.join(td, tag + '.cons')}",
                        "-s", os.path.join(td, f"{tag}.sum")]
                perr = io.StringIO()
                rc_p = run([paf, "-r", fa] + outset(f"{vname}_p")
                           + vflags, stderr=perr)
                res = subprocess.run(
                    [cli, paf, "-r", fa] + outset(f"{vname}_n") + vflags,
                    capture_output=True, text=True)
                if res.returncode != rc_p:
                    bad += 1
                    continue
                if res.stderr != perr.getvalue():
                    bad += 1
                    continue
                for e in exts:
                    pf = os.path.join(td, f"{vname}_p.{e}")
                    nf = os.path.join(td, f"{vname}_n.{e}")
                    pb = open(pf, "rb").read() if os.path.exists(pf) \
                        else None
                    nb = open(nf, "rb").read() if os.path.exists(nf) \
                        else None
                    if pb != nb:
                        bad += 1
                        break
    return bad


def sweep_ragged_m2m(trials: int = 12) -> bool:
    """Ragged many2many vs the per-pair banded oracle under adversarial
    length distributions (duplicates, 1-base seqs, huge spread, counts
    indivisible by mesh factors), flat AND 8-virtual-device mesh."""
    import jax.numpy as jnp
    import numpy as np

    from pwasm_tpu.core.dna import encode
    from pwasm_tpu.ops.banded_dp import banded_score
    from pwasm_tpu.parallel.bucketing import PAD
    from pwasm_tpu.parallel.many2many import (make_mesh2d,
                                              many2many_scores_ragged)

    rng = random.Random(20260730)
    bad = 0
    mesh = make_mesh2d(8)
    for trial in range(trials):
        band = rng.choice([16, 64])
        nq = rng.randint(1, 6)
        nt = rng.randint(1, 10)
        def seq(lo, hi):
            n = rng.randint(lo, hi)
            return bytes(rng.choice(b"ACGT") for _ in range(n))
        qs = [seq(1, 80) for _ in range(nq)]
        if nq > 1 and rng.random() < 0.5:
            qs[1] = qs[0]                      # duplicate lengths
        ts = [seq(1, 400) for _ in range(nt)]
        got = many2many_scores_ragged(qs, ts, band=band)
        got_mesh = many2many_scores_ragged(qs, ts, band=band,
                                           mesh=mesh)
        if (got != got_mesh).any():
            bad += 1
            print(f"[ragged-m2m] trial {trial}: mesh != flat")
            continue
        ts_enc = [encode(t.upper()) for t in ts]
        for i, q in enumerate(qs):
            qe = encode(q.upper())
            m = len(qe)
            for j, te in enumerate(ts_enc):
                n_eff = m if len(te) <= m else m + band - 2
                tp = np.full(n_eff, PAD, dtype=np.int8)
                tp[:min(len(te), n_eff)] = te[:n_eff]
                want = int(banded_score(
                    jnp.asarray(qe), jnp.asarray(tp),
                    jnp.asarray(len(te)), band=band))
                if int(got[i, j]) != want:
                    bad += 1
                    print(f"[ragged-m2m] trial {trial} cell "
                          f"({i},{j}): {got[i, j]} != {want}")
                    break
            else:
                continue
            break
    tag = "PASS" if bad == 0 else "FAIL"
    print(f"[{tag}] ragged-m2m vs per-pair oracle (flat+mesh): "
          f"{bad} bad trials / {trials}")
    return bad == 0


def main() -> int:
    results = [sweep_refine_batch(), sweep_realign_oracle(),
               sweep_fai_roundtrip(), sweep_paf_corruption(),
               sweep_cli_parity(), sweep_native_cli_parity(),
               sweep_ragged_m2m()]
    return 0 if all(results) else 1


if __name__ == "__main__":
    sys.exit(main())
