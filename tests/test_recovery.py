"""Backend flap recovery (pwasm_tpu.resilience.health, ISSUE 3).

The acceptance contract: a scripted outage window
(``--inject-faults=down=A-B``) on the device CLI path opens the global
breaker mid-run, the health monitor re-probes on a capped-exponential
schedule, hysteresis recloses the breaker after the window, and
subsequent batches run on the device again — with ``-o``/``-w`` output
byte-identical to the fault-free run and ``breaker_recloses >= 1`` /
``recovered_batches > 0`` in ``--stats``.  ``--recover=off`` keeps
PR 1's terminal breaker.  Breaker/monitor/fault-clock state rides the
``<report>.ckpt`` so a ``--resume`` after a mid-outage kill re-promotes
inside the same scripted window.
"""

import io
import json
import os
import time

import numpy as np
import pytest

from pwasm_tpu.cli import run
from pwasm_tpu.core.fasta import write_fasta
from pwasm_tpu.resilience import (BatchSupervisor, InjectedKill,
                                  ResiliencePolicy, parse_fault_spec)
from pwasm_tpu.resilience.health import (BackendHealthMonitor,
                                         wait_for_backend)
from pwasm_tpu.utils.runstats import RunStats

from helpers import make_paf_line


def _policy(**kw):
    kw.setdefault("backoff_s", 0.001)
    kw.setdefault("backoff_cap_s", 0.002)
    return ResiliencePolicy(**kw)


# ---------------------------------------------------------------------------
# fault plan: down= windows
# ---------------------------------------------------------------------------
def test_down_spec_parsing():
    p = parse_fault_spec("down=3-6")
    assert p.down == ((3, 6),)
    p = parse_fault_spec("down=2-4+9-12,seed=5")
    assert p.down == ((2, 4), (9, 12)) and p.seed == 5


@pytest.mark.parametrize("bad", ["down=", "down=5", "down=0-3",
                                 "down=6-2", "down=a-b", "down=1-2+"])
def test_down_spec_rejects(bad):
    with pytest.raises(ValueError):
        parse_fault_spec(bad)


def test_down_window_scripts_outage_on_call_clock():
    p = parse_fault_spec("down=2-3")
    # call clock, not draw clock: retries inside one call share the
    # window membership of that call
    p.note_call()
    assert p.draw("s") is None and not p.in_outage()
    p.note_call()
    assert p.in_outage() and p.outage_probe() is not None
    assert p.draw("s") == "down" == p.draw("s")   # retries fail too
    p.note_call()
    assert p.draw("s") == "down"
    p.note_call()
    assert not p.in_outage() and p.outage_probe() is None
    assert p.draw("s") is None


def test_down_window_dominates_sites_and_rate():
    # a dead tunnel fails every site regardless of sites=/rate=
    p = parse_fault_spec("down=1-2,rate=0,sites=other")
    p.note_call()
    assert p.draw("ctx_scan") == "down"


def test_effective_hang_cap():
    p = parse_fault_spec("hang_s=30")
    assert p.effective_hang(None) == 1.0          # deadline-less cap
    assert p.effective_hang(0.05) == pytest.approx(0.2)   # 4x deadline
    assert parse_fault_spec("hang_s=0.01").effective_hang(5) == 0.01


def test_injected_hang_capped_without_deadline():
    # the satellite contract: a default-30s hang must not stall a
    # deadline-less suite — the supervisor sleeps the capped time only
    st = RunStats()
    sup = BatchSupervisor(_policy(max_retries=0), stats=st,
                          stderr=io.StringIO(),
                          faults=parse_fault_spec("rate=1,kinds=hang"))
    t0 = time.perf_counter()
    assert sup.run("s", lambda: "ok") == "ok"
    assert time.perf_counter() - t0 < 5.0
    assert st.res_injected_faults == 1


# ---------------------------------------------------------------------------
# BackendHealthMonitor: schedule + hysteresis
# ---------------------------------------------------------------------------
class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_monitor_capped_exponential_schedule():
    clk = _Clock()
    probes = []

    def probe():
        probes.append(clk.t)
        return False, "down"

    st = RunStats()
    mon = BackendHealthMonitor(probe=probe, interval_s=2.0,
                               max_interval_s=10.0, stats=st,
                               stderr=io.StringIO(), clock=clk)
    mon.note_open()
    for _ in range(200):
        clk.t += 1.0
        mon.poll()
    # probes at +2, then doubling 4, 8, capped at 10
    gaps = [round(b - a) for a, b in zip(probes, probes[1:])]
    assert probes[0] == 2.0
    assert gaps[:3] == [4, 8, 10]
    assert set(gaps[3:]) == {10}
    assert st.res_reprobe_attempts == len(probes)


def test_monitor_schedules_from_post_probe_clock():
    # a real probe of a HUNG tunnel blocks for its full subprocess
    # timeout (150 s default) — the next probe must be scheduled from
    # the post-probe clock, or every early-backoff step would already
    # be due on return and degraded batches would stall back-to-back
    clk = _Clock()
    probes = []

    def hung_probe():
        probes.append(clk.t)
        clk.t += 150.0           # the probe itself eats wall time
        return False, "hang"

    mon = BackendHealthMonitor(probe=hung_probe, interval_s=5.0,
                               max_interval_s=300.0,
                               stderr=io.StringIO(), clock=clk)
    mon.note_open()
    for _ in range(2000):
        clk.t += 1.0
        mon.poll()
    gaps = [b - a for a, b in zip(probes, probes[1:])]
    assert len(probes) >= 3
    # every inter-probe gap spans the probe wall PLUS a real backoff
    assert all(g >= 150 + 5 for g in gaps), gaps


def test_monitor_hysteresis_and_halfopen_regression():
    clk = _Clock()
    verdicts = iter([False, True, False,        # healthy blip: no heal
                     True, True])               # 2 consecutive: reclose
    mon = BackendHealthMonitor(probe=lambda: (next(verdicts), ""),
                               interval_s=1.0, max_interval_s=8.0,
                               hysteresis=2, stderr=io.StringIO(),
                               clock=clk)
    mon.note_open()
    healed = []
    for _ in range(60):
        clk.t += 1.0
        if mon.poll():
            healed.append(clk.t)
            break
    assert healed, "monitor never healed"
    # the lone healthy probe half-opened, the next unhealthy one fell
    # back to open (streak reset) — only the final two healthy probes
    # in a row reclosed
    assert mon.state == "closed"


def test_wait_for_backend_bounded():
    # healthy on the 3rd probe: returns True well inside the budget
    verdicts = iter([False, False, True])
    assert wait_for_backend(5.0, interval_s=0.01, max_interval_s=0.02,
                            probe=lambda: (next(verdicts), ""),
                            stderr=io.StringIO())
    # never healthy: bounded False, no hang
    t0 = time.monotonic()
    assert not wait_for_backend(0.3, interval_s=0.05,
                                max_interval_s=0.1,
                                probe=lambda: (False, "down"),
                                stderr=io.StringIO())
    assert time.monotonic() - t0 < 5.0


# ---------------------------------------------------------------------------
# supervisor: open -> half-open -> closed, re-promotion, state export
# ---------------------------------------------------------------------------
def _flap_supervisor(spec="down=2-4", hysteresis=2, **kw):
    st = RunStats()
    err = io.StringIO()
    mon = BackendHealthMonitor(interval_s=0, max_interval_s=0,
                               hysteresis=hysteresis, stats=st,
                               stderr=err)
    sup = BatchSupervisor(_policy(max_retries=4, **kw), stats=st,
                          stderr=err, faults=parse_fault_spec(spec),
                          probe=lambda: (True, ""), monitor=mon)
    return sup, st, err


def test_supervisor_flap_open_then_reclose():
    sup, st, err = _flap_supervisor()
    got = [sup.run("ctx_scan", lambda i=i: f"dev{i}",
                   fallback=lambda i=i: f"host{i}")
           for i in range(1, 11)]
    # call 1 device; calls 2-5 host (window 2-4 opens the breaker at
    # call 2, probes stay scripted-dead through call 4, hysteresis
    # needs 2 healthy probes: calls 5+6 probe healthy, reclose DURING
    # call 6); calls 6-10 device again
    assert got == ["dev1", "host2", "host3", "host4", "host5",
                   "dev6", "dev7", "dev8", "dev9", "dev10"]
    assert not sup.breaker_open and sup.recloses == 1
    assert st.res_breaker_trips == 1
    assert st.res_breaker_recloses == 1
    assert st.res_degraded_batches == 3     # calls 3, 4, 5
    assert st.res_recovered_batches == 5    # calls 6-10
    assert st.res_reprobe_attempts >= 3
    assert st.res_degraded_wall_s > 0
    assert "RECLOSED" in err.getvalue()


def test_supervisor_reclose_resets_site_trip_state():
    sup, st, _ = _flap_supervisor()
    for i in range(1, 7):
        sup.run("ctx_scan", lambda: "dev", fallback=lambda: "host")
    assert sup.recloses == 1
    # the outage charged ctx_scan's window; the reclose must have
    # cleared it so post-recovery failures start a fresh window
    assert sup.consecutive("ctx_scan") == 0
    assert not sup.site_breaker_open("ctx_scan")


def test_supervisor_without_monitor_stays_degraded():
    # --recover=off: PR-1 behavior, the open breaker is terminal
    st = RunStats()
    sup = BatchSupervisor(_policy(max_retries=4), stats=st,
                          stderr=io.StringIO(),
                          faults=parse_fault_spec("down=2-4"),
                          probe=lambda: (True, ""))
    got = [sup.run("s", lambda: "dev", fallback=lambda: "host")
           for _ in range(8)]
    assert got == ["dev"] + ["host"] * 7
    assert sup.breaker_open and sup.recloses == 0
    assert st.res_breaker_recloses == 0
    assert st.res_reprobe_attempts == 0
    assert st.res_degraded_batches == 6


def test_supervisor_state_export_restore_roundtrip():
    sup, st, _ = _flap_supervisor()
    for _ in range(3):   # leave the breaker OPEN mid-window
        sup.run("ctx_scan", lambda: "dev", fallback=lambda: "host")
    assert sup.breaker_open
    exp = sup.export_state()
    assert exp["breaker_open"] and exp["fault_calls"] == 3
    json.dumps(exp)   # must be ckpt-serializable

    # a fresh supervisor (the --resume process) inherits the state:
    # no re-trip, the window continues at call 4, and it recovers
    st2 = RunStats()
    err2 = io.StringIO()
    mon2 = BackendHealthMonitor(interval_s=0, max_interval_s=0,
                                stats=st2, stderr=err2)
    sup2 = BatchSupervisor(_policy(max_retries=4), stats=st2,
                           stderr=err2,
                           faults=parse_fault_spec("down=2-4"),
                           probe=lambda: (True, ""), monitor=mon2)
    sup2.restore_state(exp)
    assert sup2.breaker_open
    got = [sup2.run("ctx_scan", lambda: "dev", fallback=lambda: "host")
           for _ in range(4)]
    assert got == ["host", "host", "dev", "dev"]   # calls 4,5 / 6,7
    assert st2.res_breaker_trips == 0              # inherited, not new
    assert st2.res_breaker_recloses == 1

    # malformed/old-build state must not kill the resume
    sup3 = BatchSupervisor(_policy(), stderr=io.StringIO())
    sup3.restore_state({"breaker_open": 0, "half_opens": "junk"})
    assert not sup3.breaker_open
    # ...and each field restores INDEPENDENTLY: one malformed field
    # drops only itself — fault_calls after it must still land, or a
    # scripted window would replay from call 1 on an open breaker
    sup4 = BatchSupervisor(_policy(), stderr=io.StringIO(),
                           faults=parse_fault_spec("down=2-4"))
    sup4.restore_state({"breaker_open": True,
                        "half_opens": {"s": "junk"},
                        "fault_calls": 7})
    assert sup4.breaker_open
    assert sup4.faults._calls == 7
    assert sup4._half_opens == {}


def test_kill_fires_during_degraded_batches():
    # kill=K counts breaker-skipped calls as attempts, so a kill can be
    # scripted to land mid-outage (the resume test's setup)
    sup, st, _ = _flap_supervisor("down=2-9,kill=8")
    sup.run("s", lambda: "dev", fallback=lambda: "host")   # attempt 1
    sup.run("s", lambda: "dev", fallback=lambda: "host")   # 2-6 (retry)
    with pytest.raises(InjectedKill):
        for _ in range(5):   # skipped calls tick 7, 8 -> kill
            sup.run("s", lambda: "dev", fallback=lambda: "host")
    assert sup.breaker_open


# ---------------------------------------------------------------------------
# CLI end-to-end: the acceptance contract
# ---------------------------------------------------------------------------
def _corpus(tmp_path, n=24, qlen=120):
    rng = np.random.default_rng(3)
    q = "".join("ACGT"[i] for i in rng.integers(0, 4, qlen))
    lines = []
    for i in range(n):
        cut = 10 + int(rng.integers(0, qlen - 40))
        qb = q[cut]
        tb = "ACGT"[("ACGT".index(qb) + 1) % 4]
        ops = [("=", cut), ("*", tb, qb), ("=", 20), ("ins", "gg"),
               ("=", qlen - cut - 21)]
        lines.append(make_paf_line("q", q, f"asm{i}", "+", ops)[0])
    fa = tmp_path / "q.fa"
    write_fasta(str(fa), [("q", q.encode())])
    paf = tmp_path / "in.paf"
    paf.write_text("".join(ln + "\n" for ln in lines))
    return str(paf), str(fa)


def _cli(tmp_path, tag, extra, paf, fa):
    err = io.StringIO()
    rc = run([paf, "-r", fa, "-o", str(tmp_path / f"{tag}.dfa"),
              "-w", str(tmp_path / f"{tag}.mfa"), "--device=tpu",
              "--batch=2", f"--stats={tmp_path / f'{tag}.json'}"]
             + extra, stderr=err)
    return rc, err.getvalue()


def _outs(tmp_path, tag):
    return ((tmp_path / f"{tag}.dfa").read_bytes(),
            (tmp_path / f"{tag}.mfa").read_bytes())


def _res(tmp_path, tag):
    return json.loads((tmp_path / f"{tag}.json").read_text())["resilience"]


def test_cli_flap_recovers_byte_identical(tmp_path, monkeypatch):
    """The acceptance gate: a scripted 4-call outage window on the
    device CLI path — byte-identical report and MSA, with a breaker
    trip AND a reclose, degraded AND recovered batches in --stats."""
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path)
    rc, _ = _cli(tmp_path, "ref", [], paf, fa)
    assert rc == 0
    rc, err = _cli(tmp_path, "flap",
                   ["--inject-faults=down=3-6", "--max-retries=4",
                    "--reprobe-interval=0"], paf, fa)
    assert rc == 0, err
    assert _outs(tmp_path, "flap") == _outs(tmp_path, "ref")
    res = _res(tmp_path, "flap")
    assert res["breaker_trips"] == 1
    assert res["breaker_recloses"] >= 1
    assert res["degraded_batches"] > 0
    assert res["recovered_batches"] > 0
    assert res["reprobe_attempts"] > 0
    assert res["degraded_wall_s"] > 0
    assert "RECLOSED" in err
    # the clean run reports all-zero recovery counters
    ref = _res(tmp_path, "ref")
    assert ref["breaker_recloses"] == ref["degraded_batches"] == 0
    assert ref["recovered_batches"] == ref["reprobe_attempts"] == 0


def test_cli_flap_recover_off_stays_degraded(tmp_path, monkeypatch):
    """--recover=off: same scripted flap, same bytes, but the breaker
    never recloses — the run ends degraded and says so."""
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path)
    rc, _ = _cli(tmp_path, "ref", [], paf, fa)
    assert rc == 0
    rc, err = _cli(tmp_path, "off",
                   ["--inject-faults=down=3-6", "--max-retries=4",
                    "--recover=off"], paf, fa)
    assert rc == 0, err
    assert _outs(tmp_path, "off") == _outs(tmp_path, "ref")
    res = _res(tmp_path, "off")
    assert res["breaker_trips"] == 1
    assert res["breaker_recloses"] == 0
    assert res["recovered_batches"] == 0
    assert res["reprobe_attempts"] == 0
    assert res["degraded_batches"] > 0
    assert "ended with the circuit breaker OPEN" in err


def test_resume_mid_outage_repromotes_in_window(tmp_path, monkeypatch):
    """Satellite: kill mid-outage (kill= lands while the breaker is
    open), --resume inherits the ckpt's breaker + fault-clock state —
    the resumed run continues INSIDE the same scripted window (no
    re-trip: breaker_trips == 0), recloses after it, re-promotes, and
    the final output is byte-identical."""
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path)
    rc, _ = _cli(tmp_path, "ref", [], paf, fa)
    assert rc == 0
    with pytest.raises(InjectedKill):
        _cli(tmp_path, "k",
             ["--inject-faults=down=2-9,kill=10", "--max-retries=4",
              "--reprobe-interval=0"], paf, fa)
    ckpt = tmp_path / "k.dfa.ckpt"
    assert ckpt.exists()
    ck = json.loads(ckpt.read_text())
    st = ck["resilience"]
    assert st["breaker_open"] is True      # killed while degraded
    assert 2 <= st["fault_calls"] <= 9     # ...inside the window
    rc, err = _cli(tmp_path, "k",
                   ["--resume", "--inject-faults=down=2-9",
                    "--max-retries=4", "--reprobe-interval=0"],
                   paf, fa)
    assert rc == 0, err
    assert _outs(tmp_path, "k") == _outs(tmp_path, "ref")
    res = _res(tmp_path, "k")
    assert res["breaker_trips"] == 0       # inherited open, no re-trip
    assert res["breaker_recloses"] == 1
    assert res["recovered_batches"] > 0
    assert not ckpt.exists()


def test_fallback_fail_abort_leaves_durable_prefix(tmp_path,
                                                   monkeypatch):
    """Satellite: the durability contract AFTER a --fallback=fail
    abort — the <report>.ckpt names a valid durable prefix (exactly
    what is on disk, whole records) and a --resume completes the run
    byte-identically."""
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path)
    rc, _ = _cli(tmp_path, "ref", [], paf, fa)
    assert rc == 0
    rc, err = _cli(tmp_path, "ff",
                   ["--fallback=fail", "--max-retries=0",
                    "--inject-faults=down=3-999"], paf, fa)
    assert rc == 1
    assert "--fallback=fail forbids degrading" in err
    ckpt = tmp_path / "ff.dfa.ckpt"
    assert ckpt.exists()
    ck = json.loads(ckpt.read_text())
    report = tmp_path / "ff.dfa"
    # valid durable prefix: the ckpt byte count is exactly the file,
    # and it holds exactly the checkpointed records, all complete
    assert ck["bytes"] == os.path.getsize(report)
    body = report.read_bytes()
    assert ck["records"] > 0
    assert body.count(b"\n>") + (1 if body.startswith(b">") else 0) \
        == ck["records"]
    assert body.endswith(b"\n")
    rc, err = _cli(tmp_path, "ff", ["--resume"], paf, fa)
    assert rc == 0, err
    assert _outs(tmp_path, "ff") == _outs(tmp_path, "ref")
    headers = [ln for ln in
               (tmp_path / "ff.dfa").read_text().splitlines()
               if ln.startswith(">")]
    assert len(headers) == len(set(headers)) == 24


def test_recovery_flag_validation(tmp_path):
    paf, fa = _corpus(tmp_path, n=2)
    for bad in (["--recover=maybe"], ["--recover"],
                ["--reprobe-interval=x"], ["--reprobe-interval=-1"],
                ["--reprobe-interval=inf"], ["--reprobe-max=x"],
                ["--reprobe-interval=10", "--reprobe-max=5"],
                ["--inject-faults=down=9-2"]):
        err = io.StringIO()
        assert run([paf, "-r", fa] + bad, stderr=err) == 1, bad
        assert "Invalid" in err.getvalue(), bad
    # setting only one side moves the other side's DEFAULT with it:
    # a raised interval lifts the ceiling, a lowered ceiling pulls the
    # first-probe delay down — neither consistent request errors
    for ok in (["--reprobe-interval=600"], ["--reprobe-max=2"]):
        err = io.StringIO()
        assert run([paf, "-r", fa, "-o", str(tmp_path / "ok.dfa")]
                   + ok, stderr=err) == 0, (ok, err.getvalue())
