"""Continuous fleet-wide many2many (ISSUE 20): the surveillance
pipeline — streamed target arrival, resident section scoring, the
per-CDS section cache, and the router-partitioned scatter/merge.

Acceptance contracts:

- **one stream, one report**: a ``--m2m-stream`` job fed record-at-a-
  time over the service socket lands byte-identical to one one-shot
  run over the same records in the same arrival order;
- **arriving-target economics**: with ``--result-cache``, an arriving
  target re-scores ONLY the pairs the section store has never seen
  (``pairs_dispatched``/``pairs_reused`` counters are truthful) and
  the spliced report stays byte-identical to a cache-off run;
- **deadline honesty**: ``--deadline-s`` preempts at the per-CDS
  dispatch boundary with exit 75 and a cache-resumable session — a
  fully-primed session never touches the dispatch boundary at all;
- **the scatter drill**: a 3-member fleet scatter (any arrival order)
  merges byte-identical to one un-scattered run, and a member
  SIGKILLed mid-stream is re-partitioned invisibly — same bytes, one
  failover in the stats.
"""

import io
import json
import os
import random
import subprocess
import sys
import tempfile
import threading
import time

import pytest

from pwasm_tpu.cli import run as cli_run
from pwasm_tpu.core.errors import EXIT_PREEMPTED
from pwasm_tpu.fleet.router import Router
from pwasm_tpu.service.client import ServiceClient, wait_for_socket
from pwasm_tpu.service.top import render
from pwasm_tpu.surveil.partition import (ScatterState, merge_fragments,
                                         rewrite_out_args)
from pwasm_tpu.surveil.records import FastaAssembler, parse_record

from test_fleet import _daemon, _fleet, _serve_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# corpus helpers (tiny: seconds, not minutes, on cpu jax)
# ---------------------------------------------------------------------------
def _seq(rng, n):
    return "".join(rng.choice("ACGT") for _ in range(n))


def _corpus(tmp_path, nq=2, nt=7, seed=11):
    rng = random.Random(seed)
    qs = [(f"cds{i}", _seq(rng, 50 + 10 * i)) for i in range(nq)]
    ts = [(f"asm{i}", _seq(rng, 120 + 15 * i)) for i in range(nt)]
    qfa = str(tmp_path / "q.fa")
    with open(qfa, "w") as f:
        for n, s in qs:
            f.write(f">{n}\n{s}\n")
    return qfa, [f">{n}\n{s}\n" for n, s in ts]


def _write_targets(tmp_path, recs, name="t.fa"):
    tfa = str(tmp_path / name)
    with open(tfa, "w") as f:
        f.write("".join(recs))
    return tfa


def _one_shot(tmp_path, qfa, recs, tag, extra=()):
    """Ground truth: one un-streamed, un-scattered run."""
    tfa = _write_targets(tmp_path, recs, f"{tag}.fa")
    o = str(tmp_path / f"{tag}.tsv")
    s = str(tmp_path / f"{tag}.sum")
    rc = cli_run(["--m2m-stream", tfa, "-r", qfa, "-o", o, "-s", s]
                 + list(extra), stderr=io.StringIO())
    assert rc == 0
    return open(o, "rb").read(), open(s, "rb").read()


# ---------------------------------------------------------------------------
# record assembly units
# ---------------------------------------------------------------------------
def test_fasta_assembler_reassembles_any_byte_split():
    text = ">a desc\nACGT\nAC\n\n>b\r\nGGTT\r\n>c\nTT"
    # one char per frame: records complete only when the NEXT header
    # arrives; finish() flushes the trailing one
    asm = FastaAssembler()
    got = []
    for ch in text:
        got.extend(asm.feed(ch))
    got.extend(asm.finish())
    assert got == [">a desc\nACGT\nAC\n", ">b\nGGTT\n", ">c\nTT\n"]
    # identical to one big frame
    asm2 = FastaAssembler()
    assert asm2.feed(text) + asm2.finish() == got
    assert parse_record(got[0]) == ("a", "ACGTAC")
    with pytest.raises(ValueError):
        parse_record("no header\nACGT\n")
    with pytest.raises(ValueError):
        parse_record(">\nACGT\n")


def test_scatter_state_roundrobin_kill_adopt():
    st = ScatterState()
    for _ in range(3):
        st.add_sub()
    assigned = [st.assign() for _ in range(7)]
    assert [g for g, _ in assigned] == list(range(7))
    assert [k for _, k in assigned] == [0, 1, 2, 0, 1, 2, 0]
    assert st.orders[0] == [0, 3, 6]
    # death: the dead sub's records replay wholesale into a fresh sub
    order = st.kill(1)
    assert order == [1, 4]
    assert st.live_subs() == [0, 2]
    k = st.add_sub()
    st.adopt(k, order)
    assert st.orders[k] == [1, 4]
    with pytest.raises(ValueError):
        st.adopt(k, [9])                # already owns records
    # post-death arrivals round-robin over the CURRENT live set
    assert [st.assign()[1] for _ in range(3)] == [2, 3, 0]
    st.kill(0)
    st.kill(2)
    st.kill(3)
    with pytest.raises(ValueError):
        st.assign()                     # no live subs


def test_rewrite_out_args_fragments_and_strips_stats():
    args = ["--m2m-stream", "-r", "q.fa", "-o", "out.tsv",
            "-s", "out.sum", "--stats=x.json", "--band=16"]
    got = rewrite_out_args(args, o="f.frag00", s="s.frag00")
    assert got == ["--m2m-stream", "-r", "q.fa", "-o", "f.frag00",
                   "-s", "s.frag00", "--band=16"]


def test_merge_fragments_global_order_and_summary():
    # two subs over 5 records: sub0 owns 0,2,4 / sub1 owns 1,3
    f0 = b">q1\t60\t3\nt0\t100\t7\nt2\t110\t.\nt4\t130\t9\n"
    f1 = b">q1\t60\t2\nt1\t105\t9\nt3\t120\t3\n"
    rep, summ = merge_fragments([f0, f1], [[0, 2, 4], [1, 3]], 5,
                                summary=True)
    assert rep == (b">q1\t60\t5\n"
                   b"t0\t100\t7\nt1\t105\t9\nt2\t110\t.\n"
                   b"t3\t120\t3\nt4\t130\t9\n")
    # best ties break to ARRIVAL order: t1 (gidx 1) beats t4 (gidx 4)
    assert summ == b"q1\t5\tt1\t9\t28\n"
    with pytest.raises(ValueError):
        merge_fragments([f0, f1], [[0, 2, 4], [1]], 5)   # row count
    with pytest.raises(ValueError):
        merge_fragments([f0, f1], [[0, 2, 4], [1, 3]], 6)  # missing
    f1_bad = f1.replace(b">q1", b">qX")
    with pytest.raises(ValueError):
        merge_fragments([f0, f1_bad], [[0, 2, 4], [1, 3]], 5)


# ---------------------------------------------------------------------------
# streamed session vs one-shot (real runner, in-process daemon)
# ---------------------------------------------------------------------------
def test_streamed_session_byte_identical_and_observable(tmp_path):
    """One daemon, records chunked at arbitrary byte splits: the
    streamed report/summary land byte-identical to one one-shot run,
    the result carries the m2m stats block, and the retired session
    feeds svc-stats, the top M2M pane, and the pwasm_m2m_* metric
    families."""
    qfa, recs = _corpus(tmp_path)
    expect_o, expect_s = _one_shot(tmp_path, qfa, recs, "cold")
    text = "".join(recs)
    o = str(tmp_path / "st.tsv")
    s = str(tmp_path / "st.sum")
    with _daemon() as h:
        with ServiceClient(h.sock) as c:
            r = c.stream(["--m2m-stream", "-r", qfa, "-o", o,
                          "-s", s],
                         [text[i:i + 61]
                          for i in range(0, len(text), 61)],
                         cwd=str(tmp_path))
            assert r.get("ok"), r
            res = c.result(r["job_id"], timeout=180)
            assert res.get("ok") and res.get("rc") == 0, res
            m2m = (res.get("stats") or {}).get("m2m")
            assert m2m and m2m["targets_in"] == len(recs), m2m
            assert m2m["pairs_dispatched"] == 2 * len(recs), m2m
            st = c.stats()["stats"]
            mt = c.metrics()
        assert open(o, "rb").read() == expect_o
        assert open(s, "rb").read() == expect_s
        # the additive svc-stats block folds the retired session
        blk = st.get("m2m")
        assert blk and blk["sessions"] == 1 \
            and blk["targets_in"] == len(recs), blk
        pane = render(st)
        m2m_lines = [ln for ln in pane.splitlines()
                     if ln.startswith(" M2M:")]
        assert m2m_lines and "1 session(s)" in m2m_lines[0], pane
        text_m = mt.get("metrics") or ""
        assert "pwasm_m2m_sessions_total 1" in text_m
        assert f"pwasm_m2m_targets_total {len(recs)}" in text_m


def test_incremental_arrivals_splice_from_section_cache(tmp_path):
    """The arriving-target contract: a --result-cache primed with 5
    targets re-scores ONLY the 2 arrivals on the grown input — the
    counters say so — and the spliced bytes equal the cache-off run."""
    qfa, recs = _corpus(tmp_path)
    rc_dir = str(tmp_path / "rc")
    stats_p = str(tmp_path / "inc.json")
    _one_shot(tmp_path, qfa, recs[:5], "prime",
              [f"--result-cache={rc_dir}"])
    expect_o, expect_s = _one_shot(tmp_path, qfa, recs, "full")
    o = str(tmp_path / "inc.tsv")
    s = str(tmp_path / "inc.sum")
    tfa = _write_targets(tmp_path, recs, "grown.fa")
    rc = cli_run(["--m2m-stream", tfa, "-r", qfa, "-o", o, "-s", s,
                  f"--result-cache={rc_dir}", f"--stats={stats_p}"],
                 stderr=io.StringIO())
    assert rc == 0
    m2m = json.load(open(stats_p))["m2m"]
    assert m2m["targets_reused"] == 5, m2m
    assert m2m["pairs_dispatched"] == 2 * 2, m2m   # 2 arrivals x 2 CDS
    assert m2m["pairs_reused"] == 2 * 5, m2m
    assert open(o, "rb").read() == expect_o
    assert open(s, "rb").read() == expect_s


def test_deadline_preempts_resumable_and_primed_run_completes(
        tmp_path):
    """--deadline-s at the per-CDS dispatch boundary: a cold session
    with a microscopic budget exits 75 (preempted, cache-resumable);
    the SAME budget over a fully-primed cache completes rc 0 — an
    all-splice session never reaches the dispatch boundary at all."""
    qfa, recs = _corpus(tmp_path)
    rc_dir = str(tmp_path / "rc")
    tfa = _write_targets(tmp_path, recs)
    o = str(tmp_path / "dl.tsv")
    err = io.StringIO()
    rc = cli_run(["--m2m-stream", tfa, "-r", qfa, "-o", o,
                  "--deadline-s=0.000001",
                  f"--result-cache={rc_dir}"], stderr=err)
    assert rc == EXIT_PREEMPTED, err.getvalue()
    assert "deadline_exceeded" in err.getvalue()
    assert not os.path.exists(o)       # no partial report
    # prime, then the same impossible budget completes from splices
    expect_o, expect_s = _one_shot(tmp_path, qfa, recs, "cold",
                                   [f"--result-cache={rc_dir}"])
    s = str(tmp_path / "dl.sum")
    rc = cli_run(["--m2m-stream", tfa, "-r", qfa, "-o", o, "-s", s,
                  "--deadline-s=0.000001",
                  f"--result-cache={rc_dir}"], stderr=io.StringIO())
    assert rc == 0
    assert open(o, "rb").read() == expect_o
    assert open(s, "rb").read() == expect_s
    err = io.StringIO()
    rc = cli_run(["--m2m-stream", tfa, "-r", qfa, "-o", o,
                  "--deadline-s=0"], stderr=err)
    assert rc == 1 and "--deadline-s" in err.getvalue()


# ---------------------------------------------------------------------------
# fleet scatter (in-process 3-member fleet, real runner)
# ---------------------------------------------------------------------------
def test_scatter_three_members_shuffled_arrival_parity(tmp_path):
    """Arrival-order determinism: the SAME records in a shuffled
    order, scattered across 3 members, merge byte-identical to one
    un-scattered run over that same shuffled order — the partition
    never reorders, whatever the member interleaving does."""
    qfa, recs = _corpus(tmp_path, nt=9)
    shuffled = list(recs)
    random.Random(4).shuffle(shuffled)
    expect_o, expect_s = _one_shot(tmp_path, qfa, shuffled, "shuf")
    o = str(tmp_path / "sc.tsv")
    s = str(tmp_path / "sc.sum")
    text = "".join(shuffled)
    with _fleet(3) as f:
        with ServiceClient(f.sock) as c:
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if c.stats()["stats"]["fleet"]["alive"] == 3:
                    break
                time.sleep(0.05)
            r = c.stream(["--m2m-stream", "-r", qfa, "-o", o,
                          "-s", s],
                         [text[i:i + 73]
                          for i in range(0, len(text), 73)],
                         cwd=str(tmp_path))
            assert r.get("ok"), r
            assert len(r.get("scatter", [])) == 3, r
            res = c.result(r["job_id"], timeout=180)
            assert res.get("ok") and res.get("rc") == 0, res
            sc = (res.get("stats") or {}).get("scatter")
            assert sc == {"subs": 3, "records": 9, "failovers": 0}, sc
            m2m = (res.get("stats") or {}).get("m2m")
            assert m2m and m2m["targets_in"] == 9, m2m
    assert open(o, "rb").read() == expect_o
    assert open(s, "rb").read() == expect_s
    # no fragment litter after the merge
    assert not [p for p in os.listdir(tmp_path) if ".frag" in p]


def test_scatter_kill_member_midstream_repartitions_to_parity(
        tmp_path):
    """THE ISSUE 20 drill: SIGKILL one of three members mid-stream.
    The router re-partitions the dead member's sub-stream onto a
    survivor (replaying its buffered records in order), the client
    never sees the death, and the merged report is byte-identical to
    an un-scattered run — failovers == 1 in the scatter stats."""
    qfa, recs = _corpus(tmp_path, nt=9, seed=23)
    expect_o, expect_s = _one_shot(tmp_path, qfa, recs, "cold")
    d = tempfile.mkdtemp(prefix="pwsurv")
    socks, procs = [], []
    o = str(tmp_path / "kd.tsv")
    s = str(tmp_path / "kd.sum")
    try:
        for i in range(3):
            sk = os.path.join(d, f"m{i}.sock")
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "pwasm_tpu.cli", "serve",
                 f"--socket={sk}"],
                env=_serve_env(), stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE, text=True))
            socks.append(sk)
        for sk in socks:
            assert wait_for_socket(sk, 60)
        rsock = os.path.join(d, "router.sock")
        rerr = io.StringIO()
        router = Router(socks, socket_path=rsock, stderr=rerr,
                        poll_interval=0.2)
        rt = threading.Thread(target=router.serve, daemon=True)
        rt.start()
        assert wait_for_socket(rsock, 15)
        with ServiceClient(rsock) as c:
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if c.stats()["stats"]["fleet"]["alive"] == 3:
                    break
                time.sleep(0.1)
            r = c.stream_open(["--m2m-stream", "-r", qfa, "-o", o,
                               "-s", s], cwd=str(tmp_path))
            assert r.get("ok") and r.get("scatter"), r
            jid = r["job_id"]
            for t in recs[:5]:
                assert c.stream_data(jid, t).get("ok")
            # SIGKILL the member hosting sub 0 (the ledger anchor)
            victim = r["scatter"][0]
            vi = socks.index(router.members[victim].target)
            procs[vi].kill()
            procs[vi].wait(timeout=30)
            for t in recs[5:]:
                assert c.stream_data(jid, t).get("ok")
            assert c.stream_end(jid).get("ok")
            res = c.result(jid, timeout=300)
            assert res.get("ok") and res.get("rc") == 0, res
            sc = (res.get("stats") or {}).get("scatter")
            assert sc and sc["failovers"] == 1 \
                and sc["records"] == 9, sc
            st = c.stats()["stats"]
            assert st["fleet"]["jobs_recovered"]["stream_replayed"] \
                == 1, st["fleet"]
            c.drain()
        rt.join(20)
        assert any("re-partitioned" in ln
                   for ln in rerr.getvalue().splitlines()), \
            rerr.getvalue()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
            p.stderr.close()
        import shutil
        shutil.rmtree(d, ignore_errors=True)
    assert open(o, "rb").read() == expect_o
    assert open(s, "rb").read() == expect_s


# ---------------------------------------------------------------------------
# the tier-1 jax-freeness gate
# ---------------------------------------------------------------------------
def test_surveil_qa_gate_clean_and_detects_loss():
    sys.path.insert(0, os.path.join(REPO, "qa"))
    try:
        import check_supervision as cs
    finally:
        sys.path.pop(0)
    assert cs.find_surveil_violations() == []
    # the gate must FAIL when the subsystem goes missing — the
    # jax-free walk alone returns [] for an absent directory
    with tempfile.TemporaryDirectory() as fake:
        missing = cs.find_surveil_violations(fake)
        assert missing and all("missing" in m for m in missing)
