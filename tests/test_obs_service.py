"""Daemon-side observability + result eviction (ISSUE 6).

Contracts:

- the ``metrics`` protocol command (and the ``pwasm-tpu metrics``
  client verb) answer the daemon's full Prometheus text exposition —
  queue depth, in-flight gauge, breaker state, per-job wall and
  queue-wait histograms, job outcome counters, and the cumulative
  fold of every finished job's ``--stats``;
- ``serve --metrics-textfile=PATH`` republishes the same exposition
  atomically after every job (no tmp remnant, always a whole
  document);
- ``svc-stats`` sources queue-depth/in-flight/breaker-state from the
  SAME registry gauges, so the two operator surfaces cannot drift;
- ``--result-ttl-s`` / ``--max-results`` evict TERMINAL job results
  (LRU by last access); evicted ids answer ``unknown_job`` and the
  eviction is counted on both surfaces.
"""

import io
import json
import os
import shutil
import tempfile
import threading
import time
from contextlib import contextmanager
from types import SimpleNamespace

from pwasm_tpu.cli import run
from pwasm_tpu.service.client import ServiceClient, wait_for_socket
from pwasm_tpu.service.daemon import Daemon

from test_obs import _corpus as _obs_corpus
from test_obs import assert_valid_exposition


def _corpus(tmp_path, n=8, qlen=120):
    return _obs_corpus(tmp_path, n=n, qlen=qlen)


@contextmanager
def _daemon(**kw):
    sockdir = tempfile.mkdtemp(prefix="pwobs")
    sock = os.path.join(sockdir, "s")
    err = io.StringIO()
    dm = Daemon(sock, stderr=err, **kw)
    rcbox: list = []
    t = threading.Thread(target=lambda: rcbox.append(dm.serve()),
                         daemon=True)
    t.start()
    assert wait_for_socket(sock, 15), err.getvalue()
    try:
        yield SimpleNamespace(daemon=dm, sock=sock, rc=rcbox, err=err,
                              thread=t)
    finally:
        if not dm.drain.requested:
            dm.drain.request("test teardown")
        t.join(20)
        shutil.rmtree(sockdir, ignore_errors=True)


def _submit_ok(c, tmp_path, tag, paf, fa):
    sub = c.submit([paf, "-r", fa,
                    "-o", str(tmp_path / f"{tag}.dfa"), "--batch=2"])
    assert sub.get("ok"), sub
    res = c.result(sub["job_id"], timeout=120)
    assert res.get("ok") and res.get("rc") == 0, res
    return sub["job_id"]


def test_metrics_over_socket_covers_required_families(tmp_path):
    paf, fa = _corpus(tmp_path)
    with _daemon() as h:
        with ServiceClient(h.sock) as c:
            _submit_ok(c, tmp_path, "a", paf, fa)
            resp = c.metrics()
        assert resp.get("ok"), resp
        text = resp["metrics"]
    assert_valid_exposition(text)
    lines = text.splitlines()
    # the acceptance quartet: queue depth, warm-hit rate inputs,
    # breaker state, per-job wall histogram
    assert "pwasm_service_queue_depth 0" in lines
    assert "pwasm_service_jobs_inflight 0" in lines
    assert "pwasm_service_breaker_state 0" in lines
    assert any(ln.startswith("pwasm_service_job_wall_seconds_bucket")
               for ln in lines)
    assert any(ln.startswith(
        "pwasm_service_job_queue_wait_seconds_bucket")
        for ln in lines)
    assert "pwasm_backend_probes_total" in text
    assert "pwasm_backend_warm_hits_total" in text
    assert 'pwasm_service_jobs_total{outcome="accepted"} 1' in lines
    assert 'pwasm_service_jobs_total{outcome="done"} 1' in lines
    # the finished job's --stats folded into the cumulative families
    assert "pwasm_run_alignments_total 8" in lines
    assert 'pwasm_run_finished_total{outcome="completed"} 1' in lines


def test_metrics_client_verb(tmp_path):
    paf, fa = _corpus(tmp_path, n=4)
    with _daemon() as h:
        with ServiceClient(h.sock) as c:
            _submit_ok(c, tmp_path, "a", paf, fa)
        out, err = io.StringIO(), io.StringIO()
        rc = run(["metrics", f"--socket={h.sock}"], stdout=out,
                 stderr=err)
    assert rc == 0, err.getvalue()
    assert_valid_exposition(out.getvalue())
    assert "pwasm_service_queue_depth" in out.getvalue()


def test_svc_stats_sources_registry_and_versions(tmp_path):
    paf, fa = _corpus(tmp_path, n=4)
    with _daemon() as h:
        with ServiceClient(h.sock) as c:
            _submit_ok(c, tmp_path, "a", paf, fa)
            st = c.stats()["stats"]
            text = c.metrics()["metrics"]
    assert st["stats_version"] == 1
    # same registry, two renderings: the JSON fields must equal the
    # gauge samples in the exposition taken in the same quiet window
    lines = text.splitlines()
    assert f"pwasm_service_queue_depth {st['queue_depth']}" in lines
    assert f"pwasm_service_jobs_inflight {st['running']}" in lines
    assert f"pwasm_service_breaker_state {st['breaker_state']}" \
        in lines
    assert st["jobs"]["evicted"] == 0


def test_metrics_textfile_republished_atomically(tmp_path):
    paf, fa = _corpus(tmp_path, n=4)
    prom = tmp_path / "svc.prom"
    with _daemon(metrics_textfile=str(prom)) as h:
        assert prom.is_file()   # published at daemon start
        with ServiceClient(h.sock) as c:
            _submit_ok(c, tmp_path, "a", paf, fa)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if 'pwasm_service_jobs_total{outcome="done"} 1' \
                    in prom.read_text():
                break
            time.sleep(0.05)
    text = prom.read_text()
    assert_valid_exposition(text)
    assert 'pwasm_service_jobs_total{outcome="done"} 1' \
        in text.splitlines()
    # atomic publish: no tmp remnant beside the textfile
    assert [p.name for p in tmp_path.iterdir()
            if "svc.prom." in p.name] == []


def test_log_json_service_events(tmp_path):
    paf, fa = _corpus(tmp_path, n=4)
    log = tmp_path / "svc.ndjson"
    with _daemon(log_json=str(log)) as h:
        with ServiceClient(h.sock) as c:
            jid = _submit_ok(c, tmp_path, "a", paf, fa)
    evs = [json.loads(ln) for ln in log.read_text().splitlines()]
    kinds = [e["event"] for e in evs]
    assert kinds[0] == "daemon_start"
    assert ["job_admit", "job_start", "job_finish"] == \
        [k for k in kinds if k.startswith("job_")]
    fin = next(e for e in evs if e["event"] == "job_finish")
    assert fin["job_id"] == jid and fin["state"] == "done" \
        and fin["rc"] == 0 and fin["wall_s"] > 0
    # the drain (teardown) and the daemon exit are on the record too
    assert "drain" in kinds and "daemon_exit" in kinds
    assert evs[-1]["event"] == "daemon_exit"
    assert evs[-1]["rc"] == 75 and evs[-1]["drained"] is True


def test_result_eviction_lru_max_results(tmp_path):
    paf, fa = _corpus(tmp_path, n=4)
    with _daemon(max_results=1) as h:
        with ServiceClient(h.sock) as c:
            ids = [_submit_ok(c, tmp_path, t, paf, fa)
                   for t in ("a", "b", "c")]
            # only the most recent terminal result survives the LRU
            r_old = c.status(ids[0])
            r_new = c.status(ids[2])
            st = c.stats()["stats"]
            text = c.metrics()["metrics"]
    assert r_old.get("error") == "unknown_job"
    assert r_new.get("ok"), r_new
    assert st["jobs"]["evicted"] == 2
    assert "pwasm_service_results_evicted_total 2" \
        in text.splitlines()
    assert "pwasm_service_results_held 1" in text.splitlines()


def test_result_eviction_ttl(tmp_path):
    paf, fa = _corpus(tmp_path, n=4)
    with _daemon(result_ttl_s=0.2) as h:
        with ServiceClient(h.sock) as c:
            jid = _submit_ok(c, tmp_path, "a", paf, fa)
            assert c.status(jid).get("ok")
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                if c.status(jid).get("error") == "unknown_job":
                    break
                time.sleep(0.05)
            assert c.status(jid).get("error") == "unknown_job"
            st = c.stats()["stats"]
    assert st["jobs"]["evicted"] == 1


def test_eviction_never_touches_queued_or_running(tmp_path):
    """Eviction candidates are TERMINAL jobs only: a queued job under
    a 0-TTL daemon still runs and answers its result."""
    paf, fa = _corpus(tmp_path, n=4)
    slow = "--inject-faults=seed=1,rate=1,kinds=hang,hang_s=0.25"
    with _daemon(result_ttl_s=0.0, max_results=0) as h:
        with ServiceClient(h.sock) as c:
            sub = c.submit([paf, "-r", fa, "--device=tpu",
                            "-o", str(tmp_path / "s.dfa"),
                            "--batch=2", slow])
            assert sub.get("ok"), sub
            res = c.result(sub["job_id"], timeout=120)
            # the job ran to completion (it may already be evicted by
            # the time we ask again — but the blocking result call
            # held the Job object and must see the real rc)
            assert res.get("ok") and res.get("rc") == 0, res


def test_serve_main_flag_validation(tmp_path):
    from pwasm_tpu.service.daemon import serve_main
    for bad in (["--socket=s", "--result-ttl-s=abc"],
                ["--socket=s", "--result-ttl-s=-1"],
                ["--socket=s", "--max-results=x"]):
        err = io.StringIO()
        assert serve_main(bad, stderr=err) == 1
        assert "Invalid" in err.getvalue()


def test_accessed_s_is_the_lru_clock(tmp_path):
    """Touching an old result via status refreshes its LRU slot, so
    the OTHER result is the eviction victim."""
    paf, fa = _corpus(tmp_path, n=4)
    with _daemon(max_results=2) as h:
        with ServiceClient(h.sock) as c:
            a = _submit_ok(c, tmp_path, "a", paf, fa)
            b = _submit_ok(c, tmp_path, "b", paf, fa)
            time.sleep(0.02)
            assert c.status(a).get("ok")   # refresh a's access time
            _submit_ok(c, tmp_path, "c", paf, fa)   # b becomes LRU
            assert c.status(a).get("ok")
            assert c.status(b).get("error") == "unknown_job"
