import pytest

from pwasm_tpu.core.errors import PwasmError
from pwasm_tpu.core.fasta import FastaFile, write_fasta


def test_fetch_multi_record(tmp_path):
    p = tmp_path / "x.fa"
    write_fasta(str(p), [("a", b"ACGTACGTACGT"), ("b desc", b"TTTT")], width=5)
    fa = FastaFile(p)
    assert len(fa) == 2
    assert fa.names == ["a", "b"]
    assert fa.fetch("a") == b"ACGTACGTACGT"
    assert fa.fetch("b") == b"TTTT"
    assert fa.fetch("missing") is None
    assert fa.length("a") == 12


def test_header_with_description(tmp_path):
    p = tmp_path / "x.fa"
    p.write_text(">seq1 some description here\nACGT\nAC\n")
    fa = FastaFile(p)
    assert fa.fetch("seq1") == b"ACGTAC"


def test_empty_fasta_raises(tmp_path):
    p = tmp_path / "empty.fa"
    p.write_text("")
    with pytest.raises(PwasmError, match="invalid FASTA"):
        FastaFile(p)


def test_crlf(tmp_path):
    p = tmp_path / "crlf.fa"
    p.write_bytes(b">s\r\nACGT\r\nGG\r\n")
    fa = FastaFile(p)
    assert fa.fetch("s") == b"ACGTGG"


def test_file_size(tmp_path):
    p = tmp_path / "x.fa"
    p.write_text(">s\nACGT\n")
    assert FastaFile(p).file_size() == 8


def test_fai_sidecar_written_and_loaded(tmp_path, monkeypatch):
    """Second open of a uniformly-wrapped FASTA loads the .fai sidecar
    with NO full scan, and the loaded index equals a fresh build."""
    p = tmp_path / "big.fa"
    recs = [("g1", b"ACGTACGTACGT" * 10), ("g2", b"TTTTGGGGCCCC" * 7),
            ("g3", b"ACG")]
    write_fasta(str(p), recs, width=60)
    fa1 = FastaFile(p)
    fai = tmp_path / "big.fa.fai"
    assert fai.exists()
    body = fai.read_text()
    assert body.splitlines()[0].split("\t")[:2] == ["g1", "120"]

    def boom(self):
        raise AssertionError("full scan ran despite a fresh sidecar")

    monkeypatch.setattr(FastaFile, "_full_scan", boom)
    fa2 = FastaFile(p)
    assert fa2.names == fa1.names
    for name, seq in recs:
        assert fa2.fetch(name) == seq
        assert fa2.length(name) == fa1.length(name)
    assert fa2._index == fa1._index


def test_fai_sidecar_stale_triggers_rescan(tmp_path):
    """A FASTA newer than its sidecar must be re-scanned (and the
    sidecar refreshed), never served stale."""
    import os
    import time as _time

    p = tmp_path / "x.fa"
    write_fasta(str(p), [("a", b"ACGT" * 5)])
    FastaFile(p)
    write_fasta(str(p), [("a", b"ACGT" * 5), ("b", b"GG" * 30)])
    now = _time.time()
    os.utime(p, (now + 5, now + 5))  # FASTA strictly newer
    fa = FastaFile(p)
    assert fa.names == ["a", "b"]
    assert fa.fetch("b") == b"GG" * 30


def test_fai_not_written_when_geometry_cannot_describe(tmp_path):
    """A wrapping the 5-column format can't reproduce (derived end
    would be wrong) must not be persisted — correctness over caching."""
    p = tmp_path / "odd.fa"
    p.write_text(">s\nAC\nACGTACGT\n")  # short FIRST line
    fa = FastaFile(p)
    assert fa.fetch("s") == b"ACACGTACGT"
    assert not (tmp_path / "odd.fa.fai").exists()


def test_fai_not_written_for_midfile_eof_coincidence(tmp_path):
    """A mid-file record whose window coincides with the missing-final-
    newline size must NOT persist: the derived end would overshoot into
    the next record's header on reload (code-review r3 finding)."""
    p = tmp_path / "trap.fa"
    p.write_text(">s\nACGTACGT\nACGTACGTA\n>t\nAC\n")
    fa = FastaFile(p)
    assert fa.fetch("s") == b"ACGTACGTACGTACGTA"
    assert fa.fetch("t") == b"AC"
    assert not (tmp_path / "trap.fa.fai").exists()
    # and a second open (full re-scan) still fetches identically
    fa2 = FastaFile(p)
    assert fa2.fetch("s") == b"ACGTACGTACGTACGTA"


def test_fai_not_written_for_any_irregular_wrapping(tmp_path):
    """Even when the derived end coincidentally matches the scanned
    window (lines 8,2,8), the geometry misdescribes the record for
    foreign faidx readers (samtools/pysam derive in-record offsets from
    linebases) — so no sidecar may be written (code-review r3)."""
    p = tmp_path / "odd2.fa"
    p.write_text(">s\nACGTACGT\nAC\nACGTACGT\n")
    fa = FastaFile(p)
    assert fa.fetch("s") == b"ACGTACGTACACGTACGT"
    assert not (tmp_path / "odd2.fa.fai").exists()


def test_fai_mtime_preserving_swap_detected(tmp_path):
    """Replacing the FASTA with cp -p style mtime preservation must not
    serve the stale index: the structural probes catch a layout change
    and fall back to a full scan (code-review r3)."""
    import os

    p = tmp_path / "swap.fa"
    write_fasta(str(p), [("a", b"ACGT" * 30), ("b", b"TTTT" * 9)])
    fa1 = FastaFile(p)
    old_times = (os.path.getatime(p), os.path.getmtime(p))
    fai_times = (os.path.getatime(str(p) + ".fai"),
                 os.path.getmtime(str(p) + ".fai"))
    # swap in a differently-shaped file, preserving mtimes
    write_fasta(str(p), [("x", b"GG" * 8), ("y", b"CC" * 50),
                         ("z", b"AA" * 3)], width=20)
    os.utime(p, old_times)
    os.utime(str(p) + ".fai", fai_times)
    fa2 = FastaFile(p)
    assert fa2.names == ["x", "y", "z"]
    assert fa2.fetch("y") == b"CC" * 50
    assert fa1.names == ["a", "b"]


def test_fai_renamed_swap_detected(tmp_path):
    """A same-geometry mtime-preserving swap that only RENAMES records
    must not serve stale names (code-review r3: header-name probe)."""
    import os

    p = tmp_path / "ren.fa"
    write_fasta(str(p), [("aa", b"ACGT" * 15), ("bb", b"TT" * 30)])
    FastaFile(p)
    times = (os.path.getatime(p), os.path.getmtime(p))
    fai_t = (os.path.getatime(str(p) + ".fai"),
             os.path.getmtime(str(p) + ".fai"))
    write_fasta(str(p), [("xx", b"ACGT" * 15), ("yy", b"TT" * 30)])
    os.utime(p, times)
    os.utime(str(p) + ".fai", fai_t)
    fa = FastaFile(p)
    assert fa.names == ["xx", "yy"]
    assert fa.fetch("yy") == b"TT" * 30
    assert fa.fetch("aa") is None
