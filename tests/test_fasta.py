import pytest

from pwasm_tpu.core.errors import PwasmError
from pwasm_tpu.core.fasta import FastaFile, write_fasta


def test_fetch_multi_record(tmp_path):
    p = tmp_path / "x.fa"
    write_fasta(str(p), [("a", b"ACGTACGTACGT"), ("b desc", b"TTTT")], width=5)
    fa = FastaFile(p)
    assert len(fa) == 2
    assert fa.names == ["a", "b"]
    assert fa.fetch("a") == b"ACGTACGTACGT"
    assert fa.fetch("b") == b"TTTT"
    assert fa.fetch("missing") is None
    assert fa.length("a") == 12


def test_header_with_description(tmp_path):
    p = tmp_path / "x.fa"
    p.write_text(">seq1 some description here\nACGT\nAC\n")
    fa = FastaFile(p)
    assert fa.fetch("seq1") == b"ACGTAC"


def test_empty_fasta_raises(tmp_path):
    p = tmp_path / "empty.fa"
    p.write_text("")
    with pytest.raises(PwasmError, match="invalid FASTA"):
        FastaFile(p)


def test_crlf(tmp_path):
    p = tmp_path / "crlf.fa"
    p.write_bytes(b">s\r\nACGT\r\nGG\r\n")
    fa = FastaFile(p)
    assert fa.fetch("s") == b"ACGTGG"


def test_file_size(tmp_path):
    p = tmp_path / "x.fa"
    p.write_text(">s\nACGT\n")
    assert FastaFile(p).file_size() == 8
