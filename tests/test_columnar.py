"""Columnar host analysis (report/columnar.py): byte-exact parity with
the scalar ground truth, the scalar-routing escape hatches, the batch
CLI engine switch, and the dispatch-budget counters."""

import io
import json

import numpy as np
import pytest

from pwasm_tpu.cli import run
from pwasm_tpu.core.dna import revcomp
from pwasm_tpu.core.errors import PwasmError
from pwasm_tpu.core.events import DiffEvent, extract_alignment
from pwasm_tpu.core.paf import parse_paf_line
from pwasm_tpu.report.columnar import analyze_events_columnar
from pwasm_tpu.report.diff_report import analyze_event_host

from helpers import make_paf_line
from test_events import _random_ops


def _events_for(q, line):
    rec = parse_paf_line(line)
    refseq_aln = revcomp(q) if rec.alninfo.reverse else q
    return extract_alignment(rec, refseq_aln).tdiffs, refseq_aln


def _copy(events):
    return [DiffEvent(evt=e.evt, evtlen=e.evtlen, evtbases=e.evtbases,
                      evtsub=e.evtsub, rloc=e.rloc, tloc=e.tloc,
                      tctx=e.tctx) for e in events]


def _assert_parity(q, events, skip_codan=False, motifs=None):
    kw = {} if motifs is None else {"motifs": motifs}
    scalar_ev = _copy(events)
    col = analyze_events_columnar(q, events, skip_codan, **kw)
    scal = [analyze_event_host(e, q, skip_codan, **kw)
            for e in scalar_ev]
    assert col == scal
    # both paths upper-case evtbases in place
    for a, b in zip(events, scalar_ev):
        assert a.evtbases == b.evtbases


@pytest.mark.parametrize("strand", ["+", "-"])
@pytest.mark.parametrize("skip_codan", [False, True])
def test_columnar_fuzz_parity(strand, skip_codan):
    rng = np.random.default_rng(42 if strand == "+" else 43)
    for trial in range(25):
        n = int(rng.integers(30, 220))
        q = "".join(rng.choice(list("ACGT"), size=n))
        q_aln = revcomp(q.encode()).decode() if strand == "-" else q
        ops = _random_ops(rng, q_aln)
        line, _ = make_paf_line("q", q, "t", strand, ops)
        events, refseq_aln = _events_for(q.encode(), line)
        if not events:
            continue
        _assert_parity(q.encode().upper(), events,
                       skip_codan=skip_codan)


def test_columnar_edge_positions():
    # events at the very first/last bases exercise the context window
    # edge clamps (incl. the reference's wrong-sign right-edge quirk)
    q = b"ATGGCCTGGAAAGATCTGTACCTGACGT"
    events = [DiffEvent(evt="S", evtlen=1, evtbases=b"A", evtsub=b"C",
                        rloc=r, tloc=r, tctx=b"ACGTACGT")
              for r, c in ((0, "G"), (1, "T"), (26, "A"), (27, "C"))]
    for e, sub in zip(events, (b"A", b"T", b"G", b"T")):
        e.evtsub = q[e.rloc:e.rloc + 1]     # consistent with the ref
    _assert_parity(q, events)


def test_columnar_degenerate_short_ref():
    # <9bp reference: get_ref_context's degenerate clamp branch
    q = b"ATGACG"
    events = [DiffEvent(evt="S", evtlen=1, evtbases=b"C",
                        evtsub=q[2:3], rloc=2, tloc=2, tctx=b"ATG"),
              DiffEvent(evt="D", evtlen=2, evtbases=b"AC",
                        evtsub=b"", rloc=3, tloc=3, tctx=b"ATG")]
    _assert_parity(q, events)


def test_columnar_iupac_routes_scalar():
    # non-ACGT content must not change results: the code space
    # collapses IUPAC to N, so these events route through the scalar
    # analyzer — parity is the contract either way
    q = b"ATGGNNCTGGAARRATCTGTACCTGA"
    events = [
        DiffEvent(evt="S", evtlen=1, evtbases=b"C", evtsub=q[4:5],
                  rloc=4, tloc=4, tctx=b"GGNNC"),     # sub of an N
        DiffEvent(evt="I", evtlen=3, evtbases=b"RRR", evtsub=b"",
                  rloc=8, tloc=8, tctx=b"TGGAA"),     # IUPAC insert
        DiffEvent(evt="S", evtlen=1, evtbases=b"T", evtsub=q[12:13],
                  rloc=12, tloc=12, tctx=b"AARRA"),   # IUPAC window
    ]
    _assert_parity(q, events)


def test_columnar_oversized_events_route_scalar():
    q = bytes(np.random.default_rng(7).choice(list(b"ACGT"), 400))
    big = b"A" * 80    # > HOST_MAX_EV: must take the scalar path
    events = [DiffEvent(evt="I", evtlen=len(big), evtbases=big,
                        evtsub=b"", rloc=200, tloc=200, tctx=b"ACGT"),
              DiffEvent(evt="S", evtlen=1, evtbases=b"C",
                        evtsub=q[100:101], rloc=100, tloc=100,
                        tctx=b"ACGT")]
    _assert_parity(q, events)


def test_columnar_sub_mismatch_raises_scalar_message():
    # the reference's fatal modseq-vs-evtsub verification: the columnar
    # path must raise the scalar path's exact message (with indices)
    q = b"ATGGCCTGGAAAGATCTGTACCTGA"
    bad = DiffEvent(evt="S", evtlen=1, evtbases=b"C", evtsub=b"T",
                    rloc=9, tloc=9, tctx=b"ACGT")  # q[9] is 'A' != 'T'
    with pytest.raises(PwasmError) as col_err:
        analyze_events_columnar(q, [bad])
    bad2 = DiffEvent(evt="S", evtlen=1, evtbases=b"C", evtsub=b"T",
                     rloc=9, tloc=9, tctx=b"ACGT")
    with pytest.raises(PwasmError) as scal_err:
        analyze_event_host(bad2, q, False)
    assert str(col_err.value) == str(scal_err.value)
    assert "modseq[" in str(col_err.value)


def test_cli_host_engines_byte_identical(tmp_path, monkeypatch):
    # the CLI's two host report engines (columnar default, scalar via
    # PWASM_HOST_COLUMNAR=0) produce identical report+summary bytes
    rng = np.random.default_rng(11)
    q = "".join(rng.choice(list("ACGT"), size=180))
    lines = []
    for k in range(12):
        strand = "-" if k % 3 == 0 else "+"
        q_aln = revcomp(q.encode()).decode() if strand == "-" else q
        ops = _random_ops(rng, q_aln)
        lines.append(make_paf_line("q", q, f"t{k}", strand, ops)[0])
    fa = tmp_path / "q.fa"
    fa.write_text(f">q\n{q}\n")
    paf = tmp_path / "in.paf"
    paf.write_text("".join(l + "\n" for l in lines))
    outs = {}
    for tag, flag in (("col", "1"), ("scalar", "0")):
        monkeypatch.setenv("PWASM_HOST_COLUMNAR", flag)
        rep = tmp_path / f"{tag}.dfa"
        summ = tmp_path / f"{tag}.sum"
        rc = run([str(paf), "-r", str(fa), "-o", str(rep),
                  "-s", str(summ), "--batch=5"], stderr=io.StringIO())
        assert rc == 0
        outs[tag] = rep.read_bytes() + summ.read_bytes()
    assert outs["col"] == outs["scalar"]


def test_cpu_path_batch_checkpoints(tmp_path):
    # the CPU report path now leaves batch-granular checkpoints during
    # the run (PR-1 durability extended beyond the device path); the
    # completed run removes the ckpt and the stats count the writes
    rng = np.random.default_rng(5)
    q = "".join(rng.choice(list("ACGT"), size=120))
    lines = []
    for k in range(9):
        ops = _random_ops(rng, q)
        lines.append(make_paf_line("q", q, f"t{k}", "+", ops)[0])
    fa = tmp_path / "q.fa"
    fa.write_text(f">q\n{q}\n")
    paf = tmp_path / "in.paf"
    paf.write_text("".join(l + "\n" for l in lines))
    rep = tmp_path / "r.dfa"
    stats = tmp_path / "r.stats"
    rc = run([str(paf), "-r", str(fa), "-o", str(rep), "--batch=2",
              f"--stats={stats}"], stderr=io.StringIO())
    assert rc == 0
    st = json.loads(stats.read_text())
    # 9 alignments at batch 2 -> 5 flushes, each checkpointed
    assert st["resilience"]["checkpoints"] >= 4
    assert not (tmp_path / "r.dfa.ckpt").exists()  # removed when whole
