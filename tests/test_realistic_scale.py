"""Realistic-scale end-to-end fixture (VERDICT r4 item 5).

The reference's contract is anchored to real minimap2 --cs output over
Nanopore assemblies (/root/reference/README.md:22-30): a ~1.5 kb CDS
query aligned against hundreds of assemblies of wildly varying length
with percent-level indel/substitution noise.  Every other repo fixture
is tiny; this one runs the full CLI at the intended scale and asserts

- CPU vs --device=tpu byte parity for report + summary + MSA outputs,
- a device-share floor from RunStats (the ctx-scan scope limits must
  not silently route a realistic event mix to the host),
- that oversized events (> MAX_EV bases, present at realistic indel
  rates) really take the scalar path — both routes live.

``make_corpus`` is importable by qa/realistic_scale.py, which runs the
same corpus standalone and records wall numbers for BASELINE.md.
"""

import io
import json

import numpy as np

from pwasm_tpu.cli import run
from pwasm_tpu.core.dna import revcomp

from helpers import make_paf_line

BASES = np.array(list(b"ACGT"), dtype=np.uint8)


def make_corpus(seed: int = 20260730, n_aln: int = 200,
                cds_len: int = 1500,
                asm_lo: int = 50_000, asm_hi: int = 150_000):
    """A Nanopore-like corpus: one ``cds_len`` query, ``n_aln``
    full-CDS alignments against assemblies of ragged length
    ``asm_lo``..``asm_hi`` with 3-8%% combined noise (subs dominate;
    indel lengths are geometric with a tail past the device MAX_EV=16
    scope limit).  Returns (query_str, paf_lines)."""
    rng = np.random.default_rng(seed)
    q = "".join(chr(b) for b in rng.choice(BASES, size=cds_len))
    lines = []
    for k in range(n_aln):
        strand = "-" if rng.random() < 0.35 else "+"
        q_aln = revcomp(q.encode()).decode() if strand == "-" else q
        sub_rate = rng.uniform(0.02, 0.05)
        ind_rate = rng.uniform(0.01, 0.03)
        # real aligner output is match-anchored at both ends (an
        # alignment can't start/end on an indel); reserve head/tail
        # match runs and confine the noise to the interior
        head = int(rng.integers(10, 30))
        tail = int(rng.integers(10, 30))
        noise_end = cds_len - tail
        ops = [("=", head)]
        pos = head
        mrun = 0                       # accumulated match run

        def flush_match():
            nonlocal mrun
            if mrun:
                ops.append(("=", mrun))
                mrun = 0

        while pos < noise_end:
            r = rng.random()           # PER-BASE noise draws
            if r < sub_rate:
                flush_match()
                qb = q_aln[pos]
                tb = "ACGT"[("ACGT".index(qb.upper())
                             + int(rng.integers(1, 4))) % 4]
                ops.append(("*", tb.lower(), qb.lower()))
                pos += 1
            elif r < sub_rate + ind_rate:
                flush_match()
                ln = min(1 + int(rng.geometric(0.25)), 24)
                if rng.random() < 0.5:
                    ins = "".join(
                        chr(b).lower() for b in
                        rng.choice(BASES, size=ln))
                    ops.append(("ins", ins))
                else:
                    ln = min(ln, noise_end - pos)
                    if ln > 0:
                        ops.append(("del", ln))
                        pos += ln
            else:
                mrun += 1
                pos += 1
        flush_match()
        ops.append(("=", cds_len - pos))
        asm_len = int(rng.integers(asm_lo, asm_hi))
        t_start = int(rng.integers(0, asm_len - 2 * cds_len))
        lines.append(make_paf_line(
            "cds1", q, f"asm{k:03d}", strand, ops,
            t_start=t_start, t_len=asm_len)[0])
    return q, lines


def test_realistic_scale_cpu_tpu_parity(tmp_path):
    qseq, lines = make_corpus()
    fa = tmp_path / "cds.fa"
    fa.write_text(f">cds1\n{qseq}\n")
    paf = tmp_path / "in.paf"
    paf.write_text("".join(l + "\n" for l in lines))
    outs = {}
    modes = {"cpu": ["--device=cpu"], "tpu": ["--device=tpu"],
             "shard": ["--device=tpu", "--shard"]}
    for tag, extra in modes.items():
        rep = tmp_path / f"{tag}.dfa"
        summ = tmp_path / f"{tag}.sum"
        mfa = tmp_path / f"{tag}.mfa"
        cons = tmp_path / f"{tag}.cons"
        stats = tmp_path / f"{tag}.stats"
        err = io.StringIO()
        rc = run([str(paf), "-r", str(fa), "-o", str(rep),
                  "-s", str(summ), "-w", str(mfa),
                  f"--cons={cons}", f"--stats={stats}"] + extra,
                 stderr=err)
        assert rc == 0, err.getvalue()[:2000]
        outs[tag] = (rep.read_bytes(), summ.read_bytes(),
                     mfa.read_bytes(), cons.read_bytes())
    assert outs["cpu"] == outs["tpu"]
    # the full 8-virtual-device mesh run is byte-identical too
    assert outs["cpu"] == outs["shard"]

    st = json.loads((tmp_path / "tpu.stats").read_text())
    assert st["alignments"] == 200
    assert st["fallback_batches"] == 0
    total = st["device_events"] + st["scalar_events"]
    assert total == st["events"] > 10_000      # realistic event count
    # device-share floor: the realistic mix must stay overwhelmingly
    # on device — scope-limit regressions show up here
    assert st["device_events"] / total >= 0.90, st
    # ...while the oversized-indel tail really exercises the scalar
    # route (its absence would mean the fixture lost its long indels)
    assert st["scalar_events"] > 0, st
    # dispatch budget (VERDICT r5 item 3): the whole 200-alignment run
    # must cost single-digit device round-trips — one packed ctx-scan
    # fetch per flush plus one consensus launch, NOT a fetch per
    # output field or a program per ref-length/event-count.  Through a
    # ~1-2 ms/dispatch tunnel this is the difference between dispatch
    # overhead being noise vs ~10-20% of the whole host wall.
    dev = st["device"]
    assert 0 < dev["flushes"] <= 9, dev
    assert 0 < dev["dispatches"] <= 9, dev
    assert dev["by_site"].get("ctx_scan", 0) >= 1, dev
    assert dev["by_site"].get("consensus", 0) >= 1, dev


def test_realistic_scale_fault_injected_byte_parity(tmp_path):
    """Chaos at realistic scale (ROADMAP PR-1 follow-up): a seeded
    fault storm through the supervised device pipeline must leave the
    output byte-identical to the clean run — retries and host
    degradations change counters, never bytes."""
    qseq, lines = make_corpus(n_aln=60)
    fa = tmp_path / "cds.fa"
    fa.write_text(f">cds1\n{qseq}\n")
    paf = tmp_path / "in.paf"
    paf.write_text("".join(l + "\n" for l in lines))
    outs = {}
    stats = {}
    # --batch=16: the dispatch-lean pipeline coalesces the whole corpus
    # into very few supervised round-trips, so a realistic flush count
    # is forced to give the (seeded, deterministic) fault plan enough
    # draw opportunities — and batch size must never change bytes
    for tag, extra in (
            ("clean", ["--batch=16"]),
            ("chaos", ["--batch=16",
                       "--inject-faults=seed=11,rate=0.4,"
                       "kinds=raise+nan+corrupt", "--max-retries=4"])):
        rep = tmp_path / f"{tag}.dfa"
        summ = tmp_path / f"{tag}.sum"
        mfa = tmp_path / f"{tag}.mfa"
        cons = tmp_path / f"{tag}.cons"
        stj = tmp_path / f"{tag}.stats"
        err = io.StringIO()
        rc = run([str(paf), "-r", str(fa), "-o", str(rep), "-s",
                  str(summ), "-w", str(mfa), f"--cons={cons}",
                  "--device=tpu", f"--stats={stj}"] + extra,
                 stderr=err)
        assert rc == 0, err.getvalue()[:2000]
        outs[tag] = (rep.read_bytes(), summ.read_bytes(),
                     mfa.read_bytes(), cons.read_bytes())
        stats[tag] = json.loads(stj.read_text())
    assert outs["clean"] == outs["chaos"]
    st = stats["chaos"]
    assert st["resilience"]["injected_faults"] > 0, st
    # injected faults re-execute: the chaos run must show retries or
    # degradations somewhere in the supervised pipeline
    assert (st["resilience"]["retries"] > 0
            or st["resilience"]["fallbacks"] > 0), st


def test_realistic_scale_flap_recovery_byte_parity(tmp_path,
                                                   monkeypatch):
    """The ISSUE 3 acceptance gate at realistic scale: a scripted
    outage window (``down=2-4`` over the supervised-call clock) on the
    200-alignment corpus opens the global breaker mid-run, the health
    monitor recloses it after the window, and the re-promoted device
    batches finish the run — byte-identical to the fault-free run,
    with ``breaker_recloses >= 1`` and ``recovered_batches > 0``.
    The ``--recover=off`` arm stays degraded (``breaker_recloses ==
    0``) and STILL matches bytes: recovery changes wall time and
    counters, never output."""
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    qseq, lines = make_corpus()
    fa = tmp_path / "cds.fa"
    fa.write_text(f">cds1\n{qseq}\n")
    paf = tmp_path / "in.paf"
    paf.write_text("".join(l + "\n" for l in lines))
    outs = {}
    stats = {}
    for tag, extra in (
            ("clean", []),
            ("flap", ["--inject-faults=down=2-4", "--max-retries=4",
                      "--reprobe-interval=0"]),
            ("off", ["--inject-faults=down=2-4", "--max-retries=4",
                     "--recover=off"])):
        rep = tmp_path / f"{tag}.dfa"
        summ = tmp_path / f"{tag}.sum"
        mfa = tmp_path / f"{tag}.mfa"
        cons = tmp_path / f"{tag}.cons"
        stj = tmp_path / f"{tag}.stats"
        err = io.StringIO()
        rc = run([str(paf), "-r", str(fa), "-o", str(rep), "-s",
                  str(summ), "-w", str(mfa), f"--cons={cons}",
                  "--device=tpu", "--batch=16", f"--stats={stj}"]
                 + extra, stderr=err)
        assert rc == 0, err.getvalue()[:2000]
        outs[tag] = (rep.read_bytes(), summ.read_bytes(),
                     mfa.read_bytes(), cons.read_bytes())
        stats[tag] = json.loads(stj.read_text())["resilience"]
    assert outs["clean"] == outs["flap"]
    assert outs["clean"] == outs["off"]
    flap = stats["flap"]
    assert flap["breaker_trips"] == 1, flap
    assert flap["breaker_recloses"] >= 1, flap
    assert flap["recovered_batches"] > 0, flap
    assert flap["degraded_batches"] > 0, flap
    off = stats["off"]
    assert off["breaker_trips"] == 1, off
    assert off["breaker_recloses"] == 0, off
    assert off["recovered_batches"] == 0, off
    assert off["degraded_batches"] > flap["degraded_batches"], (off,
                                                                flap)
