import numpy as np
import pytest

from pwasm_tpu.core import dna


def test_revcomp_basic():
    assert dna.revcomp(b"ACGT") == b"ACGT"
    assert dna.revcomp(b"AACC") == b"GGTT"
    assert dna.revcomp(b"acgtN") == b"Nacgt"


def test_revcomp_preserves_case_and_iupac():
    assert dna.revcomp(b"aCgT") == b"AcGt"
    assert dna.revcomp(b"MRWSYK") == b"MRSWYK"
    assert dna.complement(b"MRWSYKVHDB") == b"KYWSRMBDHV"


def test_revcomp_involution():
    rng = np.random.default_rng(0)
    seq = rng.choice(list(b"ACGTacgtNn"), size=100).astype(np.uint8).tobytes()
    assert dna.revcomp(dna.revcomp(seq)) == seq


def test_encode_decode():
    codes = dna.encode(b"ACGTNacgtn-X*")
    assert list(codes) == [0, 1, 2, 3, 4, 0, 1, 2, 3, 4, 5, 4, 5]
    assert dna.decode(np.array([0, 1, 2, 3, 4, 5])) == b"ACGTN-"


@pytest.mark.parametrize("codon,aa", [
    (b"ATG", "M"), (b"TAA", "."), (b"TAG", "."), (b"TGA", "."),
    (b"TTT", "F"), (b"GGG", "G"), (b"NNN", "X"), (b"AT", "X"),
    (b"atg", "M"), (b"TTR", "X"),
])
def test_translate_codon(codon, aa):
    assert dna.translate_codon(codon) == aa


def test_translate_codon_pos_and_end():
    seq = b"ATGTAA"
    assert dna.translate_codon(seq, 0) == "M"
    assert dna.translate_codon(seq, 3) == "."
    assert dna.translate_codon(seq, 5) == "X"  # reads off the end


def test_translate_codes_matches_scalar():
    rng = np.random.default_rng(1)
    seq = rng.choice(list(b"ACGTN"), size=300).astype(np.uint8).tobytes()
    codes = dna.encode(seq)
    aas = dna.translate_codes(codes)
    expect = [dna.translate_codon(seq, i) for i in range(0, 300, 3)]
    assert [chr(a) for a in aas] == expect


def test_translate_codes_batched():
    seqs = np.stack([dna.encode(b"ATGTAA"), dna.encode(b"TTTGGG")])
    aas = dna.translate_codes(seqs)
    assert aas.shape == (2, 2)
    assert bytes(aas[0]) == b"M."
    assert bytes(aas[1]) == b"FG"
