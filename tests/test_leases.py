"""Device-lease scheduler (ISSUE 8 tentpole).

Unit contracts for :class:`pwasm_tpu.service.leases.LeaseManager`
(grant/release/drain ordering, FIFO anti-starvation, timeouts) plus
the daemon-level contracts: per-lease breaker isolation (a flap on
lane 0 must not degrade lane 1), lease-gated admission when lanes <
workers, and the acceptance gate — ``--max-concurrent=2`` on 2 lanes
yields byte-identical per-job reports vs sequential cold runs.
"""

import json
import threading
import time

import pytest

from pwasm_tpu.service.leases import DeviceLease, LeaseManager

from test_service import (_cold, _corpus, _daemon, _job_args,
                          _submit_and_wait, SLOW)


# ---------------------------------------------------------------------------
# LeaseManager unit contracts
# ---------------------------------------------------------------------------
def test_lanes_partition_device_index_space():
    lm = LeaseManager(4, devices_per_lease=2)
    spans = [lease.devices for lease in lm.leases()]
    assert spans == [(0, 2), (2, 4), (4, 6), (6, 8)]
    assert lm.free_count() == 4 and lm.busy_count() == 0


def test_grant_release_roundtrip_and_counts():
    lm = LeaseManager(2)
    a = lm.acquire()
    b = lm.acquire()
    assert {a.lane, b.lane} == {0, 1}
    assert a.busy and b.busy
    assert lm.free_count() == 0
    assert lm.acquire(timeout=0.05) is None      # pool exhausted
    lm.release(a)
    assert lm.free_count() == 1 and not a.busy
    c = lm.acquire()
    assert c is a                                # the freed lane
    assert lm.grants == 3
    lm.release(b)
    lm.release(c)
    assert lm.free_count() == 2
    assert a.jobs_run == 2 and b.jobs_run == 1


def test_fifo_grant_order_no_starvation():
    """Grants go to waiters strictly in arrival order: with one lane
    and many queued acquirers, completion order == arrival order (a
    bare Condition.notify gives no such guarantee)."""
    lm = LeaseManager(1)
    first = lm.acquire()
    order: list[int] = []
    started = []

    def waiter(k):
        started.append(k)
        lease = lm.acquire(timeout=10)
        order.append(k)
        time.sleep(0.01)
        lm.release(lease)

    threads = []
    for k in range(5):
        t = threading.Thread(target=waiter, args=(k,))
        threads.append(t)
        t.start()
        while k not in started:      # enqueue strictly in k order
            time.sleep(0.001)
        time.sleep(0.02)             # let the acquire actually queue
    assert lm.waiting_count() == 5
    lm.release(first)
    for t in threads:
        t.join(10)
    assert order == [0, 1, 2, 3, 4]
    assert lm.wait_s_total > 0


def test_drain_wakes_waiters_and_rejects_new_acquires():
    lm = LeaseManager(1)
    held = lm.acquire()
    got: list = ["sentinel"]

    def waiter():
        got[0] = lm.acquire(timeout=10)

    t = threading.Thread(target=waiter)
    t.start()
    while lm.waiting_count() == 0:
        time.sleep(0.001)
    lm.drain()
    t.join(5)
    assert got[0] is None                 # woken empty-handed
    assert lm.acquire(timeout=0.05) is None
    lm.release(held)                      # in-flight release still fine
    assert lm.acquire(timeout=0.05) is None   # ...but no new grants


def test_acquire_timeout_withdraws_ticket():
    lm = LeaseManager(1)
    held = lm.acquire()
    assert lm.acquire(timeout=0.05) is None
    assert lm.waiting_count() == 0        # the timed-out ticket is gone
    lm.release(held)
    assert lm.free_count() == 1           # ...and the lease was NOT
    #                                       handed to the dead waiter


def test_acquire_should_abort_keeps_one_ticket():
    """A blocking acquire polling ``should_abort`` holds ONE ticket for
    the whole wait (the daemon worker's mode): the wait survives many
    poll slices without re-enqueueing (which would reorder FIFO), the
    recorded wait spans the full queue time, and flipping the abort
    flag releases the waiter empty-handed with its ticket withdrawn."""
    lm = LeaseManager(1)
    held = lm.acquire()
    stop = threading.Event()
    got: list = ["sentinel"]

    def waiter():
        got[0] = lm.acquire(should_abort=stop.is_set, poll_s=0.01)

    t = threading.Thread(target=waiter)
    t.start()
    while lm.waiting_count() == 0:
        time.sleep(0.001)
    time.sleep(0.1)                      # many poll slices elapse...
    assert lm.waiting_count() == 1       # ...same single ticket queued
    lm.release(held)
    t.join(5)
    assert got[0] is held                # granted to the waiting ticket
    assert lm.wait_s_total >= 0.1        # full wait, not the last slice
    lm.release(got[0])

    held = lm.acquire()
    got[0] = "sentinel"
    t = threading.Thread(target=waiter)
    t.start()
    while lm.waiting_count() == 0:
        time.sleep(0.001)
    stop.set()
    t.join(5)
    assert got[0] is None                # aborted empty-handed
    assert lm.waiting_count() == 0       # ticket withdrawn
    lm.release(held)
    assert lm.free_count() == 1


def test_breaker_rollup_is_worst_lane():
    lm = LeaseManager(3)
    assert lm.breaker_rollup() == 0
    leases = lm.leases()
    leases[1].supervisor_state = {"breaker_open": True}
    assert lm.breaker_rollup() == 2

    class HalfOpenMon:
        state = "half-open"

    leases[1].monitor = HalfOpenMon()
    assert lm.breaker_rollup() == 1       # open but probing healthy
    leases[2].supervisor_state = {"breaker_open": True}
    assert lm.breaker_rollup() == 2       # lane 2 has no monitor: open
    rows = lm.lane_states()
    assert [r["breaker_state"] for r in rows] == [0, 1, 2]
    assert rows[0]["devices"] == [0, 1]


def test_device_lease_repr_and_defaults():
    lease = DeviceLease(3, 6, 8)
    assert "lane=3" in repr(lease)
    assert lease.supervisor_state is None and lease.monitor is None


# ---------------------------------------------------------------------------
# daemon-level lease contracts
# ---------------------------------------------------------------------------
def test_two_lane_concurrent_jobs_byte_identical(tmp_path):
    """The ISSUE 8 acceptance gate: --max-concurrent=2 (2 lanes) runs
    two jobs concurrently, each byte-identical to a sequential cold
    run, and both lanes saw work."""
    paf, fa = _corpus(tmp_path)
    cold = _cold(tmp_path, "cold", paf, fa)
    results: dict = {}

    def submitter(tag, sock):
        results[tag] = _submit_and_wait(
            sock, _job_args(tmp_path, tag, paf, fa, [SLOW]))

    with _daemon(max_queue=4, max_concurrent=2) as h:
        ts = [threading.Thread(target=submitter, args=(t, h.sock))
              for t in ("la", "lb")]
        for t in ts:
            t.start()
        # observe genuine concurrency: both lanes leased at once while
        # the injected-hang jobs run (a wall-clock bound would be
        # flaky on a loaded box; lane occupancy is exact)
        saw_both = False
        deadline = time.time() + 60
        while time.time() < deadline and not saw_both:
            saw_both = h.daemon.leases.busy_count() == 2
            time.sleep(0.005)
        for t in ts:
            t.join(180)
        assert h.daemon.leases.n_lanes == 2
        lanes_used = {row["lane"]: row["jobs_run"]
                      for row in h.daemon.leases.lane_states()}
    for tag in ("la", "lb"):
        assert results[tag].get("ok") and results[tag]["rc"] == 0, \
            results[tag]
        assert (tmp_path / f"{tag}.dfa").read_bytes() == cold, tag
    # both jobs ran CONCURRENTLY on separate lanes
    assert saw_both
    assert sum(lanes_used.values()) == 2
    assert max(lanes_used.values()) == 1, lanes_used


def test_per_lease_breaker_isolation(tmp_path, monkeypatch):
    """A flap that opens the breaker on one lane must not degrade the
    other lane — and the NEXT job on the flapped lane (the only free
    one while a slow clean job still holds its lane) inherits the open
    breaker without re-tripping."""
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path)
    cold = _cold(tmp_path, "cold", paf, fa)

    def stats_of(tag):
        return json.loads(
            (tmp_path / f"{tag}.json").read_text())["resilience"]

    with _daemon(max_queue=8, max_concurrent=2) as h:
        # occupy one lane with a SLOW clean job for the whole test
        slow_res: dict = {}

        def slow_submitter():
            slow_res.update(_submit_and_wait(
                h.sock, _job_args(tmp_path, "slowclean", paf, fa,
                                  [SLOW, "--recover=off"]),
                timeout=300))

        ts = threading.Thread(target=slow_submitter)
        ts.start()
        while h.daemon.leases.busy_count() == 0:
            time.sleep(0.01)
        # flap job on the OTHER lane: opens that lane's breaker
        r1 = _submit_and_wait(h.sock, _job_args(
            tmp_path, "flap", paf, fa,
            ["--inject-faults=down=1-999", "--max-retries=0",
             "--recover=off"]))
        assert r1["rc"] == 0, r1
        st1 = stats_of("flap")
        assert st1["breaker_trips"] == 1 and st1["degraded_batches"] > 0
        # while the slow job still holds its lane, the only free lease
        # is the flapped one: the next job MUST inherit its open
        # breaker (degraded, no re-trip)
        assert h.daemon.leases.busy_count() >= 1
        r2 = _submit_and_wait(h.sock, _job_args(
            tmp_path, "inherit", paf, fa, ["--recover=off"]))
        assert r2["rc"] == 0, r2
        st2 = stats_of("inherit")
        assert st2["breaker_trips"] == 0, st2
        assert st2["degraded_batches"] > 0, st2
        # daemon roll-up: worst lane is OPEN, per-lane vector disagrees
        assert h.daemon.leases.breaker_rollup() == 2
        states = sorted(r["breaker_state"]
                        for r in h.daemon.leases.lane_states())
        assert states == [0, 2], states
        ts.join(300)
        assert slow_res.get("rc") == 0, slow_res
        # the clean lane NEVER degraded: isolation held
        st_slow = stats_of("slowclean")
        assert st_slow["breaker_trips"] == 0, st_slow
        assert st_slow["degraded_batches"] == 0, st_slow
    for tag in ("flap", "inherit", "slowclean"):
        assert (tmp_path / f"{tag}.dfa").read_bytes() == cold, tag


def test_lease_gated_admission_when_lanes_below_workers(tmp_path):
    """lanes=1 with 2 workers: both workers dequeue, but only one job
    runs at a time — the second waits for the LEASE (measured by the
    lease-wait histogram), and outputs stay byte-identical."""
    paf, fa = _corpus(tmp_path, n=8)
    cold = _cold(tmp_path, "cold", paf, fa)
    results: dict = {}

    def submitter(tag, sock):
        results[tag] = _submit_and_wait(
            sock, _job_args(tmp_path, tag, paf, fa, [SLOW]))

    with _daemon(max_queue=4, max_concurrent=2, lanes=1) as h:
        ts = [threading.Thread(target=submitter, args=(t, h.sock))
              for t in ("ga", "gb")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(180)
        assert h.daemon.leases.n_lanes == 1
        grants = h.daemon.leases.grants
        hist = h.daemon.svc_metrics["lease_wait_seconds"]
        exposition = h.daemon.registry.expose()
    assert grants == 2
    # one job genuinely waited: the wait histogram saw a sample well
    # past the first bucket (the waiting job sat out the holder's
    # injected-hang batches)
    assert "pwasm_service_lease_wait_seconds_count 2" in exposition
    assert hist is not None
    for tag in ("ga", "gb"):
        assert results[tag].get("ok") and results[tag]["rc"] == 0, \
            results[tag]
        assert (tmp_path / f"{tag}.dfa").read_bytes() == cold, tag


def test_drain_preempts_lease_waiter(tmp_path):
    """A job dequeued but still WAITING for a lease when the drain
    lands is preempted exactly like a queued one."""
    paf, fa = _corpus(tmp_path, n=8)
    with _daemon(max_queue=4, max_concurrent=2, lanes=1) as h:
        from pwasm_tpu.service.client import ServiceClient
        with ServiceClient(h.sock) as c:
            a = c.submit(_job_args(tmp_path, "da", paf, fa, [SLOW]))
            assert a.get("ok"), a
            b = c.submit(_job_args(tmp_path, "db", paf, fa, [SLOW]))
            assert b.get("ok"), b
            # wait until BOTH are dequeued (queue empty) but only one
            # holds the lease — the other is lease-waiting
            deadline = time.time() + 30
            while time.time() < deadline:
                if (h.daemon.queue.depth() == 0
                        and h.daemon.leases.waiting_count() == 1):
                    break
                time.sleep(0.01)
            assert h.daemon.leases.waiting_count() == 1
            c.drain()
            res_b = c.result(b["job_id"], timeout=60)
            assert res_b.get("ok"), res_b
            assert res_b["job"]["state"] == "preempted", res_b
            res_a = c.result(a["job_id"], timeout=120)
            # the lease HOLDER drains at a batch boundary (preempted,
            # resumable) — never killed mid-batch
            assert res_a["job"]["state"] in ("preempted", "done"), res_a


def test_serve_flags_lanes_and_devices_per_job(tmp_path):
    """serve_main grammar: --devices-per-job/--lanes parse, bad values
    are usage errors."""
    import io

    from pwasm_tpu.core.errors import EXIT_USAGE
    from pwasm_tpu.service.daemon import serve_main

    for bad in ("--devices-per-job=0", "--devices-per-job=x",
                "--lanes=-2", "--lanes="):
        err = io.StringIO()
        rc = serve_main([f"--socket={tmp_path / 's'}", bad],
                        stderr=err)
        assert rc == EXIT_USAGE, (bad, rc)
        assert "Invalid" in err.getvalue()


def test_job_warm_routes_state_to_lease():
    """_JobWarm reads/writes breaker state and monitor ON the lease,
    and exposes the device span only when asked."""
    from pwasm_tpu.service.daemon import WarmContext, _JobWarm

    shared = WarmContext()
    lease = DeviceLease(1, 2, 4)
    w = _JobWarm(shared, drain=None, lease=lease, expose_devices=True)
    assert w.lease_devices == (2, 4)
    w.supervisor_state = {"breaker_open": True}
    assert lease.supervisor_state == {"breaker_open": True}
    w.monitor = "mon"
    assert lease.monitor == "mon"
    w2 = _JobWarm(shared, drain=None, lease=lease)
    assert w2.lease_devices is None          # classic single-lane shape
    assert w2.supervisor_state == {"breaker_open": True}
    shared.close()


def test_lane_device_pool_clamps_to_available(monkeypatch):
    """cli._lane_device_pool maps a span past the real device count
    onto the available pool instead of crashing (single-CPU backend:
    every lane degrades to device 0)."""
    from pwasm_tpu import cli as cli_mod

    pool = cli_mod._lane_device_pool((0, 1))
    assert len(pool) == 1
    import jax

    n = len(jax.devices())
    wrap = cli_mod._lane_device_pool((n + 3, n + 4))
    assert len(wrap) == 1 and wrap[0] in jax.devices()
