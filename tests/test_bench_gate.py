"""Bench regression gate (``qa/bench_gate.py``, ROADMAP item 4): a
fresh bench run's legs compared against the committed BENCH_ALL.json
trajectory — wall slowdowns and rate drops beyond tolerance fail, lost
boolean/parity legs fail, new/retired legs skip, ``--allow`` waives an
explained regression explicitly."""

import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def gate():
    for p in (REPO, os.path.join(REPO, "qa")):
        if p not in sys.path:
            sys.path.insert(0, p)
    import bench_gate
    return bench_gate


def _rows(**vals):
    out = []
    for name, (value, unit) in vals.items():
        out.append({"metric": name, "value": value, "unit": unit,
                    "config": 8})
    return out


def test_wall_slowdown_fails_within_tolerance_passes(gate):
    base = _rows(wall=(1.0, "s"))
    ok = gate.compare(_rows(wall=(1.2, "s")), base, tolerance=0.25)
    assert ok["regressions"] == [] and ok["checked"] == 1
    bad = gate.compare(_rows(wall=(1.3, "s")), base, tolerance=0.25)
    assert [r["metric"] for r in bad["regressions"]] == ["wall"]
    assert bad["regressions"][0]["ratio"] == pytest.approx(1.3)


def test_ratio_unit_gated_lower_is_better(gate):
    # unit "x" (lower-is-better multipliers, e.g.
    # realistic_pycli_vs_native_ratio): gated exactly like a wall
    base = _rows(ratio=(1.2, "x"))
    ok = gate.compare(_rows(ratio=(1.4, "x")), base, tolerance=0.25)
    assert ok["regressions"] == [] and ok["checked"] == 1
    bad = gate.compare(_rows(ratio=(1.6, "x")), base, tolerance=0.25)
    assert [r["metric"] for r in bad["regressions"]] == ["ratio"]
    good = gate.compare(_rows(ratio=(1.0, "x")), base, tolerance=0.25)
    assert [r["metric"] for r in good["improved"]] == ["ratio"]


def test_rate_drop_fails_gain_improves(gate):
    base = _rows(rate=(1000.0, "bases/s"))
    bad = gate.compare(_rows(rate=(700.0, "bases/s")), base,
                       tolerance=0.25)
    assert [r["metric"] for r in bad["regressions"]] == ["rate"]
    good = gate.compare(_rows(rate=(2000.0, "bases/s")), base)
    assert good["regressions"] == []
    assert [r["metric"] for r in good["improved"]] == ["rate"]


def test_bool_leg_lost_fails_gained_passes(gate):
    base = _rows(parity=(1, "bool"), lowering=(0, "bool"))
    res = gate.compare(_rows(parity=(0, "bool"), lowering=(1, "bool")),
                       base)
    assert [r["metric"] for r in res["regressions"]] == ["parity"]


def test_missing_metrics_skip_not_fail(gate):
    base = _rows(wall=(1.0, "s"), retired=(2.0, "s"))
    res = gate.compare(_rows(wall=(1.0, "s"), fresh=(3.0, "s")), base)
    assert res["regressions"] == []
    skipped = {e["metric"] for e in res["skipped"]}
    assert skipped == {"retired", "fresh"}


def test_allow_waives_named_regression(gate):
    base = _rows(wall=(1.0, "s"))
    res = gate.compare(_rows(wall=(9.0, "s")), base,
                       allow=frozenset({"wall"}))
    assert res["regressions"] == [] \
        and [r["metric"] for r in res["waived"]] == ["wall"]


def test_ungated_units_and_bad_baseline_skip(gate):
    base = _rows(count=(5, "alignments"), zero=(0.0, "s"))
    res = gate.compare(_rows(count=(50, "alignments"),
                             zero=(1.0, "s")), base)
    assert res["regressions"] == [] and res["checked"] == 0


def test_load_rows_both_shapes(gate, tmp_path):
    rows = _rows(wall=(1.0, "s"))
    agg = tmp_path / "agg.json"
    agg.write_text(json.dumps(rows))
    nd = tmp_path / "nd.json"
    nd.write_text("not json\n" + "".join(
        json.dumps(r) + "\n" for r in rows))
    assert gate.index_rows(gate.load_rows(str(agg))).keys() == {"wall"}
    assert gate.index_rows(gate.load_rows(str(nd))).keys() == {"wall"}


def test_cli_self_compare_committed_trajectory_passes(gate, capsys):
    """The committed BENCH_ALL.json gates cleanly against itself —
    the invariant every PR's fresh run is compared under."""
    rc = gate.main([os.path.join(REPO, "BENCH_ALL.json")])
    assert rc == 0
    out = capsys.readouterr().out
    assert "0 regression(s)" in out


def test_cli_exit_codes(gate, tmp_path, capsys):
    new = tmp_path / "new.json"
    new.write_text(json.dumps(_rows(
        realistic_pycli_wall_s=(99.0, "s"))))
    assert gate.main([str(new)]) == 1
    assert gate.main([str(new),
                      "--allow=realistic_pycli_wall_s"]) == 0
    assert gate.main([]) == 2
    for bad in ("bogus", "nan", "inf", "-0.5"):
        assert gate.main([str(new), f"--tolerance={bad}"]) == 2


def test_ms_unit_gated_lower_is_better(gate):
    # unit "ms" (queue-wait legs, e.g.
    # realistic_serve_fairshare_p50_light_ms): gated like a wall
    base = _rows(wait=(800.0, "ms"))
    ok = gate.compare(_rows(wait=(900.0, "ms")), base, tolerance=0.25)
    assert ok["regressions"] == [] and ok["checked"] == 1
    bad = gate.compare(_rows(wait=(1200.0, "ms")), base,
                       tolerance=0.25)
    assert [r["metric"] for r in bad["regressions"]] == ["wait"]
    good = gate.compare(_rows(wait=(400.0, "ms")), base)
    assert [r["metric"] for r in good["improved"]] == ["wait"]
