"""Zero-trust fleet edge (ISSUE 19): TLS/mTLS transport, scoped
capability tokens, adaptive per-client rate limiting.

Acceptance contracts drilled here:

- **TLS floor**: ``serve --tls-cert/--tls-key`` upgrades the TCP
  listener (TLS 1.2+); a plaintext probe against the TLS port gets a
  LOUD close (never a hang) and increments the handshake-failure
  counter; the unix socket stays plaintext behind its 0600 mode;
- **mTLS identity**: with ``--tls-client-ca`` the verified peer CN is
  the connection's attested identity (``cn:<name>``), outranking
  ``client_token`` in the fair-share resolution order; an untrusted
  client cert never completes the handshake;
- **scoped tokens**: ``--auth-tokens`` maps credentials to
  {submit, read, cancel-own, admin}; control verbs (drain /
  lease-grant / fence, and the stats-borne lease grant) demand admin;
  cancel demands ownership-or-admin; every refusal answers
  ``unauthorized`` having written NOTHING to queue/journal state;
  the file hot-reloads keep-last-good on the accept-loop tick;
- **rate limiting**: ``--rate-limit`` is a per-identity token bucket
  in FRONT of admission on both tiers, refusing with a truthful
  ``retry_after_s``; repeated auth failures earn a capped-exponential
  penalty and feed the auth-failure counter + SLO rule;
- **fleet drill**: an all-mTLS fleet (TCP members with client-cert
  verification, router dialing with its own cert, warm standby
  riding the same config) survives primary-router death AND a
  member SIGKILL with byte-identical reports vs the uncrashed arm;
- **byte identity**: with none of the new flags, behavior is
  unchanged — anonymous submit/drain still serve.
"""

import io
import json
import os
import shutil
import socket as socket_mod
import stat
import subprocess
import sys
import tempfile
import threading
import time

import pytest

from pwasm_tpu.fleet.router import Router
from pwasm_tpu.fleet.transport import (ClientTLS, ServerTLS, connect,
                                       router_journal_path,
                                       target_name)
from pwasm_tpu.service import authz
from pwasm_tpu.service.authz import AuthRegistry, PenaltyBox
from pwasm_tpu.service.client import (ServiceClient, ServiceError,
                                      wait_for_socket)
from pwasm_tpu.service.queue import RateLimiter, parse_rate_limit
from pwasm_tpu.utils.fsio import ensure_private_dir

from test_fleet import (_corpus, _daemon, _job_args, _serve_env,
                        _stub_runner)

HERE = os.path.dirname(os.path.abspath(__file__))
CERTS = os.path.join(HERE, "certs")
CA = os.path.join(CERTS, "ca.pem")
SRV_CERT = os.path.join(CERTS, "server.pem")
SRV_KEY = os.path.join(CERTS, "server.key")
ADMIN_CERT = os.path.join(CERTS, "fleet-admin.pem")
ADMIN_KEY = os.path.join(CERTS, "fleet-admin.key")
ALICE_CERT = os.path.join(CERTS, "alice.pem")
ALICE_KEY = os.path.join(CERTS, "alice.key")
ROGUE_CERT = os.path.join(CERTS, "rogue.pem")
ROGUE_KEY = os.path.join(CERTS, "rogue.key")

SLOW = "--inject-faults=seed=1,rate=1,kinds=hang,hang_s=0.25"


def _server_tls(client_ca=None):
    return ServerTLS(SRV_CERT, SRV_KEY, client_ca=client_ca)


def _client_tls(cert=None, key=None):
    return ClientTLS(CA, certfile=cert, keyfile=key)


def _journal_bytes(path):
    try:
        return open(path, "rb").read()
    except OSError:
        return b""


# ---------------------------------------------------------------------------
# primitives: private dirs, rate limiter, penalty box, token file
# ---------------------------------------------------------------------------
def test_ensure_private_dir(tmp_path):
    d = str(tmp_path / "a" / "b")
    assert ensure_private_dir(d) == d
    assert stat.S_IMODE(os.stat(d).st_mode) == 0o700
    # idempotent, and a PRE-EXISTING dir keeps its operator-given
    # mode (shared storage stays shared)
    wide = str(tmp_path / "wide")
    os.makedirs(wide, mode=0o755)
    os.chmod(wide, 0o755)
    ensure_private_dir(wide)
    assert stat.S_IMODE(os.stat(wide).st_mode) == 0o755
    # a file squatting the path is an error, not a silent pass
    f = tmp_path / "f"
    f.write_text("x")
    with pytest.raises(FileExistsError):
        ensure_private_dir(str(f))


def test_parse_rate_limit_grammar():
    assert parse_rate_limit("10") == (10.0, 10.0)
    assert parse_rate_limit("10/s") == (10.0, 10.0)
    assert parse_rate_limit("2.5/s:8") == (2.5, 8.0)
    assert parse_rate_limit("0.5") == (0.5, 1.0)   # floor burst 1
    for bad in ("0", "-1", "nope", "1:-2", "1:0.5", "inf", "1:inf"):
        with pytest.raises(ValueError):
            parse_rate_limit(bad)


def test_rate_limiter_truthful_and_bounded():
    rl = RateLimiter(2.0, 3.0)
    t0 = 1000.0
    got = [rl.admit("a", now=t0) for _ in range(5)]
    assert got[:3] == [0.0, 0.0, 0.0]       # burst admits
    assert got[3] == got[4] == 0.5          # truthful: 1 token / 2 per s
    # honoring the hint readmits exactly then
    assert rl.admit("a", now=t0 + 0.5) == 0.0
    # identities are independent
    assert rl.admit("b", now=t0) == 0.0
    assert rl.refusals == 2
    # bounded table: full (idle) buckets are swept first at the cap
    small = RateLimiter(1.0, 1.0, max_clients=4)
    for i in range(4):
        small.admit(f"c{i}", now=t0)
    small.admit("c0", now=t0 + 100)         # c0 refilled = idle
    small.admit("fresh", now=t0 + 100)
    assert len(small._buckets) <= 4


def test_penalty_box_caps_and_clears():
    pb = PenaltyBox(base_s=0.05, cap_s=2.0, max_peers=3)
    assert pb.fail("x") == pytest.approx(0.05)
    assert pb.fail("x") == pytest.approx(0.10)
    for _ in range(10):
        d = pb.fail("x")
    assert d == 2.0                          # capped
    pb.clear("x")
    assert pb.fail("x") == pytest.approx(0.05)
    # bounded: a 4th peer evicts the oldest, never grows the table
    for k in ("a", "b", "c", "d"):
        pb.fail(k)
    assert len(pb._counts) <= 3


def test_token_file_roundtrip_and_integrity(tmp_path):
    p = str(tmp_path / "tokens.json")
    authz.write_auth_tokens(p, {"sekrit": ["submit", "read"],
                                "cn:fleet-admin": ["admin"]})
    reg = AuthRegistry(p)
    assert reg.scopes_for("sekrit", None) == {"submit", "read"}
    assert reg.scopes_for(None, "cn:fleet-admin") == {"admin"}
    assert reg.scopes_for("nope", "uid:12") == frozenset()
    # admin implies everything
    assert reg.allows({"client_token": None}, "cn:fleet-admin",
                      authz.SCOPE_SUBMIT)
    # CRC integrity: a hand-edited byte refuses to load
    raw = open(p).read()
    open(p, "w").write(raw.replace("submit", "sudmit"))
    with pytest.raises(ValueError):
        AuthRegistry(p)
    # unknown scope refuses at mint-validation time too
    obj = {"tokens": {"t": ["root"]}}
    from pwasm_tpu.utils.fsio import payload_crc
    obj["crc"] = payload_crc(obj)
    open(p, "w").write(json.dumps(obj))
    with pytest.raises(ValueError) as ei:
        AuthRegistry(p)
    assert "unknown scope" in str(ei.value)


def test_required_scope_map():
    assert authz.required_scope("ping", {}) is None
    assert authz.required_scope("nonesuch", {}) is None  # unknown_cmd
    assert authz.required_scope("submit", {}) == authz.SCOPE_SUBMIT
    assert authz.required_scope("cancel", {}) == authz.SCOPE_CANCEL_OWN
    assert authz.required_scope("drain", {}) == authz.SCOPE_ADMIN
    assert authz.required_scope("stats", {}) == authz.SCOPE_READ
    # a stats frame carrying a lease is a lease GRANT: admin
    assert authz.required_scope(
        "stats", {"lease": {"epoch": 1}}) == authz.SCOPE_ADMIN


# ---------------------------------------------------------------------------
# TLS transport on the daemon
# ---------------------------------------------------------------------------
def test_tls_roundtrip_and_plaintext_probe(tmp_path):
    with _daemon(runner=_stub_runner(), listen="127.0.0.1:0",
                 tls=_server_tls()) as h:
        tcp = f"127.0.0.1:{h.daemon.tcp_port}"
        out = str(tmp_path / "o.dfa")
        # the same protocol, now under TLS
        with ServiceClient(tcp, tls=_client_tls()) as c:
            assert c.ping()["ok"]
            r = c.result(c.submit(["in.paf", "-o", out],
                                  cwd=str(tmp_path))["job_id"],
                         timeout=30)
            assert r["rc"] == 0
        # a client WITHOUT tls config speaks plaintext at a TLS port:
        # loud close (or an alert blob), never a hang, never a serve
        conn = connect(tcp, timeout=5)
        try:
            conn.sendall(b'{"cmd":"ping"}\n')
            conn.settimeout(5)
            try:
                data = conn.recv(1 << 16)
            except OSError:
                data = b""
            assert b'"ok"' not in data   # nothing was served plain
        finally:
            conn.close()
        # the failure was COUNTED (observable, not swallowed)
        deadline = time.monotonic() + 5
        seen = 0
        while time.monotonic() < deadline:
            with ServiceClient(h.sock) as c:   # unix side: plaintext
                body = c.metrics()["metrics"]
            m = [l for l in body.splitlines()
                 if l.startswith(
                     "pwasm_transport_tls_handshake_failures_total")]
            seen = float(m[0].split()[-1]) if m else 0
            if seen >= 1:
                break
            time.sleep(0.05)
        assert seen >= 1
        # the unix socket itself is 0600 (satellite: perm contract)
        assert stat.S_IMODE(os.stat(h.sock).st_mode) == 0o600


def test_mtls_peer_cn_is_attested_identity(tmp_path):
    with _daemon(runner=_stub_runner(), listen="127.0.0.1:0",
                 tls=_server_tls(client_ca=CA)) as h:
        tcp = f"127.0.0.1:{h.daemon.tcp_port}"
        out = str(tmp_path / "o.dfa")
        # verified CN becomes the fair-share identity, OUTRANKING a
        # client_token on the same frame
        with ServiceClient(tcp, client_token="spoof",
                           tls=_client_tls(ALICE_CERT,
                                           ALICE_KEY)) as c:
            r = c.result(c.submit(["in.paf", "-o", out],
                                  cwd=str(tmp_path))["job_id"],
                         timeout=30)
            assert r["job"]["client"] == "cn:alice"
        # an explicit client= still wins (resolution order intact)
        with ServiceClient(tcp, tls=_client_tls(ALICE_CERT,
                                                ALICE_KEY)) as c:
            r = c.result(c.submit(["in.paf", "-o", out],
                                  cwd=str(tmp_path),
                                  client="tenant9")["job_id"],
                         timeout=30)
            assert r["job"]["client"] == "tenant9"
        # an untrusted (self-signed) client cert never completes the
        # handshake — refused at the transport, not at a verb
        with pytest.raises((ServiceError, OSError)):
            with ServiceClient(tcp, timeout=5,
                               tls=ClientTLS(CA, certfile=ROGUE_CERT,
                                             keyfile=ROGUE_KEY)) as c:
                c.ping()
        # and the daemon still serves afterwards
        with ServiceClient(tcp, tls=_client_tls(ALICE_CERT,
                                                ALICE_KEY)) as c:
            assert c.ping()["ok"]


def test_state_dirs_created_private(tmp_path):
    """Result-cache and spool dirs land 0700 at creation."""
    cache = str(tmp_path / "cache")
    spool = str(tmp_path / "spool")
    with _daemon(runner=_stub_runner(), result_cache=cache,
                 spool_threshold_bytes=1, spool_dir=spool) as h:
        with ServiceClient(h.sock) as c:
            r = c.result(c.submit(["in.paf", "-o",
                                   str(tmp_path / "o.dfa")],
                                  cwd=str(tmp_path))["job_id"],
                         timeout=30)
            assert r["rc"] == 0
    assert stat.S_IMODE(os.stat(cache).st_mode) == 0o700
    assert stat.S_IMODE(os.stat(spool).st_mode) == 0o700


# ---------------------------------------------------------------------------
# scoped capability tokens on the daemon
# ---------------------------------------------------------------------------
def _mint(tmp_path, tokens):
    p = str(tmp_path / "tokens.json")
    authz.write_auth_tokens(p, tokens)
    return p


def test_scoped_tokens_matrix_and_zero_state_on_refusal(tmp_path):
    tok = _mint(tmp_path, {
        "writer": ["submit", "read"],
        "reader": ["read"],
        "alice-t": ["submit", "read", "cancel-own"],
        "bob-t": ["submit", "read", "cancel-own"],
        "boss": ["admin"],
    })
    with _daemon(runner=_stub_runner(sleep=0.3),
                 auth_tokens=tok) as h:
        journal = h.sock + ".journal"
        out = str(tmp_path / "o.dfa")

        def deny(client, req):
            r = client._req(req)
            assert r["ok"] is False and r["error"] == "unauthorized", r
            return r

        with ServiceClient(h.sock, client_token="writer") as c:
            assert c.ping()["ok"]            # ping stays open
            j = c.submit(["in.paf", "-o", out], cwd=str(tmp_path))
            assert j["ok"], j
            assert c.result(j["job_id"], timeout=30)["rc"] == 0
            before = _journal_bytes(journal)
            # control plane demands admin — and a refusal writes
            # NOTHING (journal byte-identical, daemon not draining)
            deny(c, {"cmd": "drain"})
            deny(c, {"cmd": "fence", "reason": "test"})
            deny(c, {"cmd": "lease-grant",
                     "lease": {"epoch": 99, "ttl_s": 5}})
            deny(c, {"cmd": "stats", "lease": {"epoch": 99,
                                               "ttl_s": 5}})
            assert _journal_bytes(journal) == before
            assert c.ping()["draining"] is False
        with ServiceClient(h.sock, client_token="reader") as c:
            deny(c, {"cmd": "submit", "argv": ["x"]})   # read-only
            assert c._req({"cmd": "stats"})["ok"]
        with ServiceClient(h.sock) as c:     # anonymous unix peer:
            deny(c, {"cmd": "submit", "argv": ["x"]})   # no grant
        # cancel-own: ownership follows the resolved identity
        with ServiceClient(h.sock, client_token="alice-t") as ca, \
                ServiceClient(h.sock, client_token="bob-t") as cb, \
                ServiceClient(h.sock, client_token="boss") as cboss:
            j1 = ca.submit(["in.paf", "-o", out], cwd=str(tmp_path))
            deny(cb, {"cmd": "cancel", "job_id": j1["job_id"]})
            assert ca.cancel(j1["job_id"])["ok"]        # owner may
            j2 = ca.submit(["in.paf", "-o", out], cwd=str(tmp_path))
            assert cboss.cancel(j2["job_id"])["ok"]     # admin may
            # unknown ids pass the gate and answer unknown_job — the
            # auth layer is not a job-id oracle
            r = ca._req({"cmd": "cancel", "job_id": "job-9999"})
            assert r["error"] == "unknown_job"
            # admin can drain (and that DOES latch)
            assert cboss.drain()["ok"]


def test_auth_hot_reload_keep_last_good(tmp_path):
    tok = _mint(tmp_path, {"old-tok": ["submit", "read"]})
    with _daemon(runner=_stub_runner(), auth_tokens=tok) as h:
        out = str(tmp_path / "o.dfa")
        with ServiceClient(h.sock, client_token="old-tok") as c:
            assert c.submit(["in.paf", "-o", out],
                            cwd=str(tmp_path))["ok"]
        # rotate LIVE: old credential out, new one in
        time.sleep(0.02)                     # distinct mtime_ns
        authz.write_auth_tokens(tok, {"new-tok": ["submit", "read"]})
        deadline = time.monotonic() + 10
        admitted = False
        while time.monotonic() < deadline and not admitted:
            with ServiceClient(h.sock, client_token="new-tok") as c:
                admitted = c.submit(["in.paf", "-o", out],
                                    cwd=str(tmp_path)).get("ok", False)
            time.sleep(0.05)
        assert admitted, "rotated token never became valid"
        with ServiceClient(h.sock, client_token="old-tok") as c:
            r = c._req({"cmd": "submit", "argv": ["x"]})
            assert r["error"] == "unauthorized"
        # corrupt rotation: keep-last-good (new-tok still serves)
        time.sleep(0.02)
        open(tok, "w").write("{not json")
        time.sleep(0.5)                      # a few accept ticks
        with ServiceClient(h.sock, client_token="new-tok") as c:
            assert c.submit(["in.paf", "-o", out],
                            cwd=str(tmp_path))["ok"]
        assert "reload refused" in h.err.getvalue()


def test_auth_failures_metered_and_penalized(tmp_path):
    tok = _mint(tmp_path, {"boss": ["admin"]})
    with _daemon(runner=_stub_runner(), auth_tokens=tok) as h:
        with ServiceClient(h.sock, client_token="intruder") as c:
            t0 = time.monotonic()
            for _ in range(4):
                r = c._req({"cmd": "submit", "argv": ["x"]})
                assert r["error"] == "unauthorized"
            held = time.monotonic() - t0
        # capped-exponential penalty: 0.05+0.1+0.2+0.4 = 0.75s floor
        assert held >= 0.5, held
        with ServiceClient(h.sock, client_token="boss") as c:
            body = c.metrics()["metrics"]
        m = [l for l in body.splitlines()
             if l.startswith("pwasm_transport_auth_failures_total")
             and "intruder" in l]
        assert m and float(m[0].split()[-1]) >= 4
        # the default SLO rule set watches this counter
        from pwasm_tpu.obs.catalog import default_slo_rules
        assert any(r["name"] == "auth_failure_burst"
                   for r in default_slo_rules())


def test_daemon_rate_limit_truthful_retry(tmp_path):
    with _daemon(runner=_stub_runner(),
                 rate_limit=(2.0, 2.0)) as h:
        out = str(tmp_path / "o.dfa")
        with ServiceClient(h.sock, client_token="burst") as c:
            a = c.submit(["in.paf", "-o", out], cwd=str(tmp_path))
            b = c.submit(["in.paf", "-o", out], cwd=str(tmp_path))
            assert a["ok"] and b["ok"]
            r = c.submit(["in.paf", "-o", out], cwd=str(tmp_path))
            assert r["ok"] is False and r["error"] == "overloaded", r
            assert r["retry_after_s"] > 0
            # reads are NOT rate limited (only admission verbs)
            assert c.request({"cmd": "stats"})["ok"]
            # honoring the truthful hint admits
            time.sleep(r["retry_after_s"] + 0.05)
            assert c.submit(["in.paf", "-o", out],
                            cwd=str(tmp_path))["ok"]
        # identities are independent buckets
        with ServiceClient(h.sock, client_token="other") as c:
            assert c.submit(["in.paf", "-o", out],
                            cwd=str(tmp_path))["ok"]


def test_no_new_flags_byte_identical_behavior(tmp_path):
    """The whole zero-trust edge is strictly opt-in: without the
    flags, anonymous clients submit, cancel and drain exactly as
    before (the rest of the suite is the wider regression net)."""
    with _daemon(runner=_stub_runner()) as h:
        assert h.daemon.auth is None
        assert h.daemon.rate_limiter is None
        assert h.daemon.tls is None
        with ServiceClient(h.sock) as c:
            j = c.submit(["in.paf", "-o", str(tmp_path / "o.dfa")],
                         cwd=str(tmp_path))
            assert j["ok"]
            assert c.result(j["job_id"], timeout=30)["rc"] == 0
            assert c.request({"cmd": "stats",
                              "lease": {"epoch": 1,
                                        "ttl_s": 5.0}})["ok"]
            assert c.drain()["ok"]


# ---------------------------------------------------------------------------
# router edge
# ---------------------------------------------------------------------------
def test_router_edge_auth_rate_and_frame_ceiling(tmp_path):
    tok = _mint(tmp_path, {"writer": ["submit", "read"],
                           "boss": ["admin"]})
    with _daemon(runner=_stub_runner()) as m:
        rdir = tempfile.mkdtemp(prefix="pwsec")
        rsock = os.path.join(rdir, "router.sock")
        err = io.StringIO()
        r = Router([m.sock], socket_path=rsock,
                   listen="127.0.0.1:0", stderr=err,
                   poll_interval=0.1, auth_tokens=tok,
                   rate_limit=(1.0, 1.0), max_frame_bytes=4096)
        t = threading.Thread(target=r.serve, daemon=True)
        t.start()
        try:
            assert wait_for_socket(rsock, 15), err.getvalue()
            journal = router_journal_path(rsock, None, None)
            out = str(tmp_path / "o.dfa")
            with ServiceClient(rsock, client_token="writer") as c:
                j = c.submit(["in.paf", "-o", out],
                             cwd=str(tmp_path))
                assert j["ok"], j
                assert c.result(j["job_id"], timeout=30)["rc"] == 0
                # rate limit at the EDGE: refused frames reach no
                # member and write no journal
                before = _journal_bytes(journal)
                rr = c.submit(["in.paf", "-o", out],
                              cwd=str(tmp_path))
                assert rr["error"] == "overloaded", rr
                assert rr["retry_after_s"] > 0
                # unauthorized control verbs: zero ledger writes
                for req in ({"cmd": "drain"},
                            {"cmd": "fence"},
                            {"cmd": "lease-grant",
                             "lease": {"epoch": 9}}):
                    resp = c._req(req)
                    assert resp["error"] == "unauthorized", resp
                assert _journal_bytes(journal) == before
                assert c.ping()["draining"] is False
            # frame ceiling parity on BOTH router transports
            for target in (rsock, f"127.0.0.1:{r.tcp_port}"):
                conn = connect(target, timeout=5)
                try:
                    conn.sendall(b'{"pad":"' + b"A" * 8192 + b'"}\n')
                    line = conn.makefile("rb").readline(1 << 16)
                    resp = json.loads(line)
                    assert resp["error"] == "frame_too_large", \
                        (target, resp)
                finally:
                    conn.close()
            with ServiceClient(rsock, client_token="boss") as c:
                assert c.drain()["ok"]       # admin drains for real
        finally:
            if not r.drain.requested:
                r.drain.request("test done")
            t.join(20)
            shutil.rmtree(rdir, ignore_errors=True)


def test_router_member_token_reaches_auth_armed_member(tmp_path):
    """Members running --auth-tokens demand admin for the stats-borne
    lease grant: a router armed with --member-token polls, places and
    fetches as normal — the token rides every router→member frame."""
    tok = _mint(tmp_path, {"fleet-svc": ["admin"]})
    with _daemon(runner=_stub_runner(), auth_tokens=tok) as m:
        rdir = tempfile.mkdtemp(prefix="pwsec")
        rsock = os.path.join(rdir, "router.sock")
        err = io.StringIO()
        r = Router([m.sock], socket_path=rsock, stderr=err,
                   poll_interval=0.1, member_token="fleet-svc")
        t = threading.Thread(target=r.serve, daemon=True)
        t.start()
        try:
            assert wait_for_socket(rsock, 15), err.getvalue()
            out = str(tmp_path / "o.dfa")
            with ServiceClient(rsock) as c:
                j = c.submit(["in.paf", "-o", out],
                             cwd=str(tmp_path))
                assert j["ok"], j
                assert c.result(j["job_id"], timeout=30)["rc"] == 0
                st = c.stats()["stats"]
                assert len(st["fleet"]["members"]) == 1
        finally:
            if not r.drain.requested:
                r.drain.request("test done")
            t.join(20)
            shutil.rmtree(rdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# the all-mTLS fleet acceptance drill
# ---------------------------------------------------------------------------
def _free_port():
    s = socket_mod.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_mtls_fleet_standby_takeover_and_member_kill(tmp_path):
    """THE ISSUE 19 acceptance drill: three TCP members demanding
    client certificates, a primary router (subprocess, full CLI
    flags) dialing them with its own cert, a warm standby riding the
    SAME zero-trust config.  SIGKILL the primary → the standby
    promotes and keeps dialing members over mTLS; SIGKILL a member
    mid-job → the job resumes on a sibling with the report
    byte-identical to the uncrashed plaintext arm and the trace_id
    intact."""
    paf, fa = _corpus(tmp_path)
    from pwasm_tpu.cli import run as cli_run
    assert cli_run(_job_args(tmp_path, "colda", paf, fa, [SLOW]),
                   stderr=io.StringIO()) == 0
    assert cli_run(_job_args(tmp_path, "coldb", paf, fa),
                   stderr=io.StringIO()) == 0
    expect_a = (tmp_path / "colda.dfa").read_bytes()
    expect_b = (tmp_path / "coldb.dfa").read_bytes()

    d = tempfile.mkdtemp(prefix="pwmtls")
    jd = os.path.join(d, "journals")       # shared durable storage:
    os.makedirs(jd)                        # TCP members journal here
    procs = []
    try:
        ports, targets = [], []
        for i in range(3):
            port = _free_port()
            ports.append(port)
            targets.append(f"127.0.0.1:{port}")
            p = subprocess.Popen(
                [sys.executable, "-m", "pwasm_tpu.cli", "serve",
                 f"--socket={os.path.join(d, f'm{i}.sock')}",
                 f"--listen=127.0.0.1:{port}",
                 f"--tls-cert={SRV_CERT}", f"--tls-key={SRV_KEY}",
                 f"--tls-client-ca={CA}", f"--journal-dir={jd}"],
                env=_serve_env(), stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE, text=True)
            procs.append(p)
        for i in range(3):
            assert wait_for_socket(os.path.join(d, f"m{i}.sock"), 60)
        # members verify client certs: a bare TCP client can't speak
        with pytest.raises((ServiceError, OSError)):
            with ServiceClient(targets[0], timeout=5,
                               tls=_client_tls()) as c:
                c.ping()
        # PRIMARY router: the full zero-trust CLI surface
        rsock = os.path.join(d, "router.sock")
        rp = subprocess.Popen(
            [sys.executable, "-m", "pwasm_tpu.cli", "route",
             f"--backends={','.join(targets)}",
             f"--socket={rsock}", f"--journal-dir={jd}",
             "--poll-interval=0.1",
             f"--member-tls-ca={CA}",
             f"--member-tls-cert={ADMIN_CERT}",
             f"--member-tls-key={ADMIN_KEY}"],
            env=_serve_env(), stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE, text=True)
        procs.append(rp)
        assert wait_for_socket(rsock, 60)
        with ServiceClient(rsock) as c:      # members reachable via
            stats = c.stats()["stats"]       # mTLS dialing
            assert len(stats["fleet"]["members"]) == 3, stats
        # warm STANDBY rides the SAME zero-trust flag surface —
        # member_tls must survive the promotion or takeover strands
        # every TLS member
        sb = subprocess.Popen(
            [sys.executable, "-m", "pwasm_tpu.cli", "route",
             f"--standby-of={rsock}", f"--journal-dir={jd}",
             "--poll-interval=0.2",
             f"--member-tls-ca={CA}",
             f"--member-tls-cert={ADMIN_CERT}",
             f"--member-tls-key={ADMIN_KEY}"],
            env=_serve_env(), stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE, text=True)
        procs.append(sb)
        time.sleep(1.5)                      # let it see the primary
        rp.kill()                            # murder the PRIMARY
        rp.wait(timeout=30)
        deadline = time.monotonic() + 60     # standby binds + serves
        promoted = False
        while time.monotonic() < deadline and not promoted:
            try:
                with ServiceClient(rsock, timeout=2.0) as c:
                    promoted = c.request({"cmd": "ping"}).get("ok",
                                                              False)
            except (ServiceError, OSError):
                time.sleep(0.1)
        assert promoted, "standby never took over the socket"
        assert sb.poll() is None
        # the PROMOTED router dials members over the inherited mTLS
        with ServiceClient(rsock, trace_id="mtls-drill") as c:
            ja = c.submit(_job_args(tmp_path, "a", paf, fa, [SLOW]),
                          cwd=str(tmp_path))
            jb = c.submit(_job_args(tmp_path, "b", paf, fa),
                          cwd=str(tmp_path))
            assert ja["ok"] and jb["ok"], (ja, jb)
            ck = str(tmp_path / "a.dfa.ckpt")
            deadline = time.monotonic() + 60
            mid = False
            while time.monotonic() < deadline:
                s = c.status(ja["job_id"])["job"]["state"]
                if s == "running" and os.path.exists(ck):
                    mid = True
                    break
                assert s in ("queued", "running"), s
                time.sleep(0.02)
            assert mid, "job never reached mid-run with a ckpt"
            victim = ja["member"]
            vi = next(i for i, t in enumerate(targets)
                      if target_name(t) == victim)
            procs[vi].kill()                 # SIGKILL mid-job
            procs[vi].wait(timeout=30)
            ra = c.result(ja["job_id"], timeout=300)
            rb = c.result(jb["job_id"], timeout=300)
            assert ra.get("rc") == 0, ra
            assert rb.get("rc") == 0, rb
            assert ra["job"]["trace_id"] == "mtls-drill"
            assert ra["job"]["member"] != victim
            assert ra["job"]["failovers"] == 1
            st = c.stats()["stats"]
            assert st["ha"]["takeover"] is True
            c.drain()
        assert sb.wait(timeout=120) == 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
            p.stderr.close()
        shutil.rmtree(d, ignore_errors=True)
    # byte parity with the uncrashed plaintext arm, both jobs
    assert (tmp_path / "a.dfa").read_bytes() == expect_a
    assert (tmp_path / "b.dfa").read_bytes() == expect_b


# ---------------------------------------------------------------------------
# CLI flag surfaces
# ---------------------------------------------------------------------------
def test_serve_and_route_flag_validation(tmp_path):
    from pwasm_tpu.fleet.router import route_main
    from pwasm_tpu.service.daemon import serve_main

    def run_serve(extra):
        err = io.StringIO()
        rc = serve_main([f"--socket={tmp_path / 's.sock'}"] + extra,
                        stderr=err)
        return rc, err.getvalue()

    rc, out = run_serve(["--tls-cert=/x"])
    assert rc != 0 and "must be given together" in out
    rc, out = run_serve(["--tls-client-ca=/x"])
    assert rc != 0 and "requires --tls-cert" in out
    rc, out = run_serve(["--rate-limit=banana"])
    assert rc != 0 and "rate-limit" in out
    rc, out = run_serve([f"--auth-tokens={tmp_path / 'nope.json'}"])
    assert rc != 0                      # fail-fast: unreadable policy

    base = [f"--backends={tmp_path / 'm.sock'}",
            f"--socket={tmp_path / 'r.sock'}"]

    def run_route(extra):
        err = io.StringIO()
        rc = route_main(base + extra, stderr=err)
        return rc, err.getvalue()

    rc, out = run_route(["--tls-key=/x"])
    assert rc != 0 and "must be given together" in out
    rc, out = run_route(["--member-tls-cert=/x",
                         "--member-tls-key=/y"])
    assert rc != 0 and "need --member-tls-ca" in out
    rc, out = run_route(["--max-frame-bytes=zero"])
    assert rc != 0 and "max-frame-bytes" in out
    rc, out = run_route(["--rate-limit=0"])
    assert rc != 0 and "rate-limit" in out
