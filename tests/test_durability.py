"""Preemption-grade durability (ISSUE 4).

Acceptance contracts:

- **self-validating checkpoints**: the ``<report>.ckpt`` is versioned,
  CRC'd, and boundary-checked against the actual report; the
  corrupted-ckpt matrix (truncated JSON, wrong CRC, bytes past the
  report, offset mid-record, unversioned legacy) must each quarantine
  to ``<report>.ckpt.bad`` and restart cleanly — never resume wrong;
- **kill-at-every-batch-boundary sweep**: wherever an ``InjectedKill``
  lands, the resumed report is byte-identical to an uninterrupted run;
- **graceful drain**: a scripted preemption (``preempt=N``, the
  deterministic twin of SIGTERM) exits with the documented
  "preempted, resumable" code (75) after flushing a final valid
  checkpoint + partial ``--stats``; ``--resume`` completes
  byte-identically; a second signal hard-aborts;
- **OOM-aware bisection**: an injected device memory ceiling
  (``oom=N``) finishes ON-DEVICE via recursive batch bisection —
  ``batch_splits > 0``, ``breaker_trips == 0``, no host degradation,
  byte parity with the fault-free arm (incl. a 200-alignment
  realistic corpus);
- **static gate**: every rename-publish in the tree routes through
  the audited fsync-then-replace (``qa/check_durability.py``).
"""

import io
import json
import os
import signal
import sys

import numpy as np
import pytest

from pwasm_tpu.cli import (CKPT_VERSION, _ckpt_crc, _load_checkpoint,
                           run)
from pwasm_tpu.core.errors import EXIT_PREEMPTED
from pwasm_tpu.core.fasta import write_fasta
from pwasm_tpu.resilience import (BatchSupervisor, BisectableBatch,
                                  InjectedKill, InjectedOOM,
                                  PreemptedError, ResiliencePolicy,
                                  SignalDrain, is_oom_error,
                                  parse_fault_spec)
from pwasm_tpu.utils.runstats import RunStats

from helpers import make_paf_line

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# fault-spec legs + OOM classifier
# ---------------------------------------------------------------------------
def test_fault_spec_preempt_and_oom_legs():
    plan = parse_fault_spec("preempt=4,oom=128")
    assert plan.preempt == 4
    assert plan.oom == 128
    assert plan.oom_for(129)
    assert not plan.oom_for(128)
    assert not plan.oom_for(None)
    for bad in ("preempt=-1", "oom=-2", "preempt=x", "oom="):
        with pytest.raises(ValueError):
            parse_fault_spec(bad)


def test_preempt_leg_pulls_the_drain_hook_once():
    plan = parse_fault_spec("preempt=3")
    pulled = []
    plan.on_preempt = pulled.append
    for _ in range(5):
        plan.note_call()
    assert len(pulled) == 1
    assert "supervised call 3" in pulled[0]


def test_is_oom_classifier():
    assert is_oom_error(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "123456 bytes"))
    assert is_oom_error(RuntimeError("Failed to allocate 8.0G hbm"))
    assert is_oom_error(InjectedOOM(
        "injected RESOURCE_EXHAUSTED at ctx_scan"))
    assert not is_oom_error(RuntimeError("INTERNAL: something else"))
    assert not is_oom_error(None)


# ---------------------------------------------------------------------------
# supervisor bisection (unit)
# ---------------------------------------------------------------------------
def _bisect_supervisor(**policy):
    st = RunStats()
    sup = BatchSupervisor(
        ResiliencePolicy(max_retries=1, backoff_s=0.0,
                         **policy), stats=st, stderr=io.StringIO(),
        probe=lambda: (True, ""))
    return sup, st


def test_supervisor_bisects_oom_to_floor_and_demotes():
    sup, st = _bisect_supervisor()
    items = list(range(10))

    def attempt_for(sub):
        if len(sub) > 2:
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: out of memory allocating batch")
        return list(sub)

    spec = BisectableBatch(
        items=items, attempt_for=attempt_for,
        combine=lambda parts: [x for _s, r in parts for x in r])
    out = sup.run("ctx_scan", lambda: attempt_for(items), bisect=spec)
    assert out == items               # order preserved through splits
    assert st.res_oom_events > 0
    assert st.res_batch_splits > 0
    assert st.res_bucket_demotions > 0
    assert sup.bucket_ceiling == 2    # demoted to the working size
    assert st.res_breaker_trips == 0  # OOM NEVER charges the breaker
    assert st.res_retries == 0        # and never retries the shape
    assert not sup.breaker_open


def test_supervisor_oom_without_bisect_degrades_without_trip():
    sup, st = _bisect_supervisor(breaker_threshold=2)
    calls = []

    def attempt():
        calls.append(1)
        raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

    for _ in range(6):   # far past the breaker threshold
        assert sup.run("consensus", attempt,
                       fallback=lambda: "host") == "host"
    assert st.res_oom_events == 6
    assert st.res_breaker_trips == 0
    assert not sup.breaker_open
    assert len(calls) == 6            # one attempt each: no same-shape
    #                                   retries for an allocation error


def test_supervisor_oom_floor_exhaustion_degrades_whole_batch():
    sup, st = _bisect_supervisor()

    def attempt_for(sub):   # even single items OOM
        raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

    spec = BisectableBatch(
        items=[1, 2, 3, 4], attempt_for=attempt_for,
        combine=lambda parts: [x for _s, r in parts for x in r])
    out = sup.run("ctx_scan", lambda: attempt_for(spec.items),
                  bisect=spec, fallback=lambda: "whole-batch-host")
    assert out == "whole-batch-host"  # the CALLER's fallback ran once,
    #                                   for the whole batch — halves
    #                                   never degrade alone
    assert st.res_breaker_trips == 0


def test_injected_oom_leg_fires_by_declared_size():
    st = RunStats()
    sup = BatchSupervisor(
        ResiliencePolicy(max_retries=0, backoff_s=0.0), stats=st,
        stderr=io.StringIO(), probe=lambda: (True, ""),
        faults=parse_fault_spec("oom=4"))

    def attempt_for(sub):
        return list(sub)

    spec = BisectableBatch(
        items=list(range(6)), attempt_for=attempt_for,
        combine=lambda parts: [x for _s, r in parts for x in r])
    out = sup.run("ctx_scan", lambda: attempt_for(spec.items),
                  bisect=spec)
    assert out == list(range(6))      # 6 OOMs, 3+3 succeeds
    assert st.res_injected_faults > 0
    assert st.res_oom_events == 1
    assert st.res_batch_splits == 1


def test_bucket_ceiling_repromotes_after_clean_flushes():
    """ISSUE 5 satellite (ROADMAP open item from PR 4): after N
    consecutive clean SIZED flushes at a demoted ceiling, the ceiling
    probation-raises one pow2 step — a long run (or a long-lived serve
    process) that OOMed once must not stay chunked forever."""
    sup, st = _bisect_supervisor(repromote_after=3)
    sup.bucket_ceiling = 2
    for _ in range(2):
        sup.run("ctx_scan", lambda: "ok", size=2)
    assert sup.bucket_ceiling == 2
    assert st.res_bucket_repromotions == 0
    sup.run("ctx_scan", lambda: "ok", size=2)     # the 3rd clean flush
    assert sup.bucket_ceiling == 4
    assert st.res_bucket_repromotions == 1
    # unsized successes (consensus-style launches) never count
    sup.run("consensus", lambda: "ok")
    assert sup._ceiling_clean == 0
    # nor do flushes far below the ceiling: a 1-item success under a
    # 4-item ceiling proves nothing about memory at the ceiling
    sup.run("ctx_scan", lambda: "ok", size=1)
    assert sup._ceiling_clean == 0
    # probation repeats: another 3 clean flushes raise one more step
    for _ in range(3):
        sup.run("ctx_scan", lambda: "ok", size=4)
    assert sup.bucket_ceiling == 8
    assert st.res_bucket_repromotions == 2


def test_oom_resets_repromotion_probation_and_redemotes():
    sup, st = _bisect_supervisor(repromote_after=2)
    sup.bucket_ceiling = 4
    sup.run("ctx_scan", lambda: "ok", size=4)     # 1 clean flush

    def oom():
        raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

    assert sup.run("ctx_scan", oom, fallback=lambda: "host") == "host"
    assert sup._ceiling_clean == 0                # probation restarted
    sup.run("ctx_scan", lambda: "ok", size=4)
    assert sup.bucket_ceiling == 4                # 1 clean ≠ 2 yet
    sup.run("ctx_scan", lambda: "ok", size=4)
    assert sup.bucket_ceiling == 8                # probation met anew
    # a raised ceiling can still be demoted back by a fresh OOM
    items = list(range(8))
    spec = BisectableBatch(
        items=items,
        attempt_for=lambda sub: (_ for _ in ()).throw(RuntimeError(
            "RESOURCE_EXHAUSTED: oom")) if len(sub) > 2 else list(sub),
        combine=lambda parts: [x for _s, r in parts for x in r])
    assert sup.run("ctx_scan", lambda: spec.attempt_for(items),
                   bisect=spec) == items
    assert sup.bucket_ceiling == 2


def test_bisection_halves_do_not_count_toward_probation():
    """The halves that succeed right after an OOM are not 'clean
    flushes at the ceiling' — counting them would re-raise the ceiling
    while the allocator is still the problem."""
    sup, st = _bisect_supervisor(repromote_after=2)
    items = list(range(8))

    def attempt_for(sub):
        if len(sub) > 2:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")
        return list(sub)

    spec = BisectableBatch(
        items=items, attempt_for=attempt_for,
        combine=lambda parts: [x for _s, r in parts for x in r])
    out = sup.run("ctx_scan", lambda: attempt_for(items), bisect=spec)
    assert out == items
    # 4 successful 2-item halves ran, yet the probation is untouched
    assert sup._ceiling_clean == 0
    assert st.res_bucket_repromotions == 0
    assert sup.bucket_ceiling == 2


def test_repromotion_restores_at_origin_instead_of_doubling_forever():
    """The up-transition terminates: climbing back to the pow2 bucket
    that originally OOMed RESTORES the ceiling to None (undemoted) —
    it never doubles past what actually failed, and a long-lived
    process stops paying the probation warn/counter churn."""
    sup, st = _bisect_supervisor(repromote_after=2)
    items = list(range(8))

    def attempt_for(sub):
        if len(sub) > 4:
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")
        return list(sub)

    spec = BisectableBatch(
        items=items, attempt_for=attempt_for,
        combine=lambda parts: [x for _s, r in parts for x in r])
    assert sup.run("ctx_scan", lambda: attempt_for(items),
                   bisect=spec) == items
    assert sup.bucket_ceiling == 4
    assert sup._ceiling_origin == 8       # the bucket that failed
    # two ceiling-filling clean flushes meet the probation; the next
    # step would reach the origin bucket, so the ceiling is RESTORED
    for _ in range(2):
        sup.run("ctx_scan", lambda: "ok", size=4)
    assert sup.bucket_ceiling is None
    assert st.res_bucket_repromotions == 1
    # the restore point rides the ckpt so a --resume (or the next warm
    # job) keeps it
    exported = sup.export_state()
    assert exported["bucket_demoted_from"] == 8
    sup2, _ = _bisect_supervisor(repromote_after=2)
    sup2.restore_state(exported)
    assert sup2._ceiling_origin == 8
    # fully restored: clean flushes no longer touch counters or warns
    for _ in range(10):
        sup.run("ctx_scan", lambda: "ok", size=4)
    assert st.res_bucket_repromotions == 1
    assert sup.bucket_ceiling is None


def test_repromotion_disabled_at_zero():
    sup, st = _bisect_supervisor(repromote_after=0)
    sup.bucket_ceiling = 2
    for _ in range(20):
        sup.run("ctx_scan", lambda: "ok", size=2)
    assert sup.bucket_ceiling == 2
    assert st.res_bucket_repromotions == 0


def test_repromotion_probation_rides_the_checkpoint_state():
    sup, _ = _bisect_supervisor(repromote_after=5)
    sup.bucket_ceiling = 2
    sup._ceiling_clean = 3
    st = sup.export_state()
    assert st["bucket_clean_flushes"] == 3
    sup2, _ = _bisect_supervisor(repromote_after=5)
    sup2.restore_state(st)
    assert sup2._ceiling_clean == 3
    # garbage drops only itself
    sup2.restore_state({"bucket_clean_flushes": "x"})
    assert sup2._ceiling_clean == 3


def test_oom_cli_run_repromotes_and_stays_byte_identical(tmp_path,
                                                         monkeypatch):
    """End to end: an oom=2 run demotes the ceiling, then the stream
    of clean pre-chunked flushes probation-raises it (the raise
    re-OOMs once, re-demotes, and the oscillation never changes
    bytes)."""
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path)
    rc, _ = _cli(tmp_path, "ref", [], paf, fa)
    assert rc == 0
    rc, err = _cli(tmp_path, "repro", ["--inject-faults=oom=2"],
                   paf, fa)
    assert rc == 0, err
    assert _outs(tmp_path, "repro") == _outs(tmp_path, "ref")
    st = json.loads((tmp_path / "repro.json").read_text())
    res = st["resilience"]
    assert res["bucket_repromotions"] >= 1, res
    assert res["bucket_demotions"] >= 2, res   # demoted, raised, re-
    #                                            demoted by the probe
    assert res["breaker_trips"] == 0, res
    assert st["fallback_batches"] == 0, st


def test_bucket_ceiling_rides_the_checkpoint_state():
    sup, _ = _bisect_supervisor()
    sup.bucket_ceiling = 128
    st = sup.export_state()
    assert st["bucket_ceiling"] == 128
    sup2, _ = _bisect_supervisor()
    sup2.restore_state(st)
    assert sup2.bucket_ceiling == 128
    # absent/None restores to None, and garbage drops only itself
    sup3, _ = _bisect_supervisor()
    sup3.restore_state({"bucket_ceiling": None})
    assert sup3.bucket_ceiling is None
    sup3.restore_state({"bucket_ceiling": "x"})
    assert sup3.bucket_ceiling is None


# ---------------------------------------------------------------------------
# graceful drain: the SignalDrain manager
# ---------------------------------------------------------------------------
def test_signal_drain_first_flags_second_hard_aborts():
    err = io.StringIO()
    exits = []
    with SignalDrain(stderr=err, hard_exit=exits.append) as drain:
        assert not drain.requested
        os.kill(os.getpid(), signal.SIGTERM)
        assert drain.requested
        assert "SIGTERM" in drain.reason
        assert not exits
        os.kill(os.getpid(), signal.SIGTERM)
        assert exits == [128 + signal.SIGTERM]
    # handlers restored on exit
    assert signal.getsignal(signal.SIGTERM) != drain._on_signal
    assert "draining" in err.getvalue()
    assert "hard abort" in err.getvalue()


def test_signal_drain_request_is_idempotent():
    err = io.StringIO()
    drain = SignalDrain(stderr=err, hard_exit=lambda c: None)
    drain.request("first")
    drain.request("second")
    assert drain.reason == "first"


def test_interrupting_phase_aborts_on_request():
    """Inside the interruptible phase (the end-of-run MSA tail) a
    drain request raises immediately instead of waiting for a batch
    boundary the phase will never reach; a request already pending
    raises on phase entry; outside the phase, requests only flag."""
    drain = SignalDrain(stderr=io.StringIO(), hard_exit=lambda c: None)
    with pytest.raises(PreemptedError):
        with drain.interrupting():
            drain.request("mid-tail")
    assert not drain._interrupt       # phase disarmed by the unwind
    drain.request("after")            # outside: flag only, no raise
    with pytest.raises(PreemptedError):
        with drain.interrupting():    # pending request raises on entry
            raise AssertionError("phase body must not run")


# ---------------------------------------------------------------------------
# CLI end-to-end fixtures (mirrors tests/test_resilience.py)
# ---------------------------------------------------------------------------
def _corpus(tmp_path, n=24, qlen=120):
    rng = np.random.default_rng(3)
    q = "".join("ACGT"[i] for i in rng.integers(0, 4, qlen))
    lines = []
    for i in range(n):
        cut = 10 + int(rng.integers(0, qlen - 40))
        qb = q[cut]
        tb = "ACGT"[("ACGT".index(qb) + 1) % 4]
        ops = [("=", cut), ("*", tb, qb), ("=", 20), ("ins", "gg"),
               ("=", qlen - cut - 21)]
        lines.append(make_paf_line("q", q, f"asm{i}", "+", ops)[0])
    fa = tmp_path / "q.fa"
    write_fasta(str(fa), [("q", q.encode())])
    paf = tmp_path / "in.paf"
    paf.write_text("".join(ln + "\n" for ln in lines))
    return str(paf), str(fa)


def _cli(tmp_path, tag, extra, paf, fa):
    err = io.StringIO()
    rc = run([paf, "-r", fa, "-o", str(tmp_path / f"{tag}.dfa"),
              "-w", str(tmp_path / f"{tag}.mfa"), "--device=tpu",
              "--batch=2", f"--stats={tmp_path / f'{tag}.json'}"]
             + extra, stderr=err)
    return rc, err.getvalue()


def _outs(tmp_path, tag):
    return ((tmp_path / f"{tag}.dfa").read_bytes(),
            (tmp_path / f"{tag}.mfa").read_bytes())


# ---------------------------------------------------------------------------
# checkpoint format v2
# ---------------------------------------------------------------------------
def test_ckpt_v2_versioned_crc_on_record_boundary(tmp_path,
                                                  monkeypatch):
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path)
    with pytest.raises(InjectedKill):
        _cli(tmp_path, "k", ["--inject-faults=kill=8"], paf, fa)
    ck = json.loads((tmp_path / "k.dfa.ckpt").read_text())
    assert ck["version"] == CKPT_VERSION == 2
    assert ck["crc"] == _ckpt_crc(ck)
    assert ck["records"] > 0
    # the recorded offset is a record boundary of the actual report
    body = (tmp_path / "k.dfa").read_bytes()
    assert ck["bytes"] <= len(body)
    assert ck["bytes"] == 0 or body[ck["bytes"] - 1:ck["bytes"]] == b"\n"
    # and the verifying loader accepts it whole
    got = _load_checkpoint(str(tmp_path / "k.dfa"))
    assert isinstance(got, tuple)
    assert got[0] == ck["bytes"] and got[1] == ck["records"]


def _corrupt_ckpt(path: str, report: str, how: str) -> None:
    """Apply one corruption from the matrix to a VALID ckpt at
    ``path``."""
    text = open(path).read()
    ck = json.loads(text)
    if how == "truncated":
        open(path, "w").write(text[:max(1, len(text) // 2)])
        return
    if how == "badcrc":
        ck["records"] += 1          # payload changed, stale crc
    elif how == "bytes_past_eof":
        ck["bytes"] = os.path.getsize(report) + 999
        ck["crc"] = _ckpt_crc(ck)   # crc VALID: only the boundary
        #                             check can reject it
    elif how == "mid_record":
        ck["bytes"] -= 3            # lands inside a record's rows
        ck["crc"] = _ckpt_crc(ck)
    elif how == "legacy_v1":
        ck = {"bytes": ck["bytes"], "records": ck["records"]}
    else:
        raise AssertionError(how)
    open(path, "w").write(json.dumps(ck))


@pytest.mark.parametrize("how", ["truncated", "badcrc",
                                 "bytes_past_eof", "mid_record",
                                 "legacy_v1"])
def test_corrupted_ckpt_quarantines_and_restarts(tmp_path, monkeypatch,
                                                 how):
    """The matrix: every corrupt/torn/mismatched ckpt must be
    quarantined to <report>.ckpt.bad and the run RESTARTED cleanly —
    resumed output byte-identical to an uninterrupted run, never a
    half-resume onto garbage."""
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path)
    rc, _ = _cli(tmp_path, "ref", [], paf, fa)
    assert rc == 0
    with pytest.raises(InjectedKill):
        _cli(tmp_path, how, ["--inject-faults=kill=8"], paf, fa)
    report = str(tmp_path / f"{how}.dfa")
    ckpt = report + ".ckpt"
    _corrupt_ckpt(ckpt, report, how)
    # the verifying loader must already reject it with a diagnostic
    assert isinstance(_load_checkpoint(report), str)
    rc, err = _cli(tmp_path, how, ["--resume"], paf, fa)
    assert rc == 0, err
    assert "quarantined" in err
    assert os.path.exists(ckpt + ".bad")
    assert not os.path.exists(ckpt)   # completed run retires its ckpt
    assert _outs(tmp_path, how) == _outs(tmp_path, "ref")
    headers = [ln for ln in open(report) if ln.startswith(">")]
    assert len(headers) == len(set(headers)) == 24


def test_kill_at_every_batch_boundary_resume_parity(tmp_path,
                                                    monkeypatch):
    """The sweep: wherever the kill lands on the supervised-attempt
    clock, the checkpointed prefix + --resume reproduce the
    uninterrupted run byte-for-byte (no lost, duplicated, or reordered
    records)."""
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path, n=12)
    rc, _ = _cli(tmp_path, "ref", [], paf, fa)
    assert rc == 0
    killed = 0
    for k in range(1, 9):
        tag = f"k{k}"
        try:
            rc, err = _cli(tmp_path, tag,
                           [f"--inject-faults=kill={k}"], paf, fa)
            assert rc == 0, err   # kill clock ran past the run's
            #                       supervised attempts: a clean finish
        except InjectedKill:
            killed += 1
            rc, err = _cli(tmp_path, tag, ["--resume"], paf, fa)
            assert rc == 0, err
        assert _outs(tmp_path, tag) == _outs(tmp_path, "ref"), k
        headers = [ln for ln in open(tmp_path / f"{tag}.dfa")
                   if ln.startswith(">")]
        assert len(headers) == len(set(headers)) == 12, k
    assert killed >= 4   # the sweep must actually cover mid-run kills


# ---------------------------------------------------------------------------
# graceful drain: CLI end-to-end (scripted preemption)
# ---------------------------------------------------------------------------
def test_preempt_drains_checkpoints_and_resumes_byte_identical(
        tmp_path, monkeypatch):
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path)
    rc, _ = _cli(tmp_path, "ref", [], paf, fa)
    assert rc == 0
    rc, err = _cli(tmp_path, "pre", ["--inject-faults=preempt=2"],
                   paf, fa)
    assert rc == EXIT_PREEMPTED == 75
    assert "draining" in err and "preempted" in err
    # the final checkpoint is whole and CRC-valid
    got = _load_checkpoint(str(tmp_path / "pre.dfa"))
    assert isinstance(got, tuple) and got[1] > 0
    # partial --stats landed, flagged as such
    st = json.loads((tmp_path / "pre.json").read_text())
    assert st["preempted"] is True
    assert 0 < st["alignments"] < 24
    rc, err = _cli(tmp_path, "pre", ["--resume"], paf, fa)
    assert rc == 0, err
    assert _outs(tmp_path, "pre") == _outs(tmp_path, "ref")
    st = json.loads((tmp_path / "pre.json").read_text())
    assert st["preempted"] is False
    assert not os.path.exists(tmp_path / "pre.dfa.ckpt")


def test_preempt_during_output_tail_aborts_resumable(tmp_path,
                                                     monkeypatch):
    """A drain landing AFTER the last report batch — during the
    end-of-run MSA/consensus tail — must still exit 75 (the tail runs
    in the drain's interruptible phase), with the full report already
    durable; --resume rebuilds the tail outputs whole."""
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path, n=8)

    def cli_cons(tag, extra):
        err = io.StringIO()
        rc = run([paf, "-r", fa, "-o", str(tmp_path / f"{tag}.dfa"),
                  "-w", str(tmp_path / f"{tag}.mfa"),
                  f"--cons={tmp_path / f'{tag}.cons'}", "--device=tpu",
                  "--batch=2", f"--stats={tmp_path / f'{tag}.json'}"]
                 + extra, stderr=err)
        return rc, err.getvalue()

    def outs3(tag):
        return tuple((tmp_path / f"{tag}.{k}").read_bytes()
                     for k in ("dfa", "mfa", "cons"))

    rc, _ = cli_cons("ref", [])
    assert rc == 0
    # supervised-call clock: every ctx_scan flush of the clean run,
    # then the consensus call inside the tail — aim preempt just past
    # the report flushes so it fires mid-tail (on the consensus call)
    ref_st = json.loads((tmp_path / "ref.json").read_text())
    n_report_calls = ref_st["device"]["by_site"]["ctx_scan"]
    assert ref_st["device"]["by_site"]["consensus"] >= 1
    rc, err = cli_cons(
        "tail", [f"--inject-faults=preempt={n_report_calls + 1}"])
    assert rc == EXIT_PREEMPTED, err
    # the report itself is COMPLETE (all batches checkpointed before
    # the tail began) — only the MSA/consensus outputs were aborted
    got = _load_checkpoint(str(tmp_path / "tail.dfa"))
    assert isinstance(got, tuple) and got[1] == 8
    rc, err = cli_cons("tail", ["--resume"])
    assert rc == 0, err
    assert outs3("tail") == outs3("ref")


def test_preempt_without_report_still_exits_resumable(tmp_path,
                                                      monkeypatch):
    """No -o report (stdout mode): nothing to checkpoint, but the drain
    contract (exit 75, no crash) holds."""
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path, n=8)
    err = io.StringIO()
    out = io.StringIO()
    rc = run([paf, "-r", fa, "--device=tpu", "--batch=2",
              "--inject-faults=preempt=1"], stdout=out, stderr=err)
    assert rc == EXIT_PREEMPTED
    assert "nothing checkpointed" in err.getvalue()


# ---------------------------------------------------------------------------
# OOM bisection: CLI end-to-end
# ---------------------------------------------------------------------------
def test_oom_injected_run_bisects_on_device_byte_identical(
        tmp_path, monkeypatch):
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path)
    rc, _ = _cli(tmp_path, "ref", [], paf, fa)
    assert rc == 0
    rc, err = _cli(tmp_path, "oom", ["--inject-faults=oom=2"],
                   paf, fa)
    assert rc == 0, err
    assert _outs(tmp_path, "oom") == _outs(tmp_path, "ref")
    st = json.loads((tmp_path / "oom.json").read_text())
    res = st["resilience"]
    assert res["oom_events"] > 0
    assert res["batch_splits"] > 0
    assert res["breaker_trips"] == 0
    assert st["fallback_batches"] == 0   # finished ON-DEVICE


@pytest.mark.parametrize("n_aln", [200])
def test_oom_bisection_realistic_scale_byte_parity(tmp_path,
                                                   monkeypatch, n_aln):
    """The ISSUE 4 OOM acceptance gate at realistic scale: a simulated
    device memory ceiling (oom=192 items — every realistic flush is
    bigger) on the 200-alignment Nanopore-like corpus must finish
    ON-DEVICE via bisection + bucket demotion, byte-identical to the
    fault-free arm, with the breaker untouched."""
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    from test_realistic_scale import make_corpus
    qseq, lines = make_corpus(n_aln=n_aln)
    fa = tmp_path / "cds.fa"
    fa.write_text(f">cds1\n{qseq}\n")
    paf = tmp_path / "in.paf"
    paf.write_text("".join(l + "\n" for l in lines))
    outs = {}
    stats = {}
    for tag, extra in (("clean", []),
                       ("oom", ["--inject-faults=oom=192"])):
        rep = tmp_path / f"{tag}.dfa"
        summ = tmp_path / f"{tag}.sum"
        mfa = tmp_path / f"{tag}.mfa"
        cons = tmp_path / f"{tag}.cons"
        stj = tmp_path / f"{tag}.stats"
        err = io.StringIO()
        rc = run([str(paf), "-r", str(fa), "-o", str(rep), "-s",
                  str(summ), "-w", str(mfa), f"--cons={cons}",
                  "--device=tpu", "--batch=16", f"--stats={stj}"]
                 + extra, stderr=err)
        assert rc == 0, err.getvalue()[:2000]
        outs[tag] = (rep.read_bytes(), summ.read_bytes(),
                     mfa.read_bytes(), cons.read_bytes())
        stats[tag] = json.loads(stj.read_text())
    assert outs["clean"] == outs["oom"]
    st = stats["oom"]
    res = st["resilience"]
    assert res["oom_events"] > 0, res
    assert res["batch_splits"] > 0, res
    assert res["bucket_demotions"] > 0, res
    assert res["breaker_trips"] == 0, res
    assert st["fallback_batches"] == 0, st
    clean = stats["clean"]["resilience"]
    assert clean["oom_events"] == clean["batch_splits"] == 0


# ---------------------------------------------------------------------------
# static gate: every rename-publish uses the audited pattern
# ---------------------------------------------------------------------------
def _check_durability_mod():
    qa = os.path.join(REPO, "qa")
    if qa not in sys.path:
        sys.path.insert(0, qa)
    import check_durability
    return check_durability


def test_every_state_writer_uses_fsync_then_replace():
    cd = _check_durability_mod()
    assert cd.find_unregistered() == []
    assert cd.stale_registry_entries() == []
    assert cd.impl_self_check() == []


def test_durability_gate_catches_a_naked_replace(tmp_path):
    """The gate actually bites: a module with a bare os.replace outside
    the registry is reported."""
    cd = _check_durability_mod()
    pkg = tmp_path / "pwasm_tpu"
    pkg.mkdir()
    (pkg / "rogue.py").write_text(
        "import os\n\ndef save(tmp, dest):\n"
        "    os." + "replace(tmp, dest)\n")  # split so the gate's
    # scan of THIS test file does not match the fixture string
    (tmp_path / "qa").mkdir()
    (tmp_path / "tests").mkdir()
    bad = cd.find_unregistered(str(tmp_path))
    assert len(bad) == 1
    assert "rogue.py" in bad[0]


def test_durability_gate_catches_a_naked_fsync(tmp_path):
    """The ISSUE 9 extension bites too: a hand-rolled append journal
    with its own os.fsync outside fsio.py (and the registered in-place
    exemptions) is reported — journal/spool writers must route through
    fsio.DurableAppender / write_durable_*."""
    cd = _check_durability_mod()
    pkg = tmp_path / "pwasm_tpu"
    pkg.mkdir()
    (pkg / "rogue_journal.py").write_text(
        "import os\n\ndef append(f, rec):\n"
        "    f.write(rec)\n    f.flush()\n"
        "    os." + "fsync(f.fileno())\n")  # split so the gate's
    # scan of THIS test file does not match the fixture string
    (tmp_path / "qa").mkdir()
    (tmp_path / "tests").mkdir()
    bad = cd.find_unregistered(str(tmp_path))
    assert len(bad) == 1
    assert "rogue_journal.py" in bad[0]
    assert "DurableAppender" in bad[0]


def test_durable_appender_fsync_per_record_and_torn_tail(tmp_path):
    """The appender the journal rides: every append is durable on
    return, the file is append-only (records accumulate), and a
    partial final line (what a kill -9 mid-append leaves) is exactly
    what the journal replay's torn-tail rule expects to see."""
    from pwasm_tpu.utils.fsio import DurableAppender
    p = str(tmp_path / "j.ndjson")
    with DurableAppender(p) as ap:
        ap.append(b'{"rec":"a"}\n')
        ap.append(b'{"rec":"b"}\n')
    # reopen appends, never truncates
    with DurableAppender(p) as ap:
        ap.append(b'{"rec":"c"}\n')
    with open(p, "rb") as f:
        assert f.read() == (b'{"rec":"a"}\n{"rec":"b"}\n'
                            b'{"rec":"c"}\n')
