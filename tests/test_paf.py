import pytest

from pwasm_tpu.core.errors import PwasmError
from pwasm_tpu.core.paf import parse_paf_line, _atoi


def _line(tags):
    fields = ["q", "10", "0", "10", "+", "t", "12", "0", "12",
              "10", "12", "60", "tp:A:P", "cm:i:5", "s1:i:9"] + tags
    return "\t".join(fields)


def test_parse_basic():
    rec = parse_paf_line(_line(["NM:i:3", "AS:i:17", "cg:Z:10M",
                                "cs:Z::10"]))
    al = rec.alninfo
    assert (al.r_id, al.r_len, al.r_alnstart, al.r_alnend) == ("q", 10, 0, 10)
    assert (al.t_id, al.t_len, al.t_alnstart, al.t_alnend) == ("t", 12, 0, 12)
    assert al.reverse == 0
    assert rec.edist == 3
    assert rec.alnscore == 17
    assert rec.cigar == "10M"
    assert rec.cs == ":10"


def test_parse_reverse_strand():
    line = _line(["cg:Z:10M", "cs:Z::10"]).replace("\t+\t", "\t-\t")
    assert parse_paf_line(line).alninfo.reverse == 1


def test_parse_too_few_fields():
    with pytest.raises(PwasmError, match="invalid PAF"):
        parse_paf_line("a\tb\tc")


def test_parse_duplicate_tag_semantics():
    # Reference behavior (pafreport.cpp:492-520): each match overwrites and
    # scanning stops only once all four tags were seen, so with AS absent a
    # duplicate NM overwrites the first.
    rec = parse_paf_line(_line(["NM:i:1", "NM:i:2", "cg:Z:10M", "cs:Z::10"]))
    assert rec.edist == 2
    # ...but once NM/AS/cg/cs have all been seen, scanning stops.
    rec = parse_paf_line(_line(["NM:i:1", "AS:i:7", "cg:Z:10M", "cs:Z::10",
                                "NM:i:9"]))
    assert rec.edist == 1


def test_parse_missing_tags():
    rec = parse_paf_line(_line(["xx:Z:foo"]))
    assert rec.cigar is None and rec.cs is None
    assert rec.edist == -1 and rec.alnscore == 0


def test_atoi():
    assert _atoi("123") == 123
    assert _atoi("-5") == -5
    assert _atoi("12ab") == 12
    assert _atoi("ab") == 0
    assert _atoi("") == 0
