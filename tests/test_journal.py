"""Crash-safe serving (ISSUE 9): durable job journal, disk-spooled
results, per-client fair-share admission.

Acceptance contracts:

- **journal**: every admission/start/finish/cancel/evict is an fsync'd
  NDJSON record; a daemon restarted after a hard crash (kill -9)
  replays the journal — queued jobs re-queue, running jobs re-admit as
  ``--resume`` continuations of their own checkpoints, terminal
  results restore — and the recovered fleet's reports are
  byte-identical to a never-crashed daemon's;
- **torn tail**: a record the crash tore mid-append never durably
  happened (its job was never acked);
- **spool**: past ``--spool-threshold-bytes`` a finished job's result
  moves to disk (fsio-atomic, CRC'd); daemon RAM keeps an index entry
  only, ``result`` frames stream from the file, eviction unlinks it;
- **fair share**: ``--max-queue`` is a PER-CLIENT quota and dequeue is
  weighted deficit-round-robin over clients — one heavy submitter can
  neither fill the whole queue nor make a light client wait behind its
  entire backlog; ``--priority-lanes`` adds strict tiers above that;
- **client backoff**: ``submit --retry[=N]`` honors ``retry_after_s``
  with a capped-exponential schedule instead of exiting 11 at the
  first ``queue_full``.
"""

import io
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time
from contextlib import contextmanager
from types import SimpleNamespace

import numpy as np
import pytest

from pwasm_tpu.cli import run
from pwasm_tpu.core.errors import EXIT_PREEMPTED, EXIT_USAGE
from pwasm_tpu.core.fasta import write_fasta
from pwasm_tpu.service.client import (ServiceClient, client_main,
                                      retry_backoff_s,
                                      wait_for_socket)
from pwasm_tpu.service.daemon import Daemon, serve_main
from pwasm_tpu.service.journal import (REC_ADMIT, REC_CANCEL,
                                       REC_EVICT, REC_FINISH,
                                       REC_START, JobJournal,
                                       fold_records)
from pwasm_tpu.service.queue import Job, JobQueue, QueueFull

from helpers import make_paf_line

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SLOW = "--inject-faults=seed=1,rate=1,kinds=hang,hang_s=0.25"


# ---------------------------------------------------------------------------
# journal primitives
# ---------------------------------------------------------------------------
def test_journal_append_replay_roundtrip(tmp_path):
    p = str(tmp_path / "j.journal")
    j = JobJournal(p)
    j.open()
    assert j.append(REC_ADMIT, job_id="job-0001", argv=["a", "-o", "b"])
    assert j.append(REC_START, job_id="job-0001", lane=0)
    assert j.append(REC_FINISH, job_id="job-0001", state="done", rc=0)
    j.close()
    recs = JobJournal(p).replay()
    assert [r["rec"] for r in recs] == [REC_ADMIT, REC_START,
                                        REC_FINISH]
    assert recs[0]["argv"] == ["a", "-o", "b"]
    assert all(r["v"] == 1 for r in recs)


def test_journal_replay_skips_torn_tail_and_garbage(tmp_path):
    p = str(tmp_path / "j.journal")
    with open(p, "w") as f:
        f.write('{"v":1,"rec":"admit","job_id":"job-0001","argv":[]}\n')
        f.write("not json at all\n")
        f.write('{"v":1,"rec":"start","job_id":"job-0001"}\n')
        f.write('{"v":1,"rec":"admit","job_id":"job-0002","ar')  # torn
    recs = JobJournal(p).replay()
    # the torn final line and the garbage line simply never happened
    assert [(r["rec"], r["job_id"]) for r in recs] == [
        ("admit", "job-0001"), ("start", "job-0001")]
    # no file at all = empty history, not an error
    assert JobJournal(str(tmp_path / "missing")).replay() == []


def test_journal_compact_keeps_only_given_records(tmp_path):
    p = str(tmp_path / "j.journal")
    j = JobJournal(p)
    j.open()
    for i in range(5):
        j.append(REC_ADMIT, job_id=f"job-{i:04d}", argv=[])
    keep = [{"v": 1, "rec": REC_ADMIT, "job_id": "job-0003",
             "argv": []}]
    j.compact(keep)
    # appender still live after the rewrite
    assert j.append(REC_START, job_id="job-0003")
    j.close()
    recs = JobJournal(p).replay()
    assert [(r["rec"], r["job_id"]) for r in recs] == [
        ("admit", "job-0003"), ("start", "job-0003")]


def test_journal_broken_latch_degrades_without_raising(tmp_path,
                                                       monkeypatch):
    p = str(tmp_path / "j.journal")
    j = JobJournal(p)
    j.open()
    assert j.append(REC_ADMIT, job_id="job-0001", argv=[])

    def boom(data):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(j._appender, "append", boom)
    assert j.append(REC_ADMIT, job_id="job-0002", argv=[]) is False
    assert "No space left" in j.broken
    # latched: later appends return False without touching the file
    assert j.append(REC_ADMIT, job_id="job-0003", argv=[]) is False
    assert j.records_written == 1


def test_fold_records_lifecycle_and_orphans():
    folded = fold_records([
        {"rec": REC_ADMIT, "job_id": "a", "argv": ["x"]},
        {"rec": REC_ADMIT, "job_id": "b", "argv": ["y"]},
        {"rec": REC_START, "job_id": "b", "lane": 1},
        {"rec": REC_START, "job_id": "orphan"},   # no admit: dropped
        {"rec": REC_FINISH, "job_id": "b", "state": "done", "rc": 0},
        {"rec": REC_ADMIT, "job_id": "c", "argv": ["z"]},
        {"rec": REC_CANCEL, "job_id": "c"},
        {"rec": REC_EVICT, "job_id": "b"},
    ])
    assert list(folded) == ["a", "b", "c"]     # admit order
    assert folded["a"]["start"] is None
    assert folded["b"]["start"]["lane"] == 1
    assert folded["b"]["finish"]["rc"] == 0
    assert folded["b"]["evicted"] is True
    assert folded["c"]["cancel"] is not None
    assert "orphan" not in folded
    assert [folded[k]["_ord"] for k in ("a", "b", "c")] == [0, 1, 2]


# ---------------------------------------------------------------------------
# fair-share queue units
# ---------------------------------------------------------------------------
def _mkjob(i, client="", priority=""):
    return Job(id=f"job-{i:04d}", argv=["in.paf", "-o", "x"],
               client=client, priority=priority)


def test_fair_share_light_client_not_starved():
    """The acceptance gate: a 1-job submitter never waits behind a
    heavy submitter's whole backlog — round-robin serves it within one
    rotation of the client set."""
    q = JobQueue(max_queue=100)
    for i in range(50):
        q.submit(_mkjob(i, client="heavy"))
    q.submit(_mkjob(99, client="light"))
    order = [q.take(timeout=0).client for _ in range(6)]
    assert "light" in order[:2], order
    # FIFO within the heavy client all the while
    heavy_ids = [j for j in order if j == "heavy"]
    assert len(heavy_ids) >= 4


def test_fair_share_round_robin_interleaves_clients():
    q = JobQueue(max_queue=10)
    for i in range(3):
        q.submit(_mkjob(i, client="a"))
    for i in range(3):
        q.submit(_mkjob(10 + i, client="b"))
    got = [q.take(timeout=0) for _ in range(6)]
    clients = [j.client for j in got]
    # strict alternation with equal weights
    assert clients == ["a", "b", "a", "b", "a", "b"]
    # and FIFO within each client
    assert [j.id for j in got if j.client == "a"] == [
        "job-0000", "job-0001", "job-0002"]


def test_per_client_quota_replaces_global_cliff():
    q = JobQueue(max_queue=2, max_total=3)
    q.submit(_mkjob(0, client="hog"))
    q.submit(_mkjob(1, client="hog"))
    with pytest.raises(QueueFull) as e:
        q.submit(_mkjob(2, client="hog"))
    assert "hog" in str(e.value)
    # another client still has its own quota...
    q.submit(_mkjob(3, client="other"))
    # ...until the global backstop
    with pytest.raises(QueueFull) as e2:
        q.submit(_mkjob(4, client="third"))
    assert "total" in str(e2.value)
    assert q.client_depths() == {"hog": 2, "other": 1}


def test_priority_lanes_strict_tiers_fair_within():
    q = JobQueue(max_queue=10, priority_lanes=("hi", "lo"))
    q.submit(_mkjob(0, client="a", priority="lo"))
    q.submit(_mkjob(1, client="b"))            # untagged -> lowest
    q.submit(_mkjob(2, client="a", priority="hi"))
    got = [q.take(timeout=0) for _ in range(3)]
    assert got[0].id == "job-0002"             # hi beats every lo
    assert {got[1].client, got[2].client} == {"a", "b"}


def test_client_weights_shape_the_rotation():
    q = JobQueue(max_queue=20)
    q.set_client_weight("gold", 2.0)
    for i in range(6):
        q.submit(_mkjob(i, client="gold"))
        q.submit(_mkjob(10 + i, client="free"))
    first6 = [q.take(timeout=0).client for _ in range(6)]
    assert first6.count("gold") == 4           # 2:1 service ratio
    assert first6.count("free") == 2


def test_drain_returns_admission_order_across_clients():
    q = JobQueue(max_queue=10, priority_lanes=("hi", "lo"))
    ids = []
    for i, (c, p) in enumerate([("a", "lo"), ("b", "hi"), ("a", "hi"),
                                ("c", "lo")]):
        q.submit(_mkjob(i, client=c, priority=p))
        ids.append(f"job-{i:04d}")
    assert [j.id for j in q.drain()] == ids


def test_remove_updates_client_depths():
    q = JobQueue(max_queue=10)
    j1, j2 = _mkjob(0, client="a"), _mkjob(1, client="a")
    q.submit(j1)
    q.submit(j2)
    assert q.remove(j1) is True
    assert q.remove(j1) is False
    assert q.client_depths() == {"a": 1}
    assert q.take(timeout=0) is j2


# ---------------------------------------------------------------------------
# client backoff schedule (submit --retry)
# ---------------------------------------------------------------------------
def test_retry_backoff_schedule_doubles_from_hint_and_caps():
    sched = [retry_backoff_s(a, 2.0) for a in range(6)]
    assert sched == [2.0, 4.0, 8.0, 16.0, 30.0, 30.0]


def test_retry_backoff_schedule_defaults_without_hint():
    assert [retry_backoff_s(a, None) for a in range(4)] == [
        0.5, 1.0, 2.0, 4.0]
    # a nonsense hint (zero/negative/non-numeric) falls back to base
    assert retry_backoff_s(0, 0) == 0.5
    assert retry_backoff_s(0, -3) == 0.5
    assert retry_backoff_s(0, "soon") == 0.5
    assert retry_backoff_s(2, None, base_s=1.0, cap_s=3.0) == 3.0


# ---------------------------------------------------------------------------
# in-process daemon harness (stub runner: no jax, no corpus)
# ---------------------------------------------------------------------------
@contextmanager
def _daemon(runner=None, **kw):
    sockdir = tempfile.mkdtemp(prefix="pwjrnl")
    sock = os.path.join(sockdir, "s")
    err = io.StringIO()
    dm = Daemon(sock, stderr=err, runner=runner, **kw)
    rcbox: list = []
    t = threading.Thread(target=lambda: rcbox.append(dm.serve()),
                         daemon=True)
    t.start()
    assert wait_for_socket(sock, 15), err.getvalue()
    try:
        yield SimpleNamespace(daemon=dm, sock=sock, rc=rcbox, err=err,
                              thread=t, dir=sockdir)
    finally:
        if not dm.drain.requested:
            dm.drain.request("test teardown")
        t.join(20)
        shutil.rmtree(sockdir, ignore_errors=True)


def _stub_runner(log=None, stats=None, sleep=0.0, rc=0):
    """A runner that mimics cli.run enough for service-layer tests:
    records argv order, honors the injected --stats sink."""
    def runner(argv, stdout=None, stderr=None, warm=None):
        if log is not None:
            log.append(list(argv))
        if sleep:
            time.sleep(sleep)
        sp = next((a.split("=", 1)[1] for a in argv
                   if a.startswith("--stats=")), None)
        if sp and stats is not None:
            with open(sp, "w") as f:
                json.dump(stats, f)
        return rc
    return runner


def _journal_file(tmp_path, recs, torn=None):
    p = str(tmp_path / "crash.journal")
    with open(p, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
        if torn is not None:
            f.write(torn)                       # no newline: torn
    return p


# ---------------------------------------------------------------------------
# replay: requeue / resume / restore / compact
# ---------------------------------------------------------------------------
def test_replay_requeues_resumes_restores_and_drops_torn(tmp_path):
    out_a = str(tmp_path / "a.dfa")
    out_b = str(tmp_path / "b.dfa")
    jp = _journal_file(tmp_path, [
        {"v": 1, "rec": "admit", "job_id": "job-0001",
         "argv": ["a.paf", "-o", out_a], "client": "uid:7",
         "priority": "", "t": 1.0},
        {"v": 1, "rec": "admit", "job_id": "job-0002",
         "argv": ["b.paf", "-o", out_b], "client": "uid:8",
         "priority": "", "t": 2.0},
        {"v": 1, "rec": "start", "job_id": "job-0002", "lane": 0},
        {"v": 1, "rec": "admit", "job_id": "job-0003",
         "argv": ["c.paf", "-o", "c.dfa"], "client": "uid:7",
         "priority": "", "t": 3.0},
        {"v": 1, "rec": "finish", "job_id": "job-0003",
         "state": "done", "rc": 0, "t": 3.5},
    ], torn='{"v":1,"rec":"admit","job_id":"job-9999","argv":["x')
    ran: list = []
    with _daemon(runner=_stub_runner(log=ran),
                 journal_path=jp) as h:
        with ServiceClient(h.sock) as c:
            assert c.result("job-0001", timeout=30)["rc"] == 0
            r2 = c.result("job-0002", timeout=30)
            assert r2["rc"] == 0
            assert "recovered" in r2["job"]["detail"]
            # terminal result restored without re-running
            r3 = c.result("job-0003", timeout=30)
            assert r3["job"]["state"] == "done" and r3["rc"] == 0
            # the torn admission never durably happened
            assert c.status("job-9999")["error"] == "unknown_job"
            st = c.stats()["stats"]
        assert st["journal"]["replays"] == 1
        assert st["journal"]["jobs_recovered"] == 2
        assert st["jobs"]["recovered"] == 2
        # new admissions continue the id sequence past the recovered
        with ServiceClient(h.sock) as c:
            nxt = c.submit(["d.paf", "-o", str(tmp_path / "d.dfa")],
                           cwd=str(tmp_path))
            assert nxt["job_id"] == "job-0004"
            assert c.result("job-0004", timeout=30)["rc"] == 0
    argvs = {tuple(a[:2]) for a in ran}
    assert ("a.paf", "-o") in argvs
    # the mid-run job came back as a --resume continuation
    resumed = next(a for a in ran if a and a[0] == "b.paf")
    assert "--resume" in resumed
    # job-0003 was NOT re-run
    assert not any(a[0] == "c.paf" for a in ran)


def test_replay_lands_inflight_cancel_terminal_cancelled(tmp_path):
    jp = _journal_file(tmp_path, [
        {"v": 1, "rec": "admit", "job_id": "job-0001",
         "argv": ["a.paf", "-o", "a.dfa"], "client": "", "t": 1.0},
        {"v": 1, "rec": "start", "job_id": "job-0001", "lane": 0},
        {"v": 1, "rec": "cancel", "job_id": "job-0001"},
    ])
    ran: list = []
    with _daemon(runner=_stub_runner(log=ran), journal_path=jp) as h:
        with ServiceClient(h.sock) as c:
            r = c.result("job-0001", timeout=30)
        # the cancel was acked before the crash: replay must NOT
        # silently un-cancel it by re-running
        assert r["job"]["state"] == "cancelled"
        assert "crash" in r["job"]["detail"]
    assert ran == []


def test_replay_compacts_journal_to_live_state(tmp_path):
    out_a = str(tmp_path / "a.dfa")
    jp = _journal_file(tmp_path, [
        {"v": 1, "rec": "admit", "job_id": "job-0001",
         "argv": ["a.paf", "-o", out_a], "client": "", "t": 1.0},
        {"v": 1, "rec": "admit", "job_id": "job-0002",
         "argv": ["b.paf", "-o", "b.dfa"], "client": "", "t": 2.0},
        {"v": 1, "rec": "evict", "job_id": "job-0002"},
        # job-0002 was admitted AND evicted -> dead history
        {"v": 1, "rec": "finish", "job_id": "job-0002",
         "state": "done", "rc": 0},
    ])
    with _daemon(runner=_stub_runner(), journal_path=jp) as h:
        with ServiceClient(h.sock) as c:
            c.result("job-0001", timeout=30)
    # after replay+compact the evicted job's records are gone; the
    # journal itself was retired by the clean drain in teardown
    assert not os.path.exists(jp)


def test_replay_survives_wrong_typed_fields(tmp_path):
    """Bit-rot or hand edits in numeric journal fields must degrade
    (defaults), never raise into daemon startup — a journal that
    wedges every restart is worse than no journal."""
    out_a = str(tmp_path / "a.dfa")
    jp = _journal_file(tmp_path, [
        {"v": 1, "rec": "admit", "job_id": "job-0001",
         "argv": ["a.paf", "-o", out_a], "client": "",
         "t": "yesterday-ish"},
        {"v": 1, "rec": "start", "job_id": "job-0001",
         "lane": "zero"},
        {"v": 1, "rec": "admit", "job_id": "job-0002",
         "argv": ["b.paf", "-o", "b.dfa"], "client": "", "t": 2.0},
        {"v": 1, "rec": "finish", "job_id": "job-0002",
         "state": "done", "rc": 0, "t": True,
         "spool": {"path": "/nonexistent", "bytes": "many"}},
    ])
    with _daemon(runner=_stub_runner(), journal_path=jp) as h:
        with ServiceClient(h.sock) as c:
            assert c.result("job-0001", timeout=30)["rc"] == 0
            r2 = c.result("job-0002", timeout=30)
        assert r2["job"]["state"] == "done"
        # the spool file named by the rotted record is gone: noted in
        # the detail, not a crash
        assert "lost" in r2["job"]["detail"]
        assert "replay" not in h.err.getvalue() or True
        assert h.daemon.stats.journal_replays == 1


def test_rejected_submission_never_resurrected_by_replay(tmp_path):
    """The write-ahead order: admit is journaled BEFORE the queue can
    reject it, and a rejection retracts the id with an evict record —
    replay must not re-queue a job the client was told was
    rejected."""
    jp = str(tmp_path / "live.journal")
    with _daemon(runner=_stub_runner(sleep=5.0), journal_path=jp,
                 max_queue=1, max_queue_total=1) as h:
        with ServiceClient(h.sock) as c:
            ok = c.submit(["a.paf", "-o", str(tmp_path / "a.dfa")],
                          cwd=str(tmp_path))
            assert ok.get("ok")
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if c.stats()["stats"]["running"] >= 1:
                    break
                time.sleep(0.02)
            c.submit(["b.paf", "-o", str(tmp_path / "b.dfa")],
                     cwd=str(tmp_path))            # fills the slot
            rej = c.submit(["c.paf", "-o", str(tmp_path / "c.dfa")],
                           cwd=str(tmp_path))
            assert rej["ok"] is False
        recs = JobJournal(jp).replay()
    by_job: dict = {}
    for r in recs:
        by_job.setdefault(r.get("job_id"), []).append(r["rec"])
    rejected = [k for k, v in by_job.items() if "evict" in v]
    assert len(rejected) == 1
    # folded: the rejected id is marked evicted -> replay skips it
    folded = fold_records(recs)
    assert folded[rejected[0]]["evicted"] is True


def test_clean_drain_retires_journal_hard_exit_keeps_it(tmp_path):
    jp = str(tmp_path / "live.journal")
    with _daemon(runner=_stub_runner(), journal_path=jp) as h:
        with ServiceClient(h.sock) as c:
            c.submit(["a.paf", "-o", str(tmp_path / "a.dfa")],
                     cwd=str(tmp_path))
            time.sleep(0.1)
        assert os.path.exists(jp)     # live daemon: journal on disk
        with ServiceClient(h.sock) as c:
            c.drain()
    assert h.rc == [EXIT_PREEMPTED]
    # clean drain: every client got its verdict, nothing to recover
    assert not os.path.exists(jp)


def test_journal_off_serves_without_crash_safety(tmp_path):
    with _daemon(runner=_stub_runner(), journal_path=None) as h:
        with ServiceClient(h.sock) as c:
            sub = c.submit(["a.paf", "-o", str(tmp_path / "a.dfa")],
                           cwd=str(tmp_path))
            assert c.result(sub["job_id"], timeout=30)["rc"] == 0
            st = c.stats()["stats"]
        assert st["journal"]["path"] is None
        assert st["journal"]["records"] == 0


# ---------------------------------------------------------------------------
# disk-spooled results
# ---------------------------------------------------------------------------
BIG_STATS = {"stats_version": 1, "alignments": 7,
             "blob": "x" * 4096}


def test_spool_moves_big_result_to_disk_and_serves_it(tmp_path):
    with _daemon(runner=_stub_runner(stats=BIG_STATS),
                 spool_threshold_bytes=1024) as h:
        with ServiceClient(h.sock) as c:
            sub = c.submit(["a.paf", "-o", str(tmp_path / "a.dfa")],
                           cwd=str(tmp_path))
            res = c.result(sub["job_id"], timeout=30)
            st = c.stats()["stats"]
        # the frame streamed the FULL stats back from the spool file
        assert res["stats"]["blob"] == BIG_STATS["blob"]
        job = h.daemon.jobs[sub["job_id"]]
        # ...but daemon RAM holds only the index entry
        assert job.stats is None and job.stderr_tail == ""
        assert job.spool is not None
        assert os.path.exists(job.spool["path"])
        assert st["spool"]["bytes"] == job.spool["bytes"] > 1024
        # a SECOND read still streams (the spool is not one-shot)
        with ServiceClient(h.sock) as c:
            res2 = c.result(sub["job_id"], timeout=30)
        assert res2["stats"] == res["stats"]


def test_small_results_stay_resident_below_threshold(tmp_path):
    with _daemon(runner=_stub_runner(stats={"stats_version": 1}),
                 spool_threshold_bytes=1 << 20) as h:
        with ServiceClient(h.sock) as c:
            sub = c.submit(["a.paf", "-o", str(tmp_path / "a.dfa")],
                           cwd=str(tmp_path))
            assert c.result(sub["job_id"], timeout=30)["rc"] == 0
        job = h.daemon.jobs[sub["job_id"]]
        assert job.spool is None and job.stats is not None


def test_spool_crc_mismatch_reported_never_served(tmp_path):
    with _daemon(runner=_stub_runner(stats=BIG_STATS),
                 spool_threshold_bytes=256) as h:
        with ServiceClient(h.sock) as c:
            sub = c.submit(["a.paf", "-o", str(tmp_path / "a.dfa")],
                           cwd=str(tmp_path))
            c.result(sub["job_id"], timeout=30)
        path = h.daemon.jobs[sub["job_id"]].spool["path"]
        blob = open(path).read().replace('"xxx', '"yyy', 1)
        with open(path, "w") as f:
            f.write(blob)
        with ServiceClient(h.sock) as c:
            res = c.result(sub["job_id"], timeout=30)
        # ckpt-v2 rule: a result that fails verification is reported
        # unreadable, never served as if whole
        assert res["stats"] is None
        assert "CRC" in res["spool_error"]
        assert res["rc"] == 0        # the verdict itself survives


def test_eviction_unlinks_spool_and_bounds_disk(tmp_path):
    with _daemon(runner=_stub_runner(stats=BIG_STATS),
                 spool_threshold_bytes=256, max_results=1) as h:
        paths = []
        with ServiceClient(h.sock) as c:
            for tag in ("a", "b"):
                sub = c.submit(
                    ["in.paf", "-o", str(tmp_path / f"{tag}.dfa")],
                    cwd=str(tmp_path))
                assert c.result(sub["job_id"], timeout=30)["rc"] == 0
                paths.append(
                    h.daemon.jobs[sub["job_id"]].spool["path"])
            # max_results=1: admitting+finishing b evicted a
            c.ping()                  # dispatch tick runs eviction
            st = c.stats()["stats"]
        assert st["jobs"]["evicted"] >= 1
        assert not os.path.exists(paths[0])
        assert os.path.exists(paths[1])
        assert st["spool"]["bytes"] < 2 * (1 << 20)


# ---------------------------------------------------------------------------
# fair share through the daemon (admission + scheduling E2E)
# ---------------------------------------------------------------------------
def test_daemon_quota_names_client_and_spares_others(tmp_path):
    gate = threading.Event()

    def runner(argv, stdout=None, stderr=None, warm=None):
        gate.wait(30)
        return 0

    with _daemon(runner=runner, max_queue=2,
                 max_queue_total=16) as h:
        try:
            with ServiceClient(h.sock) as c:
                subs = []
                for i in range(3):   # 1 runs, 2 queue = hog at quota
                    r = c.submit(["in.paf", "-o",
                                  str(tmp_path / f"h{i}.dfa")],
                                 cwd=str(tmp_path), client="hog")
                    subs.append(r)
                    assert r.get("ok"), r
                rej = c.submit(["in.paf", "-o",
                                str(tmp_path / "h3.dfa")],
                               cwd=str(tmp_path), client="hog")
                assert rej["ok"] is False
                assert rej["error"] == "queue_full"
                assert rej["client"] == "hog"
                assert rej["client_depth"] == 2
                assert rej["retry_after_s"] > 0
                # the light client is NOT behind the hog's quota
                ok = c.submit(["in.paf", "-o",
                               str(tmp_path / "l0.dfa")],
                              cwd=str(tmp_path), client="light")
                assert ok.get("ok"), ok
                st = c.stats()["stats"]
                assert st["fair_share"]["clients"] == {
                    "hog": 2, "light": 1}
        finally:
            gate.set()


def test_daemon_light_client_scheduled_before_heavy_backlog(tmp_path):
    done_order: list = []
    gate = threading.Event()   # holds the worker until every submit
    #   has landed — otherwise a fast runner on a slow box can drain
    #   several heavy jobs before the light submit even arrives, and
    #   the DRR-order assertion below races the socket round-trips

    def runner(argv, stdout=None, stderr=None, warm=None):
        gate.wait(30)
        tag = next(a for a in argv if a.endswith(".dfa"))
        time.sleep(0.01)
        done_order.append(os.path.basename(tag))
        return 0

    with _daemon(runner=runner, max_queue=16,
                 max_concurrent=1) as h:
        with ServiceClient(h.sock) as c:
            heavy = [c.submit(["in.paf", "-o",
                               str(tmp_path / f"h{i}.dfa")],
                              cwd=str(tmp_path), client="heavy")
                     for i in range(6)]
            light = c.submit(["in.paf", "-o",
                              str(tmp_path / "light.dfa")],
                             cwd=str(tmp_path), client="light")
            assert light.get("ok")
            gate.set()
            assert c.result(light["job_id"], timeout=60)["rc"] == 0
            for s in heavy:
                assert c.result(s["job_id"], timeout=60)["rc"] == 0
    # the light job finished well before the heavy backlog drained:
    # it was round-robined in after at most 2 heavy completions (the
    # one running at submit time + one rotation)
    assert "light.dfa" in done_order[:3], done_order


def test_daemon_priority_lane_validated_and_honored(tmp_path):
    gate = threading.Event()
    done: list = []

    def runner(argv, stdout=None, stderr=None, warm=None):
        gate.wait(30)
        done.append(next(a for a in argv if a.endswith(".dfa")))
        return 0

    with _daemon(runner=runner, max_queue=8,
                 priority_lanes=("hi", "lo")) as h:
        try:
            with ServiceClient(h.sock) as c:
                # occupy the worker so later submits stay queued
                c.submit(["in.paf", "-o", str(tmp_path / "w.dfa")],
                         cwd=str(tmp_path))
                time.sleep(0.2)      # worker picks it up
                bad = c.submit(["in.paf", "-o",
                                str(tmp_path / "x.dfa")],
                               cwd=str(tmp_path), priority="mid")
                assert bad["ok"] is False
                assert bad["error"] == "bad_request"
                lo = c.submit(["in.paf", "-o",
                               str(tmp_path / "lo.dfa")],
                              cwd=str(tmp_path), priority="lo")
                hi = c.submit(["in.paf", "-o",
                               str(tmp_path / "hi.dfa")],
                              cwd=str(tmp_path), priority="hi")
                assert lo.get("ok") and hi.get("ok")
                gate.set()
                assert c.result(hi["job_id"], timeout=60)["rc"] == 0
                assert c.result(lo["job_id"], timeout=60)["rc"] == 0
        finally:
            gate.set()
    # the hi job was dequeued before the earlier-submitted lo job
    assert done.index(str(tmp_path / "hi.dfa")) \
        < done.index(str(tmp_path / "lo.dfa"))


def test_submit_retry_backs_off_and_lands(tmp_path):
    gate = threading.Event()

    def runner(argv, stdout=None, stderr=None, warm=None):
        gate.wait(30)
        return 0

    with _daemon(runner=runner, max_queue=1, max_queue_total=1) as h:
        try:
            with ServiceClient(h.sock) as c:
                first = c.submit(["in.paf", "-o",
                                  str(tmp_path / "f.dfa")],
                                 cwd=str(tmp_path))
                assert first.get("ok")
                # wait until it RUNS, then fill the single queue slot
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    if c.status(first["job_id"])["job"]["state"] \
                            == "running":
                        break
                    time.sleep(0.02)
                filler = c.submit(["in.paf", "-o",
                                   str(tmp_path / "q.dfa")],
                                  cwd=str(tmp_path))
                assert filler.get("ok")
            out, err = io.StringIO(), io.StringIO()
            box: list = []
            t = threading.Thread(target=lambda: box.append(
                client_main("submit",
                            [f"--socket={h.sock}", "--retry=8",
                             "--", "in.paf", "-o",
                             str(tmp_path / "r.dfa")],
                            stdout=out, stderr=err)), daemon=True)
            t.start()
            time.sleep(0.3)          # let the first rejection land
            gate.set()               # capacity frees; a retry lands
            t.join(90)
            assert box == [0], (box, err.getvalue())
            assert "retry" in err.getvalue()
            assert json.loads(out.getvalue())["state"] == "done"
        finally:
            gate.set()


def test_submit_retry_budget_spent_exits_11(tmp_path):
    gate = threading.Event()

    def runner(argv, stdout=None, stderr=None, warm=None):
        gate.wait(30)
        return 0

    with _daemon(runner=runner, max_queue=1, max_queue_total=1) as h:
        try:
            with ServiceClient(h.sock) as c:
                c.submit(["in.paf", "-o", str(tmp_path / "f.dfa")],
                         cwd=str(tmp_path))
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    st = c.stats()["stats"]
                    if st["running"] >= 1:
                        break
                    time.sleep(0.02)
                c.submit(["in.paf", "-o", str(tmp_path / "q.dfa")],
                         cwd=str(tmp_path))
            err = io.StringIO()
            rc = client_main("submit",
                             [f"--socket={h.sock}", "--retry=1",
                              "--", "in.paf", "-o",
                              str(tmp_path / "r.dfa")],
                             stdout=io.StringIO(), stderr=err)
            assert rc == 11, err.getvalue()
            assert "retry 1/1" in err.getvalue()
        finally:
            gate.set()


def test_retry_flag_validation():
    err = io.StringIO()
    rc = client_main("submit", ["--socket=/nonexistent",
                                "--retry=zero", "--", "in.paf"],
                     stdout=io.StringIO(), stderr=err)
    assert rc == EXIT_USAGE
    assert "--retry" in err.getvalue()


# ---------------------------------------------------------------------------
# serve_main flag surface
# ---------------------------------------------------------------------------
def test_serve_main_rejects_bad_crash_safety_flags(tmp_path):
    for bad in (["--socket=s", "--priority-lanes=hi,hi"],
                ["--socket=s", "--priority-lanes=,"],
                ["--socket=s", "--spool-threshold-bytes=none"],
                ["--socket=s", "--max-queue-total=0"],
                ["--socket=s", "--journal= "]):
        err = io.StringIO()
        assert serve_main(bad, stderr=err) == EXIT_USAGE, bad
        assert "Invalid" in err.getvalue()


def test_peer_identity_is_kernel_attested_uid():
    import socket as socketlib

    from pwasm_tpu.service.daemon import _peer_identity
    a, b = socketlib.socketpair(socketlib.AF_UNIX,
                                socketlib.SOCK_STREAM)
    try:
        assert _peer_identity(a) == f"uid:{os.getuid()}"
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# the crash drill: kill -9 a live serve subprocess mid-job
# ---------------------------------------------------------------------------
def _corpus(tmp_path, n=24, qlen=120, seed=3):
    rng = np.random.default_rng(seed)
    q = "".join("ACGT"[i] for i in rng.integers(0, 4, qlen))
    lines = []
    for i in range(n):
        cut = 10 + int(rng.integers(0, qlen - 40))
        qb = q[cut]
        tb = "ACGT"[("ACGT".index(qb) + 1) % 4]
        ops = [("=", cut), ("*", tb, qb), ("=", 20), ("ins", "gg"),
               ("=", qlen - cut - 21)]
        lines.append(make_paf_line("q", q, f"asm{i}", "+", ops)[0])
    fa = tmp_path / "q.fa"
    write_fasta(str(fa), [("q", q.encode())])
    paf = tmp_path / "in.paf"
    paf.write_text("".join(ln + "\n" for ln in lines))
    return str(paf), str(fa)


def _job_args(tmp_path, tag, paf, fa, extra=()):
    return [paf, "-r", fa, "-o", str(tmp_path / f"{tag}.dfa"),
            "--device=tpu", "--batch=2",
            f"--stats={tmp_path / f'{tag}.json'}"] + list(extra)


def _serve_env():
    old_pp = os.environ.get("PYTHONPATH", "")
    return dict(os.environ, JAX_PLATFORMS="cpu",
                PWASM_DEVICE_PROBE="0",
                PYTHONPATH=REPO + (os.pathsep + old_pp if old_pp
                                   else ""))


def _spawn_serve(sock, extra=()):
    return subprocess.Popen(
        [sys.executable, "-m", "pwasm_tpu.cli", "serve",
         f"--socket={sock}"] + list(extra),
        env=_serve_env(), stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE, text=True)


def test_kill9_crash_drill_replay_recovers_byte_identical(tmp_path):
    """THE acceptance drill: kill -9 a live serve daemon mid-job
    (after its first durable checkpoint) with a second job still
    queued; a fresh daemon on the same socket replays the journal,
    resumes the interrupted job from its ckpt and re-queues the queued
    one — and every report is byte-identical to the uncrashed arm."""
    paf, fa = _corpus(tmp_path)
    # the uncrashed arm: cold runs of the exact same job argvs
    cold_a = run(_job_args(tmp_path, "colda", paf, fa, [SLOW]),
                 stderr=io.StringIO())
    cold_b = run(_job_args(tmp_path, "coldb", paf, fa),
                 stderr=io.StringIO())
    assert cold_a == 0 and cold_b == 0
    expect_a = (tmp_path / "colda.dfa").read_bytes()
    expect_b = (tmp_path / "coldb.dfa").read_bytes()

    sockdir = tempfile.mkdtemp(prefix="pwkill9")
    sock = os.path.join(sockdir, "s")
    sp = _spawn_serve(sock)
    sp2 = None
    try:
        assert wait_for_socket(sock, 60)
        with ServiceClient(sock) as c:
            ja = c.submit(_job_args(tmp_path, "a", paf, fa, [SLOW]))
            assert ja.get("ok"), ja
            jb = c.submit(_job_args(tmp_path, "b", paf, fa))
            assert jb.get("ok"), jb
            # wait until job a is demonstrably MID-RUN with a durable
            # ckpt — the window where a crash loses real work
            ck = str(tmp_path / "a.dfa.ckpt")
            deadline = time.monotonic() + 60
            mid = False
            while time.monotonic() < deadline:
                st = c.status(ja["job_id"])["job"]["state"]
                if st == "running" and os.path.exists(ck):
                    mid = True
                    break
                assert st in ("queued", "running"), st
                time.sleep(0.02)
            assert mid, "job never reached mid-run with a ckpt"
        sp.kill()                     # SIGKILL: no drain, no cleanup
        sp.wait(timeout=30)
        assert os.path.exists(sock + ".journal")

        sp2 = _spawn_serve(sock)
        assert wait_for_socket(sock, 60)
        with ServiceClient(sock) as c:
            # ids survive the crash: clients keep polling the same ids
            ra = c.result(ja["job_id"], timeout=240)
            rb = c.result(jb["job_id"], timeout=240)
            st = c.stats()["stats"]
            c.drain()
        assert ra.get("rc") == 0, ra
        assert rb.get("rc") == 0, rb
        assert "recovered" in ra["job"]["detail"]
        assert st["journal"]["replays"] == 1
        assert st["journal"]["jobs_recovered"] == 2
        # no lost, duplicated, or reordered work: bytes identical to
        # the never-crashed arm for BOTH jobs
        assert (tmp_path / "a.dfa").read_bytes() == expect_a
        assert (tmp_path / "b.dfa").read_bytes() == expect_b
        assert sp2.wait(timeout=120) == EXIT_PREEMPTED
        # the recovered fleet drained clean: journal retired
        assert not os.path.exists(sock + ".journal")
    finally:
        for p in (sp, sp2):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()
            if p is not None:
                p.stderr.close()
        shutil.rmtree(sockdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# journal portability: the failover primitive (ISSUE 13 satellite)
# ---------------------------------------------------------------------------
def test_journal_portable_across_cwd_and_socket(tmp_path, monkeypatch):
    """A journal written by daemon A must replay IDENTICALLY in a
    process with a different cwd and a different socket path — the
    primitive fleet failover stands on (the router hands a dead
    member's journal to a sibling daemon; nothing about the journal
    may depend on where the writer ran).  Two fresh daemons on two
    different cwds/sockets replay byte-identical copies and must
    recover the same job table and replay the same argvs."""
    gate = threading.Event()
    started = threading.Event()

    def blocking_runner(argv, stdout=None, stderr=None, warm=None,
                        **kw):
        started.set()
        gate.wait(30)
        return 0

    # daemon A: one job mid-run, one queued — the live-at-crash state
    srcdir = tmp_path / "a_cwd"
    srcdir.mkdir()
    snap = str(tmp_path / "crash-snapshot.journal")
    with _daemon(runner=blocking_runner) as h:
        with ServiceClient(h.sock, trace_id="port-trace") as c:
            # RELATIVE paths + client cwd: the daemon absolutizes at
            # admission, so the journal must carry cwd-free argvs
            ja = c.submit(["a.paf", "-o", "a.dfa"],
                          cwd=str(srcdir), client="tenant1")
            assert ja.get("ok"), ja
            assert started.wait(15)
            jb = c.submit(["b.paf", "-o", "b.dfa"],
                          cwd=str(srcdir), client="tenant2")
            assert jb.get("ok"), jb
            # snapshot the journal while both jobs are live (exactly
            # what a kill -9 would leave behind)
            shutil.copy(h.daemon.journal.path, snap)
        gate.set()

    def replay_in(cwd: str, tag: str):
        """One fresh daemon process-alike: own cwd, own socket path,
        replaying its own copy of the snapshot."""
        monkeypatch.chdir(cwd)
        jp = os.path.join(cwd, f"{tag}.journal")
        shutil.copy(snap, jp)
        ran: list = []
        with _daemon(runner=_stub_runner(log=ran),
                     journal_path=jp) as h:
            with ServiceClient(h.sock) as c:
                ra = c.result(ja["job_id"], timeout=30)
                rb = c.result(jb["job_id"], timeout=30)
                st = c.stats()["stats"]
        assert ra["rc"] == 0 and rb["rc"] == 0
        assert st["journal"]["jobs_recovered"] == 2
        rows = []
        for r in (ra, rb):
            j = r["job"]
            rows.append((j["id"], j["state"], j["client"],
                         j["trace_id"], j["recovered"]))
        # the injected --stats sink lives in each daemon's private
        # tmpdir by design — it is the one daemon-local argv token
        return rows, sorted(
            tuple(t for t in a if not t.startswith("--stats="))
            for a in ran)

    cwd_b = tmp_path / "b_cwd"
    cwd_c = tmp_path / "c_cwd" / "nested"
    cwd_b.mkdir()
    cwd_c.mkdir(parents=True)
    rows_b, ran_b = replay_in(str(cwd_b), "b")
    rows_c, ran_c = replay_in(str(cwd_c), "c")
    # identical recovery in both foreign processes
    assert rows_b == rows_c
    assert ran_b == ran_c
    # the mid-run job came back as --resume, the queued one plain,
    # and every recovered path is absolute (cwd-independent)
    resumed = next(a for a in ran_b if "--resume" in a)
    assert os.path.join(str(srcdir), "a.paf") in resumed
    for argv in ran_b:
        for tok in argv:
            if tok.endswith((".paf", ".dfa")):
                assert os.path.isabs(tok), argv
    # identity survives the foreign replay too
    assert all(r[3] == "port-trace" for r in rows_b)
