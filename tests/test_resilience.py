"""Fault-injected resilient device execution (pwasm_tpu.resilience).

The acceptance contract (ISSUE 1): with a seeded ~30%+ device-fault
rate (raise/NaN/corrupt mix) injected into a CPU-backend device CLI
run, the run completes with byte-identical -o/-w output vs the
fault-free run and nonzero retry/fallback/guardrail counters in the
--stats JSON; a run killed mid-batch resumes from the checkpoint
without duplicating report lines.
"""

import io
import json
import os

import numpy as np
import pytest

from pwasm_tpu.cli import run
from pwasm_tpu.core.fasta import write_fasta
from pwasm_tpu.resilience import (BatchSupervisor, DeviceWorkFailed,
                                  GuardrailViolation, InjectedKill,
                                  ResilienceError, ResiliencePolicy,
                                  parse_fault_spec)
from pwasm_tpu.utils.runstats import RunStats

from helpers import make_paf_line


# ---------------------------------------------------------------------------
# fault plan: spec parsing + determinism
# ---------------------------------------------------------------------------
def test_fault_spec_parsing():
    p = parse_fault_spec("seed=7,rate=0.3,kinds=raise+corrupt,"
                         "sites=ctx_scan+realign,hang_s=1.5,kill=9")
    assert p.seed == 7 and p.rate == 0.3
    assert p.kinds == ("raise", "corrupt")
    assert p.sites == frozenset({"ctx_scan", "realign"})
    assert p.hang_s == 1.5 and p.kill == 9
    # defaults
    d = parse_fault_spec("rate=1")
    assert d.seed == 0 and len(d.kinds) == 4 and d.sites is None


@pytest.mark.parametrize("bad", ["rate=2", "rate=x", "kinds=explode",
                                 "kinds=", "nonsense", "seed=1.5",
                                 "hang_s=-1", "kill=-2", "frob=1"])
def test_fault_spec_rejects(bad):
    with pytest.raises(ValueError):
        parse_fault_spec(bad)


def test_fault_draws_deterministic_and_seeded():
    a = parse_fault_spec("seed=3,rate=0.5")
    b = parse_fault_spec("seed=3,rate=0.5")
    c = parse_fault_spec("seed=4,rate=0.5")
    seq_a = [a.draw("s") for _ in range(40)]
    assert seq_a == [b.draw("s") for _ in range(40)]
    assert seq_a != [c.draw("s") for _ in range(40)]
    assert any(seq_a), "a 50% rate must inject within 40 draws"
    # sites= restricts injection but still advances counters
    r = parse_fault_spec("seed=3,rate=1,sites=other")
    assert [r.draw("s") for _ in range(5)] == [None] * 5


def test_fault_kill_is_uncatchable_by_supervisor():
    plan = parse_fault_spec("kill=3")
    sup = BatchSupervisor(ResiliencePolicy(max_retries=5,
                                           backoff_s=0.001),
                          faults=plan)
    sup.run("s", lambda: 1)
    sup.run("s", lambda: 2)
    with pytest.raises(InjectedKill):
        sup.run("s", lambda: 3)


# ---------------------------------------------------------------------------
# supervisor: retry / deadline / breaker / policy
# ---------------------------------------------------------------------------
def _policy(**kw):
    kw.setdefault("backoff_s", 0.001)
    kw.setdefault("backoff_cap_s", 0.002)
    return ResiliencePolicy(**kw)


def test_supervisor_retries_then_succeeds():
    st = RunStats()
    calls = []
    sup = BatchSupervisor(_policy(max_retries=3), stats=st)

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("transient")
        return "ok"

    assert sup.run("s", flaky) == "ok"
    assert st.res_retries == 2 and st.res_fallbacks == 0
    assert sup.consecutive("s") == 0  # success resets the site window


def test_supervisor_guardrail_reject_reexecutes():
    st = RunStats()
    sup = BatchSupervisor(_policy(max_retries=2), stats=st,
                          stderr=io.StringIO())
    results = iter([np.array([99]), np.array([1])])

    def validate(r):
        if r[0] > 10:
            raise GuardrailViolation("out of range")

    out = sup.run("s", lambda: next(results), validate=validate)
    assert out[0] == 1
    assert st.res_guardrail_rejects == 1 and st.res_retries == 1


def test_supervisor_deadline_timeout():
    import time

    st = RunStats()
    sup = BatchSupervisor(_policy(max_retries=1, deadline_s=0.05),
                          stats=st, stderr=io.StringIO())
    with pytest.raises(DeviceWorkFailed):
        sup.run("s", lambda: time.sleep(0.5))
    assert st.res_deadline_timeouts == 2   # initial attempt + 1 retry
    # the host fallback is used when provided
    st2 = RunStats()
    sup2 = BatchSupervisor(_policy(max_retries=0, deadline_s=0.05),
                           stats=st2, stderr=io.StringIO())
    got = sup2.run("s", lambda: time.sleep(0.5), fallback=lambda: "host")
    assert got == "host" and st2.res_fallbacks == 1


def test_supervisor_breaker_opens_on_unhealthy_probe():
    st = RunStats()
    sup = BatchSupervisor(_policy(max_retries=0, breaker_threshold=3),
                          stats=st, stderr=io.StringIO(),
                          probe=lambda: (False, "tunnel down"))
    calls = []

    def dead():
        calls.append(1)
        raise RuntimeError("boom")

    for _ in range(3):
        with pytest.raises(DeviceWorkFailed):
            sup.run("s", dead)
    assert sup.breaker_open and st.res_breaker_trips == 1
    n = len(calls)
    # breaker open: the device is never touched again
    assert sup.run("s", dead, fallback=lambda: "host") == "host"
    assert len(calls) == n
    assert st.res_fallbacks >= 1


def test_supervisor_breaker_half_opens_on_healthy_probe():
    st = RunStats()
    sup = BatchSupervisor(_policy(max_retries=0, breaker_threshold=2),
                          stats=st, stderr=io.StringIO(),
                          probe=lambda: (True, ""))
    for _ in range(2):
        with pytest.raises(DeviceWorkFailed):
            sup.run("s", lambda: (_ for _ in ()).throw(
                RuntimeError("computational")))
    # healthy probe: breaker half-opens instead of walling off a
    # healthy device — attempts continue, and a half-open is NOT a
    # trip (operators alert on the trip counter)
    assert not sup.breaker_open
    assert st.res_breaker_trips == 0
    assert sup.run("s", lambda: "fine") == "fine"


def test_supervisor_per_site_windows_and_thresholds():
    # failures at one site must not charge another site's window, and
    # site_thresholds overrides the global breaker_threshold per site
    st = RunStats()
    sup = BatchSupervisor(
        _policy(max_retries=0, breaker_threshold=5,
                site_thresholds={"ctx_scan": 2}),
        stats=st, stderr=io.StringIO(), probe=lambda: (True, ""))
    with pytest.raises(DeviceWorkFailed):
        sup.run("realign", lambda: (_ for _ in ()).throw(
            RuntimeError("x")))
    assert sup.consecutive("realign") == 1
    assert sup.consecutive("ctx_scan") == 0
    # ctx_scan's lower threshold (2) trips its probe independently
    for _ in range(2):
        with pytest.raises(DeviceWorkFailed):
            sup.run("ctx_scan", lambda: (_ for _ in ()).throw(
                RuntimeError("y")))
    assert sup.consecutive("ctx_scan") == 0     # half-opened (healthy)
    assert sup.consecutive("realign") == 1      # untouched


def test_supervisor_site_breaker_trips_on_repeated_half_opens():
    # a healthy backend + one persistently-failing site: after
    # site_trip_limit exhausted windows that SITE's breaker opens while
    # the other sites keep their device path
    st = RunStats()
    sup = BatchSupervisor(
        _policy(max_retries=0, breaker_threshold=2, site_trip_limit=2),
        stats=st, stderr=io.StringIO(), probe=lambda: (True, ""))
    calls = []

    def bad():
        calls.append(1)
        raise RuntimeError("miscompiled")

    for _ in range(4):   # 2 windows of 2 failures -> 2 half-opens
        with pytest.raises(DeviceWorkFailed):
            sup.run("refine", bad)
    assert sup.site_breaker_open("refine")
    assert not sup.breaker_open                 # global stays closed
    # a site trip on a healthy backend must NOT fire the operators'
    # dead-backend alarm — it has its own counter
    assert st.res_breaker_trips == 0
    assert st.res_site_breaker_trips == 1
    n = len(calls)
    # the tripped site degrades without touching the device...
    assert sup.run("refine", bad, fallback=lambda: "host") == "host"
    assert len(calls) == n
    # ...while other sites still run on device
    assert sup.run("consensus", lambda: "dev") == "dev"


def test_supervisor_fallback_fail_policy_is_fatal():
    sup = BatchSupervisor(_policy(max_retries=0, fallback="fail"),
                          stderr=io.StringIO())
    with pytest.raises(ResilienceError) as ei:
        sup.run("s", lambda: (_ for _ in ()).throw(RuntimeError("x")),
                fallback=lambda: "host")   # policy beats the fallback
    assert ei.value.exit_code == 1


# ---------------------------------------------------------------------------
# guardrails: domain checks + conservation laws
# ---------------------------------------------------------------------------
def test_guardrail_consensus_conservation():
    from pwasm_tpu.resilience.guardrails import check_consensus

    rng = np.random.default_rng(0)
    pile = rng.integers(0, 7, (16, 64)).astype(np.int8)
    counts = np.stack([(pile == k).sum(0) for k in range(6)],
                      axis=1).astype(np.int32)
    chars = np.full(64, ord("A"), dtype=np.int64)
    check_consensus(chars, counts, pile)     # clean passes
    bad = counts.copy()
    bad[5, 2] += 1                           # breaks conservation
    with pytest.raises(GuardrailViolation):
        check_consensus(chars, bad, pile)
    weird = chars.copy()
    weird[0] = ord("Z")                      # outside the alphabet
    with pytest.raises(GuardrailViolation):
        check_consensus(weird, counts, pile)


def test_guardrail_realign_conservation():
    from pwasm_tpu.ops.realign import banded_realign_rows
    from pwasm_tpu.resilience.guardrails import check_realign

    rng = np.random.default_rng(1)
    q = rng.integers(0, 4, 24).astype(np.int8)
    t = rng.integers(0, 4, 24).astype(np.int8)
    qs, ts = q[None, :], t[None, :]
    q_lens = np.array([24], dtype=np.int32)
    t_lens = np.array([24], dtype=np.int32)
    res = tuple(np.asarray(x) for x in banded_realign_rows(
        qs, ts, q_lens, t_lens, band=8))
    check_realign(*res, q_lens=q_lens, t_lens=t_lens, match_score=1)
    scores, leads, iy, ops, ok = [x.copy() for x in res]
    iy[0, 3] += 2                            # fake target consumption
    with pytest.raises(GuardrailViolation):
        check_realign(scores, leads, iy, ops, ok, q_lens=q_lens,
                      t_lens=t_lens, match_score=1)


def test_corrupted_outputs_always_caught_or_harmless():
    """Every corrupt/nan injection into a ctx_scan-shaped output dict is
    either rejected by the guardrail or lands outside the live rows the
    report reads — the no-silent-corruption property."""
    from pwasm_tpu.resilience.guardrails import check_ctx_scan

    n_events, pad = 4, 64
    host = {
        "aa": np.full(pad, ord("M"), dtype=np.uint8),
        "aapos": np.arange(pad, dtype=np.int32) % 7,
        "hpoly": np.zeros(pad, dtype=bool),
        "motif": np.ones(pad, dtype=np.int32),
        "stop_aapos": np.full(pad, -1, dtype=np.int32),
        "s_aapos": np.zeros((pad, 3), dtype=np.int32),
    }
    check_ctx_scan(host, n_events, ref_len=30, n_motifs=4,
                   skip_codan=False)
    caught = harmless = 0
    for seed in range(30):
        plan = parse_fault_spec(f"seed={seed},rate=1,kinds=corrupt")
        bad = plan.corrupt({k: v.copy() for k, v in host.items()},
                           "ctx_scan", "corrupt")
        changed = any((bad[k] != host[k]).any() for k in host)
        assert changed, "corrupt() must modify some array"
        try:
            check_ctx_scan(bad, n_events, ref_len=30, n_motifs=4,
                           skip_codan=False)
            # passed validation: the live prefix must be untouched
            for k in host:
                assert (np.asarray(bad[k])[:n_events]
                        == host[k][:n_events]).all(), k
            harmless += 1
        except GuardrailViolation:
            caught += 1
    assert caught > 0


# ---------------------------------------------------------------------------
# CLI end-to-end: the acceptance contract
# ---------------------------------------------------------------------------
def _corpus(tmp_path, n=24, qlen=120):
    rng = np.random.default_rng(3)
    q = "".join("ACGT"[i] for i in rng.integers(0, 4, qlen))
    lines = []
    for i in range(n):
        cut = 10 + int(rng.integers(0, qlen - 40))
        qb = q[cut]
        tb = "ACGT"[("ACGT".index(qb) + 1) % 4]
        ops = [("=", cut), ("*", tb, qb), ("=", 20), ("ins", "gg"),
               ("=", qlen - cut - 21)]
        lines.append(make_paf_line("q", q, f"asm{i}", "+", ops)[0])
    fa = tmp_path / "q.fa"
    write_fasta(str(fa), [("q", q.encode())])
    paf = tmp_path / "in.paf"
    paf.write_text("".join(ln + "\n" for ln in lines))
    return str(paf), str(fa)


def _cli(tmp_path, tag, extra, paf, fa):
    err = io.StringIO()
    rc = run([paf, "-r", fa, "-o", str(tmp_path / f"{tag}.dfa"),
              "-w", str(tmp_path / f"{tag}.mfa"), "--device=tpu",
              "--batch=2", f"--stats={tmp_path / f'{tag}.json'}"]
             + extra, stderr=err)
    return rc, err.getvalue()


def _outs(tmp_path, tag):
    return ((tmp_path / f"{tag}.dfa").read_bytes(),
            (tmp_path / f"{tag}.mfa").read_bytes())


def test_fault_injected_run_byte_identical(tmp_path, monkeypatch):
    """The acceptance gate: ~35% seeded raise/NaN/corrupt faults on the
    CPU-backend device pipeline — byte-identical report and MSA, with
    nonzero retries / fallbacks / guardrail_rejects / checkpoints in
    the --stats resilience block."""
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path)
    rc, _ = _cli(tmp_path, "ref", [], paf, fa)
    assert rc == 0
    rc, _ = _cli(tmp_path, "fi",
                 ["--inject-faults=seed=2,rate=0.35,"
                  "kinds=raise+nan+corrupt", "--max-retries=1"],
                 paf, fa)
    assert rc == 0
    assert _outs(tmp_path, "fi") == _outs(tmp_path, "ref")
    res = json.loads((tmp_path / "fi.json").read_text())["resilience"]
    assert res["injected_faults"] > 0
    assert res["retries"] > 0
    assert res["fallbacks"] > 0
    assert res["guardrail_rejects"] > 0
    assert res["checkpoints"] > 0
    # the clean run reports all-zero resilience counters
    ref = json.loads((tmp_path / "ref.json").read_text())["resilience"]
    assert ref["retries"] == ref["fallbacks"] == 0
    assert ref["injected_faults"] == 0


def test_fault_injected_hang_deadline_byte_identical(tmp_path,
                                                     monkeypatch):
    """The hang member of the fault mix: injected hangs outlive the
    --device-deadline, cost one timeout each, and the retried batches
    keep the output byte-identical."""
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path, n=16)
    rc, _ = _cli(tmp_path, "ref", [], paf, fa)   # warms the jit cache
    assert rc == 0
    # post-warm attempts take ~10 ms, so a 1 s deadline only ever trips
    # on the injected 3 s hangs (each drawn hang costs one deadline)
    rc, _ = _cli(tmp_path, "hg",
                 ["--device-deadline=1", "--max-retries=3",
                  "--inject-faults=seed=4,rate=0.25,kinds=hang,"
                  "hang_s=3"],
                 paf, fa)
    assert rc == 0
    assert _outs(tmp_path, "hg") == _outs(tmp_path, "ref")
    res = json.loads((tmp_path / "hg.json").read_text())["resilience"]
    assert res["deadline_timeouts"] > 0
    assert res["retries"] > 0


def test_fault_injected_realign_byte_identical(tmp_path, monkeypatch):
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path, n=12)
    rc, _ = _cli(tmp_path, "rref", ["--realign", "--batch=4"], paf, fa)
    assert rc == 0
    rc, _ = _cli(tmp_path, "rfi",
                 ["--realign", "--batch=4", "--max-retries=2",
                  "--inject-faults=seed=5,rate=0.4,"
                  "kinds=raise+nan+corrupt"], paf, fa)
    assert rc == 0
    assert _outs(tmp_path, "rfi") == _outs(tmp_path, "rref")
    res = json.loads((tmp_path / "rfi.json").read_text())["resilience"]
    assert res["injected_faults"] > 0


def test_kill_mid_batch_resumes_from_checkpoint(tmp_path, monkeypatch):
    """A run killed mid-batch leaves an atomic <report>.ckpt; --resume
    continues at the last completed batch: byte-identical final output,
    no duplicated report lines, and no re-emission of checkpointed
    records."""
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path)
    rc, _ = _cli(tmp_path, "ref", [], paf, fa)
    assert rc == 0
    with pytest.raises(InjectedKill):
        _cli(tmp_path, "k", ["--inject-faults=kill=8"], paf, fa)
    ckpt = tmp_path / "k.dfa.ckpt"
    assert ckpt.exists()
    ck = json.loads(ckpt.read_text())
    assert ck["records"] > 0
    assert ck["bytes"] == os.path.getsize(tmp_path / "k.dfa")
    rc, _ = _cli(tmp_path, "k", ["--resume"], paf, fa)
    assert rc == 0
    assert _outs(tmp_path, "k") == _outs(tmp_path, "ref")
    headers = [ln for ln in (tmp_path / "k.dfa").read_text().splitlines()
               if ln.startswith(">")]
    assert len(headers) == len(set(headers)) == 24
    stats = json.loads((tmp_path / "k.json").read_text())
    assert stats["resumed_past"] == ck["records"]
    assert not ckpt.exists()   # completed run retires its checkpoint


def test_fallback_fail_aborts_the_run(tmp_path, monkeypatch):
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path, n=6)
    err = io.StringIO()
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "f.dfa"),
              "--device=tpu", "--batch=2", "--fallback=fail",
              "--max-retries=0",
              "--inject-faults=seed=2,rate=1,kinds=raise"], stderr=err)
    assert rc == 1
    assert "--fallback=fail forbids degrading" in err.getvalue()


def test_resilience_flag_validation(tmp_path):
    paf, fa = _corpus(tmp_path, n=2)
    for bad in (["--max-retries=x"], ["--max-retries"],
                ["--device-deadline=0"], ["--device-deadline=x"],
                ["--device-deadline=nan"], ["--device-deadline=inf"],
                ["--fallback=maybe"], ["--inject-faults"],
                ["--inject-faults=rate=9"]):
        err = io.StringIO()
        assert run([paf, "-r", fa] + bad, stderr=err) == 1, bad
        assert "Invalid" in err.getvalue() or "requires" in err.getvalue()


def test_realign_supervised_degrades_to_oracle_with_counters():
    """Total device failure during supervised realign: every lane takes
    the bit-exact host oracle, and the degradation is visible (counted
    in res_fallbacks + warned) — not silent."""
    from pwasm_tpu.ops.realign import realign_pairs

    rng = np.random.default_rng(7)
    pairs = []
    for n in (20, 26, 31):
        q = bytes("".join("ACGT"[i] for i in rng.integers(0, 4, n)),
                  "ascii")
        t = bytearray(q)
        t[5] = ord("ACGT"["ACGT".index(chr(t[5])) - 1])
        pairs.append((q, bytes(t)))
    want = realign_pairs(pairs, band=8)
    st = RunStats()
    err = io.StringIO()
    sup = BatchSupervisor(
        _policy(max_retries=0), stats=st, stderr=err,
        faults=parse_fault_spec("seed=1,rate=1,kinds=raise"))
    got = realign_pairs(pairs, band=8, supervisor=sup)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert g is not None and w is not None
        assert g[0] == w[0]
        np.testing.assert_array_equal(g[1], w[1])
    assert st.res_fallbacks > 0
    assert "host oracle" in err.getvalue()


# ---------------------------------------------------------------------------
# many2many: supervised TPU→CPU degradation
# ---------------------------------------------------------------------------
def test_many2many_supervised_cpu_degradation():
    from pwasm_tpu.parallel.many2many import many2many_scores_ragged

    rng = np.random.default_rng(2)
    qs = ["".join("ACGT"[i] for i in rng.integers(0, 4, 40))
          for _ in range(3)]
    ts = ["".join("ACGT"[i] for i in rng.integers(0, 4, n))
          for n in (30, 45, 60)]
    want = many2many_scores_ragged(qs, ts, band=16)
    st = RunStats()
    # every first attempt raises; max_retries=0 → every bucket degrades
    # through the supervisor's cpu fallback, and scores stay identical
    plan = parse_fault_spec("seed=1,rate=1,kinds=raise")
    sup = BatchSupervisor(_policy(max_retries=0), stats=st, faults=plan,
                          stderr=io.StringIO())
    got = many2many_scores_ragged(qs, ts, band=16, supervisor=sup)
    np.testing.assert_array_equal(got, want)
    assert st.res_fallbacks > 0 and st.res_injected_faults > 0


def test_many2many_supervised_mesh_degrades_to_cpu_twin():
    """A SHARDED many2many under total device failure degrades through
    the mesh's CPU twin (cpu_like_mesh) — partitioning preserved, same
    integers."""
    import jax

    from pwasm_tpu.parallel.many2many import (make_mesh2d,
                                              many2many_scores_ragged)
    from pwasm_tpu.parallel.mesh import cpu_like_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual 8-device CPU mesh")
    mesh = make_mesh2d(4)
    assert cpu_like_mesh(mesh) is not None
    rng = np.random.default_rng(5)
    qs = ["".join("ACGT"[i] for i in rng.integers(0, 4, 32))
          for _ in range(4)]
    ts = ["".join("ACGT"[i] for i in rng.integers(0, 4, n))
          for n in (20, 30, 40, 28)]
    want = many2many_scores_ragged(qs, ts, band=16, mesh=mesh)
    st = RunStats()
    sup = BatchSupervisor(
        _policy(max_retries=0), stats=st, stderr=io.StringIO(),
        faults=parse_fault_spec("seed=1,rate=1,kinds=raise"))
    got = many2many_scores_ragged(qs, ts, band=16, mesh=mesh,
                                  supervisor=sup)
    np.testing.assert_array_equal(got, want)
    assert st.res_fallbacks > 0
