"""Self-monitoring serving (ISSUE 14): SLO engine, synthetic canary
probes, fleet health verdicts, log queries, exemplars.

Acceptance contracts:

- **the detection drill**: on a 3-member routed fleet with
  ``--canary-interval`` and the default rules, an injected outage on
  ONE member's serving path surfaces as a firing rule in the
  ROUTER's ``health`` verdict within two canary intervals, resolves
  after the member heals, and the firing→resolved transitions appear
  in the member's event log in order;
- **byte neutrality**: job outputs through a self-monitored fleet are
  byte-identical to a fleet running with the engine and canary off;
- **orchestrator probes**: ``pwasm-tpu health --exit-code`` answers
  0/1/2 for ok/degraded/failing;
- **the engine is declarative**: threshold (+ratio, +for_s), rate
  (windowed counter increase) and multi-window burn-rate rules over
  the live registry, with user rules merged by name from
  ``--slo-rules=FILE``.
"""

import io
import json
import os
import shutil
import sys
import tempfile
import threading
import time
from contextlib import contextmanager
from types import SimpleNamespace

import pytest

from pwasm_tpu.fleet.router import Router, route_main
from pwasm_tpu.obs.catalog import (build_canary_metrics,
                                   build_fleet_metrics,
                                   build_service_metrics,
                                   build_slo_metrics,
                                   default_fleet_slo_rules,
                                   default_slo_rules)
from pwasm_tpu.obs.logquery import query_log, record_matches
from pwasm_tpu.obs.metrics import MetricsRegistry
from pwasm_tpu.obs.slo import (SloEngine, load_rules_file,
                               merge_rules, parse_rules,
                               validate_rule, verdict_exit_code,
                               worst_verdict)
from pwasm_tpu.service.client import (ServiceClient, client_main,
                                      wait_for_socket)
from pwasm_tpu.service.daemon import Daemon, serve_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# engine units
# ---------------------------------------------------------------------------
def _engine(rules, reg=None):
    reg = reg or MetricsRegistry()
    return reg, SloEngine(reg, rules, metrics=build_slo_metrics(reg),
                          eval_interval_s=0.01)


def test_threshold_fire_resolve_and_transitions():
    reg = MetricsRegistry()
    g = reg.gauge("pwasm_test_depth", "h")
    events = []
    eng = SloEngine(
        reg,
        [{"name": "deep", "kind": "threshold",
          "metric": "pwasm_test_depth", "op": ">", "value": 5,
          "severity": "page"}],
        metrics=build_slo_metrics(reg),
        on_event=lambda ev, **f: events.append((ev, f)))
    # the firing gauge exists (0) before anything fires
    assert reg.get("pwasm_alerts_firing").value(rule="deep") == 0
    assert eng.evaluate()["verdict"] == "ok"
    g.set(9)
    v = eng.evaluate()
    assert v["verdict"] == "failing"
    (f,) = v["firing"]
    assert f["rule"] == "deep" and f["severity"] == "page"
    assert f["value"] == 9 and "pwasm_test_depth" in f["detail"]
    assert reg.get("pwasm_alerts_firing").value(rule="deep") == 1
    g.set(2)
    assert eng.evaluate()["verdict"] == "ok"
    t = reg.get("pwasm_alert_transitions_total")
    assert t.value(rule="deep", state="firing") == 1
    assert t.value(rule="deep", state="resolved") == 1
    assert [e for e, _ in events] == ["alert_firing",
                                      "alert_resolved"]


def test_threshold_ratio_and_labeled_any_cell():
    reg = MetricsRegistry()
    depth = reg.gauge("pwasm_test_client_depth", "h",
                      labels=("client",))
    quota = reg.gauge("pwasm_test_quota", "h")
    _, eng = _engine(
        [{"name": "pressure", "kind": "threshold",
          "metric": "pwasm_test_client_depth",
          "divide_by": "pwasm_test_quota", "op": ">", "value": 0.8}],
        reg)
    quota.set(10)
    depth.set(3, client="a")
    depth.set(4, client="b")
    assert eng.evaluate()["verdict"] == "ok"
    depth.set(9, client="b")      # one cell over: any-cell fires
    v = eng.evaluate()
    assert v["verdict"] == "degraded"
    assert "client=b" in v["firing"][0]["detail"]


def test_threshold_for_s_holds_before_firing():
    reg = MetricsRegistry()
    g = reg.gauge("pwasm_test_level", "h")
    _, eng = _engine(
        [{"name": "held", "kind": "threshold",
          "metric": "pwasm_test_level", "op": ">=", "value": 1,
          "for_s": 10.0}], reg)
    g.set(1)
    t0 = 1000.0
    assert eng.evaluate(now=t0)["verdict"] == "ok"       # pending
    assert eng.evaluate(now=t0 + 5)["verdict"] == "ok"   # still held
    assert eng.evaluate(now=t0 + 11)["verdict"] == "degraded"
    # a dip resets the hold clock
    g.set(0)
    assert eng.evaluate(now=t0 + 12)["verdict"] == "ok"
    g.set(1)
    assert eng.evaluate(now=t0 + 13)["verdict"] == "ok"
    assert eng.evaluate(now=t0 + 24)["verdict"] == "degraded"


def test_rate_rule_window_and_zero_baseline():
    reg = MetricsRegistry()
    c = reg.counter("pwasm_test_replays_total", "h")
    _, eng = _engine(
        [{"name": "replayed", "kind": "rate",
          "metric": "pwasm_test_replays_total", "op": ">",
          "value": 0, "window_s": 60.0, "baseline": "zero"}], reg)
    c.inc(1)            # a "startup replay" before the first sample
    t0 = 2000.0
    # baseline=zero: pre-engine history counts as an increase
    assert eng.evaluate(now=t0)["verdict"] == "degraded"
    # ...and resolves once the window slides past it
    assert eng.evaluate(now=t0 + 30)["verdict"] == "degraded"
    assert eng.evaluate(now=t0 + 61)["verdict"] == "ok"
    c.inc(1)            # a fresh increase re-fires
    assert eng.evaluate(now=t0 + 62)["verdict"] == "degraded"
    assert eng.evaluate(now=t0 + 130)["verdict"] == "ok"


def test_rate_rule_first_baseline_ignores_history():
    reg = MetricsRegistry()
    c = reg.counter("pwasm_test_drops_total", "h")
    c.inc(40)
    _, eng = _engine(
        [{"name": "drops", "kind": "rate",
          "metric": "pwasm_test_drops_total", "op": ">", "value": 0,
          "window_s": 60.0}], reg)
    t0 = 3000.0
    assert eng.evaluate(now=t0)["verdict"] == "ok"   # history invisible
    c.inc(1)
    assert eng.evaluate(now=t0 + 1)["verdict"] == "degraded"


def test_burn_rate_two_windows():
    reg = MetricsRegistry()
    h = reg.histogram("pwasm_test_wall_seconds", "h",
                      buckets=(0.1, 1.0, 10.0))
    _, eng = _engine(
        [{"name": "burn", "kind": "burn_rate",
          "metric": "pwasm_test_wall_seconds", "objective_s": 1.0,
          "budget": 0.10, "short_s": 60.0, "long_s": 300.0}], reg)
    t0 = 5000.0
    for _ in range(20):
        h.observe(0.05)
    assert eng.evaluate(now=t0)["verdict"] == "ok"
    # 50% of fresh observations above the 1s objective: both windows
    # over the 10% budget -> fires
    for _ in range(10):
        h.observe(5.0)
        h.observe(0.05)
    v = eng.evaluate(now=t0 + 10)
    assert v["verdict"] == "degraded"
    assert v["firing"][0]["rule"] == "burn"
    # the bleeding stops; the short window clears first and the rule
    # resolves even while the long window still remembers
    for _ in range(100):
        h.observe(0.05)
    assert eng.evaluate(now=t0 + 80)["verdict"] == "ok"


def test_no_data_rules_do_not_fire():
    _, eng = _engine(
        [{"name": "ghost", "kind": "threshold",
          "metric": "pwasm_not_registered", "op": ">", "value": 0},
         {"name": "ghost_rate", "kind": "rate",
          "metric": "pwasm_not_registered_total", "op": ">",
          "value": 0, "window_s": 10.0},
         {"name": "ghost_burn", "kind": "burn_rate",
          "metric": "pwasm_not_registered_seconds",
          "objective_s": 1.0, "budget": 0.1, "short_s": 5.0,
          "long_s": 10.0}])
    assert eng.evaluate()["verdict"] == "ok"


def test_rule_validation_errors():
    for bad, msg in (
            ({"name": "BadName", "metric": "m"}, "snake_case"),
            ({"name": "x", "metric": "m", "severity": "meh"},
             "severity"),
            ({"name": "x", "metric": "m", "kind": "wat"}, "kind"),
            ({"name": "x", "metric": "m", "op": "~="}, "op"),
            ({"name": "x", "metric": "m", "value": "9"}, "number"),
            ({"name": "x", "metric": "m", "value": 1,
              "surprise": 1}, "unknown field"),
            ({"name": "x", "kind": "burn_rate", "metric": "m",
              "objective_s": 1, "budget": 0.1, "short_s": 60,
              "long_s": 60}, "short_s"),
            ({"name": "x", "kind": "rate", "metric": "m",
              "value": 0, "baseline": "maybe"}, "baseline"),
    ):
        with pytest.raises(ValueError, match=msg):
            validate_rule(bad)
    with pytest.raises(ValueError, match="duplicate"):
        parse_rules([{"name": "a", "metric": "m", "value": 1},
                     {"name": "a", "metric": "m", "value": 2}])
    with pytest.raises(ValueError, match="JSON list"):
        parse_rules({"name": "a"})


def test_default_rule_sets_validate():
    # the shipped defaults must themselves pass the user-rule grammar
    assert len(parse_rules(default_slo_rules())) == 9
    assert len(parse_rules(default_fleet_slo_rules())) == 6


def test_merge_rules_overrides_by_name():
    merged = merge_rules(
        default_slo_rules(),
        parse_rules([{"name": "breaker_open", "kind": "threshold",
                      "metric": "pwasm_service_breaker_state",
                      "op": ">=", "value": 1, "severity": "warn"}]))
    assert len(merged) == len(default_slo_rules())
    override = [r for r in merged if r["name"] == "breaker_open"]
    assert override[0]["value"] == 1.0
    assert override[0]["severity"] == "warn"


def test_verdict_helpers():
    assert worst_verdict("ok", "ok") == "ok"
    assert worst_verdict("ok", "degraded") == "degraded"
    assert worst_verdict("degraded", "failing") == "failing"
    assert worst_verdict("ok", "garbled") == "degraded"
    assert worst_verdict() == "ok"
    assert [verdict_exit_code(v) for v in
            ("ok", "degraded", "failing", "???")] == [0, 1, 2, 1]


def test_load_rules_file(tmp_path):
    p = tmp_path / "rules.json"
    p.write_text(json.dumps([
        {"name": "my_rule", "kind": "threshold",
         "metric": "pwasm_service_queue_depth", "op": ">",
         "value": 3, "severity": "warn"}]))
    rules = load_rules_file(str(p))
    assert rules[0]["name"] == "my_rule"
    p.write_text("{not json")
    with pytest.raises(ValueError, match="not valid JSON"):
        load_rules_file(str(p))
    p.write_text(json.dumps([{"name": "x"}]))
    with pytest.raises(ValueError, match="metric"):
        load_rules_file(str(p))
    with pytest.raises(ValueError, match="cannot read"):
        load_rules_file(str(tmp_path / "absent.json"))


# ---------------------------------------------------------------------------
# exemplars (ISSUE 14 satellite)
# ---------------------------------------------------------------------------
def test_histogram_exemplars_in_exposition():
    reg = MetricsRegistry()
    h = reg.histogram("pwasm_test_x_seconds", "h",
                      buckets=(1.0, 10.0))
    h.observe(0.5)                       # no trace: plain line
    h.observe(0.7, trace_id="job-abc")   # latest traced obs wins
    h.observe(20.0, trace_id="job-inf")  # +Inf bucket exemplar
    # exemplars are OPT-IN: the default exposition stays pure
    # Prometheus 0.0.4 (a strict scraper/textfile collector would
    # reject the trailing '#')
    assert "# {" not in reg.expose()
    text = reg.expose(exemplars=True)
    lines = {l.split(" ", 1)[0].split("{")[0] + l[
        l.find("{"):l.find("}") + 1]: l
        for l in text.splitlines() if "_bucket" in l}
    b1 = lines['pwasm_test_x_seconds_bucket{le="1"}']
    assert b1.startswith('pwasm_test_x_seconds_bucket{le="1"} 2')
    assert '# {trace_id="job-abc"} 0.7' in b1
    binf = lines['pwasm_test_x_seconds_bucket{le="+Inf"}']
    assert '# {trace_id="job-inf"} 20' in binf
    # untraced families render exactly as before even when asked
    reg2 = MetricsRegistry()
    h2 = reg2.histogram("pwasm_test_y_seconds", "h", buckets=(1.0,))
    h2.observe(0.5)
    assert "# {" not in reg2.expose(exemplars=True)


# ---------------------------------------------------------------------------
# log queries (ISSUE 14 satellite)
# ---------------------------------------------------------------------------
def test_logquery_rotation_filters_and_limit(tmp_path):
    log = tmp_path / "ev.ndjson"
    old = [{"event": "job_admit", "run_id": "r1", "job_id": "j1",
            "trace_id": "t1"},
           {"event": "job_finish", "run_id": "r1", "job_id": "j1",
            "trace_id": "t1"}]
    new = [{"event": "job_admit", "run_id": "r2", "job_id": "j2",
            "trace_id": "t2"},
           {"event": "canary_fail", "run_id": "r2"},
           "NOT JSON AT ALL",
           {"event": "job_finish", "run_id": "r2", "job_id": "j2",
            "trace_id": "t2"}]
    (tmp_path / "ev.ndjson.1").write_text(
        "".join(json.dumps(r) + "\n" for r in old))
    log.write_text("".join(
        (r if isinstance(r, str) else json.dumps(r)) + "\n"
        for r in new))
    # rotation order: .1 generation first, torn lines skipped
    all_recs = query_log(str(log))
    assert [r["event"] for r in all_recs] == [
        "job_admit", "job_finish", "job_admit", "canary_fail",
        "job_finish"]
    assert [r["job_id"] for r in
            query_log(str(log), job_id="j1")] == ["j1", "j1"]
    assert [r["event"] for r in
            query_log(str(log), event="canary_fail")] \
        == ["canary_fail"]
    # trace filter matches run_id too (a run's own lines)
    assert len(query_log(str(log), trace_id="r2")) == 3
    assert len(query_log(str(log), trace_id="t2")) == 2
    # limit keeps the NEWEST matches
    assert [r["event"] for r in query_log(str(log), limit=2)] == [
        "canary_fail", "job_finish"]
    # a missing log is empty, not an error
    assert query_log(str(tmp_path / "nope.ndjson")) == []
    assert record_matches({"event": "e"},
                          trace_id=None, job_id=None, event=None)


# ---------------------------------------------------------------------------
# in-process daemon/fleet harness (stub runner — no jax, no corpus)
# ---------------------------------------------------------------------------
def _box_runner(box):
    """A controllable stub runner: writes ``box['body']`` to the -o
    path and answers ``box['rc']`` — flipping the box injects an
    outage on THIS daemon's serving path (bad bytes = canary digest
    drift; bad rc = canary failure), restoring it heals."""
    def runner(argv, stdout=None, stderr=None, warm=None, **kw):
        out = None
        for i, a in enumerate(argv):
            if a == "-o" and i + 1 < len(argv):
                out = argv[i + 1]
            elif a.startswith("-o") and len(a) > 2:
                out = a[2:]
        if out:
            try:
                with open(out, "wb") as f:
                    f.write(box.get("body", b"OK"))
            except OSError:
                pass
        sp = next((a.split("=", 1)[1] for a in argv
                   if a.startswith("--stats=")), None)
        if sp:
            with open(sp, "w") as f:
                json.dump({"wall_s": 0.001}, f)
        return box.get("rc", 0)
    return runner


@contextmanager
def _daemon(box=None, **kw):
    box = box if box is not None else {}
    d = tempfile.mkdtemp(prefix="pwslo")
    sock = os.path.join(d, os.path.basename(d) + ".sock")
    err = io.StringIO()
    dm = Daemon(sock, stderr=err, runner=_box_runner(box), **kw)
    rcbox: list = []
    t = threading.Thread(target=lambda: rcbox.append(dm.serve()),
                         daemon=True)
    t.start()
    assert wait_for_socket(sock, 15), err.getvalue()
    try:
        yield SimpleNamespace(daemon=dm, sock=sock, box=box, err=err,
                              dir=d, rc=rcbox)
    finally:
        if not dm.drain.requested:
            dm.drain.request("test teardown")
        t.join(20)
        shutil.rmtree(d, ignore_errors=True)


def _wait_canary_runs(sock, n=1, budget_s=15.0):
    """Block until the canary has completed >= n probes — the golden
    digest must be captured from a HEALTHY run before a test injects
    its outage."""
    deadline = time.monotonic() + budget_s
    runs = 0
    while time.monotonic() < deadline:
        with ServiceClient(sock) as c:
            runs = (c.health()["health"]["canary"]
                    or {}).get("runs", 0)
        if runs >= n:
            return True
        time.sleep(0.03)
    return False


def _wait_health(sock, want, budget_s=10.0):
    """Poll the health verb until the verdict is ``want``; returns
    (seconds waited, last health dict)."""
    t0 = time.monotonic()
    h = None
    while time.monotonic() - t0 < budget_s:
        with ServiceClient(sock) as c:
            h = c.health()["health"]
        if h["verdict"] == want:
            return time.monotonic() - t0, h
        time.sleep(0.03)
    return time.monotonic() - t0, h


# ---------------------------------------------------------------------------
# canary + health on one daemon
# ---------------------------------------------------------------------------
def test_canary_probes_and_health_verb():
    with _daemon(canary_interval_s=0.05) as h:
        deadline = time.monotonic() + 10
        health = None
        while time.monotonic() < deadline:
            with ServiceClient(h.sock) as c:
                health = c.health()["health"]
            if (health["canary"] or {}).get("runs", 0) >= 2:
                break
            time.sleep(0.03)
        assert health["verdict"] == "ok"
        can = health["canary"]
        assert can["runs"] >= 2 and can["fails"] == 0
        assert can["last_ok"] is True
        assert health["rules"] == 9          # the default set
        # canary runs never enter the job table or the journal
        assert h.daemon.jobs == {}
        # canary families are live
        with ServiceClient(h.sock) as c:
            plain = c.metrics()["metrics"]
            m = c.metrics(exemplars=True)["metrics"]
        assert "pwasm_canary_ok 1" in plain
        assert 'pwasm_canary_runs_total{outcome="ok"}' in plain
        # exemplars only on request (default stays strict 0.0.4);
        # the canary wall histogram carries probe exemplars
        assert "# {" not in plain
        assert '# {trace_id="canary-' in m


def test_canary_failure_fires_and_recloses():
    with _daemon(canary_interval_s=0.05) as h:
        assert _wait_canary_runs(h.sock)
        h.box["body"] = b"CORRUPTED"       # the injected outage
        waited, health = _wait_health(h.sock, "failing")
        assert health["verdict"] == "failing", health
        rules = [f["rule"] for f in health["firing"]]
        assert "canary_failing" in rules
        assert "digest drift" in health["canary"]["last_detail"]
        h.box["body"] = b"OK"              # heal
        _, health = _wait_health(h.sock, "ok")
        assert health["verdict"] == "ok", health
        t = h.daemon.registry.get("pwasm_alert_transitions_total")
        assert t.value(rule="canary_failing", state="firing") >= 1
        assert t.value(rule="canary_failing", state="resolved") >= 1


def test_canary_bad_rc_fires_too():
    with _daemon(canary_interval_s=0.05) as h:
        assert _wait_canary_runs(h.sock)
        h.box["rc"] = 3
        _, health = _wait_health(h.sock, "failing")
        assert "canary_failing" in [f["rule"] for f in
                                    health["firing"]]
        assert "exit 3" in health["canary"]["last_detail"]


def test_health_exit_code_matrix(tmp_path):
    # ok = 0
    with _daemon(canary_interval_s=0.05) as h:
        assert _wait_canary_runs(h.sock)
        out = io.StringIO()
        rc = client_main("health", [f"--socket={h.sock}",
                                    "--exit-code"], out,
                         io.StringIO())
        assert rc == 0
        doc = json.loads(out.getvalue())
        assert doc["verdict"] == "ok" and doc["canary"]["runs"] >= 1
        # without --exit-code the shell rc stays 0 regardless
        h.box["rc"] = 9
        _wait_health(h.sock, "failing")
        assert client_main("health", [f"--socket={h.sock}"],
                           io.StringIO(), io.StringIO()) == 0
        # failing = 2
        rc = client_main("health", [f"--socket={h.sock}",
                                    "--exit-code"], io.StringIO(),
                         io.StringIO())
        assert rc == 2
    # degraded = 1: a user warn rule that always fires
    rules = tmp_path / "r.json"
    rules.write_text(json.dumps([
        {"name": "always_warn", "kind": "threshold",
         "metric": "pwasm_service_max_queue", "op": ">=", "value": 1,
         "severity": "warn"}]))
    from pwasm_tpu.obs.slo import load_rules_file
    with _daemon(slo_rules=load_rules_file(str(rules))) as h:
        _wait_health(h.sock, "degraded")
        rc = client_main("health", [f"--socket={h.sock}",
                                    "--exit-code"], io.StringIO(),
                         io.StringIO())
        assert rc == 1


def test_slo_rules_off_disables_engine():
    with _daemon(slo_rules="off") as h:
        with ServiceClient(h.sock) as c:
            health = c.health()["health"]
        assert health["verdict"] == "ok" and health["rules"] == 0


def test_stats_and_top_carry_the_alerts_pane():
    from pwasm_tpu.service.top import render
    with _daemon(canary_interval_s=0.05) as h:
        assert _wait_canary_runs(h.sock)
        h.box["body"] = b"DRIFT"
        _wait_health(h.sock, "failing")
        with ServiceClient(h.sock) as c:
            st = c.stats()["stats"]
        assert st["health"]["verdict"] == "failing"
        frame = render(st)
        assert "ALERTS (failing)" in frame
        assert "canary_failing[page" in frame
        assert "canary: FAILING" in frame
    # and a healthy daemon renders the quiet pane
    frame = render({"health": {"verdict": "ok", "firing": []}})
    assert "ALERTS: none" in frame


def test_logs_verb_socket_and_validation(tmp_path):
    log = str(tmp_path / "svc.ndjson")
    with _daemon(log_json=log) as h:
        out = str(tmp_path / "o.dfa")
        with ServiceClient(h.sock) as c:
            jid = c.submit(["in.paf", "-o", out],
                           cwd=str(tmp_path))["job_id"]
            r = c.result(jid, timeout=30)
            assert r["rc"] == 0
            trace = r["job"]["trace_id"]
            resp = c.logs(trace_id=trace)
            assert resp["ok"]
            evs = [l["event"] for l in resp["lines"]]
            assert evs == ["job_admit", "job_start", "job_finish"]
            assert all(l["trace_id"] == trace for l in resp["lines"])
            # job filter + event filter
            assert [l["event"] for l in
                    c.logs(job_id=jid, event="job_finish")["lines"]] \
                == ["job_finish"]
            # bad limit is a bad_request, not a dead daemon
            bad = c.request({"cmd": "logs", "limit": 0})
            assert bad["error"] == "bad_request"
    # a daemon without --log-json says so
    with _daemon() as h:
        with ServiceClient(h.sock) as c:
            resp = c.logs()
        assert not resp["ok"] and "--log-json" in resp["detail"]


def test_logs_cli_file_mode(tmp_path):
    log = tmp_path / "ev.ndjson"
    log.write_text(json.dumps({"event": "canary_fail",
                               "run_id": "x"}) + "\n"
                   + json.dumps({"event": "canary_ok",
                                 "run_id": "x"}) + "\n")
    out = io.StringIO()
    rc = client_main("logs", [str(log), "--event=canary_fail"],
                     out, io.StringIO())
    assert rc == 0
    assert json.loads(out.getvalue())["event"] == "canary_fail"
    # no socket, no file -> usage
    err = io.StringIO()
    assert client_main("logs", ["--event=x"], io.StringIO(), err) != 0
    # missing file -> pointed error
    err = io.StringIO()
    assert client_main("logs", [str(tmp_path / "no.ndjson")],
                       io.StringIO(), err) != 0
    assert "no event log" in err.getvalue()


def test_serve_main_validates_selfmon_flags(tmp_path):
    err = io.StringIO()
    rc = serve_main([f"--socket={tmp_path}/s.sock",
                     "--canary-interval=0"], stderr=err)
    assert rc != 0 and "--canary-interval" in err.getvalue()
    err = io.StringIO()
    rc = serve_main([f"--socket={tmp_path}/s.sock",
                     "--canary-interval=nope"], stderr=err)
    assert rc != 0 and "--canary-interval" in err.getvalue()
    bad = tmp_path / "bad.json"
    bad.write_text("{")
    err = io.StringIO()
    rc = serve_main([f"--socket={tmp_path}/s.sock",
                     f"--slo-rules={bad}"], stderr=err)
    assert rc != 0 and "not valid JSON" in err.getvalue()


def test_route_main_validates_slo_rules(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps([{"name": "x"}]))
    err = io.StringIO()
    rc = route_main([f"--backends={tmp_path}/m.sock",
                     f"--socket={tmp_path}/r.sock",
                     f"--slo-rules={bad}"], stderr=err)
    assert rc != 0 and "metric" in err.getvalue()


# ---------------------------------------------------------------------------
# the detection drill (acceptance): 3-member fleet, one injected
# outage -> firing at member AND router within two canary intervals,
# resolved after heal, transitions in event-log order, bytes neutral
# ---------------------------------------------------------------------------
CANARY_S = 0.75


@contextmanager
def _fleet(n=3, canary=True, slo="defaults", member_logs=False,
           tmp=None):
    stack, members = [], []
    try:
        for i in range(n):
            kw = {}
            if canary:
                kw["canary_interval_s"] = CANARY_S
            if slo == "off":
                kw["slo_rules"] = "off"
            if member_logs:
                kw["log_json"] = os.path.join(tmp, f"m{i}.ndjson")
            cm = _daemon(**kw)
            stack.append(cm)
            members.append(cm.__enter__())
        rd = tempfile.mkdtemp(prefix="pwslort")
        rsock = os.path.join(rd, "router.sock")
        err = io.StringIO()
        r = Router([m.sock for m in members], socket_path=rsock,
                   stderr=err, poll_interval=0.1,
                   slo_rules="off" if slo == "off" else None)
        rcbox: list = []
        t = threading.Thread(target=lambda: rcbox.append(r.serve()),
                             daemon=True)
        t.start()
        assert wait_for_socket(rsock, 15), err.getvalue()
        try:
            yield SimpleNamespace(router=r, sock=rsock,
                                  members=members, err=err)
        finally:
            if not r.drain.requested:
                r.drain.request("test teardown")
            t.join(20)
            shutil.rmtree(rd, ignore_errors=True)
    finally:
        for cm in reversed(stack):
            cm.__exit__(None, None, None)


def test_fleet_outage_detection_drill(tmp_path):
    with _fleet(n=3, member_logs=True, tmp=str(tmp_path)) as f:
        victim = f.members[0]
        victim_name = os.path.basename(victim.sock)
        # (0) every member probes healthy; fleet verdict ok
        for m in f.members:
            waited, h = _wait_health(m.sock, "ok", budget_s=15)
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                with ServiceClient(m.sock) as c:
                    if (c.health()["health"]["canary"]
                            or {}).get("runs", 0) >= 1:
                        break
                time.sleep(0.05)
        _, h = _wait_health(f.sock, "ok", budget_s=15)
        assert h["verdict"] == "ok", h
        # (1) inject the outage on ONE member's serving path
        t_inject = time.monotonic()
        victim.box["body"] = b"WEDGED-LANE-GARBAGE"
        # (2) the MEMBER's own verdict fails...
        _, mh = _wait_health(victim.sock, "failing",
                             budget_s=2 * CANARY_S + 5)
        assert "canary_failing" in [x["rule"] for x in mh["firing"]]
        # (3) ...and the ROUTER surfaces it within two canary
        # intervals of the injection (the acceptance bound; the
        # budget below only caps the polling loop itself)
        deadline = time.monotonic() + 2 * CANARY_S + 10
        detected_at = None
        fh = None
        while time.monotonic() < deadline:
            with ServiceClient(f.sock) as c:
                fh = c.health()["health"]
            if fh["verdict"] == "failing":
                detected_at = time.monotonic()
                break
            time.sleep(0.05)
        assert detected_at is not None, fh
        detect_wall = detected_at - t_inject
        assert detect_wall <= 2 * CANARY_S, (
            f"detection took {detect_wall:.2f}s > two canary "
            f"intervals ({2 * CANARY_S:.2f}s)")
        assert fh["members"][victim_name]["verdict"] == "failing"
        assert "canary_failing" in fh["members"][victim_name][
            "firing"]
        # the siblings stay clean
        for m in f.members[1:]:
            name = os.path.basename(m.sock)
            assert fh["members"][name]["verdict"] == "ok", fh
        # (4) heal ("reclose"): the rule resolves at member and router
        victim.box["body"] = b"OK"
        _, mh = _wait_health(victim.sock, "ok",
                             budget_s=2 * CANARY_S + 10)
        assert mh["verdict"] == "ok", mh
        _, fh = _wait_health(f.sock, "ok", budget_s=2 * CANARY_S + 10)
        assert fh["verdict"] == "ok", fh
        # (5) transitions land in the member's event log IN ORDER:
        # canary_fail before alert_firing before canary_ok (healed)
        # before alert_resolved
        log = str(tmp_path / "m0.ndjson")
        evs = [r["event"] for r in query_log(log)]
        i_fail = evs.index("canary_fail")
        i_fire = evs.index("alert_firing")
        i_resolved = evs.index("alert_resolved")
        i_heal = next(i for i, e in enumerate(evs)
                      if e == "canary_ok" and i > i_fire)
        assert i_fail < i_fire < i_heal < i_resolved, evs
        firing_recs = query_log(log, event="alert_firing")
        assert firing_recs[0]["rule"] == "canary_failing"
        assert firing_recs[0]["severity"] == "page"


def test_selfmon_byte_parity_on_vs_off(tmp_path):
    """Job outputs through a self-monitored fleet (canary + engine
    on) are byte-identical to a fleet with self-monitoring off."""
    outs = {}
    for tag, canary, slo in (("on", True, "defaults"),
                             ("off", False, "off")):
        with _fleet(n=3, canary=canary, slo=slo) as f:
            body = b""
            for k in range(3):
                out = str(tmp_path / f"{tag}{k}.dfa")
                with ServiceClient(f.sock) as c:
                    r = c.result(c.submit(
                        ["in.paf", "-o", out],
                        cwd=str(tmp_path))["job_id"], timeout=60)
                assert r["rc"] == 0, r
                body += open(out, "rb").read()
            outs[tag] = body
    assert outs["on"] == outs["off"] and outs["on"]


def test_router_member_down_rule_and_fleet_health():
    with _fleet(n=2, canary=False) as f:
        _wait_health(f.sock, "ok", budget_s=15)
        # drain member 1 away: the router's own member_down rule
        # fires (page) and the fleet verdict fails without any
        # member's cooperation
        f.members[1].daemon.drain.request("die")
        deadline = time.monotonic() + 15
        fh = None
        while time.monotonic() < deadline:
            with ServiceClient(f.sock) as c:
                fh = c.health()["health"]
            if "member_down" in [x["rule"] for x in fh["firing"]]:
                break
            time.sleep(0.05)
        assert fh["verdict"] == "failing", fh
        name = os.path.basename(f.members[1].sock)
        assert fh["members"][name]["verdict"] == "unreachable"
        # failover_burst rides along once the failover pass ran
        t = f.router.registry.get("pwasm_alert_transitions_total")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if t.value(rule="failover_burst", state="firing") >= 1:
                break
            time.sleep(0.05)
        assert t.value(rule="failover_burst", state="firing") >= 1
        # fleet stats carry the health block; fleet-aware top shows it
        from pwasm_tpu.service.top import render
        with ServiceClient(f.sock) as c:
            st = c.stats()["stats"]
        assert st["health"]["verdict"] == "failing"
        assert "member_down[page" in render(st)


# ---------------------------------------------------------------------------
# qa gates (ISSUE 14 satellite)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def checker():
    for p in (REPO, os.path.join(REPO, "qa")):
        if p not in sys.path:
            sys.path.insert(0, p)
    import check_supervision
    return check_supervision


def test_slo_gate_clean_on_this_tree(checker):
    assert checker.find_slo_violations() == []


def test_slo_gate_detects_jax_and_absence(checker, tmp_path):
    (tmp_path / "pwasm_tpu" / "obs").mkdir(parents=True)
    (tmp_path / "pwasm_tpu" / "obs" / "slo.py").write_text(
        "import jax\n"
        "# import jax in a comment is NOT a hit\n"
        "y = jax.device_get(1)\n")
    bad = checker.find_slo_violations(str(tmp_path))
    assert sum("slo.py" in b and "jax" in b for b in bad) == 2
    assert any("canary.py" in b and "missing" in b for b in bad)


def test_rule_doc_drift_clean_and_detects(checker, tmp_path):
    # every shipped default rule name appears in the doc
    names = checker.catalog_rule_names()
    assert set(names) == {
        r["name"] for r in (default_slo_rules()
                            + default_fleet_slo_rules())}
    assert checker.find_doc_drift() == []
    # and the detector actually detects: a rules region naming a rule
    # the doc does not mention fails
    (tmp_path / "pwasm_tpu" / "obs").mkdir(parents=True)
    (tmp_path / "docs").mkdir()
    (tmp_path / "pwasm_tpu" / "obs" / "catalog.py").write_text(
        'a = reg.gauge("pwasm_fine_depth", "h")\n'
        f"# {checker.CATALOG_END_SENTINEL}\n"
        'RULES = ({"name": "documented_rule", "op": ">"},\n'
        '         {"name": "ghost_rule", "op": ">"})\n')
    (tmp_path / "docs" / "OBSERVABILITY.md").write_text(
        "| `pwasm_fine_depth` | fine |\n"
        "| `documented_rule` | fine |\n")
    bad = checker.find_doc_drift(str(tmp_path))
    assert len(bad) == 1 and "ghost_rule" in bad[0]
    # rule-region metric references are NOT registrations: a name
    # repeated below the sentinel must not trip the uniqueness lint
    (tmp_path / "pwasm_tpu" / "obs" / "catalog.py").write_text(
        'a = reg.gauge("pwasm_fine_depth", "h")\n'
        f"# {checker.CATALOG_END_SENTINEL}\n"
        'RULES = ({"name": "documented_rule", '
        '"metric": "pwasm_fine_depth"},)\n')
    assert checker.find_metric_lint(str(tmp_path)) == []
