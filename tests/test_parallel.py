"""Multi-chip sharding tests on the 8-virtual-device CPU mesh."""

import numpy as np

import jax
import jax.numpy as jnp

from pwasm_tpu.ops.consensus import consensus_votes
from pwasm_tpu.parallel.mesh import (
    make_mesh,
    make_pipeline_step,
    sharded_consensus,
)
from pwasm_tpu.ops.banded_dp import banded_scores_batch


def test_make_mesh_shapes():
    mesh = make_mesh(8)
    assert mesh.shape["batch"] * mesh.shape["depth"] == 8
    assert mesh.shape["depth"] == 2
    mesh4 = make_mesh(4)
    assert dict(mesh4.shape) == {"batch": 2, "depth": 2}


def test_make_mesh_explicit_devices():
    # the device-lease path: a leased job builds its mesh over EXACTLY
    # the device list it is handed (its lane's slice of jax.devices()),
    # not the global pool — both mesh factories take devices=
    from pwasm_tpu.parallel.many2many import make_mesh2d

    devs = jax.devices()
    lane = devs[2:6]
    mesh = make_mesh(devices=lane)
    assert set(np.asarray(mesh.devices).ravel()) == set(lane)
    assert mesh.shape["batch"] * mesh.shape["depth"] == 4
    mesh2d = make_mesh2d(devices=lane)
    assert set(np.asarray(mesh2d.devices).ravel()) == set(lane)
    assert mesh2d.shape["query"] * mesh2d.shape["target"] == 4
    # n_devices= still truncates an explicit list, like the global pool
    mesh2 = make_mesh(2, devices=lane)
    assert set(np.asarray(mesh2.devices).ravel()) == set(lane[:2])


def test_sharded_consensus_matches_single():
    mesh = make_mesh(8)
    rng = np.random.default_rng(0)
    bases = rng.integers(0, 7, size=(8, 128)).astype(np.int8)
    fn = sharded_consensus(mesh)
    votes = np.asarray(fn(jnp.asarray(bases)))
    np.testing.assert_array_equal(
        votes, np.asarray(consensus_votes(jnp.asarray(bases))))


def test_pipeline_step_matches_unsharded():
    mesh = make_mesh(8)
    nb = mesh.shape["batch"]
    rng = np.random.default_rng(1)
    m = 24
    q = rng.integers(0, 4, size=m).astype(np.int8)
    T = 4 * nb
    n = 40
    ts = np.full((T, n), 127, dtype=np.int8)
    t_lens = np.zeros(T, dtype=np.int32)
    for k in range(T):
        t = list(q)
        for _ in range(int(rng.integers(0, 3))):
            t[int(rng.integers(0, len(t)))] = int(rng.integers(0, 4))
        ts[k, :len(t)] = t
        t_lens[k] = len(t)
    pileup = rng.integers(0, 7, size=(8, 32 * nb)).astype(np.int8)
    step = make_pipeline_step(mesh, band=32)
    scores, votes = step(jnp.asarray(q), jnp.asarray(ts),
                         jnp.asarray(t_lens), jnp.asarray(pileup))
    ref_scores = np.asarray(banded_scores_batch(
        jnp.asarray(q), jnp.asarray(ts), jnp.asarray(t_lens), band=32))
    np.testing.assert_array_equal(np.asarray(scores), ref_scores)
    np.testing.assert_array_equal(
        np.asarray(votes),
        np.asarray(consensus_votes(jnp.asarray(pileup))))


def test_graft_entry_and_dryrun():
    import __graft_entry__ as g

    fn, args = g.entry()
    scores, votes = fn(*args)
    assert scores.shape[0] == args[1].shape[0]
    assert votes.shape[0] == args[3].shape[1]
    g.dryrun_multichip(len(jax.devices()))
    g.dryrun_multichip(4)


def _dp_workload(rng, m, T, n):
    q = rng.integers(0, 4, size=m).astype(np.int8)
    ts = np.full((T, n), 127, dtype=np.int8)
    tl = np.zeros(T, dtype=np.int32)
    for k in range(T):
        t = list(q)
        for _ in range(int(rng.integers(0, 6))):
            t[int(rng.integers(0, len(t)))] = int(rng.integers(0, 4))
        for _ in range(int(rng.integers(0, 4))):
            p = int(rng.integers(1, len(t) - 1))
            if rng.random() < 0.5:
                t.insert(p, int(rng.integers(0, 4)))
            else:
                del t[p]
        ts[k, :len(t)] = t
        tl[k] = len(t)
    return q, ts, tl


def test_wavefront_sp_matches_batch():
    """Sequence-parallel pipelined DP (query rows sharded over 8 devices,
    ppermute halo exchange) is bit-exact vs the single-device batch."""
    from jax.sharding import Mesh
    from pwasm_tpu.parallel.wavefront_sp import make_wavefront_sp

    devs = np.asarray(jax.devices()[:8])
    mesh = Mesh(devs, ("seq",))
    rng = np.random.default_rng(7)
    m, T, n, band = 64, 11, 96, 64
    q, ts, tl = _dp_workload(rng, m, T, n)
    fn = make_wavefront_sp(mesh, m, n, T, band=band)
    sp = np.asarray(fn(jnp.asarray(q), jnp.asarray(ts), jnp.asarray(tl)))
    ref = np.asarray(banded_scores_batch(jnp.asarray(q), jnp.asarray(ts),
                                         jnp.asarray(tl), band=band))
    np.testing.assert_array_equal(sp, ref)


def test_wavefront_sp_rejects_indivisible():
    from jax.sharding import Mesh
    import pytest
    from pwasm_tpu.parallel.wavefront_sp import make_wavefront_sp

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("seq",))
    with pytest.raises(ValueError, match="must divide"):
        make_wavefront_sp(mesh, 30, 64, 4)


def _m2m_workload(Q, T, m, n, seed=0):
    rng = np.random.default_rng(seed)
    qs = rng.integers(0, 4, size=(Q, m)).astype(np.int8)
    ts = np.full((T, n), 127, dtype=np.int8)
    t_lens = np.zeros(T, dtype=np.int32)
    for k in range(T):
        t = list(qs[k % Q])
        for _ in range(int(rng.integers(0, 4))):
            t[int(rng.integers(0, len(t)))] = int(rng.integers(0, 4))
        if rng.random() < 0.5 and len(t) > 2:
            del t[int(rng.integers(1, len(t) - 1))]
        ts[k, :len(t)] = t
        t_lens[k] = len(t)
    return qs, ts, t_lens


def test_many2many_mesh_matches_unsharded():
    from pwasm_tpu.parallel.many2many import (make_many2many, make_mesh2d,
                                              many2many_scores)

    mesh = make_mesh2d(8)
    assert mesh.shape["query"] * mesh.shape["target"] == 8
    nq, nt = mesh.shape["query"], mesh.shape["target"]
    Q, T, m, n = 2 * nq, 4 * nt, 24, 32
    qs, ts, t_lens = _m2m_workload(Q, T, m, n)
    fn = make_many2many(mesh, band=16)
    got = np.asarray(fn(jnp.asarray(qs), jnp.asarray(ts),
                        jnp.asarray(t_lens)))
    expect = np.asarray(many2many_scores(jnp.asarray(qs), jnp.asarray(ts),
                                         jnp.asarray(t_lens), band=16))
    assert got.shape == (Q, T)
    np.testing.assert_array_equal(got, expect)


def test_many2many_pallas_kernel_matches():
    from pwasm_tpu.parallel.many2many import make_many2many, make_mesh2d

    mesh = make_mesh2d(4)
    nq, nt = mesh.shape["query"], mesh.shape["target"]
    Q, T, m, n = nq, 2 * nt, 16, 24
    qs, ts, t_lens = _m2m_workload(Q, T, m, n, seed=3)
    xla = make_many2many(mesh, band=16, kernel="xla")
    pal = make_many2many(mesh, band=16, kernel="pallas")
    a = np.asarray(xla(jnp.asarray(qs), jnp.asarray(ts),
                       jnp.asarray(t_lens)))
    b = np.asarray(pal(jnp.asarray(qs), jnp.asarray(ts),
                       jnp.asarray(t_lens)))
    np.testing.assert_array_equal(a, b)


def test_many2many_scores_pallas_sequential_matches():
    # the lax.map-over-queries single-chip path (bench config #3) must be
    # bit-exact with the vmapped scan reference
    from pwasm_tpu.parallel.many2many import (many2many_scores,
                                              many2many_scores_pallas)

    Q, T, m, n = 5, 12, 20, 28
    qs, ts, t_lens = _m2m_workload(Q, T, m, n, seed=9)
    a = np.asarray(many2many_scores(jnp.asarray(qs), jnp.asarray(ts),
                                    jnp.asarray(t_lens), band=16))
    b = np.asarray(many2many_scores_pallas(jnp.asarray(qs),
                                           jnp.asarray(ts),
                                           jnp.asarray(t_lens), band=16))
    np.testing.assert_array_equal(a, b)


def test_multislice_step_matches_single_device():
    # 2 DCN slices x (2 batch x 2 depth) ICI mesh: results must be
    # bit-exact with the unsharded path and with the single-slice step
    from pwasm_tpu.ops.banded_dp import banded_scores_batch
    from pwasm_tpu.ops.consensus import consensus_votes
    from pwasm_tpu.parallel.mesh import (make_multislice_mesh,
                                         make_multislice_step)

    mesh = make_multislice_mesh(2, 8)
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
        "slice": 2, "batch": 2, "depth": 2}
    rng = np.random.default_rng(17)
    m, T, n, depth, cols = 24, 8, 32, 4, 16
    q = rng.integers(0, 4, size=m).astype(np.int8)
    ts = np.full((T, n), 127, dtype=np.int8)
    tl = np.zeros(T, dtype=np.int32)
    for k in range(T):
        L = int(rng.integers(m - 2, n + 1))
        ts[k, :L] = rng.integers(0, 4, size=L)
        tl[k] = L
    pileup = rng.integers(0, 6, size=(depth, cols)).astype(np.int8)
    step = make_multislice_step(mesh, band=16)
    scores, votes = step(jnp.asarray(q), jnp.asarray(ts), jnp.asarray(tl),
                         jnp.asarray(pileup))
    np.testing.assert_array_equal(
        np.asarray(scores),
        np.asarray(banded_scores_batch(jnp.asarray(q), jnp.asarray(ts),
                                       jnp.asarray(tl), band=16)))
    np.testing.assert_array_equal(
        np.asarray(votes), np.asarray(consensus_votes(jnp.asarray(pileup))))
