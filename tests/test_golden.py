"""Golden byte-lock tests (SURVEY.md §4 test strategy).

The committed files under tests/golden/ lock the byte format of every
writer — the .dfa diff report (pafreport.cpp:885-955 equivalent), the -s
summary (the reference's vestigial flag, SURVEY.md §2.5.1), the -w
multifasta MSA (GapAssem.cpp:482-520,1039-1046), and the consensus-path
ACE/info/cons outputs (GapAssem.cpp:1200-1367).  The suite regenerates
all six through the real CLI into a temp dir and byte-compares; any
one-byte drift in a writer fails here.  Regenerate intentionally with:
    python tests/golden/gen.py
"""

import importlib.util
import os

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
GOLDEN = os.path.join(HERE, "golden")

_spec = importlib.util.spec_from_file_location(
    "golden_gen", os.path.join(GOLDEN, "gen.py"))
_gen = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_gen)


@pytest.fixture(scope="module")
def regenerated(tmp_path_factory):
    outdir = tmp_path_factory.mktemp("golden_regen")
    names = _gen.generate(str(outdir))
    return outdir, names


def test_golden_files_committed_nonempty():
    for name in ("report.dfa", "summary.txt", "msa.mfa", "contig.ace",
                 "contig.info", "cons.fa"):
        path = os.path.join(GOLDEN, name)
        assert os.path.exists(path), f"missing golden file {name}"
        assert os.path.getsize(path) > 0, f"empty golden file {name}"


@pytest.mark.parametrize("name", ["report.dfa", "summary.txt", "msa.mfa",
                                  "contig.ace", "contig.info", "cons.fa"])
def test_golden_byte_lock(regenerated, name):
    outdir, names = regenerated
    assert name in names
    with open(os.path.join(GOLDEN, name), "rb") as f:
        want = f.read()
    with open(os.path.join(str(outdir), name), "rb") as f:
        got = f.read()
    assert got == want, (
        f"{name} drifted from the committed golden copy "
        f"({len(got)} vs {len(want)} bytes); if the change is "
        f"intentional, regenerate with `python tests/golden/gen.py`")
