"""Device context/codon scan: byte parity against the scalar host
analysis over randomized alignments, plus targeted unit checks."""

import numpy as np
import pytest

import jax.numpy as jnp

from pwasm_tpu.core.dna import encode, revcomp
from pwasm_tpu.core.events import extract_alignment
from pwasm_tpu.core.paf import parse_paf_line
from pwasm_tpu.ops.ctx_scan import (
    ctx_scan,
    motif_hits,
    pack_events,
    pack_motifs,
    ref_context_windows,
)
from pwasm_tpu.report.device_report import analyze_events_device
from pwasm_tpu.report.diff_report import (
    analyze_event_host,
    format_event_row,
    get_ref_context,
)

from helpers import make_paf_line
from test_events import _random_ops


def _events_for(q, line):
    rec = parse_paf_line(line)
    refseq_aln = revcomp(q) if rec.alninfo.reverse else q
    return extract_alignment(rec, refseq_aln).tdiffs


def test_ref_context_windows_match_host():
    q = b"ATGGCCTGGAAAGATCTGTACCTGA"
    rlocs = list(range(len(q)))
    win, loc = ref_context_windows(jnp.asarray(encode(q)),
                                   jnp.int32(len(q)),
                                   jnp.asarray(np.array(rlocs)))
    for i, r in enumerate(rlocs):
        rctx, evtloc = get_ref_context(q, r)
        assert bytes(b"ACGTN-"[c] for c in np.asarray(win[i])) == rctx
        assert int(loc[i]) == evtloc, r


def test_motif_hits_first_wins():
    q = b"CCTGGGATC"  # contains motif 1 (CCTGG) and motif 3 (GATC)
    win = jnp.asarray(encode(q))[None, :]
    codes, lens = pack_motifs(("CCTGG", "CCAGG", "GATC", "GTAC"))
    assert int(motif_hits(win, codes, lens)[0]) == 1
    win2 = jnp.asarray(encode(b"AAAGATCAA"))[None, :]
    assert int(motif_hits(win2, codes, lens)[0]) == 3
    win3 = jnp.asarray(encode(b"AAAAAAAAA"))[None, :]
    assert int(motif_hits(win3, codes, lens)[0]) == 0


@pytest.mark.parametrize("strand", ["+", "-"])
@pytest.mark.parametrize("seed", range(6))
def test_device_analysis_matches_host(strand, seed):
    rng = np.random.default_rng(200 + seed)
    n = int(rng.integers(60, 150))
    q = "".join(rng.choice(list("ACGT"), size=n)).encode()
    ops = _random_ops(rng, q.decode() if strand == "+" else
                      revcomp(q).decode())
    line, _ = make_paf_line("q", q.decode(), "t", strand, ops)
    events = _events_for(q, line)
    if not events:
        pytest.skip("no events generated")
    import copy
    ev_host = copy.deepcopy(events)
    ev_dev = copy.deepcopy(events)
    host_rows = []
    for di in ev_host:
        aa, aapos, rctx, status, impact = analyze_event_host(
            di, q, skip_codan=False)
        host_rows.append(format_event_row(di, aa, aapos, rctx, status,
                                          impact))
    dev = analyze_events_device(q, ev_dev, skip_codan=False)
    dev_rows = [format_event_row(di, *res)
                for di, res in zip(ev_dev, dev)]
    assert dev_rows == host_rows


def test_device_analysis_skip_codan():
    q = b"ATGGCCTGGAAAGATCTGTACCTGA"
    line = ("geneA\t25\t0\t25\t+\tasm1\t23\t0\t23\t23\t25\t60\t"
            "NM:i:3\tAS:i:40\tcg:Z:12M2I11M\tcs:Z::6*ct:5+at:11")
    events = _events_for(q, line)
    res = analyze_events_device(q, events, skip_codan=True)
    assert all(r[4] == "" for r in res)
    assert res[0][3] == "motif CCTGG"


def test_device_analysis_long_event_fallback():
    # a 20-base deletion exceeds MAX_EV=16 -> scalar fallback, same result
    q = bytes(b"ACGT" * 20)
    ins = "acgt" * 5
    line, _ = make_paf_line("q", q.decode(), "t", "+",
                            [("=", 30), ("ins", ins), ("=", 50)])
    events = _events_for(q, line)
    import copy
    ev_host = copy.deepcopy(events)
    host = [analyze_event_host(di, q, False) for di in ev_host]
    dev = analyze_events_device(q, events, False)
    assert dev == host


def test_premature_stop_parity():
    q = b"ATGGCCTGGAAAGATCTGTACCTGA"
    # G->A at rloc 8 turns TGG (W) into TGA (stop)
    line = ("geneA\t25\t0\t25\t+\tasm1\t25\t0\t25\t25\t25\t60\t"
            "NM:i:1\tAS:i:44\tcg:Z:25M\tcs:Z::8*ag:16")
    events = _events_for(q, line)
    res = analyze_events_device(q, events, skip_codan=False)
    assert res[0][4] == "AA3|W:.|premature stop at AA3"
