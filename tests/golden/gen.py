"""Regenerate the golden outputs (run from repo root):
    python tests/golden/gen.py
Inputs are deterministic; outputs lock the report/MSA/ACE/info/cons
byte formats across refactors (SURVEY.md §4 golden-file strategy).
"""
import io
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
if __name__ == "__main__":  # pytest already puts tests/ + rootdir on path
    sys.path.insert(0, os.path.dirname(os.path.dirname(HERE)))
    sys.path.insert(0, os.path.dirname(HERE))  # tests/ for helpers

from helpers import make_paf_line  # noqa: E402

from pwasm_tpu.cli import run  # noqa: E402

QSEQ = "ATGGCCTGGACGTACGATCAAGGTCCTGGAGATCTTT"


def lines():
    return [
        make_paf_line("q", QSEQ, "a1", "+",
                      [("=", 4), ("*", "a", "c"), ("=", 32)])[0],
        make_paf_line("q", QSEQ, "a2", "+",
                      [("=", 6), ("ins", "gg"), ("=", 31)])[0],
        make_paf_line("q", QSEQ, "a3", "-",
                      [("=", 10), ("del", 2), ("=", 25)])[0],
        make_paf_line("q", QSEQ, "a4", "-",
                      [("=", 3), ("*", "a", "g"), ("=", 33)])[0],
        make_paf_line("q", QSEQ, "a5", "+",
                      [("=", 8), ("*", "c", "g"), ("*", "t", "a"),
                       ("=", 27)])[0],
    ]


def generate(outdir):
    fa = os.path.join(outdir, "q.fa")
    with open(fa, "w") as f:
        f.write(f">q\n{QSEQ}\n")
    paf = os.path.join(outdir, "in.paf")
    with open(paf, "w") as f:
        f.write("".join(ln + "\n" for ln in lines()))
    args = [paf, "-r", fa,
            "-o", os.path.join(outdir, "report.dfa"),
            "-s", os.path.join(outdir, "summary.txt"),
            "-w", os.path.join(outdir, "msa.mfa"),
            "--ace=" + os.path.join(outdir, "contig.ace"),
            "--info=" + os.path.join(outdir, "contig.info"),
            "--cons=" + os.path.join(outdir, "cons.fa")]
    err = io.StringIO()
    rc = run(args, stderr=err)
    assert rc == 0, f"cli rc={rc}: {err.getvalue()}"

    return ["report.dfa", "summary.txt", "msa.mfa", "contig.ace",
            "contig.info", "cons.fa"]


if __name__ == "__main__":
    names = generate(HERE)
    print("golden outputs written:", ", ".join(names))
