"""Protocol fuzz smoke (ISSUE 19 tentpole): the bounded tier-1 slice
of ``qa/protocol_fuzz.py``.

Contracts held here, per transport (unix AND tcp, daemon AND router):

- **survival**: >=500 seeded mutations (bit flips, truncations,
  length lies, NUL/UTF-8-invalid garbage, JSON bombs, pipelined
  batches) against a live accept loop — control pings answer
  throughout, and the fd/thread census returns to baseline (no
  leaks);
- **truthful rejection**: every in-band answer carries a documented
  ``ERR_*`` code — the fuzzer asserts this internally per response;
- **bounded memory** (ISSUE 19 satellite): a never-terminated
  multi-MiB line cannot balloon the server — ``read_frame`` buffers
  at most ``max_frame_bytes + 1`` before answering
  ``frame_too_large`` and closing, measured here as an RSS delta
  bound while streaming far more than the ceiling;
- **slow-loris**: parked half-frame connections cost threads, never
  the accept loop.

The long campaign lives in ``qa/fleet_chaos.py --fuzz``.
"""

import io
import json
import os
import shutil
import socket
import sys
import tempfile
import threading

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "qa"))
from protocol_fuzz import (fuzz_target, ping_ok,  # noqa: E402
                           slow_loris_drill)

from pwasm_tpu.fleet.router import Router  # noqa: E402
from pwasm_tpu.fleet.transport import connect  # noqa: E402
from pwasm_tpu.service import protocol  # noqa: E402
from pwasm_tpu.service.client import wait_for_socket  # noqa: E402

from test_fleet import _daemon, _stub_runner  # noqa: E402

# a small ceiling makes length-lie mutations (and the bounded-memory
# drill) cheap without changing the code path they exercise
CEILING = 4096


def test_fuzz_daemon_both_transports():
    """>=500 mutations per transport against one live daemon; the
    fuzzer raises on any survival-contract breach (crash, hang,
    undocumented code, fd/thread leak)."""
    with _daemon(runner=_stub_runner(), listen="127.0.0.1:0",
                 max_frame_bytes=CEILING) as h:
        s1 = fuzz_target(h.sock, n=500, seed=11, ceiling=CEILING)
        s2 = fuzz_target(f"127.0.0.1:{h.daemon.tcp_port}", n=500,
                         seed=12, ceiling=CEILING)
    for s in (s1, s2):
        assert s["responses"] > 0 and s["control_pings"] > 0
        # the rejection vocabulary actually fired (not all closes)
        assert s["codes"].get("bad_json", 0) > 0
        assert s["codes"].get("frame_too_large", 0) > 0


def test_fuzz_router_and_slow_loris():
    with _daemon(runner=_stub_runner()) as m:
        rdir = tempfile.mkdtemp(prefix="pwfz")
        rsock = os.path.join(rdir, "router.sock")
        err = io.StringIO()
        r = Router([m.sock], socket_path=rsock, stderr=err,
                   poll_interval=0.1, max_frame_bytes=CEILING)
        t = threading.Thread(target=r.serve, daemon=True)
        t.start()
        try:
            assert wait_for_socket(rsock, 15), err.getvalue()
            s = fuzz_target(rsock, n=500, seed=13, ceiling=CEILING)
            assert s["responses"] > 0 and s["control_pings"] > 0
            assert s["codes"].get("frame_too_large", 0) > 0
            loris = slow_loris_drill(rsock, holders=4, hold_s=0.3)
            assert loris["alive_during_hold"]
            assert loris["alive_after_hold"]
        finally:
            r.drain.request("test done")
            t.join(20)
            shutil.rmtree(rdir, ignore_errors=True)


def _rss_bytes() -> int:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) * 1024
    raise RuntimeError("no VmRSS")


def test_never_terminated_line_bounded_memory():
    """ISSUE 19 satellite: a client streaming a newline-free line far
    past the frame ceiling costs the server AT MOST ceiling+1 bytes
    of buffer — the connection answers frame_too_large (or closes
    loudly mid-stream) and the process RSS moves by a bounded amount,
    not by the bytes sent."""
    ceiling = 1 << 20                       # 1 MiB ceiling
    send_total = 64 << 20                   # stream 64x past it
    with _daemon(runner=_stub_runner(), listen="127.0.0.1:0",
                 max_frame_bytes=ceiling) as h:
        before = _rss_bytes()
        conn = connect(f"127.0.0.1:{h.daemon.tcp_port}", timeout=10)
        chunk = b"A" * (1 << 20)
        sent = 0
        closed_early = False
        try:
            while sent < send_total:
                try:
                    conn.sendall(chunk)
                except OSError:
                    closed_early = True     # server hung up: loud
                    break
                sent += len(chunk)
            if not closed_early:
                conn.settimeout(10)
                try:
                    line = conn.makefile("rb").readline(1 << 16)
                except OSError:
                    line = b""
                if line:
                    resp = json.loads(line)
                    assert resp["error"] == \
                        protocol.ERR_FRAME_TOO_LARGE, resp
        finally:
            try:
                conn.close()
            except OSError:
                pass
        after = _rss_bytes()
        # the server buffered <= ceiling+1; anything near the 64 MiB
        # sent means readline stopped honouring its bound.  32 MiB of
        # slack absorbs allocator noise from the rest of the process.
        assert after - before < 32 << 20, \
            f"RSS grew {after - before} bytes on a {sent}-byte line"
        # the daemon survived and still serves
        assert ping_ok(h.sock)


def test_json_bomb_answered_in_band():
    """Regression for the fuzzer-found RecursionError: a deeply
    nested JSON frame answers bad_json on the wire instead of
    killing the connection thread with a traceback."""
    bomb = b'{"cmd":"ping","b":' + b"[" * 4000 + b"0" \
        + b"]" * 4000 + b"}\n"
    rf = io.BytesIO(bomb)
    with pytest.raises(protocol.FrameError) as ei:
        protocol.read_frame(rf)
    assert ei.value.code == protocol.ERR_BAD_JSON
    assert not ei.value.fatal               # next line = fresh frame
    with _daemon(runner=_stub_runner()) as h:
        conn = connect(h.sock, timeout=10)
        try:
            conn.sendall(bomb + b'{"cmd":"ping"}\n')
            rfile = conn.makefile("rb")
            first = json.loads(rfile.readline(1 << 16))
            assert first["ok"] is False
            assert first["error"] == protocol.ERR_BAD_JSON
            second = json.loads(rfile.readline(1 << 16))
            assert second["ok"] is True     # line-sync survived
        finally:
            conn.close()
