"""Byte-parity tests: the standalone C++ ``pafreport`` binary vs the
Python CLI's CPU path.

The native binary (pwasm_tpu/native/pafreport_main.cpp) is the SURVEY.md
§2.4.7-8 / §7.3 deliverable — a pure-C++ ``--device=cpu`` CLI whose
report (-o), summary (-s), warning stderr and exit codes must match the
Python CLI exactly (which is itself golden-locked against the reference
behavior spec, reference pafreport.cpp:175-460,721-955)."""

import io
import json
import os
import random
import subprocess

import pytest

from pwasm_tpu.cli import run
from pwasm_tpu.core.fasta import write_fasta
from pwasm_tpu.native import native_cli_path

from helpers import make_paf_line

_BIN: list = []  # lazily resolved so collection never triggers a compile


@pytest.fixture(autouse=True)
def _require_native_bin():
    if not _BIN:
        _BIN.append(native_cli_path())
    if _BIN[0] is None:
        pytest.skip("native toolchain unavailable")


def _run_py(args):
    from pwasm_tpu.core.errors import PwasmError

    out, err = io.StringIO(), io.StringIO()
    try:
        rc = run(args, stdout=out, stderr=err)
    except PwasmError as e:  # pre-run CliErrors propagate; main() catches
        err.write(str(e))
        rc = e.exit_code
    return rc, out.getvalue(), err.getvalue()


def _run_py_subproc(args):
    """Run the Python CLI in a subprocess — needed when the compared
    output goes to the real sys.stderr (clipmax/softclip messages)."""
    import sys
    res = subprocess.run(
        [sys.executable, "-m", "pwasm_tpu.cli"] + args,
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return res.returncode, res.stdout, res.stderr


def _run_native(args):
    res = subprocess.run([_BIN[0]] + args, capture_output=True, text=True)
    return res.returncode, res.stdout, res.stderr


def _assert_parity(tmp_path, args, compare_stderr=True):
    """Run both CLIs with -o/-s file outputs redirected per side; compare
    report/summary bytes, stderr and exit code."""
    py_rep, py_sum = tmp_path / "py.dfa", tmp_path / "py.sum"
    na_rep, na_sum = tmp_path / "na.dfa", tmp_path / "na.sum"
    rc_p, out_p, err_p = _run_py(
        args + ["-o", str(py_rep), "-s", str(py_sum)])
    rc_n, out_n, err_n = _run_native(
        args + ["-o", str(na_rep), "-s", str(na_sum)])
    assert rc_n == rc_p
    assert out_n == out_p
    if compare_stderr:
        assert err_n == err_p
    if py_rep.exists() or na_rep.exists():
        assert na_rep.read_bytes() == py_rep.read_bytes()
    if py_sum.exists() or na_sum.exists():
        assert na_sum.read_bytes() == py_sum.read_bytes()
    return py_rep.read_bytes() if py_rep.exists() else b""


def _write_inputs(tmp_path, lines, records):
    fa = tmp_path / "q.fa"
    write_fasta(str(fa), records)
    paf = tmp_path / "in.paf"
    paf.write_text("".join(l + "\n" for l in lines))
    return str(paf), str(fa)


def _rand_ops(rng, q_aln):
    # real minimap2 alignments are anchored on matches, so the first and
    # last ops are always match runs — an indel at the very edge would
    # also put a gap outside the MSA layout (GapAssem.cpp:105-107),
    # which is a separate, deliberate test case
    n = len(q_aln)
    first = rng.randint(1, min(n, 40))
    ops = [("=", first)]
    pos = first
    while pos < n - 1:
        r = rng.random()
        left = n - 1 - pos   # reserve one base for the final anchor
        if r < 0.55:
            k = rng.randint(1, min(left, 80))
            ops.append(("=", k))
            pos += k
        elif r < 0.78:
            qb = q_aln[pos].upper()
            tb = rng.choice([c for c in "ACGT" if c != qb])
            ops.append(("*", tb.lower(), qb.lower()))
            pos += 1
        elif r < 0.9:
            ops.append(("ins", "".join(
                rng.choice("acgt") for _ in range(rng.randint(1, 15)))))
        else:
            k = rng.randint(1, min(left, 10))
            ops.append(("del", k))
            pos += k
    ops.append(("=", n - pos))
    return ops


def _rand_lines(rng, qname, qseq, n_targets, with_revcomp=True):
    from pwasm_tpu.core.dna import revcomp

    lines = []
    qlen = len(qseq)
    for t in range(n_targets):
        strand = "-" if with_revcomp and rng.random() < 0.4 else "+"
        q_start = rng.randint(0, qlen // 3)
        q_end = rng.randint(q_start + qlen // 3, qlen)
        if strand == "-":
            q_aln = revcomp(qseq.encode()).decode()[
                qlen - q_end:qlen - q_start]
        else:
            q_aln = qseq[q_start:q_end]
        ops = _rand_ops(rng, q_aln.upper())
        line, _ = make_paf_line(qname, qseq, f"t{t}", strand, ops,
                                q_start=q_start, q_end=q_end,
                                t_start=rng.randint(0, 30),
                                nm=rng.randint(0, 9),
                                score=rng.randint(0, 999))
        lines.append(line)
    return lines


def test_report_and_summary_parity_randomized(tmp_path):
    rng = random.Random(20260730)
    qseq = "".join(rng.choice("ACGT") for _ in range(1200))
    # plant a homopolymer and a methylation motif so both checks fire
    qseq = qseq[:300] + "AAAAAA" + qseq[306:600] + "CCTGG" + qseq[605:]
    lines = _rand_lines(rng, "gene1", qseq, 24)
    paf, fa = _write_inputs(tmp_path, lines, [("gene1", qseq.encode())])
    rep = _assert_parity(tmp_path, [paf, "-r", fa])
    assert rep.count(b">") == 24  # every alignment reported


def test_parity_multi_query_and_fullgenome(tmp_path):
    rng = random.Random(99)
    q1 = "".join(rng.choice("ACGT") for _ in range(600))
    q2 = "".join(rng.choice("ACGT") for _ in range(450))
    lines = (_rand_lines(rng, "geneA", q1, 5)
             + _rand_lines(rng, "geneB", q2, 5))
    rng.shuffle(lines)
    paf, fa = _write_inputs(tmp_path, lines,
                            [("geneA", q1.encode()), ("geneB", q2.encode())])
    # gene mode, multi-record FASTA: rlabel prefixes kept
    rep = _assert_parity(tmp_path, [paf, "-r", fa])
    assert b">geneA--" in rep or b">geneB--" in rep
    # full-genome mode: duplicates kept, coordinates in rlabel, no codons
    _assert_parity(tmp_path, [paf, "-r", fa, "-F"])
    # forced codon analysis in -F would still be skipped (skip_codan set
    # by -F itself); exercise -G -N instead
    _assert_parity(tmp_path, [paf, "-r", fa, "-G", "-N"])


def test_parity_dedup_self_skip_and_verbose(tmp_path):
    rng = random.Random(5)
    qseq = "".join(rng.choice("ACGT") for _ in range(400))
    lines = _rand_lines(rng, "g", qseq, 3)
    lines += [lines[0], lines[0]]  # dup twice: one warning
    self_line, _ = make_paf_line("g", qseq, "g", "+", [("=", len(qseq))])
    lines.append(self_line)
    paf, fa = _write_inputs(tmp_path, lines, [("g", qseq.encode())])
    _assert_parity(tmp_path, [paf, "-r", fa])
    # verbose adds the self-skip message (final stats brief differs by
    # wall time, so compare only the prefix of stderr)
    rc_p, _, err_p = _run_py([paf, "-r", fa, "-o", str(tmp_path / "p")])
    rc_n, _, err_n = _run_native(
        [paf, "-r", fa, "-v", "-o", str(tmp_path / "n")])
    assert rc_n == rc_p == 0
    assert "Skipping alignment of qry seq to itself." in err_n
    assert (tmp_path / "n").read_bytes() == (tmp_path / "p").read_bytes()


def test_parity_auto_fullgenome_by_file_size(tmp_path):
    rng = random.Random(17)
    qseq = "".join(rng.choice("ACGT") for _ in range(130000))
    lines = _rand_lines(rng, "chr", qseq, 2)
    paf, fa = _write_inputs(tmp_path, lines, [("chr", qseq.encode())])
    assert os.path.getsize(fa) > 120000
    rep = _assert_parity(tmp_path, [paf, "-r", fa])
    # auto mode: full genome => coordinates in rlabel, impact column empty
    assert rep.splitlines()[0].startswith(b">chr:")


def test_parity_impact_paths(tmp_path):
    # deterministic codon-impact cases: synonymous, nonsense, frameshift
    q = "ATGGCTGCAGCTGCAGCTTGGGCTGCAGCTGCAGCTGCAGCTGCAGCTGCAGCTGCATAA"
    cases = [
        ("syn", [("=", 3), ("*", "a", "t"), ("=", 56)]),      # GCT->GCA? pos3
        ("stop", [("=", 21), ("*", "a", "g"), ("=", 38)]),
        ("frame", [("=", 30), ("del", 1), ("=", 29)]),
        ("insfs", [("=", 12), ("ins", "tt"), ("=", 48)]),
        ("inshp", [("=", 9), ("ins", "gg"), ("=", 51)]),
    ]
    lines = []
    for name, ops in cases:
        try:
            line, _ = make_paf_line("cds", q, name, "+", ops)
        except AssertionError:
            continue
        lines.append(line)
    assert lines
    paf, fa = _write_inputs(tmp_path, lines, [("cds", q.encode())])
    _assert_parity(tmp_path, [paf, "-r", fa, "-C"])


def test_parity_display_truncation(tmp_path):
    # event >12 bases and context >22 bytes trigger [len] truncation
    rng = random.Random(3)
    q = "".join(rng.choice("ACGT") for _ in range(200))
    ops = [("=", 80), ("ins", "acgtacgtacgtacgtacgt"), ("=", 40),
           ("del", 15), ("=", 65)]
    line, _ = make_paf_line("g", q, "t", "+", ops)
    paf, fa = _write_inputs(tmp_path, [line], [("g", q.encode())])
    rep = _assert_parity(tmp_path, [paf, "-r", fa])
    assert b"[20]" in rep and b"[15]" in rep


def test_parity_error_paths(tmp_path):
    rng = random.Random(11)
    q = "".join(rng.choice("ACGT") for _ in range(120))
    good, _ = make_paf_line("g", q, "t", "+", [("=", 120)])
    fa_rec = [("g", q.encode())]

    def swap(line, old, new):
        assert old in line
        return line.replace(old, new, 1)

    # each corruption must fail with the same message and exit code
    corruptions = [
        swap(good, "cs:Z::120", "cs:Z::60*ac:59"),      # base mismatch
        swap(good, "cg:Z:120M", "cg:Z:120Q"),           # unknown cigar op
        swap(good, "cg:Z:120M", "cg:Z:119M"),           # tseq len mismatch
        swap(good, "cs:Z::120", "cs:Z::119~gt10ag:1"),  # splice op
        swap(good, "\tcs:Z::120", ""),                  # missing cs tag
        swap(good, "cs:Z::120", "cs:Z::120!"),          # unhandled cs op
        "too\tfew\tfields",                             # short line
    ]
    for k, bad in enumerate(corruptions):
        paf, fa = _write_inputs(tmp_path, [bad], fa_rec)
        rc_p, out_p, err_p = _run_py([paf, "-r", fa])
        rc_n, out_n, err_n = _run_native([paf, "-r", fa])
        assert (rc_n, err_n) == (rc_p, err_p), f"corruption {k}"
        assert rc_p == 1
    # --skip-bad-lines: same warnings, same surviving report
    lines = [good] + corruptions + [swap(good, "\tt\t", "\tt2\t")]
    paf, fa = _write_inputs(tmp_path, lines, fa_rec)
    _assert_parity(tmp_path, [paf, "-r", fa, "--skip-bad-lines"])


def test_parity_refseq_errors(tmp_path):
    rng = random.Random(13)
    q = "".join(rng.choice("ACGT") for _ in range(80))
    line, _ = make_paf_line("nosuch", q, "t", "+", [("=", 80)])
    paf, fa = _write_inputs(tmp_path, [line], [("g", q.encode())])
    rc_p, _, err_p = _run_py([paf, "-r", fa])
    rc_n, _, err_n = _run_native([paf, "-r", fa])
    assert (rc_n, err_n) == (rc_p, err_p)
    assert "could not retrieve sequence" in err_n
    # r_len mismatch vs FASTA
    line2, _ = make_paf_line("g", q, "t", "+", [("=", 80)])
    line2 = line2.replace(f"\t{len(q)}\t", "\t81\t", 1)
    paf2, fa2 = _write_inputs(tmp_path, [line2], [("g", q.encode())])
    rc_p, _, err_p = _run_py([paf2, "-r", fa2])
    rc_n, _, err_n = _run_native([paf2, "-r", fa2])
    assert (rc_n, err_n) == (rc_p, err_p)
    assert "differs from loaded sequence length" in err_n


def test_parity_softclip_warning(tmp_path):
    import sys
    rng = random.Random(29)
    q = "".join(rng.choice("ACGT") for _ in range(60))
    line, _ = make_paf_line("g", q, "t", "+", [("=", 60)])
    # inject a soft clip (query consumed but not aligned): 5S + 55M with
    # the cs/target shrunk to the 55 aligned bases so the length
    # cross-validations still pass
    line = line.replace("cg:Z:60M", "cg:Z:5S55M").replace(
        "cs:Z::60", "cs:Z::55")
    line = line.replace("\tt\t60\t0\t60\t", "\tt\t55\t0\t55\t", 1)
    paf, fa = _write_inputs(tmp_path, [line], [("g", q.encode())])
    # the Python extractor prints the soft-clip warning to the real
    # sys.stderr (reference pafreport.cpp:675-679), so compare via
    # subprocess on both sides
    res_p = subprocess.run(
        [sys.executable, "-m", "pwasm_tpu.cli", paf, "-r", fa],
        capture_output=True, text=True, cwd=os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    rc_n, out_n, err_n = _run_native([paf, "-r", fa])
    assert "soft clipping" in err_n
    assert (rc_n, out_n, err_n) == (res_p.returncode, res_p.stdout,
                                    res_p.stderr)


def test_parity_motifs_file_and_clipmax(tmp_path):
    rng = random.Random(31)
    q = "".join(rng.choice("ACGT") for _ in range(300))
    q = q[:100] + "GGWCC"[:0] + q[100:]  # no-op, keep deterministic
    lines = _rand_lines(rng, "g", q, 4)
    motifs = tmp_path / "motifs.txt"
    motifs.write_text("# custom\nGGCC\nTTAA\n")
    paf, fa = _write_inputs(tmp_path, lines, [("g", q.encode())])
    _assert_parity(tmp_path,
                   [paf, "-r", fa, f"--motifs={motifs}"])
    # clipmax verbose messages (parsed-but-unused parity, quirk §2.5):
    # compare the message line itself (the final -v stats brief embeds
    # wall time, so only the first stderr line is comparable)
    for spec, msg in (("25%", "Percentual max clipping set to 25%"),
                      ("10", "Max clipping set to 10 bases")):
        rc_p, _, err_p = _run_py_subproc(
            [paf, "-r", fa, "-v", "-c", spec, "-o", str(tmp_path / "p")])
        rc_n, _, err_n = _run_native(
            [paf, "-r", fa, "-v", "-c", spec, "-o", str(tmp_path / "n")])
        assert rc_n == rc_p == 0
        assert err_p.splitlines()[0] == msg
        assert err_n.splitlines()[0] == msg
    rc_p, _, err_p = _run_py([paf, "-r", fa, "-c", "0"])
    rc_n, _, err_n = _run_native([paf, "-r", fa, "-c", "0"])
    assert (rc_n, err_n) == (rc_p, err_p)
    rc_p, _, err_p = _run_py([paf, "-r", fa, "-c", "120%"])
    rc_n, _, err_n = _run_native([paf, "-r", fa, "-c", "120%"])
    assert (rc_n, err_n) == (rc_p, err_p)


def test_native_stats_file(tmp_path):
    rng = random.Random(37)
    q = "".join(rng.choice("ACGT") for _ in range(200))
    lines = _rand_lines(rng, "g", q, 3)
    paf, fa = _write_inputs(tmp_path, lines, [("g", q.encode())])
    stats = tmp_path / "stats.json"
    rc, _, _ = _run_native([paf, "-r", fa, "-o", str(tmp_path / "r"),
                            f"--stats={stats}"])
    assert rc == 0
    d = json.loads(stats.read_text())
    assert d["alignments"] == 3
    assert d["aligned_bases"] > 0
    assert set(d) >= {"lines", "events", "wall_s", "aligned_bases_per_s"}


def test_parity_knob_validation_and_motif_errors(tmp_path):
    rng = random.Random(43)
    q = "".join(rng.choice("ACGT") for _ in range(100))
    lines = _rand_lines(rng, "g", q, 1)
    paf, fa = _write_inputs(tmp_path, lines, [("g", q.encode())])
    # invalid tuning knobs fail on both sides with exit 1
    for extra in (["--band=abc"], ["--batch=0"], ["--stats"],
                  ["--motifs"]):
        rc_p, _, _ = _run_py([paf, "-r", fa] + extra)
        rc_n, _, _ = _run_native([paf, "-r", fa] + extra)
        assert rc_n == rc_p == 1, extra
    # valid knobs are accepted and do not change the report
    _assert_parity(tmp_path, [paf, "-r", fa, "--band=32", "--batch=16"])
    # missing motif file: same message and exit code
    rc_p, _, err_p = _run_py([paf, "-r", fa, "--motifs=/nonexistent/m"])
    rc_n, _, err_n = _run_native([paf, "-r", fa, "--motifs=/nonexistent/m"])
    assert (rc_n, err_n) == (rc_p, err_p)
    assert "Cannot open motif file" in err_n


def test_parity_zero_length_query(tmp_path):
    # degenerate zero-length record: both sides print coverage:nan and
    # keep going (the reference's double division would NaN too)
    (tmp_path / "q.fa").write_text(">e\n\n>g\nACGT\n")
    line = ("e\t0\t0\t0\t+\tt\t0\t0\t0\t0\t0\t60\tNM:i:0\tAS:i:0\t"
            "cg:Z:0M\tcs:Z::0")
    paf = tmp_path / "in.paf"
    paf.write_text(line + "\n")
    rep = _assert_parity(tmp_path, [str(paf), "-r", str(tmp_path / "q.fa")])
    assert b"coverage:nan" in rep


def test_parity_crlf_and_cr_line_endings(tmp_path):
    # the Python CLI reads the PAF in text mode (universal newlines);
    # the native LineReader must treat '\n', '\r\n' and lone '\r' alike
    rng = random.Random(47)
    q = "".join(rng.choice("ACGT") for _ in range(150))
    lines = _rand_lines(rng, "g", q, 3)
    fa = tmp_path / "q.fa"
    write_fasta(str(fa), [("g", q.encode())])
    for sep in ("\r\n", "\r"):
        paf = tmp_path / "in.paf"
        paf.write_bytes(sep.join(lines).encode() + sep.encode())
        rep = _assert_parity(tmp_path, [str(paf), "-r", str(fa)])
        assert rep.count(b">") == 3


def test_parity_device_values(tmp_path):
    rng = random.Random(53)
    q = "".join(rng.choice("ACGT") for _ in range(80))
    lines = _rand_lines(rng, "g", q, 1)
    paf, fa = _write_inputs(tmp_path, lines, [("g", q.encode())])
    # bare --device and junk values: both exit 1; native names the value
    for extra in (["--device"], ["--device=gpu"]):
        rc_p, _, _ = _run_py([paf, "-r", fa] + extra)
        rc_n, _, err_n = _run_native([paf, "-r", fa] + extra)
        assert rc_n == rc_p == 1, extra
        assert "Invalid --device value" in err_n
    # --device=cpu runs natively and matches
    _assert_parity(tmp_path, [paf, "-r", fa, "--device=cpu"])


def _assert_msa_parity(tmp_path, lines, records, extra=None):
    paf, fa = _write_inputs(tmp_path, lines, records)
    args = [paf, "-r", fa] + (extra or [])
    py_m, na_m = tmp_path / "p.mfa", tmp_path / "n.mfa"
    rc_p, _, err_p = _run_py(args + ["-o", str(tmp_path / "p.dfa"),
                                     "-w", str(py_m)])
    rc_n, _, err_n = _run_native(args + ["-o", str(tmp_path / "n.dfa"),
                                         "-w", str(na_m)])
    assert (rc_n, err_n) == (rc_p, err_p)
    if py_m.exists() or na_m.exists():
        assert na_m.read_bytes() == py_m.read_bytes()
    assert (tmp_path / "n.dfa").read_bytes() == \
        (tmp_path / "p.dfa").read_bytes()
    return py_m.read_bytes() if py_m.exists() else b""


def test_parity_msa_randomized(tmp_path):
    rng = random.Random(20260731)
    q = "".join(rng.choice("ACGT") for _ in range(800))
    lines = _rand_lines(rng, "g", q, 16)
    mfa = _assert_msa_parity(tmp_path, lines, [("g", q.encode())])
    assert mfa.count(b">") == 17  # query + every alignment


def test_parity_msa_debug_layout(tmp_path):
    rng = random.Random(61)
    q = "".join(rng.choice("ACGT") for _ in range(200))
    lines = _rand_lines(rng, "g", q, 4)
    paf, fa = _write_inputs(tmp_path, lines, [("g", q.encode())])
    rc_p, _, err_p = _run_py(
        [paf, "-r", fa, "-D", "-o", str(tmp_path / "p.dfa"),
         "-w", str(tmp_path / "p.mfa")])
    rc_n, _, err_n = _run_native(
        [paf, "-r", fa, "-D", "-o", str(tmp_path / "n.dfa"),
         "-w", str(tmp_path / "n.mfa")])
    assert rc_n == rc_p == 0
    # the -D layout dump goes to stderr on both sides; the native -v
    # brief has wall-clock in it, so compare the layout block only
    assert ">MSA (5)" in err_n
    # drop the -v stats brief (embeds wall time) before comparing
    p_block = [l for l in err_p[err_p.index(">MSA"):].splitlines()
               if "bases/s" not in l]
    n_block = [l for l in err_n[err_n.index(">MSA"):].splitlines()
               if "bases/s" not in l]
    assert n_block == p_block


def test_parity_msa_out_of_layout_gap(tmp_path):
    # a reverse-strand alignment starting with an insertion event puts a
    # ref gap at position r_len — fatal at MSA insertion, skippable
    # under --skip-bad-lines (cli.py msa_add; GapAssem.cpp:105-107)
    rng = random.Random(67)
    q = "".join(rng.choice("ACGT") for _ in range(120))
    bad, _ = make_paf_line("g", q, "tbad", "-",
                           [("ins", "cc"), ("=", 120)])
    good, _ = make_paf_line("g", q, "tok", "+", [("=", 120)])
    records = [("g", q.encode())]
    # without skip: both fail with the same message and exit code
    paf, fa = _write_inputs(tmp_path, [bad], records)
    rc_p, _, err_p = _run_py([paf, "-r", fa, "-o",
                              str(tmp_path / "p.dfa"),
                              "-w", str(tmp_path / "p.mfa")])
    rc_n, _, err_n = _run_native([paf, "-r", fa, "-o",
                                  str(tmp_path / "n.dfa"),
                                  "-w", str(tmp_path / "n.mfa")])
    assert (rc_n, err_n) == (rc_p, err_p)
    assert rc_p == 1 and "invalid gap position" in err_n
    # with skip: dropped from the MSA with the same warning, and the
    # dedup slot frees so a later valid alignment of the pair lands
    bad2 = bad.replace("\ttbad\t", "\ttok\t")
    mfa = _assert_msa_parity(tmp_path, [bad2, good], records,
                             extra=["--skip-bad-lines"])
    assert mfa.count(b">tok") == 1
    stats = tmp_path / "st.json"
    paf, fa = _write_inputs(tmp_path, [bad2, good], records)
    rc, _, _ = _run_native([paf, "-r", fa, "--skip-bad-lines",
                            "-o", str(tmp_path / "r.dfa"),
                            "-w", str(tmp_path / "m.mfa"),
                            f"--stats={stats}"])
    assert rc == 0
    assert json.loads(stats.read_text())["msa_dropped"] == 1


def test_parity_msa_multi_query_writes_last(tmp_path):
    # cli.py writes the LAST query's MSA when the PAF spans several
    # queries; the native binary must mirror that exactly
    rng = random.Random(71)
    q1 = "".join(rng.choice("ACGT") for _ in range(150))
    q2 = "".join(rng.choice("ACGT") for _ in range(180))
    lines = (_rand_lines(rng, "gA", q1, 2)
             + _rand_lines(rng, "gB", q2, 2))
    mfa = _assert_msa_parity(tmp_path, lines,
                             [("gA", q1.encode()), ("gB", q2.encode())])
    assert b">gB\n" in mfa and b">gA\n" not in mfa


def _assert_cons_parity(tmp_path, lines, records, extra=None):
    """Byte-parity of the consensus path: --ace/--info/--cons (plus the
    report and MSA) between the native binary and the Python CLI."""
    paf, fa = _write_inputs(tmp_path, lines, records)

    def args(pfx):
        return ([paf, "-r", fa, "-o", str(tmp_path / f"{pfx}.dfa"),
                 "-w", str(tmp_path / f"{pfx}.mfa"),
                 f"--ace={tmp_path / (pfx + '.ace')}",
                 f"--info={tmp_path / (pfx + '.info')}",
                 f"--cons={tmp_path / (pfx + '.cons')}"] + (extra or []))

    rc_p, _, err_p = _run_py(args("p"))
    rc_n, _, err_n = _run_native(args("n"))
    assert (rc_n, err_n) == (rc_p, err_p)
    for suff in ("dfa", "mfa", "ace", "info", "cons"):
        pa, na = tmp_path / f"p.{suff}", tmp_path / f"n.{suff}"
        if pa.exists() or na.exists():
            assert na.read_bytes() == pa.read_bytes(), suff
    return ((tmp_path / "p.ace").read_bytes()
            if (tmp_path / "p.ace").exists() else b"")


def test_parity_consensus_writers(tmp_path):
    rng = random.Random(20260801)
    q = "".join(rng.choice("ACGT") for _ in range(600))
    lines = _rand_lines(rng, "g", q, 12)
    ace = _assert_cons_parity(tmp_path, lines, [("g", q.encode())])
    assert ace.startswith(b"CO g ") and b"\nBQ \n" in ace
    # the two refinement flags change the outputs; parity must hold on
    # every combination (reference statics, SURVEY.md §2.5.8)
    _assert_cons_parity(tmp_path, lines, [("g", q.encode())],
                        extra=["--remove-cons-gaps"])
    _assert_cons_parity(tmp_path, lines, [("g", q.encode())],
                        extra=["--no-refine-clip"])
    _assert_cons_parity(tmp_path, lines, [("g", q.encode())],
                        extra=["--remove-cons-gaps", "--no-refine-clip"])


def test_parity_consensus_reverse_heavy(tmp_path):
    # majority-reverse MSA: the ACE contig direction flips to 'C'
    rng = random.Random(20260802)
    q = "".join(rng.choice("ACGT") for _ in range(300))
    lines = []
    for t in range(5):
        strand = "-" if t < 4 else "+"
        ops = _rand_ops(rng, q.upper()) if strand == "+" else None
        if strand == "-":
            from pwasm_tpu.core.dna import revcomp
            q_aln = revcomp(q.encode()).decode()
            ops = _rand_ops(rng, q_aln.upper())
        line, _ = make_paf_line("g", q, f"t{t}", strand, ops)
        lines.append(line)
    ace = _assert_cons_parity(tmp_path, lines, [("g", q.encode())])
    assert b" C\n" in ace.splitlines()[0] + b"\n"


def test_refine_clipping_parity_fuzz(tmp_path):
    """Clip-seeded fuzz of the native X-drop refinement against the
    Python engine's transliterated reference walk (the CLI flow never
    sets clips, so this hook is the only way to exercise the port —
    reference GapAssem.cpp:182-349)."""
    import contextlib

    from pwasm_tpu.align.gapseq import GapSeq

    rng = random.Random(20260803)
    cases = []
    cons_alpha = "ACGT*"
    cons = "".join(rng.choice(cons_alpha) for _ in range(400))
    for k in range(250):
        n = rng.randint(8, 60)
        bases = "".join(rng.choice("ACGT") for _ in range(n))
        # bias toward consensus-like content so the seek finds matches
        cpos = rng.randint(0, 300)
        if rng.random() < 0.7:
            seg = cons[cpos:cpos + n].replace("*", "A")
            bases = (seg + bases)[:n]
        gaps = [0] * n
        for _ in range(rng.randint(0, 5)):
            gaps[rng.randint(0, n - 1)] = rng.randint(0, 3)
        skip_dels = rng.random() < 0.3
        if skip_dels and rng.random() < 0.5:
            gaps[rng.randint(0, n - 1)] = -1
        clp5 = rng.randint(0, n // 3)
        clp3 = rng.randint(0, n - clp5 - 1) if rng.random() < 0.8 else 0
        rev = rng.randint(0, 1)
        cases.append((f"c{k}", rev, clp5, clp3, cpos, int(skip_dels),
                      gaps, bases))
    infile = tmp_path / "cases.tsv"
    with open(infile, "w") as f:
        f.write(cons + "\n")
        for name, rev, c5, c3, cpos, sd, gaps, bases in cases:
            f.write(f"{name}\t{rev}\t{c5}\t{c3}\t{cpos}\t{sd}\t"
                    f"{','.join(map(str, gaps))}\t{bases}\n")
    rc, out, _err = _run_native([f"--refine-selftest={infile}"])
    assert rc == 0
    got = {}
    for line in out.splitlines():
        name, c5, c3 = line.split("\t")
        got[name] = (int(c5), int(c3))
    assert len(got) == len(cases)
    import numpy as np
    for name, rev, c5, c3, cpos, sd, gaps, bases in cases:
        s = GapSeq(name, "", bases.encode())
        s.gaps = np.asarray(gaps, dtype=np.int32)
        s.numgaps = int(sum(gaps))
        s.revcompl = rev
        s.clp5, s.clp3 = c5, c3
        try:  # swallow seek-miss warnings, keep the clip results
            with contextlib.redirect_stderr(io.StringIO()):
                s.refine_clipping_scalar(cons.encode(), cpos,
                                         skip_dels=bool(sd))
        except Exception as e:  # length-mismatch guard must agree
            raise AssertionError(f"{name}: oracle raised {e}")
        assert got[name] == (s.clp5, s.clp3), name


def test_parity_resume(tmp_path):
    """--resume must behave exactly like the Python CLI: truncate the
    torn last record, re-emit it, skip the survivors, and produce a
    final report byte-identical to an uninterrupted run."""
    rng = random.Random(20260804)
    q = "".join(rng.choice("ACGT") for _ in range(300))
    lines = _rand_lines(rng, "g", q, 8)
    paf, fa = _write_inputs(tmp_path, lines, [("g", q.encode())])
    # the uninterrupted ground truth (either side; they are identical)
    full = tmp_path / "full.dfa"
    rc, _, _ = _run_native([paf, "-r", fa, "-o", str(full)])
    assert rc == 0
    body = full.read_bytes()
    # simulate an interruption: keep the first 5 records plus a TORN
    # prefix of the 6th (its header and half a row)
    header_offs = [i for i in range(len(body))
                   if body[i:i + 1] == b">"
                   and (i == 0 or body[i - 1:i] == b"\n")]
    assert len(header_offs) == 8
    torn = body[:header_offs[5] + 40]
    for pfx in ("p", "n"):
        (tmp_path / f"{pfx}.dfa").write_bytes(torn)
    rc_p, _, err_p = _run_py([paf, "-r", fa, "--resume",
                              "-o", str(tmp_path / "p.dfa")])
    rc_n, _, err_n = _run_native([paf, "-r", fa, "--resume",
                                  "-o", str(tmp_path / "n.dfa")])
    assert (rc_n, err_n) == (rc_p, err_p)
    assert rc_p == 0
    assert (tmp_path / "n.dfa").read_bytes() == \
        (tmp_path / "p.dfa").read_bytes() == body
    # resumed stats: 5 records were skipped by the cursor
    stats = tmp_path / "st.json"
    (tmp_path / "n2.dfa").write_bytes(torn)
    rc, _, _ = _run_native([paf, "-r", fa, "--resume",
                            "-o", str(tmp_path / "n2.dfa"),
                            f"--stats={stats}"])
    assert rc == 0
    d = json.loads(stats.read_text())
    assert d["resumed_past"] == 5 and d["alignments"] == 8
    # --resume without -o: same error and exit code on both sides
    rc_p, _, err_p = _run_py([paf, "-r", fa, "--resume"])
    rc_n, _, err_n = _run_native([paf, "-r", fa, "--resume"])
    assert rc_n == rc_p == 1
    assert "--resume requires -o" in err_n
    # fresh resume (no existing report) acts like a plain run
    rc_n, _, _ = _run_native([paf, "-r", fa, "--resume",
                              "-o", str(tmp_path / "fresh.dfa")])
    assert rc_n == 0
    assert (tmp_path / "fresh.dfa").read_bytes() == body


def _write_selftest_seqs(f, specs, with_bases=False):
    """Serialize SEQ lines for the --clip-selftest hook."""
    for sp in specs:
        row = (f"SEQ\t{sp['name']}\t{sp['revcompl']}\t{sp['offset']}\t"
               f"{sp['clp5']}\t{sp['clp3']}\t"
               f"{','.join(map(str, sp['gaps']))}\t{sp['seqlen']}")
        if with_bases:
            row += f"\t{sp['bases']}"
        f.write(row + "\n")


def _build_python_msa(specs):
    """The Python-engine twin of the hook's MSA construction."""
    import numpy as np

    from pwasm_tpu.align.gapseq import GapSeq
    from pwasm_tpu.align.msa import Msa

    pseqs = []
    for sp in specs:
        s = GapSeq(sp["name"], "", sp.get("bases", "").encode(),
                   seqlen=sp["seqlen"], offset=sp["offset"],
                   clp5=sp["clp5"], clp3=sp["clp3"],
                   revcompl=sp["revcompl"])
        s.gaps = np.asarray(sp["gaps"], dtype=np.int32)
        s.numgaps = int(sum(sp["gaps"]))
        pseqs.append(s)
    msa = Msa(pseqs[0], pseqs[1])
    for s in pseqs[2:]:
        msa.add_seq(s, s.offset, s.ng_ofs)
    return msa, pseqs


def test_clip_transaction_parity_fuzz(tmp_path):
    """Clip-transaction fuzz: the native eval_clipping/apply_clipping
    (GapAssem.cpp:823-996 capability) must accept/reject and apply
    exactly like the Python engine on random MSAs and random proposed
    end-trims, across strands and clipmax forms (absolute/fraction)."""
    import numpy as np

    from pwasm_tpu.align.gapseq import GapSeq
    from pwasm_tpu.align.msa import AlnClipOps, Msa

    rng = random.Random(20260805)
    for case in range(40):
        clipmax = rng.choice([0.0, 0.25, 0.4, 12.0, 30.0])
        n_seqs = rng.randint(2, 6)
        seqs_spec = []
        for k in range(n_seqs):
            seqlen = rng.randint(12, 60)
            gaps = [0] * seqlen
            for _ in range(rng.randint(0, 4)):
                gaps[rng.randint(0, seqlen - 1)] = rng.randint(1, 3)
            seqs_spec.append(dict(
                name=f"s{k}", revcompl=rng.randint(0, 1),
                offset=rng.randint(0, 20), clp5=rng.randint(0, 3),
                clp3=rng.randint(0, 3), gaps=gaps, seqlen=seqlen))
        evals = []
        for _ in range(rng.randint(1, 6)):
            idx = rng.randint(0, n_seqs - 1)
            sl = seqs_spec[idx]["seqlen"]
            c5 = rng.randint(-1, sl // 2)
            c3 = rng.randint(-1, sl // 2)
            evals.append((idx, c5, c3))
        # native side
        infile = tmp_path / f"clip{case}.tsv"
        with open(infile, "w") as f:
            f.write(f"{clipmax}\n")
            _write_selftest_seqs(f, seqs_spec)
            for idx, c5, c3 in evals:
                f.write(f"EVAL\t{idx}\t{c5}\t{c3}\n")
        rc, out, err = _run_native([f"--clip-selftest={infile}"])
        assert rc == 0, err
        lines = out.splitlines()
        got_verdicts = lines[:len(evals)]
        got_clips = {}
        for line in lines[len(evals):]:
            name, c5, c3 = line.split("\t")
            got_clips[name] = (int(c5), int(c3))
        # python side
        msa, pseqs = _build_python_msa(seqs_spec)
        want_verdicts = []
        for idx, c5, c3 in evals:
            ops = AlnClipOps()
            ok = msa.eval_clipping(pseqs[idx], c5, c3, clipmax, ops)
            if ok:
                msa.apply_clipping(ops)
            want_verdicts.append("ok" if ok else "rejected")
        assert got_verdicts == want_verdicts, f"case {case}"
        for s in pseqs:
            assert got_clips[s.name] == (s.clp5, s.clp3), \
                f"case {case} seq {s.name}"


def test_clip_bearing_writers_parity_fuzz(tmp_path):
    """ACE/info writer parity on MSAs WITH clips — the QA clip math,
    negative AF offsets and the seql/seqr strand swap are unreachable
    from the CLI flow (nothing sets clips there), so this drives the
    native engine's writers directly via the clip-selftest hook and
    byte-compares against the Python engine."""
    import io as _io

    import numpy as np

    from pwasm_tpu.align.gapseq import GapSeq
    from pwasm_tpu.align.msa import Msa

    rng = random.Random(20260806)
    for case in range(15):
        n_seqs = rng.randint(2, 5)
        seqlen = rng.randint(10, 30)
        specs = []
        for k in range(n_seqs):
            bases = "".join(rng.choice("ACGT") for _ in range(seqlen))
            gaps = [0] * seqlen
            for _ in range(rng.randint(0, 3)):
                gaps[rng.randint(0, seqlen - 1)] = rng.randint(1, 2)
            # member 0 stays unclipped so every layout column keeps at
            # least one unclipped contributor (an all-clipped column
            # would be a zero-coverage exit-5 on both sides)
            clp5 = rng.randint(0, 3) if k else 0
            clp3 = (rng.randint(0, max(0, seqlen // 2 - clp5 - 2))
                    if k else 0)
            specs.append(dict(name=f"s{k}", revcompl=rng.randint(0, 1),
                              offset=0, clp5=clp5, clp3=clp3,
                              gaps=gaps, bases=bases, seqlen=seqlen))
        # same layout length for every member keeps the MSA covered
        # (no zero-coverage exit-5 columns) without gap propagation
        total = [sum(sp["gaps"]) for sp in specs]
        mx = max(total)
        for sp, t in zip(specs, total):
            if t < mx:
                sp["gaps"][0] += mx - t
        infile = tmp_path / f"wclip{case}.tsv"
        with open(infile, "w") as f:
            f.write("0.0\n")
            _write_selftest_seqs(f, specs, with_bases=True)
            f.write("WRITE\tace\nWRITE\tinfo\n")
        rc, out, err = _run_native([f"--clip-selftest={infile}"])
        assert rc == 0, err
        # strip the trailing per-seq clip-summary lines (tab-separated,
        # unlike the space-separated writer bodies)
        native_out = out[:out.rfind(f"{specs[0]['name']}\t")]
        # python twin
        msa, _pseqs = _build_python_msa(specs)
        buf = _io.StringIO()
        msa.write_ace(buf, "ctg", remove_cons_gaps=False,
                      refine_clipping=False)
        msa.write_info(buf, "ctg", remove_cons_gaps=False,
                       refine_clipping=False)
        assert native_out == buf.getvalue(), f"case {case}"


def test_native_rejects_python_only_features(tmp_path):
    rng = random.Random(41)
    q = "".join(rng.choice("ACGT") for _ in range(100))
    lines = _rand_lines(rng, "g", q, 1)
    paf, fa = _write_inputs(tmp_path, lines, [("g", q.encode())])
    for extra in (["--device=tpu"], ["--realign"], ["--shard"],
                  ["--profile=" + str(tmp_path / "t")]):
        rc, _, err = _run_native([paf, "-r", fa] + extra)
        assert rc == 1
        # the rejection line itself (not the USAGE banner, which also
        # mentions the Python CLI) must point at the Python CLI
        assert "is handled by the Python CLI" in err
