"""Fleet-grade observability (ISSUE 6): the pwasm_tpu.obs subsystem.

Acceptance contracts exercised here:

- **exposition format**: the MetricsRegistry renders valid Prometheus
  text exposition — HELP/TYPE headers, label escaping, histogram
  bucket CUMULATIVITY (each ``le`` counts observations at-or-under,
  ``+Inf`` equals ``_count``), gauge set/reset;
- **trace schema**: ``--trace-json`` writes Chrome trace-event JSON
  whose complete spans nest monotonically (a child's ``[ts, ts+dur]``
  interval sits inside its parent's on the same thread);
- **event-log replay**: a scripted flap (``down=A-B``) shows
  breaker_trip -> reprobe -> breaker_half_open -> breaker_reclose in
  the NDJSON log, and an ``oom=N`` leg shows
  oom/batch_split/bucket_demotion — the resilience machinery is
  observable WHILE it happens, not just in end-of-run counters;
- **byte parity**: every report output is byte-identical with all
  observability flags on vs off (observability writes only to its own
  sinks), and the ``--stats`` schema is unchanged.
"""

import io
import json
import re

import numpy as np
import pytest

from pwasm_tpu.cli import run
from pwasm_tpu.core.fasta import write_fasta
from pwasm_tpu.obs import (EventLog, MetricsRegistry, Observability,
                           TraceRecorder, make_observability)
from pwasm_tpu.obs.catalog import (breaker_state_value,
                                   build_run_metrics,
                                   build_service_metrics,
                                   fold_run_stats)

from helpers import make_paf_line


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------
def test_metric_name_grammar_enforced():
    reg = MetricsRegistry()
    for bad in ("queue_depth", "pwasm_Queue", "pwasm_", "pwasm_a-b",
                "Pwasm_x", "pwasm_x__y"):
        with pytest.raises(ValueError):
            reg.counter(bad, "h")
    assert reg.counter("pwasm_ok_total", "h").name == "pwasm_ok_total"


def test_duplicate_registration_raises():
    reg = MetricsRegistry()
    reg.gauge("pwasm_depth", "h")
    with pytest.raises(ValueError):
        reg.counter("pwasm_depth", "h")


def test_counter_monotonic():
    reg = MetricsRegistry()
    c = reg.counter("pwasm_jobs_total", "h", labels=("outcome",))
    c.inc(outcome="done")
    c.inc(2, outcome="done")
    c.inc(outcome="failed")
    assert c.value(outcome="done") == 3
    assert c.value(outcome="failed") == 1
    with pytest.raises(ValueError):
        c.inc(-1, outcome="done")
    with pytest.raises(ValueError):
        c.inc(1)   # missing declared label


def test_gauge_set_inc_reset_exposed():
    reg = MetricsRegistry()
    g = reg.gauge("pwasm_depth", "queue depth")
    g.set(5)
    g.inc()
    g.dec(2)
    assert g.value() == 4
    assert "pwasm_depth 4" in reg.expose().splitlines()
    g.reset()
    assert g.value() == 0
    assert "pwasm_depth 0" in reg.expose().splitlines()


def test_histogram_bucket_cumulativity():
    reg = MetricsRegistry()
    h = reg.histogram("pwasm_wall_seconds", "h",
                      buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.05, 0.5, 5.0, 50.0):
        h.observe(v)
    lines = reg.expose().splitlines()
    sample = {}
    for ln in lines:
        if ln.startswith("#"):
            continue
        k, v = ln.rsplit(" ", 1)
        sample[k] = float(v)
    # CUMULATIVE buckets: le=0.1 holds 2, le=1 holds those plus 0.5...
    assert sample['pwasm_wall_seconds_bucket{le="0.1"}'] == 2
    assert sample['pwasm_wall_seconds_bucket{le="1"}'] == 3
    assert sample['pwasm_wall_seconds_bucket{le="10"}'] == 4
    assert sample['pwasm_wall_seconds_bucket{le="+Inf"}'] == 5
    assert sample["pwasm_wall_seconds_count"] == 5
    assert sample["pwasm_wall_seconds_sum"] == pytest.approx(55.6)
    # buckets must be declared sorted+unique
    with pytest.raises(ValueError):
        reg.histogram("pwasm_bad_seconds", "h", buckets=(1.0, 0.5))


def test_exposition_escaping():
    reg = MetricsRegistry()
    c = reg.counter("pwasm_esc_total", 'help with \\ and\nnewline',
                    labels=("path",))
    c.inc(path='a"b\\c\nd')
    text = reg.expose()
    assert "# HELP pwasm_esc_total help with \\\\ and\\nnewline" \
        in text.splitlines()
    assert 'pwasm_esc_total{path="a\\"b\\\\c\\nd"} 1' \
        in text.splitlines()


_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z0-9_]+="(\\.|[^"\\])*"'
    r'(,[a-zA-Z0-9_]+="(\\.|[^"\\])*")*\})? -?[0-9.e+Inf-]+'
    # the optional OpenMetrics exemplar suffix (ISSUE 14): histogram
    # bucket samples may carry `# {trace_id="..."} value ts`
    r'( # \{[a-zA-Z0-9_]+="(\\.|[^"\\])*"\}'
    r' -?[0-9.e+-]+( -?[0-9.e+-]+)?)?$')


def assert_valid_exposition(text: str) -> None:
    """Minimal independent grammar check of the text exposition: every
    line is a comment (HELP/TYPE) or a sample, every sample's family
    was TYPEd first."""
    typed = set()
    assert text.endswith("\n")
    for ln in text.splitlines():
        if ln.startswith("# HELP ") or ln.startswith("# TYPE "):
            parts = ln.split(" ", 3)
            assert len(parts) >= 3
            if parts[1] == "TYPE":
                typed.add(parts[2])
            continue
        assert _SAMPLE_RE.match(ln), ln
        name = re.split(r"[{ ]", ln, 1)[0]
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        assert name in typed or base in typed, ln


def test_catalog_builds_valid_exposition():
    reg = MetricsRegistry()
    rm = build_run_metrics(reg)
    sm = build_service_metrics(reg)
    rm["batch_attempt_seconds"].observe(0.2, site="ctx_scan")
    sm["jobs"].inc(outcome="done")
    sm["job_wall_seconds"].observe(1.5)
    fold_run_stats(rm, {"alignments": 3, "wall_s": 0.5,
                        "resilience": {"breaker_trips": 1},
                        "backend": {"probes": 1, "warm_hits": 2},
                        "device": {"dispatches": 4, "flushes": 2}})
    text = reg.expose()
    assert_valid_exposition(text)
    assert "pwasm_run_alignments_total 3" in text.splitlines()
    assert "pwasm_breaker_trips_total 1" in text.splitlines()
    assert "pwasm_backend_warm_hits_total 2" in text.splitlines()
    # a malformed stats dict folds as zeros, never raises
    fold_run_stats(rm, {"alignments": "gibberish",
                        "resilience": "not-a-dict"})
    fold_run_stats(rm, None)


def test_breaker_state_encoding():
    assert breaker_state_value(False) == 0
    assert breaker_state_value(False, "half-open") == 0
    assert breaker_state_value(True, "half-open") == 1
    assert breaker_state_value(True, "open") == 2
    assert breaker_state_value(True, None) == 2


# ---------------------------------------------------------------------------
# trace recorder
# ---------------------------------------------------------------------------
class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_trace_spans_nest_monotonically():
    clk = _Clock()
    rec = TraceRecorder(clock=clk)
    with rec.span("outer", phase="run"):
        clk.t = 1.0
        with rec.span("inner", site="ctx_scan"):
            clk.t = 2.0
        clk.t = 3.0
    doc = rec.to_dict()
    evs = {e["name"]: e for e in doc["traceEvents"]}
    inner, outer = evs["inner"], evs["outer"]
    for e in (inner, outer):
        assert e["ph"] == "X"
        for key in ("ts", "dur", "pid", "tid", "args", "name"):
            assert key in e
    # containment: the child's interval sits inside the parent's
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"]
    assert outer["dur"] == 3_000_000 and inner["dur"] == 1_000_000


def test_trace_span_records_error_and_instant():
    rec = TraceRecorder(clock=_Clock())
    with pytest.raises(RuntimeError):
        with rec.span("doomed"):
            raise RuntimeError("x")
    rec.instant("breaker_trip", site="ctx_scan")
    evs = {e["name"]: e for e in rec.to_dict()["traceEvents"]}
    assert evs["doomed"]["args"]["error"] == "RuntimeError"
    assert evs["breaker_trip"]["ph"] == "i"


def test_trace_event_cap_bounds_memory():
    rec = TraceRecorder(clock=_Clock(), max_events=3)
    for i in range(5):
        rec.instant(f"e{i}")
    assert len(rec.to_dict()["traceEvents"]) == 3
    assert rec.to_dict()["otherData"]["dropped_events"] == 2


# ---------------------------------------------------------------------------
# event log
# ---------------------------------------------------------------------------
def test_event_log_lines_and_clocks():
    buf = io.StringIO()
    log = EventLog(buf, owns_stream=False)
    log.emit("run_start", device="cpu")
    log.emit("ckpt_write", records=4, skipme=None)
    recs = [json.loads(ln) for ln in buf.getvalue().splitlines()]
    assert [r["event"] for r in recs] == ["run_start", "ckpt_write"]
    assert all(r["run_id"] == log.run_id for r in recs)
    assert all("ts_wall" in r and "ts_mono" in r for r in recs)
    assert recs[0]["ts_mono"] <= recs[1]["ts_mono"]
    assert "skipme" not in recs[1]   # None fields dropped
    assert recs[1]["records"] == 4


def test_event_log_never_raises():
    class Dead:
        def write(self, *_a):
            raise OSError("gone")

        def flush(self):
            raise OSError("gone")

    log = EventLog(Dead(), owns_stream=False)
    log.emit("run_start")        # swallowed
    log.close()
    log.emit("after_close")      # no-op


# ---------------------------------------------------------------------------
# CLI wiring
# ---------------------------------------------------------------------------
def _corpus(tmp_path, n=24, qlen=120):
    rng = np.random.default_rng(3)
    q = "".join("ACGT"[i] for i in rng.integers(0, 4, qlen))
    lines = []
    for i in range(n):
        cut = 10 + int(rng.integers(0, qlen - 40))
        qb = q[cut]
        tb = "ACGT"[("ACGT".index(qb) + 1) % 4]
        ops = [("=", cut), ("*", tb, qb), ("=", 20), ("ins", "gg"),
               ("=", qlen - cut - 21)]
        lines.append(make_paf_line("q", q, f"asm{i}", "+", ops)[0])
    fa = tmp_path / "q.fa"
    write_fasta(str(fa), [("q", q.encode())])
    paf = tmp_path / "in.paf"
    paf.write_text("".join(ln + "\n" for ln in lines))
    return str(paf), str(fa)


def _cli(tmp_path, tag, extra, paf, fa, device="cpu"):
    err = io.StringIO()
    rc = run([paf, "-r", fa, "-o", str(tmp_path / f"{tag}.dfa"),
              "-s", str(tmp_path / f"{tag}.sum"),
              "-w", str(tmp_path / f"{tag}.mfa"),
              f"--device={device}", "--batch=2",
              f"--stats={tmp_path / f'{tag}.json'}"] + extra, stderr=err)
    return rc, err.getvalue()


def _outs(tmp_path, tag):
    return tuple((tmp_path / f"{tag}.{ext}").read_bytes()
                 for ext in ("dfa", "sum", "mfa"))


def _events(path):
    return [json.loads(ln) for ln in open(path)]


def test_cli_byte_parity_with_all_obs_flags(tmp_path):
    """THE acceptance bar: -o/-s/-w bytes identical with every
    observability flag armed vs none, and the --stats schema keys
    unchanged (observability is additive, never perturbing)."""
    paf, fa = _corpus(tmp_path, n=12)
    rc, err = _cli(tmp_path, "off", [], paf, fa)
    assert rc == 0, err
    rc, err = _cli(tmp_path, "on", [
        f"--trace-json={tmp_path / 't.json'}",
        f"--log-json={tmp_path / 'ev.ndjson'}",
        f"--metrics-textfile={tmp_path / 'm.prom'}"], paf, fa)
    assert rc == 0, err
    assert _outs(tmp_path, "on") == _outs(tmp_path, "off")

    def keys(d, pre=""):
        out = set()
        for k, v in d.items():
            out.add(pre + k)
            if isinstance(v, dict):
                out |= keys(v, pre + k + ".")
        return out

    off = json.loads((tmp_path / "off.json").read_text())
    on = json.loads((tmp_path / "on.json").read_text())
    assert keys(on) == keys(off)
    assert on["stats_version"] == off["stats_version"]
    # all three sinks landed
    assert (tmp_path / "t.json").is_file()
    assert (tmp_path / "ev.ndjson").is_file()
    assert (tmp_path / "m.prom").is_file()


def test_cli_metrics_textfile_matches_stats(tmp_path):
    paf, fa = _corpus(tmp_path, n=8)
    rc, err = _cli(tmp_path, "m", [
        f"--metrics-textfile={tmp_path / 'm.prom'}"], paf, fa)
    assert rc == 0, err
    text = (tmp_path / "m.prom").read_text()
    assert_valid_exposition(text)
    st = json.loads((tmp_path / "m.json").read_text())
    lines = text.splitlines()
    assert f"pwasm_run_alignments_total {st['alignments']}" in lines
    assert f"pwasm_run_events_total {st['events']}" in lines
    assert "pwasm_run_breaker_state 0" in lines
    assert 'pwasm_run_finished_total{outcome="completed"} 1' in lines
    # no tmp remnant from the atomic publish
    leftovers = [p.name for p in tmp_path.iterdir()
                 if ".prom." in p.name]
    assert leftovers == []


def test_cli_log_json_stdout_dash(tmp_path):
    paf, fa = _corpus(tmp_path, n=4)
    out = io.StringIO()
    err = io.StringIO()
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "d.dfa"),
              "--log-json=-"], stdout=out, stderr=err)
    assert rc == 0, err.getvalue()
    evs = [json.loads(ln) for ln in out.getvalue().splitlines()]
    assert evs[0]["event"] == "run_start"
    assert evs[-1]["event"] == "run_finish"
    assert evs[-1]["rc"] == 0


@pytest.mark.parametrize("flag", ["--trace-json", "--log-json",
                                  "--metrics-textfile"])
def test_obs_flags_require_value(tmp_path, flag):
    paf, fa = _corpus(tmp_path, n=2)
    err = io.StringIO()
    rc = run([paf, "-r", fa, flag], stderr=err)
    assert rc == 1
    assert "requires a file argument" in err.getvalue()


def test_log_json_dash_requires_report_file(tmp_path):
    """Without -o the report itself streams to stdout — event lines
    interleaved with report rows would corrupt both, so the
    combination is a usage error, not a footgun."""
    paf, fa = _corpus(tmp_path, n=2)
    err = io.StringIO()
    rc = run([paf, "-r", fa, "--log-json=-"], stderr=err)
    assert rc == 1
    assert "--log-json=- requires -o" in err.getvalue()


def test_log_json_appends_across_runs(tmp_path):
    """The event log is append-only as documented: a second run (or a
    restarted daemon) extends the incident timeline, never wipes it."""
    paf, fa = _corpus(tmp_path, n=2)
    log = tmp_path / "runs.ndjson"
    for _ in range(2):
        err = io.StringIO()
        rc = run([paf, "-r", fa, "-o", str(tmp_path / "a.dfa"),
                  f"--log-json={log}"], stderr=err)
        assert rc == 0, err.getvalue()
    evs = _events(log)
    assert [e["event"] for e in evs].count("run_start") == 2
    assert len({e["run_id"] for e in evs}) == 2


def test_cli_flap_replay_in_event_log(tmp_path, monkeypatch):
    """The scripted flap (down=3-6) replayed from the NDJSON log: the
    trip, the bounded re-probes, the half-open and the reclose appear
    AS EVENTS in order — and bytes stay identical to the clean run."""
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path)
    rc, err = _cli(tmp_path, "ref", [], paf, fa, device="tpu")
    assert rc == 0, err
    rc, err = _cli(tmp_path, "flap", [
        "--inject-faults=down=3-6", "--max-retries=4",
        "--reprobe-interval=0",
        f"--log-json={tmp_path / 'flap.ndjson'}",
        f"--trace-json={tmp_path / 'flap.trace'}"], paf, fa,
        device="tpu")
    assert rc == 0, err
    assert _outs(tmp_path, "flap") == _outs(tmp_path, "ref")
    evs = _events(tmp_path / "flap.ndjson")
    kinds = [e["event"] for e in evs]
    assert "breaker_trip" in kinds
    assert "reprobe" in kinds
    assert "breaker_half_open" in kinds
    assert "breaker_reclose" in kinds
    # ordering: trip before half-open before reclose
    assert kinds.index("breaker_trip") \
        < kinds.index("breaker_half_open") \
        < kinds.index("breaker_reclose")
    # every event shares the run id and monotonic time never regresses
    assert len({e["run_id"] for e in evs}) == 1
    monos = [e["ts_mono"] for e in evs]
    assert monos == sorted(monos)
    trip = next(e for e in evs if e["event"] == "breaker_trip")
    assert trip["site"] == "ctx_scan" and trip["why"]
    st = json.loads((tmp_path / "flap.json").read_text())["resilience"]
    assert st["breaker_trips"] == 1 and st["breaker_recloses"] >= 1
    # the same transitions land on the trace timeline as instant marks
    tr = json.loads((tmp_path / "flap.trace").read_text())
    instants = {e["name"] for e in tr["traceEvents"]
                if e["ph"] == "i"}
    assert {"breaker_trip", "breaker_reclose"} <= instants


def test_cli_oom_bisection_replay_in_event_log(tmp_path, monkeypatch):
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path, n=16)
    err = io.StringIO()
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "oom.dfa"),
              "--device=tpu", "--batch=8", "--inject-faults=oom=2",
              f"--log-json={tmp_path / 'oom.ndjson'}",
              f"--stats={tmp_path / 'oom.json'}"], stderr=err)
    assert rc == 0, err.getvalue()
    kinds = [e["event"] for e in _events(tmp_path / "oom.ndjson")]
    assert "oom" in kinds and "batch_split" in kinds \
        and "bucket_demotion" in kinds
    assert kinds.index("oom") < kinds.index("bucket_demotion")
    res = json.loads((tmp_path / "oom.json").read_text())["resilience"]
    assert res["oom_events"] > 0 and res["batch_splits"] > 0
    assert res["breaker_trips"] == 0


def test_cli_trace_json_schema_and_nesting(tmp_path, monkeypatch):
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path, n=8)
    rc, err = _cli(tmp_path, "tr", [
        f"--trace-json={tmp_path / 'tr.trace'}"], paf, fa,
        device="tpu")
    assert rc == 0, err
    doc = json.loads((tmp_path / "tr.trace").read_text())
    evs = doc["traceEvents"]
    assert isinstance(evs, list) and evs
    for e in evs:
        assert e["ph"] in ("X", "i")
        assert isinstance(e["ts"], int) and e["ts"] >= 0
        if e["ph"] == "X":
            assert isinstance(e["dur"], int) and e["dur"] >= 0
    names = {e["name"] for e in evs}
    assert {"run", "input_loop", "device_batch",
            "msa_tail"} <= names
    # monotonic nesting: every same-thread span sits inside the run
    # span, and each device_batch sits inside some flush/run interval
    spans = [e for e in evs if e["ph"] == "X"]
    runs = [e for e in spans if e["name"] == "run"]
    assert len(runs) == 1
    r0, r1 = runs[0]["ts"], runs[0]["ts"] + runs[0]["dur"]
    for e in spans:
        if e["tid"] == runs[0]["tid"] and e is not runs[0]:
            assert r0 <= e["ts"] and e["ts"] + e["dur"] <= r1, e


def test_cli_ckpt_write_and_preempt_events(tmp_path, monkeypatch):
    """A scripted preemption drains at a batch boundary: the log shows
    the drain request and a run_finish with rc 75 — the incident
    timeline an operator replays after a fleet preemption."""
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path, n=16)
    err = io.StringIO()
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "p.dfa"),
              "--device=tpu", "--batch=2",
              "--inject-faults=preempt=3",
              f"--log-json={tmp_path / 'p.ndjson'}"], stderr=err)
    assert rc == 75, err.getvalue()
    evs = _events(tmp_path / "p.ndjson")
    kinds = [e["event"] for e in evs]
    assert "ckpt_write" in kinds
    assert "drain" in kinds
    fin = evs[-1]
    assert fin["event"] == "run_finish" and fin["rc"] == 75 \
        and fin["preempted"] is True


def test_observability_facade_null_hooks():
    """The null bundle must absorb every hook cheaply (the default
    wiring for every run without obs flags)."""
    from pwasm_tpu.obs import NULL_OBS
    assert not NULL_OBS.enabled
    with NULL_OBS.span("x", a=1):
        NULL_OBS.event("anything", n=3)
    NULL_OBS.observe("batch_attempt_seconds", 0.1, site="s")
    NULL_OBS.set_gauge("breaker_state", 2)
    NULL_OBS.span_complete("y", NULL_OBS.clock())


def test_make_observability_subsets(tmp_path):
    obs = make_observability()
    assert not obs.enabled
    obs = make_observability(log_json=str(tmp_path / "e.ndjson"))
    assert obs.enabled and obs.registry is None
    obs.event("run_start")
    obs.close(io.StringIO())
    assert _events(tmp_path / "e.ndjson")[0]["event"] == "run_start"
    obs = make_observability(
        metrics_textfile=str(tmp_path / "m.prom"))
    assert obs.registry is not None and obs.run_metrics
    obs.close(io.StringIO())
    assert_valid_exposition((tmp_path / "m.prom").read_text())


def test_observability_wraps_into_supervisor_histogram():
    """The supervisor observes every attempt's wall into the per-site
    histogram — success AND failure attempts."""
    from pwasm_tpu.resilience import BatchSupervisor, ResiliencePolicy
    reg = MetricsRegistry()
    rm = build_run_metrics(reg)
    obs = Observability(registry=reg, run_metrics=rm)
    sup = BatchSupervisor(
        ResiliencePolicy(max_retries=1, backoff_s=0.001,
                         backoff_cap_s=0.002),
        stderr=io.StringIO(), obs=obs, probe=lambda: (True, ""))
    assert sup.run("ctx_scan", lambda: "ok") == "ok"
    boom = [True]

    def flaky():
        if boom.pop() if boom else False:
            raise RuntimeError("transient")
        return "ok2"

    assert sup.run("ctx_scan", flaky) == "ok2"
    h = rm["batch_attempt_seconds"]
    assert h.count(site="ctx_scan") == 3   # 1 + (1 failed + 1 retry)
