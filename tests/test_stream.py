"""Streaming ingestion + multi-CDS jobs (ISSUE 10).

Acceptance contracts:

- **incremental == whole-file**: a streamed run — follow-mode tail of
  a growing file, or stream-data frames over the service socket, with
  records arriving at fuzzed (non-record-aligned) chunk boundaries —
  produces report/-s bytes identical to the one-shot CLI run over the
  same records (incl. the realistic 200-alignment corpus);
- **preemptible/resumable**: a mid-stream SIGTERM drains at a batch
  boundary → exit 75 with a valid checkpoint → ``--resume`` over the
  completed records finishes byte-identically; a daemon kill -9
  mid-stream replays the journal, lands the stream terminal
  preempted-RESUMABLE, and a re-opened ``--resume`` stream completes
  byte-identically;
- **fair share**: a heavy stream at its buffer quota gets queue_full
  backpressure (the client helper backs off on ``retry_backoff_s``)
  while a light concurrent stream feeds and finishes unimpeded;
- **multi-CDS**: a ``--many2many`` job's per-CDS report sections and
  summary roll-up are byte-identical to N single-CDS runs while
  paying ONE backend reachability check (one warm device session).
"""

import io
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from pwasm_tpu.cli import run
from pwasm_tpu.core.errors import EXIT_PREEMPTED, EXIT_USAGE
from pwasm_tpu.core.fasta import write_fasta
from pwasm_tpu.service import protocol
from pwasm_tpu.service.client import ServiceClient, wait_for_socket
from pwasm_tpu.service.daemon import Daemon
from pwasm_tpu.service.queue import QueueFull, StreamBook
from pwasm_tpu.stream.pafstream import (FollowReader, LineAssembler,
                                        StreamFeed)

from helpers import make_paf_line

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the deterministic SLOW job of test_service.py: every supervised
# device call sleeps, stretching wall time without changing bytes
SLOW = "--inject-faults=seed=1,rate=1,kinds=hang,hang_s=0.25"


def _corpus(tmp_path, n=16, qlen=120, seed=3):
    rng = np.random.default_rng(seed)
    q = "".join("ACGT"[i] for i in rng.integers(0, 4, qlen))
    lines = []
    for i in range(n):
        cut = 10 + int(rng.integers(0, qlen - 40))
        qb = q[cut]
        tb = "ACGT"[("ACGT".index(qb) + 1) % 4]
        ops = [("=", cut), ("*", tb, qb), ("=", 20), ("ins", "gg"),
               ("=", qlen - cut - 21)]
        lines.append(make_paf_line("q", q, f"asm{i}", "+", ops)[0])
    fa = tmp_path / "q.fa"
    write_fasta(str(fa), [("q", q.encode())])
    paf = tmp_path / "in.paf"
    paf.write_text("".join(ln + "\n" for ln in lines))
    return str(paf), str(fa), lines


def _oneshot(tmp_path, tag, paf, fa, extra=()):
    err = io.StringIO()
    rc = run([paf, "-r", fa, "-o", str(tmp_path / f"{tag}.dfa"),
              "-s", str(tmp_path / f"{tag}.sum"), "--batch=4"]
             + list(extra), stderr=err)
    assert rc == 0, err.getvalue()[:2000]
    return ((tmp_path / f"{tag}.dfa").read_bytes(),
            (tmp_path / f"{tag}.sum").read_bytes())


def _fuzz_chunks(text, n_cuts, seed):
    rng = np.random.default_rng(seed)
    cuts = sorted(set(rng.integers(1, len(text),
                                   n_cuts).tolist())) + [len(text)]
    chunks, prev = [], 0
    for c in cuts:
        if c > prev:
            chunks.append(text[prev:c])
            prev = c
    return chunks


# ---------------------------------------------------------------------------
# units: assembler, follow reader, feed, quota book
# ---------------------------------------------------------------------------
def test_line_assembler_fuzzed_chunking_rebuilds_lines():
    rng = np.random.default_rng(11)
    for trial in range(20):
        lines = [f"rec{k}\tpayload{'x' * int(rng.integers(0, 9))}\n"
                 for k in range(int(rng.integers(1, 30)))]
        text = "".join(lines)
        if rng.random() < 0.5:
            text = text[:-1]       # final record without its newline
        asm = LineAssembler()
        got = []
        for ch in _fuzz_chunks(text, int(rng.integers(1, 40)),
                               int(rng.integers(0, 1 << 30))):
            assert asm.completed(ch) == ch.count("\n")
            got.extend(asm.push(ch))
        got.extend(asm.flush())
        assert "".join(got) == text         # nothing lost or reordered
        assert len(got) == len(lines)       # record boundaries exact
        assert asm.pending == ""


def test_follow_reader_tails_growth_and_survives_rotation(tmp_path):
    path = str(tmp_path / "grow.paf")
    open(path, "w").close()
    rd = FollowReader(path, idle_timeout_s=0.4, poll_s=0.01)

    def writer():
        with open(path, "a") as f:
            f.write("a1\na2\npar")     # partial line stays pending
            f.flush()
            time.sleep(0.05)
            f.write("tial\n")
            f.flush()
        time.sleep(0.05)
        # rotation: replace the file wholesale (new inode)
        with open(path + ".new", "w") as f:
            f.write("b1\nb2")           # final record, no newline
        os.replace(path + ".new", path)

    t = threading.Thread(target=writer)
    t.start()
    got = list(rd)
    t.join()
    rd.close()
    assert got == ["a1\n", "a2\n", "partial\n", "b1\n", "b2"]
    assert rd.rotations == 1


def test_stream_feed_batches_lag_and_final_partial():
    feed = StreamFeed()
    batches = []
    feed.on_batch = batches.append
    feed.feed("r1\nr2\nr3")
    assert feed.buffered == 2 and feed.records_in == 2
    assert next(feed) == "r1\n" and next(feed) == "r2\n"
    assert feed.buffered == 0 and feed.records_out == 2
    assert batches == [2]          # one arrival batch drained
    feed.feed("-tail\nlast")
    feed.end()                     # the newline-less tail arrives now
    assert list(feed) == ["r3-tail\n", "last"]
    assert feed.batches == 2 and feed.records_out == 4
    with pytest.raises(ValueError):
        feed.feed("too late\n")


def test_stream_feed_drain_wakes_blocked_consumer():
    feed = StreamFeed()
    drain = SimpleNamespace(requested=False)
    feed.bind_drain(drain)
    got = []

    def consume():
        got.extend(feed)

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.1)
    assert t.is_alive()            # blocked waiting for records
    drain.requested = True
    t.join(5)
    assert not t.is_alive() and got == []


def test_stream_book_quota_and_fair_share():
    def fake(buffered):
        return SimpleNamespace(buffered=buffered, records_in=buffered,
                               records_out=0, batches=0)

    book = StreamBook(max_buffer=10)   # global ceiling 40
    heavy, light = fake(0), fake(0)
    book.register("h", "heavy", heavy)
    book.register("l", "light", light)
    book.admit("h", 10)                # exactly at quota: fine
    book.admit("h", 999)  # EMPTY buffer always admits, even a frame
    #   past the whole quota — "resend the same frame" must be able
    #   to make progress, never livelock on an idle daemon
    heavy.buffered = 10
    with pytest.raises(QueueFull, match="buffer quota"):
        book.admit("h", 1)             # per-stream quota
    heavy.buffered = heavy.records_in = 9
    # drive past the GLOBAL ceiling with more streams (43 > 40)
    light.buffered = light.records_in = 1
    for k in range(3):
        book.register(f"o{k}", f"c{k}", fake(11))
    # fair share = 40/5 = 8.  heavy (at 9, under its quota but over
    # its share) is refused; light (at 1, under) still feeds.
    with pytest.raises(QueueFull, match="fair share"):
        book.admit("h", 1)
    book.admit("l", 7)
    with pytest.raises(QueueFull, match="fair share"):
        book.admit("l", 8)
    lag = book.client_lag()
    assert lag["heavy"] == 9 and lag["light"] == 1
    # retirement folds flow counters into the cumulative totals
    book.unregister("h")
    tot = book.totals()
    assert tot["active"] == 4 and tot["records_in"] == 43
    assert book.client_lag()["heavy"] == 0   # series stays, reads 0


# ---------------------------------------------------------------------------
# follow mode end to end
# ---------------------------------------------------------------------------
def test_follow_mode_byte_parity_with_oneshot(tmp_path):
    paf, fa, lines = _corpus(tmp_path)
    want = _oneshot(tmp_path, "one", paf, fa)
    grow = str(tmp_path / "grow.paf")
    open(grow, "w").close()
    text = "".join(ln + "\n" for ln in lines)

    def writer():
        with open(grow, "a") as f:
            for ch in _fuzz_chunks(text, 40, seed=9):
                f.write(ch)
                f.flush()
                time.sleep(0.005)

    t = threading.Thread(target=writer)
    t.start()
    err = io.StringIO()
    rc = run([grow, "--follow=1.0", "-r", fa,
              "-o", str(tmp_path / "fol.dfa"),
              "-s", str(tmp_path / "fol.sum"), "--batch=4"],
             stderr=err)
    t.join()
    assert rc == 0, err.getvalue()[:2000]
    assert ((tmp_path / "fol.dfa").read_bytes(),
            (tmp_path / "fol.sum").read_bytes()) == want


def test_follow_crlf_input_byte_parity_with_oneshot(tmp_path):
    """The one-shot CLI opens its input in text mode (universal
    newlines), so a CRLF PAF must stream to the same bytes — incl. a
    \\r\\n split exactly across two appends."""
    paf, fa, lines = _corpus(tmp_path)
    crlf = str(tmp_path / "crlf.paf")
    open(crlf, "w", newline="").write(
        "".join(ln + "\r\n" for ln in lines))
    want = _oneshot(tmp_path, "one", crlf, fa)
    grow = str(tmp_path / "grow.paf")
    open(grow, "w").close()

    def writer():
        with open(grow, "a", newline="") as f:
            for ln in lines:
                f.write(ln + "\r")    # the \r lands first...
                f.flush()
                time.sleep(0.005)
                f.write("\n")         # ...its \n a poll later
                f.flush()

    t = threading.Thread(target=writer)
    t.start()
    err = io.StringIO()
    rc = run([grow, "--follow=1.0", "-r", fa,
              "-o", str(tmp_path / "fol.dfa"),
              "-s", str(tmp_path / "fol.sum"), "--batch=4"],
             stderr=err)
    t.join()
    assert rc == 0, err.getvalue()[:2000]
    assert ((tmp_path / "fol.dfa").read_bytes(),
            (tmp_path / "fol.sum").read_bytes()) == want


def test_stdin_dash_marker_reads_stdin(tmp_path, monkeypatch):
    """`pafreport - ...` is the documented pipe shape: '-' reads
    stdin exactly like the no-positional form."""
    paf, fa, lines = _corpus(tmp_path, n=4)
    want = _oneshot(tmp_path, "one", paf, fa)[0]
    monkeypatch.setattr(
        "sys.stdin", io.StringIO("".join(ln + "\n" for ln in lines)))
    err = io.StringIO()
    rc = run(["-", "-r", fa, "-o", str(tmp_path / "d.dfa"),
              "--batch=4"], stderr=err)
    assert rc == 0, err.getvalue()[:2000]
    assert (tmp_path / "d.dfa").read_bytes() == want


def test_follow_usage_errors(tmp_path):
    from pwasm_tpu.cli import CliError

    paf, fa, _ = _corpus(tmp_path, n=2)
    with pytest.raises(CliError, match="Invalid --follow"):
        run([paf, "--follow=nope", "-r", fa], stderr=io.StringIO())
    with pytest.raises(CliError, match="requires an input PAF"):
        run(["--follow", "-r", fa], stderr=io.StringIO())


def test_follow_sigterm_midstream_exit75_then_resume_parity(tmp_path):
    """Mid-stream preemption: SIGTERM a live --follow run after its
    first durable checkpoint → exit 75; --resume over the COMPLETED
    file finishes the report byte-identically (the -s summary is
    excluded by the documented resume contract)."""
    paf, fa, lines = _corpus(tmp_path, n=24)
    want = _oneshot(tmp_path, "one", paf, fa)[0]
    grow = str(tmp_path / "grow.paf")
    open(grow, "w").close()
    rep = str(tmp_path / "st.dfa")
    old_pp = os.environ.get("PYTHONPATH", "")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + (os.pathsep + old_pp if old_pp
                                  else ""))
    sp = subprocess.Popen(
        [sys.executable, "-m", "pwasm_tpu.cli", grow, "--follow",
         "-r", fa, "-o", rep, "--batch=4"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        text=True)
    try:
        # feed enough for several durable batches, then hold the rest
        with open(grow, "a") as f:
            f.write("".join(ln + "\n" for ln in lines[:16]))
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if os.path.exists(rep + ".ckpt"):
                break
            assert sp.poll() is None, sp.stderr.read()[:2000]
            time.sleep(0.02)
        assert os.path.exists(rep + ".ckpt"), "no ckpt before signal"
        sp.send_signal(__import__("signal").SIGTERM)
        rc = sp.wait(timeout=60)
        assert rc == EXIT_PREEMPTED, sp.stderr.read()[:2000]
    finally:
        if sp.poll() is None:
            sp.kill()
            sp.wait()
        sp.stderr.close()
    # the writer "finishes" the file; --resume completes the report
    with open(grow, "a") as f:
        f.write("".join(ln + "\n" for ln in lines[16:]))
    err = io.StringIO()
    rc = run([grow, "--resume", "-r", fa, "-o", rep, "--batch=4"],
             stderr=err)
    assert rc == 0, err.getvalue()[:2000]
    assert open(rep, "rb").read() == want


# ---------------------------------------------------------------------------
# socket-stream mode end to end
# ---------------------------------------------------------------------------
def _daemon(**kw):
    sockdir = tempfile.mkdtemp(prefix="pwstream")
    sock = os.path.join(sockdir, "s")
    err = io.StringIO()
    dm = Daemon(sock, stderr=err, **kw)
    rcbox: list = []
    t = threading.Thread(target=lambda: rcbox.append(dm.serve()),
                         daemon=True)
    t.start()
    assert wait_for_socket(sock, 15), err.getvalue()
    return SimpleNamespace(daemon=dm, sock=sock, dir=sockdir,
                           err=err, thread=t, rc=rcbox)


def _stop(h):
    if not h.daemon.drain.requested:
        h.daemon.drain.request("test teardown")
    h.thread.join(30)
    shutil.rmtree(h.dir, ignore_errors=True)


def test_socket_stream_fuzzed_chunks_byte_parity(tmp_path):
    paf, fa, lines = _corpus(tmp_path)
    want = _oneshot(tmp_path, "one", paf, fa)
    text = "".join(ln + "\n" for ln in lines)
    h = _daemon()
    try:
        with ServiceClient(h.sock) as c:
            resp = c.stream(
                ["-r", fa, "-o", str(tmp_path / "st.dfa"),
                 "-s", str(tmp_path / "st.sum"), "--batch=4"],
                iter(_fuzz_chunks(text, 30, seed=5)))
            assert resp.get("ok") and resp["records"] == len(lines)
            res = c.result(resp["job_id"], timeout=120)
            assert res.get("rc") == 0, res
            st = c.stats()["stats"]["streams"]
        assert st["records_in"] == len(lines)
        assert st["batches"] >= 1 and st["active"] == 0
        assert ((tmp_path / "st.dfa").read_bytes(),
                (tmp_path / "st.sum").read_bytes()) == want
    finally:
        _stop(h)


def test_stream_admission_and_frame_validation(tmp_path):
    paf, fa, _ = _corpus(tmp_path, n=4)
    h = _daemon()
    try:
        with ServiceClient(h.sock) as c:
            # a positional PAF in a stream argv is a bad_request
            r = c.stream_open([paf, "-r", fa,
                               "-o", str(tmp_path / "x.dfa")])
            assert not r.get("ok") \
                and r["error"] == protocol.ERR_BAD_REQUEST
            assert "positional" in r["detail"]
            r = c.stream_open(["--follow", "-r", fa,
                               "-o", str(tmp_path / "x.dfa")])
            assert not r.get("ok") and "--follow" in r["detail"]
            # stream frames against a NON-stream job are bad_request
            sub = c.submit([paf, "-r", fa,
                            "-o", str(tmp_path / "sub.dfa")])
            assert sub.get("ok")
            r = c.stream_data(sub["job_id"], "x\n")
            assert not r.get("ok") \
                and r["error"] == protocol.ERR_BAD_REQUEST
            # unknown ids are unknown
            r = c.stream_data("job-9999", "x\n")
            assert not r.get("ok") \
                and r["error"] == protocol.ERR_UNKNOWN_JOB
            # after stream-end, more data is rejected
            so = c.stream_open(["-r", fa,
                                "-o", str(tmp_path / "st.dfa")])
            assert so.get("ok"), so
            assert c.stream_data(so["job_id"], "").get("ok")
            assert c.stream_end(so["job_id"]).get("ok")
            r = c.stream_data(so["job_id"], "x\n")
            assert not r.get("ok") \
                and r["error"] == protocol.ERR_BAD_REQUEST
            res = c.result(so["job_id"], timeout=60)
            assert res.get("rc") == 0    # an empty stream: empty report
    finally:
        _stop(h)


def test_stream_backpressure_heavy_cannot_starve_light(tmp_path):
    """THE fair-share leg: a heavy stream whose producer floods a tiny
    buffer gets queue_full backpressure (handled by the client
    helper's capped-exponential backoff) while a light stream on the
    same daemon feeds, runs, and finishes — before the heavy job is
    even done.  Both byte-identical to their one-shot arms."""
    paf, fa, lines = _corpus(tmp_path, n=30)
    (tmp_path / "l").mkdir(exist_ok=True)
    lpaf, lfa, llines = _corpus(tmp_path / "l", n=4, seed=8)
    heavy_want = _oneshot(tmp_path, "oneh", paf, fa,
                          ["--device=tpu"])[0]
    light_want = _oneshot(tmp_path, "onel", lpaf, lfa)[0]
    h = _daemon(max_concurrent=2, stream_buffer=4)
    heavy_box: dict = {}

    def heavy_run():
        try:
            with ServiceClient(h.sock) as c:
                resp = c.stream(
                    ["-r", fa, "-o", str(tmp_path / "hv.dfa"),
                     "--batch=2", "--device=tpu", SLOW],
                    iter([ln + "\n" for ln in lines]),
                    client="heavy", max_retries=40)
                heavy_box["open"] = resp
                heavy_box["res"] = c.result(resp["job_id"],
                                            timeout=240)
        except Exception as e:       # surfaced by the main thread
            heavy_box["err"] = e

    t = threading.Thread(target=heavy_run)
    t.start()
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline \
                and not h.daemon.streams.active():
            time.sleep(0.01)
        assert h.daemon.streams.active() >= 1
        with ServiceClient(h.sock) as c:
            resp = c.stream(
                ["-r", lfa, "-o", str(tmp_path / "lt.dfa"),
                 "--batch=2"],
                iter([ln + "\n" for ln in llines]), client="light")
            assert resp.get("ok"), resp
            assert resp["backpressure_waits"] == 0
            res = c.result(resp["job_id"], timeout=120)
            assert res.get("rc") == 0, res
        t.join(240)
        assert not t.is_alive()
        assert "err" not in heavy_box, heavy_box.get("err")
        assert heavy_box["open"]["backpressure_waits"] > 0
        assert heavy_box["res"].get("rc") == 0, heavy_box["res"]
        assert (tmp_path / "hv.dfa").read_bytes() == heavy_want
        assert (tmp_path / "lt.dfa").read_bytes() == light_want
        assert (heavy_box["res"]["job"]["finished_s"]
                > res["job"]["finished_s"])
    finally:
        t.join(240)
        _stop(h)


def test_stream_drain_midstream_is_preempted_resumable(tmp_path):
    """A service drain while a stream job waits for records: the job
    exits 75 with a durable ckpt, and a re-opened --resume stream
    over the full record set completes byte-identically."""
    paf, fa, lines = _corpus(tmp_path)
    want = _oneshot(tmp_path, "one", paf, fa)[0]
    rep = str(tmp_path / "st.dfa")
    h = _daemon()
    try:
        with ServiceClient(h.sock) as c:
            so = c.stream_open(["-r", fa, "-o", rep, "--batch=4"])
            assert so.get("ok"), so
            c.stream_data(so["job_id"],
                          "".join(ln + "\n" for ln in lines[:12]))
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline \
                    and not os.path.exists(rep + ".ckpt"):
                time.sleep(0.01)
            assert os.path.exists(rep + ".ckpt")
            c.drain()
            res = c.result(so["job_id"], timeout=120)
        assert res.get("rc") == EXIT_PREEMPTED, res
        assert res["job"]["state"] == "preempted"
        h.thread.join(30)
        assert h.rc == [EXIT_PREEMPTED]
    finally:
        _stop(h)
    # round 2 on a fresh daemon: --resume + the full record set
    h = _daemon()
    try:
        with ServiceClient(h.sock) as c:
            resp = c.stream(
                ["-r", fa, "-o", rep, "--batch=4", "--resume"],
                iter([ln + "\n" for ln in lines]))
            assert resp.get("ok"), resp
            res = c.result(resp["job_id"], timeout=120)
            assert res.get("rc") == 0, res
        assert open(rep, "rb").read() == want
    finally:
        _stop(h)


def _spawn_serve(sock, *extra):
    old_pp = os.environ.get("PYTHONPATH", "")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PWASM_DEVICE_PROBE="0",
               PYTHONPATH=REPO + (os.pathsep + old_pp if old_pp
                                  else ""))
    return subprocess.Popen(
        [sys.executable, "-m", "pwasm_tpu.cli", "serve",
         f"--socket={sock}", *extra],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        text=True)


def test_kill9_midstream_journal_replay_reopen_resume(tmp_path):
    """kill -9 the daemon mid-stream: the restarted daemon's journal
    replay lands the stream terminal preempted-RESUMABLE (its
    connection died with the crash — re-running alone is impossible),
    and a re-opened --resume stream over the full record set
    completes byte-identically to the one-shot arm."""
    paf, fa, lines = _corpus(tmp_path, n=24)
    want = _oneshot(tmp_path, "one", paf, fa)[0]
    rep = str(tmp_path / "st.dfa")
    sockdir = tempfile.mkdtemp(prefix="pwstream9")
    sock = os.path.join(sockdir, "s")
    sp = _spawn_serve(sock)
    sp2 = None
    try:
        assert wait_for_socket(sock, 60)
        with ServiceClient(sock) as c:
            so = c.stream_open(["-r", fa, "-o", rep, "--batch=4"])
            assert so.get("ok"), so
            jid = so["job_id"]
            c.stream_data(jid,
                          "".join(ln + "\n" for ln in lines[:16]))
            deadline = time.monotonic() + 90
            mid = False
            while time.monotonic() < deadline:
                st = c.status(jid)["job"]["state"]
                if st == "running" and os.path.exists(rep + ".ckpt"):
                    mid = True
                    break
                assert st in ("queued", "running"), st
                time.sleep(0.02)
            assert mid, "stream never reached mid-run with a ckpt"
        sp.kill()                    # SIGKILL: no drain, no cleanup
        sp.wait(timeout=30)
        assert os.path.exists(sock + ".journal")
        sp2 = _spawn_serve(sock)
        assert wait_for_socket(sock, 60)
        with ServiceClient(sock) as c:
            ra = c.result(jid, timeout=60)
            assert ra.get("rc") == EXIT_PREEMPTED, ra
            assert ra["job"]["state"] == "preempted"
            assert "re-open the stream with --resume" \
                in ra["job"]["detail"]
            st = c.stats()["stats"]
            assert st["journal"]["replays"] == 1
            # the replayed verdict is DURABLE: feeding the dead id is
            # an error, not a silent buffer
            r = c.stream_data(jid, "x\n")
            assert not r.get("ok")
            # round 2: re-open with --resume, re-send everything
            resp = c.stream(
                ["-r", fa, "-o", rep, "--batch=4", "--resume"],
                iter([ln + "\n" for ln in lines]))
            assert resp.get("ok"), resp
            res = c.result(resp["job_id"], timeout=240)
            assert res.get("rc") == 0, res
            c.drain()
        assert sp2.wait(timeout=120) == EXIT_PREEMPTED
        assert open(rep, "rb").read() == want
        assert not os.path.exists(sock + ".journal")
    finally:
        for p in (sp, sp2):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()
            if p is not None:
                p.stderr.close()
        shutil.rmtree(sockdir, ignore_errors=True)


def test_stream_keepalive_outlives_idle_reaper(tmp_path):
    """A slow producer (silent longer than --stream-idle-s) survives
    when the client helper heartbeats empty frames (keepalive_s);
    without the heartbeat, the reaper drains the job
    preempted-resumable — never silently complete."""
    paf, fa, lines = _corpus(tmp_path, n=6)
    want = _oneshot(tmp_path, "one", paf, fa)[0]

    def slow_chunks():
        yield lines[0] + "\n"
        time.sleep(1.2)               # > stream_idle_s
        yield "".join(ln + "\n" for ln in lines[1:])

    h = _daemon(stream_idle_s=0.4)
    try:
        with ServiceClient(h.sock) as c:
            resp = c.stream(["-r", fa,
                             "-o", str(tmp_path / "ka.dfa"),
                             "--batch=4"], slow_chunks(),
                            keepalive_s=0.1)
            assert resp.get("ok"), resp
            res = c.result(resp["job_id"], timeout=60)
            assert res.get("rc") == 0, res
            assert (tmp_path / "ka.dfa").read_bytes() == want
            # the no-heartbeat arm: the reaper preempts, resumable
            so = c.stream_open(["-r", fa,
                                "-o", str(tmp_path / "idle.dfa")])
            assert so.get("ok"), so
            res = c.result(so["job_id"], timeout=60)
            assert res.get("rc") == EXIT_PREEMPTED, res
            assert res["job"]["state"] == "preempted"
    finally:
        _stop(h)


def test_stream_oversized_frame_admits_and_tail_flood_rejected(
        tmp_path):
    """Two admission edges: (1) one frame carrying more records than
    the whole --stream-buffer quota is admitted when the stream's
    buffer is empty (the resend-the-same-frame contract must never
    livelock) and the job completes byte-identically; (2) a client
    flooding newline-LESS chunks cannot grow the partial-record tail
    past MAX_RECORD_BYTES — the daemon answers bad_request (not
    queue_full: no resend can help), bounding daemon memory."""
    from pwasm_tpu.stream.pafstream import MAX_RECORD_BYTES

    paf, fa, lines = _corpus(tmp_path)
    want = _oneshot(tmp_path, "one", paf, fa)[0]
    h = _daemon(stream_buffer=4)     # quota far under len(lines)
    try:
        with ServiceClient(h.sock) as c:
            so = c.stream_open(["-r", fa,
                                "-o", str(tmp_path / "big.dfa"),
                                "--batch=4"])
            assert so.get("ok"), so
            # ONE frame with every record: > quota, buffer empty
            r = c.stream_data(so["job_id"],
                              "".join(ln + "\n" for ln in lines))
            assert r.get("ok"), r
            assert c.stream_end(so["job_id"]).get("ok")
            res = c.result(so["job_id"], timeout=120)
            assert res.get("rc") == 0, res
            assert (tmp_path / "big.dfa").read_bytes() == want

            # newline-less flood: bounded by the record-byte ceiling
            so = c.stream_open(["-r", fa,
                                "-o", str(tmp_path / "fl.dfa")])
            assert so.get("ok"), so
            chunk = "x" * (1 << 20)
            rejected = None
            for _ in range(8):       # 8 MiB attempted > 4 MiB cap
                r = c.stream_data(so["job_id"], chunk)
                if not r.get("ok"):
                    rejected = r
                    break
            assert rejected is not None
            assert rejected["error"] == protocol.ERR_BAD_REQUEST
            assert "unterminated" in rejected["detail"]
            assert h.daemon.jobs[so["job_id"]].feed.tail_bytes \
                <= MAX_RECORD_BYTES
            c.cancel(so["job_id"])
    finally:
        _stop(h)


def test_stream_cli_verb_pipes_stdin(tmp_path, monkeypatch):
    """`pwasm-tpu stream --socket=S -- <job args>`: the minimap2-pipe
    shape — stdin is streamed record-at-a-time and the verb exits
    with the job's exit code, byte-identical to the one-shot run."""
    paf, fa, lines = _corpus(tmp_path)
    want = _oneshot(tmp_path, "one", paf, fa)
    h = _daemon()
    try:
        monkeypatch.setattr(
            "sys.stdin", io.StringIO("".join(ln + "\n"
                                             for ln in lines)))
        out = io.StringIO()
        err = io.StringIO()
        rc = run(["stream", f"--socket={h.sock}", "--",
                  "-r", fa, "-o", str(tmp_path / "sv.dfa"),
                  "-s", str(tmp_path / "sv.sum"), "--batch=4"],
                 stdout=out, stderr=err)
        assert rc == 0, err.getvalue()[:2000]
        verdict = json.loads(out.getvalue())
        assert verdict["state"] == "done" and verdict["rc"] == 0
        assert ((tmp_path / "sv.dfa").read_bytes(),
                (tmp_path / "sv.sum").read_bytes()) == want
    finally:
        _stop(h)


# ---------------------------------------------------------------------------
# realistic-scale acceptance: streamed == one-shot, all three routes
# ---------------------------------------------------------------------------
def test_realistic_stream_follow_and_socket_byte_parity(tmp_path):
    from test_realistic_scale import make_corpus

    qseq, lines = make_corpus()
    fa = tmp_path / "cds.fa"
    fa.write_text(f">cds1\n{qseq}\n")
    paf = tmp_path / "in.paf"
    text = "".join(ln + "\n" for ln in lines)
    paf.write_text(text)
    want = _oneshot(tmp_path, "one", str(paf), str(fa))

    # follow-mode arm: the corpus arrives in bursts
    grow = str(tmp_path / "grow.paf")
    open(grow, "w").close()
    chunks = _fuzz_chunks(text, 12, seed=13)

    def writer():
        with open(grow, "a") as f:
            for ch in chunks:
                f.write(ch)
                f.flush()
                time.sleep(0.01)

    t = threading.Thread(target=writer)
    t.start()
    err = io.StringIO()
    rc = run([grow, "--follow=1.5", "-r", str(fa),
              "-o", str(tmp_path / "fol.dfa"),
              "-s", str(tmp_path / "fol.sum"), "--batch=4"],
             stderr=err)
    t.join()
    assert rc == 0, err.getvalue()[:2000]
    assert ((tmp_path / "fol.dfa").read_bytes(),
            (tmp_path / "fol.sum").read_bytes()) == want

    # socket arm: fuzzed frames through a warm daemon
    h = _daemon()
    try:
        with ServiceClient(h.sock) as c:
            resp = c.stream(
                ["-r", str(fa), "-o", str(tmp_path / "soc.dfa"),
                 "-s", str(tmp_path / "soc.sum"), "--batch=4"],
                iter(_fuzz_chunks(text, 60, seed=17)))
            assert resp.get("ok") and resp["records"] == len(lines)
            res = c.result(resp["job_id"], timeout=240)
            assert res.get("rc") == 0, res
        assert ((tmp_path / "soc.dfa").read_bytes(),
                (tmp_path / "soc.sum").read_bytes()) == want
    finally:
        _stop(h)


# ---------------------------------------------------------------------------
# multi-CDS jobs (--many2many)
# ---------------------------------------------------------------------------
def _m2m_fixture(tmp_path, n_q=4, n_t=6, seed=5):
    rng = np.random.default_rng(seed)

    def seq(n):
        return "".join("ACGT"[i]
                       for i in rng.integers(0, 4, n)).encode()

    qs = [(f"cds{i}", seq(120 + (i % 3) * 40)) for i in range(n_q)]
    ts = [(f"asm{i}", seq(150 + 17 * i)) for i in range(n_t)]
    qfa = str(tmp_path / "q.fa")
    write_fasta(qfa, qs)
    tfa = str(tmp_path / "t.fa")
    write_fasta(tfa, ts)
    return qs, ts, qfa, tfa


def test_many2many_multi_vs_single_byte_parity(tmp_path):
    """THE multi-CDS acceptance: one --many2many job's per-CDS report
    sections and -s roll-up are byte-identical to N single-CDS runs,
    while the multi job pays ONE backend reachability check (probes +
    warm_hits == 1 in --stats) vs one per run sequentially."""
    qs, _ts, qfa, tfa = _m2m_fixture(tmp_path)
    err = io.StringIO()
    rc = run(["--many2many", tfa, "-r", qfa,
              "-o", str(tmp_path / "m.tsv"),
              "-s", str(tmp_path / "m.sum"), "--device=tpu",
              f"--stats={tmp_path / 'm.json'}"], stderr=err)
    assert rc == 0, err.getvalue()[:2000]
    multi = (tmp_path / "m.tsv").read_bytes()
    msum = (tmp_path / "m.sum").read_bytes()
    bk = json.loads((tmp_path / "m.json").read_text())["backend"]
    assert bk["probes"] + bk["warm_hits"] == 1   # ONE session
    body = b""
    ssum = b""
    checks = 0
    for name, s in qs:
        q1 = str(tmp_path / f"{name}.fa")
        write_fasta(q1, [(name, s)])
        err = io.StringIO()
        rc = run(["--many2many", tfa, "-r", q1,
                  "-o", str(tmp_path / f"{name}.tsv"),
                  "-s", str(tmp_path / f"{name}.sum"),
                  "--device=tpu",
                  f"--stats={tmp_path / f'{name}.json'}"],
                 stderr=err)
        assert rc == 0, err.getvalue()[:2000]
        body += (tmp_path / f"{name}.tsv").read_bytes()
        ssum += (tmp_path / f"{name}.sum").read_bytes()
        bk = json.loads(
            (tmp_path / f"{name}.json").read_text())["backend"]
        checks += bk["probes"] + bk["warm_hits"]
    assert body == multi          # per-CDS sections: byte-identical
    assert ssum == msum           # summary roll-up concatenates
    assert checks == len(qs)      # sequential pays one PER RUN


def test_many2many_cpu_tpu_parity_and_stdout(tmp_path):
    _qs, _ts, qfa, tfa = _m2m_fixture(tmp_path, n_q=2, n_t=3)
    out = io.StringIO()
    rc = run(["--many2many", tfa, "-r", qfa], stdout=out,
             stderr=io.StringIO())
    assert rc == 0
    cpu_body = out.getvalue()
    assert cpu_body.startswith(">cds0\t")
    err = io.StringIO()
    rc = run(["--many2many", tfa, "-r", qfa, "--device=tpu",
              "-o", str(tmp_path / "t.tsv")], stderr=err)
    assert rc == 0, err.getvalue()[:2000]
    assert (tmp_path / "t.tsv").read_text() == cpu_body


def test_many2many_usage_errors(tmp_path):
    _qs, _ts, qfa, tfa = _m2m_fixture(tmp_path, n_q=1, n_t=1)
    cases = [
        (["--many2many", tfa], "required"),             # no -r
        (["--many2many", "-r", qfa], "exactly one"),    # no targets
        (["--many2many", tfa, tfa, "-r", qfa], "exactly one"),
        (["--many2many", tfa, "-r", qfa, "--band=x"], "--band"),
        (["--many2many", tfa, "-r", qfa, "-w", "x.mfa"],
         "does not apply"),
        (["--many2many", tfa, "-r", qfa, "--follow"],
         "does not apply"),
        (["--many2many", tfa, "-r", qfa, "--device=gpu"],
         "--device"),
    ]
    for argv, needle in cases:
        err = io.StringIO()
        assert run(argv, stderr=err) == EXIT_USAGE, argv
        assert needle in err.getvalue(), (argv, err.getvalue()[:500])
    err = io.StringIO()
    assert run(["--many2many", str(tmp_path / "absent.fa"),
                "-r", qfa, "-o", str(tmp_path / "x.tsv")],
               stderr=err) != 0
    assert "invalid FASTA" in err.getvalue()


def test_many2many_as_service_job_warm_session(tmp_path):
    """A --many2many submit is a first-class service citizen: the
    daemon validates and runs it like any job, bytes match the cold
    run, and the SECOND m2m job answers its reachability check from
    the warm process (probes == 0, warm_hits == 1)."""
    _qs, _ts, qfa, tfa = _m2m_fixture(tmp_path)
    err = io.StringIO()
    rc = run(["--many2many", tfa, "-r", qfa,
              "-o", str(tmp_path / "cold.tsv"), "--device=tpu"],
             stderr=err)
    assert rc == 0, err.getvalue()[:2000]
    want = (tmp_path / "cold.tsv").read_bytes()
    h = _daemon()
    try:
        for j in (1, 2):
            with ServiceClient(h.sock) as c:
                sub = c.submit(
                    ["--many2many", tfa, "-r", qfa,
                     "-o", str(tmp_path / f"w{j}.tsv"),
                     "--device=tpu",
                     f"--stats={tmp_path / f'w{j}.json'}"])
                assert sub.get("ok"), sub
                res = c.result(sub["job_id"], timeout=120)
            assert res.get("rc") == 0, res
            assert (tmp_path / f"w{j}.tsv").read_bytes() == want
        bk = json.loads((tmp_path / "w2.json").read_text())["backend"]
        assert bk["probes"] == 0 and bk["warm_hits"] == 1
    finally:
        _stop(h)


def test_follow_restart_on_grown_file_is_delta_hit(tmp_path):
    """ISSUE 17a: a --follow run that idle-ends populates the cache
    under its FOLLOW-LESS key; a follow restart after the file grew
    is served as a delta — the cached prefix is written, only the
    tail is computed — and the output is byte-identical to a cold
    one-shot over the grown file."""
    paf, fa, lines = _corpus(tmp_path, n=20)
    grow = str(tmp_path / "grow.paf")
    open(grow, "w").write("".join(ln + "\n" for ln in lines[:15]))
    cd = str(tmp_path / "cd")
    err = io.StringIO()
    rc = run([grow, "-r", fa, "-o", str(tmp_path / "a.dfa"),
              "--follow=0.3", f"--result-cache={cd}"], stderr=err)
    assert rc == 0, err.getvalue()[:2000]
    # the idle-ended pass populated a delta-indexed entry
    assert any(n.endswith(".dx") for n in os.listdir(cd))
    # the file grows between runs; the restart delta-hits + tails
    open(grow, "a").write("".join(ln + "\n" for ln in lines[15:]))
    stj = str(tmp_path / "b.json")
    err = io.StringIO()
    rc = run([grow, "-r", fa, "-o", str(tmp_path / "b.dfa"),
              "--follow=0.3", f"--result-cache={cd}",
              f"--stats={stj}"], stderr=err)
    assert rc == 0, err.getvalue()[:2000]
    st = json.load(open(stj))
    assert st["cache_delta"] is True
    assert st["cache_records_served"] == 14     # last record re-runs
    assert st["cache_records_total"] == 20
    # byte parity vs a cold one-shot over the grown file
    err = io.StringIO()
    assert run([grow, "-r", fa, "-o", str(tmp_path / "c.dfa")],
               stderr=err) == 0, err.getvalue()
    assert (tmp_path / "b.dfa").read_bytes() \
        == (tmp_path / "c.dfa").read_bytes()
