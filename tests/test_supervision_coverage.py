"""Tier-1 gate for the static supervision-coverage check: every device
round-trip entry point in ``pwasm_tpu/`` (jit programs, explicit
host<->device transfers) must live in a module registered against a
``BatchSupervisor.run`` site — new device code cannot silently bypass
the resilience layer (ISSUE 3 satellite)."""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def checker():
    for p in (REPO, os.path.join(REPO, "qa")):
        if p not in sys.path:
            sys.path.insert(0, p)
    import check_supervision
    return check_supervision


def test_all_device_entry_points_registered(checker):
    bad = checker.find_unregistered()
    assert bad == [], "\n".join(bad)


def test_registry_has_no_stale_entries(checker):
    stale = checker.stale_registry_entries()
    assert stale == [], stale


def test_service_modules_stay_jax_free(checker):
    """ISSUE 5 satellite: the warm-pool service layer reaches the
    device ONLY through cli.run's supervised sites — no direct jax
    use (not even an import) in pwasm_tpu/service/."""
    bad = checker.find_service_violations()
    assert bad == [], "\n".join(bad)


def test_service_rule_detects_direct_jax(checker, tmp_path):
    svc = tmp_path / "pwasm_tpu" / "service"
    svc.mkdir(parents=True)
    (svc / "rogue.py").write_text(
        "import jax\n"
        "from pwasm_tpu import cli\n"     # not a hit
        "# import jax in a comment is NOT a hit\n"
        "y = jax.device_get(1)\n")
    bad = checker.find_service_violations(str(tmp_path))
    assert len(bad) == 2, bad
    assert all("rogue.py" in b for b in bad)
    # a tree without a service dir is trivially clean
    assert checker.find_service_violations(str(tmp_path / "empty")) \
        == []


def test_checker_detects_patterns(checker, tmp_path):
    # the check must actually SEE a violation, or a pattern regression
    # (e.g. jax API rename) would silently pass forever
    pkg = tmp_path / "pwasm_tpu"
    pkg.mkdir()
    (pkg / "rogue.py").write_text(
        "import jax\n"
        "f = jax.jit(lambda x: x)\n"
        "y = jax.device_put(1)\n"
        "# jax.device_get(y) in a comment is NOT a hit\n"
        "z = f(y).block_until_ready()\n")
    bad = checker.find_unregistered(str(tmp_path))
    assert len(bad) == 3, bad
    assert all("rogue.py" in b for b in bad)
