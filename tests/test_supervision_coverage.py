"""Tier-1 gate for the static supervision-coverage check: every device
round-trip entry point in ``pwasm_tpu/`` (jit programs, explicit
host<->device transfers) must live in a module registered against a
``BatchSupervisor.run`` site — new device code cannot silently bypass
the resilience layer (ISSUE 3 satellite)."""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def checker():
    for p in (REPO, os.path.join(REPO, "qa")):
        if p not in sys.path:
            sys.path.insert(0, p)
    import check_supervision
    return check_supervision


def test_all_device_entry_points_registered(checker):
    bad = checker.find_unregistered()
    assert bad == [], "\n".join(bad)


def test_registry_has_no_stale_entries(checker):
    stale = checker.stale_registry_entries()
    assert stale == [], stale


def test_service_modules_stay_jax_free(checker):
    """ISSUE 5 satellite: the warm-pool service layer reaches the
    device ONLY through cli.run's supervised sites — no direct jax
    use (not even an import) in pwasm_tpu/service/."""
    bad = checker.find_service_violations()
    assert bad == [], "\n".join(bad)


def test_service_rule_detects_direct_jax(checker, tmp_path):
    svc = tmp_path / "pwasm_tpu" / "service"
    svc.mkdir(parents=True)
    (svc / "rogue.py").write_text(
        "import jax\n"
        "from pwasm_tpu import cli\n"     # not a hit
        "# import jax in a comment is NOT a hit\n"
        "y = jax.device_get(1)\n")
    bad = checker.find_service_violations(str(tmp_path))
    assert len(bad) == 2, bad
    assert all("rogue.py" in b for b in bad)
    # a tree without a service dir is trivially clean
    assert checker.find_service_violations(str(tmp_path / "empty")) \
        == []


def test_obs_modules_stay_jax_free(checker):
    """ISSUE 6 satellite: pwasm_tpu/obs/ must stay jax-free — the
    observability layer runs on the plain-CPU path, inside the jax-free
    daemon, and in signal-handler-adjacent code."""
    bad = checker.find_obs_violations()
    assert bad == [], "\n".join(bad)


def test_obs_rule_detects_direct_jax(checker, tmp_path):
    obs = tmp_path / "pwasm_tpu" / "obs"
    obs.mkdir(parents=True)
    (obs / "rogue.py").write_text(
        "import jax\n"
        "# import jax in a comment is NOT a hit\n"
        "y = jax.device_get(1)\n")
    bad = checker.find_obs_violations(str(tmp_path))
    assert len(bad) == 2 and all("rogue.py" in b for b in bad)


def test_stream_modules_stay_jax_free(checker):
    """ISSUE 10 satellite: pwasm_tpu/stream/ must stay jax-free —
    the streaming readers run inside the daemon and around signal
    handling, and the multi-CDS driver reaches the device only
    through the supervised many2many site in pwasm_tpu/parallel/."""
    bad = checker.find_stream_violations()
    assert bad == [], "\n".join(bad)


def test_stream_rule_detects_direct_jax(checker, tmp_path):
    stream = tmp_path / "pwasm_tpu" / "stream"
    stream.mkdir(parents=True)
    (stream / "rogue.py").write_text(
        "import jax\n"
        "from pwasm_tpu.parallel.many2many import "
        "many2many_scores_ragged\n"          # lazy-import style: NOT
        "# import jax in a comment is NOT a hit\n"
        "y = jax.device_put(1)\n")
    bad = checker.find_stream_violations(str(tmp_path))
    assert len(bad) == 2 and all("rogue.py" in b for b in bad)
    # a tree without a stream dir is trivially clean
    assert checker.find_stream_violations(str(tmp_path / "no")) == []


def test_fleet_modules_stay_jax_free(checker):
    """ISSUE 13 satellite: pwasm_tpu/fleet/ must stay jax-free — the
    router/transport/ledger move protocol frames and read journals;
    every device touch happens inside a member daemon's cli.run,
    behind the supervised sites."""
    bad = checker.find_fleet_violations()
    assert bad == [], "\n".join(bad)


def test_fleet_rule_detects_direct_jax(checker, tmp_path):
    fleet = tmp_path / "pwasm_tpu" / "fleet"
    fleet.mkdir(parents=True)
    (fleet / "rogue.py").write_text(
        "from jax import numpy as jnp\n"
        "# import jax in a comment is NOT a hit\n"
        "y = jnp.zeros(3).block_until_ready()\n")
    bad = checker.find_fleet_violations(str(tmp_path))
    assert len(bad) == 2 and all("rogue.py" in b for b in bad)
    assert checker.find_fleet_violations(str(tmp_path / "no")) == []


def test_cache_gate_clean_on_this_tree(checker):
    """ISSUE 15 satellite: service/cache.py exists and is jax-free —
    the content-addressed result cache is on every serving tier's
    admission hot path."""
    bad = checker.find_cache_violations()
    assert bad == [], "\n".join(bad)


def test_cache_gate_detects_missing_and_jax(checker, tmp_path):
    # a tree without the module at all: the existence half fires
    bad = checker.find_cache_violations(str(tmp_path))
    assert len(bad) == 1 and "missing" in bad[0], bad
    # a tree where the cache module imports jax: the jax-free half
    svc = tmp_path / "pwasm_tpu" / "service"
    svc.mkdir(parents=True)
    (svc / "cache.py").write_text(
        "import jax\n"
        "# import jax in a comment is NOT a hit\n"
        "def get(key):\n    return jax.device_get(key)\n")
    bad = checker.find_cache_violations(str(tmp_path))
    assert all("cache.py" in b for b in bad)
    jax_bad = [b for b in bad if "touches jax" in b]
    assert len(jax_bad) == 2, bad
    # the delta-serving surface (ISSUE 17) is required alongside
    # jax-freedom: a cache module without it can only answer exact
    # repeats, and every missing symbol is its own violation
    sym_bad = [b for b in bad if "missing `" in b]
    assert len(sym_bad) == len(checker.CACHE_DELTA_SYMBOLS), bad


def test_fencing_gate_clean_on_this_tree(checker):
    """ISSUE 16 satellite: fleet/fencing.py exists, and every
    ``--resume`` re-admission site in pwasm_tpu/ either routes the
    job's epoch through readmit_epoch_guard in the same function or
    is a registered single-process exemption — no failover path can
    re-place a started job without the epoch fence."""
    bad = checker.find_fencing_violations()
    assert bad == [], "\n".join(bad)


def test_fencing_gate_detects_violations(checker, tmp_path):
    # a tree without the fencing module at all: the existence half
    bad = checker.find_fencing_violations(str(tmp_path))
    assert len(bad) == 1 and "missing" in bad[0], bad
    pkg = tmp_path / "pwasm_tpu"
    (pkg / "fleet").mkdir(parents=True)
    (pkg / "service").mkdir(parents=True)
    (pkg / "fleet" / "fencing.py").write_text(
        "def readmit_epoch_guard(job_epoch, fleet_epoch):\n"
        "    return fleet_epoch\n")
    # a guard-registered site WITHOUT the epoch check: a hit
    (pkg / "fleet" / "router.py").write_text(
        "def _recover(argv, resume):\n"
        "    if resume:\n"
        "        argv = argv + ['--resume']\n"
        "    return argv\n")
    # the daemon's single-process self-replay is exempt: NOT a hit
    (pkg / "service" / "daemon.py").write_text(
        "def _replay(run_argv, resume):\n"
        "    if resume:\n"
        "        run_argv.append('--resume')\n")
    # an UNREGISTERED module growing a re-admission path: a hit
    (pkg / "rogue.py").write_text(
        "# argv.append('--resume') in a comment is NOT a hit\n"
        "def readmit(argv):\n"
        "    argv.append('--resume')\n")
    bad = checker.find_fencing_violations(str(tmp_path))
    assert len(bad) == 2, bad
    assert any("router.py" in b and "epoch fence" in b for b in bad)
    assert any("rogue.py" in b and "unregistered" in b for b in bad)
    # calling the guard earlier in the SAME function clears the site
    (pkg / "fleet" / "router.py").write_text(
        "def _recover(argv, resume, job_epoch, fleet_epoch):\n"
        "    epoch = readmit_epoch_guard(job_epoch, fleet_epoch)\n"
        "    if resume:\n"
        "        argv = argv + ['--resume']\n"
        "    return argv, epoch\n")
    bad = checker.find_fencing_violations(str(tmp_path))
    assert len(bad) == 1 and "rogue.py" in bad[0], bad


def test_metric_lint_clean_on_this_tree(checker):
    """ISSUE 6 satellite: every metric registration lives in
    obs/catalog.py, with snake_case pwasm_-prefixed unique names."""
    bad = checker.find_metric_lint()
    assert bad == [], "\n".join(bad)


def test_metric_lint_detects_violations(checker, tmp_path):
    pkg = tmp_path / "pwasm_tpu"
    (pkg / "obs").mkdir(parents=True)
    # registrations outside the catalog — the CALL alone is the
    # violation, so a multi-line registration (the repo's normal
    # style, name literal on the next line) must be caught too
    (pkg / "rogue.py").write_text(
        'c = reg.counter("pwasm_rogue_total", "h")\n'
        '# reg.counter("pwasm_commented_total") is NOT a hit\n'
        'h = reg.histogram(\n'
        '    "pwasm_sneaky_seconds", "multi-line style")\n')
    # a catalog with a bad name and a duplicate
    (pkg / "obs" / "catalog.py").write_text(
        'a = reg.gauge("pwasm_ok_depth", "h")\n'
        'b = reg.gauge("pwasm_BadName", "h")\n'
        'c = reg.counter("pwasm_ok_depth", "h")\n')
    bad = checker.find_metric_lint(str(tmp_path))
    assert len(bad) == 4, bad
    assert sum("outside the catalog" in b for b in bad) == 2
    assert any("violates the grammar" in b for b in bad)
    assert any("duplicate metric name" in b for b in bad)


def test_metric_doc_drift_clean_on_this_tree(checker):
    """ISSUE 11 satellite: every family registered in obs/catalog.py
    appears in docs/OBSERVABILITY.md — the doc is the operator's
    catalog of record, so an undocumented series is lint-fatal."""
    bad = checker.find_doc_drift()
    assert bad == [], "\n".join(bad)


def test_metric_doc_drift_detects_undocumented(checker, tmp_path):
    (tmp_path / "pwasm_tpu" / "obs").mkdir(parents=True)
    (tmp_path / "docs").mkdir()
    (tmp_path / "pwasm_tpu" / "obs" / "catalog.py").write_text(
        'a = reg.gauge("pwasm_documented_depth", "h")\n'
        'b = reg.counter(\n'
        '    "pwasm_missing_total", "multi-line style")\n'
        '# "pwasm_commented_total" is NOT a registration\n')
    (tmp_path / "docs" / "OBSERVABILITY.md").write_text(
        "| `pwasm_documented_depth` | fine |\n")
    bad = checker.find_doc_drift(str(tmp_path))
    assert len(bad) == 1, bad
    assert "pwasm_missing_total" in bad[0]
    assert "OBSERVABILITY.md" in bad[0]
    # a doc-less tree flags every name
    (tmp_path / "docs" / "OBSERVABILITY.md").unlink()
    assert len(checker.find_doc_drift(str(tmp_path))) == 2


def test_checker_detects_patterns(checker, tmp_path):
    # the check must actually SEE a violation, or a pattern regression
    # (e.g. jax API rename) would silently pass forever
    pkg = tmp_path / "pwasm_tpu"
    pkg.mkdir()
    (pkg / "rogue.py").write_text(
        "import jax\n"
        "f = jax.jit(lambda x: x)\n"
        "y = jax.device_put(1)\n"
        "# jax.device_get(y) in a comment is NOT a hit\n"
        "z = f(y).block_until_ready()\n")
    bad = checker.find_unregistered(str(tmp_path))
    assert len(bad) == 3, bad
    assert all("rogue.py" in b for b in bad)


def test_sharding_api_routed_through_jaxcompat(checker):
    """ISSUE 8 satellite: every sharding/collective API use in
    pwasm_tpu/ goes through utils/jaxcompat.py — no bare shard_map
    imports or jax.lax.psum/ppermute/pcast calls outside the shim, so
    the next jax surface move costs one edit there."""
    bad = checker.find_sharding_violations()
    assert bad == [], "\n".join(bad)


def test_sharding_rule_detects_bare_collectives(checker, tmp_path):
    pkg = tmp_path / "pwasm_tpu"
    (pkg / "utils").mkdir(parents=True)
    (pkg / "rogue.py").write_text(
        "from jax.experimental.shard_map import shard_map\n"
        "from jax import jit, shard_map\n"
        "from pwasm_tpu.utils.jaxcompat import shard_map  # NOT a hit\n"
        "# jax.lax.psum(x, 'd') in a comment is NOT a hit\n"
        "t = jax.lax.psum(x, 'depth')\n"
        "u = lax.ppermute(x, 'seq', perm)\n"
        "v = jax.shard_map(f, mesh=m)\n")
    # the shim itself is exempt — it IS the one place the raw APIs live
    (pkg / "utils" / "jaxcompat.py").write_text(
        "from jax.experimental.shard_map import shard_map\n")
    bad = checker.find_sharding_violations(str(tmp_path))
    assert len(bad) == 5, bad
    assert all("rogue.py" in b for b in bad)


# ---------------------------------------------------------------------------
# monotonic-clock audit (ISSUE 18): duration arithmetic on time.time()
# is a gray failure waiting for an NTP step
# ---------------------------------------------------------------------------

def test_clock_gate_clean_on_this_tree(checker):
    bad = checker.find_clock_violations()
    assert bad == [], "\n".join(bad)


def test_clock_allowlist_has_no_stale_rows(checker):
    assert checker.stale_clock_allowlist() == []


def test_clock_rule_detects_wall_clock_durations(checker, tmp_path):
    pkg = tmp_path / "pwasm_tpu"
    pkg.mkdir()
    (pkg / "rogue.py").write_text(
        "t0 = time.time()\n"
        "work()\n"
        "wait_s = time.time() - t0\n"
        "neg = t0 - time.time()\n"
        "# elapsed = time.time() - t0 in a comment is NOT a hit\n"
        "stamp = time.time()   # bare stamps are fine\n")
    bad = checker.find_clock_violations(str(tmp_path))
    assert len(bad) == 2, bad
    assert all("rogue.py" in b for b in bad)
    assert all("time.monotonic()" in b for b in bad)


def test_clock_allowlist_rows_must_stay_live(checker, tmp_path):
    # an allowlisted file with no subtraction left (or missing
    # entirely) is a STALE row — the gate must say so, not silently
    # keep the exemption around for the next regression to hide under
    (tmp_path / "pwasm_tpu" / "service").mkdir(parents=True)
    (tmp_path / "pwasm_tpu" / "service" / "cache.py").write_text(
        "x = 1\n")
    stale = checker.stale_clock_allowlist(str(tmp_path))
    assert "pwasm_tpu/service/cache.py" in stale


# ---------------------------------------------------------------------------
# protocol error-vocabulary coverage (ISSUE 18): every ERR_* the wire
# can speak is exercised by at least one test
# ---------------------------------------------------------------------------

def test_error_vocab_gate_clean_on_this_tree(checker):
    bad = checker.find_error_vocab_gaps()
    assert bad == [], "\n".join(bad)


def test_error_vocab_gate_detects_unexercised_code(checker,
                                                   tmp_path):
    svc = tmp_path / "pwasm_tpu" / "service"
    svc.mkdir(parents=True)
    (svc / "protocol.py").write_text(
        'ERR_COVERED = "covered_code"\n'
        'ERR_GHOST = "ghost_code"\n')
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_x.py").write_text(
        'def test_a(c):\n'
        '    assert c.ping()["error"] == "covered_code"\n')
    bad = checker.find_error_vocab_gaps(str(tmp_path))
    assert len(bad) == 1, bad
    assert "ERR_GHOST" in bad[0]
    # naming the CONSTANT in a test counts as coverage too
    (tests / "test_y.py").write_text(
        "from pwasm_tpu.service.protocol import ERR_GHOST\n")
    assert checker.find_error_vocab_gaps(str(tmp_path)) == []


def test_error_vocab_gate_loud_when_protocol_missing(checker,
                                                     tmp_path):
    bad = checker.find_error_vocab_gaps(str(tmp_path))
    assert len(bad) == 1
    assert "ERR_" in bad[0]
