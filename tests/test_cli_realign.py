"""End-to-end --realign CLI tests: the DP traceback replaces the PAF's
gap structure before MSA construction (SURVEY.md §0 north star — the
re-aligner as a product feature, not just a kernel)."""

import io

from pwasm_tpu.cli import run
from pwasm_tpu.core.fasta import write_fasta

from helpers import make_paf_line


def _mk(tmp_path, lines, qseq):
    fa = tmp_path / "q.fa"
    write_fasta(str(fa), [("q", qseq.encode())])
    paf = tmp_path / "in.paf"
    paf.write_text("".join(ln + "\n" for ln in lines))
    return str(paf), str(fa)


def test_realign_requires_msa_output(tmp_path):
    q = "AAACGGGG"
    line, _ = make_paf_line("q", q, "t1", "+", [("=", 8)])
    paf, fa = _mk(tmp_path, [line], q)
    err = io.StringIO()
    rc = run([paf, "-r", fa, "--realign"], stdout=io.StringIO(),
             stderr=err)
    assert rc == 1
    assert "--realign requires an MSA output" in err.getvalue()


def test_band_zero_rejected(tmp_path):
    q = "AAACGGGG"
    line, _ = make_paf_line("q", q, "t1", "+", [("=", 8)])
    paf, fa = _mk(tmp_path, [line], q)
    import pytest

    from pwasm_tpu.cli import CliError
    with pytest.raises(CliError, match="Invalid --band value"):
        run([paf, "-r", fa, "-w", str(tmp_path / "m.mfa"), "--realign",
             "--band=0"], stdout=io.StringIO(), stderr=io.StringIO())


def test_realign_moves_suboptimal_gap(tmp_path):
    """A PAF encoding (sub C->g, then delete a G) whose optimal
    re-alignment is a single gap over the C: --realign must move the
    target gap and leave the plain run untouched."""
    q = "AAACGGGG"
    line, _ = make_paf_line(
        "q", q, "t1", "+",
        [("=", 3), ("*", "g", "c"), ("del", 1), ("=", 3)])
    paf, fa = _mk(tmp_path, [line], q)

    plain = tmp_path / "plain.mfa"
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "r1.dfa"),
              "-w", str(plain)], stderr=io.StringIO())
    assert rc == 0
    assert plain.read_text() == (
        ">q\nAAACGGGG\n"
        ">t1:0-7+\nAAAg-GGG\n")

    re = tmp_path / "re.mfa"
    stats = tmp_path / "st.json"
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "r2.dfa"),
              "-w", str(re), "--realign", f"--stats={stats}"],
             stderr=io.StringIO())
    assert rc == 0
    assert re.read_text() == (
        ">q\nAAACGGGG\n"
        ">t1:0-7+\nAAA-gGGG\n")
    assert '"realigned": 1' in stats.read_text()


def test_realign_preserves_optimal_alignments(tmp_path):
    """Alignments that are already optimal (unique-optimum events far
    apart) re-align to the identical MSA, forward and reverse."""
    q = "ACGGTCCTGAACGGTTCCAATCGA"
    lines = [
        make_paf_line("q", q, "a1", "+",
                      [("=", 6), ("ins", "TT"), ("=", 18)])[0],
        make_paf_line("q", q, "a2", "-",
                      [("=", 10), ("del", 2), ("=", 12)])[0],
        make_paf_line("q", q, "a3", "+", [("=", 24)])[0],
    ]
    paf, fa = _mk(tmp_path, lines, q)
    plain = tmp_path / "plain.mfa"
    rc = run([paf, "-r", fa, "-w", str(plain)], stdout=io.StringIO(),
             stderr=io.StringIO())
    assert rc == 0
    re = tmp_path / "re.mfa"
    rc = run([paf, "-r", fa, "-w", str(re), "--realign"],
             stdout=io.StringIO(), stderr=io.StringIO())
    assert rc == 0
    assert re.read_text() == plain.read_text()


def test_realign_two_queries_flush_at_boundary(tmp_path):
    """Buffered re-alignments must merge into the FIRST query's MSA
    before the layout state resets for the second query (the flush at
    the refseq-change branch); the written MSA is the last query's, and
    it must match the non-realigned run for already-optimal inputs."""
    q1 = "ACGGTCCTGAACGGTTCCAATCGA"
    q2 = "TTGACCGGATACCAGTTGACAGGT"
    fa = tmp_path / "q.fa"
    write_fasta(str(fa), [("q1", q1.encode()), ("q2", q2.encode())])
    lines = [
        make_paf_line("q1", q1, "a1", "+",
                      [("=", 6), ("ins", "TT"), ("=", 18)])[0],
        make_paf_line("q2", q2, "b1", "+",
                      [("=", 10), ("del", 2), ("=", 12)])[0],
        make_paf_line("q2", q2, "b2", "-", [("=", 24)])[0],
    ]
    paf = tmp_path / "in.paf"
    paf.write_text("".join(ln + "\n" for ln in lines))
    plain = tmp_path / "plain.mfa"
    rc = run([str(paf), "-r", str(fa), "-w", str(plain)],
             stdout=io.StringIO(), stderr=io.StringIO())
    assert rc == 0
    re = tmp_path / "re.mfa"
    st = tmp_path / "st.json"
    rc = run([str(paf), "-r", str(fa), "-w", str(re), "--realign",
              f"--stats={st}"],
             stdout=io.StringIO(), stderr=io.StringIO())
    assert rc == 0
    assert re.read_text() == plain.read_text()
    assert ">q2" in re.read_text()          # last query's MSA written
    assert '"realigned": 3' in st.read_text()  # q1's buffer flushed too


def test_realign_band_escalation(tmp_path):
    """A target with an insertion far larger than --band must still
    re-align (device band escalation), not hang or fall back silently."""
    q = "ACGGTCCTGAACGGTTCCAATCGA" * 4          # 96 bases
    ins = "TTTTGGGGCCCCAAAA" * 8                # 128-base insertion
    lines = [make_paf_line("q", q, "big", "+",
                           [("=", 48), ("ins", ins), ("=", 48)])[0]]
    paf, fa = _mk(tmp_path, lines, q)
    re = tmp_path / "re.mfa"
    st = tmp_path / "st.json"
    rc = run([str(paf), "-r", str(fa), "-w", str(re), "--realign",
              "--band=16", f"--stats={st}"],
             stdout=io.StringIO(), stderr=io.StringIO())
    assert rc == 0
    assert '"realigned": 1' in st.read_text()
    # the 128-base insertion survives re-alignment as a query gap run
    # (sequences wrap at 60 columns; join each record's lines)
    recs: dict[str, str] = {}
    name = None
    for ln in re.read_text().splitlines():
        if ln.startswith(">"):
            name = ln[1:]
            recs[name] = ""
        else:
            recs[name] += ln
    assert "-" * 128 in recs["q"]


def test_realign_batched_flush(tmp_path):
    """--batch=2 forces mid-stream flushes; the MSA must be identical to
    a single-flush run (insertion order preserved across flushes)."""
    q = "ACGGTCCTGAACGGTTCCAATCGA"
    lines = []
    for k in range(5):
        lines.append(make_paf_line("q", q, f"b{k}", "+",
                                   [("=", 4 + k), ("ins", "GG"),
                                    ("=", 20 - k)])[0])
    paf, fa = _mk(tmp_path, lines, q)
    one = tmp_path / "one.mfa"
    rc = run([paf, "-r", fa, "-w", str(one), "--realign"],
             stdout=io.StringIO(), stderr=io.StringIO())
    assert rc == 0
    many = tmp_path / "many.mfa"
    rc = run([paf, "-r", fa, "-w", str(many), "--realign", "--batch=2"],
             stdout=io.StringIO(), stderr=io.StringIO())
    assert rc == 0
    assert many.read_text() == one.read_text()


def test_realign_shard_byte_identical(tmp_path):
    """--realign --shard over the virtual 8-device mesh: MSA and report
    byte-identical to the unsharded device run."""
    import io
    import sys

    import numpy as np

    from pwasm_tpu.cli import run
    from pwasm_tpu.core.fasta import write_fasta

    sys.path.insert(0, "tests")
    from helpers import make_paf_line

    rng = np.random.default_rng(33)
    q = "".join("ACGT"[i] for i in rng.integers(0, 4, 150))
    fa = tmp_path / "q.fa"
    write_fasta(str(fa), [("q", q.encode())])
    lines = []
    for k in range(12):
        ops = [[("=", 150)], [("=", 40), ("ins", "TT"), ("=", 110)],
               [("=", 70), ("del", 3), ("=", 77)]][k % 3]
        lines.append(make_paf_line("q", q, f"t{k}", "+", ops)[0])
    paf = tmp_path / "in.paf"
    paf.write_text("".join(l + "\n" for l in lines))
    outs = {}
    for mode, extra in (("plain", []), ("shard", ["--shard"])):
        rep = tmp_path / f"{mode}.dfa"
        mfa = tmp_path / f"{mode}.mfa"
        rc = run([str(paf), "-r", str(fa), "-o", str(rep),
                  "-w", str(mfa), "--realign", "--device=tpu"] + extra,
                 stderr=io.StringIO())
        assert rc == 0, mode
        outs[mode] = rep.read_text() + mfa.read_text()
    assert outs["plain"] == outs["shard"]
