"""Per-column provenance (the NucOri capability, GapAssem.h:142-161):
which member contributed which base at a layout column, and who
disagrees with the consensus vote there (VERDICT r1 missing #4)."""

import numpy as np
import pytest

from pwasm_tpu.align.gapseq import GapSeq
from pwasm_tpu.align.msa import Msa
from pwasm_tpu.core.errors import PwasmError


def _known_msa():
    """Layout (from tests/test_cli.py's end-to-end case):
        col:        0123456789AB
        q           ACGTAC--GTAC
        asm1        ACGTACggGTAC
        asm2        AC--AC--GTAC
        asm3        ACGTAC--GTAC
    """
    q = GapSeq("q", "", b"ACGTACGTAC")
    a1 = GapSeq("asm1", "", b"ACGTACggGTAC")
    a2 = GapSeq("asm2", "", b"ACACGTAC")
    a3 = GapSeq("asm3", "", b"ACGTACGTAC")
    q.set_gap(6, 2)       # the a1 insertion propagated to the others
    a2.set_gap(2, 2)      # wait: a2 lost two bases vs q
    a3.set_gap(6, 2)
    # a2's gap structure: bases AC then gap gap then ACGTAC... its own
    # coordinates: gap before base 2, length 2, plus the a1 insertion
    # columns (6,7) are also gaps before its base 4
    a2.gaps[:] = 0
    a2.set_gap(2, 2)
    a2.set_gap(4, 2)
    msa = Msa(q, a1)
    msa.add_seq(a2, 0, 0)
    msa.add_seq(a3, 0, 0)
    return msa


def test_provenance_matrix_matches_layout():
    msa = _known_msa()
    prov = msa.provenance_matrix()
    mat = msa.pileup_matrix()
    assert prov.shape == mat.shape
    assert (prov[:, 12:] == 0).all()  # layout over-allocation is empty
    # member 0 (q): no gaps until col 6; cols 6,7 are its gap run
    np.testing.assert_array_equal(prov[0, :12],
                                  [1, 2, 3, 4, 5, 6, 0, 0, 7, 8, 9, 10])
    # member 2 (asm2): AC--AC--GTAC
    np.testing.assert_array_equal(prov[2, :12],
                                  [1, 2, 0, 0, 3, 4, 0, 0, 5, 6, 7, 8])
    # wherever prov is set, the pileup code must be that base's bucket
    for k, s in enumerate(msa.seqs):
        set_cols = np.nonzero(prov[k])[0]
        for c in set_cols:
            assert mat[k, c] != 6
            assert chr(s.seq[prov[k, c] - 1]).upper() in "ACGTN"


def test_column_contributors_and_mismatches():
    msa = _known_msa()
    msa.build_msa()
    # column 6: a1 contributes 'g' (base 6); others contribute gaps
    contrib = msa.column_contributors(6)
    assert (1, 6, "g", False) in contrib
    gap_members = {k for k, _p, sym, _c in contrib if sym == "-"}
    assert gap_members == {0, 2, 3}
    # the vote at column 6 is '-' (3 gaps vs 1 G) => a1 is the mismatch
    mm = msa.column_mismatches(6)
    assert mm == [(1, 6, "g")]
    # column 2: asm2 has a gap, everyone else 'G'; vote G => asm2 flagged
    mm2 = msa.column_mismatches(2)
    assert mm2 == [(2, 2, "-")]
    # column 0: everyone agrees 'A' => no mismatches
    assert msa.column_mismatches(0) == []


def test_clipped_contributors_flagged_not_mismatched():
    msa = _known_msa()
    msa.seqs[3].clp5 = 2  # clip asm3's first two bases
    msa.build_msa()
    contrib = msa.column_contributors(0)
    flags = {k: clipped for k, _p, _s, clipped in contrib}
    assert flags[3] is True          # present, marked clipped
    assert msa.column_mismatches(0) == []   # clipped never mismatches


def test_provenance_requires_pre_refine():
    msa = _known_msa()
    msa.seqs[1].remove_base(0)
    with pytest.raises(PwasmError, match="pre-refine"):
        msa.provenance_matrix()
