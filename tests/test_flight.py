"""Job flight recorder (ISSUE 11): cross-process trace correlation,
utilization accounting, and live fleet introspection.

Acceptance contracts exercised here:

- **identity plumbing**: a trace_id minted by the client rides every
  protocol frame, is stamped into the journal (surviving replay onto
  the recovered job's flight record), the daemon event log, and both
  sides' Chrome traces;
- **flight records**: every served job accumulates phase-accounted
  walls (queue wait, lease wait, exec with the run's per-flush
  breakdown inside) whose accounted sum covers >= 90% of the job
  wall; ``inspect`` serves them from RAM and — CRC-verified — from
  the result spool;
- **trace-merge**: two wall-anchored trace documents join onto one
  timeline that still satisfies the monotonic-nesting schema;
- **bounded observability**: event-log rotation caps the NDJSON log,
  the trace recorder's cap surfaces drops live, and the whole surface
  stays byte-neutral (report bytes identical with everything on).
"""

import io
import json
import os
import shutil
import tempfile
import threading
import time
from contextlib import contextmanager
from types import SimpleNamespace

import pytest

from pwasm_tpu.cli import run
from pwasm_tpu.obs import EventLog, TraceRecorder, make_observability
from pwasm_tpu.obs.flight import FlightRecorder
from pwasm_tpu.obs.merge import merge_traces, trace_merge_main
from pwasm_tpu.service.client import ServiceClient, wait_for_socket
from pwasm_tpu.service.daemon import Daemon
from pwasm_tpu.service.top import render, top_main

from test_obs import _corpus as _obs_corpus
from test_obs import assert_valid_exposition


def _corpus(tmp_path, n=8, qlen=120):
    return _obs_corpus(tmp_path, n=n, qlen=qlen)


@contextmanager
def _daemon(runner=None, **kw):
    sockdir = tempfile.mkdtemp(prefix="pwflt")
    sock = os.path.join(sockdir, "s")
    err = io.StringIO()
    dm = Daemon(sock, stderr=err, runner=runner, **kw)
    rcbox: list = []
    t = threading.Thread(target=lambda: rcbox.append(dm.serve()),
                         daemon=True)
    t.start()
    assert wait_for_socket(sock, 15), err.getvalue()
    try:
        yield SimpleNamespace(daemon=dm, sock=sock, rc=rcbox, err=err,
                              thread=t, dir=sockdir)
    finally:
        if not dm.drain.requested:
            dm.drain.request("test teardown")
        t.join(20)
        shutil.rmtree(sockdir, ignore_errors=True)


def _stub_runner(rc=0):
    def runner(argv, stdout=None, stderr=None, warm=None):
        sp = next((a.split("=", 1)[1] for a in argv
                   if a.startswith("--stats=")), None)
        if sp:
            with open(sp, "w") as f:
                json.dump({"stats_version": 1, "wall_s": 0.01}, f)
        return rc
    return runner


# ---------------------------------------------------------------------------
# flight recorder unit
# ---------------------------------------------------------------------------
def test_flight_recorder_phases_ring_and_coverage():
    fl = FlightRecorder(trace_id="t1", max_entries=3, max_marks=4)
    fl.note("queue_wait", 0.2)
    fl.note("lease_wait", 0.1)
    fl.note("exec", 0.6, lane=0)
    fl.note("exec", 0.05)             # phases accumulate; ring caps
    for i in range(6):
        fl.mark("retry", attempt=i)   # mark ring bounded at 4
    s = fl.summary(wall_s=1.0)
    assert s["version"] == 1 and s["trace_id"] == "t1"
    assert s["phases"]["exec"] == {"s": 0.65, "n": 2}
    assert s["accounted_s"] == pytest.approx(0.95)
    assert s["coverage"] == pytest.approx(0.95)
    assert len(s["entries"]) == 3 and s["entries_dropped"] == 1
    assert len(s["events"]) == 4 and s["events_dropped"] == 2
    # routine span notes can NEVER evict diagnostic marks: the two
    # rings are separate (the incident-review property)
    assert all(e["ev"] == "retry" for e in s["events"])
    assert fl.phase_s("queue_wait") == pytest.approx(0.2)
    # per-batch-cadence marks route to the SPAN ring: a day of
    # ckpt_write marks must never evict an hour-1 OOM from events
    fl.mark("ckpt_write", records=10)
    s2 = fl.summary()
    assert all(e["ev"] == "retry" for e in s2["events"])
    assert any(e.get("ev") == "ckpt_write" for e in s2["entries"])
    # no wall -> no coverage key, and the summary is JSON-able
    json.dumps(fl.summary())


def test_flight_recorder_never_raises_on_weird_fields():
    fl = FlightRecorder()
    fl.mark("ev", skipme=None, keep=1)
    fl.note("ph", 0.1, extra="x")
    s = fl.summary()
    assert "skipme" not in s["events"][0]
    assert s["events"][0]["keep"] == 1
    assert s["entries"][0]["extra"] == "x"


# ---------------------------------------------------------------------------
# identity plumbing: frames -> job -> journal -> events -> flight
# ---------------------------------------------------------------------------
def test_trace_id_rides_frames_journal_events_and_flight(tmp_path):
    paf, fa = _corpus(tmp_path)
    log = tmp_path / "svc.ndjson"
    jp = str(tmp_path / "j.journal")
    with _daemon(log_json=str(log), journal_path=jp) as h:
        with ServiceClient(h.sock, trace_id="trace.abc-1") as c:
            sub = c.submit([paf, "-r", fa,
                            "-o", str(tmp_path / "a.dfa"),
                            "--batch=2"])
            assert sub.get("ok") and sub["trace_id"] == "trace.abc-1"
            res = c.result(sub["job_id"], timeout=120)
            assert res.get("ok") and res.get("rc") == 0, res
            assert res["job"]["trace_id"] == "trace.abc-1"
            insp = c.inspect(sub["job_id"])
            assert insp.get("ok"), insp
            assert insp["trace_id"] == "trace.abc-1"
            assert insp["flight"]["trace_id"] == "trace.abc-1"
            # the journal admit record carries it (read BEFORE the
            # clean drain retires the journal)
            recs = [json.loads(ln) for ln in
                    open(jp).read().splitlines()]
            admit = next(r for r in recs if r["rec"] == "admit")
            assert admit["trace_id"] == "trace.abc-1"
    evs = [json.loads(ln) for ln in log.read_text().splitlines()]
    for kind in ("job_admit", "job_start", "job_finish"):
        ev = next(e for e in evs if e["event"] == kind)
        assert ev["trace_id"] == "trace.abc-1", kind


def test_daemon_mints_trace_id_when_frame_has_none(tmp_path):
    paf, fa = _corpus(tmp_path, n=4)
    with _daemon() as h:
        with ServiceClient(h.sock) as c:
            # a hand-rolled frame without trace_id (an older client)
            resp = c.request({"cmd": "submit",
                              "args": [paf, "-r", fa, "-o",
                                       str(tmp_path / "a.dfa")],
                              "cwd": str(tmp_path)})
            assert resp.get("ok"), resp
            assert resp["trace_id"]      # daemon-minted, non-empty
            assert c.result(resp["job_id"], timeout=120)["rc"] == 0


def test_bad_trace_id_rejected(tmp_path):
    paf, fa = _corpus(tmp_path, n=2)
    with _daemon() as h:
        with ServiceClient(h.sock) as c:
            resp = c.request({"cmd": "submit",
                              "args": [paf, "-r", fa, "-o", "o.dfa"],
                              "cwd": str(tmp_path),
                              "trace_id": "bad id with spaces"})
            assert resp.get("error") == "bad_request"
            assert "trace_id" in resp.get("detail", "")


def test_trace_id_survives_journal_replay_onto_flight(tmp_path):
    """The kill -9 drill for identity: a journal left by a crashed
    daemon names the job's trace_id; the restarted daemon's recovered
    job carries it — on the job record, the flight record, and its
    finish events."""
    out = str(tmp_path / "a.dfa")
    jp = str(tmp_path / "crash.journal")
    with open(jp, "w") as f:
        f.write(json.dumps(
            {"v": 1, "rec": "admit", "job_id": "job-0001",
             "argv": ["a.paf", "-o", out], "client": "uid:7",
             "priority": "", "trace_id": "crashed.trace.9",
             "t": 1.0}) + "\n")
        f.write(json.dumps(
            {"v": 1, "rec": "start", "job_id": "job-0001",
             "lane": 0}) + "\n")
    log = tmp_path / "svc.ndjson"
    with _daemon(runner=_stub_runner(), journal_path=jp,
                 log_json=str(log)) as h:
        with ServiceClient(h.sock) as c:
            res = c.result("job-0001", timeout=30)
            assert res.get("rc") == 0, res
            assert res["job"]["trace_id"] == "crashed.trace.9"
            assert res["job"]["recovered"] is True
            insp = c.inspect("job-0001")
            assert insp["trace_id"] == "crashed.trace.9"
            fl = insp["flight"]
            assert fl["trace_id"] == "crashed.trace.9"
            assert any(e.get("ev") == "journal_recovered"
                       for e in fl["events"])
            # the recovered run came back as --resume AND kept its
            # identity in the re-compacted journal
            recs = [json.loads(ln) for ln in
                    open(jp).read().splitlines()]
            admit = next(r for r in recs if r["rec"] == "admit")
            assert admit["trace_id"] == "crashed.trace.9"
            assert "--resume" in admit["argv"]
    evs = [json.loads(ln) for ln in log.read_text().splitlines()]
    fin = next(e for e in evs if e["event"] == "job_finish")
    assert fin["trace_id"] == "crashed.trace.9"


def test_stream_verbs_carry_trace_id(tmp_path):
    paf, fa = _corpus(tmp_path, n=4)
    records = open(paf).read()
    log = tmp_path / "svc.ndjson"
    with _daemon(log_json=str(log)) as h:
        with ServiceClient(h.sock, trace_id="stream.t1") as c:
            opened = c.stream_open(["-r", fa,
                                    "-o", str(tmp_path / "s.dfa")])
            assert opened.get("ok"), opened
            assert opened["trace_id"] == "stream.t1"
            jid = opened["job_id"]
            # split mid-record on purpose: reassembly is orthogonal
            cut = len(records) // 2 + 3
            assert c.stream_data(jid, records[:cut]).get("ok")
            assert c.stream_data(jid, records[cut:]).get("ok")
            assert c.stream_end(jid).get("ok")
            res = c.result(jid, timeout=120)
            assert res.get("rc") == 0, res
            insp = c.inspect(jid)
            assert insp["trace_id"] == "stream.t1"
            assert insp["flight"]["coverage"] >= 0.9
    evs = [json.loads(ln) for ln in log.read_text().splitlines()]
    admit = next(e for e in evs if e["event"] == "job_admit")
    assert admit["trace_id"] == "stream.t1" and admit["stream"]


# ---------------------------------------------------------------------------
# flight records over the spool
# ---------------------------------------------------------------------------
def test_inspect_reads_spooled_flight_with_crc(tmp_path):
    paf, fa = _corpus(tmp_path)
    with _daemon(spool_threshold_bytes=1) as h:
        with ServiceClient(h.sock) as c:
            sub = c.submit([paf, "-r", fa,
                            "-o", str(tmp_path / "a.dfa"),
                            "--batch=2"])
            assert c.result(sub["job_id"], timeout=120)["rc"] == 0
            job = h.daemon.jobs[sub["job_id"]]
            assert job.spool is not None     # result went to disk
            assert job.flight is None        # RAM keeps the index only
            insp = c.inspect(sub["job_id"])
            assert insp.get("ok"), insp
            fl = insp["flight"]
            assert fl["trace_id"] == c.trace_id
            assert fl["coverage"] >= 0.9
            for phase in ("queue_wait", "lease_wait", "exec"):
                assert phase in fl["phases"], fl["phases"]
            # rot the spooled record: inspect must REPORT it, never
            # serve a half-verified flight record
            raw = open(job.spool["path"]).read()
            bad = raw.replace('"state":"done"', '"state":"dome"', 1)
            assert bad != raw
            with open(job.spool["path"], "w") as f:
                f.write(bad)
            insp2 = c.inspect(sub["job_id"])
            assert insp2.get("ok")
            assert "CRC" in insp2["spool_error"] \
                or "unreadable" in insp2["spool_error"]
            assert insp2["flight"] is None


def test_inspect_unknown_job(tmp_path):
    with _daemon() as h:
        with ServiceClient(h.sock) as c:
            assert c.inspect("job-9999")["error"] == "unknown_job"


def test_inspect_live_job_before_terminal(tmp_path):
    """A RUNNING job answers inspect too — the live half of "why is
    job X slow RIGHT NOW"."""
    paf, fa = _corpus(tmp_path, n=4)
    slow = "--inject-faults=seed=1,rate=1,kinds=hang,hang_s=0.3"
    with _daemon() as h:
        with ServiceClient(h.sock) as c:
            sub = c.submit([paf, "-r", fa, "--device=tpu",
                            "-o", str(tmp_path / "a.dfa"),
                            "--batch=2", slow])
            deadline = time.monotonic() + 60
            seen_running = None
            while time.monotonic() < deadline:
                insp = c.inspect(sub["job_id"])
                if insp["job"]["state"] == "running":
                    seen_running = insp
                    break
                if insp["job"]["state"] not in ("queued", "running"):
                    break
                time.sleep(0.02)
            assert seen_running is not None
            fl = seen_running["flight"]
            assert "queue_wait" in fl["phases"]
            assert c.result(sub["job_id"], timeout=120)["rc"] == 0


# ---------------------------------------------------------------------------
# trace merge
# ---------------------------------------------------------------------------
class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _assert_monotonic_nesting(events):
    """The schema property: same-(pid,tid) complete spans nest — for
    any two spans that overlap, one contains the other."""
    by_track = {}
    for e in events:
        if e.get("ph") == "X":
            by_track.setdefault((e["pid"], e["tid"]), []).append(
                (e["ts"], e["ts"] + e["dur"]))
    for spans in by_track.values():
        for i, (a0, a1) in enumerate(spans):
            for b0, b1 in spans[i + 1:]:
                overlap = max(a0, b0) < min(a1, b1)
                contained = (a0 <= b0 and b1 <= a1) \
                    or (b0 <= a0 and a1 <= b1)
                assert not overlap or contained, (spans,)


def test_merge_traces_aligns_on_wall_anchor():
    ca, cb = _Clock(), _Clock()
    ra, rb = TraceRecorder(clock=ca), TraceRecorder(clock=cb)
    ra.anchor_wall_s = 100.0      # client started 2s before daemon
    rb.anchor_wall_s = 102.0
    with ra.span("submit_rpc", trace_id="t"):
        ca.t = 1.0
    with rb.span("job_exec", trace_id="t"):
        cb.t = 0.5
    merged = merge_traces([("client.json", ra.to_dict()),
                           ("daemon.json", rb.to_dict())])
    evs = {e["name"]: e for e in merged["traceEvents"]
           if e.get("ph") == "X"}
    # client events keep their base; daemon events shift +2s
    assert evs["submit_rpc"]["ts"] == 0
    assert evs["job_exec"]["ts"] == 2_000_000
    assert merged["otherData"]["anchor_wall_s"] == 100.0
    assert merged["otherData"]["merged"] == 2
    names = [e for e in merged["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"]
    assert {n["args"]["name"] for n in names} \
        == {"client.json", "daemon.json"}
    _assert_monotonic_nesting(merged["traceEvents"])


def test_merge_traces_remaps_colliding_pids():
    ca, cb = _Clock(), _Clock()
    ra, rb = TraceRecorder(clock=ca), TraceRecorder(clock=cb)
    ra.anchor_wall_s = rb.anchor_wall_s = 0.0
    ra.instant("a")
    rb.instant("b")       # same process => same pid in both docs
    merged = merge_traces([("x", ra.to_dict()), ("y", rb.to_dict())])
    pids = {e["pid"] for e in merged["traceEvents"]
            if e.get("ph") == "i"}
    assert len(pids) == 2   # two tracks, despite one real pid


def test_trace_merge_main_cli(tmp_path):
    c = _Clock()
    rec = TraceRecorder(clock=c)
    rec.anchor_wall_s = 5.0
    with rec.span("run"):
        c.t = 1.0
    a = tmp_path / "a.json"
    a.write_text(json.dumps(rec.to_dict()))
    out, err = io.StringIO(), io.StringIO()
    dst = tmp_path / "merged.json"
    assert run(["trace-merge", str(a), str(a), "-o", str(dst)],
               stdout=out, stderr=err) == 0
    doc = json.loads(dst.read_text())
    assert len([e for e in doc["traceEvents"]
                if e.get("ph") == "X"]) == 2
    _assert_monotonic_nesting(doc["traceEvents"])
    # usage errors
    assert run(["trace-merge"], stdout=out, stderr=err) == 1
    assert run(["trace-merge", str(tmp_path / "nope.json")],
               stdout=out, stderr=err) == 1


# ---------------------------------------------------------------------------
# the one-command incident reconstruction (acceptance)
# ---------------------------------------------------------------------------
def test_incident_reconstruction_end_to_end(tmp_path):
    """A 200-aln job submitted with tracing on: ONE trace_id greppable
    across client trace, daemon events, and journal; inspect's
    accounted phases cover >= 90% of the job wall; trace-merge emits
    one valid Chrome trace holding both processes' spans."""
    paf, fa = _corpus(tmp_path, n=200)
    log = tmp_path / "svc.ndjson"
    jp = str(tmp_path / "j.journal")
    dtrace = tmp_path / "daemon.trace.json"
    ctrace = tmp_path / "client.trace.json"
    trace_ids = {}
    with _daemon(log_json=str(log), journal_path=jp,
                 trace_json=str(dtrace)) as h:
        out, err = io.StringIO(), io.StringIO()
        rc = run(["submit", f"--socket={h.sock}",
                  f"--trace-json={ctrace}", "--trace-id=incident.7",
                  "--", paf, "-r", fa,
                  "-o", str(tmp_path / "a.dfa"), "--batch=64"],
                 stdout=out, stderr=err)
        assert rc == 0, err.getvalue()
        verdict = json.loads(out.getvalue())
        assert verdict["trace_id"] == "incident.7"
        with ServiceClient(h.sock) as c:
            insp = c.inspect(verdict["job_id"])
        assert insp["trace_id"] == "incident.7"
        assert insp["flight"]["coverage"] >= 0.9, insp["flight"]
        journal_text = open(jp).read()
    # one id, greppable on every surface
    assert "incident.7" in ctrace.read_text()
    assert "incident.7" in log.read_text()
    assert "incident.7" in journal_text
    # merge the two processes' traces: one valid doc, both sides in
    out, err = io.StringIO(), io.StringIO()
    merged_path = tmp_path / "one.json"
    assert run(["trace-merge", str(ctrace), str(dtrace),
                "-o", str(merged_path)],
               stdout=out, stderr=err) == 0
    doc = json.loads(merged_path.read_text())
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"submit_rpc", "result_wait"} <= names     # client side
    assert {"job_exec", "job_queue_wait"} <= names    # daemon side
    assert len({e["pid"] for e in doc["traceEvents"]}) >= 2
    _assert_monotonic_nesting(doc["traceEvents"])


# ---------------------------------------------------------------------------
# event-log rotation (--log-json-max-bytes)
# ---------------------------------------------------------------------------
def test_event_log_rotation_caps_and_seams(tmp_path):
    path = str(tmp_path / "ev.ndjson")
    log = EventLog(path=path, max_bytes=400)
    for i in range(50):
        log.emit("tick", i=i)
    log.close()
    assert log.rotations >= 1
    assert os.path.exists(path + ".1")
    # bounded on disk: current + exactly one previous generation,
    # each about the cap (one overshoot line at most)
    assert os.path.getsize(path) <= 400 + 200
    assert os.path.getsize(path + ".1") <= 400 + 200
    # the fresh file opens with the rotation seam event
    first = json.loads(open(path).readline())
    assert first["event"] == "log_rotate"
    assert first["previous"] == path + ".1"
    # nothing was lost across the seam: the tick sequence is
    # contiguous over (previous, current)
    ticks = []
    for p in (path + ".1", path):
        for ln in open(p).read().splitlines():
            rec = json.loads(ln)
            if rec["event"] == "tick":
                ticks.append(rec["i"])
    assert ticks == sorted(ticks) and ticks[-1] == 49


def test_event_log_rotation_never_raises(tmp_path, monkeypatch):
    path = str(tmp_path / "ev.ndjson")
    log = EventLog(path=path, max_bytes=100)
    import os as _os
    real_replace = _os.replace

    def boom(*a, **k):
        raise OSError("no rename for you")
    monkeypatch.setattr("os.replace", boom)
    for i in range(20):
        log.emit("tick", i=i)     # rotation fails; appending goes on
    monkeypatch.setattr("os.replace", real_replace)
    log.close()
    recs = [json.loads(ln) for ln in open(path).read().splitlines()]
    assert [r["i"] for r in recs if r["event"] == "tick"] \
        == list(range(20))


def test_cli_log_json_max_bytes_rotates(tmp_path):
    paf, fa = _corpus(tmp_path, n=8)
    log = tmp_path / "run.ndjson"
    err = io.StringIO()
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "a.dfa"),
              "--batch=2", f"--log-json={log}",
              "--log-json-max-bytes=256"], stderr=err)
    assert rc == 0, err.getvalue()
    assert (tmp_path / "run.ndjson.1").exists()
    # bad values are usage errors
    for bad in ("0", "x", "-5"):
        err = io.StringIO()
        assert run([paf, "-r", fa, "-o", str(tmp_path / "b.dfa"),
                    f"--log-json={log}",
                    f"--log-json-max-bytes={bad}"],
                   stderr=err) == 1
        assert "Invalid --log-json-max-bytes" in err.getvalue()


# ---------------------------------------------------------------------------
# trace cap + live dropped counter (--trace-max-events)
# ---------------------------------------------------------------------------
def test_trace_max_events_surfaces_drops_live(tmp_path):
    paf, fa = _corpus(tmp_path, n=12)
    trace = tmp_path / "t.json"
    prom = tmp_path / "m.prom"
    err = io.StringIO()
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "a.dfa"),
              "--batch=2", f"--trace-json={trace}",
              "--trace-max-events=4",
              f"--metrics-textfile={prom}"], stderr=err)
    assert rc == 0, err.getvalue()
    doc = json.loads(trace.read_text())
    assert len(doc["traceEvents"]) == 4
    dropped = doc["otherData"]["dropped_events"]
    assert dropped > 0
    text = prom.read_text()
    assert_valid_exposition(text)
    assert f"pwasm_trace_events_dropped_total {dropped}" \
        in text.splitlines()
    # bad values are usage errors
    err = io.StringIO()
    assert run([paf, "-r", fa, "-o", str(tmp_path / "b.dfa"),
                f"--trace-json={trace}", "--trace-max-events=no"],
               stderr=err) == 1


def test_trace_recorder_on_drop_hook_never_raises():
    rec = TraceRecorder(max_events=1)

    def boom():
        raise RuntimeError("hook bug")
    rec.on_drop = boom
    rec.instant("a")
    rec.instant("b")       # dropped; hook raises; drop still counted
    assert rec.dropped == 1


# ---------------------------------------------------------------------------
# utilization accounting
# ---------------------------------------------------------------------------
def test_pad_and_compile_accounting(tmp_path, monkeypatch):
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path, n=12)
    stats = tmp_path / "s.json"
    prom = tmp_path / "m.prom"
    err = io.StringIO()
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "a.dfa"),
              "--device=tpu", "--batch=4", f"--stats={stats}",
              f"--metrics-textfile={prom}"], stderr=err)
    assert rc == 0, err.getvalue()
    dev = json.loads(stats.read_text())["device"]
    # pow2 bucketing: 12 alignments' events launched in >= 1 padded
    # batch of 256-slot buckets
    assert dev["pad_items"] > 0
    assert dev["pad_slots"] >= max(dev["pad_items"], 256)
    # the first attempt at each site is the compile-inclusive one
    assert dev["compile_s"] > 0
    assert dev["steady_s"] >= 0
    text = prom.read_text()
    assert_valid_exposition(text)
    sample = {ln.rsplit(" ", 1)[0]: float(ln.rsplit(" ", 1)[1])
              for ln in text.splitlines() if not ln.startswith("#")}
    waste = sample["pwasm_device_pad_waste_ratio"]
    assert waste == pytest.approx(
        1.0 - dev["pad_items"] / dev["pad_slots"], abs=1e-4)
    assert 0 < waste < 1
    assert sample["pwasm_device_compile_fraction"] > 0


def test_lane_busy_fraction_gauge(tmp_path):
    paf, fa = _corpus(tmp_path, n=4)
    with _daemon() as h:
        with ServiceClient(h.sock) as c:
            sub = c.submit([paf, "-r", fa,
                            "-o", str(tmp_path / "a.dfa")])
            assert c.result(sub["job_id"], timeout=120)["rc"] == 0
            text = c.metrics()["metrics"]
            st = c.stats()["stats"]
    lines = text.splitlines()
    row = next(ln for ln in lines if ln.startswith(
        'pwasm_service_lane_busy_fraction{lane="0"}'))
    frac = float(row.rsplit(" ", 1)[1])
    assert 0 < frac <= 1
    # svc-stats lanes rows carry the busy wall the gauge derives from
    assert st["lanes"][0]["busy_s"] > 0


def test_stream_feed_lag_age():
    from pwasm_tpu.stream.pafstream import StreamFeed
    feed = StreamFeed()
    feed.feed("a\tb\n")
    now = time.monotonic()
    assert feed.lag_age_s(now=now + 5.0) >= 5.0
    feed.end()
    for _ in feed:
        pass                        # drain everything
    assert feed.lag_age_s() == 0.0


def test_host_stages_fold_per_flush_without_double_count(tmp_path):
    """Satellite (c): pwasm_host_stage_seconds_total is fed per FLUSH
    (live attribution for the drifting host canary) and the end-of-run
    residual fold keeps the counter total EXACTLY equal to the --stats
    host block — folding per-flush must not double-count."""
    paf, fa = _corpus(tmp_path, n=12)
    stats = tmp_path / "s.json"
    prom = tmp_path / "m.prom"
    err = io.StringIO()
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "a.dfa"),
              "--batch=2", f"--stats={stats}",
              f"--metrics-textfile={prom}"], stderr=err)
    assert rc == 0, err.getvalue()
    host = json.loads(stats.read_text())["host"]
    sample = {}
    for ln in prom.read_text().splitlines():
        if ln.startswith("pwasm_host_stage_seconds_total"):
            k, v = ln.rsplit(" ", 1)
            sample[k] = float(v)
    for stage in ("parse", "extract", "analyze", "format"):
        key = ('pwasm_host_stage_seconds_total{stage="%s"}' % stage)
        assert sample.get(key, 0.0) == pytest.approx(
            host[stage + "_s"], abs=2e-5), (stage, sample)
    # the per-flush proof lands on the flight side of the same hook:
    # a served job's flight record carries one host_* note per flush
    with _daemon() as h:
        with ServiceClient(h.sock) as c:
            sub = c.submit([paf, "-r", fa,
                            "-o", str(tmp_path / "b.dfa"),
                            "--batch=2"])
            assert c.result(sub["job_id"], timeout=120)["rc"] == 0
            fl = c.inspect(sub["job_id"])["flight"]
    assert fl["phases"]["host_analyze"]["n"] >= 2   # per-flush, not
    #                                                 one end-of-run sum


# ---------------------------------------------------------------------------
# pwasm-tpu top
# ---------------------------------------------------------------------------
def test_top_render_pure():
    st = {"uptime_s": 12.5, "draining": False, "breaker_state": 2,
          "running": 1, "queue_depth": 3,
          "jobs": {"completed": 5, "failed": 1, "preempted": 0,
                   "cancelled": 0, "rejected": 2, "recovered": 1},
          "lanes": [{"lane": 0, "devices": [0, 1], "busy": True,
                     "jobs_run": 5, "busy_s": 6.0,
                     "breaker_state": 0},
                    {"lane": 1, "devices": [1, 2], "busy": False,
                     "jobs_run": 1, "busy_s": 1.0,
                     "breaker_state": 2}],
          "fair_share": {"max_queue_per_client": 16,
                         "max_queue_total": 128,
                         "clients": {"uid:7": 3, "uid:9": 0}},
          "streams": {"active": 2, "lag_records": 40,
                      "max_buffer_total": 2048, "records_in": 900,
                      "batches": 12},
          "warm": {"backend_warm_hits": 4, "backend_probes": 1},
          "journal": {"broken": False, "records": 9, "replays": 1}}
    frame = render(st)
    assert "breaker OPEN" in frame
    assert "1 running, 3 queued" in frame
    assert "uid:7" in frame and "uid:9" not in frame  # 0-depth hidden
    assert "STREAMS: 2 live" in frame
    assert "LANE" in frame and "48%" in frame         # 6.0 / 12.5
    assert "replay(s)" in frame
    # pure and total on an empty dict too
    assert "QUEUE empty" in render({})


def test_top_once_against_live_daemon(tmp_path):
    paf, fa = _corpus(tmp_path, n=4)
    with _daemon() as h:
        with ServiceClient(h.sock) as c:
            sub = c.submit([paf, "-r", fa,
                            "-o", str(tmp_path / "a.dfa")])
            assert c.result(sub["job_id"], timeout=120)["rc"] == 0
        out, err = io.StringIO(), io.StringIO()
        rc = run(["top", f"--socket={h.sock}", "--once"],
                 stdout=out, stderr=err)
        assert rc == 0, err.getvalue()
        frame = out.getvalue()
        assert "pwasm-tpu top" in frame
        assert "\x1b[" not in frame     # --once never clears
    # usage errors
    out, err = io.StringIO(), io.StringIO()
    assert run(["top"], stdout=out, stderr=err) == 1
    assert run(["top", "--socket=s", "--interval=nope"],
               stdout=out, stderr=err) == 1


# ---------------------------------------------------------------------------
# byte parity: the whole new surface on vs off
# ---------------------------------------------------------------------------
def test_byte_parity_with_flight_tracing_and_gauges(tmp_path,
                                                    monkeypatch):
    """Report/-s bytes identical with the flight recorder, trace
    propagation, utilization gauges, rotation and trace-cap knobs all
    ON vs all off — cold run and served job alike."""
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path, n=12)

    def outs(tag):
        return [str(tmp_path / f"{tag}.dfa"),
                str(tmp_path / f"{tag}.sum")]

    def body(tag):
        return b"".join(open(p, "rb").read() for p in outs(tag))

    o = outs("ref")
    err = io.StringIO()
    assert run([paf, "-r", fa, "-o", o[0], "-s", o[1],
                "--device=tpu", "--batch=4"], stderr=err) == 0, \
        err.getvalue()
    o = outs("obs")
    err = io.StringIO()
    assert run([paf, "-r", fa, "-o", o[0], "-s", o[1],
                "--device=tpu", "--batch=4",
                f"--trace-json={tmp_path / 'o.trace'}",
                "--trace-max-events=100000",
                f"--log-json={tmp_path / 'o.ndjson'}",
                "--log-json-max-bytes=100000",
                f"--stats={tmp_path / 'o.json'}",
                f"--metrics-textfile={tmp_path / 'o.prom'}"],
               stderr=err) == 0, err.getvalue()
    assert body("obs") == body("ref")
    # served (flight recorder + trace_id always on) vs cold
    with _daemon(spool_threshold_bytes=1) as h:
        o = outs("svc")
        with ServiceClient(h.sock) as c:
            sub = c.submit([paf, "-r", fa, "-o", o[0], "-s", o[1],
                            "--device=tpu", "--batch=4"])
            assert c.result(sub["job_id"], timeout=120)["rc"] == 0
    assert body("svc") == body("ref")
