"""End-to-end CLI tests: report + MSA outputs, modes, exit codes."""

import io
import json
import subprocess
import sys

from pwasm_tpu.cli import run
from pwasm_tpu.core.fasta import write_fasta

from helpers import make_paf_line

Q = "ACGTACGTAC"


def _mk_inputs(tmp_path, lines, qname="q", qseq=Q):
    fa = tmp_path / "q.fa"
    write_fasta(str(fa), [(qname, qseq.encode())])
    paf = tmp_path / "in.paf"
    paf.write_text("".join(l + "\n" for l in lines))
    return str(paf), str(fa)


def _three_alignments():
    l1, _ = make_paf_line("q", Q, "asm1", "+",
                          [("=", 6), ("ins", "gg"), ("=", 4)])
    l2, _ = make_paf_line("q", Q, "asm2", "+",
                          [("=", 2), ("del", 2), ("=", 6)])
    l3, _ = make_paf_line("q", Q, "asm3", "-", [("=", 10)])
    return [l1, l2, l3]


def test_report_and_msa_end_to_end(tmp_path):
    paf, fa = _mk_inputs(tmp_path, _three_alignments())
    report = tmp_path / "out.dfa"
    mfa = tmp_path / "out.mfa"
    err = io.StringIO()
    rc = run([paf, "-r", fa, "-o", str(report), "-w", str(mfa)],
             stderr=err)
    assert rc == 0
    rep = report.read_text().splitlines()
    assert rep[0] == ">asm1:0-12+ coverage:100.00 score=0 edit_distance=0"
    assert rep[1].startswith("I\t7\t")
    assert rep[2] == ">asm2:0-8+ coverage:100.00 score=0 edit_distance=0"
    assert rep[3].startswith("D\t3\t")
    assert rep[4] == ">asm3:0-10- coverage:100.00 score=0 edit_distance=0"
    assert mfa.read_text() == (
        ">q\nACGTAC--GTAC\n"
        ">asm1:0-12+\nACGTACggGTAC\n"
        ">asm2:0-8+\nAC--AC--GTAC\n"
        ">asm3:0-10-\nACGTAC--GTAC\n")


def test_gene_mode_dedup_warning(tmp_path):
    lines = _three_alignments()
    lines.append(lines[0])  # duplicate q~asm1
    lines.append(lines[0])  # third occurrence: no extra warning
    paf, fa = _mk_inputs(tmp_path, lines)
    out = io.StringIO()
    err = io.StringIO()
    rc = run([paf, "-r", fa], stdout=out, stderr=err)
    assert rc == 0
    warnings = [l for l in err.getvalue().splitlines()
                if "already seen" in l]
    assert len(warnings) == 1  # warned only on the second occurrence
    assert out.getvalue().count(">asm1") == 1


def test_fullgenome_keeps_duplicates_and_rlabel(tmp_path):
    lines = [_three_alignments()[0]] * 2
    paf, fa = _mk_inputs(tmp_path, lines)
    out = io.StringIO()
    rc = run([paf, "-r", fa, "-F"], stdout=out, stderr=io.StringIO())
    assert rc == 0
    # -F: all alignments kept, rlabel prefixed, codon analysis skipped
    body = out.getvalue()
    assert body.count(">q:0-10--asm1:0-12+") == 2
    assert body.splitlines()[1].endswith("\t")  # empty impact column


def test_self_alignment_skipped(tmp_path):
    line, _ = make_paf_line("q", Q, "q", "+", [("=", 10)])
    paf, fa = _mk_inputs(tmp_path, [line])
    out = io.StringIO()
    rc = run([paf, "-r", fa, "-v"], stdout=out, stderr=io.StringIO())
    assert rc == 0
    assert out.getvalue() == ""


def test_summary_output(tmp_path):
    paf, fa = _mk_inputs(tmp_path, _three_alignments())
    summ = tmp_path / "s.txt"
    rc = run([paf, "-r", fa, "-s", str(summ), "-o", str(tmp_path / "r.dfa")],
             stderr=io.StringIO())
    assert rc == 0
    body = summ.read_text()
    assert "alignments\t3" in body
    assert "insertions\t1\t2 bases" in body
    assert "deletions\t1\t2 bases" in body


def test_usage_errors(tmp_path):
    paf, fa = _mk_inputs(tmp_path, _three_alignments())
    err = io.StringIO()
    assert run([paf, "-r", fa, "-G", "-F"], stderr=err) == 1
    assert "cannot use both -G and -F" in err.getvalue()
    err = io.StringIO()
    assert run([paf, "-r", fa, "-C", "-N"], stderr=err) == 1
    err = io.StringIO()
    assert run([paf], stderr=err) == 1
    assert "query FASTA file (-r) is required" in err.getvalue()
    err = io.StringIO()
    assert run(["/nonexistent.paf", "-r", fa], stderr=err) == 1
    assert "Cannot open input file" in err.getvalue()
    err = io.StringIO()
    assert run([paf, "-r", fa, "-F", "-w", str(tmp_path / "x.mfa")],
               stderr=err) == 1
    assert "can only generate MSA for -G mode" in err.getvalue()


def test_bad_clipmax(tmp_path):
    paf, fa = _mk_inputs(tmp_path, _three_alignments())
    err = io.StringIO()
    assert run([paf, "-r", fa, "-c", "0"], stderr=err) == 1
    assert "invalid -c" in err.getvalue()
    err = io.StringIO()
    assert run([paf, "-r", fa, "-c", "150%"], stderr=err) == 1


def test_ref_len_mismatch_fatal(tmp_path):
    line, _ = make_paf_line("q", Q, "asm1", "+", [("=", 10)])
    # corrupt the query length field
    f = line.split("\t")
    f[1] = "11"
    paf, fa = _mk_inputs(tmp_path, ["\t".join(f)])
    err = io.StringIO()
    rc = run([paf, "-r", fa], stdout=io.StringIO(), stderr=err)
    assert rc == 1
    assert "differs from loaded sequence length" in err.getvalue()


def test_comment_lines_skipped(tmp_path):
    lines = ["# a comment"] + _three_alignments()
    paf, fa = _mk_inputs(tmp_path, lines)
    assert run([paf, "-r", fa], stdout=io.StringIO(),
               stderr=io.StringIO()) == 0


def test_motifs_file(tmp_path):
    paf, fa = _mk_inputs(tmp_path, _three_alignments())
    mot = tmp_path / "motifs.txt"
    mot.write_text("# custom\nACGTAC\n")
    out = io.StringIO()
    rc = run([paf, "-r", fa, f"--motifs={mot}"], stdout=out,
             stderr=io.StringIO())
    assert rc == 0
    assert "motif ACGTAC" in out.getvalue()


def test_device_path_byte_parity(tmp_path):
    """--device=tpu (batched ctx_scan program; interpret-mode on CPU) must
    produce byte-identical report + summary vs the scalar --device=cpu
    path, across multiple batch flushes and both strands."""
    qseq = "ATGGCCTGGACGTACGATCAAGGT"  # codon-aligned, motif-bearing
    lines = [
        make_paf_line("q", qseq, "a1", "+",
                      [("=", 4), ("*", "a", "c"), ("=", 19)])[0],
        make_paf_line("q", qseq, "a2", "+",
                      [("=", 6), ("ins", "gg"), ("=", 18)])[0],
        make_paf_line("q", qseq, "a3", "-",
                      [("=", 10), ("del", 2), ("=", 12)])[0],
        make_paf_line("q", qseq, "a4", "-",
                      [("=", 3), ("*", "g", "t"), ("=", 20)])[0],
        make_paf_line("q", qseq, "a5", "+",
                      [("=", 2), ("*", "c", "g"), ("*", "a", "g"),
                       ("=", 20)])[0],
    ]
    paf, fa = _mk_inputs(tmp_path, lines, qseq=qseq)
    outs = {}
    for dev in ("cpu", "tpu"):
        rep = tmp_path / f"r_{dev}.dfa"
        summ = tmp_path / f"s_{dev}.txt"
        rc = run([paf, "-r", fa, "-o", str(rep), "-s", str(summ),
                  f"--device={dev}", "--batch=2"], stderr=io.StringIO())
        assert rc == 0
        outs[dev] = (rep.read_text(), summ.read_text())
    assert outs["cpu"] == outs["tpu"]
    assert "S\t" in outs["cpu"][0]  # sanity: events actually analyzed


def test_device_path_flushes_on_error(tmp_path):
    """A bad line mid-stream must not drop earlier alignments buffered by
    the device path — the cpu path writes them progressively."""
    qseq = "ATGGCCTGGACGTACGATCAAGGT"
    good = make_paf_line("q", qseq, "a1", "+",
                         [("=", 4), ("*", "a", "c"), ("=", 19)])[0]
    bad = good.replace("a1", "a2").split("\t")
    bad[1] = "99"  # r_len contradicts the FASTA -> fatal after a1
    lines = [good, "\t".join(bad)]
    paf, fa = _mk_inputs(tmp_path, lines, qseq=qseq)
    outs = {}
    for dev in ("cpu", "tpu"):
        rep = tmp_path / f"e_{dev}.dfa"
        rc = run([paf, "-r", fa, "-o", str(rep), f"--device={dev}"],
                 stderr=io.StringIO())
        assert rc == 1
        outs[dev] = rep.read_text()
    assert outs["cpu"] == outs["tpu"]
    assert ">a1" in outs["tpu"]


def test_subprocess_entry(tmp_path):
    paf, fa = _mk_inputs(tmp_path, _three_alignments())
    r = subprocess.run(
        [sys.executable, "-m", "pwasm_tpu.cli", paf, "-r", fa],
        capture_output=True, text=True, cwd="/root/repo")
    assert r.returncode == 0
    assert r.stdout.startswith(">asm1:0-12+")


def test_consensus_outputs_ace_info_cons(tmp_path):
    paf, fa = _mk_inputs(tmp_path, _three_alignments())
    ace = tmp_path / "out.ace"
    info = tmp_path / "out.info"
    cons = tmp_path / "out.cons"
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "r.dfa"),
              f"--ace={ace}", f"--info={info}", f"--cons={cons}"],
             stderr=io.StringIO())
    assert rc == 0
    ace_body = ace.read_text()
    assert ace_body.startswith("CO q ")
    assert "AF q U 1" in ace_body
    assert "RD asm1:0-12+ 12 0 0" in ace_body
    info_body = info.read_text()
    assert info_body.startswith(">q 4 ")
    # consensus keeps the all-gap column ('*') without --remove-cons-gaps
    cons_lines = cons.read_text().splitlines()
    assert cons_lines[0].startswith(">q_cons 4 seqs")
    assert cons_lines[1] == "ACGTAC**GTAC"


def test_consensus_remove_cons_gaps(tmp_path):
    paf, fa = _mk_inputs(tmp_path, _three_alignments())
    cons = tmp_path / "out.cons"
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "r.dfa"),
              f"--cons={cons}", "--remove-cons-gaps"],
             stderr=io.StringIO())
    assert rc == 0
    # the 2-col 'gg' insertion columns (1 base vs 3 gaps) win as gaps and
    # are removed from the layout
    assert cons.read_text().splitlines()[1] == "ACGTACGTAC"


def test_consensus_device_matches_cpu(tmp_path):
    paf, fa = _mk_inputs(tmp_path, _three_alignments())
    out_cpu = tmp_path / "cpu.ace"
    out_dev = tmp_path / "dev.ace"
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "r1.dfa"),
              f"--ace={out_cpu}"], stderr=io.StringIO())
    assert rc == 0
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "r2.dfa"),
              f"--ace={out_dev}", "--device=tpu"], stderr=io.StringIO())
    assert rc == 0
    assert out_dev.read_text() == out_cpu.read_text()


def test_device_probe_failure_demotes_to_cpu(tmp_path, monkeypatch):
    """--device=tpu against an unreachable backend (simulated probe
    failure): the run demotes to the CPU path loudly instead of hanging
    at jax init — outputs byte-identical to --device=cpu and the
    demotion counted in engine_fallbacks."""
    import pwasm_tpu.utils.backend as backend

    paf, fa = _mk_inputs(tmp_path, _three_alignments())
    monkeypatch.setattr(backend, "device_backend_reachable",
                        lambda: (False, "probe hang (> 150s)"))
    err = io.StringIO()
    stats = tmp_path / "s.json"
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "d.dfa"),
              f"--ace={tmp_path / 'd.ace'}", "--device=tpu",
              f"--stats={stats}"], stderr=err)
    assert rc == 0
    assert "backend unreachable" in err.getvalue()
    assert json.loads(stats.read_text())["engine_fallbacks"] == 1
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "c.dfa"),
              f"--ace={tmp_path / 'c.ace'}"], stderr=io.StringIO())
    assert rc == 0
    assert (tmp_path / "d.dfa").read_bytes() == \
        (tmp_path / "c.dfa").read_bytes()
    assert (tmp_path / "d.ace").read_bytes() == \
        (tmp_path / "c.ace").read_bytes()


def test_ace_remove_cons_gaps_device_no_fallback(tmp_path):
    """--ace --remove-cons-gaps --device=tpu: the whole consensus path
    (counts+votes, gap-column removal, both refine passes) runs without
    any engine-level host demotion (VERDICT r3 item 4) — byte-identical
    to the cpu run and engine_fallbacks == 0 in --stats."""
    paf, fa = _mk_inputs(tmp_path, _three_alignments())
    outs = {}
    for dev in ("cpu", "tpu"):
        ace = tmp_path / f"{dev}.ace"
        info = tmp_path / f"{dev}.info"
        stats = tmp_path / f"{dev}.stats"
        err = io.StringIO()
        rc = run([paf, "-r", fa, "-o", str(tmp_path / f"r_{dev}.dfa"),
                  f"--ace={ace}", f"--info={info}", "--remove-cons-gaps",
                  f"--device={dev}", f"--stats={stats}"], stderr=err)
        assert rc == 0
        assert "fell back" not in err.getvalue()
        assert "unavailable" not in err.getvalue()
        d = json.loads(stats.read_text())
        assert d["engine_fallbacks"] == 0
        outs[dev] = ace.read_text() + info.read_text()
    assert outs["cpu"] == outs["tpu"]


def test_ace_device_deep_pileup_kernel_counts(tmp_path, monkeypatch):
    """--ace --device=tpu on a 256-deep pileup: the consensus counts come
    from the Pallas kernel (spied call over the full-depth pileup) and the
    ACE output is byte-identical to the host engine (VERDICT r2 next #1)."""
    lines = []
    for k in range(256):
        ops = [[("=", 10)],
               [("=", 6), ("ins", "gg"), ("=", 4)],
               [("=", 2), ("del", 2), ("=", 6)]][k % 3]
        l, _ = make_paf_line("q", Q, f"t{k:03d}", "+", ops)
        lines.append(l)
    paf, fa = _mk_inputs(tmp_path, lines)
    out_cpu = tmp_path / "cpu.ace"
    out_dev = tmp_path / "dev.ace"
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "r1.dfa"),
              f"--ace={out_cpu}"], stderr=io.StringIO())
    assert rc == 0

    import pwasm_tpu.ops.consensus as consmod
    shapes = []
    real = consmod.consensus_pallas

    def spy(bases, *a, **k):
        shapes.append(tuple(bases.shape))
        return real(bases, *a, **k)

    monkeypatch.setattr(consmod, "consensus_pallas", spy)
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "r2.dfa"),
              f"--ace={out_dev}", "--device=tpu"], stderr=io.StringIO())
    assert rc == 0
    # one kernel launch over the full pileup: ref + 256 targets deep
    assert shapes and shapes[0][0] == 257
    assert out_dev.read_text() == out_cpu.read_text()


def test_shard_cli_byte_identical_on_virtual_mesh(tmp_path):
    """--shard over the 8 virtual CPU devices (conftest mesh): report
    AND consensus outputs byte-identical to the unsharded device run —
    the product multi-chip path (VERDICT r2 next #5)."""
    import jax

    assert len(jax.devices()) >= 8
    lines = []
    for k in range(64):
        ops = [[("=", 10)], [("=", 6), ("ins", "gg"), ("=", 4)],
               [("=", 2), ("del", 2), ("=", 6)]][k % 3]
        l, _ = make_paf_line("q", Q, f"t{k:03d}", "+", ops)
        lines.append(l)
    paf, fa = _mk_inputs(tmp_path, lines)
    outs = {}
    for mode, extra in (("plain", []), ("shard", ["--shard"]),
                        ("shard4", ["--shard=4"])):
        rep = tmp_path / f"{mode}.dfa"
        ace = tmp_path / f"{mode}.ace"
        rc = run([paf, "-r", fa, "-o", str(rep), f"--ace={ace}",
                  "--device=tpu"] + extra, stderr=io.StringIO())
        assert rc == 0, mode
        outs[mode] = rep.read_text() + ace.read_text()
    assert outs["plain"] == outs["shard"] == outs["shard4"]


def test_shard_requires_device_tpu(tmp_path):
    paf, fa = _mk_inputs(tmp_path, _three_alignments())
    err = io.StringIO()
    assert run([paf, "-r", fa, "--shard"], stderr=err) == 1
    assert "--shard requires --device=tpu" in err.getvalue()


def test_cons_requires_gene_mode(tmp_path):
    paf, fa = _mk_inputs(tmp_path, _three_alignments())
    err = io.StringIO()
    assert run([paf, "-r", fa, "-F", f"--ace={tmp_path / 'x.ace'}"],
               stderr=err) == 1
    assert "can only generate MSA for -G mode" in err.getvalue()
    err = io.StringIO()
    assert run([paf, "-r", fa, "--ace"], stderr=err) == 1
    assert "--ace requires a file argument" in err.getvalue()


def test_device_fallback_counted_and_surfaced(tmp_path, monkeypatch):
    """A failing device batch must replay on host with correct output,
    count fallback_batches in --stats, and warn at exit (VERDICT r2
    next #9)."""
    import json

    import pwasm_tpu.report.device_report as dr

    monkeypatch.setattr(dr, "_warned_fallback", False)

    def boom(*a, **k):
        raise RuntimeError("injected device failure")

    monkeypatch.setattr(dr, "submit_events_device", boom)
    paf, fa = _mk_inputs(tmp_path, _three_alignments())
    rep_cpu = tmp_path / "cpu.dfa"
    rc = run([paf, "-r", fa, "-o", str(rep_cpu)], stderr=io.StringIO())
    assert rc == 0
    rep = tmp_path / "dev.dfa"
    stats = tmp_path / "stats.json"
    err = io.StringIO()
    rc = run([paf, "-r", fa, "-o", str(rep), "--device=tpu",
              f"--stats={stats}"], stderr=err)
    assert rc == 0
    assert rep.read_text() == rep_cpu.read_text()
    st = json.loads(stats.read_text())
    assert st["fallback_batches"] >= 1
    assert st["device_batches"] >= st["fallback_batches"]
    # (the once-per-run failure warning prints to process stderr from
    # the device module; the CLI's own closing warning is what must
    # flow through the injected stream)
    assert "1/1 device batches fell back to the host scalar path" \
        in err.getvalue()


def test_skip_bad_lines(tmp_path):
    lines = _three_alignments()
    lines.insert(1, "not\ta\tpaf\tline")                  # too few fields
    l_badcs, _ = make_paf_line("q", Q, "asmX", "+", [("=", 10)])
    # corrupt the cs tag so extraction fails on base mismatch
    lines.insert(3, l_badcs.replace("cs:Z::10", "cs:Z::4*gc:5"))
    paf, fa = _mk_inputs(tmp_path, lines)
    report = tmp_path / "out.dfa"
    err = io.StringIO()
    # without the flag: fatal parse error, exit code 3
    rc = run([paf, "-r", fa, "-o", str(report)], stderr=err)
    assert rc != 0
    # with the flag: bad lines skipped with warnings, good ones reported
    err = io.StringIO()
    stats = tmp_path / "stats.json"
    rc = run([paf, "-r", fa, "-o", str(report), "--skip-bad-lines",
              f"--stats={stats}"], stderr=err)
    assert rc == 0
    rep = report.read_text()
    assert rep.count(">") == 3
    assert err.getvalue().count("skipping malformed PAF line") == 2
    import json
    st = json.loads(stats.read_text())
    assert st["skipped_bad_lines"] == 2
    assert st["alignments"] == 3
    assert st["aligned_bases"] > 0


def test_skip_bad_lines_covers_out_of_layout_gaps(tmp_path):
    """An alignment whose gap structure cannot be inserted (a reverse
    alignment STARTING with a deletion puts a ref gap at r_len — fatal
    in the reference's setGap, GapAssem.cpp:105-107) aborts a bare -w
    run exactly like the reference, and is skipped cleanly under
    --skip-bad-lines."""
    import json

    good, _ = make_paf_line("q", Q, "t0", "+", [("=", 10)])
    bad, _ = make_paf_line("q", Q, "tBAD", "-", [("del", 2), ("=", 8)])
    # a later VALID alignment of the same pair must take the dropped
    # one's gene-mode dedup slot
    redo, _ = make_paf_line("q", Q, "tBAD", "+", [("=", 10)])
    paf, fa = _mk_inputs(tmp_path, [good, bad, redo])
    mfa = tmp_path / "out.mfa"
    err = io.StringIO()
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "r.dfa"),
              "-w", str(mfa)], stderr=err)
    assert rc == 1
    assert "invalid gap position" in err.getvalue()
    err = io.StringIO()
    stats = tmp_path / "stats.json"
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "r2.dfa"),
              "-w", str(mfa), "--skip-bad-lines",
              f"--stats={stats}"], stderr=err)
    assert rc == 0
    assert "excluding alignment tBAD:0-8- from the MSA" in err.getvalue()
    body = mfa.read_text()
    assert ">t0:0-10+" in body
    assert ">tBAD:0-10+" in body      # the valid retry made it in
    assert "tBAD:0-8-" not in body    # the bad one did not
    st = json.loads(stats.read_text())
    # the dropped alignment's report rows exist: it counts as an
    # alignment AND as msa_dropped, not as a skipped line
    assert st["msa_dropped"] == 1
    assert st["skipped_bad_lines"] == 0
    assert st["alignments"] == 3


def test_resume_appends_remaining_alignments(tmp_path):
    lines = _three_alignments()
    paf, fa = _mk_inputs(tmp_path, lines)
    full = tmp_path / "full.dfa"
    assert run([paf, "-r", fa, "-o", str(full)], stderr=io.StringIO()) == 0

    # simulate an interrupted run: only the first alignment was emitted
    part = tmp_path / "part.dfa"
    paf1 = tmp_path / "first.paf"
    paf1.write_text(lines[0] + "\n")
    assert run([str(paf1), "-r", fa, "-o", str(part)],
               stderr=io.StringIO()) == 0
    # resume over the full input appends exactly the missing alignments
    assert run([paf, "-r", fa, "-o", str(part), "--resume"],
               stderr=io.StringIO()) == 0
    assert part.read_text() == full.read_text()
    # resuming a complete report is a no-op
    assert run([paf, "-r", fa, "-o", str(part), "--resume"],
               stderr=io.StringIO()) == 0
    assert part.read_text() == full.read_text()


def test_resume_with_msa_rebuilds_full_msa(tmp_path):
    """--resume with an MSA output: report rows for already-emitted
    alignments are skipped, but the MSA must still include EVERY
    alignment (the fast-path cursor is disabled when an MSA output is
    requested — every line goes through extraction and merge)."""
    lines = _three_alignments()
    paf, fa = _mk_inputs(tmp_path, lines)
    full = tmp_path / "full.dfa"
    full_mfa = tmp_path / "full.mfa"
    assert run([paf, "-r", fa, "-o", str(full), "-w", str(full_mfa)],
               stderr=io.StringIO()) == 0
    part = tmp_path / "part.dfa"
    paf1 = tmp_path / "first.paf"
    paf1.write_text(lines[0] + "\n")
    assert run([str(paf1), "-r", fa, "-o", str(part)],
               stderr=io.StringIO()) == 0
    mfa = tmp_path / "resumed.mfa"
    assert run([paf, "-r", fa, "-o", str(part), "-w", str(mfa),
                "--resume"], stderr=io.StringIO()) == 0
    assert part.read_text() == full.read_text()
    assert mfa.read_text() == full_mfa.read_text()


def test_resume_requires_report(tmp_path):
    paf, fa = _mk_inputs(tmp_path, _three_alignments())
    err = io.StringIO()
    assert run([paf, "-r", fa, "--resume"], stderr=err) != 0


def test_stats_and_profile_flags(tmp_path):
    paf, fa = _mk_inputs(tmp_path, _three_alignments())
    report = tmp_path / "out.dfa"
    stats = tmp_path / "stats.json"
    err = io.StringIO()
    rc = run([paf, "-r", fa, "-o", str(report), f"--stats={stats}", "-v"],
             stderr=err)
    assert rc == 0
    import json
    st = json.loads(stats.read_text())
    assert st["alignments"] == 3
    assert st["events"] == 2
    assert st["wall_s"] >= 0
    assert "alignments, " in err.getvalue()  # -v brief line


def test_resume_truncates_torn_record(tmp_path):
    lines = _three_alignments()
    paf, fa = _mk_inputs(tmp_path, lines)
    full = tmp_path / "full.dfa"
    assert run([paf, "-r", fa, "-o", str(full)], stderr=io.StringIO()) == 0

    # interrupted mid-record: header + half an event row, no newline
    torn = tmp_path / "torn.dfa"
    content = full.read_text()
    second_hdr = content.index(">", 1)
    torn.write_text(content[:second_hdr] + ">asm2:0-8+ coverage:100.00 "
                    "score=0 edit_distance=0\nD\t3\t1(T")
    assert run([paf, "-r", fa, "-o", str(torn), "--resume"],
               stderr=io.StringIO()) == 0
    assert torn.read_text() == content


def test_skip_bad_line_does_not_poison_dedup(tmp_path):
    # a skipped malformed line must not mark its (q,t) pair as seen
    good, _ = make_paf_line("q", Q, "asm1", "+",
                            [("=", 6), ("ins", "gg"), ("=", 4)])
    bad = good.replace("cs:Z::6", "cs:Z::2*gc:3")  # base mismatch vs ref
    paf, fa = _mk_inputs(tmp_path, [bad, good])
    report = tmp_path / "out.dfa"
    err = io.StringIO()
    rc = run([paf, "-r", fa, "-o", str(report), "--skip-bad-lines"],
             stderr=err)
    assert rc == 0
    assert "already seen" not in err.getvalue()
    assert report.read_text().count(">asm1") == 1


def test_resume_with_skip_bad_lines_stays_in_sync(tmp_path):
    """A line that parses but fails extraction (skipped in the original
    run, absent from the report) must not consume a --resume cursor slot."""
    good1, good2, good3 = _three_alignments()
    bad = good1.replace("asm1", "asmB").replace("cs:Z::6", "cs:Z::2*gc:3")
    lines = [bad, good1, good2, good3]
    paf, fa = _mk_inputs(tmp_path, lines)
    full = tmp_path / "full.dfa"
    assert run([paf, "-r", fa, "-o", str(full), "--skip-bad-lines"],
               stderr=io.StringIO()) == 0

    # interrupted after the first two emitted records
    part = tmp_path / "part.dfa"
    content = full.read_text()
    third_hdr = content.index(">asm3")
    part.write_text(content[:third_hdr])
    assert run([paf, "-r", fa, "-o", str(part), "--resume",
                "--skip-bad-lines"], stderr=io.StringIO()) == 0
    assert part.read_text() == content


def test_device_share_observability(tmp_path):
    """VERDICT r4 weak #6: the per-event device/scalar routing of the
    ctx-scan path must be visible in RunStats, so a heavy-indel input
    quietly running mostly on host fails a test instead of hiding.
    Events longer than MAX_EV=16 bases are out of device scope (they
    take the scalar path inside finish()); everything else must run on
    the device program."""
    import json

    qseq = "ATGGCCTGGACGTACGATCAAGGTCCTGGAGATCTTTACGTACGATCAAGG"  # 51bp
    big_ins = "acgtacgtacgtacgtacgt"            # 20 > MAX_EV
    lines = [
        # 2 in-scope events
        make_paf_line("q", qseq, "a1", "+",
                      [("=", 4), ("*", "a", "c"), ("=", 10),
                       ("ins", "gg"), ("=", 36)])[0],
        # 1 out-of-scope insertion + 1 in-scope substitution
        make_paf_line("q", qseq, "a2", "+",
                      [("=", 6), ("ins", big_ins), ("=", 20),
                       ("*", "c", "t"), ("=", 24)])[0],
        # 1 out-of-scope deletion
        make_paf_line("q", qseq, "a3", "+",
                      [("=", 8), ("del", 18), ("=", 25)])[0],
    ]
    paf, fa = _mk_inputs(tmp_path, lines, qseq=qseq)
    stats_f = tmp_path / "stats.json"
    rep_dev = tmp_path / "dev.dfa"
    rc = run([paf, "-r", fa, "-o", str(rep_dev), "--device=tpu",
              f"--stats={stats_f}"], stderr=io.StringIO())
    assert rc == 0
    d = json.loads(stats_f.read_text())
    assert d["device_events"] == 3
    assert d["scalar_events"] == 2
    assert d["fallback_batches"] == 0
    # the same input on --device=cpu reports zero device share
    rep_cpu = tmp_path / "cpu.dfa"
    stats_c = tmp_path / "stats_cpu.json"
    rc = run([paf, "-r", fa, "-o", str(rep_cpu), "--device=cpu",
              f"--stats={stats_c}"], stderr=io.StringIO())
    assert rc == 0
    dc = json.loads(stats_c.read_text())
    assert dc["device_events"] == 0 and dc["scalar_events"] == 0
    # and the routed output stays byte-identical to the scalar path
    assert rep_dev.read_bytes() == rep_cpu.read_bytes()


def test_device_share_counters_roll_back_on_fallback(tmp_path,
                                                     monkeypatch):
    """When the device batch fails and replays on host, the routing
    counters must say so: device_events stays 0 (no partial credit)
    and every event counts as scalar — otherwise a dead device path
    masquerades as full device share (the exact blind spot the
    counters exist to expose)."""
    import json

    import pwasm_tpu.report.device_report as dr

    monkeypatch.setattr(dr, "_warned_fallback", False)
    real_submit = dr.submit_events_device
    calls = []

    def fail_fetch(*a, **k):
        # the submit succeeds; the FETCH inside finish() fails — the
        # partial-credit window the snapshot/rollback protects
        fin = real_submit(*a, **k)
        calls.append(1)

        def bad_finish():
            raise RuntimeError("injected fetch failure")

        return bad_finish

    monkeypatch.setattr(dr, "submit_events_device", fail_fetch)
    paf, fa = _mk_inputs(tmp_path, _three_alignments())
    rep = tmp_path / "dev.dfa"
    stats = tmp_path / "stats.json"
    rc = run([paf, "-r", fa, "-o", str(rep), "--device=tpu",
              f"--stats={stats}"], stderr=io.StringIO())
    assert rc == 0
    assert calls  # the injected path actually ran
    st = json.loads(stats.read_text())
    assert st["device_events"] == 0
    assert st["scalar_events"] == st["events"] > 0
    assert st["fallback_batches"] >= 1


def test_compilation_cache_arming(tmp_path, monkeypatch):
    """enable_compilation_cache: sets the persistent-cache config keys
    exactly once, honors PWASM_JAX_CACHE_DIR, and PWASM_JAX_CACHE=0
    opts out — unit-tested against a captured config.update so the
    process-global jax config stays untouched."""
    import pwasm_tpu.ops as ops

    calls = []

    class FakeConfig:
        def update(self, k, v):
            calls.append((k, v))

    class FakeJax:
        config = FakeConfig()

    monkeypatch.setattr(ops, "_cache_armed", False)
    monkeypatch.setenv("PWASM_JAX_CACHE_DIR", str(tmp_path / "jc"))
    monkeypatch.delenv("PWASM_JAX_CACHE", raising=False)
    monkeypatch.setitem(sys.modules, "jax", FakeJax())
    ops.enable_compilation_cache()
    keys = dict(calls)
    assert keys["jax_compilation_cache_dir"] == str(tmp_path / "jc")
    assert (tmp_path / "jc").is_dir()
    assert keys["jax_persistent_cache_min_compile_time_secs"] == 0.0
    # idempotent: second call is a no-op
    n = len(calls)
    ops.enable_compilation_cache()
    assert len(calls) == n
    # opt-out
    monkeypatch.setattr(ops, "_cache_armed", False)
    monkeypatch.setenv("PWASM_JAX_CACHE", "0")
    ops.enable_compilation_cache()
    assert len(calls) == n
