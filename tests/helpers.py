"""Test helpers: synthesize PAF+cs+cigar lines from explicit alignment ops.

The synthesizer is an independent oracle: it builds minimap2-style records
from a declarative op list, so extractor tests don't share logic with the
code under test.

Alignment ops (in alignment orientation, query side = the ``-r`` FASTA):
  ("=", n)        n matching bases
  ("*", t, q)     substitution: target base t, query base q
  ("ins", bases)  bases present only in the target  (cs '-', cigar D)
  ("del", n)      n query bases absent from the target (cs '+', cigar I)
"""

from __future__ import annotations

from pwasm_tpu.core.dna import revcomp

_COMP = {"a": "t", "c": "g", "g": "c", "t": "a", "n": "n"}


def _comp(b: str) -> str:
    return _COMP[b.lower()]


def synth_alignment(q_aln: str, ops) -> tuple[str, str, str]:
    """Apply ops to the aligned query slice; return (cs, cigar, target_seq).

    ``q_aln`` is the query subsequence covered by the alignment, in
    *alignment orientation* (i.e. already reverse-complemented for '-'
    alignments), upper-case.
    """
    cs_parts = []
    cig_parts = []
    tseq = []
    qpos = 0

    def cig(n, op):
        if cig_parts and cig_parts[-1][1] == op:
            cig_parts[-1] = (cig_parts[-1][0] + n, op)
        else:
            cig_parts.append((n, op))

    for op in ops:
        kind = op[0]
        if kind == "=":
            n = op[1]
            cs_parts.append(f":{n}")
            tseq.append(q_aln[qpos:qpos + n])
            qpos += n
            cig(n, "M")
        elif kind == "*":
            t, q = op[1].lower(), op[2].lower()
            assert q_aln[qpos].lower() == q, "op mismatch vs q_aln"
            cs_parts.append(f"*{t}{q}")
            tseq.append(t.upper())
            qpos += 1
            cig(1, "M")
        elif kind == "ins":
            bases = op[1].lower()
            cs_parts.append("-" + bases)
            tseq.append(bases.upper())
            cig(len(bases), "D")
        elif kind == "del":
            n = op[1]
            cs_parts.append("+" + q_aln[qpos:qpos + n].lower())
            qpos += n
            cig(n, "I")
        else:
            raise ValueError(kind)
    assert qpos == len(q_aln), "ops must consume the whole aligned query"
    cigar = "".join(f"{n}{c}" for n, c in cig_parts)
    return "".join(cs_parts), cigar, "".join(tseq)


def reverse_ops(ops):
    """Express the same biological alignment in the opposite orientation."""
    out = []
    for op in reversed(ops):
        kind = op[0]
        if kind == "=":
            out.append(op)
        elif kind == "*":
            out.append(("*", _comp(op[1]), _comp(op[2])))
        elif kind == "ins":
            out.append(("ins", revcomp(op[1].encode()).decode()))
        else:
            out.append(op)
    return out


def make_paf_line(q_id: str, q_seq: str, t_id: str, strand: str, ops,
                  q_start: int = 0, q_end: int | None = None,
                  t_start: int = 0, t_len: int | None = None,
                  nm: int = 0, score: int = 0) -> tuple[str, str]:
    """Build a full PAF line; returns (line, target_seq_in_aln_orientation).

    ``q_start``/``q_end`` are forward-query coordinates of the aligned
    region.  For strand '-', ``ops`` must describe the alignment of the
    target against revcomp(query), i.e. they consume
    revcomp(q)[qlen-q_end : qlen-q_start].
    """
    q_len = len(q_seq)
    if q_end is None:
        q_end = q_len
    if strand == "-":
        q_aln = revcomp(q_seq.encode()).decode()[q_len - q_end:q_len - q_start]
    else:
        q_aln = q_seq[q_start:q_end]
    cs, cigar, tseq = synth_alignment(q_aln.upper(), ops)
    t_end = t_start + len(tseq)
    if t_len is None:
        t_len = t_end
    fields = [
        q_id, str(q_len), str(q_start), str(q_end), strand,
        t_id, str(t_len), str(t_start), str(t_end),
        str(q_end - q_start), str(max(q_end - q_start, len(tseq))), "60",
        f"NM:i:{nm}", f"AS:i:{score}", f"cg:Z:{cigar}", f"cs:Z:{cs}",
    ]
    return "\t".join(fields), tseq
