"""Parity fuzz for the vectorized X-drop clip refinement.

The vectorized ``refine_clipping`` must be bit-exact with the
transliterated reference walk ``refine_clipping_scalar`` on arbitrary
gapped sequences, clips and consensus offsets (VERDICT r1 next-step 4).
"""

import io
import contextlib
import time

import numpy as np
import pytest

from pwasm_tpu.align.gapseq import GapSeq


def _random_gapseq(rng, seqlen=None, with_dels=False):
    seqlen = seqlen or int(rng.integers(10, 60))
    seq = rng.choice(list(b"ACGT"), seqlen).astype("uint8").tobytes()
    s = GapSeq(f"s{rng.integers(1e9)}", "", seq)
    for _ in range(int(rng.integers(0, 6))):
        s.set_gap(int(rng.integers(0, seqlen)), int(rng.integers(1, 4)))
    if with_dels:
        for _ in range(int(rng.integers(0, 3))):
            p = int(rng.integers(0, seqlen))
            if s.gaps[p] <= 0:
                s.remove_base(p)
    s.clp5 = int(rng.integers(0, max(1, seqlen // 3)))
    s.clp3 = int(rng.integers(0, max(1, seqlen // 3)))
    s.revcompl = int(rng.integers(0, 2))
    return s


def _clone(s: GapSeq) -> GapSeq:
    c = GapSeq(s.name, s.descr, bytes(s.seq))
    c.gaps = s.gaps.copy()
    c.numgaps = s.numgaps
    c.clp5, c.clp3 = s.clp5, s.clp3
    c.revcompl = s.revcompl
    c.offset = s.offset
    return c


def _run_both(s: GapSeq, cons: bytes, cpos: int, skip_dels: bool):
    a, b = _clone(s), _clone(s)
    ea, eb = io.StringIO(), io.StringIO()
    with contextlib.redirect_stderr(ea):
        a.refine_clipping(cons, cpos, skip_dels=skip_dels)
    with contextlib.redirect_stderr(eb):
        b.refine_clipping_scalar(cons, cpos, skip_dels=skip_dels)
    assert (a.clp5, a.clp3) == (b.clp5, b.clp3), \
        (s.name, cons, cpos, skip_dels, s.revcompl,
         (a.clp5, a.clp3), (b.clp5, b.clp3))
    assert ea.getvalue() == eb.getvalue()


@pytest.mark.parametrize("seed", range(8))
@pytest.mark.parametrize("skip_dels", [False, True])
@pytest.mark.parametrize("with_dels", [False, True])
def test_refine_clipping_matches_scalar_fuzz(seed, skip_dels, with_dels):
    # with_dels x skip_dels decoupled: refine_msa's FIRST refine call
    # runs skip_dels=False on sequences already carrying deleted bases
    # (msa.py refine driver), so that regime needs oracle coverage too
    rng = np.random.default_rng(seed)
    for _ in range(40):
        s = _random_gapseq(rng, with_dels=with_dels)
        glen = s.seqlen + s.numgaps
        # consensus: sometimes related to the sequence, sometimes noise;
        # cpos jittered so edge clamps are exercised
        if rng.random() < 0.6:
            cons = bytes(s.seq) + rng.choice(
                list(b"ACGT"),
                int(rng.integers(0, 9))).astype("uint8").tobytes()
        else:
            cons = rng.choice(
                list(b"ACGT"),
                max(4, glen + int(rng.integers(-4, 5)))).astype(
                    "uint8").tobytes()
        cpos = int(rng.integers(-3, 6))
        _run_both(s, cons, cpos, skip_dels)


def test_refine_clipping_degenerate_inputs():
    """Empty consensus and fully-deleted layouts must warn + return like
    the scalar oracle, not crash (masked takes in seek)."""
    rng = np.random.default_rng(5)
    # empty consensus
    s = _random_gapseq(rng)
    s.clp5, s.clp3 = 2, 2
    _run_both(s, b"", 0, False)
    # every base deleted -> empty gapped layout
    s2 = GapSeq("alldel", "", b"ACGT")
    for p in range(4):
        s2.remove_base(p)
    s2.clp3 = 2
    _run_both(s2, b"ACGTACGT", 0, False)


def test_refine_clipping_mixed_case_consensus():
    """A consensus containing '*' gap columns (from refine_msa with
    remove_cons_gaps=False) exercises the star-vs-star comparisons."""
    rng = np.random.default_rng(99)
    for _ in range(30):
        s = _random_gapseq(rng)
        glen = s.seqlen + s.numgaps
        cons = bytearray(rng.choice(list(b"ACGT*"), glen + 6))
        _run_both(s, bytes(cons), int(rng.integers(0, 4)), False)


def test_refine_clipping_256_member_timing():
    """The vectorized pass over a 256-member, ~1.5 kb pileup must run in
    interactive time (the reference's per-character walk was the serial
    hot loop of BASELINE config 4)."""
    rng = np.random.default_rng(7)
    m = 1500
    base = rng.choice(list(b"ACGT"), m).astype(np.uint8)
    seqs = []
    for _ in range(256):
        arr = base.copy()
        idx = rng.integers(0, m, 40)
        arr[idx] = rng.choice(list(b"ACGT"), 40)
        s = GapSeq(f"r{len(seqs)}", "", bytes(arr))
        s.clp5 = int(rng.integers(1, 30))
        s.clp3 = int(rng.integers(1, 30))
        for _ in range(4):
            s.set_gap(int(rng.integers(0, m)), 1)
        seqs.append(s)
    cons = bytes(base)
    t0 = time.perf_counter()
    for s in seqs:
        s.refine_clipping(cons, 0)
    dt = time.perf_counter() - t0
    # generous CI bound; the scalar walk takes ~10x longer
    assert dt < 2.0, f"vectorized refine too slow: {dt:.2f}s"


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.parametrize("skip_dels", [False, True])
@pytest.mark.parametrize("device", [False, True])
def test_refine_clipping_batch_matches_single(seed, skip_dels, device):
    """The one-pass 2-D batch (refine_clipping_batch) — and its device
    phase program (ops/refine_clip.py, VERDICT r3 item 3) — must leave
    every member with exactly the clips the per-member pass produces,
    including no-hit abort bumps and zero-clip skips (VERDICT r2
    next #10)."""
    from pwasm_tpu.align.gapseq import refine_clipping_batch

    rng = np.random.default_rng(100 + seed)
    seqs, clones, cposes = [], [], []
    for k in range(24):
        s = _random_gapseq(rng, with_dels=skip_dels)
        if k % 5 == 0:
            s.clp5 = s.clp3 = 0      # the skip path
        seqs.append(s)
        clones.append(_clone(s))
        cposes.append(int(rng.integers(0, 5)))
    glen_max = max(s.seqlen + s.numgaps for s in seqs)
    cons = rng.choice(list(b"ACGT*"), glen_max + 8).astype("uint8").tobytes()
    err = io.StringIO()
    with contextlib.redirect_stderr(err):
        demoted = refine_clipping_batch(seqs, cons, cposes,
                                        skip_dels=skip_dels,
                                        device=device)
    assert demoted == 0
    err2 = io.StringIO()
    with contextlib.redirect_stderr(err2):
        for c, cp in zip(clones, cposes):
            c.refine_clipping(cons, cp, skip_dels=skip_dels)
    for s, c in zip(seqs, clones):
        assert (s.clp5, s.clp3) == (c.clp5, c.clp3), s.name
    # same number of no-hit warnings (order may differ)
    assert (err.getvalue().count("Warning")
            == err2.getvalue().count("Warning"))


@pytest.mark.parametrize("skip_dels", [False, True])
def test_refine_clipping_batch_mesh_sharded(skip_dels):
    """The device phase program with the member axis sharded over the
    virtual 8-device mesh: bit-exact with the host batch pass (pure
    data parallelism — no collective, so exactness is structural)."""
    import jax

    from pwasm_tpu.align.gapseq import refine_clipping_batch
    from pwasm_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= 8
    mesh = make_mesh(8)
    rng = np.random.default_rng(31)
    seqs, clones, cposes = [], [], []
    for k in range(20):   # deliberately NOT a multiple of the mesh size
        s = _random_gapseq(rng, with_dels=skip_dels)
        seqs.append(s)
        clones.append(_clone(s))
        cposes.append(int(rng.integers(0, 5)))
    glen_max = max(s.seqlen + s.numgaps for s in seqs)
    cons = rng.choice(list(b"ACGT*"), glen_max + 8).astype("uint8").tobytes()
    with contextlib.redirect_stderr(io.StringIO()):
        assert refine_clipping_batch(seqs, cons, cposes,
                                     skip_dels=skip_dels, device=True,
                                     mesh=mesh) == 0
        refine_clipping_batch(clones, cons, cposes, skip_dels=skip_dels)
    for s, c in zip(seqs, clones):
        assert (s.clp5, s.clp3) == (c.clp5, c.clp3), s.name


def test_refine_clipping_batch_256_member_speedup():
    """One 2-D pass over a 256-member ~1.5 kb pileup must beat the
    member-by-member loop (measured; VERDICT r2 next #10)."""
    from pwasm_tpu.align.gapseq import refine_clipping_batch

    rng = np.random.default_rng(7)
    m = 1500
    base = rng.choice(list(b"ACGT"), m).astype(np.uint8)
    seqs, clones = [], []
    for k in range(256):
        arr = base.copy()
        idx = rng.integers(0, m, 40)
        arr[idx] = rng.choice(list(b"ACGT"), 40)
        s = GapSeq(f"r{k}", "", bytes(arr))
        s.clp5 = int(rng.integers(1, 30))
        s.clp3 = int(rng.integers(1, 30))
        for _ in range(4):
            s.set_gap(int(rng.integers(0, m)), 1)
        seqs.append(s)
        clones.append(_clone(s))
    cons = bytes(base)
    t0 = time.perf_counter()
    refine_clipping_batch(seqs, cons, [0] * 256)
    dt_batch = time.perf_counter() - t0
    t0 = time.perf_counter()
    for c in clones:
        c.refine_clipping(cons, 0)
    dt_loop = time.perf_counter() - t0
    for s, c in zip(seqs, clones):
        assert (s.clp5, s.clp3) == (c.clp5, c.clp3)
    # the batch must at least keep pace; typically it is ~2-4x faster
    assert dt_batch < dt_loop, (dt_batch, dt_loop)
