"""Content-addressed result cache (ISSUE 15).

Acceptance contracts:

- **byte parity**: a cache hit's served output files are
  byte-identical to a cache-off run of the same inputs+flags, on
  every tier (cold CLI, serve daemon, fleet router);
- **canonicalization**: a cosmetic argv reorder, a different output
  path, or a byte-neutral knob (``--device``/``--batch``) still HITS;
  anything result-affecting (``--band``, ``-c``, mode flags, input or
  ref content) keys a distinct entry; anything the table cannot vouch
  for (unknown flags, ``--resume``/``--follow``/``--inject-faults``)
  BYPASSES;
- **integrity**: CRC rot is a miss (and drops the entry) — a corrupt
  byte is served exactly never; a kill -9 mid-insert leaves orphan
  blobs the startup sweep removes, never a servable half-entry;
- **zero pipeline involvement on a daemon hit**: the job lands
  terminal at admission — no queue, no lease, no probe
  (``backend.probes == 0``) — and the journal carries a ``cache_hit``
  record so replay accounting stays truthful;
- **m2m section granularity**: a ``--many2many`` job re-scoring
  cached CDS + new ones dispatches only the new ones and its report
  is byte-identical to the all-miss run;
- **eviction**: LRU under ``--result-cache-max-bytes``, TTL expiry,
  and the unified byte ledger tracking disk truth.
"""

import hashlib
import io
import json
import os
import shutil
import tempfile
import threading
import time
from contextlib import contextmanager
from types import SimpleNamespace

import numpy as np
import pytest

from pwasm_tpu.cli import _parse_args, run
from pwasm_tpu.core.fasta import write_fasta
from pwasm_tpu.fleet.router import Router
from pwasm_tpu.service.cache import (ByteLedger, CacheStore, classify,
                                     classify_argv, derive_key,
                                     digest_file, fasta_digest,
                                     record_digest, section_key,
                                     serve_outputs)
from pwasm_tpu.service.client import ServiceClient, wait_for_socket
from pwasm_tpu.service.daemon import Daemon

from helpers import make_paf_line

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _corpus(tmp_path, n=24, qlen=120, seed=3):
    rng = np.random.default_rng(seed)
    q = "".join("ACGT"[i] for i in rng.integers(0, 4, qlen))
    lines = []
    for i in range(n):
        cut = 10 + int(rng.integers(0, qlen - 40))
        qb = q[cut]
        tb = "ACGT"[("ACGT".index(qb) + 1) % 4]
        ops = [("=", cut), ("*", tb, qb), ("=", 20), ("ins", "gg"),
               ("=", qlen - cut - 21)]
        lines.append(make_paf_line("q", q, f"asm{i}", "+", ops)[0])
    fa = tmp_path / "q.fa"
    write_fasta(str(fa), [("q", q.encode())])
    paf = tmp_path / "in.paf"
    paf.write_text("".join(ln + "\n" for ln in lines))
    return str(paf), str(fa)


def _key_of(argv):
    cls = classify_argv(argv)
    assert cls is not None, argv
    key = derive_key(cls)
    assert key is not None, argv
    return key


@contextmanager
def _daemon(**kw):
    sockdir = tempfile.mkdtemp(prefix="pwcache")
    sock = os.path.join(sockdir,
                        os.path.basename(sockdir) + ".sock")
    err = io.StringIO()
    dm = Daemon(sock, stderr=err, **kw)
    rcbox: list = []
    t = threading.Thread(target=lambda: rcbox.append(dm.serve()),
                         daemon=True)
    t.start()
    assert wait_for_socket(sock, 15), err.getvalue()
    try:
        yield SimpleNamespace(daemon=dm, sock=sock, rc=rcbox,
                              err=err, thread=t, dir=sockdir)
    finally:
        if not dm.drain.requested:
            dm.drain.request("test teardown")
        t.join(20)
        shutil.rmtree(sockdir, ignore_errors=True)


def _submit_wait(sock, argv, timeout=120):
    with ServiceClient(sock) as c:
        sub = c.submit(argv)
        assert sub.get("ok"), sub
        res = c.result(sub["job_id"], timeout=timeout)
    assert res.get("ok"), res
    return res


# ---------------------------------------------------------------------------
# key derivation / canonicalization matrix
# ---------------------------------------------------------------------------
def test_flag_canonicalization_matrix(tmp_path):
    """The documented table, exercised as a matrix: cosmetic
    differences hit, result-affecting differences miss, uncacheable
    semantics bypass."""
    paf, fa = _corpus(tmp_path)
    base = [paf, "-r", fa, "-o", str(tmp_path / "a.dfa")]
    k0 = _key_of(base)
    # cosmetic: argv reorder, a different -o path, joined-value forms
    assert _key_of(["-r", fa, "-o", str(tmp_path / "b.dfa"),
                    paf]) == k0
    assert _key_of([f"-o{tmp_path / 'c.dfa'}", paf,
                    f"-r{fa}"]) == k0
    # byte-neutral knobs (parity-gated across the repo): still hit
    assert _key_of(base + ["--device=tpu", "--batch=16"]) == k0
    assert _key_of(base + ["--max-retries=5", "--fallback=fail",
                           "--recover=off", "-v", "-D",
                           f"--stats={tmp_path / 's.json'}"]) == k0
    # result-affecting: each keys a DISTINCT entry
    distinct = {k0}
    for extra in (["-G"], ["-F"], ["-C"], ["-N"], ["-c", "30"],
                  ["--band=32"], ["--skip-bad-lines"],
                  ["--realign", "-w", str(tmp_path / "m.mfa")]):
        k = _key_of(base + extra)
        assert k not in distinct, extra
        distinct.add(k)
    # the output KIND set is keyed (paths are not)
    ks = _key_of(base + ["-s", str(tmp_path / "x.sum")])
    assert ks != k0
    assert _key_of(["-s", str(tmp_path / "y.sum")] + base) == ks


def test_bypass_semantics(tmp_path):
    """--resume/--follow/--inject-faults, unknown flags, stdin input,
    and a stdout report all refuse to key (classify → None): unknown
    means 'cannot vouch for byte identity'."""
    paf, fa = _corpus(tmp_path)
    out = str(tmp_path / "a.dfa")
    base = [paf, "-r", fa, "-o", out]
    for argv in (base + ["--resume"], base + ["--follow"],
                 base + ["--inject-faults=seed=1,rate=1,kinds=hang"],
                 base + ["--totally-unknown-flag=1"],
                 ["-", "-r", fa, "-o", out],
                 [paf, "-r", fa]):
        opts, pos = _parse_args(list(argv))
        assert classify(opts, pos) is None, argv


def test_ref_fasta_digest_is_canonical(tmp_path):
    """Line wrapping and sequence case are cosmetic; sequence content
    and record names are not."""
    a = tmp_path / "a.fa"
    b = tmp_path / "b.fa"
    a.write_text(">q descr\nACGTACGTACGT\n")
    b.write_text(">q descr\nacgt\nACGTA\nCGT\n\n")
    assert fasta_digest(str(a)) == fasta_digest(str(b))
    b.write_text(">q descr\nACGTACGTACGA\n")
    assert fasta_digest(str(a)) != fasta_digest(str(b))
    b.write_text(">q2 descr\nACGTACGTACGT\n")
    assert fasta_digest(str(a)) != fasta_digest(str(b))


def test_input_change_misses(tmp_path):
    paf, fa = _corpus(tmp_path)
    argv = [paf, "-r", fa, "-o", str(tmp_path / "a.dfa")]
    k0 = _key_of(argv)
    with open(paf, "a") as f:
        f.write("# a comment line changes the input digest\n")
    assert _key_of(argv) != k0


# ---------------------------------------------------------------------------
# the store: CRC, orphans, eviction, ledger
# ---------------------------------------------------------------------------
def test_store_roundtrip_and_crc_rot(tmp_path):
    store = CacheStore(str(tmp_path / "cd"))
    key = "k" * 64
    assert store.insert(key, {"o": b"report bytes", "s": b"sum"})
    manifest, blobs = store.get(key)
    assert blobs == {"o": b"report bytes", "s": b"sum"}
    assert store.contains(key)
    # rot one blob: the next get is a MISS (never a corrupt serve)
    # and the entry is dropped whole
    with open(tmp_path / "cd" / (key + ".o"), "r+b") as f:
        f.write(b"X")
    assert store.get(key) is None
    assert not store.contains(key)
    assert not os.path.exists(tmp_path / "cd" / (key + ".json"))
    st = store.stats_dict()
    assert st["hits"] == 1 and st["misses"] == 1


def test_store_manifest_rot_is_a_miss(tmp_path):
    store = CacheStore(str(tmp_path / "cd"))
    key = "m" * 64
    store.insert(key, {"o": b"x" * 100})
    mpath = tmp_path / "cd" / (key + ".json")
    obj = json.loads(mpath.read_text())
    obj["bytes"] = 999999        # payload no longer matches its CRC
    mpath.write_text(json.dumps(obj))
    assert store.get(key) is None


def test_kill9_mid_insert_leaves_consistent_cache(tmp_path):
    """The manifest is the COMMIT POINT: blobs without one (the
    kill -9 window) are orphans the next store's sweep removes ONCE
    they age past the grace window (a YOUNG orphan may be a shared-dir
    sibling's in-flight insert and must survive); a manifest whose
    blob vanished is dropped lazily at get time."""
    from pwasm_tpu.service.cache import SWEEP_GRACE_S
    root = tmp_path / "cd"
    store = CacheStore(str(root))
    store.insert("a" * 64, {"o": b"whole entry"})
    # simulate the crash window: blobs landed, manifest did not
    (root / ("b" * 64 + ".o")).write_bytes(b"orphan blob")
    # and the inverse defect: manifest whose blob is gone
    store.insert("c" * 64, {"o": b"doomed"})
    os.unlink(root / ("c" * 64 + ".o"))
    # a FRESH orphan survives the sweep (in-flight-insert protection)
    young = CacheStore(str(root))
    assert os.path.exists(root / ("b" * 64 + ".o"))
    assert young.get("a" * 64) is not None
    # aged past the grace window, the next sweep reaps it
    old = time.time() - SWEEP_GRACE_S - 60
    os.utime(root / ("b" * 64 + ".o"), (old, old))
    store2 = CacheStore(str(root))     # restart = sweep
    assert store2.get("a" * 64) is not None
    assert not os.path.exists(root / ("b" * 64 + ".o"))
    assert store2.get("c" * 64) is None    # lazy drop at get
    assert not os.path.exists(root / ("c" * 64 + ".json"))
    # ledger truth: bytes == what is actually on disk
    disk = sum(os.path.getsize(root / n) for n in os.listdir(root))
    assert store2.stats_dict()["bytes"] == disk


def test_lru_eviction_under_max_bytes(tmp_path):
    store = CacheStore(str(tmp_path / "cd"), max_bytes=250)
    store.insert("a" * 64, {"o": b"x" * 100})
    time.sleep(0.02)
    store.insert("b" * 64, {"o": b"y" * 100})
    time.sleep(0.02)
    assert store.get("a" * 64) is not None   # refresh a's LRU clock
    time.sleep(0.02)
    store.insert("c" * 64, {"o": b"z" * 100})   # budget forces one out
    assert store.get("b" * 64) is None       # b was least-recent
    assert store.get("a" * 64) is not None
    assert store.get("c" * 64) is not None
    assert store.stats_dict()["evictions"] >= 1


def test_ttl_expiry(tmp_path):
    store = CacheStore(str(tmp_path / "cd"), ttl_s=0.05)
    store.insert("t" * 64, {"o": b"short-lived"})
    assert store.get("t" * 64) is not None
    time.sleep(0.08)
    assert store.get("t" * 64) is None
    assert store.stats_dict()["evictions"] >= 1


def test_byte_ledger_accounts():
    led = ByteLedger()
    led.add("spool", 100)
    led.add("cache", 40)
    led.sub("spool", 30)
    assert led.value("spool") == 70 and led.value("cache") == 40
    led.sub("cache", 1000)          # floors at 0, never negative
    assert led.value("cache") == 0


# ---------------------------------------------------------------------------
# mmap/block-scan ingest (ROADMAP item 5 satellite)
# ---------------------------------------------------------------------------
def test_block_line_reader_matches_text_read(tmp_path):
    from pwasm_tpu.stream.pafstream import BlockLineReader
    cases = ["a\tb\nc\td\n", "one\ntwo", "crlf\r\nlone\rend\r\n",
             "", "x" * 3000 + "\n" + "y" * 10, "\n\n\n",
             # multi-byte UTF-8 characters placed to STRADDLE the
             # 7-byte block boundary: the incremental decoder must
             # reassemble them, byte-identical to the text-mode read
             "abcdé\tñ\nrecord\tcafé\n", "é" * 40 + "\n"]
    for i, text in enumerate(cases):
        p = tmp_path / f"c{i}.txt"
        p.write_bytes(text.encode())
        with open(p) as f:
            expect = list(f)
        h = hashlib.sha256()
        r = BlockLineReader(str(p), block_bytes=7, hasher=h)
        got = list(r)
        assert got == expect, (text, got, expect)
        assert r.consumed
        assert r.hexdigest() == hashlib.sha256(
            text.encode()).hexdigest()
        r.close()


def test_mmap_ingest_byte_parity(tmp_path, monkeypatch):
    """The block-scan ingest path produces byte-identical outputs to
    the text-mode readline path (the A/B hatch)."""
    paf, fa = _corpus(tmp_path, n=40)
    outs = {}
    for hatch in ("1", "0"):
        monkeypatch.setenv("PWASM_MMAP_INGEST", hatch)
        out = str(tmp_path / f"h{hatch}.dfa")
        sm = str(tmp_path / f"h{hatch}.sum")
        err = io.StringIO()
        rc = run([paf, "-r", fa, "-o", out, "-s", sm], stderr=err)
        assert rc == 0, err.getvalue()
        outs[hatch] = (open(out, "rb").read(), open(sm, "rb").read())
    assert outs["1"] == outs["0"]


# ---------------------------------------------------------------------------
# cold CLI tier
# ---------------------------------------------------------------------------
def test_cli_hit_parity_and_stats(tmp_path):
    paf, fa = _corpus(tmp_path)
    cd = str(tmp_path / "cd")

    def args(tag, shuffle=False):
        o = [str(tmp_path / f"{tag}.dfa"), str(tmp_path / f"{tag}.sum"),
             str(tmp_path / f"{tag}.json")]
        if shuffle:
            return ["-r", fa, "-s", o[1], paf, "-o", o[0],
                    f"--result-cache={cd}", f"--stats={o[2]}"], o
        return [paf, "-r", fa, "-o", o[0], "-s", o[1],
                f"--result-cache={cd}", f"--stats={o[2]}"], o

    argv, o1 = args("cold")
    err = io.StringIO()
    assert run(argv, stderr=err) == 0, err.getvalue()
    st1 = json.load(open(o1[2]))
    assert "cache_hit" not in st1
    argv, o2 = args("hit", shuffle=True)
    err = io.StringIO()
    assert run(argv, stderr=err) == 0, err.getvalue()
    assert open(o1[0], "rb").read() == open(o2[0], "rb").read()
    assert open(o1[1], "rb").read() == open(o2[1], "rb").read()
    st2 = json.load(open(o2[2]))
    assert st2["cache_hit"] is True
    assert st2["backend"] == {"probes": 0, "warm_hits": 0}
    # cache-off arm: the ground truth the hit must match
    off = str(tmp_path / "off.dfa")
    offsum = str(tmp_path / "off.sum")
    assert run([paf, "-r", fa, "-o", off, "-s", offsum],
               stderr=io.StringIO()) == 0
    assert open(off, "rb").read() == open(o2[0], "rb").read()
    assert open(offsum, "rb").read() == open(o2[1], "rb").read()


def test_cli_rot_falls_back_to_real_run(tmp_path):
    """A rotted entry is never served: the run happens for real and
    REPLACES the entry."""
    paf, fa = _corpus(tmp_path)
    cd = tmp_path / "cd"
    argv = [paf, "-r", fa, "-o", str(tmp_path / "a.dfa"),
            f"--result-cache={cd}"]
    assert run(list(argv), stderr=io.StringIO()) == 0
    good = open(tmp_path / "a.dfa", "rb").read()
    blob = next(p for p in os.listdir(cd) if p.endswith(".o"))
    with open(cd / blob, "r+b") as f:
        f.write(b"\x00\x00")
    argv2 = [paf, "-r", fa, "-o", str(tmp_path / "b.dfa"),
             f"--result-cache={cd}"]
    assert run(argv2, stderr=io.StringIO()) == 0
    assert open(tmp_path / "b.dfa", "rb").read() == good
    # the real run re-populated a CLEAN entry
    store = CacheStore(str(cd))
    assert store.get(_key_of(argv)) is not None


# ---------------------------------------------------------------------------
# serve-daemon tier
# ---------------------------------------------------------------------------
def test_serve_admission_hit_zero_pipeline(tmp_path):
    """The daemon tier: job 1 misses and inserts; job 2 (reordered
    argv, different outputs) is answered AT ADMISSION — done state,
    zero probes, no second lease grant, a cache_hit journal record."""
    paf, fa = _corpus(tmp_path)
    cd = str(tmp_path / "cd")
    with _daemon(result_cache=cd) as h:
        a1 = [paf, "-r", fa, "-o", str(tmp_path / "j1.dfa"),
              f"--stats={tmp_path / 'j1.json'}"]
        r1 = _submit_wait(h.sock, a1)
        assert r1.get("rc") == 0, r1
        grants_after_miss = h.daemon.leases.grants
        a2 = ["-r", fa, str(paf), f"--stats={tmp_path / 'j2.json'}",
              "-o", str(tmp_path / "j2.dfa")]
        t0 = time.perf_counter()
        r2 = _submit_wait(h.sock, a2)
        hit_wall = time.perf_counter() - t0
        assert r2.get("rc") == 0, r2
        assert "result cache" in r2["job"]["detail"]
        assert open(tmp_path / "j1.dfa", "rb").read() \
            == open(tmp_path / "j2.dfa", "rb").read()
        st2 = json.load(open(tmp_path / "j2.json"))
        assert st2["cache_hit"] is True
        assert st2["backend"]["probes"] == 0
        # zero device/lease/queue involvement: no new lease grant
        assert h.daemon.leases.grants == grants_after_miss
        assert hit_wall < 1.0       # sanity, not the gated timing
        with ServiceClient(h.sock) as c:
            st = c.stats()["stats"]
        assert st["cache"]["hits"] == 1
        assert st["cache"]["misses"] == 1
        assert st["cache"]["insertions"] == 1
        # the journal carries the truth: admit + cache_hit + finish,
        # and NO start record for the hit job
        jtext = open(h.sock + ".journal").read()
        rows = [json.loads(l) for l in jtext.splitlines()]
        hit_recs = [r for r in rows if r.get("job_id") == "job-0002"]
        kinds = [r["rec"] for r in hit_recs]
        assert kinds == ["admit", "cache_hit", "finish"], kinds
        h.daemon.drain.request("done")
    assert h.rc == [75]


def test_serve_hit_survives_restart(tmp_path):
    """The cache outlives the daemon: a fresh daemon on the same dir
    serves a hit for a job a DEAD predecessor answered."""
    paf, fa = _corpus(tmp_path)
    cd = str(tmp_path / "cd")
    argv = [paf, "-r", fa, "-o", str(tmp_path / "p.dfa")]
    with _daemon(result_cache=cd) as h:
        assert _submit_wait(h.sock, argv).get("rc") == 0
        h.daemon.drain.request("cycle")
    with _daemon(result_cache=cd) as h2:
        a2 = [paf, "-r", fa, "-o", str(tmp_path / "q.dfa"),
              f"--stats={tmp_path / 'q.json'}"]
        r = _submit_wait(h2.sock, a2)
        assert r.get("rc") == 0
        assert json.load(open(tmp_path / "q.json"))["cache_hit"] \
            is True
    assert open(tmp_path / "p.dfa", "rb").read() \
        == open(tmp_path / "q.dfa", "rb").read()


def test_serve_eviction_under_budget(tmp_path):
    """--result-cache-max-bytes: distinct jobs (same input, a
    result-affecting flag apart) overflow a 1-byte budget and LRU
    eviction runs; svc-stats counts it."""
    paf, fa = _corpus(tmp_path)
    with _daemon(result_cache=str(tmp_path / "cd"),
                 result_cache_max_bytes=1) as h:
        for i, extra in enumerate(([], ["-c", "30"])):
            r = _submit_wait(h.sock, [
                paf, "-r", fa,
                "-o", str(tmp_path / f"e{i}.dfa")] + extra)
            assert r.get("rc") == 0
        with ServiceClient(h.sock) as c:
            st = c.stats()["stats"]["cache"]
        assert st["insertions"] == 2
        assert st["evictions"] >= 1


def test_cache_probe_verb(tmp_path):
    paf, fa = _corpus(tmp_path)
    cd = str(tmp_path / "cd")
    argv = [paf, "-r", fa, "-o", str(tmp_path / "a.dfa")]
    with _daemon(result_cache=cd) as h:
        assert _submit_wait(h.sock, argv).get("rc") == 0
        with ServiceClient(h.sock) as c:
            hitp = c.cache_probe(_key_of(argv))
            missp = c.cache_probe("0" * 64)
            badp = c.cache_probe("")
        assert hitp.get("hit") is True and hitp.get("enabled")
        assert missp.get("hit") is False
        assert badp.get("error") == "bad_request"
    with _daemon() as h2:      # caching off: enabled=False, never hit
        with ServiceClient(h2.sock) as c:
            p = c.cache_probe("0" * 64)
        assert p.get("enabled") is False and p.get("hit") is False


# ---------------------------------------------------------------------------
# many2many: per-CDS section granularity
# ---------------------------------------------------------------------------
def _m2m_files(tmp_path, nq=4, nt=6, seed=7):
    rng = np.random.default_rng(seed)

    def seq(n):
        return "".join("ACGT"[i] for i in rng.integers(0, 4, n))

    qs = [(f"cds{k}", seq(120 + 10 * k)) for k in range(nq)]
    ts = [(f"asm{k}", seq(200 + 13 * k)) for k in range(nt)]
    tfa = tmp_path / "targets.fa"
    tfa.write_text("".join(f">{n}\n{s}\n" for n, s in ts))
    return qs, str(tfa)


def _write_qfa(tmp_path, name, qs):
    p = tmp_path / name
    p.write_text("".join(f">{n}\n{s}\n" for n, s in qs))
    return str(p)


def test_m2m_partial_hit_splices_byte_identical(tmp_path):
    """9-cached-plus-1-new in miniature: 3 cached CDS + 1 new one —
    only the new one is scored (stats count exactly its alignments)
    and the report/summary are byte-identical to the all-miss run."""
    qs, tfa = _m2m_files(tmp_path)
    q3 = _write_qfa(tmp_path, "q3.fa", qs[:3])
    q4 = _write_qfa(tmp_path, "q4.fa", qs)
    cd = str(tmp_path / "m2mcd")
    # the all-miss ground truth, cache off
    ref_o, ref_s = str(tmp_path / "ref.tsv"), str(tmp_path / "ref.sum")
    assert run(["--many2many", tfa, "-r", q4, "-o", ref_o,
                "-s", ref_s], stderr=io.StringIO()) == 0
    # populate 3 sections
    assert run(["--many2many", tfa, "-r", q3,
                "-o", str(tmp_path / "c3.tsv"),
                f"--result-cache={cd}"], stderr=io.StringIO()) == 0
    # the partial-hit run: 1 of 4 dispatched
    st4 = str(tmp_path / "c4.json")
    assert run(["--many2many", tfa, "-r", q4,
                "-o", str(tmp_path / "c4.tsv"),
                "-s", str(tmp_path / "c4.sum"),
                f"--result-cache={cd}", f"--stats={st4}"],
               stderr=io.StringIO()) == 0
    assert open(tmp_path / "c4.tsv", "rb").read() \
        == open(ref_o, "rb").read()
    assert open(tmp_path / "c4.sum", "rb").read() \
        == open(ref_s, "rb").read()
    st = json.load(open(st4))
    assert st["alignments"] == 6     # exactly ONE query x 6 targets
    # an all-hit rerun scores nothing and pays no probe
    st5 = str(tmp_path / "c5.json")
    assert run(["--many2many", tfa, "-r", q4,
                "-o", str(tmp_path / "c5.tsv"),
                f"--result-cache={cd}", f"--stats={st5}",
                "--device=tpu"], stderr=io.StringIO()) == 0
    assert open(tmp_path / "c5.tsv", "rb").read() \
        == open(ref_o, "rb").read()
    st = json.load(open(st5))
    assert st["alignments"] == 0
    assert st["backend"]["probes"] == 0


def test_m2m_band_keys_distinct_sections(tmp_path):
    """--band is result-affecting: sections cached under one band are
    never served to a job under another."""
    qs, tfa = _m2m_files(tmp_path, nq=2)
    q2 = _write_qfa(tmp_path, "q2.fa", qs)
    cd = str(tmp_path / "cd")
    k64 = section_key(record_digest(*qs[0]), "t" * 64, 64)
    k32 = section_key(record_digest(*qs[0]), "t" * 64, 32)
    assert k64 != k32
    # end to end: band=48 run after a band-default populate re-scores
    assert run(["--many2many", tfa, "-r", q2,
                "-o", str(tmp_path / "a.tsv"),
                f"--result-cache={cd}"], stderr=io.StringIO()) == 0
    stj = str(tmp_path / "b.json")
    assert run(["--many2many", tfa, "-r", q2, "--band=48",
                "-o", str(tmp_path / "b.tsv"),
                f"--result-cache={cd}", f"--stats={stj}"],
               stderr=io.StringIO()) == 0
    assert json.load(open(stj))["alignments"] > 0   # re-scored


def test_m2m_served_job_uses_daemon_cache_dir(tmp_path):
    """A served --many2many job inherits `serve --result-cache` via
    the warm context: its sections land in the daemon's dir and a
    later served job partial-hits."""
    qs, tfa = _m2m_files(tmp_path)
    q3 = _write_qfa(tmp_path, "q3.fa", qs[:3])
    q4 = _write_qfa(tmp_path, "q4.fa", qs)
    cd = str(tmp_path / "cd")
    with _daemon(result_cache=cd) as h:
        r = _submit_wait(h.sock, ["--many2many", tfa, "-r", q3,
                                  "-o", str(tmp_path / "s3.tsv")])
        assert r.get("rc") == 0, r
        stj = str(tmp_path / "s4.json")
        r = _submit_wait(h.sock, ["--many2many", tfa, "-r", q4,
                                  "-o", str(tmp_path / "s4.tsv"),
                                  f"--stats={stj}"])
        assert r.get("rc") == 0, r
        assert json.load(open(stj))["alignments"] == 6
    # ground truth parity
    ref = str(tmp_path / "ref.tsv")
    assert run(["--many2many", tfa, "-r", q4, "-o", ref],
               stderr=io.StringIO()) == 0
    assert open(tmp_path / "s4.tsv", "rb").read() \
        == open(ref, "rb").read()


# ---------------------------------------------------------------------------
# fleet tier
# ---------------------------------------------------------------------------
@contextmanager
def _fleet(tmp_path, n=2, daemon_kw=None, router_kw=None):
    stack, members = [], []
    try:
        for _k in range(n):
            cm = _daemon(**(daemon_kw or {}))
            stack.append(cm)
            members.append(cm.__enter__())
        rdir = tempfile.mkdtemp(prefix="pwrt")
        rsock = os.path.join(rdir, "router.sock")
        err = io.StringIO()
        r = Router([m.sock for m in members], socket_path=rsock,
                   stderr=err, poll_interval=0.1,
                   **(router_kw or {}))
        rcbox: list = []
        t = threading.Thread(target=lambda: rcbox.append(r.serve()),
                             daemon=True)
        t.start()
        assert wait_for_socket(rsock, 15), err.getvalue()
        try:
            yield SimpleNamespace(router=r, sock=rsock,
                                  members=members, err=err, rc=rcbox)
        finally:
            if not r.drain.requested:
                r.drain.request("test teardown")
            t.join(20)
            shutil.rmtree(rdir, ignore_errors=True)
    finally:
        for cm in reversed(stack):
            cm.__exit__(None, None, None)


def test_router_shared_dir_hit_never_reaches_a_member(tmp_path):
    """The fleet contract: members + router share one cache dir; a
    repeat submit is answered AT THE ROUTER — no member sees it."""
    paf, fa = _corpus(tmp_path)
    shared = str(tmp_path / "shared")
    with _fleet(tmp_path, n=2,
                daemon_kw={"result_cache": shared},
                router_kw={"result_cache": shared}) as f:
        a1 = [paf, "-r", fa, "-o", str(tmp_path / "f1.dfa")]
        with ServiceClient(f.sock) as c:
            s1 = c.submit(a1)
            assert s1.get("ok"), s1
            r1 = c.result(s1["job_id"], timeout=120)
        assert r1.get("rc") == 0, r1
        a2 = ["-r", fa, paf, "-o", str(tmp_path / "f2.dfa"),
              f"--stats={tmp_path / 'f2.json'}"]
        with ServiceClient(f.sock) as c:
            s2 = c.submit(a2)
            assert s2.get("ok"), s2
            r2 = c.result(s2["job_id"], timeout=120)
        assert r2.get("rc") == 0, r2
        assert s2.get("member") == "cache"
        assert s2.get("cache_hit") is True
        assert r2["job"]["state"] == "done"
        assert json.load(open(
            tmp_path / "f2.json"))["cache_hit"] is True
        assert open(tmp_path / "f1.dfa", "rb").read() \
            == open(tmp_path / "f2.dfa", "rb").read()
        # exactly ONE member ever ran a job
        ran = sum(m.daemon.stats.jobs_accepted for m in f.members)
        assert ran == 1
        with ServiceClient(f.sock) as c:
            fs = c.stats()["stats"]
        assert fs["cache"]["hits"] == 1


def test_router_cache_affinity_places_on_hitting_member(tmp_path):
    """Members with PRIVATE caches: the router (own empty dir) misses
    but probes members with the key — the member that already
    answered the job gets its repeat, whose admission serves it."""
    paf, fa = _corpus(tmp_path)
    # per-member PRIVATE dirs need distinct kwargs — build manually
    stack, members = [], []
    try:
        for k in range(2):
            cm = _daemon(result_cache=str(tmp_path / f"m{k}cd"))
            stack.append(cm)
            members.append(cm.__enter__())
        rdir = tempfile.mkdtemp(prefix="pwrt")
        rsock = os.path.join(rdir, "router.sock")
        err = io.StringIO()
        r = Router([m.sock for m in members], socket_path=rsock,
                   stderr=err, poll_interval=0.1,
                   result_cache=str(tmp_path / "router-cd2"))
        rcbox: list = []
        t = threading.Thread(target=lambda: rcbox.append(r.serve()),
                             daemon=True)
        t.start()
        assert wait_for_socket(rsock, 15), err.getvalue()
        try:
            a1 = [paf, "-r", fa, "-o", str(tmp_path / "g1.dfa")]
            with ServiceClient(rsock) as c:
                s1 = c.submit(a1)
                assert s1.get("ok"), s1
                r1 = c.result(s1["job_id"], timeout=120)
            assert r1.get("rc") == 0, r1
            first_member = s1["member"]
            a2 = [paf, "-r", fa, "-o", str(tmp_path / "g2.dfa"),
                  f"--stats={tmp_path / 'g2.json'}"]
            with ServiceClient(rsock) as c:
                s2 = c.submit(a2)
                assert s2.get("ok"), s2
                r2 = c.result(s2["job_id"], timeout=120)
            assert r2.get("rc") == 0, r2
            # affinity: the repeat landed on the SAME member, and that
            # member answered it from its private cache
            assert s2["member"] == first_member, (s1, s2)
            assert json.load(open(
                tmp_path / "g2.json"))["cache_hit"] is True
            assert open(tmp_path / "g1.dfa", "rb").read() \
                == open(tmp_path / "g2.dfa", "rb").read()
        finally:
            if not r.drain.requested:
                r.drain.request("test teardown")
            t.join(20)
            shutil.rmtree(rdir, ignore_errors=True)
    finally:
        for cm in reversed(stack):
            cm.__exit__(None, None, None)


# ---------------------------------------------------------------------------
# obs: cache_thrash rule + top pane
# ---------------------------------------------------------------------------
def test_cache_thrash_rule_fires_on_sustained_thrash():
    from pwasm_tpu.obs.catalog import (build_cache_metrics,
                                       build_slo_metrics,
                                       default_slo_rules)
    from pwasm_tpu.obs.metrics import MetricsRegistry
    from pwasm_tpu.obs.slo import SloEngine
    rules = [r for r in default_slo_rules()
             if r["name"] == "cache_thrash"]
    assert rules, "cache_thrash must ship in the default set"
    reg = MetricsRegistry()
    cm = build_cache_metrics(reg)
    sm = build_slo_metrics(reg)
    eng = SloEngine(reg, rules, metrics=sm)
    t0 = 1000.0
    # healthy: lots of insertions, few evictions
    cm["insertions"].inc(100)
    cm["evictions"].inc(10)
    eng.evaluate(now=t0)
    eng.evaluate(now=t0 + 20)
    assert eng.verdict()["verdict"] == "ok"
    # thrash: eviction keeps pace with insertion, held past for_s
    cm["evictions"].inc(85)
    eng.evaluate(now=t0 + 30)          # pending (for_s hold)
    assert eng.verdict()["verdict"] == "ok"
    eng.evaluate(now=t0 + 45)          # held > 10s: fires degraded
    v = eng.verdict()
    assert v["verdict"] == "degraded"
    assert v["firing"][0]["rule"] == "cache_thrash"


def test_top_renders_cache_row():
    from pwasm_tpu.service.top import render
    st = {"uptime_s": 5.0, "jobs": {},
          "cache": {"enabled": True, "hits": 7, "misses": 3,
                    "hit_ratio": 0.7, "insertions": 3,
                    "evictions": 1, "bytes": 12345}}
    out = render(st)
    assert "CACHE: 7 hits / 3 misses (ratio 70%)" in out
    assert "12345 bytes" in out
    # cache off: no row, still a total render
    out = render({"uptime_s": 1.0, "cache": {"enabled": False}})
    assert "CACHE:" not in out


# ---------------------------------------------------------------------------
# incremental compute: per-record delta serving (ISSUE 17)
# ---------------------------------------------------------------------------
def _grown_corpus(tmp_path, n=30, n_prefix=27, qlen=120, seed=3):
    """One corpus, two files: the first ``n_prefix`` lines and the
    whole thing — byte-identical in the shared prefix, so the full
    file is exactly 'the cached input, appended to'."""
    rng = np.random.default_rng(seed)
    q = "".join("ACGT"[i] for i in rng.integers(0, 4, qlen))
    lines = []
    for i in range(n):
        cut = 10 + int(rng.integers(0, qlen - 40))
        qb = q[cut]
        tb = "ACGT"[("ACGT".index(qb) + 1) % 4]
        ops = [("=", cut), ("*", tb, qb), ("=", 20), ("ins", "gg"),
               ("=", qlen - cut - 21)]
        lines.append(make_paf_line("q", q, f"asm{i}", "+", ops)[0])
    fa = tmp_path / "dq.fa"
    write_fasta(str(fa), [("q", q.encode())])
    p1 = tmp_path / "prefix.paf"
    p1.write_text("".join(ln + "\n" for ln in lines[:n_prefix]))
    p2 = tmp_path / "full.paf"
    p2.write_text("".join(ln + "\n" for ln in lines))
    return str(p1), str(p2), str(fa)


def test_cli_appended_delta_parity_and_truthful_stats(tmp_path):
    """Tentpole (a), cold-CLI tier: a grown input exact-misses but
    delta-hits its cached prefix — only the tail is recomputed, the
    report is byte-identical to the cache-off cold run, and --stats
    stays truthful (cache_delta with computed-vs-served counts)."""
    p1, p2, fa = _grown_corpus(tmp_path)
    cd = str(tmp_path / "cd")
    assert run([p1, "-r", fa, "-o", str(tmp_path / "a.dfa"),
                f"--result-cache={cd}"], stderr=io.StringIO()) == 0
    stj = str(tmp_path / "b.json")
    err = io.StringIO()
    assert run([p2, "-r", fa, "-o", str(tmp_path / "b.dfa"),
                f"--result-cache={cd}", f"--stats={stj}"],
               stderr=err) == 0, err.getvalue()
    st = json.load(open(stj))
    assert st["cache_delta"] is True
    # the LAST cached record re-runs (its durable row is the resume
    # cursor's truncation point): 26 of 30 served, 4 computed
    assert st["cache_records_served"] == 26
    assert st["cache_records_total"] == 30
    assert st["resumed_past"] == 26
    assert "cache_hit" not in st        # a delta is not an exact hit
    # ground truth: the cache-off cold run on the full input
    assert run([p2, "-r", fa, "-o", str(tmp_path / "c.dfa")],
               stderr=io.StringIO()) == 0
    assert (tmp_path / "b.dfa").read_bytes() \
        == (tmp_path / "c.dfa").read_bytes()
    # the completed delta run re-populated its own exact entry (with
    # the delta markers STRIPPED): an identical rerun is a plain hit
    stj2 = str(tmp_path / "d.json")
    assert run([p2, "-r", fa, "-o", str(tmp_path / "d.dfa"),
                f"--result-cache={cd}", f"--stats={stj2}"],
               stderr=io.StringIO()) == 0
    st2 = json.load(open(stj2))
    assert st2.get("cache_hit") is True
    assert "cache_delta" not in st2
    assert (tmp_path / "d.dfa").read_bytes() \
        == (tmp_path / "c.dfa").read_bytes()


def test_kill9_mid_delta_insert_sweep_consistency(tmp_path):
    """The ``.dx`` delta index rides the blobs-then-manifest commit
    protocol: every kill -9 window leaves either a whole entry, an
    aged sidecar orphan the sweep reaps, or a rotted index that only
    DISQUALIFIES delta serving (exact hits still work) — never a
    corrupt splice; the byte ledger always matches disk truth."""
    from pwasm_tpu.service.cache import SWEEP_GRACE_S
    root = tmp_path / "cd"
    store = CacheStore(str(root))
    digs = [f"{i:016x}" for i in range(10)]
    dx = "".join(digs).encode("ascii")
    assert store.insert("a" * 64, {"o": b"prefix rows"},
                        delta={"family": "famA", "lines": len(digs),
                               "dx": dx})
    # window 1: sidecar landed, manifest did not (kill -9 between the
    # blob writes and the commit) -> an aged orphan the sweep reaps
    (root / ("b" * 64 + ".dx")).write_bytes(b"orphan index")
    old = time.time() - SWEEP_GRACE_S - 60
    os.utime(root / ("b" * 64 + ".dx"), (old, old))
    store2 = CacheStore(str(root))          # restart = sweep
    assert not os.path.exists(root / ("b" * 64 + ".dx"))
    # the committed entry still delta-serves a grown input
    grown = digs + ["f" * 16]
    hit = store2.delta_lookup("famA", grown)
    assert hit is not None and hit[3] == len(digs)
    # window 2: the index rots -> the candidate is skipped (miss),
    # the exact path is unharmed
    with open(root / ("a" * 64 + ".dx"), "r+b") as f:
        f.write(b"XX")
    store3 = CacheStore(str(root))
    assert store3.delta_lookup("famA", grown) is None
    assert store3.get("a" * 64) is not None
    disk = sum(os.path.getsize(root / n) for n in os.listdir(root))
    assert store3.stats_dict()["bytes"] == disk


def test_serve_admission_delta_rearms_as_resume(tmp_path):
    """Tentpole (c), daemon tier: an appended input exact-misses at
    admission but delta-hits — the daemon writes the cached prefix,
    re-arms the job as ``--resume``, patches its finished stats with
    the truthful delta counts, journals a delta-flavored cache_hit
    record, and moves svc-stats' hit ratio FRACTIONALLY."""
    p1, p2, fa = _grown_corpus(tmp_path)
    cd = str(tmp_path / "cd")
    with _daemon(result_cache=cd) as h:
        r1 = _submit_wait(h.sock, [p1, "-r", fa,
                                   "-o", str(tmp_path / "j1.dfa")])
        assert r1.get("rc") == 0, r1
        r2 = _submit_wait(h.sock, [p2, "-r", fa,
                                   "-o", str(tmp_path / "j2.dfa"),
                                   f"--stats={tmp_path / 'j2.json'}"])
        assert r2.get("rc") == 0, r2
        st = r2.get("stats") or {}
        assert st.get("cache_delta") is True
        assert st["cache_records_served"] == 26
        assert st["cache_records_total"] == 30
        with ServiceClient(h.sock) as c:
            cb = c.stats()["stats"]["cache"]
        assert cb["delta_hits"] == 1
        assert cb["delta_records_served"] == 26
        assert cb["hits"] == 0 and cb["misses"] == 2
        assert abs(cb["hit_ratio"] - (26 / 30) / 2) < 1e-6
        rows = [json.loads(l) for l in
                open(h.sock + ".journal").read().splitlines()]
        drecs = [r for r in rows
                 if r.get("rec") == "cache_hit" and r.get("delta")]
        assert drecs and drecs[0]["served"] == 26 \
            and drecs[0]["total"] == 30
        # crash-replay safety: the ADMIT record keeps the ORIGINAL
        # argv (no --resume) so an unfinished delta job re-runs cold
        admits = [r for r in rows if r.get("rec") == "admit"
                  and r.get("job_id") == drecs[0]["job_id"]]
        assert admits and "--resume" not in admits[0]["argv"]
    # byte parity vs the cache-off cold run
    assert run([p2, "-r", fa, "-o", str(tmp_path / "cold.dfa")],
               stderr=io.StringIO()) == 0
    assert (tmp_path / "j2.dfa").read_bytes() \
        == (tmp_path / "cold.dfa").read_bytes()


def test_m2m_superset_splices_and_scores_only_new_targets(tmp_path):
    """Tentpole (b): a --many2many job whose target set strictly
    CONTAINS a cached section's serves the cached per-target scores
    and dispatches only the delta targets — byte-identical splice,
    honest pair-level stats, band-keyed isolation."""
    rng = np.random.default_rng(17)

    def seq(n):
        return "".join("ACGT"[i] for i in rng.integers(0, 4, n))

    qs = [(f"cds{k}", seq(120 + 10 * k)) for k in range(3)]
    ts = [(f"asm{k}", seq(200 + 13 * k)) for k in range(6)]
    qfa = _write_qfa(tmp_path, "q.fa", qs)
    t3 = tmp_path / "t3.fa"
    t3.write_text("".join(f">{n}\n{s}\n" for n, s in ts[:3]))
    t6 = tmp_path / "t6.fa"
    t6.write_text("".join(f">{n}\n{s}\n" for n, s in ts))
    cd = str(tmp_path / "cd")
    # ground truth: all 6 targets, cache off
    ref = str(tmp_path / "ref.tsv")
    assert run(["--many2many", str(t6), "-r", qfa, "-o", ref],
               stderr=io.StringIO()) == 0
    # populate sections over the 3-target subset
    assert run(["--many2many", str(t3), "-r", qfa,
                "-o", str(tmp_path / "p.tsv"),
                f"--result-cache={cd}"], stderr=io.StringIO()) == 0
    # the superset run: every section exact-misses (different target
    # set) but splices its cached 3 and scores only the 3 new ones
    stj = str(tmp_path / "s.json")
    assert run(["--many2many", str(t6), "-r", qfa,
                "-o", str(tmp_path / "s.tsv"),
                f"--result-cache={cd}", f"--stats={stj}"],
               stderr=io.StringIO()) == 0
    assert (tmp_path / "s.tsv").read_bytes() \
        == open(ref, "rb").read()
    st = json.load(open(stj))
    assert st["alignments"] == 9      # 3 queries x 3 NEW targets
    # repeat superset run: pure section hits, nothing scored
    stj2 = str(tmp_path / "s2.json")
    assert run(["--many2many", str(t6), "-r", qfa,
                "-o", str(tmp_path / "s2.tsv"),
                f"--result-cache={cd}", f"--stats={stj2}",
                "--device=tpu"], stderr=io.StringIO()) == 0
    assert (tmp_path / "s2.tsv").read_bytes() \
        == open(ref, "rb").read()
    st2 = json.load(open(stj2))
    assert st2["alignments"] == 0
    assert st2["backend"]["probes"] == 0
    # band keying: a different band never reuses those rows
    stj3 = str(tmp_path / "s3.json")
    assert run(["--many2many", str(t6), "-r", qfa, "--band=48",
                "-o", str(tmp_path / "s3.tsv"),
                f"--result-cache={cd}", f"--stats={stj3}"],
               stderr=io.StringIO()) == 0
    assert json.load(open(stj3))["alignments"] == 18   # all re-scored


def test_warm_spawn_prefetch_drill(tmp_path):
    """Tentpole (c): a member started with --cache-prefetch over an
    already-populated shared dir warms entries BEFORE its socket
    appears; its first repeat job is an admission hit — zero probes,
    cache hits >= 1 — and svc-stats counts the prefetched entries.
    The scaler injects the flag for cache-armed spawn policies."""
    from pwasm_tpu.fleet.scaler import warm_spawn_args
    assert warm_spawn_args(["--result-cache=/d"]) \
        == ["--result-cache=/d", "--cache-prefetch=64"]
    assert warm_spawn_args(["--result-cache=off"]) \
        == ["--result-cache=off"]
    assert warm_spawn_args(
        ["--result-cache=/d", "--cache-prefetch=8"]) \
        == ["--result-cache=/d", "--cache-prefetch=8"]
    assert warm_spawn_args([]) == []
    paf, fa = _corpus(tmp_path)
    cd = str(tmp_path / "shared")
    with _daemon(result_cache=cd) as h:
        assert _submit_wait(h.sock, [
            paf, "-r", fa,
            "-o", str(tmp_path / "w1.dfa")]).get("rc") == 0
    # the warm-spawned member: prefetch runs before the socket binds
    with _daemon(result_cache=cd, cache_prefetch=8) as h2:
        with ServiceClient(h2.sock) as c:
            cb = c.stats()["stats"]["cache"]
        assert cb["prefetched"] >= 1
        r = _submit_wait(h2.sock, [
            paf, "-r", fa, "-o", str(tmp_path / "w2.dfa"),
            f"--stats={tmp_path / 'w2.json'}"])
        assert r.get("rc") == 0, r
        st = json.load(open(tmp_path / "w2.json"))
        assert st["cache_hit"] is True
        assert st["backend"]["probes"] == 0
        with ServiceClient(h2.sock) as c:
            cb = c.stats()["stats"]["cache"]
        assert cb["hits"] >= 1
        # prefetch happened before serving: the member's stderr says
        # so before its "serving on" line
        log = h2.err.getvalue()
        assert log.index("prefetch") < log.index("serving on")
    assert (tmp_path / "w1.dfa").read_bytes() \
        == (tmp_path / "w2.dfa").read_bytes()


def test_router_family_affinity_places_delta_on_warm_member(tmp_path):
    """Tentpole (c), fleet tier: members with PRIVATE caches — the
    router's cache probe carries the input FAMILY, so an appended
    input (exact miss everywhere) still lands on the member holding
    its prefix, whose admission serves the delta."""
    p1, p2, fa = _grown_corpus(tmp_path)
    stack, members = [], []
    try:
        for k in range(2):
            cm = _daemon(result_cache=str(tmp_path / f"m{k}cd"))
            stack.append(cm)
            members.append(cm.__enter__())
        rdir = tempfile.mkdtemp(prefix="pwrt")
        rsock = os.path.join(rdir, "router.sock")
        err = io.StringIO()
        r = Router([m.sock for m in members], socket_path=rsock,
                   stderr=err, poll_interval=0.1,
                   result_cache=str(tmp_path / "router-cd"))
        rcbox: list = []
        t = threading.Thread(target=lambda: rcbox.append(r.serve()),
                             daemon=True)
        t.start()
        assert wait_for_socket(rsock, 15), err.getvalue()
        try:
            a1 = [p1, "-r", fa, "-o", str(tmp_path / "r1.dfa")]
            with ServiceClient(rsock) as c:
                s1 = c.submit(a1)
                assert s1.get("ok"), s1
                assert c.result(s1["job_id"],
                                timeout=120).get("rc") == 0
            first = s1["member"]
            a2 = [p2, "-r", fa, "-o", str(tmp_path / "r2.dfa"),
                  f"--stats={tmp_path / 'r2.json'}"]
            with ServiceClient(rsock) as c:
                s2 = c.submit(a2)
                assert s2.get("ok"), s2
                r2 = c.result(s2["job_id"], timeout=120)
            assert r2.get("rc") == 0, r2
            # family affinity: the grown job landed on the SAME
            # member, and its admission delta-served the prefix
            assert s2["member"] == first, (s1, s2)
            st = json.load(open(tmp_path / "r2.json"))
            assert st["cache_delta"] is True
            assert st["cache_records_served"] == 26
        finally:
            if not r.drain.requested:
                r.drain.request("test teardown")
            t.join(20)
            shutil.rmtree(rdir, ignore_errors=True)
    finally:
        for cm in reversed(stack):
            cm.__exit__(None, None, None)
    assert run([p2, "-r", fa, "-o", str(tmp_path / "rc.dfa")],
               stderr=io.StringIO()) == 0
    assert (tmp_path / "r2.dfa").read_bytes() \
        == (tmp_path / "rc.dfa").read_bytes()
