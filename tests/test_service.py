"""Warm-pool job service (ISSUE 5).

Acceptance contracts:

- **byte parity**: jobs submitted to one warm ``serve`` process
  produce outputs byte-identical to cold CLI runs of the same argv —
  the service changes wall time and counters, never bytes (incl. the
  200-alignment realistic corpus as 3 consecutive jobs);
- **warm reuse**: jobs after the first pay ZERO backend probes
  (``backend.probes == 0`` with ``backend.warm_hits > 0`` in their
  ``--stats``);
- **shared resilience state**: a flap that opens the global breaker in
  job N leaves it open for job N+1 (inherited, not re-tripped), and a
  reclose re-promotes subsequent jobs;
- **admission control**: a full queue answers ``queue_full`` (the
  protocol's 429 — back off and retry), a draining service answers
  ``draining``;
- **drain**: SIGTERM (or the ``drain`` command) finishes in-flight
  jobs at batch boundaries with valid resumable checkpoints, marks
  queued jobs preempted, rejects new submissions, and exits 75;
- **protocol edges**: malformed JSON frame, oversized frame, cancel of
  queued vs running jobs, client disconnect mid-result.
"""

import io
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from contextlib import contextmanager
from types import SimpleNamespace

import numpy as np
import pytest

from pwasm_tpu.cli import _load_checkpoint, run
from pwasm_tpu.core.errors import EXIT_PREEMPTED
from pwasm_tpu.core.fasta import write_fasta
from pwasm_tpu.resilience.lifecycle import SignalDrain
from pwasm_tpu.service import protocol
from pwasm_tpu.service.client import (ServiceClient, ServiceError,
                                      wait_for_socket)
from pwasm_tpu.service.daemon import Daemon
from pwasm_tpu.service.queue import (Draining, Job, JobQueue,
                                     QueueFull, ServiceStats)

from helpers import make_paf_line

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# a deterministic SLOW job: every supervised device call sleeps 0.25 s
# (injected hang, deadline-less cap) — bytes unchanged, wall stretched,
# so cancel/drain/disconnect tests have a live mid-run window to hit
SLOW = "--inject-faults=seed=1,rate=1,kinds=hang,hang_s=0.25"


def _corpus(tmp_path, n=24, qlen=120, seed=3):
    rng = np.random.default_rng(seed)
    q = "".join("ACGT"[i] for i in rng.integers(0, 4, qlen))
    lines = []
    for i in range(n):
        cut = 10 + int(rng.integers(0, qlen - 40))
        qb = q[cut]
        tb = "ACGT"[("ACGT".index(qb) + 1) % 4]
        ops = [("=", cut), ("*", tb, qb), ("=", 20), ("ins", "gg"),
               ("=", qlen - cut - 21)]
        lines.append(make_paf_line("q", q, f"asm{i}", "+", ops)[0])
    fa = tmp_path / "q.fa"
    write_fasta(str(fa), [("q", q.encode())])
    paf = tmp_path / "in.paf"
    paf.write_text("".join(ln + "\n" for ln in lines))
    return str(paf), str(fa)


def _job_args(tmp_path, tag, paf, fa, extra=()):
    return [paf, "-r", fa, "-o", str(tmp_path / f"{tag}.dfa"),
            "--device=tpu", "--batch=2",
            f"--stats={tmp_path / f'{tag}.json'}"] + list(extra)


def _cold(tmp_path, tag, paf, fa, extra=()):
    err = io.StringIO()
    rc = run(_job_args(tmp_path, tag, paf, fa, extra), stderr=err)
    assert rc == 0, err.getvalue()[:2000]
    return (tmp_path / f"{tag}.dfa").read_bytes()


@contextmanager
def _daemon(**kw):
    """An in-process daemon on a short-lived socket (serve() runs on a
    background thread; SignalDrain.install is a no-op there, so the
    drain is driven via drain.request / the protocol command — the
    same flag the main-thread SIGTERM handler pulls)."""
    sockdir = tempfile.mkdtemp(prefix="pwsvc")
    sock = os.path.join(sockdir, "s")
    err = io.StringIO()
    dm = Daemon(sock, stderr=err, **kw)
    rcbox: list = []
    t = threading.Thread(target=lambda: rcbox.append(dm.serve()),
                         daemon=True)
    t.start()
    assert wait_for_socket(sock, 15), err.getvalue()
    try:
        yield SimpleNamespace(daemon=dm, sock=sock, rc=rcbox, err=err,
                              thread=t)
    finally:
        if not dm.drain.requested:
            dm.drain.request("test teardown")
        t.join(20)
        shutil.rmtree(sockdir, ignore_errors=True)


def _submit_and_wait(sock, argv, timeout=120):
    with ServiceClient(sock) as c:
        sub = c.submit(argv)
        assert sub.get("ok"), sub
        return c.result(sub["job_id"], timeout=timeout)


def _wait_mid_run(client, job_id, ckpt_path, budget_s=60):
    """Block until ``job_id`` is demonstrably MID-RUN: running, with at
    least one durable batch checkpoint on disk — the earliest instant
    a cancel/drain/SIGTERM can prove the 'valid resumable ckpt'
    contract rather than racing the job's warmup."""
    deadline = time.monotonic() + budget_s
    while time.monotonic() < deadline:
        state = client.status(job_id)["job"]["state"]
        if state == "running" and os.path.exists(ckpt_path):
            return True
        if state not in ("queued", "running"):
            return False       # already terminal: the caller decides
        time.sleep(0.02)
    return False


# ---------------------------------------------------------------------------
# queue + protocol units
# ---------------------------------------------------------------------------
def test_job_queue_admission_and_drain_unit():
    q = JobQueue(max_queue=2)
    j1, j2, j3 = (Job(id=f"j{i}", argv=["x"]) for i in (1, 2, 3))
    assert q.submit(j1) == 0
    assert q.submit(j2) == 1
    with pytest.raises(QueueFull):
        q.submit(j3)
    assert q.depth() == 2
    assert q.take(0.01) is j1          # FIFO
    assert q.remove(j2) and not q.remove(j2)
    assert q.submit(j3) == 0
    waiting = q.drain()
    assert waiting == [j3]
    assert q.draining and q.depth() == 0
    with pytest.raises(Draining):
        q.submit(Job(id="j4", argv=["x"]))
    assert q.take(0.01) is None


def test_protocol_frames_roundtrip_and_errors():
    buf = io.BytesIO()
    protocol.write_frame(buf, {"cmd": "ping", "n": 1})
    buf.seek(0)
    assert protocol.read_frame(buf) == {"cmd": "ping", "n": 1}
    assert protocol.read_frame(buf) is None          # clean EOF
    with pytest.raises(protocol.FrameError) as e:
        protocol.read_frame(io.BytesIO(b"not json\n"))
    assert e.value.code == protocol.ERR_BAD_JSON
    assert not e.value.fatal                         # conn survives
    with pytest.raises(protocol.FrameError) as e:
        protocol.read_frame(io.BytesIO(b"[1,2]\n"))
    assert e.value.code == protocol.ERR_BAD_JSON
    big = b"{" + b" " * 64 + b"}\n"
    with pytest.raises(protocol.FrameError) as e:
        protocol.read_frame(io.BytesIO(big), max_bytes=32)
    assert e.value.code == protocol.ERR_FRAME_TOO_LARGE
    assert e.value.fatal                             # stream unsynced
    with pytest.raises(protocol.FrameError):
        protocol.read_frame(io.BytesIO(b'{"x":1}'))  # truncated at EOF


def test_service_stats_rollup_skips_versions_and_bools():
    st = ServiceStats()
    st.rollup_job({"stats_version": 1, "alignments": 3,
                   "preempted": True,
                   "backend": {"probes": 1, "warm_hits": 0}})
    st.rollup_job({"stats_version": 1, "alignments": 2,
                   "preempted": False,
                   "backend": {"probes": 0, "warm_hits": 1}})
    d = st.as_dict()
    assert d["stats_version"] == 1
    assert d["rollup"]["alignments"] == 5
    assert "stats_version" not in d["rollup"]
    assert "preempted" not in d["rollup"]
    assert d["warm"] == {"backend_probes": 1, "backend_warm_hits": 1}


def test_cross_thread_drain_request_only_flags():
    """A drain requested from ANOTHER thread while an interruptible
    phase is armed must only set the flag — raising PreemptedError in
    the requester (the daemon thread) would kill the service instead
    of the job."""
    drain = SignalDrain(stderr=io.StringIO(), hard_exit=lambda c: None)
    raised: list = []

    def other():
        try:
            drain.request("from the daemon thread")
        except BaseException as e:   # pragma: no cover - the bug
            raised.append(e)

    with drain.interrupting():
        t = threading.Thread(target=other)
        t.start()
        t.join(5)
    assert not raised
    assert drain.requested


# ---------------------------------------------------------------------------
# protocol edges against a live daemon
# ---------------------------------------------------------------------------
def _raw_conn(sock_path):
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(10)
    s.connect(sock_path)
    return s


def test_malformed_json_frame_answers_and_connection_survives():
    with _daemon(max_queue=2) as h:
        s = _raw_conn(h.sock)
        try:
            s.sendall(b"this is not json\n")
            f = s.makefile("rb")
            resp = json.loads(f.readline())
            assert resp["ok"] is False
            assert resp["error"] == protocol.ERR_BAD_JSON
            # the SAME connection keeps working: the next line is a
            # fresh frame
            s.sendall(b'{"cmd":"ping"}\n')
            resp = json.loads(f.readline())
            assert resp["ok"] is True
        finally:
            s.close()


def test_oversized_frame_rejected_and_connection_closed():
    with _daemon(max_queue=2, max_frame_bytes=1024) as h:
        s = _raw_conn(h.sock)
        try:
            s.sendall(b'{"pad":"' + b"x" * 4096 + b'"}\n')
            f = s.makefile("rb")
            resp = json.loads(f.readline())
            assert resp["ok"] is False
            assert resp["error"] == protocol.ERR_FRAME_TOO_LARGE
            # oversized = unsynced stream: the daemon closes the
            # connection after answering
            assert f.readline() == b""
        finally:
            s.close()
        # ...but the SERVICE is fine: a fresh connection works
        with ServiceClient(h.sock) as c:
            assert c.ping().get("ok")


def test_unknown_cmd_unknown_job_bad_request():
    with _daemon(max_queue=2) as h:
        with ServiceClient(h.sock) as c:
            r = c.request({"cmd": "frobnicate"})
            assert r["error"] == protocol.ERR_UNKNOWN_CMD
            r = c.status("job-9999")
            assert r["error"] == protocol.ERR_UNKNOWN_JOB
            r = c.request({"cmd": "submit", "args": "not-a-list"})
            assert r["error"] == protocol.ERR_BAD_REQUEST
            r = c.request({"cmd": "submit", "args": []})
            assert r["error"] == protocol.ERR_BAD_REQUEST
            # jobs must write to files: the socket carries control,
            # not report bytes
            r = c.submit(["in.paf", "-r", "q.fa"])
            assert r["error"] == protocol.ERR_BAD_REQUEST
            assert "-o" in r["detail"]
            # nested service commands are refused
            r = c.submit(["serve", "--socket=/x", "-o", "r"])
            assert r["error"] == protocol.ERR_BAD_REQUEST


# ---------------------------------------------------------------------------
# the warm-pool promise: parity + probe reuse + shared breaker
# ---------------------------------------------------------------------------
def test_warm_jobs_byte_identical_and_probe_free(tmp_path):
    paf, fa = _corpus(tmp_path)
    cold = _cold(tmp_path, "cold", paf, fa)
    with _daemon(max_queue=4) as h:
        for j in (1, 2, 3):
            res = _submit_and_wait(
                h.sock, _job_args(tmp_path, f"warm{j}", paf, fa))
            assert res.get("ok") and res["rc"] == 0, res
            assert (tmp_path / f"warm{j}.dfa").read_bytes() == cold
            bk = json.loads(
                (tmp_path / f"warm{j}.json").read_text())["backend"]
            if j > 1:
                # the warm-pool reuse gate: no additional backend
                # probe after the first job initialized the process
                assert bk["probes"] == 0, bk
                assert bk["warm_hits"] > 0, bk
        with ServiceClient(h.sock) as c:
            st = c.stats()["stats"]
        assert st["jobs"]["accepted"] == 3
        assert st["jobs"]["completed"] == 3
        assert st["rollup"]["alignments"] == 72
        assert st["warm"]["backend_warm_hits"] >= 2


def test_relative_paths_resolve_against_client_cwd(tmp_path):
    """The cold-to-warm drop-in contract for relative paths: a cold
    run resolves them against the CALLER's cwd, so a served job must
    too (the client sends its cwd; the daemon rewrites the argv with
    the CLI's own flag grammar — clustered short flags included)."""
    paf, fa = _corpus(tmp_path, n=6)
    cold = _cold(tmp_path, "cold", paf, fa)
    with _daemon(max_queue=4) as h:
        with ServiceClient(h.sock) as c:
            sub = c.submit(["in.paf", "-r", "q.fa", "-Do", "rel.dfa",
                            "--stats=rel.json", "--device=tpu",
                            "--batch=2"], cwd=str(tmp_path))
            assert sub.get("ok"), sub
            res = c.result(sub["job_id"], timeout=120)
            assert res.get("ok") and res["rc"] == 0, res
        assert (tmp_path / "rel.dfa").read_bytes() == cold
        assert (tmp_path / "rel.json").exists()
        # a non-absolute client cwd is a bad request, never a guess
        with ServiceClient(h.sock) as c:
            r = c.request({"cmd": "submit",
                           "args": ["in.paf", "-r", "q.fa", "-o",
                                    "x.dfa"],
                           "cwd": "relative/dir"})
            assert r["error"] == protocol.ERR_BAD_REQUEST


def test_two_concurrent_submitters_byte_identical(tmp_path):
    paf, fa = _corpus(tmp_path)
    cold = _cold(tmp_path, "cold", paf, fa)
    results: dict = {}

    def submitter(tag, sock):
        results[tag] = _submit_and_wait(
            sock, _job_args(tmp_path, tag, paf, fa))

    with _daemon(max_queue=4) as h:
        ts = [threading.Thread(target=submitter, args=(t, h.sock))
              for t in ("ca", "cb")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(120)
    for tag in ("ca", "cb"):
        assert results[tag].get("ok") and results[tag]["rc"] == 0, \
            results[tag]
        assert (tmp_path / f"{tag}.dfa").read_bytes() == cold


def test_breaker_state_inherited_across_jobs(tmp_path, monkeypatch):
    """The shared-resilience contract: job 1's scripted outage opens
    the global breaker and the warm process carries it — job 2 starts
    degraded WITHOUT re-tripping (breaker_trips == 0), and job 3 under
    --recover=auto recloses and re-promotes.  All three byte-identical
    to the cold run."""
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    paf, fa = _corpus(tmp_path)
    cold = _cold(tmp_path, "cold", paf, fa)
    with _daemon(max_queue=4) as h:
        r1 = _submit_and_wait(h.sock, _job_args(
            tmp_path, "flap", paf, fa,
            ["--inject-faults=down=1-999", "--max-retries=0",
             "--recover=off"]))
        assert r1["rc"] == 0, r1
        st1 = json.loads(
            (tmp_path / "flap.json").read_text())["resilience"]
        assert st1["breaker_trips"] == 1, st1
        assert st1["degraded_batches"] > 0, st1

        r2 = _submit_and_wait(h.sock, _job_args(
            tmp_path, "inherit", paf, fa, ["--recover=off"]))
        assert r2["rc"] == 0, r2
        st2 = json.loads(
            (tmp_path / "inherit.json").read_text())["resilience"]
        # inherited open breaker: degraded from batch 1, NO new trip
        assert st2["breaker_trips"] == 0, st2
        assert st2["degraded_batches"] > 0, st2

        r3 = _submit_and_wait(h.sock, _job_args(
            tmp_path, "heal", paf, fa,
            ["--recover=auto", "--reprobe-interval=0"]))
        assert r3["rc"] == 0, r3
        st3 = json.loads(
            (tmp_path / "heal.json").read_text())["resilience"]
        # the reclose re-promotes this and every later job
        assert st3["breaker_recloses"] == 1, st3
        assert st3["recovered_batches"] > 0, st3
    for tag in ("flap", "inherit", "heal"):
        assert (tmp_path / f"{tag}.dfa").read_bytes() == cold, tag


def test_service_realistic_three_jobs_parity(tmp_path):
    """The acceptance gate in-process: the 200-alignment realistic
    corpus as 3 consecutive jobs through one warm daemon — every
    output byte-identical to the cold run, jobs 2..3 probe-free."""
    from test_realistic_scale import make_corpus
    qseq, lines = make_corpus()
    fa = tmp_path / "cds.fa"
    fa.write_text(f">cds1\n{qseq}\n")
    paf = tmp_path / "in.paf"
    paf.write_text("".join(ln + "\n" for ln in lines))

    def args(tag):
        return [str(paf), "-r", str(fa),
                "-o", str(tmp_path / f"{tag}.dfa"),
                "-s", str(tmp_path / f"{tag}.sum"),
                "-w", str(tmp_path / f"{tag}.mfa"),
                f"--cons={tmp_path / f'{tag}.cons'}", "--device=tpu",
                f"--stats={tmp_path / f'{tag}.json'}"]

    def outs(tag):
        return tuple((tmp_path / f"{tag}.{k}").read_bytes()
                     for k in ("dfa", "sum", "mfa", "cons"))

    err = io.StringIO()
    assert run(args("cold"), stderr=err) == 0, err.getvalue()[:2000]
    with _daemon(max_queue=4) as h:
        for j in (1, 2, 3):
            res = _submit_and_wait(h.sock, args(f"sv{j}"),
                                   timeout=600)
            assert res.get("ok") and res["rc"] == 0, res
            assert outs(f"sv{j}") == outs("cold"), j
            bk = json.loads(
                (tmp_path / f"sv{j}.json").read_text())["backend"]
            if j > 1:
                assert bk["probes"] == 0, (j, bk)
                assert bk["warm_hits"] > 0, (j, bk)


# ---------------------------------------------------------------------------
# admission control + cancel + drain
# ---------------------------------------------------------------------------
def test_queue_full_rejection_is_429_shaped(tmp_path):
    paf, fa = _corpus(tmp_path, n=16)
    with _daemon(max_queue=1, max_concurrent=1) as h:
        with ServiceClient(h.sock) as c:
            # a slow job occupies the worker; the queue holds ONE more
            s1 = c.submit(_job_args(tmp_path, "s1", paf, fa, [SLOW]))
            assert s1.get("ok"), s1
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                # wait for the worker to pick s1 up, so the queue slot
                # below is deterministically the ONLY one
                if c.status(s1["job_id"])["job"]["state"] == "running":
                    break
                time.sleep(0.02)
            s2 = c.submit(_job_args(tmp_path, "s2", paf, fa))
            assert s2.get("ok"), s2
            rej = c.submit(_job_args(tmp_path, "s3", paf, fa))
            assert rej["ok"] is False
            assert rej["error"] == protocol.ERR_QUEUE_FULL
            assert rej["retry_after_s"] > 0
            assert rej["max_queue"] == 1
            # back off, retry once capacity frees: both queued jobs
            # complete and the retry is accepted
            r1 = c.result(s1["job_id"], timeout=120)
            assert r1["rc"] == 0, r1
            s3 = c.submit(_job_args(tmp_path, "s3", paf, fa))
            assert s3.get("ok"), s3
            assert c.result(s3["job_id"], timeout=120)["rc"] == 0
        with ServiceClient(h.sock) as c:
            st = c.stats()["stats"]
        assert st["jobs"]["rejected"] == 1


def test_cancel_queued_vs_running(tmp_path):
    paf, fa = _corpus(tmp_path)
    cold = _cold(tmp_path, "cold", paf, fa)
    with _daemon(max_queue=4, max_concurrent=1) as h:
        with ServiceClient(h.sock) as c:
            slow = c.submit(_job_args(tmp_path, "run", paf, fa,
                                      [SLOW]))
            queued = c.submit(_job_args(tmp_path, "qd", paf, fa))
            # wait for the slow job to be mid-run (first batch ckpt
            # durable) so the running-cancel exercises a real drain
            assert _wait_mid_run(c, slow["job_id"],
                                 str(tmp_path / "run.dfa.ckpt"))
            # cancel the QUEUED job: removed immediately, never runs
            r = c.cancel(queued["job_id"])
            assert r["ok"] and r["was"] == "queued"
            assert c.status(queued["job_id"])["job"]["state"] \
                == "cancelled"
            assert not (tmp_path / "qd.dfa").exists()
            # cancel the RUNNING job: a graceful per-job drain — it
            # stops at the next batch boundary with rc 75 and a valid
            # resumable checkpoint
            r = c.cancel(slow["job_id"])
            assert r["ok"] and r["was"] == "running"
            res = c.result(slow["job_id"], timeout=120)
            assert res["job"]["state"] == "cancelled", res
            assert res["rc"] == EXIT_PREEMPTED
        got = _load_checkpoint(str(tmp_path / "run.dfa"))
        assert isinstance(got, tuple), got
        # the cancelled job is RESUMABLE: a cold --resume completes it
        # byte-identically
        err = io.StringIO()
        rc = run(_job_args(tmp_path, "run", paf, fa, ["--resume"]),
                 stderr=err)
        assert rc == 0, err.getvalue()[:2000]
        assert (tmp_path / "run.dfa").read_bytes() == cold


def test_drain_finishes_inflight_rejects_new_exits_75(tmp_path):
    """The drain contract end-to-end (protocol-command flavor; the
    SIGTERM flavor is the subprocess test below): the in-flight job
    finishes at a batch boundary with a valid ckpt and rc 75, the
    queued job is preempted without starting, a submit during the
    drain answers ``draining``, and the daemon exits 75."""
    paf, fa = _corpus(tmp_path)
    cold = _cold(tmp_path, "cold", paf, fa)
    with _daemon(max_queue=4, max_concurrent=1) as h:
        with ServiceClient(h.sock) as c:
            slow = c.submit(_job_args(tmp_path, "infl", paf, fa,
                                      [SLOW]))
            queued = c.submit(_job_args(tmp_path, "quep", paf, fa))
            assert _wait_mid_run(c, slow["job_id"],
                                 str(tmp_path / "infl.dfa.ckpt"))
            d = c.drain()
            assert d["ok"] and d["draining"]
            assert queued["job_id"] in d["preempted_queued"]
            # submit DURING the drain: rejected with the draining code
            rej = c.submit(_job_args(tmp_path, "late", paf, fa))
            assert rej["ok"] is False
            assert rej["error"] == protocol.ERR_DRAINING
            res = c.result(slow["job_id"], timeout=120)
            assert res["job"]["state"] == "preempted", res
            assert res["rc"] == EXIT_PREEMPTED
            qres = c.result(queued["job_id"], timeout=30)
            assert qres["job"]["state"] == "preempted"
            assert "resum" in qres["job"]["detail"]
        h.thread.join(30)
        assert h.rc == [EXIT_PREEMPTED], h.err.getvalue()[-2000:]
    # the in-flight job drained onto a valid, resumable checkpoint
    got = _load_checkpoint(str(tmp_path / "infl.dfa"))
    assert isinstance(got, tuple), got
    err = io.StringIO()
    rc = run(_job_args(tmp_path, "infl", paf, fa, ["--resume"]),
             stderr=err)
    assert rc == 0, err.getvalue()[:2000]
    assert (tmp_path / "infl.dfa").read_bytes() == cold


def test_client_disconnect_mid_result_never_kills_daemon(tmp_path):
    paf, fa = _corpus(tmp_path)
    cold = _cold(tmp_path, "cold", paf, fa)
    with _daemon(max_queue=4) as h:
        s = _raw_conn(h.sock)
        f = s.makefile("rb")
        protocol.write_frame(
            s.makefile("wb"),
            {"cmd": "submit",
             "args": _job_args(tmp_path, "dj", paf, fa, [SLOW])})
        sub = json.loads(f.readline())
        assert sub["ok"], sub
        # ask for the (blocking) result, then vanish mid-wait: the
        # daemon's response hits a dead socket — its problem must end
        # at that connection
        protocol.write_frame(s.makefile("wb"),
                             {"cmd": "result",
                              "job_id": sub["job_id"]})
        s.close()
        # the job keeps running and a FRESH connection collects it
        with ServiceClient(h.sock) as c:
            res = c.result(sub["job_id"], timeout=120)
        assert res.get("ok") and res["rc"] == 0, res
        assert (tmp_path / "dj.dfa").read_bytes() == cold
        with ServiceClient(h.sock) as c:
            assert c.ping().get("ok")


def test_failed_job_is_contained(tmp_path):
    """A job whose argv is garbage fails — the daemon survives and
    says why."""
    paf, fa = _corpus(tmp_path, n=4)
    with _daemon(max_queue=4) as h:
        res = _submit_and_wait(
            h.sock, ["/nonexistent.paf", "-r", fa, "-o",
                     str(tmp_path / "x.dfa")])
        assert res["job"]["state"] == "failed", res
        assert res["rc"] not in (0, None)
        assert "Cannot open input file" in res["stderr_tail"]
        # a scripted kill (BaseException) is contained at the job
        # boundary too: the job fails, the daemon lives
        res = _submit_and_wait(
            h.sock, _job_args(tmp_path, "kill", paf, fa,
                              ["--inject-faults=kill=1"]))
        assert res["job"]["state"] == "failed", res
        assert "InjectedKill" in res["job"]["detail"]
        # and the next job is fine
        res = _submit_and_wait(h.sock,
                               _job_args(tmp_path, "ok", paf, fa))
        assert res["rc"] == 0, res


# ---------------------------------------------------------------------------
# subprocess: the real `pwasm-tpu serve` + SIGTERM drill
# ---------------------------------------------------------------------------
def _serve_env():
    old_pp = os.environ.get("PYTHONPATH", "")
    return dict(os.environ, JAX_PLATFORMS="cpu",
                PWASM_DEVICE_PROBE="0",
                PYTHONPATH=REPO + (os.pathsep + old_pp if old_pp
                                   else ""))


def test_serve_subprocess_sigterm_drains_exit75_resumable(tmp_path):
    """The acceptance drill with a REAL signal: SIGTERM to a live
    `pwasm-tpu serve` process mid-job → daemon exits 75, the in-flight
    job's checkpoint verifies, and a cold ``--resume`` completes it
    byte-identically.  Timing-tolerant: the job is slowed by injected
    hangs and the signal is sent only once the job reports running."""
    paf, fa = _corpus(tmp_path)
    cold = _cold(tmp_path, "cold", paf, fa)
    sockdir = tempfile.mkdtemp(prefix="pwsvc")
    sock = os.path.join(sockdir, "s")
    sp = subprocess.Popen(
        [sys.executable, "-m", "pwasm_tpu.cli", "serve",
         f"--socket={sock}"],
        env=_serve_env(), stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE, text=True)
    try:
        assert wait_for_socket(sock, 60)
        with ServiceClient(sock) as c:
            sub = c.submit(_job_args(tmp_path, "sig", paf, fa,
                                     [SLOW]))
            assert sub.get("ok"), sub
            caught_mid_run = _wait_mid_run(
                c, sub["job_id"], str(tmp_path / "sig.dfa.ckpt"))
        sp.send_signal(signal.SIGTERM)
        rc = sp.wait(timeout=120)
        _, stderr_tail = "", sp.stderr.read()[-3000:]
        assert rc == EXIT_PREEMPTED, (rc, stderr_tail)
        if caught_mid_run:
            # the in-flight job's final checkpoint must verify whole
            got = _load_checkpoint(str(tmp_path / "sig.dfa"))
            if os.path.exists(tmp_path / "sig.dfa.ckpt"):
                assert isinstance(got, tuple), got
        # resumable either way: a cold --resume completes the report
        # byte-identically (via the ckpt when one survived, via the
        # header scan when the drain landed before/after every batch)
        err = io.StringIO()
        rc = run(_job_args(tmp_path, "sig", paf, fa, ["--resume"]),
                 stderr=err)
        assert rc == 0, err.getvalue()[:2000]
        assert (tmp_path / "sig.dfa").read_bytes() == cold
    finally:
        if sp.poll() is None:
            sp.kill()
            sp.wait()
        sp.stderr.close()
        shutil.rmtree(sockdir, ignore_errors=True)


# ---------------------------------------------------------------------------
# CLI surface: subcommand dispatch + client mains
# ---------------------------------------------------------------------------
def test_cli_dispatch_usage_errors():
    err = io.StringIO()
    assert run(["serve"], stderr=err) == 1
    assert "--socket" in err.getvalue()
    err = io.StringIO()
    assert run(["serve", "--socket=/x", "--max-queue=frog"],
               stderr=err) == 1
    err = io.StringIO()
    assert run(["submit"], stderr=err) == 1
    assert "--socket" in err.getvalue()
    err = io.StringIO()
    assert run(["svc-stats"], stderr=err) == 1
    err = io.StringIO()
    assert run(["submit", "--socket=/nonexistent.sock", "--", "x",
                "-o", "y"], stderr=err) == 1
    assert "cannot connect" in err.getvalue()


def test_submit_and_svc_stats_client_mains(tmp_path):
    paf, fa = _corpus(tmp_path, n=8)
    cold = _cold(tmp_path, "cold", paf, fa)
    with _daemon(max_queue=4) as h:
        out = io.StringIO()
        err = io.StringIO()
        rc = run(["submit", f"--socket={h.sock}", "--"]
                 + _job_args(tmp_path, "cm", paf, fa),
                 stdout=out, stderr=err)
        assert rc == 0, err.getvalue()
        line = json.loads(out.getvalue())
        assert line["state"] == "done" and line["rc"] == 0
        assert (tmp_path / "cm.dfa").read_bytes() == cold
        out = io.StringIO()
        rc = run(["svc-stats", f"--socket={h.sock}"], stdout=out,
                 stderr=io.StringIO())
        assert rc == 0
        st = json.loads(out.getvalue())
        assert st["stats_version"] == 1
        assert st["jobs"]["completed"] == 1
