"""MSA engine tests: layout math, gap propagation, progressive merge,
consensus voting, clip refinement, writers."""

import io

import numpy as np
import pytest

from pwasm_tpu.align.gapseq import GapSeq
from pwasm_tpu.align.msa import AlnClipOps, Msa, best_char_from_counts
from pwasm_tpu.core.errors import ZeroCoverageError


def mk(name, seq, offset=0, **kw):
    return GapSeq(name, "", seq, offset=offset, **kw)


def mfa(msa):
    buf = io.StringIO()
    msa.write_msa(buf)
    return buf.getvalue()


# ---------------------------------------------------------------------------
# gap bookkeeping + layout walks
# ---------------------------------------------------------------------------
def test_set_add_gap_and_end_offset():
    s = mk("s", b"ACGTACGT")
    s.set_gap(2, 3)
    assert s.numgaps == 3
    s.set_gap(2, 1)          # set replaces
    assert s.numgaps == 1
    s.add_gap(5, 2)
    assert s.numgaps == 3
    assert s.end_offset() == 0 + 8 + 3


def test_walk_positions_match_reference_walk():
    s = mk("s", b"ACGTACGT", offset=3)
    s.set_gap(2, 2)
    s.set_gap(6, 1)
    # reference walk: salpos starts at offset, += 1+gap each step
    salpos = s.offset
    expect = []
    for j in range(s.seqlen):
        salpos += 1 + s.gap(j)
        expect.append(salpos)
    assert list(s.layout_walk_positions()) == expect
    # find_walk_pos stops at first W[j] > alpos
    for alpos in range(0, 16):
        j = 0
        while j < s.seqlen and expect[j] <= alpos:
            j += 1
        assert s.find_walk_pos(alpos) == j


def test_reverse_gaps_keeps_index0():
    s = mk("s", b"ACGTA")
    s.gaps[:] = [9, 1, 2, 3, 4]
    s.reverse_gaps()
    assert list(s.gaps) == [9, 4, 3, 2, 1]


# ---------------------------------------------------------------------------
# pairwise + inject_gap
# ---------------------------------------------------------------------------
def test_pairwise_layout_and_write():
    r = mk("r", b"ACGTACGT")
    t = mk("t", b"ACGTCGT")
    t.set_gap(4, 1)  # gap before base 4: ACGT-CGT
    msa = Msa(r, t)
    assert msa.length == 8
    out = mfa(msa)
    assert out == ">r\nACGTACGT\n>t\nACGT-CGT\n"


def test_inject_gap_propagates():
    r = mk("r", b"ACGTACGT")
    t = mk("t", b"ACGTACGT")
    msa = Msa(r, t)
    msa.inject_gap(r, 4, 2)
    assert r.gap(4) == 2
    assert t.gap(4) == 2
    assert msa.length == 10
    out = mfa(msa)
    assert out == ">r\nACGT--ACGT\n>t\nACGT--ACGT\n"


def test_inject_gap_offset_only_member():
    r = mk("r", b"ACGTACGT")
    t = mk("t", b"ACGT", offset=6)  # starts after the gap point
    msa = Msa(r, t)
    msa.inject_gap(r, 2, 1)
    assert t.offset == 7
    assert t.numgaps == 0


# ---------------------------------------------------------------------------
# progressive merge (the -w flow)
# ---------------------------------------------------------------------------
def test_progressive_merge_once_a_gap_always_a_gap():
    q = b"ACGTACGTAC"
    # aln1: target has 2bp insertion after q pos 6
    rseq = mk("q", q)
    rseq.set_gap(6, 2)
    t1 = mk("asm1", b"ACGTACggGTAC")
    msa = Msa(rseq, t1)
    # aln2: target missing q[2:4]
    rs2 = GapSeq("q", "", b"", seqlen=10)
    t2 = mk("asm2", b"ACACGTAC")
    t2.set_gap(2, 2)
    m2 = Msa(rs2, t2)
    msa.add_align(rseq, m2, rs2)
    assert msa.count() == 3
    out = mfa(msa)
    assert out == (">q\nACGTAC--GTAC\n"
                   ">asm1\nACGTACggGTAC\n"
                   ">asm2\nAC--AC--GTAC\n")


def test_progressive_merge_reverse_member():
    q = b"ACGTACGTAC"
    rseq = mk("q", q)
    rseq.set_gap(6, 2)
    t1 = mk("asm1", b"ACGTACggGTAC")
    msa = Msa(rseq, t1)
    # asm3: reverse-strand full-length exact match; bases stored in RC
    # space, gaps indexed in forward space, prep_seq RCs at write time
    from pwasm_tpu.core.dna import revcomp
    rs3 = GapSeq("q", "", b"", seqlen=10)
    t3 = GapSeq("asm3", "", revcomp(q), offset=0, revcompl=1)
    m3 = Msa(rs3, t3)
    msa.add_align(rseq, m3, rs3)
    out = mfa(msa)
    assert out.endswith(">asm3\nACGTAC--GTAC\n")


# ---------------------------------------------------------------------------
# consensus vote
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("counts,layers,expect", [
    ([3, 1, 0, 0, 0, 0], 4, "A"),
    ([0, 0, 0, 0, 0, 3], 3, "-"),
    ([2, 0, 0, 0, 0, 2], 4, "A"),      # ACGT beats '-' on ties
    ([0, 2, 0, 0, 2, 0], 4, "C"),      # ACGT beats N on ties
    ([0, 0, 0, 0, 2, 2], 4, "-"),      # N tied with '-': '-' wins
    ([0, 0, 0, 0, 2, 1], 3, "N"),
    ([0, 0, 0, 0, 1, 2], 3, "-"),
    ([1, 1, 1, 1, 0, 0], 4, "A"),      # first of ACGT wins ties
    ([0, 2, 2, 0, 0, 0], 4, "C"),
    ([0, 0, 0, 0, 0, 0], 0, None),     # zero coverage
])
def test_best_char_rule(counts, layers, expect):
    got = best_char_from_counts(np.array(counts), layers)
    assert got == (0 if expect is None else ord(expect))


def test_refine_msa_consensus_simple():
    a = mk("a", b"ACGTACGT")
    b = mk("b", b"ACGTACGT")
    c = mk("c", b"ACCTACGT")
    msa = Msa(a, b)
    msa.add_seq(c, 0, 0)
    msa.refine_msa(remove_cons_gaps=False, refine_clipping=False)
    assert bytes(msa.consensus) == b"ACGTACGT"


def test_refine_msa_gap_column_kept_as_star():
    # two seqs gap at a column, one base -> gap wins the vote
    a = mk("a", b"ACGT")
    b = mk("b", b"ACGT")
    c = mk("c", b"ACXGT")  # extra base, others gap... build via inject
    msa = Msa(a, b)
    msa.add_seq(c, 0, 0)
    msa.inject_gap(c, 2, 1)  # c's X column: a/b get gaps... wait
    # inject gap in c at pos2 -> a,b,c all gap; instead use add_gap on a,b
    # simpler direct construction below
    a2 = mk("a", b"ACGT")
    b2 = mk("b", b"ACGT")
    c2 = mk("c", b"ACXGT")
    a2.set_gap(2, 1)
    b2.set_gap(2, 1)
    m = Msa(a2, b2)
    m.add_seq(c2, 0, 0)
    m.refine_msa(remove_cons_gaps=False, refine_clipping=False)
    assert bytes(m.consensus) == b"AC*GT"


def test_refine_msa_remove_cons_gaps():
    a2 = mk("a", b"ACGT")
    b2 = mk("b", b"ACGT")
    c2 = mk("c", b"ACXGT")
    a2.set_gap(2, 1)
    b2.set_gap(2, 1)
    m = Msa(a2, b2)
    m.add_seq(c2, 0, 0)
    m.refine_msa(remove_cons_gaps=True, refine_clipping=False)
    assert bytes(m.consensus) == b"ACGT"
    # the X base was deleted from c
    assert c2.gap(2) == -1
    out = mfa(m)
    assert ">c\nACGT\n" in out


def test_zero_coverage_column_exit5():
    a = mk("a", b"AC", offset=0)
    b = mk("b", b"GT", offset=4)
    msa = Msa(a, b)
    with pytest.raises(ZeroCoverageError) as ei:
        msa.refine_msa(remove_cons_gaps=False, refine_clipping=False)
    assert ei.value.exit_code == 5


# ---------------------------------------------------------------------------
# X-drop clip refinement
# ---------------------------------------------------------------------------
def test_refine_clipping_recovers_matching_clip():
    s = mk("s", b"ACGTACGT")
    s.clp5 = 2
    s.msa = None
    s.refine_clipping(b"ACGTACGT", 0)
    assert s.clp5 == 0


def test_refine_clipping_keeps_mismatched_clip():
    # clipped prefix disagrees with consensus: first backward search walks
    # right to the first match, then extension can't beat it
    s = mk("s", b"TTGTACGT")
    s.clp5 = 2
    s.refine_clipping(b"ACGTACGT", 0)
    assert s.clp5 >= 2


def test_refine_clipping_right_end():
    s = mk("s", b"ACGTACGT")
    s.clp3 = 3
    s.refine_clipping(b"ACGTACGT", 0)
    assert s.clp3 == 0


# ---------------------------------------------------------------------------
# clipping transaction
# ---------------------------------------------------------------------------
def test_eval_clipping_propagates():
    a = mk("a", b"ACGTACGTACGTACGT")
    b = mk("b", b"ACGTACGTACGTACGT")
    msa = Msa(a, b)
    ops = AlnClipOps()
    assert msa.eval_clipping(a, 2, -1, 0.0, ops)
    seqs = {id(s): (c5, c3) for s, c5, c3 in ops.ops}
    assert seqs[id(a)] == (2, -1)
    assert seqs[id(b)] == (2, -1)
    msa.apply_clipping(ops)
    assert a.clp5 == 2 and b.clp5 == 2


def test_eval_clipping_rejects_over_25pct():
    a = mk("a", b"ACGTACGTACGTACGT")   # 16bp; max clip leaves >= 4
    b = mk("b", b"ACGTACGTACGTACGT")
    msa = Msa(a, b)
    ops = AlnClipOps()
    assert not msa.eval_clipping(a, 13, -1, 0.0, ops)


def test_eval_clipping_clipmax():
    a = mk("a", b"ACGTACGTACGTACGT")
    b = mk("b", b"ACGTACGTACGTACGT")
    msa = Msa(a, b)
    ops = AlnClipOps()
    assert not msa.eval_clipping(a, 5, -1, 4.0, ops)   # absolute max 4
    ops = AlnClipOps()
    assert msa.eval_clipping(a, 4, -1, 4.0, ops)


# ---------------------------------------------------------------------------
# ACE / info writers
# ---------------------------------------------------------------------------
def _three_seq_msa():
    a = mk("a", b"ACGTACGT")
    b = mk("b", b"ACGTACGT")
    c = mk("c", b"ACCTACGT")
    msa = Msa(a, b)
    msa.add_seq(c, 0, 0)
    return msa


def test_write_ace():
    msa = _three_seq_msa()
    buf = io.StringIO()
    msa.write_ace(buf, "contig1", remove_cons_gaps=False,
                  refine_clipping=False)
    out = buf.getvalue()
    lines = out.splitlines()
    assert lines[0] == "CO contig1 8 3 0 U"
    assert "ACGTACGT" in lines[1]
    assert "AF a U 1" in out and "AF c U 1" in out
    assert "RD a 8 0 0" in out
    assert "QA 1 8 1 8" in out


def test_write_info():
    msa = _three_seq_msa()
    buf = io.StringIO()
    msa.write_info(buf, "contig1", remove_cons_gaps=False,
                   refine_clipping=False)
    out = buf.getvalue()
    lines = out.splitlines()
    assert lines[0] == ">contig1 3 ACGTACGT"
    # reference quirk: asml/asmr double-increment shifts the pid comparison
    # one column right (GapAssem.cpp:1305-1307), so even a perfect match
    # scores 0.00 here — preserved for parity
    assert lines[1] == "a 8 1 2 9 1 8 0.00 "
    assert lines[3].startswith("c 8 1 2 9 1 8 0.00")


def test_write_info_alndata_rle():
    a = mk("a", b"ACGTACGT")
    b = mk("b", b"ACGTACGT")
    a.set_gap(4, 1)
    b.set_gap(4, 1)
    msa = Msa(a, b)
    # gap of 1 -> bare 'g' (short-indel form, no offset prefix)
    buf = io.StringIO()
    msa.write_info(buf, "ctg", remove_cons_gaps=False,
                   refine_clipping=False)
    row = buf.getvalue().splitlines()[1]
    assert row.split()[-1] == "g"
    # long gap -> '<ofs>g<len>-' form
    a2 = mk("a", b"ACGTACGT")
    b2 = mk("b", b"ACGTACGT")
    a2.set_gap(4, 5)
    b2.set_gap(4, 5)
    m2 = Msa(a2, b2)
    buf = io.StringIO()
    m2.write_info(buf, "ctg", remove_cons_gaps=False,
                  refine_clipping=False)
    row = buf.getvalue().splitlines()[1]
    assert row.split()[-1] == "4g5-"


def test_print_layout():
    msa = _three_seq_msa()
    buf = io.StringIO()
    msa.print_layout(buf, "=")
    out = buf.getvalue().splitlines()
    assert out[0].endswith("=" * 8)
    assert out[1].endswith("ACGTACGT")


def test_mfasta_wrap_and_exact_multiple_blank_line():
    s = mk("s", b"A" * 60)
    buf = io.StringIO()
    s.print_mfasta(buf, 60)
    # exact multiple of the line length leaves the reference's trailing
    # blank line (printMFasta quirk)
    assert buf.getvalue() == ">s\n" + "A" * 60 + "\n\n"


def test_coverage_tracking_pairwise_and_merge():
    # opt-in ALIGN_COVERAGE_DATA capability (GapAssem.h:42-46):
    # +1 over aligned spans, -1 over the shorter mismatched overhangs
    s1 = GapSeq("a", seq=b"ACGTACGT")
    s2 = GapSeq("b", seq=b"CGTACGTA", offset=1)
    Msa(s1, s2, cov_spans=((1, 8), (0, 7)))
    # s1: span [1,8) +1; left overhang msml=min(1,0)=0; right
    # msmr=min(8-8, 8-7)=0 -> none
    np.testing.assert_array_equal(s1.cov, [0, 1, 1, 1, 1, 1, 1, 1])
    np.testing.assert_array_equal(s2.cov, [1, 1, 1, 1, 1, 1, 1, 0])

    # strand-aware merge of another instance's coverage
    s1b = GapSeq("a", seq=b"ACGTACGT", revcompl=1)
    s1b.enable_coverage()
    s1b.cov[:] = [7, 6, 5, 4, 3, 2, 1, 0]
    s1.add_coverage(s1b)
    np.testing.assert_array_equal(s1.cov, [0, 2, 3, 4, 5, 6, 7, 8])

    # rev_complement reverses the coverage array (GapAssem.cpp:383-391)
    s1.rev_complement()
    np.testing.assert_array_equal(s1.cov, [8, 7, 6, 5, 4, 3, 2, 0])


def test_coverage_mismatched_overhang_penalty():
    s1 = GapSeq("a", seq=b"TTACGTACGTTT")  # len 12
    s2 = GapSeq("b", seq=b"GGACGTACGTGG")  # len 12
    Msa(s1, s2, cov_spans=((2, 10), (2, 10)))
    # symmetric 2-base overhangs: cov[0:2] -= 1 and cov[10:12] -= 1
    np.testing.assert_array_equal(
        s1.cov, [-1, -1, 1, 1, 1, 1, 1, 1, 1, 1, -1, -1])
    np.testing.assert_array_equal(s2.cov, s1.cov)
