"""Device consensus kernel: bit-exact parity vs the CPU engine, Pallas
variant, and depth-sharded psum reduction on a virtual multi-chip mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pwasm_tpu.align.gapseq import GapSeq
from pwasm_tpu.align.msa import Msa, best_char_from_counts
from pwasm_tpu.ops.consensus import (
    CODE_ZERO_COV,
    consensus_pallas,
    consensus_vote_counts,
    consensus_votes,
    pileup_counts,
    votes_to_chars,
)

NUC = b"ACGTN-"


def _vote_to_char(code):
    return 0 if code == CODE_ZERO_COV else NUC[code]


# ---------------------------------------------------------------------------
def test_vote_parity_random_counts():
    rng = np.random.default_rng(0)
    counts = rng.integers(0, 6, size=(2000, 6)).astype(np.int32)
    counts[:50] = 0  # zero-coverage block
    # craft every tie pattern across the 6 buckets
    crafted = []
    for pattern in range(64):
        row = [(3 if (pattern >> k) & 1 else 1) for k in range(6)]
        crafted.append(row)
    counts = np.vstack([counts, np.array(crafted, dtype=np.int32)])
    got = np.asarray(consensus_vote_counts(jnp.asarray(counts)))
    for i in range(len(counts)):
        expect = best_char_from_counts(counts[i], int(counts[i].sum()))
        got_c = _vote_to_char(int(got[i]))
        # CPU returns '-' for gap; device maps via NUC table
        assert got_c == expect, (i, counts[i], got[i], expect)


def test_pileup_counts_ignores_padding():
    rng = np.random.default_rng(1)
    bases = rng.integers(0, 8, size=(30, 100)).astype(np.int8)  # 6,7=pad
    counts = np.asarray(pileup_counts(jnp.asarray(bases)))
    for k in range(6):
        np.testing.assert_array_equal(counts[:, k],
                                      (bases == k).sum(axis=0))


def test_consensus_votes_batched():
    rng = np.random.default_rng(2)
    bases = rng.integers(0, 7, size=(4, 16, 64)).astype(np.int8)
    votes = np.asarray(consensus_votes(jnp.asarray(bases)))
    assert votes.shape == (4, 64)
    single = np.asarray(consensus_votes(jnp.asarray(bases[2])))
    np.testing.assert_array_equal(votes[2], single)


def test_pallas_matches_jax_path():
    rng = np.random.default_rng(3)
    bases = rng.integers(0, 7, size=(64, 1000)).astype(np.int8)
    votes_ref = np.asarray(consensus_votes(jnp.asarray(bases)))
    counts_ref = np.asarray(pileup_counts(jnp.asarray(bases)))
    votes, counts = consensus_pallas(jnp.asarray(bases), col_tile=256)
    np.testing.assert_array_equal(np.asarray(votes), votes_ref)
    np.testing.assert_array_equal(np.asarray(counts), counts_ref)


def test_pallas_unaligned_columns():
    rng = np.random.default_rng(4)
    bases = rng.integers(0, 7, size=(10, 333)).astype(np.int8)
    votes, counts = consensus_pallas(jnp.asarray(bases), col_tile=128)
    np.testing.assert_array_equal(
        np.asarray(votes), np.asarray(consensus_votes(jnp.asarray(bases))))


def test_pallas_assume_valid_matches_robust_path():
    """assume_valid elides the out-of-range remap; on in-contract codes
    (0..6, incl. PAD) it must be bit-identical to the robust path."""
    rng = np.random.default_rng(6)
    for depth in (1, 31, 64, 256):
        bases = rng.integers(0, 7, size=(depth, 512)).astype(np.int8)
        v0, c0 = consensus_pallas(jnp.asarray(bases), col_tile=128)
        v1, c1 = consensus_pallas(jnp.asarray(bases), col_tile=128,
                                  assume_valid=True)
        np.testing.assert_array_equal(np.asarray(v0), np.asarray(v1))
        np.testing.assert_array_equal(np.asarray(c0), np.asarray(c1))


def test_pallas_out_of_range_codes_and_odd_depths():
    """Negative codes and codes > 5 must contribute nothing, and depths
    that are not multiples of the packed-counter row chunk (31) must
    count exactly — guards the packed 5-bit accumulation (the remap to
    the never-extracted bit-30 shift and the chunk-boundary slices)."""
    rng = np.random.default_rng(5)
    for depth in (1, 30, 31, 32, 77, 256):
        bases = rng.integers(-3, 9, size=(depth, 640)).astype(np.int8)
        _votes, counts = consensus_pallas(jnp.asarray(bases), col_tile=128)
        expect = np.stack([(bases == k).sum(0) for k in range(6)], 1)
        np.testing.assert_array_equal(np.asarray(counts), expect)


# ---------------------------------------------------------------------------
# parity with the CPU MSA engine on a random progressive MSA
# ---------------------------------------------------------------------------
def _random_msa(seed):
    rng = np.random.default_rng(seed)
    n, L = 6, 40
    seqs = []
    for k in range(n):
        seq = rng.choice(list(b"ACGT"), size=L).astype(np.uint8).tobytes()
        s = GapSeq(f"s{k}", "", seq)
        for _ in range(rng.integers(0, 4)):
            s.set_gap(int(rng.integers(0, L)), int(rng.integers(1, 3)))
        seqs.append(s)
    msa = Msa(seqs[0], seqs[1])
    for s in seqs[2:]:
        msa.add_seq(s, 0, 0)
    return msa


@pytest.mark.parametrize("seed", range(5))
def test_device_consensus_matches_cpu_engine(seed):
    msa = _random_msa(seed)
    mat = msa.pileup_matrix()
    msa.refine_msa(remove_cons_gaps=False, refine_clipping=False)
    cols = msa.msacolumns
    votes = np.asarray(consensus_votes(jnp.asarray(mat)))
    window = votes[cols.mincol:cols.maxcol + 1]
    assert not (window == CODE_ZERO_COV).any()
    assert votes_to_chars(window) == bytes(msa.consensus)
    # counts parity too
    counts = np.asarray(pileup_counts(jnp.asarray(mat)))
    np.testing.assert_array_equal(counts, cols.counts)


# ---------------------------------------------------------------------------
# depth-sharded pileup with psum over the mesh (the ICI reduction)
# ---------------------------------------------------------------------------
def test_depth_sharded_consensus_psum():
    from jax.sharding import Mesh, PartitionSpec as P
    from pwasm_tpu.utils.jaxcompat import shard_map

    devs = jax.devices()
    assert len(devs) >= 4, "conftest must provide 8 virtual devices"
    mesh = Mesh(np.array(devs[:4]), ("depth",))
    rng = np.random.default_rng(7)
    bases = rng.integers(0, 7, size=(64, 256)).astype(np.int8)

    @jax.jit
    def sharded_consensus(b):
        def block(b_local):
            local = pileup_counts(b_local)
            total = jax.lax.psum(local, "depth")
            return consensus_vote_counts(total)
        fn = shard_map(block, mesh=mesh,
                       in_specs=P("depth", None),
                       out_specs=P())  # votes replicated
        return fn(b)

    votes = np.asarray(sharded_consensus(jnp.asarray(bases)))
    np.testing.assert_array_equal(
        votes, np.asarray(consensus_votes(jnp.asarray(bases))))


def test_build_msa_device_counts_come_from_kernel(monkeypatch):
    """refine_msa(device=True)'s column counts must provably originate in
    the Pallas kernel, not host scatter-adds (VERDICT r2 missing #1):
    tamper with the kernel's count output and observe the tampering in
    MsaColumns."""
    import pwasm_tpu.ops.consensus as consmod

    real = consmod.consensus_pallas

    def tampered(bases, *a, **k):
        votes, counts = real(bases, *a, **k)
        return votes, counts + 7

    monkeypatch.setattr(consmod, "consensus_pallas", tampered)
    dev = _random_msa(3)
    dev.build_msa(device=True)
    host = _random_msa(3)
    host.build_msa()
    np.testing.assert_array_equal(dev.msacolumns.counts,
                                  host.msacolumns.counts + 7)


@pytest.mark.parametrize("seed", range(3))
def test_refine_msa_device_full_parity(seed):
    """Full refine_msa parity, device counts+votes vs host engine:
    consensus, counts, layers, and post-refine clip state all bit-exact."""
    host = _random_msa(seed)
    dev = _random_msa(seed)
    host.refine_msa(remove_cons_gaps=False)
    dev.refine_msa(remove_cons_gaps=False, device=True)
    assert bytes(dev.consensus) == bytes(host.consensus)
    np.testing.assert_array_equal(dev.msacolumns.counts,
                                  host.msacolumns.counts)
    np.testing.assert_array_equal(dev.msacolumns.layers,
                                  host.msacolumns.layers)
    for sh, sd in zip(host.seqs, dev.seqs):
        assert (sh.clp5, sh.clp3) == (sd.clp5, sd.clp3)


@pytest.mark.parametrize("seed", range(3))
def test_refine_msa_device_survives_deleted_bases(seed, capsys):
    """An MSA with deleted bases (negative gaps) stays on the device
    path: collided column occupants spill onto extra pileup rows so the
    device counts remain bit-exact vs the host scatter-adds (VERDICT r3
    item 4) — no demotion, engine_fallbacks stays zero."""
    dev = _random_msa(seed)
    host = _random_msa(seed)
    for m in (dev, host):
        # delete a few interior bases (the --remove-cons-gaps state),
        # including adjacent ones so collision multiplicity exceeds 2
        for s_idx, pos in [(1, 2), (1, 3), (0, 5)]:
            if m.seqs[s_idx].seqlen > pos + 2:
                m.seqs[s_idx].remove_base(pos)
    host.refine_msa(remove_cons_gaps=False)
    dev.refine_msa(remove_cons_gaps=False, device=True)
    assert bytes(dev.consensus) == bytes(host.consensus)
    np.testing.assert_array_equal(dev.msacolumns.counts,
                                  host.msacolumns.counts)
    np.testing.assert_array_equal(dev.msacolumns.layers,
                                  host.msacolumns.layers)
    assert dev.engine_fallbacks == 0
    assert "fall back to host" not in capsys.readouterr().err


def test_pileup_matrix_spills_collided_columns():
    """With a deleted base, the member contributes two symbols to one
    column; the pileup matrix grows a spill row carrying the second
    occupant, and per-column code counts over the matrix match the host
    scatter counts exactly."""
    msa = _random_msa(0)
    depth = len(msa.seqs)
    assert msa.pileup_matrix().shape[0] == depth   # pre-refine: no spill
    msa.seqs[1].remove_base(2)                     # a deleted base
    mat = msa.pileup_matrix()
    assert mat.shape[0] > depth                    # spill row appended
    host = _random_msa(0)
    host.seqs[1].remove_base(2)
    host.build_msa()                               # host scatter counts
    counts = np.zeros((msa.length, 6), dtype=np.int32)
    for code in range(6):
        counts[:, code] = (mat == code).sum(axis=0)
    np.testing.assert_array_equal(counts, host.msacolumns.counts)


@pytest.mark.parametrize("seed", range(3))
def test_refine_msa_device_clip_phases_on_device(seed, monkeypatch):
    """refine_msa(device=True) routes the X-drop clip refinement through
    the device phase program (spied), and the resulting clip state is
    bit-exact with the host engine (VERDICT r3 item 3)."""
    import pwasm_tpu.ops.refine_clip as rc

    calls = []
    real = rc.refine_phases_device

    def spy(*a, **k):
        calls.append(a[0].shape)
        return real(*a, **k)

    monkeypatch.setattr(rc, "refine_phases_device", spy)
    host = _random_msa(seed)
    dev = _random_msa(seed)
    for m in (host, dev):
        r = np.random.default_rng(seed + 50)  # identical clips for both
        for s in m.seqs[1:]:
            s.clp5 = int(r.integers(0, 4))
            s.clp3 = int(r.integers(0, 4))
    host.refine_msa(remove_cons_gaps=False)
    dev.refine_msa(remove_cons_gaps=False, device=True)
    assert calls, "device clip phases not invoked"
    assert dev.engine_fallbacks == 0
    assert bytes(dev.consensus) == bytes(host.consensus)
    for sh, sd in zip(host.seqs, dev.seqs):
        assert (sh.clp5, sh.clp3) == (sd.clp5, sd.clp3)


def test_refine_msa_mesh_routes_consensus_and_clips(monkeypatch):
    """refine_msa(device=True, mesh=...) shards BOTH device stages: the
    consensus counts (depth psum) and the clip-refinement phases (member
    sharding) — results bit-exact with the host engine."""
    from pwasm_tpu.parallel import mesh as meshmod
    from pwasm_tpu.parallel.mesh import make_mesh

    assert len(jax.devices()) >= 8
    mesh = make_mesh(8)
    calls = []
    real_refine = meshmod.sharded_refine_phases
    real_counts = meshmod.sharded_counts_votes

    def spy_refine(*a, **k):
        calls.append("refine")
        return real_refine(*a, **k)

    def spy_counts(*a, **k):
        calls.append("counts")
        return real_counts(*a, **k)

    monkeypatch.setattr(meshmod, "sharded_refine_phases", spy_refine)
    monkeypatch.setattr(meshmod, "sharded_counts_votes", spy_counts)
    host = _random_msa(4)
    dev = _random_msa(4)
    for m in (host, dev):
        r = np.random.default_rng(60)
        for s in m.seqs[1:]:
            s.clp5 = int(r.integers(1, 4))
            s.clp3 = int(r.integers(1, 4))
    host.refine_msa(remove_cons_gaps=False)
    dev.refine_msa(remove_cons_gaps=False, device=True, mesh=mesh)
    assert "refine" in calls, "sharded refine phases not invoked"
    assert "counts" in calls, "sharded consensus counts not invoked"
    assert bytes(dev.consensus) == bytes(host.consensus)
    for sh, sd in zip(host.seqs, dev.seqs):
        assert (sh.clp5, sh.clp3) == (sd.clp5, sd.clp3)


def test_stranded_deleted_base_raises_on_both_paths():
    """A deleted base whose collapsed column falls before the layout
    start is uncountable: the host scatter would wrap the negative
    index and the device matrix has no cell for it.  Both build paths
    must refuse loudly rather than drift."""
    from pwasm_tpu.core.errors import PwasmError

    def _strand(m):
        lead = min(m.seqs, key=lambda s: s.offset)
        # ensure no gap run can absorb the deletion, then delete the
        # very first base of the leftmost member: its column collapses
        # to offset-minoffset-1 == -1, outside the layout
        lead.set_gap(0, 0)
        lead.remove_base(0)

    for device in (False, True):
        msa = _random_msa(0)
        _strand(msa)
        with pytest.raises(PwasmError, match="outside the layout"):
            msa.build_msa(device=device)
