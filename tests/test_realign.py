"""Tests for the banded DP re-aligner (ops/realign.py).

Parity contract: the device traceback must be *identical* (ops, not just
score) to the host oracle ``full_gotoh_traceback`` whenever the band
covers the full matrix, and must always emit a path that (a) consumes
exactly (q_len, t_len) bases and (b) re-scores to the DP score.
"""

import numpy as np
import pytest

from pwasm_tpu.core.dna import encode
from pwasm_tpu.ops.banded_dp import ScoreParams, banded_scores_batch
from pwasm_tpu.ops.realign import (banded_traceback_batch,
                                   full_gotoh_traceback, ops_consumed,
                                   ops_forward, ops_score, ops_to_gaps,
                                   realign_pairs)


def _mutate(rng, q, n_subs, n_indels, maxgap=3):
    t = list(q)
    for _ in range(n_subs):
        p = int(rng.integers(0, len(t)))
        t[p] = int(rng.integers(0, 4))
    for _ in range(n_indels):
        p = int(rng.integers(1, max(2, len(t) - 1)))
        g = int(rng.integers(1, maxgap + 1))
        if rng.random() < 0.5:
            for _ in range(g):
                t.insert(p, int(rng.integers(0, 4)))
        else:
            del t[p:p + g]
    return np.array(t, dtype=np.int8)


def test_oracle_self_consistency():
    rng = np.random.default_rng(0)
    for _ in range(20):
        m = int(rng.integers(5, 40))
        q = rng.integers(0, 4, m).astype(np.int8)
        t = _mutate(rng, q, 3, 2)
        score, ops = full_gotoh_traceback(q, t)
        assert ops_consumed(ops) == (len(q), len(t))
        assert ops_score(ops, q, t) == score


def test_device_matches_oracle_wide_band():
    """Band covering the whole matrix => identical ops to the oracle."""
    rng = np.random.default_rng(1)
    qs, ts, qls, tls, oracle = [], [], [], [], []
    m_max, n_max, T = 48, 56, 16
    for _ in range(T):
        m = int(rng.integers(8, m_max + 1))
        q = rng.integers(0, 4, m).astype(np.int8)
        t = _mutate(rng, q, 2, 2)[:n_max]
        oracle.append(full_gotoh_traceback(q, t))
        qs.append(np.pad(q, (0, m_max - len(q)), constant_values=127))
        ts.append(np.pad(t, (0, n_max - len(t)), constant_values=127))
        qls.append(len(q))
        tls.append(len(t))
    band = 256  # covers every diagonal of a 48x56 matrix (dlo = -128)
    scores, ops_bwd, ok = banded_traceback_batch(
        np.stack(qs), np.stack(ts), np.array(qls, np.int32),
        np.array(tls, np.int32), band=band)
    scores, ops_bwd, ok = (np.asarray(scores), np.asarray(ops_bwd),
                           np.asarray(ok))
    for k in range(T):
        want_score, want_ops = oracle[k]
        assert bool(ok[k]), k
        assert int(scores[k]) == want_score, k
        np.testing.assert_array_equal(ops_forward(ops_bwd[k]), want_ops,
                                      err_msg=f"lane {k}")


def test_device_narrow_band_invariants():
    """With a narrow band the path may differ from the unbanded optimum,
    but it must consume exact lengths and re-score to the DP score —
    and the DP score must equal the scores-only kernel's."""
    rng = np.random.default_rng(2)
    m, T = 300, 24
    q = rng.integers(0, 4, m).astype(np.int8)
    n = m + 16
    ts = np.full((T, n), 127, dtype=np.int8)
    tls = np.zeros(T, dtype=np.int32)
    for k in range(T):
        t = _mutate(rng, q, 6, 3)[:n]
        ts[k, :len(t)] = t
        tls[k] = len(t)
    band = 32
    qs = np.broadcast_to(q, (T, m)).copy()
    qls = np.full(T, m, dtype=np.int32)
    scores, ops_bwd, ok = banded_traceback_batch(qs, ts, qls, tls,
                                                 band=band)
    scores, ops_bwd, ok = (np.asarray(scores), np.asarray(ops_bwd),
                           np.asarray(ok))
    # score parity vs the scores-only kernel (shared query, same band
    # placement: dlo = -(band//2))
    from pwasm_tpu.ops.banded_dp import band_dlo  # noqa: F401
    want = np.asarray(banded_scores_batch(q, ts, tls, band=band))
    for k in range(T):
        assert bool(ok[k]), k
        ops = ops_forward(ops_bwd[k])
        assert ops_consumed(ops) == (m, int(tls[k])), k
        assert ops_score(ops, q, ts[k]) == int(scores[k]), k
    # banded_scores_batch centers the band differently (band_dlo uses
    # n - m); only compare lanes where both placements cover the path
    # fully — here n - m = 16 and band = 32 makes the two dlo values
    # differ, so compare against a matched-dlo run instead
    scores2, _, _ = banded_traceback_batch(
        qs, ts, qls, tls, band=band,
        dlo=band_dlo(m, n, band))
    np.testing.assert_array_equal(np.asarray(scores2), want)


def test_ops_to_gaps_matches_cigar_walk():
    """DP re-alignment of a synthesized PAF alignment reproduces the
    CIGAR walk's gap records exactly (unique-optimum construction)."""
    import sys
    sys.path.insert(0, "tests")
    from helpers import make_paf_line

    from pwasm_tpu.core.paf import parse_paf_line
    from pwasm_tpu.core.events import extract_alignment
    from pwasm_tpu.core.dna import revcomp

    # seed chosen so the synthesized alignment is the unique optimum
    # (gap junctions can't slide at equal score) — verified by the
    # oracle-agreement assertion below
    rng = np.random.default_rng(0)
    q = "".join("ACGT"[i] for i in rng.integers(0, 4, 120))
    for strand in ("+", "-"):
        line, _ = make_paf_line(
            "q", q, "t1", strand,
            [("=", 30), ("ins", "TT"), ("=", 40), ("del", 3), ("=", 47)])
        rec = parse_paf_line(line)
        refseq_aln = revcomp(q.encode()) if strand == "-" else q.encode()
        aln = extract_alignment(rec, refseq_aln)
        al = rec.alninfo
        q_seg = refseq_aln[aln.offset:
                           aln.offset + (al.r_alnend - al.r_alnstart)]
        [(score, ops)] = realign_pairs([(q_seg, aln.tseq)], band=64)
        want_score, want_ops = full_gotoh_traceback(
            encode(q_seg.upper()), encode(bytes(aln.tseq).upper()))
        np.testing.assert_array_equal(ops, want_ops, err_msg=strand)
        eff_t_len = al.t_alnend - al.t_alnstart
        rgaps, tgaps = ops_to_gaps(ops, aln.offset, al.r_len, eff_t_len,
                                   al.reverse)
        assert [(g.pos, g.len) for g in rgaps] == \
            [(g.pos, g.len) for g in aln.rgaps], strand
        assert [(g.pos, g.len) for g in tgaps] == \
            [(g.pos, g.len) for g in aln.tgaps], strand


def test_realign_pairs_band_fallback():
    """A pair whose length difference exceeds the band falls back to the
    host oracle and still returns an exact path."""
    rng = np.random.default_rng(4)
    q = rng.integers(0, 4, 64).astype(np.int8)
    t = np.concatenate([q[:32], rng.integers(0, 4, 100).astype(np.int8),
                        q[32:]])
    qb = bytes(b"ACGT"[c] for c in q)
    tb = bytes(b"ACGT"[c] for c in t)
    [(score, ops)] = realign_pairs([(qb, tb)], band=16)
    want_score, want_ops = full_gotoh_traceback(q, t.astype(np.int8))
    assert score == want_score
    np.testing.assert_array_equal(ops, want_ops)


@pytest.mark.parametrize("seed", [8, 9])
@pytest.mark.parametrize("reverse", [0, 1])
def test_device_gap_extraction_matches_ops_to_gaps(seed, reverse):
    """realign_gaps_batch's on-device gap slots must reproduce
    ops_to_gaps over the expanded op string exactly, both strands."""
    from pwasm_tpu.ops.realign import (gap_slots_to_gapdata,
                                       realign_gaps_batch,
                                       rows_to_ops_fwd,
                                       banded_realign_rows)

    rng = np.random.default_rng(seed)
    T, m_max, n_max = 12, 160, 200
    qs = np.full((T, m_max), 127, dtype=np.int8)
    ts = np.full((T, n_max), 127, dtype=np.int8)
    qls = np.zeros(T, dtype=np.int32)
    tls = np.zeros(T, dtype=np.int32)
    for k in range(T):
        m = int(rng.integers(30, m_max + 1))
        q = rng.integers(0, 4, m).astype(np.int8)
        t = _mutate(rng, q, int(rng.integers(0, 6)),
                    int(rng.integers(0, 5)))[:n_max]
        qs[k, :m] = q
        ts[k, :len(t)] = t
        qls[k] = m
        tls[k] = len(t)
    band = 48
    scores, ok, slots = realign_gaps_batch(qs, ts, qls, tls, band=band)
    rg_pos, rg_len, r_cnt, tg_pos, tg_len, t_cnt, ovf = \
        (np.asarray(x) for x in slots)
    scores2, leads, iy_runs, ops_rows, ok2 = banded_realign_rows(
        qs, ts, qls, tls, band=band)
    leads, iy_runs, ops_rows = (np.asarray(leads), np.asarray(iy_runs),
                                np.asarray(ops_rows))
    ok = np.asarray(ok)
    assert ok.all()
    for k in range(T):
        offset, r_len = 3, int(qls[k]) + 7
        eff_t_len = int(tls[k])
        fwd = rows_to_ops_fwd(int(leads[k]), iy_runs[k], ops_rows[k],
                              int(qls[k]))
        want_r, want_t = ops_to_gaps(fwd, offset, r_len, eff_t_len,
                                     reverse)
        assert not bool(ovf[k])
        got_r, got_t = gap_slots_to_gapdata(
            rg_pos[k], rg_len[k], int(r_cnt[k]),
            tg_pos[k], tg_len[k], int(t_cnt[k]),
            offset, r_len, eff_t_len, reverse)
        assert [(g.pos, g.len) for g in got_r] == \
            [(g.pos, g.len) for g in want_r], k
        assert [(g.pos, g.len) for g in got_t] == \
            [(g.pos, g.len) for g in want_t], k


def test_gap_extraction_overflow_flag():
    """More gaps than slots must set the overflow flag, not silently
    truncate."""
    from pwasm_tpu.ops.realign import realign_gaps_batch

    rng = np.random.default_rng(10)
    m = 120
    q = rng.integers(0, 4, m).astype(np.int8)
    t = _mutate(rng, q, 0, 30, maxgap=1)  # ~30 separate indel sites
    n = len(t)
    scores, ok, slots = realign_gaps_batch(
        q[None, :], t[None, :n], np.array([m], np.int32),
        np.array([n], np.int32), band=128, max_gaps=4)
    assert bool(np.asarray(slots[6])[0])  # overflow


def test_realign_pairs_length_buckets(monkeypatch):
    """Mixed short/long lanes dispatch in separate shape buckets — one
    long target must not inflate every short lane's tensors (SURVEY
    §7.3 variable-length batching) — and bucketing must not change any
    result."""
    import pwasm_tpu.ops.realign as ra

    shapes = []
    real = ra.banded_realign_rows

    def spy(qs, ts, *a, **k):
        shapes.append((np.asarray(qs).shape, np.asarray(ts).shape))
        return real(qs, ts, *a, **k)

    monkeypatch.setattr(ra, "banded_realign_rows", spy)
    rng = np.random.default_rng(20)
    pairs = []
    for i in range(6):
        m = 3000 if i == 3 else 300
        q = rng.integers(0, 4, m).astype(np.int8)
        t = _mutate(rng, q, 4, 2)
        pairs.append((bytes(b"ACGT"[c] for c in q),
                      bytes(b"ACGT"[c] for c in t)))
    results = ra.realign_pairs(pairs, band=32)
    short = [s for s in shapes if s[0][1] <= 512]
    long_ = [s for s in shapes if s[0][1] >= 2944]
    assert short and long_ and len(short) + len(long_) == len(shapes)
    assert all(s[0][0] == 5 for s in short)   # 5 short lanes together
    assert all(s[0][0] == 1 for s in long_)   # the long lane alone
    for p, r in zip(pairs, results):
        [(s1, o1)] = ra.realign_pairs([p], band=32)
        assert r[0] == s1
        np.testing.assert_array_equal(r[1], o1)


@pytest.mark.parametrize("kernel", ["pallas", "pallas_long"])
@pytest.mark.parametrize("seed", [11, 12, 13])
def test_pallas_rowwalk_matches_xla(seed, kernel):
    """The fused Pallas forward+walk kernels — resident AND
    HBM-streaming — must be bit-identical to the XLA scan path: scores,
    leads, per-row runs/ops, ok."""
    from pwasm_tpu.ops.realign import banded_realign_rows

    rng = np.random.default_rng(seed)
    T, m_max, n_max = 20, 100, 120
    qs = np.full((T, m_max), 127, dtype=np.int8)
    ts = np.full((T, n_max), 127, dtype=np.int8)
    qls = np.zeros(T, dtype=np.int32)
    tls = np.zeros(T, dtype=np.int32)
    for k in range(T):
        m = int(rng.integers(10, m_max + 1))
        q = rng.integers(0, 4, m).astype(np.int8)
        t = _mutate(rng, q, int(rng.integers(0, 8)),
                    int(rng.integers(0, 5)))[:n_max]
        qs[k, :m] = q
        ts[k, :len(t)] = t
        qls[k] = m
        tls[k] = len(t)
    for band in (16, 32):
        ref = banded_realign_rows(qs, ts, qls, tls, band=band,
                                  kernel="xla")
        got = banded_realign_rows(qs, ts, qls, tls, band=band,
                                  kernel=kernel)
        names = ("scores", "leads", "iy_runs", "ops_rows", "ok")
        for name, a, b in zip(names, ref, got):
            ar, br = np.asarray(a), np.asarray(b)
            if name in ("iy_runs", "ops_rows"):
                # rows past q_len / non-ok lanes are don't-cares
                okm = np.asarray(ref[4])
                live = (np.arange(ar.shape[1])[None, :]
                        < np.asarray(qls)[:, None]) & okm[:, None]
                ar, br = ar * live, br * live
            np.testing.assert_array_equal(ar, br,
                                          err_msg=f"{name} band={band}")


@pytest.mark.parametrize("kernel", ["pallas", "pallas_long"])
def test_pallas_interior_blocks_match_xla(kernel):
    """Geometry with MANY fully-interior 8-row blocks (the forward
    kernels' mask-elided branch): pinned so the elided body provably
    executes, bit-identical to the XLA scan."""
    from pwasm_tpu.ops.banded_dp import band_dlo
    from pwasm_tpu.ops.realign import banded_realign_rows

    m, n_max, band = 256, 272, 32   # n-m = band/2: band covers 0..16
    dlo = band_dlo(m, n_max, band)
    # at least one 8-row block entirely inside [1-dlo, n-band-dlo+1]
    lo = max(0, -dlo)           # 0-based first interior row index
    hi = n_max - band - dlo + 1 - 8
    assert hi - lo >= 16, "geometry no longer pins interior blocks"
    rng = np.random.default_rng(21)
    T = 12
    qs = np.full((T, m), 127, dtype=np.int8)
    ts = np.full((T, n_max), 127, dtype=np.int8)
    qls = np.zeros(T, dtype=np.int32)
    tls = np.zeros(T, dtype=np.int32)
    for k in range(T):
        q = rng.integers(0, 4, m).astype(np.int8)
        t = _mutate(rng, q, int(rng.integers(0, 10)),
                    int(rng.integers(0, 6)))[:n_max]
        qs[k] = q
        ts[k, :len(t)] = t
        qls[k] = m
        tls[k] = len(t)
    ref = banded_realign_rows(qs, ts, qls, tls, band=band, kernel="xla")
    got = banded_realign_rows(qs, ts, qls, tls, band=band, kernel=kernel)
    for name, a, b in zip(("scores", "leads", "iy", "ops", "ok"),
                          ref, got):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=name)


def test_sharded_realign_matches_unsharded():
    """Lanes sharded over the virtual 8-device mesh produce bit-identical
    compressed rows to the single-device call — the --shard realign
    path (no collectives; pure lane parallelism)."""
    import jax

    from pwasm_tpu.parallel.mesh import make_mesh
    from pwasm_tpu.ops.realign import (banded_realign_rows,
                                       sharded_realign_rows)

    assert len(jax.devices()) >= 8
    mesh = make_mesh(8)
    rng = np.random.default_rng(21)
    T, m_max, n_max = 21, 120, 140   # deliberately not a mesh multiple
    qs = np.full((T, m_max), 127, dtype=np.int8)
    ts = np.full((T, n_max), 127, dtype=np.int8)
    qls = np.zeros(T, dtype=np.int32)
    tls = np.zeros(T, dtype=np.int32)
    for k in range(T):
        m = int(rng.integers(30, m_max + 1))
        q = rng.integers(0, 4, m).astype(np.int8)
        t = _mutate(rng, q, 3, 2)[:n_max]
        qs[k, :m] = q
        ts[k, :len(t)] = t
        qls[k] = m
        tls[k] = len(t)
    ref = banded_realign_rows(qs, ts, qls, tls, band=32)
    got = sharded_realign_rows(mesh, qs, ts, qls, tls, band=32)
    for name, a, b in zip(("scores", "leads", "iy", "ops", "ok"),
                          ref, got):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=name)


@pytest.mark.parametrize("seed", [5, 6, 7])
def test_randomized_path_validity(seed):
    """Fuzz: random lengths/mutations, mixed lanes; every ok lane's path
    is length-exact and score-exact."""
    rng = np.random.default_rng(seed)
    pairs = []
    for _ in range(12):
        m = int(rng.integers(20, 200))
        q = rng.integers(0, 4, m).astype(np.int8)
        t = _mutate(rng, q, int(rng.integers(0, 8)),
                    int(rng.integers(0, 4)))
        pairs.append((bytes(b"ACGT"[c] for c in q),
                      bytes(b"ACGT"[c] for c in t)))
    results = realign_pairs(pairs, band=32)
    for (qb, tb), (score, ops) in zip(pairs, results):
        qc = encode(qb)
        tc = encode(tb)
        assert ops_consumed(ops) == (len(qc), len(tc))
        assert ops_score(ops, qc, tc) == score
