"""parallel/bucketing.py — the shared variable-length batching policy
(VERDICT r4 item 7 / weak #5): property tests over ragged length
distributions, plus ragged entry points for many2many and the
sequence-parallel wavefront that previously rejected indivisible
shapes outright."""

import numpy as np
import pytest

from pwasm_tpu.core.dna import encode
from pwasm_tpu.parallel.bucketing import (PAD, Bucket, bucket_queries,
                                          bucket_targets, group_by_shape,
                                          round_up, scatter_results)

BASES = np.array(list(b"ACGT"), dtype=np.uint8)


def _rand_seqs(rng, n, lo, hi):
    return [bytes(rng.choice(BASES, size=rng.integers(lo, hi)))
            for _ in range(n)]


@pytest.mark.parametrize("seed,n,lo,hi,step,mult", [
    (0, 40, 1, 50, 16, 1),
    (1, 80, 1, 700, 128, 1),
    (2, 64, 30, 3000, 128, 4),
    (3, 1, 5, 6, 128, 8),
    (4, 33, 200, 201, 64, 2),       # all one bucket, odd count
])
def test_bucket_targets_properties(seed, n, lo, hi, step, mult):
    rng = np.random.default_rng(seed)
    seqs = _rand_seqs(rng, n, lo, hi)
    buckets = bucket_targets(seqs, step=step, batch_multiple=mult)
    seen = []
    for b in buckets:
        assert b.width % step == 0 and b.width >= step
        assert b.data.shape[0] % mult == 0
        assert b.data.shape == (len(b.idx), b.width)
        for row, ln, ix in zip(b.data, b.lens, b.idx):
            if ix < 0:
                assert ln == 0 and (row == PAD).all()
                continue
            seen.append(int(ix))
            s = encode(seqs[ix].upper())
            assert ln == len(s) <= b.width
            # the bucket is the TIGHT step-rounding of this length
            assert b.width == round_up(len(s), step)
            assert (row[:ln] == s).all()
            assert (row[ln:] == PAD).all()
    assert sorted(seen) == list(range(n))       # each seq exactly once

    # scatter restores input order
    results = [b.lens.astype(np.int64) * 2 for b in buckets]
    got = scatter_results(buckets, results, n)
    want = np.array([len(s) * 2 for s in seqs])
    assert (got == want).all()


def test_bucket_queries_exact_lengths():
    rng = np.random.default_rng(7)
    seqs = _rand_seqs(rng, 30, 3, 40)
    buckets = bucket_queries(seqs, batch_multiple=4)
    for b in buckets:
        live = b.idx >= 0
        assert (b.lens[live] == b.width).all()   # exact, not padded
        assert b.data.shape[0] % 4 == 0
    assert sorted(int(i) for b in buckets for i in b.idx if i >= 0) \
        == list(range(30))


def test_group_by_shape_matches_realign_buckets():
    shapes = [(5, 7), (130, 7), (128, 128), (129, 129)]
    g = group_by_shape(shapes, step=128)
    assert g == {(128, 128): [0, 2], (256, 128): [1],
                 (256, 256): [3]}


def test_scatter_rejects_mismatched_rows():
    b = bucket_targets([b"ACGT"])[0]
    with pytest.raises(ValueError):
        scatter_results([b], [np.zeros(b.data.shape[0] + 1)], 1)


def test_scatter_empty_buckets_preserves_shape_and_dtype():
    """ADVICE round 5: the empty-buckets fallback must agree with the
    non-empty calls' trailing dimensions and dtype instead of handing
    back a 1-D default-dtype array."""
    out = scatter_results([], [], 3, fill=-1, trailing_shape=(4, 2),
                          dtype=np.int32)
    assert out.shape == (3, 4, 2)
    assert out.dtype == np.int32
    assert (out == -1).all()
    # the defaults keep the old 1-D call shape for scalar-row results
    out = scatter_results([], [], 2, fill=0, dtype=np.int64)
    assert out.shape == (2,) and out.dtype == np.int64
    # and a non-empty call still derives everything from per_bucket
    b = bucket_targets([b"ACGT", b"AAAA"])[0]
    r = np.ones((b.data.shape[0], 5), dtype=np.int16)
    out = scatter_results([b], [r], 2, trailing_shape=(9,),
                          dtype=np.float64)   # ignored: results exist
    assert out.shape == (2, 5) and out.dtype == np.int16


def test_many2many_ragged_matches_pairwise():
    """Ragged wrapper == per-pair banded_score over every (q, t)."""
    import jax.numpy as jnp

    from pwasm_tpu.ops.banded_dp import banded_score
    from pwasm_tpu.parallel.many2many import many2many_scores_ragged

    rng = np.random.default_rng(11)
    band = 64
    qs = _rand_seqs(rng, 5, 10, 60)
    # targets deliberately span BOTH width groups: shorter than every
    # query (the dlo=-band//2 placement) through much longer (clipped)
    ts = _rand_seqs(rng, 9, 2, 200)
    got = many2many_scores_ragged(qs, ts, band=band)
    for i, q in enumerate(qs):
        qe = encode(q.upper())
        m = len(qe)
        for j, t in enumerate(ts):
            te = encode(t.upper())
            # the width group the wrapper dispatches this pair in
            n_eff = m if len(te) <= m else m + band - 2
            tp = np.full(n_eff, PAD, dtype=np.int8)
            tp[:min(len(te), n_eff)] = te[:n_eff]
            want = int(banded_score(jnp.asarray(qe), jnp.asarray(tp),
                                    jnp.asarray(len(te)), band=band))
            assert got[i, j] == want, (i, j)
    # short targets within band//2 of the query must produce REAL
    # scores (the single-width n_eff = m+band-2 design NEG'd them all)
    from pwasm_tpu.ops.banded_dp import NEG
    live = 0
    for i, q in enumerate(qs):
        for j, t in enumerate(ts):
            if 0 <= len(q) - len(t) <= band // 2:
                assert got[i, j] != NEG, (i, j)
                live += 1
    assert live > 0


def test_many2many_ragged_on_mesh():
    """Mesh path (batch counts NOT dividing the mesh factors) equals
    the unsharded ragged result."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 virtual devices")
    from pwasm_tpu.parallel.many2many import (make_mesh2d,
                                              many2many_scores_ragged)

    rng = np.random.default_rng(13)
    qs = _rand_seqs(rng, 3, 20, 21)     # 3 !% mesh query axis
    ts = _rand_seqs(rng, 5, 10, 300)    # 5 !% mesh target axis
    mesh = make_mesh2d(4)
    got = many2many_scores_ragged(qs, ts, band=64, mesh=mesh)
    want = many2many_scores_ragged(qs, ts, band=64)
    assert (got == want).all()


def test_wavefront_sp_indivisible_query_length():
    """A query length that does not divide the seq-mesh axis now works
    (padded + masked) and is bit-exact with the single-chip scan."""
    import jax
    import jax.numpy as jnp

    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 virtual devices")
    from jax.sharding import Mesh

    from pwasm_tpu.ops.banded_dp import banded_scores_batch
    from pwasm_tpu.parallel.wavefront_sp import wavefront_sp_scores

    rng = np.random.default_rng(17)
    m = 37                               # 37 % 4 != 0
    q = rng.integers(0, 4, size=m).astype(np.int8)
    T, n = 6, 64
    ts = np.full((T, n), PAD, dtype=np.int8)
    t_lens = np.zeros(T, dtype=np.int32)
    for k in range(T):
        ln = int(rng.integers(m - 5, m + 5))
        ts[k, :ln] = rng.integers(0, 4, size=ln)
        t_lens[k] = ln
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    got = np.asarray(wavefront_sp_scores(
        jnp.asarray(q), jnp.asarray(ts), jnp.asarray(t_lens), mesh))
    want = np.asarray(banded_scores_batch(
        jnp.asarray(q), jnp.asarray(ts), jnp.asarray(t_lens)))
    assert (got == want).all()


def _gotoh_global(q, t, match=2, mismatch=4, go=6, ge=2):
    """Independent full-matrix affine-gap global DP (numpy Gotoh) —
    shares NO code or width/band policy with the library under test."""
    NEGI = -(2 ** 30)
    m, n = len(q), len(t)
    M = np.full((m + 1, n + 1), NEGI, dtype=np.int64)
    Ix = np.full((m + 1, n + 1), NEGI, dtype=np.int64)  # gap in t (up)
    Iy = np.full((m + 1, n + 1), NEGI, dtype=np.int64)  # gap in q (left)
    M[0, 0] = 0
    for j in range(1, n + 1):
        Iy[0, j] = -(go + (j - 1) * ge)
    for i in range(1, m + 1):
        Ix[i, 0] = -(go + (i - 1) * ge)
    for i in range(1, m + 1):
        for j in range(1, n + 1):
            s = match if q[i - 1] == t[j - 1] else -mismatch
            best = max(M[i - 1, j - 1], Ix[i - 1, j - 1],
                       Iy[i - 1, j - 1])
            M[i, j] = best + s if best > NEGI else NEGI
            Ix[i, j] = max(M[i - 1, j] - go, Ix[i - 1, j] - ge)
            Iy[i, j] = max(M[i, j - 1] - go, Iy[i, j - 1] - ge)
    return int(max(M[m, n], Ix[m, n], Iy[m, n]))


def test_many2many_ragged_matches_independent_full_dp():
    """Independent oracle (VERDICT-style): for sequences small enough
    that band=64 covers the ENTIRE DP matrix under both width-group
    placements, the ragged wrapper must equal a from-scratch full
    Gotoh DP — this catches a systematically wrong width/clipping
    policy that the self-consistent per-pair oracle cannot."""
    from pwasm_tpu.parallel.many2many import many2many_scores_ragged

    rng = np.random.default_rng(23)
    # Only the SHORT group (t <= m) is a fair full-DP comparison: its
    # placement (dlo=-band//2) covers every diagonal an optimal path
    # over <=20-base pairs can visit ([-20, 20] within [-32, 31]).
    # t > m pairs are dispatched at dlo=-1, which clips INTERIOR
    # diagonals below -1 — paths dipping left of the main diagonal
    # legitimately score differently from the unbanded DP there, so
    # they are excluded rather than "verified" vacuously.
    qs = _rand_seqs(rng, 6, 12, 21)
    ts = _rand_seqs(rng, 10, 4, 13)     # every t shorter than every q
    got = many2many_scores_ragged(qs, ts, band=64)
    checked = 0
    for i, q in enumerate(qs):
        for j, t in enumerate(ts):
            if len(t) > len(q):
                continue
            want = _gotoh_global(q.upper(), t.upper())
            assert got[i, j] == want, (i, j, len(q), len(t))
            checked += 1
    assert checked == len(qs) * len(ts)


def test_pad_to_width_truncation_contract():
    from pwasm_tpu.parallel.bucketing import pad_to_width

    seqs = [b"ACGT", b"ACGTACGTACGT"]          # 4 and 12 bases
    with pytest.raises(ValueError):
        pad_to_width(seqs, 8)                   # overflow w/o truncate
    b = pad_to_width(seqs, 8, truncate=True, batch_multiple=4)
    assert b.data.shape == (4, 8)
    assert list(b.lens[:2]) == [4, 12]          # TRUE length kept
    assert (b.data[1] == encode(b"ACGTACGT")).all()  # data clipped
    assert (b.data[0][:4] == encode(b"ACGT")).all()
    assert (b.data[0][4:] == PAD).all()
    assert list(b.idx) == [0, 1, -1, -1]        # filler marked
