"""Test configuration.

JAX tests run on CPU with 8 virtual devices so multi-chip sharding and ICI
collectives are exercised without TPU hardware (SURVEY.md §4: multi-chip
tests via ``--xla_force_host_platform_device_count``).

This environment routes jax to a remote TPU chip through a tunnel backend
('axon') that a sitecustomize hook registers at interpreter startup —
*before* this file runs, with jax already imported.  Initializing that
backend inside the test run would grab/hang on the tunnel, so we force the
cpu platform via jax.config (env vars are too late once jax is imported)
and drop every non-cpu backend factory.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # no tunnel for subprocesses
os.environ.setdefault("PWASM_JAX_CACHE", "0")  # tests must not arm the
#                       process-global persistent compilation cache
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    # only the tunnel backend is dangerous to initialize; 'tpu' must remain
    # a *known* platform (pallas registers tpu lowering rules at import
    # time) but jax_platforms=cpu keeps it uninitialized.  Private API —
    # if a jax upgrade moves it, lose the suppression, not the test suite.
    import jax._src.xla_bridge as _xb

    getattr(_xb, "_backend_factories", {}).pop("axon", None)
except Exception:
    pass


def pytest_configure(config):
    # tier-1 runs with `-m "not slow"`; the long fleet drills opt out
    # of it explicitly rather than riding on an unregistered mark
    config.addinivalue_line(
        "markers", "slow: long-running drill, deselected in tier-1")
