"""Test configuration.

JAX tests run on CPU with 8 virtual devices so multi-chip sharding and ICI
collectives are exercised without TPU hardware (SURVEY.md §4: multi-chip
tests via ``--xla_force_host_platform_device_count``).  The env vars must be
set before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
