"""qa/chip_burst.py contracts that must not regress silently: the
env scrub (a lingering operator ``PWASM_*`` knob must never poison a
burst step) and the ``--wait`` argument surface."""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def chip_burst():
    for p in (REPO, os.path.join(REPO, "qa")):
        if p not in sys.path:
            sys.path.insert(0, p)
    import chip_burst as cb
    return cb


def test_env_scrub_strips_all_pwasm_knobs(chip_burst):
    # the satellite contract: not just PWASM_BENCH_*/PWASM_DP_* — ANY
    # run-behavior PWASM_* knob (fault injection, host-engine escape
    # hatch, probe opt-outs) is stripped, while the backend-selecting
    # env passes through
    poisoned = {
        "PWASM_BENCH_CONFIG": "4",
        "PWASM_DP_IYCHAIN": "log",
        "PWASM_INJECT_FAULTS": "rate=1,kinds=raise",
        "PWASM_HOST_COLUMNAR": "0",
        "PWASM_NATIVE_MSA": "0",
        "PWASM_DEVICE_PROBE": "0",
        "PWASM_DEVICE_PROBE_TIMEOUT": "1",
        "PWASM_JAX_CACHE": "0",
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "10.0.0.1",
        "PATH": "/usr/bin",
        "HOME": "/root",
    }
    out = chip_burst._scrub_env(poisoned)
    assert not any(k.startswith("PWASM_")
                   and k not in chip_burst._SCRUB_KEEP
                   for k in out), out
    # probe TUNING (bounds on the health checks, no result impact)
    # survives: a slow tunnel needs the operator's raised timeout
    assert out["PWASM_DEVICE_PROBE_TIMEOUT"] == "1"
    # ...but the probe OPT-OUT is run behavior and is scrubbed
    assert "PWASM_DEVICE_PROBE" not in out
    for keep in ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS", "PATH",
                 "HOME"):
        assert out[keep] == poisoned[keep]


def test_parse_wait(chip_burst):
    assert chip_burst._parse_wait([]) is None
    assert chip_burst._parse_wait(["--wait"]) == 3600.0
    assert chip_burst._parse_wait(["--wait=90"]) == 90.0
    assert chip_burst._parse_wait(["--wait=0"]) == 0.0
    for bad in (["--wait=x"], ["--wait=-5"], ["--wait=nan"]):
        with pytest.raises(SystemExit):
            chip_burst._parse_wait(bad)


def test_wait_interrupt_exits_documented_code(chip_burst, monkeypatch,
                                              capsys):
    """Ctrl-C while blocking on --wait must exit with the documented
    interrupted status (130 = 128+SIGINT), not spill a KeyboardInterrupt
    traceback into a cron log."""
    import pwasm_tpu.resilience.health as health

    def interrupted(*a, **k):
        raise KeyboardInterrupt

    monkeypatch.setattr(health, "wait_for_backend", interrupted)
    rc = chip_burst.main(["--wait=30"])
    assert rc == 130
    err = capsys.readouterr().err
    assert "interrupted" in err
