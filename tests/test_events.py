"""Diff-event extraction tests: hand-worked cases + an independent
apply-the-events oracle over randomized alignments in both orientations."""

import numpy as np
import pytest

from pwasm_tpu.core.dna import revcomp
from pwasm_tpu.core.errors import PwasmError
from pwasm_tpu.core.events import extract_alignment
from pwasm_tpu.core.paf import parse_paf_line

from helpers import make_paf_line, reverse_ops

Q = "ACGTACGTAC"


def _extract(line, q_seq=Q):
    rec = parse_paf_line(line)
    q = q_seq.upper().encode()
    refseq_aln = revcomp(q) if rec.alninfo.reverse else q
    # pin the pure-Python path; native parity is covered by test_native.py
    return extract_alignment(rec, refseq_aln, use_native=False)


def test_forward_worked_example():
    ops = [("=", 3), ("*", "a", "t"), ("=", 2), ("ins", "gg"),
           ("del", 2), ("=", 2)]
    line, tseq = make_paf_line("q", Q, "t", "+", ops)
    aln = _extract(line)
    assert aln.tseq == b"ACGaACggAC"
    assert tseq == "ACGAACGGAC"
    assert [e.evt for e in aln.tdiffs] == ["S", "I", "D"]
    s, ins, de = aln.tdiffs
    assert (s.rloc, s.tloc, s.evtbases, s.evtsub, s.evtlen) == (3, 3, b"A", b"T", 1)
    assert s.tctx == b"ACGaACggA"
    assert (ins.rloc, ins.tloc, ins.evtbases, ins.evtlen) == (6, 6, b"gg", 2)
    assert ins.tctx == b"CGaACggA"
    assert (de.rloc, de.tloc, de.evtbases, de.evtlen) == (6, 8, b"GT", 2)
    assert de.tctx == b"aACggA"
    # CIGAR-derived gap lists: target gap where the query has extra bases,
    # query gap where the target has extra bases
    assert [(g.pos, g.len) for g in aln.rgaps] == [(6, 2)]
    assert [(g.pos, g.len) for g in aln.tgaps] == [(8, 2)]


def test_reverse_worked_example():
    ops = [("=", 4), ("*", "c", "g"), ("=", 5)]
    line, _ = make_paf_line("q", Q, "t", "-", ops)
    aln = _extract(line)
    assert aln.tseq == b"GTACcTACGT"  # reconstructed in alignment orientation
    (s,) = aln.tdiffs
    assert (s.evt, s.rloc, s.evtbases, s.evtsub) == ("S", 5, b"G", b"C")
    assert s.tloc == 6
    assert s.tctx == b"CGTAgGTAC"


def test_adjacent_substitutions_merge():
    ops = [("=", 2), ("*", "t", "g"), ("*", "a", "t"), ("=", 6)]
    line, _ = make_paf_line("q", Q, "t", "+", ops)
    aln = _extract(line)
    (s,) = aln.tdiffs
    assert (s.evt, s.rloc, s.evtbases, s.evtsub) == ("S", 2, b"TA", b"GT")
    assert s.evtlen == 1  # reference quirk: evtlen not updated on merge
    # context window therefore spans evtlen=1, not 2 (SURVEY.md §2.5.5)
    assert s.tctx == aln.tseq[0:2 + 1 + 5]


def test_substitutions_separated_dont_merge():
    ops = [("=", 2), ("*", "t", "g"), ("=", 1), ("*", "t", "a"), ("=", 5)]
    line, _ = make_paf_line("q", Q, "t", "+", ops)
    aln = _extract(line)
    assert [e.rloc for e in aln.tdiffs] == [2, 4]


def test_partial_alignment_offset():
    # align only q[2:8]
    ops = [("=", 2), ("*", "g", "a"), ("=", 3)]
    line, _ = make_paf_line("q", Q, "t", "+", ops, q_start=2, q_end=8)
    aln = _extract(line)
    assert aln.offset == 2
    (s,) = aln.tdiffs
    assert s.rloc == 4  # forward-query coordinate
    assert s.evtsub == Q[4].encode()


def test_base_mismatch_fatal():
    ops = [("=", 3), ("*", "a", "t"), ("=", 6)]
    line, _ = make_paf_line("q", Q, "t", "+", ops)
    line = line.replace("*at", "*ag")  # q base in cs contradicts the FASTA
    with pytest.raises(PwasmError, match="base mismatch"):
        _extract(line)


def test_splice_fatal():
    line, _ = make_paf_line("q", Q, "t", "+", [("=", 10)])
    line = line.replace("cs:Z::10", "cs:Z::5~gt4ac:5")
    with pytest.raises(PwasmError, match="spliced"):
        _extract(line)


def test_length_cross_validation():
    line, _ = make_paf_line("q", Q, "t", "+", [("=", 10)])
    bad = line.replace("cg:Z:10M", "cg:Z:9M")
    with pytest.raises(PwasmError, match="length mismatch"):
        _extract(bad)


def test_missing_cigar_fatal():
    line, _ = make_paf_line("q", Q, "t", "+", [("=", 10)])
    line = "\t".join(f for f in line.split("\t") if not f.startswith("cg:Z:"))
    with pytest.raises(PwasmError, match="cigar"):
        _extract(line)


# ---------------------------------------------------------------------------
# Independent oracle: applying the reported events to the forward query must
# reproduce the forward-orientation target, for both strands.
# ---------------------------------------------------------------------------
def _apply_events(q_fwd: bytes, events, q_start: int, q_end: int) -> bytes:
    seq = bytearray(q_fwd)
    delta = 0
    # At a shared rloc the insertion point precedes the S/D bases, so apply
    # S/D first while walking right-to-left.
    for ev in sorted(events, key=lambda e: (e.rloc, 0 if e.evt == "I" else 1),
                     reverse=True):
        if ev.evt == "S":
            seq[ev.rloc:ev.rloc + len(ev.evtbases)] = ev.evtbases.upper()
        elif ev.evt == "I":
            seq[ev.rloc:ev.rloc] = ev.evtbases.upper()
            delta += len(ev.evtbases)
        else:
            del seq[ev.rloc:ev.rloc + ev.evtlen]
            delta -= ev.evtlen
    return bytes(seq[q_start:q_end + delta])


def _random_ops(rng, q_aln: str):
    ops = []
    pos = 0
    n = len(q_aln)
    ops.append(("=", 3))
    pos += 3
    while pos < n - 6:
        kind = rng.choice(["=", "*", "ins", "del"], p=[0.5, 0.25, 0.125, 0.125])
        if kind == "=":
            run = int(rng.integers(1, 8))
            run = min(run, n - 6 - pos)
            if run <= 0:
                break
            ops.append(("=", run))
            pos += run
        elif kind == "*":
            qb = q_aln[pos]
            tb = rng.choice([b for b in "ACGT" if b != qb.upper()])
            ops.append(("*", tb, qb))
            pos += 1
        elif kind == "ins":
            bases = "".join(rng.choice(list("ACGT"),
                                       size=int(rng.integers(1, 5))))
            ops.append(("ins", bases))
            # guarantee separation so indels never touch the edges
            run = min(2, n - 6 - pos)
            if run > 0:
                ops.append(("=", run))
                pos += run
        else:
            dlen = int(rng.integers(1, min(4, n - 6 - pos) + 1))
            ops.append(("del", dlen))
            pos += dlen
    ops.append(("=", n - pos))
    return ops


@pytest.mark.parametrize("strand", ["+", "-"])
@pytest.mark.parametrize("seed", range(8))
def test_random_alignments_event_oracle(strand, seed):
    rng = np.random.default_rng(seed)
    q = "".join(rng.choice(list("ACGT"), size=int(rng.integers(60, 160))))
    q_start = int(rng.integers(0, 10))
    q_end = len(q) - int(rng.integers(0, 10))
    if strand == "-":
        q_aln = revcomp(q.encode()).decode()[len(q) - q_end:len(q) - q_start]
    else:
        q_aln = q[q_start:q_end]
    ops = _random_ops(rng, q_aln)
    line, tseq = make_paf_line("q", q, "t", strand, ops,
                               q_start=q_start, q_end=q_end)
    aln = _extract(line, q)
    # reconstructed target matches the synthesizer's target
    assert aln.tseq.upper() == tseq.encode()
    # events, applied in forward coordinates, reproduce the forward target
    t_fwd = revcomp(tseq.encode()) if strand == "-" else tseq.encode()
    got = _apply_events(q.encode(), aln.tdiffs, q_start, q_end)
    assert got == t_fwd


@pytest.mark.parametrize("seed", range(4))
def test_forward_reverse_event_equivalence(seed):
    """The same biological alignment reported via a '-' PAF line must yield
    identical forward-coordinate events (tloc/tctx excepted, which are
    display-orientation fields)."""
    rng = np.random.default_rng(100 + seed)
    q = "".join(rng.choice(list("ACGT"), size=80))
    ops_fwd = _random_ops(rng, q)
    line_f, tseq_f = make_paf_line("q", q, "t", "+", ops_fwd)
    aln_f = _extract(line_f, q)
    line_r, tseq_r = make_paf_line("q", q, "t", "-", reverse_ops(ops_fwd))
    aln_r = _extract(line_r, q)
    assert revcomp(tseq_r.encode()) == tseq_f.encode()
    ev_f = [(e.evt, e.rloc, e.evtbases.upper(), e.evtsub.upper())
            for e in aln_f.tdiffs]
    ev_r = [(e.evt, e.rloc, e.evtbases.upper(), e.evtsub.upper())
            for e in aln_r.tdiffs]
    assert ev_f == ev_r


def test_invalid_coordinates_raise_cleanly():
    """Corrupted PAF fields (negative or inverted spans) must raise a
    clean PwasmError from the shared guard AND from the native extractor
    called directly — found by fuzzing: the native path previously
    aborted the whole process with std::length_error on an inverted
    target span (reserve of a wrapped size_t)."""
    from pwasm_tpu.native import extract_native, native_available

    line, _ = make_paf_line("q", Q, "t", "+", [("=", 10)])
    f = line.split("\t")
    bad_lines = []
    f2 = f[:]; f2[7] = "5"; f2[8] = "2"; bad_lines.append("\t".join(f2))
    f2 = f[:]; f2[7] = "-3"; bad_lines.append("\t".join(f2))  # neg t start
    f2 = f[:]; f2[3] = "12"; bad_lines.append("\t".join(f2))  # q end>q len
    f2 = f[:]; f2[2] = "9"; f2[3] = "2"; bad_lines.append("\t".join(f2))
    f2 = f[:]; f2[7] = "1000000"; f2[8] = "0"    # huge inverted span:
    bad_lines.append("\t".join(f2))              # the original abort
    for bl in bad_lines:
        rec = parse_paf_line(bl)
        with pytest.raises(PwasmError, match="invalid alignment"):
            extract_alignment(rec, Q.encode())
        if native_available():
            # direct native call (bypasses extract_alignment's guard):
            # the wrapper-level validation must fire, same message
            with pytest.raises(PwasmError, match="invalid alignment"):
                extract_native(rec, Q.encode())
