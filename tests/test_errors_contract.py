"""The error model's exit-code contract (core/errors.py) and the
malformed-input paths the CLI promises to survive.

The reference fails fast with distinct exit codes (SURVEY.md §2.5.12):
usage/fatal = 1, the declared-but-never-raised parse path = 3, a
zero-coverage MSA column = 5.  These tests pin the documented contract
and the --skip-bad-lines behavior on truncated/garbage PAF input.
"""

import io
import json

import pytest

from pwasm_tpu.cli import CliError, run
from pwasm_tpu.core.errors import (EXIT_FATAL, EXIT_PARSE, EXIT_USAGE,
                                   EXIT_ZERO_COVERAGE, ParseError,
                                   PwasmError, ZeroCoverageError)
from pwasm_tpu.core.fasta import write_fasta

from helpers import make_paf_line

Q = "ACGTACGTACGTACGTACGT"


def test_exit_code_constants():
    assert EXIT_USAGE == 1
    assert EXIT_FATAL == 1
    assert EXIT_PARSE == 3
    assert EXIT_ZERO_COVERAGE == 5


def test_exception_exit_codes():
    assert PwasmError("x").exit_code == 1
    assert PwasmError("x", exit_code=7).exit_code == 7
    assert ParseError("x").exit_code == 3
    assert ZeroCoverageError("x").exit_code == 5
    assert CliError("x").exit_code == 1
    # the class hierarchy: both special codes remain PwasmErrors, so
    # the CLI's single except clause routes them to sys.exit
    assert issubclass(ParseError, PwasmError)
    assert issubclass(ZeroCoverageError, PwasmError)


def _inputs(tmp_path, lines):
    fa = tmp_path / "q.fa"
    write_fasta(str(fa), [("q", Q.encode())])
    paf = tmp_path / "in.paf"
    paf.write_text("".join(ln + "\n" for ln in lines))
    return str(paf), str(fa)


def _bad_lines():
    good1, _ = make_paf_line("q", Q, "a1", "+", [("=", len(Q))])
    good2, _ = make_paf_line("q", Q, "a2", "+",
                             [("=", 4), ("ins", "tt"), ("=", 16)])
    truncated = "\t".join(good1.split("\t")[:6])    # cut mid-record
    garbage = "\x00\xff not a paf line at all"
    nocs = "\t".join(good1.split("\t")[:12])        # no cg/cs tags
    return good1, good2, truncated, garbage, nocs


def test_malformed_line_is_fatal_without_skip(tmp_path):
    good1, _good2, truncated, _g, _n = _bad_lines()
    paf, fa = _inputs(tmp_path, [good1, truncated])
    err = io.StringIO()
    rc = run([paf, "-r", fa], stdout=io.StringIO(), stderr=err)
    assert rc == EXIT_FATAL == 1


def test_skip_bad_lines_survives_truncated_and_garbage(tmp_path):
    good1, good2, truncated, garbage, nocs = _bad_lines()
    paf, fa = _inputs(tmp_path,
                      [truncated, good1, garbage, nocs, good2])
    out = io.StringIO()
    err = io.StringIO()
    stats = tmp_path / "s.json"
    rc = run([paf, "-r", fa, "--skip-bad-lines", f"--stats={stats}"],
             stdout=out, stderr=err)
    assert rc == 0
    body = out.getvalue()
    assert ">a1" in body and ">a2" in body
    warnings = [ln for ln in err.getvalue().splitlines()
                if "skipping malformed PAF line" in ln]
    assert len(warnings) == 3
    d = json.loads(stats.read_text())
    assert d["skipped_bad_lines"] == 3
    assert d["alignments"] == 2


def test_fatal_errors_report_exit_code_through_run(tmp_path):
    good1, *_ = _bad_lines()
    paf, fa = _inputs(tmp_path, [good1])
    # usage error → 1
    assert run([paf, "-r", fa, "-G", "-F"],
               stderr=io.StringIO()) == EXIT_USAGE
    # fatal error (bad -c) → 1
    assert run([paf, "-r", fa, "-c", "0"],
               stderr=io.StringIO()) == EXIT_FATAL
    # a PwasmError subclass carries its own exit code out of run():
    # the zero-coverage analog the library reserves exit 5 for
    with pytest.raises(SystemExit) as ei:
        import pwasm_tpu.cli as cli
        orig = cli.run
        try:
            cli.run = lambda argv: (_ for _ in ()).throw(
                ZeroCoverageError("zero-coverage column"))
            cli.main()
        finally:
            cli.run = orig
    assert ei.value.code == EXIT_ZERO_COVERAGE == 5
