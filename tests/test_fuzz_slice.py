"""Bounded, seeded slice of the qa/extended_fuzz.py adversarial sweeps.

The full sweeps run ad hoc per round (and found two real defects in
round 3), but nothing forced them to run — this gate runs a ~30 s
deterministic slice of every sweep inside the normal pytest run, so a
regression in any fuzzed surface fails CI, not just builder discipline
(VERDICT r3 item 7).  Budgets are per-sweep trial counts, not wall
clock, so the slice is reproducible bit-for-bit (each sweep seeds its
own RNG from a constant).
"""

import importlib.util
import os
import sys

import pytest

_QA = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "qa", "extended_fuzz.py")


@pytest.fixture(scope="module")
def fuzz():
    spec = importlib.util.spec_from_file_location("extended_fuzz", _QA)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["extended_fuzz"] = mod
    spec.loader.exec_module(mod)
    return mod


def test_slice_refine_batch(fuzz):
    assert fuzz.sweep_refine_batch(seeds=6)


def test_slice_realign_oracle(fuzz):
    assert fuzz.sweep_realign_oracle(seeds=4)


def test_slice_fai_roundtrip(fuzz):
    assert fuzz.sweep_fai_roundtrip(trials=20)


def test_slice_paf_corruption(fuzz):
    assert fuzz.sweep_paf_corruption(trials=3000)


def test_slice_cli_parity(fuzz):
    assert fuzz.sweep_cli_parity(trials=2)


def test_slice_native_cli_parity(fuzz):
    assert fuzz.sweep_native_cli_parity(trials=3)


def test_slice_ragged_m2m(fuzz):
    assert fuzz.sweep_ragged_m2m(trials=3)
