"""Native MSA engine delegation (VERDICT r3 item 5): the Python CLI's
pure-CPU -w/consensus builds run through the ctypes bridge to the C++
engine; every output and warning must be byte-identical to the Python
engine (PWASM_NATIVE_MSA=0)."""

import io
import os

import numpy as np
import pytest

from pwasm_tpu.cli import run
from pwasm_tpu.core.dna import revcomp
from pwasm_tpu.core.fasta import write_fasta
from pwasm_tpu.native import native_msa

from helpers import make_paf_line

pytestmark = pytest.mark.skipif(native_msa() is None,
                                reason="native library unavailable")


def _rand_lines(rng, qname, Q, n, tprefix="t"):
    L = len(Q)
    lines = []
    for k in range(n):
        strand = "-" if rng.random() < 0.3 else "+"
        q_aln = revcomp(Q.encode()).decode() if strand == "-" else Q
        head = int(rng.integers(3, 10))
        tail = int(rng.integers(3, 10))
        ops = [("=", head)]
        pos = head
        while pos < L - tail:
            r = rng.random()
            span = int(rng.integers(1, L - tail - pos + 1))
            if r < 0.55:
                ops.append(("=", span))
                pos += span
            elif r < 0.7:
                qb = q_aln[pos]
                tb = "ACGT"[("ACGT".index(qb) + 1) % 4]
                ops.append(("*", tb.lower(), qb.lower()))
                pos += 1
            elif r < 0.85:
                ins = "".join("acgt"[i] for i in
                              rng.integers(0, 4, int(rng.integers(1, 6))))
                ops.append(("ins", ins))
            else:
                d = min(int(rng.integers(1, 6)), L - tail - pos)
                if d > 0:
                    ops.append(("del", d))
                    pos += d
        ops.append(("=", L - pos))
        lines.append(make_paf_line(qname, Q, f"{tprefix}{k:02d}",
                                   strand, ops)[0])
    return lines


def _write_inputs(tmp_path, lines, recs):
    paf = tmp_path / "in.paf"
    paf.write_text("".join(l + "\n" for l in lines))
    fa = tmp_path / "q.fa"
    write_fasta(str(fa), recs)
    return str(paf), str(fa)


def _run_both(tmp_path, monkeypatch, paf, fa, extra, exts):
    """Run the CLI with and without delegation; return the two
    (rc, stderr, concatenated outputs) triples."""
    out = {}
    for tag, env in (("native", "1"), ("python", "0")):
        monkeypatch.setenv("PWASM_NATIVE_MSA", env)
        args = [paf, "-r", fa, "-o", str(tmp_path / f"{tag}.dfa")]
        for e in exts:
            if e == "mfa":
                args += ["-w", str(tmp_path / f"{tag}.mfa")]
            else:
                args += [f"--{e}={tmp_path / tag}.{e}"]
        err = io.StringIO()
        rc = run(args + extra, stderr=err)
        body = b""
        for e in ["dfa"] + list(exts):
            p = tmp_path / f"{tag}.{e}"
            if p.exists():
                body += p.read_bytes()
        out[tag] = (rc, err.getvalue(), body)
    return out["native"], out["python"]


@pytest.mark.parametrize("seed,extra", [
    (0, []),
    (1, ["--remove-cons-gaps"]),
    (2, ["--no-refine-clip"]),
    (3, ["-c", "25%"]),
])
def test_delegated_outputs_byte_identical(tmp_path, monkeypatch, seed,
                                          extra):
    rng = np.random.default_rng(seed)
    L = int(rng.integers(80, 200))
    Q = "".join("ACGT"[i] for i in rng.integers(0, 4, L))
    lines = _rand_lines(rng, "q", Q, int(rng.integers(3, 12)))
    paf, fa = _write_inputs(tmp_path, lines, [("q", Q.encode())])
    native, python = _run_both(tmp_path, monkeypatch, paf, fa, extra,
                               ("mfa", "ace", "info", "cons"))
    assert native == python
    assert native[0] == 0


def test_delegated_multi_query_reset(tmp_path, monkeypatch):
    """A second query resets the MSA on both engines; only the LAST
    query's MSA is written."""
    rng = np.random.default_rng(7)
    Q1 = "".join("ACGT"[i] for i in rng.integers(0, 4, 90))
    Q2 = "".join("ACGT"[i] for i in rng.integers(0, 4, 120))
    lines = (_rand_lines(rng, "q1", Q1, 3, "a")
             + _rand_lines(rng, "q2", Q2, 4, "b"))
    paf, fa = _write_inputs(tmp_path, lines,
                            [("q1", Q1.encode()), ("q2", Q2.encode())])
    native, python = _run_both(tmp_path, monkeypatch, paf, fa, [],
                               ("mfa", "ace"))
    assert native == python
    assert native[0] == 0
    assert b"b00" in native[2] and b"a00" not in native[2].split(b">q1")[0]


def test_delegated_skip_bad_lines_drop(tmp_path, monkeypatch):
    """An out-of-layout gap structure (reverse-strand alignment starting
    with a deletion puts a ref gap at r_len) is dropped from the MSA
    with the same warning and stats on both engines."""
    rng = np.random.default_rng(11)
    Q = "".join("ACGT"[i] for i in rng.integers(0, 4, 60))
    good = _rand_lines(rng, "q", Q, 3)
    q_rc = revcomp(Q.encode()).decode()
    bad, _ = make_paf_line("q", Q, "tbad", "-",
                           [("del", 2), ("=", 58)])
    lines = good[:2] + [bad] + good[2:]
    paf, fa = _write_inputs(tmp_path, lines, [("q", Q.encode())])
    _ = q_rc
    outs = {}
    for tag, env in (("native", "1"), ("python", "0")):
        monkeypatch.setenv("PWASM_NATIVE_MSA", env)
        err = io.StringIO()
        rc = run([paf, "-r", fa, "-o", str(tmp_path / f"{tag}.dfa"),
                  "-w", str(tmp_path / f"{tag}.mfa"), "--skip-bad-lines",
                  f"--stats={tmp_path / tag}.stats"], stderr=err)
        outs[tag] = (rc, err.getvalue(),
                     (tmp_path / f"{tag}.mfa").read_bytes(),
                     (tmp_path / f"{tag}.dfa").read_bytes())
    assert outs["native"] == outs["python"]
    assert "excluding alignment" in outs["native"][1]
    import json
    d = json.loads((tmp_path / "native.stats").read_text())
    assert d["msa_dropped"] == 1
    # without --skip-bad-lines the same input is fatal with the same
    # message on both engines
    for tag, env in (("native", "1"), ("python", "0")):
        monkeypatch.setenv("PWASM_NATIVE_MSA", env)
        err = io.StringIO()
        rc = run([paf, "-r", fa, "-o", str(tmp_path / f"f_{tag}.dfa"),
                  "-w", str(tmp_path / f"f_{tag}.mfa")], stderr=err)
        outs[f"fatal_{tag}"] = (rc, err.getvalue())
    assert outs["fatal_native"] == outs["fatal_python"]
    assert outs["fatal_native"][0] == 1
    assert "invalid gap position" in outs["fatal_native"][1]


def test_delegated_keeps_previous_msa_when_last_query_all_dropped(
        tmp_path, monkeypatch):
    """If every alignment of the LAST query is excluded under
    --skip-bad-lines, both engines still write the PREVIOUS query's MSA
    (the reset on query change is lazy: the old graph lives until the
    new query's first successful insertion)."""
    rng = np.random.default_rng(23)
    Q1 = "".join("ACGT"[i] for i in rng.integers(0, 4, 80))
    Q2 = "".join("ACGT"[i] for i in rng.integers(0, 4, 50))
    good = _rand_lines(rng, "q1", Q1, 3, "a")
    # reverse-strand alignment starting with a deletion: ref gap lands
    # at r_len — out-of-layout, dropped from the MSA under
    # --skip-bad-lines
    bad, _ = make_paf_line("q2", Q2, "tbad", "-", [("del", 2), ("=", 48)])
    paf, fa = _write_inputs(tmp_path, good + [bad],
                            [("q1", Q1.encode()), ("q2", Q2.encode())])
    outs = {}
    for tag, env in (("native", "1"), ("python", "0")):
        monkeypatch.setenv("PWASM_NATIVE_MSA", env)
        err = io.StringIO()
        rc = run([paf, "-r", fa, "-o", str(tmp_path / f"{tag}.dfa"),
                  "-w", str(tmp_path / f"{tag}.mfa"), "--skip-bad-lines"],
                 stderr=err)
        outs[tag] = (rc, err.getvalue(),
                     (tmp_path / f"{tag}.mfa").read_bytes())
    assert outs["native"] == outs["python"]
    assert outs["native"][0] == 0
    assert b">q1" in outs["native"][2]     # previous query's MSA written
    assert b"tbad" not in outs["native"][2]


def test_delegated_debug_layout(tmp_path, monkeypatch):
    rng = np.random.default_rng(13)
    Q = "".join("ACGT"[i] for i in rng.integers(0, 4, 70))
    lines = _rand_lines(rng, "q", Q, 4)
    paf, fa = _write_inputs(tmp_path, lines, [("q", Q.encode())])
    outs = {}
    for tag, env in (("native", "1"), ("python", "0")):
        monkeypatch.setenv("PWASM_NATIVE_MSA", env)
        err = io.StringIO()
        rc = run([paf, "-r", fa, "-o", str(tmp_path / f"{tag}.dfa"),
                  "-w", str(tmp_path / f"{tag}.mfa"), "-D"], stderr=err)
        # -D implies -v whose closing brief carries wall-clock rates;
        # drop that one timing-dependent line before comparing
        text = "".join(l for l in err.getvalue().splitlines(keepends=True)
                       if not l.rstrip().endswith("bases/s)"))
        outs[tag] = (rc, text)
    assert outs["native"] == outs["python"]
    assert ">MSA (5)" in outs["native"][1]


def test_delegated_realign_path(tmp_path, monkeypatch):
    """--realign feeds DP-derived gap structures through msa_add; the
    delegated merge must stay byte-identical."""
    rng = np.random.default_rng(17)
    Q = "".join("ACGT"[i] for i in rng.integers(0, 4, 100))
    lines = _rand_lines(rng, "q", Q, 5)
    paf, fa = _write_inputs(tmp_path, lines, [("q", Q.encode())])
    native, python = _run_both(tmp_path, monkeypatch, paf, fa,
                               ["--realign", "--band=32"], ("mfa", "ace"))
    assert native == python
    assert native[0] == 0


@pytest.mark.parametrize("extra", [[], ["--remove-cons-gaps"],
                                   ["--shard"]])
def test_device_delegation_byte_identical(tmp_path, monkeypatch, extra):
    """--device=tpu with the native engine: the C++ merge renders the
    pileup, the device kernel votes, C++ applies the votes — outputs
    byte-identical to the Python-engine device path (and the cpu
    path)."""
    rng = np.random.default_rng(29)
    L = 120
    Q = "".join("ACGT"[i] for i in rng.integers(0, 4, L))
    lines = _rand_lines(rng, "q", Q, 8)
    paf, fa = _write_inputs(tmp_path, lines, [("q", Q.encode())])
    outs = {}
    for tag, env, dev in (("native_tpu", "1", "tpu"),
                          ("python_tpu", "0", "tpu"),
                          ("native_cpu", "1", "cpu")):
        if extra == ["--shard"] and dev == "cpu":
            continue  # --shard requires --device=tpu
        monkeypatch.setenv("PWASM_NATIVE_MSA", env)
        err = io.StringIO()
        stats = tmp_path / f"{tag}.stats"
        rc = run([paf, "-r", fa, "-o", str(tmp_path / f"{tag}.dfa"),
                  "-w", str(tmp_path / f"{tag}.mfa"),
                  f"--ace={tmp_path / tag}.ace",
                  f"--info={tmp_path / tag}.info",
                  f"--device={dev}", f"--stats={stats}"] + extra,
                 stderr=err)
        assert rc == 0, err.getvalue()
        import json as _json
        assert _json.loads(stats.read_text())["engine_fallbacks"] == 0
        outs[tag] = b"".join(
            (tmp_path / f"{tag}.{e}").read_bytes()
            for e in ("dfa", "mfa", "ace", "info"))
    assert len(set(outs.values())) == 1


def test_device_delegation_kernel_provenance(tmp_path, monkeypatch):
    """The delegated --device=tpu consensus provably uses the Pallas
    kernel: tamper with its votes and watch the ACE consensus change."""
    import pwasm_tpu.ops.consensus as consmod

    rng = np.random.default_rng(33)
    Q = "".join("ACGT"[i] for i in rng.integers(0, 4, 60))
    lines = _rand_lines(rng, "q", Q, 4)
    paf, fa = _write_inputs(tmp_path, lines, [("q", Q.encode())])
    monkeypatch.setenv("PWASM_NATIVE_MSA", "1")
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "r.dfa"),
              f"--ace={tmp_path / 'good'}.ace", "--device=tpu"],
             stderr=io.StringIO())
    assert rc == 0

    real = consmod.consensus_pallas

    def tampered(bases, *a, **k):
        votes, counts = real(bases, *a, **k)
        # flip every vote to 'T' (code 3) where there is coverage
        import jax.numpy as jnp
        return jnp.where(votes >= 0, jnp.int8(3), votes), counts

    monkeypatch.setattr(consmod, "consensus_pallas", tampered)
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "r2.dfa"),
              f"--ace={tmp_path / 'bad'}.ace", "--device=tpu"],
             stderr=io.StringIO())
    assert rc == 0
    good = (tmp_path / "good.ace").read_text()
    bad = (tmp_path / "bad.ace").read_text()
    assert good != bad
    # tampered consensus is all T over its live window
    cons_line = bad.splitlines()[1]
    assert set(cons_line) == {"T"}


def test_delegation_used(tmp_path, monkeypatch):
    """Prove the native engine actually handles the build when enabled:
    tamper with the Python engine's merge and observe no effect (and the
    reverse with delegation off)."""
    import pwasm_tpu.align.msa as msamod

    rng = np.random.default_rng(19)
    Q = "".join("ACGT"[i] for i in rng.integers(0, 4, 60))
    lines = _rand_lines(rng, "q", Q, 3)
    paf, fa = _write_inputs(tmp_path, lines, [("q", Q.encode())])

    def boom(*a, **k):
        raise AssertionError("python engine used despite delegation")

    monkeypatch.setenv("PWASM_NATIVE_MSA", "1")
    monkeypatch.setattr(msamod.Msa, "add_align", boom)
    err = io.StringIO()
    rc = run([paf, "-r", fa, "-o", str(tmp_path / "r.dfa"),
              "-w", str(tmp_path / "r.mfa")], stderr=err)
    assert rc == 0
    assert (tmp_path / "r.mfa").read_bytes()


def test_engine_warnings_go_to_callers_stderr_stream():
    """Replayed engine warnings must reach the stream the caller passed
    (the CLI threads its stderr in), not the process sys.stderr — a
    caller capturing stderr (as every CLI test does) must see native
    warnings exactly like Python-engine warnings (ADVICE r4)."""
    import contextlib

    stream = io.StringIO()
    nmsa = native_msa(stream=stream)
    try:
        with open(nmsa._warn_path, "w") as f:
            f.write("Warning: synthetic engine warning\n")
        proc_err = io.StringIO()
        with contextlib.redirect_stderr(proc_err):
            nmsa._replay_warnings()
        assert stream.getvalue() == "Warning: synthetic engine warning\n"
        assert proc_err.getvalue() == ""
        # default (no stream): sys.stderr resolved at REPLAY time, so a
        # redirect active when the warning fires is honored
        nmsa2 = native_msa()
        try:
            assert nmsa2.stream is None
            with open(nmsa2._warn_path, "w") as f:
                f.write("late\n")
            late = io.StringIO()
            with contextlib.redirect_stderr(late):
                nmsa2._replay_warnings()
            assert late.getvalue() == "late\n"
        finally:
            nmsa2.close()
    finally:
        nmsa.close()


# ---------------------------------------------------------------------------
# batched add marshalling (ISSUE 8 satellite / ROADMAP item 2 lever a)
# ---------------------------------------------------------------------------
def _extract_items(lines, Q):
    """PAF lines -> the (tlabel, tseq, t_offset, reverse, rgaps, tgaps,
    ord_num) rows cli.py buffers for add_batch (same extraction path)."""
    from pwasm_tpu.core.events import extract_alignment
    from pwasm_tpu.core.paf import parse_paf_line

    refseq = Q.encode()
    refseq_rc = revcomp(refseq)
    items = []
    for k, line in enumerate(lines, 1):
        rec = parse_paf_line(line)
        al = rec.alninfo
        aln = extract_alignment(
            rec, refseq_rc if al.reverse else refseq)
        tlabel = (f"{al.t_id}:{al.t_alnstart}-{al.t_alnend}"
                  + ("-" if al.reverse else "+"))
        items.append((tlabel, bytes(aln.tseq), al.r_alnstart,
                      aln.reverse, aln.rgaps, aln.tgaps, k))
    return items


def test_add_batch_matches_sequential_adds(tmp_path):
    """ONE pw_msa_add_batch crossing produces the same engine state —
    byte-identical writers — as per-item add() calls."""
    rng = np.random.default_rng(31)
    Q = "".join("ACGT"[i] for i in rng.integers(0, 4, 100))
    items = _extract_items(_rand_lines(rng, "q", Q, 6), Q)
    outs = {}
    for tag in ("seq", "batch"):
        nmsa = native_msa()
        try:
            if tag == "seq":
                for (tl, ts, toff, rev, rg, tg, k) in items:
                    assert nmsa.add(tl, ts, toff, rev, "q", Q.encode(),
                                    len(Q), rg, tg, k)
            else:
                dropped = []
                nmsa.add_batch("q", Q.encode(), len(Q), items,
                               lambda i, m: dropped.append(i))
                assert dropped == []
            assert nmsa.count() == len(items) + 1  # + the reference row
            body = b""
            for kind in ("mfa", "ace", "cons"):
                p = tmp_path / f"{tag}.{kind}"
                nmsa.write(kind, str(p))
                body += p.read_bytes()
            outs[tag] = body
        finally:
            nmsa.close()
    assert outs["seq"] == outs["batch"]


def test_add_batch_drop_hook_skips_in_order_or_raises(tmp_path):
    """A mid-batch out-of-layout item fires on_drop with its index and
    the engine's message, nothing of IT is mutated, and the rest of the
    batch still lands; a raising hook aborts exactly at the item."""
    rng = np.random.default_rng(37)
    Q = "".join("ACGT"[i] for i in rng.integers(0, 4, 60))
    good = _extract_items(_rand_lines(rng, "q", Q, 4), Q)
    bad_line, _ = make_paf_line("q", Q, "tbad", "-",
                                [("del", 2), ("=", 58)])
    bad = _extract_items([bad_line], Q)[0]
    items = good[:2] + [bad] + good[2:]
    nmsa = native_msa()
    try:
        drops = []
        nmsa.add_batch("q", Q.encode(), len(Q), items,
                       lambda i, m: drops.append((i, m)))
        assert [i for i, _ in drops] == [2]
        assert "invalid gap position" in drops[0][1]
        assert nmsa.count() == len(good) + 1   # bad never inserted
    finally:
        nmsa.close()
    from pwasm_tpu.core.errors import PwasmError

    nmsa = native_msa()
    try:
        def fatal(i, m):
            raise PwasmError(m)
        with pytest.raises(PwasmError, match="invalid gap position"):
            nmsa.add_batch("q", Q.encode(), len(Q), items, fatal)
        assert nmsa.count() == 3   # the two items before the bad one
    finally:
        nmsa.close()


def test_batch_marshalling_hatch_byte_identical(tmp_path, monkeypatch):
    """PWASM_NATIVE_MSA_BATCH=0 (the per-alignment A/B hatch) and the
    default batched path produce byte-identical outputs end to end."""
    rng = np.random.default_rng(41)
    Q1 = "".join("ACGT"[i] for i in rng.integers(0, 4, 90))
    Q2 = "".join("ACGT"[i] for i in rng.integers(0, 4, 70))
    lines = (_rand_lines(rng, "q1", Q1, 5, "a")
             + _rand_lines(rng, "q2", Q2, 4, "b"))
    paf, fa = _write_inputs(tmp_path, lines,
                            [("q1", Q1.encode()), ("q2", Q2.encode())])
    outs = {}
    monkeypatch.setenv("PWASM_NATIVE_MSA", "1")
    for tag, env in (("batched", "1"), ("peritem", "0")):
        monkeypatch.setenv("PWASM_NATIVE_MSA_BATCH", env)
        err = io.StringIO()
        rc = run([paf, "-r", fa, "-o", str(tmp_path / f"{tag}.dfa"),
                  "-w", str(tmp_path / f"{tag}.mfa"),
                  f"--cons={tmp_path / tag}.cons", "--batch=3"],
                 stderr=err)
        outs[tag] = (rc, err.getvalue(), b"".join(
            (tmp_path / f"{tag}.{e}").read_bytes()
            for e in ("dfa", "mfa", "cons")))
    assert outs["batched"] == outs["peritem"]
    assert outs["batched"][0] == 0
