"""The bounded backend health gate (utils/backend.py): caching, TTL,
and skip semantics — all probe calls are stubbed, so these tests never
touch a real backend."""

import os

import pytest

import pwasm_tpu.utils.backend as B

# captured before the autouse fixture below swaps it out per test
_REAL_SUCCESS_MARKER = B._success_marker


@pytest.fixture(autouse=True)
def _fresh(monkeypatch, tmp_path):
    """Isolate every test: no in-process cache, a private marker path,
    and pretend no jax backend is initialized (the pytest process has
    one, which would short-circuit the gate)."""
    monkeypatch.setattr(B, "_probe_cache", None)
    marker = tmp_path / "marker"
    monkeypatch.setattr(B, "_success_marker", lambda: str(marker))
    monkeypatch.setattr(B, "_backend_already_initialized", lambda: False)
    monkeypatch.delenv("PWASM_DEVICE_PROBE", raising=False)
    monkeypatch.delenv("PWASM_DEVICE_PROBE_TTL", raising=False)
    yield marker


def test_probe_failure_demotes_and_caches(monkeypatch, _fresh):
    calls = []

    def probe(env, timeout):
        calls.append(1)
        return None, "probe hang (> 1s)"

    monkeypatch.setattr(B, "probe_backend", probe)
    ok, why = B.device_backend_reachable()
    assert not ok and "hang" in why
    ok2, _ = B.device_backend_reachable()
    assert not ok2
    assert len(calls) == 1          # verdict cached within the TTL
    assert not os.path.exists(_fresh)   # failure writes no marker


def test_probe_success_writes_marker_and_skips_reprobe(monkeypatch,
                                                       _fresh):
    calls = []

    def probe(env, timeout):
        calls.append(1)
        return "tpu", ""

    monkeypatch.setattr(B, "probe_backend", probe)
    assert B.device_backend_reachable() == (True, "")
    assert os.path.exists(_fresh)
    # a second process (fresh in-process cache) trusts the marker
    monkeypatch.setattr(B, "_probe_cache", None)
    monkeypatch.setattr(
        B, "probe_backend",
        lambda *a: (_ for _ in ()).throw(AssertionError("re-probed")))
    assert B.device_backend_reachable() == (True, "")
    assert len(calls) == 1


def test_failed_verdict_recovers_after_ttl(monkeypatch, _fresh):
    monkeypatch.setenv("PWASM_DEVICE_PROBE_TTL", "100")
    now = [1000.0]
    monkeypatch.setattr(B, "probe_backend",
                        lambda *a: (None, "down"))
    import time as _time

    monkeypatch.setattr(_time, "time", lambda: now[0])
    assert not B.device_backend_reachable()[0]
    # tunnel comes back; verdict flips only after the TTL expires
    monkeypatch.setattr(B, "probe_backend", lambda *a: ("tpu", ""))
    assert not B.device_backend_reachable()[0]   # still cached
    now[0] += 200.0
    assert B.device_backend_reachable()[0]       # re-probed, healthy


def test_probe_opt_out(monkeypatch, _fresh):
    monkeypatch.setenv("PWASM_DEVICE_PROBE", "0")
    monkeypatch.setattr(
        B, "probe_backend",
        lambda *a: (_ for _ in ()).throw(AssertionError("probed")))
    assert B.device_backend_reachable() == (True, "")


def test_initialized_backend_skips(monkeypatch, _fresh):
    monkeypatch.setattr(B, "_backend_already_initialized", lambda: True)
    monkeypatch.setattr(
        B, "probe_backend",
        lambda *a: (_ for _ in ()).throw(AssertionError("probed")))
    assert B.device_backend_reachable() == (True, "")


def test_untrusted_marker_falls_through_to_probe(monkeypatch, _fresh,
                                                 tmp_path):
    """A symlink or a foreign-uid file at the marker path must NOT be
    trusted (shared temp dir: another local user can pre-create the
    predictable name) — the gate re-probes instead (ADVICE r4)."""
    calls = []

    def probe(env, timeout):
        calls.append(1)
        return "tpu", ""

    monkeypatch.setattr(B, "probe_backend", probe)
    # marker is a symlink to a fresh file some other process controls
    target = tmp_path / "planted"
    target.write_text("")
    os.symlink(target, _fresh)
    monkeypatch.setattr(B, "_probe_cache", None)
    assert B.device_backend_reachable() == (True, "")
    assert len(calls) == 1          # symlink ignored, real probe ran

    # a foreign-uid regular file is equally untrusted
    os.unlink(_fresh)
    _fresh.write_text("")
    real_lstat = os.lstat

    class _St:
        def __init__(self, st):
            self.st_mode = st.st_mode
            self.st_uid = st.st_uid + 1
            self.st_mtime = st.st_mtime

    monkeypatch.setattr(
        B.os, "lstat",
        lambda p: _St(real_lstat(p)) if str(p) == str(_fresh)
        else real_lstat(p))
    monkeypatch.setattr(B, "_probe_cache", None)
    assert B.device_backend_reachable() == (True, "")
    assert len(calls) == 2          # foreign file ignored, re-probed


def test_untrusted_marker_is_removed_so_cache_recovers(monkeypatch,
                                                       _fresh, tmp_path):
    """Distrusting a planted marker must also remove it: otherwise the
    cross-process cache is permanently disabled at that path (every
    run re-probes; a dead tunnel costs the full timeout every time)."""
    calls = []
    monkeypatch.setattr(B, "probe_backend",
                        lambda *a: (calls.append(1), ("tpu", ""))[1])
    target = tmp_path / "planted2"
    target.write_text("")
    os.symlink(target, _fresh)
    assert B.device_backend_reachable() == (True, "")
    assert len(calls) == 1
    # the symlink is gone and a real marker took its place: a fresh
    # process now trusts it without re-probing
    assert os.path.exists(_fresh) and not os.path.islink(_fresh)
    monkeypatch.setattr(B, "_probe_cache", None)
    assert B.device_backend_reachable() == (True, "")
    assert len(calls) == 1


def test_marker_dir_mode_is_tightened(monkeypatch, tmp_path):
    """ADVICE round 5: ``makedirs(mode=0o700)`` does not tighten a
    PRE-EXISTING marker directory — a group/world-accessible dir we own
    must be chmod'd back to 0700 (or the cache refused) before any
    marker inside it is trusted."""
    import stat as _stat
    import tempfile

    monkeypatch.setattr(tempfile, "gettempdir", lambda: str(tmp_path))
    d = tmp_path / f"pwasm_probe_{B._marker_uid()}"
    d.mkdir(mode=0o777)
    os.chmod(d, 0o775)          # pre-existing loose dir (umask-proof)
    marker = _REAL_SUCCESS_MARKER()
    assert marker is not None
    mode = os.lstat(d).st_mode
    assert _stat.S_IMODE(mode) == 0o700

    # chmod failure → the cache is refused, not trusted loose
    os.chmod(d, 0o775)
    monkeypatch.setattr(B.os, "chmod",
                        lambda *a, **k: (_ for _ in ()).throw(
                            OSError("nope")))
    assert _REAL_SUCCESS_MARKER() is None
