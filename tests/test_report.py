"""Diff-report layer tests: hand-computed golden rows + analysis functions.

The worked example: query CDS ATGGCCTGGAAAGATCTGTACCTGA (25bp), one
substitution inside a CCTGG motif, one 2bp deletion near a GATC motif
causing a frame shift.
"""

import io

import pytest

from pwasm_tpu.core.dna import revcomp
from pwasm_tpu.core.events import extract_alignment
from pwasm_tpu.core.paf import parse_paf_line
from pwasm_tpu.report.diff_report import (
    Summary,
    get_ref_context,
    hpoly_check,
    mmotif_check,
    predict_impact,
    print_diff_info,
)

Q = b"ATGGCCTGGAAAGATCTGTACCTGA"

PAF1 = ("geneA\t25\t0\t25\t+\tasm1\t23\t0\t23\t23\t25\t60\t"
        "NM:i:3\tAS:i:40\tcg:Z:12M2I11M\tcs:Z::6*ct:5+at:11")


def _report(line, q=Q, skip_codan=False, rlabel="", tlabel="asm1:0-23+",
            summary=None):
    rec = parse_paf_line(line)
    refseq_aln = revcomp(q) if rec.alninfo.reverse else q
    aln = extract_alignment(rec, refseq_aln)
    buf = io.StringIO()
    print_diff_info(aln, rlabel, tlabel, buf, q, skip_codan=skip_codan,
                    summary=summary)
    return buf.getvalue()


def test_worked_example_report():
    out = _report(PAF1)
    lines = out.splitlines()
    assert lines[0] == ">asm1:0-23+ coverage:100.00 score=40 edit_distance=3"
    assert lines[1] == ("S\t7\t3(W)\tT:C\t7\tTGGCCcGGAAA\tGGCCTGGAA\t"
                        "motif CCTGG\tAA3|W:R")
    assert lines[2] == ("D\t13\t5(D)\tGA:\t13\tGGAAATCTGT\tGAAAGATCT\t"
                        "motif GATC\tframe shift DLY+:SVP+")


def test_rlabel_header():
    out = _report(PAF1, rlabel="geneA")
    assert out.splitlines()[0].startswith(
        ">geneA--asm1:0-23+ coverage:100.00")


def test_skip_codan_empty_impact_column():
    out = _report(PAF1, skip_codan=True)
    # impact column present but empty -> line ends with a tab-separated
    # status then empty field
    assert out.splitlines()[1].endswith("motif CCTGG\t")


def test_premature_stop_substitution():
    # TGG (W, codon 3) -> TGA ('.'): sub at rloc 8, G->A
    paf = ("geneA\t25\t0\t25\t+\tasm1\t25\t0\t25\t25\t25\t60\t"
           "NM:i:1\tAS:i:44\tcg:Z:25M\tcs:Z::8*ag:16")
    out = _report(paf)
    row = out.splitlines()[1]
    assert "AA3|W:.|premature stop at AA3" in row


def test_synonymous_substitution():
    # CTG (L, codons 16-18... rloc 15..17) -> CTA still L: sub T->A? take
    # GCC (A) codon at 3-5 -> GCA (A): sub at rloc 5, C->A
    paf = ("geneA\t25\t0\t25\t+\tasm1\t25\t0\t25\t25\t25\t60\t"
           "NM:i:1\tAS:i:44\tcg:Z:25M\tcs:Z::5*ac:19")
    out = _report(paf)
    assert out.splitlines()[1].endswith("\tsynonymous")


def test_insertion_premature_stop():
    # insert TAA-forming frameshift right after codon boundary: insertion of
    # 'ta' at rloc 12 -> downstream premature stop expected (frameshift)
    paf = ("geneA\t25\t0\t25\t+\tasm1\t27\t0\t27\t25\t27\t60\t"
           "NM:i:2\tAS:i:40\tcg:Z:12M2D13M\tcs:Z::12-ta:13")
    out = _report(paf)
    row = out.splitlines()[1]
    assert row.startswith("I\t13\t")
    assert ("premature stop" in row) or ("frame shift" in row)


def test_get_ref_context_center_and_edges():
    rctx, loc = get_ref_context(Q, 10)
    assert rctx == Q[6:15].upper()
    assert loc == 4
    rctx, loc = get_ref_context(Q, 1)
    assert rctx == Q[0:9]
    assert loc == 1
    rctx, loc = get_ref_context(Q, 24)
    assert rctx == Q[16:25]
    # reference quirk: at the right edge the shift is applied with the
    # wrong sign (pafreport.cpp:726-728), so the local event offset comes
    # out 0 instead of 8 — preserved for parity
    assert loc == 0


def test_hpoly_check():
    #            012345678
    rctx = b"ACAAAACGT"
    assert hpoly_check(b"A", rctx, 4)
    assert hpoly_check(b"AA", rctx, 4)
    assert not hpoly_check(b"AG", rctx, 4)   # mixed bases
    assert not hpoly_check(b"C", rctx, 4)    # no CCCC run
    # run present but not overlapping the event position
    assert hpoly_check(b"A", rctx, 2)
    assert not hpoly_check(b"A", rctx, 8)    # l=2, l+4=6 < 8


def test_mmotif_check():
    idx, status = mmotif_check(b"GGCCTGGAA")
    assert (idx, status) == (1, "motif CCTGG")
    idx, status = mmotif_check(b"GAAAGATCT")
    assert (idx, status) == (3, "motif GATC")
    idx, status = mmotif_check(b"AAAAAAAAA")
    assert (idx, status) == (0, "")
    # first motif in table order wins
    idx, status = mmotif_check(b"CCTGGGATC")
    assert idx == 1


def test_predict_impact_deletion_inframe():
    # delete one full codon: no frameshift, no stop -> frame-shift text only
    # if aa4/maa4 differ; an in-frame 3bp deletion shifts codons by one
    from pwasm_tpu.core.events import DiffEvent
    di = DiffEvent("D", 3, b"GAT", b"", rloc=12, tloc=12)
    txt = predict_impact(di, Q, 9)
    # downstream codons change (frame preserved but sequence shifted)
    assert txt.startswith("frame shift") or txt == ""


def test_summary_counters():
    s = Summary()
    _report(PAF1, summary=s)
    assert s.alignments == 1
    assert s.events == {"S": 1, "I": 0, "D": 1}
    assert s.bases["D"] == 2
    assert s.status["motif"] == 2
    assert s.impact["frame_shift"] == 1
    assert s.impact["nonsynonymous"] == 1
    buf = io.StringIO()
    s.write(buf)
    assert "substitutions\t1" in buf.getvalue()


def test_long_event_truncation():
    # 15-base deletion -> evtbases displayed as [15]
    ins = "".join("ACGT"[i % 4] for i in range(15))
    paf = (f"geneA\t25\t0\t25\t+\tasm1\t40\t0\t40\t25\t40\t60\t"
           f"NM:i:15\tAS:i:20\tcg:Z:12M15D13M\tcs:Z::12-{ins.lower()}:13")
    out = _report(paf)
    row = out.splitlines()[1]
    assert "\t:[15]\t" in row
    # tctx is 5 + 15 + 5 = 25 > 22 -> first5 + [len-10] + last5
    assert "\tGGAAA[15]GATCT\t" in row


def test_device_batch_analysis_failure_replays_scalar():
    """If the batched device analysis fails, print_diff_info_batch must
    fall back to the progressive scalar path so rows before the failing
    event are still written (parity with --device=cpu)."""
    from helpers import make_paf_line
    from pwasm_tpu.core.errors import PwasmError
    from pwasm_tpu.report.device_report import print_diff_info_batch

    q = "ATGGCCTGGACGTACGATCAAGGT"
    good_line, _ = make_paf_line("q", q, "a1", "+",
                                 [("=", 4), ("*", "a", "c"), ("=", 19)])
    bad_line, _ = make_paf_line("q", q, "a2", "+",
                                [("=", 7), ("*", "t", "g"), ("=", 16)])
    ref = q.encode()
    good = extract_alignment(parse_paf_line(good_line), ref)
    bad = extract_alignment(parse_paf_line(bad_line), ref)
    bad.tdiffs[0].evtsub = b"A"  # contradicts the ref -> s_mismatch fatal
    out = io.StringIO()
    with pytest.raises(PwasmError, match="modseq"):
        print_diff_info_batch(
            [(good, "", "a1:0-24+", ref), (bad, "", "a2:0-24+", ref)], out)
    body = out.getvalue()
    assert ">a1:0-24+" in body          # written before the fatal
    assert body.count("S\t") == 1       # good alignment's row only
