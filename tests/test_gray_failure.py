"""Gray-failure defense (ISSUE 18): end-to-end deadlines, slow-member
quarantine, brownout shedding, and the fleet chaos harness.

Four behavior families, one contract:

- **Deadlines** — a ``deadline_ms`` budget minted once at the client
  rides every frame, is decremented at each hop, and a job whose
  budget is spent stops at the next durable boundary with the
  resumable ``deadline_exceeded`` verdict (rc 75), never a hang and
  never a half-written output.  No deadline → byte-identical to the
  pre-deadline protocol (no stray keys, no new argv).
- **Quarantine** — the router's per-member latency EWMAs feed a
  median-outlier detector: a member K× slower than the fleet median
  for consecutive polls stops taking placements but keeps serving
  what it has, and probation-exits by itself.  The fleet is never
  quarantined below one eligible member.
- **Shedding** — sustained queue pressure browns out the lowest
  priority tier with a truthful ``overloaded`` + ``retry_after_s``
  (no member was asked), damped by hysteresis in both directions.
- **Chaos harness** — ``qa/fleet_chaos.py``'s injectors (latency
  proxy, blackhole, truncation, SIGSTOP windows) are themselves under
  test here, because a drill that can't inject is a drill that always
  passes.
"""

import io
import os
import socket
import subprocess
import sys
import time
from types import SimpleNamespace

import pytest

from test_fleet import (REPO, SLOW, _corpus, _daemon, _fleet,
                        _job_args, _stub_runner)

sys.path.insert(0, os.path.join(REPO, "qa"))
import fleet_chaos as chaos  # noqa: E402

from pwasm_tpu.cli import run  # noqa: E402
from pwasm_tpu.core.errors import EXIT_PREEMPTED, EXIT_USAGE  # noqa: E402
from pwasm_tpu.service import protocol  # noqa: E402
from pwasm_tpu.service.client import ServiceClient, ServiceError  # noqa: E402


# ---------------------------------------------------------------------------
# deadline grammar (protocol.parse_deadline_ms)
# ---------------------------------------------------------------------------

def test_parse_deadline_ms_grammar():
    assert protocol.parse_deadline_ms({"deadline_ms": 1500}) \
        == (1500, None)
    assert protocol.parse_deadline_ms({}) == (None, None)
    for bad in (True, False, "soon", 1.5, [3]):
        v, err = protocol.parse_deadline_ms({"deadline_ms": bad})
        assert v is None
        assert err["error"] == protocol.ERR_BAD_REQUEST
    for spent in (0, -5):
        v, err = protocol.parse_deadline_ms({"deadline_ms": spent})
        assert v is None
        assert err["error"] == protocol.ERR_DEADLINE_EXCEEDED
        assert err["deadline_ms"] == spent


def test_client_deadline_stamping_and_remaining():
    c = ServiceClient.__new__(ServiceClient)
    c._deadline_mono = None
    assert c.deadline_remaining_s() == float("inf")
    c._deadline_mono = time.monotonic() + 5.0
    rem = c.deadline_remaining_s()
    assert 0.0 < rem <= 5.0


# ---------------------------------------------------------------------------
# daemon hop: budget rides into the exec argv; no deadline = no trace
# ---------------------------------------------------------------------------

def test_daemon_passes_remaining_budget_to_runner(tmp_path):
    log = []
    with _daemon(runner=_stub_runner(log=log)) as h:
        with ServiceClient(h.sock, deadline_s=30.0) as c:
            s = c.submit(["in.paf", "-o", str(tmp_path / "o.dfa")])
            assert s.get("ok"), s
            r = c.result(s["job_id"], timeout=30)
            assert r.get("rc") == 0
    flags = [a for argv in log for a in argv
             if a.startswith("--deadline-s=")]
    assert len(flags) == 1
    v = float(flags[0].split("=", 1)[1])
    assert 0.0 < v <= 30.0


def test_no_deadline_leaves_protocol_byte_identical(tmp_path):
    log = []
    with _daemon(runner=_stub_runner(log=log)) as h:
        frames = []
        with ServiceClient(h.sock) as c:
            real_request = c.request

            def spy(req, **kw):
                frames.append(dict(req))
                return real_request(req, **kw)

            c.request = spy
            s = c.submit(["in.paf", "-o", str(tmp_path / "o.dfa")])
            assert s.get("ok"), s
            r = c.result(s["job_id"], timeout=30)
            assert r.get("rc") == 0
        c2 = ServiceClient(h.sock)
        try:
            c2.drain()
        finally:
            c2.close()
    assert frames and all("deadline_ms" not in f for f in frames)
    assert not any(a.startswith("--deadline-s=")
                   for argv in log for a in argv)


def test_deadline_spent_in_queue_lands_preempted_resumable(tmp_path):
    with _daemon(runner=_stub_runner(sleep=0.5)) as h:
        with ServiceClient(h.sock) as filler:
            f0 = filler.submit(["a.paf", "-o", str(tmp_path / "a")])
            assert f0.get("ok"), f0
            with ServiceClient(h.sock, deadline_s=0.15) as c:
                s = c.submit(["b.paf", "-o", str(tmp_path / "b")])
                assert s.get("ok"), s
                r = c.result(s["job_id"], timeout=30)
            assert r["job"]["state"] == "preempted"
            assert r.get("rc") == EXIT_PREEMPTED
            assert "deadline_exceeded" in (r["job"].get("detail")
                                           or "")
            assert filler.result(f0["job_id"],
                                 timeout=30).get("rc") == 0
            filler.drain()


def test_deadline_already_spent_refused_at_admission(tmp_path):
    with _daemon(runner=_stub_runner()) as h:
        with ServiceClient(h.sock, deadline_s=0.05) as c:
            time.sleep(0.1)    # burn the whole budget client-side
            s = c.submit(["in.paf", "-o", str(tmp_path / "o")])
            assert not s.get("ok")
            assert s.get("error") == "deadline_exceeded"
        with ServiceClient(h.sock) as c:
            for bad in ("soon", True):
                resp = c.request({"cmd": "submit",
                                  "args": ["x.paf"],
                                  "deadline_ms": bad})
                assert resp.get("error") == "bad_request"


def test_router_decrements_deadline_toward_member(tmp_path):
    log = []
    with _fleet(n=2, runner=_stub_runner(log=log)) as f:
        with ServiceClient(f.sock, deadline_s=0.02) as c:
            time.sleep(0.05)
            s = c.submit(["in.paf", "-o", str(tmp_path / "o")])
            assert not s.get("ok")
            assert s.get("error") == "deadline_exceeded"
        with ServiceClient(f.sock, deadline_s=30.0) as c:
            s = c.submit(["in.paf", "-o", str(tmp_path / "o")])
            assert s.get("ok"), s
            assert c.result(s["job_id"], timeout=30).get("rc") == 0
    flags = [a for argv in log for a in argv
             if a.startswith("--deadline-s=")]
    assert len(flags) == 1
    v = float(flags[0].split("=", 1)[1])
    # the member's runner sees what is LEFT of the 30s budget after
    # the client->router->member hops each took their bite
    assert 0.0 < v < 30.0


# ---------------------------------------------------------------------------
# cold CLI: --deadline-s
# ---------------------------------------------------------------------------

def test_cold_cli_rejects_bad_deadline(tmp_path):
    paf, fa = _corpus(tmp_path, n=4)
    for bad in ("0", "-1", "nope", "inf"):
        err = io.StringIO()
        rc = run(_job_args(tmp_path, "bad", paf, fa,
                           [f"--deadline-s={bad}"]), stderr=err)
        assert rc == EXIT_USAGE, (bad, err.getvalue())


@pytest.mark.slow
def test_cold_cli_deadline_exit75_then_resume_byte_identical(
        tmp_path):
    paf, fa = _corpus(tmp_path)
    assert run(_job_args(tmp_path, "ref", paf, fa, [])) == 0
    ref = (tmp_path / "ref.dfa").read_bytes()
    # SLOW hangs 0.25s per batch and the corpus is 12 batches: a
    # 0.3s budget always expires mid-run, far from the finish line
    err = io.StringIO()
    rc = run(_job_args(tmp_path, "dl", paf, fa,
                       [SLOW, "--deadline-s=0.3"]), stderr=err)
    assert rc == EXIT_PREEMPTED, err.getvalue()
    assert "deadline_exceeded" in err.getvalue()
    # the final checkpoint verifies whole: version + CRC + record
    # boundary against the actual report (the signal-drill contract)
    import json as _json
    from pwasm_tpu.cli import CKPT_VERSION, _load_checkpoint
    ckpt = str(tmp_path / "dl.dfa") + ".ckpt"
    assert os.path.exists(ckpt)
    got = _load_checkpoint(str(tmp_path / "dl.dfa"))
    assert isinstance(got, tuple), got
    assert got[1] > 0       # records durably behind the budget
    assert _json.loads(open(ckpt).read())["version"] == CKPT_VERSION
    # resume WITHOUT a deadline finishes and matches the clean run
    assert run(_job_args(tmp_path, "dl", paf, fa,
                         ["--resume"])) == 0
    assert (tmp_path / "dl.dfa").read_bytes() == ref


# ---------------------------------------------------------------------------
# quarantine: median-outlier detection, floor, probation
# ---------------------------------------------------------------------------

def _mkrouter(n, **kw):
    # a Router that never serves: the detector/controller methods are
    # exercised directly against hand-set member state (the socket
    # path is required by the ctor but never bound)
    import tempfile
    from pwasm_tpu.fleet.router import Router
    d = tempfile.mkdtemp(prefix="pwgray")
    r = Router([f"/nowhere/m{i}.sock" for i in range(n)],
               socket_path=os.path.join(d, "r.sock"),
               stderr=io.StringIO(), **kw)
    for m in r.members.values():
        m.alive = True
    return r


def _set_lat(r, lats):
    for m, v in zip(r.members.values(), lats):
        m.lat_ewma_ms = v


def test_quarantine_needs_consecutive_strikes():
    r = _mkrouter(3, quarantine_x=3.0)
    _set_lat(r, [100.0, 100.0, 900.0])
    r._quarantine_scan()
    assert not any(m.quarantined for m in r.members.values())
    r._quarantine_scan()
    slow = r.members["m2.sock"]
    assert slow.quarantined
    assert slow.quarantines == 1


def test_quarantine_floor_spares_fast_small_fleets():
    # sub-floor latencies (all well under _Q_FLOOR_MS): a 10x relative
    # outlier at 0.1ms vs 0.01ms is noise, not a gray failure
    r = _mkrouter(3, quarantine_x=3.0)
    _set_lat(r, [0.01, 0.01, 0.1])
    for _ in range(4):
        r._quarantine_scan()
    assert not any(m.quarantined for m in r.members.values())


def test_quarantine_never_below_one_eligible_member():
    # two members already quarantined: the LAST eligible member is a
    # clear outlier, but the detector must hold its fire — a slow
    # member beats no member at all
    r = _mkrouter(3, quarantine_x=3.0)
    for name in ("m1.sock", "m2.sock"):
        r.members[name].quarantined = True
    _set_lat(r, [900.0, 100.0, 100.0])
    for _ in range(3):
        r._quarantine_scan()
    assert not r.members["m0.sock"].quarantined


def test_two_member_fleet_cannot_name_an_outlier():
    # with only two samples the upper median IS the slow member: the
    # detector cannot tell which side is wrong, so nobody enters
    r = _mkrouter(2, quarantine_x=3.0)
    _set_lat(r, [100.0, 900.0])
    for _ in range(3):
        r._quarantine_scan()
    assert not any(m.quarantined for m in r.members.values())


def test_quarantine_disabled_and_single_member_never_scan():
    r = _mkrouter(3, quarantine_x=0.0)
    _set_lat(r, [100.0, 100.0, 9000.0])
    for _ in range(3):
        r._quarantine_scan()
    assert not any(m.quarantined for m in r.members.values())
    r1 = _mkrouter(1, quarantine_x=3.0)
    _set_lat(r1, [9000.0])
    for _ in range(3):
        r1._quarantine_scan()
    assert not any(m.quarantined for m in r1.members.values())


def test_quarantine_probation_exit_after_clean_polls():
    r = _mkrouter(3, quarantine_x=3.0, quarantine_probation=2)
    _set_lat(r, [100.0, 100.0, 900.0])
    r._quarantine_scan()
    r._quarantine_scan()
    slow = r.members["m2.sock"]
    assert slow.quarantined
    slow.lat_ewma_ms = 110.0        # back with the pack
    r._quarantine_scan()
    assert slow.quarantined         # one clean poll: still probation
    r._quarantine_scan()
    assert not slow.quarantined     # second clean poll: released
    # a relapse while on probation resets the clean count
    _set_lat(r, [100.0, 100.0, 900.0])
    r._quarantine_scan()
    r._quarantine_scan()
    assert slow.quarantined


def test_placement_skips_quarantined_with_last_resort_fallback():
    r = _mkrouter(3, quarantine_x=3.0)
    _set_lat(r, [100.0, 100.0, 900.0])
    r.members["m2.sock"].quarantined = True
    order = r._members_by_depth()
    assert {m.name for m in order} == {"m0.sock", "m1.sock"}
    for m in r.members.values():
        m.quarantined = True
    # all quarantined: fall back to them rather than wedge the fleet
    assert len(r._members_by_depth()) == 3


def test_scaler_census_excludes_quarantined():
    from pwasm_tpu.fleet.scaler import FleetScaler
    r = _mkrouter(3, quarantine_x=3.0)
    r.members["m2.sock"].quarantined = True
    sc = object.__new__(FleetScaler)
    sc.router = r
    alive = FleetScaler._census(sc)[0]
    assert alive == 2


def test_fleet_stats_surface_quarantine_and_shed_blocks():
    r = _mkrouter(3, quarantine_x=4.0, quarantine_probation=5,
                  priority_lanes=("rt", "bulk"))
    r.members["m2.sock"].quarantined = True
    r.members["m2.sock"].lat_ewma_ms = 123.456
    st = r._fleet_stats()
    row = [m for m in st["fleet"]["members"]
           if m["name"] == "m2.sock"][0]
    assert row["quarantined"] is True
    assert row["lat_ewma_ms"] == pytest.approx(123.46)
    assert st["fleet"]["quarantined"] == 1
    q = st["ha"]["quarantine"]
    assert q["x"] == 4.0 and q["probation"] == 5
    assert q["members"] == 1
    assert st["ha"]["shed"] == {"level": 0,
                                "priority_lanes": ["rt", "bulk"],
                                "lanes_shed": []}
    r._shed_level = 1
    st = r._fleet_stats()
    assert st["ha"]["shed"]["lanes_shed"] == ["bulk"]


def test_top_renders_quarantine_state_and_shed_banner():
    from pwasm_tpu.service.top import render
    st = {"uptime_s": 10.0,
          "fleet": {"members": [
              {"name": "m0.sock", "alive": True, "queue_depth": 1,
               "running": 1, "jobs_routed": 5, "lat_ewma_ms": 12.0},
              {"name": "m1.sock", "alive": True, "quarantined": True,
               "queue_depth": 0, "running": 0, "jobs_routed": 2,
               "lat_ewma_ms": 640.0},
          ], "alive": 2},
          "ha": {"shed": {"level": 1, "lanes_shed": ["bulk"]}}}
    frame = render(st)
    assert "QUAR" in frame
    assert "640" in frame
    assert "SHEDDING: tier(s) bulk turned away (level 1)" in frame


# ---------------------------------------------------------------------------
# brownout shedding
# ---------------------------------------------------------------------------

def _pressurize(r, firing):
    r.slo.firing = lambda: list(firing)
    r._shed_last = -1e9     # let the next tick run immediately


def _tick(r):
    r._shed_last = -1e9
    r._shed_tick()


def test_shed_escalates_per_tick_and_respects_top_tier():
    r = _mkrouter(2, priority_lanes=("rt", "bulk", "batch"))
    _pressurize(r, [{"rule": "fleet_queue_pressure"}])
    _tick(r)
    assert r._shed_level == 1
    _tick(r)
    assert r._shed_level == 2
    _tick(r)
    assert r._shed_level == 2   # the top tier is never shed
    assert r._shed_check("rt") is None
    for lane in ("bulk", "batch", "", None, "mystery"):
        resp = r._shed_check(lane)
        assert resp is not None
        assert resp["error"] == "overloaded"
        assert float(resp["retry_after_s"]) >= 1.0
        assert "retry" in resp["detail"]


def test_shed_deescalates_only_after_clean_hysteresis():
    r = _mkrouter(2, priority_lanes=("rt", "bulk"))
    _pressurize(r, [{"rule": "ledger_saturation"}])
    _tick(r)
    assert r._shed_level == 1
    _pressurize(r, [])
    _tick(r)
    _tick(r)
    assert r._shed_level == 1   # two clean ticks: still shedding
    _tick(r)
    assert r._shed_level == 0   # third clean tick releases a tier
    assert r._shed_check("bulk") is None


def test_shed_inert_without_priority_lanes():
    r = _mkrouter(2)
    _pressurize(r, [{"rule": "fleet_queue_pressure"}])
    for _ in range(3):
        _tick(r)
    assert r._shed_level == 0
    assert r._shed_check("anything") is None


def test_shed_tick_self_paced_against_stats_poll_storm():
    # the stats verb calls slo.evaluate() directly, so slo.due() can
    # stay false forever under a fast poll loop — the controller must
    # pace itself off its own clock, not the engine's
    r = _mkrouter(2, priority_lanes=("rt", "bulk"))
    r.slo.firing = lambda: [{"rule": "fleet_queue_pressure"}]
    r.slo._last_eval = time.monotonic()    # a poller just evaluated
    assert not r.slo.due()
    r._shed_last = -1e9
    r._shed_tick()
    assert r._shed_level == 1
    # and back-to-back ticks inside one eval interval are no-ops
    r._shed_tick()
    assert r._shed_level == 1


def test_shed_end_to_end_truthful_refusal_and_rt_admission(
        tmp_path, monkeypatch):
    lanes = ("rt", "bulk")
    with _fleet(n=1, runner=_stub_runner(),
                router_kw={"priority_lanes": lanes},
                daemon_kw={"priority_lanes": lanes}) as f:
        monkeypatch.setattr(
            f.router.slo, "firing",
            lambda: [{"rule": "fleet_queue_pressure"}])
        assert chaos.wait_until(
            lambda: f.router._shed_level >= 1, 10.0)
        with ServiceClient(f.sock, trace_id="shed-e2e") as c:
            bulk = c.submit(["in.paf", "-o", str(tmp_path / "b")],
                            priority="bulk")
            assert not bulk.get("ok")
            assert bulk.get("error") == "overloaded"
            assert bulk.get("lane") == "bulk"
            assert float(bulk.get("retry_after_s") or 0) > 0
            rt = c.submit(["in.paf", "-o", str(tmp_path / "r")],
                          priority="rt")
            assert rt.get("ok"), rt
            assert c.result(rt["job_id"], timeout=30).get("rc") == 0
            sh = (c.stats()["stats"].get("ha") or {}).get("shed")
            assert sh["lanes_shed"] == ["bulk"]
        monkeypatch.setattr(f.router.slo, "firing", lambda: [])
        assert chaos.wait_until(
            lambda: f.router._shed_level == 0, 10.0)


# ---------------------------------------------------------------------------
# chaos harness: the injectors themselves
# ---------------------------------------------------------------------------

def test_chaos_proxy_passthrough_and_delay(tmp_path):
    with _daemon(runner=_stub_runner()) as h:
        proxy = chaos.ChaosProxy(h.sock)
        addr = proxy.start()
        try:
            with ServiceClient(addr) as c:
                assert c.ping().get("ok")
            proxy.delay_s = 0.2
            t0 = time.monotonic()
            with ServiceClient(addr) as c:
                assert c.ping().get("ok")
            assert time.monotonic() - t0 >= 0.2
        finally:
            proxy.stop()
        with ServiceClient(h.sock) as c:
            c.drain()


def test_chaos_proxy_blackhole_and_truncation(tmp_path):
    with _daemon(runner=_stub_runner()) as h:
        proxy = chaos.ChaosProxy(h.sock)
        addr = proxy.start()
        try:
            proxy.truncate_after = 3
            with pytest.raises(ServiceError):
                with ServiceClient(addr) as c:
                    c.ping()
            proxy.truncate_after = None
            proxy.blackhole = True
            with pytest.raises(ServiceError):
                with ServiceClient(addr, timeout=0.5) as c:
                    c.ping()
        finally:
            proxy.stop()
        with ServiceClient(h.sock) as c:
            c.drain()


def test_stop_windows_freeze_thaw_leaves_process_running():
    p = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(30)"])
    try:
        sw = chaos.StopWindows(p.pid, stop_s=0.05, run_s=0.05)
        sw.start()
        time.sleep(0.5)
        sw.stop()
        assert sw.windows >= 2
        assert p.poll() is None
        with open(f"/proc/{p.pid}/stat") as f:
            state = f.read().rsplit(")", 1)[1].split()[0]
        assert state != "T"     # stop() always leaves it CONTinued
    finally:
        p.kill()
        p.wait()


def test_deny_writes_restores_mode(tmp_path):
    d = tmp_path / "guarded"
    d.mkdir()
    mode = os.stat(d).st_mode
    with chaos.deny_writes(str(d)) as effective:
        if effective:    # root ignores modes; only assert when real
            with pytest.raises(OSError):
                (d / "f").write_text("x")
    assert os.stat(d).st_mode == mode
    (d / "f").write_text("x")    # and writable again afterwards


@pytest.mark.slow
def test_fleet_chaos_gray_drill_end_to_end(capsys):
    # the harness's own main(): 3 members, one behind a latency
    # proxy, quarantine observed, relief, probation-exit observed —
    # rc 0 is the whole drill contract
    assert chaos.main() == 0


# ---------------------------------------------------------------------------
# ENOSPC degradation (satellite a)
# ---------------------------------------------------------------------------

def test_cache_insert_enospc_degrades_to_passthrough(
        tmp_path, monkeypatch):
    from pwasm_tpu.service.cache import CacheStore
    from pwasm_tpu.utils import fsio
    store = CacheStore(str(tmp_path / "c"))
    assert store.insert("a" * 16, {"o.dfa": b"payload"}) is True

    def boom(*a, **kw):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(fsio, "write_durable_bytes", boom)
    assert store.insert("b" * 16, {"o.dfa": b"payload"}) is False
    st = store.stats_dict()
    assert st["insert_errors"] == 1
    assert st["insertions"] == 1    # the failed insert is not counted
    # lookups still serve: the cache degrades, never poisons
    assert store.get("a" * 16) is not None


def test_daemon_cache_insert_warns_once_per_outage(
        tmp_path, monkeypatch):
    from pwasm_tpu.service import cache as cache_mod
    with _daemon(runner=_stub_runner(),
                 result_cache=str(tmp_path / "rc")) as h:
        job = SimpleNamespace(cache=("k" * 16, None), id="job-x",
                              stats=None, trace_id=None)
        monkeypatch.setattr(cache_mod, "insert_from_paths",
                            lambda *a, **kw: False)
        h.daemon._cache_insert(job)
        h.daemon._cache_insert(job)
        out = h.err.getvalue()
        assert out.count("result-cache insert skipped") == 1
        monkeypatch.setattr(cache_mod, "insert_from_paths",
                            lambda *a, **kw: True)
        h.daemon._cache_insert(job)     # success re-arms the latch
        monkeypatch.setattr(cache_mod, "insert_from_paths",
                            lambda *a, **kw: False)
        h.daemon._cache_insert(job)
        assert h.err.getvalue().count(
            "result-cache insert skipped") == 2
        with ServiceClient(h.sock) as c:
            c.drain()


def test_spool_enospc_serves_from_ram_and_warns_once(
        tmp_path, monkeypatch):
    from pwasm_tpu.utils import fsio

    def boom(*a, **kw):
        raise OSError(28, "No space left on device")

    with _daemon(runner=_stub_runner(),
                 spool_threshold_bytes=1,
                 spool_dir=str(tmp_path / "spool")) as h:
        monkeypatch.setattr(fsio, "write_durable_text", boom)
        with ServiceClient(h.sock) as c:
            for k in range(2):
                s = c.submit(["in.paf", "-o",
                              str(tmp_path / f"o{k}")])
                assert s.get("ok"), s
                r = c.result(s["job_id"], timeout=30)
                assert r.get("rc") == 0     # served from RAM
            c.drain()
        assert h.err.getvalue().count("cannot spool results") == 1
