"""Fleet federation (ISSUE 13): TCP transport, token identity, the
``route`` daemon, and journal-aware failover.

Acceptance contracts:

- **one protocol, two transports**: ``serve --listen=HOST:PORT``
  answers the same NDJSON protocol as the unix socket, with client
  identity attested-or-explicit on both — ``SO_PEERCRED`` uid on unix,
  ``tok:<client-token>`` on TCP, anonymous otherwise;
- **one submit surface**: the router exposes submit/stream/result/
  cancel/status/inspect/stats/metrics/drain over N member daemons,
  with least-loaded placement, member-queue_full spillover to
  siblings, and a fleet-wide per-client quota no member-spraying can
  dodge;
- **the kill-one-of-three drill**: SIGKILL a member mid-job behind
  the router → its jobs resume on a sibling and every report is
  byte-identical to an uncrashed fleet, with the job's trace_id
  intact end-to-end (trace-merge of client+router+surviving members
  is one valid timeline);
- **warm fleet members**: ``serve --warmup --compile-cache-dir=DIR``
  pays the backend probe and the pow2-bucket compiles at daemon
  start, so the first real job runs probe-free and a restarted member
  finds its programs on disk.
"""

import io
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time
from contextlib import contextmanager
from types import SimpleNamespace

import numpy as np
import pytest

from pwasm_tpu.core.fasta import write_fasta
from pwasm_tpu.fleet import transport
from pwasm_tpu.fleet.ledger import FleetLedger
from pwasm_tpu.fleet.router import Router, route_main
from pwasm_tpu.service.client import (ServiceClient, ServiceError,
                                      wait_for_socket)
from pwasm_tpu.service.daemon import Daemon, serve_main
from pwasm_tpu.service.queue import QueueFull
from pwasm_tpu.service.top import render

from helpers import make_paf_line

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SLOW = "--inject-faults=seed=1,rate=1,kinds=hang,hang_s=0.25"


# ---------------------------------------------------------------------------
# transport units
# ---------------------------------------------------------------------------
def test_target_grammar():
    assert transport.is_tcp_target("localhost:9211")
    assert transport.is_tcp_target("10.0.0.7:1")
    assert not transport.is_tcp_target("/tmp/a.sock")
    assert not transport.is_tcp_target("a.sock")       # no port
    assert not transport.is_tcp_target("host:port")    # non-numeric
    assert not transport.is_tcp_target("a/b:9211")     # path-ish
    assert not transport.is_tcp_target("")
    assert transport.split_hostport("h:80") == ("h", 80)
    with pytest.raises(ValueError):
        transport.split_hostport("h:99999")            # port > 65535
    with pytest.raises(ValueError):
        transport.split_hostport("/tmp/a.sock")


def test_target_names_and_journal_placement(tmp_path):
    assert transport.target_name("/var/run/m0.sock") == "m0.sock"
    assert transport.target_name("node7:9211") == "node7_9211"
    # per-daemon (fast local disk): next to the socket; TCP targets
    # are unreachable without shared storage
    assert transport.member_journal_path("/tmp/a.sock", None) \
        == "/tmp/a.sock.journal"
    assert transport.member_journal_path("h:9211", None) is None
    # shared --journal-dir: the SAME arithmetic serves both sides
    shared = str(tmp_path / "shared")
    assert transport.member_journal_path("/tmp/a.sock", shared) \
        == os.path.join(shared, "a.sock.journal")
    assert transport.member_journal_path("h:9211", shared) \
        == os.path.join(shared, "h_9211.journal")


# ---------------------------------------------------------------------------
# ledger units
# ---------------------------------------------------------------------------
def test_ledger_quota_move_retire():
    led = FleetLedger(max_queue=2, max_total=3)
    led.admit("a", "m0")
    led.admit("a", "m1")
    with pytest.raises(QueueFull) as ei:
        led.admit("a", "m0")            # per-client fleet quota
    assert "FLEET" in str(ei.value)
    led.admit("b", "m0")
    with pytest.raises(QueueFull):
        led.admit("c", "m0")            # fleet total backstop
    assert led.client_depths() == {"a": 2, "b": 1}
    assert led.member_pressure("m0") == 2
    led.move("a", "m1", "m0")           # failover re-placement
    assert led.member_pressure("m0") == 3
    assert led.client_depths()["a"] == 2   # quota unchanged by a move
    led.retire("a", "m0")
    led.retire("a", "m0")
    led.retire("b", "m0")
    assert led.client_depths() == {}
    assert led.member_pressure("m0") == 0
    led.admit("c", "m0")                # slots freed


# ---------------------------------------------------------------------------
# in-process harness (stub runner: no jax, no corpus)
# ---------------------------------------------------------------------------
def _stub_runner(log=None, sleep=0.0, rc=0):
    def runner(argv, stdout=None, stderr=None, warm=None, **kw):
        if log is not None:
            log.append(list(argv))
        if sleep:
            time.sleep(sleep)
        sp = next((a.split("=", 1)[1] for a in argv
                   if a.startswith("--stats=")), None)
        if sp:
            with open(sp, "w") as f:
                json.dump({"wall_s": sleep}, f)
        return rc
    return runner


@contextmanager
def _daemon(runner=None, **kw):
    sockdir = tempfile.mkdtemp(prefix="pwflt")
    # unique basename: member names (fleet/transport.target_name) key
    # on it, and a fleet of members all called "s" would collide
    sock = os.path.join(sockdir, os.path.basename(sockdir) + ".sock")
    err = io.StringIO()
    dm = Daemon(sock, stderr=err, runner=runner, **kw)
    rcbox: list = []
    t = threading.Thread(target=lambda: rcbox.append(dm.serve()),
                         daemon=True)
    t.start()
    assert wait_for_socket(sock, 15), err.getvalue()
    try:
        yield SimpleNamespace(daemon=dm, sock=sock, rc=rcbox, err=err,
                              thread=t, dir=sockdir)
    finally:
        if not dm.drain.requested:
            dm.drain.request("test teardown")
        t.join(20)
        shutil.rmtree(sockdir, ignore_errors=True)


@contextmanager
def _fleet(n=2, runner=None, router_kw=None, daemon_kw=None):
    """N in-process member daemons + one in-process router."""
    with _nested(n, runner, daemon_kw or {}) as members:
        rdir = tempfile.mkdtemp(prefix="pwrt")
        rsock = os.path.join(rdir, "router.sock")
        err = io.StringIO()
        r = Router([m.sock for m in members], socket_path=rsock,
                   stderr=err, poll_interval=0.1,
                   **(router_kw or {}))
        rcbox: list = []
        t = threading.Thread(target=lambda: rcbox.append(r.serve()),
                             daemon=True)
        t.start()
        assert wait_for_socket(rsock, 15), err.getvalue()
        try:
            yield SimpleNamespace(router=r, sock=rsock,
                                  members=members, err=err, rc=rcbox)
        finally:
            if not r.drain.requested:
                r.drain.request("test teardown")
            t.join(20)
            shutil.rmtree(rdir, ignore_errors=True)


@contextmanager
def _nested(n, runner, daemon_kw):
    stack = []
    try:
        out = []
        for _ in range(n):
            cm = _daemon(runner=runner, **daemon_kw)
            stack.append(cm)
            out.append(cm.__enter__())
        yield out
    finally:
        for cm in reversed(stack):
            cm.__exit__(None, None, None)


# ---------------------------------------------------------------------------
# TCP transport + token identity
# ---------------------------------------------------------------------------
def test_tcp_listener_and_token_identity(tmp_path):
    """serve --listen: the same protocol over TCP, with token-based
    fair-share identity — tok:<token> buckets, anonymous without,
    SO_PEERCRED untouched on the unix side."""
    with _daemon(runner=_stub_runner(),
                 listen="127.0.0.1:0") as h:
        tcp = f"127.0.0.1:{h.daemon.tcp_port}"
        out = str(tmp_path / "o.dfa")
        with ServiceClient(tcp, client_token="alice") as c:
            assert c.ping()["ok"]
            r = c.result(c.submit(["in.paf", "-o", out],
                                  cwd=str(tmp_path))["job_id"],
                         timeout=30)
            assert r["rc"] == 0
            assert r["job"]["client"] == "tok:alice"
        with ServiceClient(tcp) as c:        # untokened: anonymous
            r = c.result(c.submit(["in.paf", "-o", out],
                                  cwd=str(tmp_path))["job_id"],
                         timeout=30)
            assert r["job"]["client"] == ""
        with ServiceClient(h.sock) as c:     # unix: kernel-attested
            r = c.result(c.submit(["in.paf", "-o", out],
                                  cwd=str(tmp_path))["job_id"],
                         timeout=30)
            assert r["job"]["client"] == f"uid:{os.getuid()}"
            # an explicit client= still overrides the token default
        with ServiceClient(tcp, client_token="alice") as c:
            r = c.result(c.submit(["in.paf", "-o", out],
                                  cwd=str(tmp_path),
                                  client="tenant9")["job_id"],
                         timeout=30)
            assert r["job"]["client"] == "tenant9"


def test_tcp_token_quota_is_per_token(tmp_path):
    """The DRR quota follows the token: one token at quota answers
    queue_full naming tok:<name>; another token keeps its own slots."""
    with _daemon(runner=_stub_runner(sleep=0.5), max_queue=1,
                 listen="127.0.0.1:0") as h:
        tcp = f"127.0.0.1:{h.daemon.tcp_port}"
        out = str(tmp_path / "o.dfa")
        with ServiceClient(tcp, client_token="heavy") as c:
            first = c.submit(["in.paf", "-o", out],
                             cwd=str(tmp_path))
            assert first["ok"]
            # keep submitting until the running job has dequeued or
            # not — at quota 1 the SECOND queued submit must 429
            rejected = None
            for _ in range(3):
                r = c.submit(["in.paf", "-o", out],
                             cwd=str(tmp_path))
                if not r.get("ok"):
                    rejected = r
                    break
            assert rejected is not None
            assert rejected["error"] == "queue_full"
            assert rejected["client"] == "tok:heavy"
        with ServiceClient(tcp, client_token="light") as c:
            assert c.submit(["in.paf", "-o", out],
                            cwd=str(tmp_path))["ok"]


def test_serve_main_validates_fleet_flags(tmp_path):
    err = io.StringIO()
    assert serve_main(["--socket=" + str(tmp_path / "s"),
                       "--listen=nope"], stderr=err) == 1
    assert "--listen" in err.getvalue()
    err = io.StringIO()
    assert serve_main(["--socket=" + str(tmp_path / "s"),
                       "--warmup=gpu"], stderr=err) == 1
    assert "--warmup" in err.getvalue()
    err = io.StringIO()
    assert serve_main(["--socket=" + str(tmp_path / "s"),
                       "--journal-dir= "], stderr=err) == 1
    assert "--journal-dir" in err.getvalue()
    # an explicit --journal would defeat the shared placement a
    # router's --journal-dir computes: refuse the combination
    err = io.StringIO()
    assert serve_main(["--socket=" + str(tmp_path / "s"),
                       "--journal-dir=" + str(tmp_path),
                       "--journal=" + str(tmp_path / "j")],
                      stderr=err) == 1
    assert "mutually exclusive" in err.getvalue()


def test_route_main_validates_flags(tmp_path):
    err = io.StringIO()
    assert route_main([], stderr=err) == 1
    assert "--backends" in err.getvalue()
    err = io.StringIO()
    assert route_main(["--backends=a.sock"], stderr=err) == 1
    assert "--socket" in err.getvalue()
    err = io.StringIO()
    assert route_main(["--backends=a.sock", "--listen=zzz"],
                      stderr=err) == 1
    assert "--listen" in err.getvalue()
    err = io.StringIO()
    assert route_main(["--backends=/x/m.sock,/y/m.sock",
                       "--socket=" + str(tmp_path / "r")],
                      stderr=err) == 1
    assert "distinct" in err.getvalue()
    err = io.StringIO()
    assert route_main(["--backends=a.sock",
                       "--socket=" + str(tmp_path / "r"),
                       "--bogus=1"], stderr=err) == 1
    assert "--bogus" in err.getvalue()


# ---------------------------------------------------------------------------
# router: routing, placement, fair share, aggregation
# ---------------------------------------------------------------------------
def test_router_submit_result_status_inspect_cancel(tmp_path):
    ran: list = []
    with _fleet(n=2, runner=_stub_runner(log=ran)) as f:
        out = str(tmp_path / "o.dfa")
        with ServiceClient(f.sock, trace_id="rt-1") as c:
            p = c.ping()
            assert p["router"] and p["members"] == 2
            sub = c.submit(["in.paf", "-o", out], cwd=str(tmp_path))
            assert sub["ok"] and sub["job_id"].startswith("fleet-")
            assert sub["member"] in f.router.members
            st = c.status(sub["job_id"])
            assert st["ok"] and st["job"]["id"] == sub["job_id"]
            r = c.result(sub["job_id"], timeout=30)
            assert r["rc"] == 0
            # ids rewritten at the edge: the member's job-NNNN never
            # leaks, the fleet id and placement do
            assert r["job"]["id"] == sub["job_id"]
            assert r["job"]["member"] == sub["member"]
            assert r["job"]["trace_id"] == "rt-1"
            ins = c.inspect(sub["job_id"])
            assert ins["ok"] and ins["job"]["id"] == sub["job_id"]
            # unknown ids answer unknown_job, not a crash
            assert c.status("fleet-9999")["error"] == "unknown_job"
            assert c.cancel(sub["job_id"])["ok"]   # terminal: a no-op


def test_router_spreads_by_least_depth(tmp_path):
    with _fleet(n=3, runner=_stub_runner(sleep=0.3)) as f:
        out = lambda k: str(tmp_path / f"o{k}.dfa")
        with ServiceClient(f.sock) as c:
            jids = [c.submit(["in.paf", "-o", out(k)],
                             cwd=str(tmp_path))["job_id"]
                    for k in range(6)]
            for j in jids:
                assert c.result(j, timeout=60)["rc"] == 0
            st = c.stats()["stats"]
        routed = {m["name"]: m["jobs_routed"]
                  for m in st["fleet"]["members"]}
        # 6 jobs over 3 members, least-loaded: every member worked
        assert sum(routed.values()) == 6
        assert all(n >= 1 for n in routed.values()), routed


def test_router_fleet_quota_and_member_spillover(tmp_path):
    """The global ledger: a client at the FLEET quota answers
    queue_full at the router; below it, a member's own queue_full
    spills the job to a sibling instead of bouncing the client."""
    with _fleet(n=2, runner=_stub_runner(sleep=0.4),
                router_kw={"max_queue": 3},
                daemon_kw={"max_queue": 1}) as f:
        out = lambda k: str(tmp_path / f"q{k}.dfa")
        with ServiceClient(f.sock, client_token="t") as c:
            subs = [c.submit(["in.paf", "-o", out(k)],
                             cwd=str(tmp_path)) for k in range(3)]
            assert all(s["ok"] for s in subs), subs
            # member quota is 1/client, but 2 members absorb 3 live
            # jobs (2 running + 1 queued); the FOURTH hits the fleet
            # ledger (quota 3) — rejected at the router, by name
            r = c.submit(["in.paf", "-o", out(9)], cwd=str(tmp_path))
            assert not r.get("ok") and r["error"] == "queue_full"
            assert "FLEET" in r["detail"]
            assert r["client"] == "tok:t"
            for s in subs:
                assert c.result(s["job_id"], timeout=60)["rc"] == 0
        # the three accepted jobs spread over both members
        names = {s["member"] for s in subs}
        assert len(names) == 2


def test_router_aggregated_stats_metrics_and_top(tmp_path):
    with _fleet(n=2, runner=_stub_runner()) as f:
        out = str(tmp_path / "o.dfa")
        with ServiceClient(f.sock, client_token="agg") as c:
            for _ in range(2):
                r = c.result(c.submit(["in.paf", "-o", out],
                                      cwd=str(tmp_path))["job_id"],
                             timeout=30)
                assert r["rc"] == 0
            st = c.stats()["stats"]
            met = c.metrics()["metrics"]
        assert st["router"] is True
        assert st["fleet"]["alive"] == 2
        assert st["fleet"]["jobs_routed"] == 2
        # member jobs counters aggregate (both completions visible)
        assert st["jobs"]["completed"] == 2
        assert st["fair_share"]["clients"] == {}   # all retired
        for fam in ("pwasm_fleet_member_up",
                    "pwasm_fleet_jobs_routed_total",
                    "pwasm_fleet_members 2"):
            assert fam in met, fam
        # the fleet-aware top renders the member table from the same
        # stats dict (pure function)
        frame = render(st)
        assert "FLEET" in frame and "MEMBER" in frame
        assert "up" in frame


def test_router_stream_verbs_forward(tmp_path):
    feeds: list = []

    def stream_runner(argv, stdout=None, stderr=None, warm=None,
                      input_stream=None, **kw):
        if input_stream is not None:
            feeds.append(list(input_stream))
        return 0

    with _fleet(n=2, runner=stream_runner) as f:
        out = str(tmp_path / "s.dfa")
        with ServiceClient(f.sock) as c:
            so = c.stream_open(["-r", "q.fa", "-o", out],
                               cwd=str(tmp_path))
            assert so["ok"], so
            jid = so["job_id"]
            assert jid.startswith("fleet-")
            assert c.stream_data(jid, "rec1\tx\nrec2\t")["ok"]
            assert c.stream_data(jid, "y\n")["ok"]
            end = c.stream_end(jid)
            assert end["ok"] and end["records"] == 2
            r = c.result(jid, timeout=30)
            assert r["rc"] == 0
    assert feeds and [l.rstrip("\n") for l in feeds[0]] \
        == ["rec1\tx", "rec2\ty"]


def test_router_drain_rejects_new_keeps_results(tmp_path):
    with _fleet(n=1, runner=_stub_runner(sleep=0.3)) as f:
        out = str(tmp_path / "d.dfa")
        with ServiceClient(f.sock) as c:
            sub = c.submit(["in.paf", "-o", out], cwd=str(tmp_path))
            assert sub["ok"]
            d = c.drain()
            assert d["ok"] and d["draining"]
            r = c.submit(["in.paf", "-o", out], cwd=str(tmp_path))
            assert r["error"] == "draining"
            # the in-flight job's result stays fetchable through the
            # draining router
            assert c.result(sub["job_id"], timeout=60)["rc"] == 0
        assert f.rc == [] or f.rc[0] == 0


# ---------------------------------------------------------------------------
# failover: unit-level verdicts from a crafted journal
# ---------------------------------------------------------------------------
def _craft_router_with_dead_member(tmp_path, sibling, journal_recs,
                                   stream=False):
    """A router whose member 'ghost' is alive-then-dead with a
    hand-written journal, plus one real sibling to take jobs over."""
    ghost_target = str(tmp_path / "ghost.sock")
    r = Router([sibling.sock, ghost_target], socket_path=None,
               listen="127.0.0.1:0", stderr=io.StringIO(),
               poll_interval=999)
    ghost = r.members["ghost.sock"]
    ghost.alive = True
    ghost.ever_alive = True
    sib = r.members[transport.target_name(sibling.sock)]
    sib.alive = True
    sib.ever_alive = True
    with open(ghost.journal_path, "w") as f:
        for rec in journal_recs:
            f.write(json.dumps(rec) + "\n")
    from pwasm_tpu.fleet.router import _FleetJob
    job = _FleetJob("fleet-0001", "cl1", "", "tr-9",
                    {"args": ["a.paf", "-o",
                              str(tmp_path / "a.dfa")],
                     "cwd": str(tmp_path)},
                    "ghost.sock", "job-0001", stream=stream)
    r.jobs[job.fid] = job
    r.ledger.admit("cl1", "ghost.sock")
    return r, job


def test_failover_started_job_resumes_on_sibling(tmp_path):
    ran: list = []
    with _daemon(runner=_stub_runner(log=ran)) as sib:
        r, job = _craft_router_with_dead_member(tmp_path, sib, [
            {"v": 1, "rec": "admit", "job_id": "job-0001",
             "argv": ["a.paf", "-o", str(tmp_path / "a.dfa")],
             "client": "cl1", "t": 1.0},
            {"v": 1, "rec": "start", "job_id": "job-0001",
             "lane": 0},
        ])
        r._member_down("ghost.sock")
        assert job.member == transport.target_name(sib.sock)
        assert job.gen == 1 and job.failovers == 1
        # the re-admission is a --resume continuation with the SAME
        # trace identity, and the consumed journal is set aside
        deadline = time.monotonic() + 15
        while not ran and time.monotonic() < deadline:
            time.sleep(0.02)
        assert ran and "--resume" in ran[0]
        assert os.path.exists(
            r.members["ghost.sock"].journal_path + ".recovered")
        assert not os.path.exists(
            r.members["ghost.sock"].journal_path)
        with ServiceClient(sib.sock) as c:
            got = c.result(job.mjid, timeout=30)
        assert got["rc"] == 0 and got["job"]["trace_id"] == "tr-9"
        assert r.recovered["resumed"] == 1


def test_failover_unstarted_job_requeues_plain(tmp_path):
    ran: list = []
    with _daemon(runner=_stub_runner(log=ran)) as sib:
        r, job = _craft_router_with_dead_member(tmp_path, sib, [
            {"v": 1, "rec": "admit", "job_id": "job-0001",
             "argv": ["a.paf", "-o", str(tmp_path / "a.dfa")],
             "client": "cl1", "t": 1.0},
        ])
        r._member_down("ghost.sock")
        deadline = time.monotonic() + 15
        while not ran and time.monotonic() < deadline:
            time.sleep(0.02)
        assert ran and "--resume" not in ran[0]
        assert r.recovered["requeued"] == 1


def test_failover_finished_job_served_from_journal_and_spool(
        tmp_path):
    from pwasm_tpu.utils.fsio import payload_crc, write_durable_text
    spool = str(tmp_path / "job-0001.result.json")
    payload = {"version": 1, "job_id": "job-0001", "state": "done",
               "rc": 0, "trace_id": "tr-9", "flight": None,
               "stats": {"alignments": 7}, "stderr_tail": "tail!"}
    payload["crc"] = payload_crc(
        {k: v for k, v in payload.items() if k != "crc"})
    write_durable_text(spool, json.dumps(payload, sort_keys=True,
                                         separators=(",", ":")))
    with _daemon(runner=_stub_runner()) as sib:
        r, job = _craft_router_with_dead_member(tmp_path, sib, [
            {"v": 1, "rec": "admit", "job_id": "job-0001",
             "argv": ["a.paf", "-o", "a.dfa"], "client": "cl1",
             "t": 1.0},
            {"v": 1, "rec": "start", "job_id": "job-0001",
             "lane": 0},
            {"v": 1, "rec": "finish", "job_id": "job-0001",
             "state": "done", "rc": 0,
             "spool": {"path": spool, "bytes": 1}, "t": 2.0},
        ])
        r._member_down("ghost.sock")
        # no re-run: served straight from journal + CRC'd spool
        term = job.terminal
        assert term is not None and term["rc"] == 0
        assert term["stats"] == {"alignments": 7}
        assert term["stderr_tail"] == "tail!"
        assert r.recovered["restored"] == 1
        # a corrupt spool would be reported, never served: covered by
        # the daemon-side CRC tests (same loader)


def test_failover_cancelled_and_stream_verdicts(tmp_path):
    with _daemon(runner=_stub_runner()) as sib:
        r, job = _craft_router_with_dead_member(tmp_path, sib, [
            {"v": 1, "rec": "admit", "job_id": "job-0001",
             "argv": ["a.paf", "-o", "a.dfa"], "client": "cl1",
             "t": 1.0},
            {"v": 1, "rec": "cancel", "job_id": "job-0001"},
        ])
        r._member_down("ghost.sock")
        assert job.terminal["job"]["state"] == "cancelled"
        assert r.recovered["cancelled"] == 1
    with _daemon(runner=_stub_runner()) as sib:
        r, job = _craft_router_with_dead_member(
            tmp_path, sib, [
                {"v": 1, "rec": "admit", "job_id": "job-0001",
                 "argv": ["-r", "q.fa", "-o", "a.dfa"],
                 "client": "cl1", "stream": True, "t": 1.0},
            ], stream=True)
        r._member_down("ghost.sock")
        assert job.terminal["job"]["state"] == "preempted"
        assert job.terminal["rc"] == 75
        assert "--resume" in job.terminal["job"]["detail"]
        assert r.recovered["stream_preempted"] == 1


def test_failover_without_journal_still_resumes(tmp_path):
    """Per-daemon journal on an unreachable host (TCP member, no
    --journal-dir): the router still re-admits with --resume — the
    resume contract restarts cleanly when no ckpt exists."""
    ran: list = []
    with _daemon(runner=_stub_runner(log=ran)) as sib:
        ghost_target = "ghosthost:19999"
        r = Router([sib.sock, ghost_target], socket_path=None,
                   listen="127.0.0.1:0", stderr=io.StringIO(),
                   poll_interval=999)
        for m in r.members.values():
            m.alive = m.ever_alive = True
        assert r.members["ghosthost_19999"].journal_path is None
        from pwasm_tpu.fleet.router import _FleetJob
        job = _FleetJob("fleet-0001", "cl1", "", "tr-9",
                        {"args": ["a.paf", "-o",
                                  str(tmp_path / "a.dfa")],
                         "cwd": str(tmp_path)},
                        "ghosthost_19999", "job-0001")
        r.jobs[job.fid] = job
        r.ledger.admit("cl1", "ghosthost_19999")
        r._member_down("ghosthost_19999")
        deadline = time.monotonic() + 15
        while not ran and time.monotonic() < deadline:
            time.sleep(0.02)
        assert ran and "--resume" in ran[0]
        assert r.recovered["resumed"] == 1


def test_failover_no_sibling_lands_failed(tmp_path):
    r = Router(["/nonexistent/a.sock", "/nonexistent/b.sock"],
               socket_path=None, listen="127.0.0.1:0",
               stderr=io.StringIO(), poll_interval=999)
    for m in r.members.values():
        m.alive = m.ever_alive = True
    from pwasm_tpu.fleet.router import _FleetJob
    job = _FleetJob("fleet-0001", "cl1", "", "tr",
                    {"args": ["a.paf", "-o", "a.dfa"],
                     "cwd": str(tmp_path)}, "a.sock", "job-0001")
    r.jobs[job.fid] = job
    r.ledger.admit("cl1", "a.sock")
    r._member_down("a.sock")
    assert job.terminal["job"]["state"] == "failed"
    assert "resubmit" in job.terminal["job"]["detail"]
    assert r.recovered["failed"] == 1
    # the ledger slot was released: the client is not quota-wedged
    assert r.ledger.client_depths() == {}


# ---------------------------------------------------------------------------
# THE drill: kill one of three daemons behind the router
# ---------------------------------------------------------------------------
def _corpus(tmp_path, n=24, qlen=120, seed=3):
    rng = np.random.default_rng(seed)
    q = "".join("ACGT"[i] for i in rng.integers(0, 4, qlen))
    lines = []
    for i in range(n):
        cut = 10 + int(rng.integers(0, qlen - 40))
        qb = q[cut]
        tb = "ACGT"[("ACGT".index(qb) + 1) % 4]
        ops = [("=", cut), ("*", tb, qb), ("=", 20), ("ins", "gg"),
               ("=", qlen - cut - 21)]
        lines.append(make_paf_line("q", q, f"asm{i}", "+", ops)[0])
    fa = tmp_path / "q.fa"
    write_fasta(str(fa), [("q", q.encode())])
    paf = tmp_path / "in.paf"
    paf.write_text("".join(ln + "\n" for ln in lines))
    return str(paf), str(fa)


def _job_args(tmp_path, tag, paf, fa, extra=()):
    return [paf, "-r", fa, "-o", str(tmp_path / f"{tag}.dfa"),
            "--device=tpu", "--batch=2",
            f"--stats={tmp_path / f'{tag}.json'}"] + list(extra)


def _serve_env():
    old_pp = os.environ.get("PYTHONPATH", "")
    return dict(os.environ, JAX_PLATFORMS="cpu",
                PWASM_DEVICE_PROBE="0",
                PYTHONPATH=REPO + (os.pathsep + old_pp if old_pp
                                   else ""))


def test_kill_one_of_three_members_failover_byte_identical(tmp_path):
    """THE ISSUE 13 acceptance drill: three serve daemons behind one
    router; SIGKILL the member running a mid-run job (after its first
    durable ckpt) → the router reads the dead member's journal,
    resumes the job on a sibling, and every report lands
    byte-identical to the uncrashed arm — with the client-minted
    trace_id intact end-to-end and trace-merge of client + router +
    surviving members yielding one valid timeline."""
    from pwasm_tpu.obs import TraceRecorder
    from pwasm_tpu.obs.merge import merge_traces

    paf, fa = _corpus(tmp_path)
    # the uncrashed arm: cold runs of the same argvs
    from pwasm_tpu.cli import run as cli_run
    assert cli_run(_job_args(tmp_path, "colda", paf, fa, [SLOW]),
                   stderr=io.StringIO()) == 0
    assert cli_run(_job_args(tmp_path, "coldb", paf, fa),
                   stderr=io.StringIO()) == 0
    expect_a = (tmp_path / "colda.dfa").read_bytes()
    expect_b = (tmp_path / "coldb.dfa").read_bytes()

    d = tempfile.mkdtemp(prefix="pwdrill")
    socks, procs = [], []
    member_traces = []
    try:
        for i in range(3):
            s = os.path.join(d, f"m{i}.sock")
            tr = os.path.join(d, f"m{i}.trace")
            p = subprocess.Popen(
                [sys.executable, "-m", "pwasm_tpu.cli", "serve",
                 f"--socket={s}", f"--trace-json={tr}"],
                env=_serve_env(), stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE, text=True)
            socks.append(s)
            procs.append(p)
            member_traces.append(tr)
        for s in socks:
            assert wait_for_socket(s, 60)
        rsock = os.path.join(d, "router.sock")
        rtrace = os.path.join(d, "router.trace")
        router = Router(socks, socket_path=rsock,
                        stderr=io.StringIO(), poll_interval=0.2,
                        trace_json=rtrace)
        rt = threading.Thread(target=router.serve, daemon=True)
        rt.start()
        assert wait_for_socket(rsock, 15)

        ctrace = TraceRecorder()     # the CLIENT side of the story
        with ServiceClient(rsock, trace_id="drill-trace") as c:
            t0 = ctrace.now()
            ja = c.submit(_job_args(tmp_path, "a", paf, fa, [SLOW]),
                          cwd=str(tmp_path))
            ctrace.complete("submit_rpc", t0, trace_id=c.trace_id)
            jb = c.submit(_job_args(tmp_path, "b", paf, fa),
                          cwd=str(tmp_path))
            assert ja["ok"] and jb["ok"], (ja, jb)
            # wait until job a is demonstrably MID-RUN with a ckpt
            ck = str(tmp_path / "a.dfa.ckpt")
            deadline = time.monotonic() + 60
            mid = False
            while time.monotonic() < deadline:
                st = c.status(ja["job_id"])["job"]["state"]
                if st == "running" and os.path.exists(ck):
                    mid = True
                    break
                assert st in ("queued", "running"), st
                time.sleep(0.02)
            assert mid, "job never reached mid-run with a ckpt"
            victim = ja["member"]
            vi = socks.index(router.members[victim].target)
            procs[vi].kill()          # SIGKILL: no drain, no cleanup
            procs[vi].wait(timeout=30)
            t0 = ctrace.now()
            ra = c.result(ja["job_id"], timeout=300)
            ctrace.complete("result_wait", t0, trace_id=c.trace_id)
            rb = c.result(jb["job_id"], timeout=300)
            assert ra.get("rc") == 0, ra
            assert rb.get("rc") == 0, rb
            # identity intact end-to-end, placement visible
            assert ra["job"]["trace_id"] == "drill-trace"
            assert rb["job"]["trace_id"] == "drill-trace"
            assert ra["job"]["member"] != victim
            assert ra["job"]["failovers"] == 1
            st = c.stats()["stats"]
            assert st["fleet"]["failovers"] == 1
            assert st["fleet"]["jobs_recovered"]["resumed"] == 1
            c.drain()
        rt.join(20)
        # byte parity vs the uncrashed arm for BOTH jobs
        assert (tmp_path / "a.dfa").read_bytes() == expect_a
        assert (tmp_path / "b.dfa").read_bytes() == expect_b
        # the victim's journal was set aside, not left to double-run
        assert os.path.exists(socks[vi] + ".journal.recovered")
        # surviving members drain clean and write their traces
        for i, p in enumerate(procs):
            if i == vi:
                continue
            with ServiceClient(socks[i]) as c:
                c.drain()
            assert p.wait(timeout=120) == 75
        ctrace_path = os.path.join(d, "client.trace")
        ctrace.write(ctrace_path)
        docs = [("client", json.load(open(ctrace_path))),
                ("router", json.load(open(rtrace)))]
        for i, tr in enumerate(member_traces):
            if i != vi and os.path.exists(tr):
                docs.append((f"member{i}", json.load(open(tr))))
        assert len(docs) == 4     # client + router + both survivors
        merged = merge_traces(docs)
        events = merged["traceEvents"]
        assert events, "empty merged timeline"
        # one valid timeline: the drill trace_id appears in spans
        # from at least three of the four processes
        pids_with_id = {e["pid"] for e in events
                        if isinstance(e.get("args"), dict)
                        and e["args"].get("trace_id")
                        == "drill-trace"}
        assert len(pids_with_id) >= 3, pids_with_id
        assert merged["otherData"]["merged"] == 4
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
            p.stderr.close()
        shutil.rmtree(d, ignore_errors=True)


# ---------------------------------------------------------------------------
# warmup + persistent compile cache (ROADMAP item 2b satellite)
# ---------------------------------------------------------------------------
def test_warmup_pays_probe_and_populates_compile_cache(tmp_path):
    """serve --warmup --compile-cache-dir: the daemon's warmup job
    pays the backend probe and the device compiles at START, so the
    first real job answers its probe warm — and the compile cache dir
    holds persisted programs for the next restart.  A subprocess
    daemon: conftest deliberately disarms the process-global cache
    inside the pytest interpreter (PWASM_JAX_CACHE=0), so the cache
    behavior can only be observed in a child process."""
    cache = str(tmp_path / "xla-cache")
    d = tempfile.mkdtemp(prefix="pwwarm")
    sock = os.path.join(d, "w.sock")
    env = _serve_env()
    env["PWASM_JAX_CACHE"] = "1"     # re-arm: the child OWNS its cache
    env.pop("PWASM_DEVICE_PROBE", None)   # probes must really happen
    p = subprocess.Popen(
        [sys.executable, "-m", "pwasm_tpu.cli", "serve",
         f"--socket={sock}", "--warmup=tpu",
         f"--compile-cache-dir={cache}"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        text=True)
    try:
        assert wait_for_socket(sock, 60)
        paf, fa = _corpus(tmp_path, n=8)
        # wait for the warmup to land (cache dir fills), then submit
        deadline = time.monotonic() + 120
        while not (os.path.isdir(cache) and os.listdir(cache)):
            assert time.monotonic() < deadline
            assert p.poll() is None
            time.sleep(0.2)
        with ServiceClient(sock) as c:
            sub = c.submit(_job_args(tmp_path, "w1", paf, fa),
                           cwd=str(tmp_path))
            assert sub["ok"], sub
            r = c.result(sub["job_id"], timeout=120)
            c.drain()
        assert r["rc"] == 0, r
        # the warmup paid the probe: the FIRST real job is probe-free
        assert r["stats"]["backend"]["probes"] == 0
        assert r["stats"]["backend"]["warm_hits"] >= 1
        assert p.wait(timeout=120) == 75
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
        p.stderr.close()
        shutil.rmtree(d, ignore_errors=True)


def test_compile_cache_dir_flag_cold_run(tmp_path):
    """--compile-cache-dir on a cold run: the dir is created and
    populated, and a second run with the same dir stays
    byte-identical (the cache is an optimization, never bytes)."""
    paf, fa = _corpus(tmp_path, n=8)
    cache = str(tmp_path / "cc")
    env = _serve_env()
    env["PWASM_JAX_CACHE"] = "1"     # conftest disarms it by default
    outs = []
    for tag in ("c1", "c2"):
        args = _job_args(tmp_path, tag, paf, fa,
                         [f"--compile-cache-dir={cache}"])
        r = subprocess.run(
            [sys.executable, "-m", "pwasm_tpu.cli"] + args,
            env=env, capture_output=True)
        assert r.returncode == 0, r.stderr.decode()[:2000]
        outs.append((tmp_path / f"{tag}.dfa").read_bytes())
    assert outs[0] == outs[1]
    assert os.path.isdir(cache) and os.listdir(cache)


def test_warmup_files_deterministic(tmp_path):
    from pwasm_tpu.cli import warmup_files
    p1 = warmup_files(str(tmp_path / "w1"))
    p2 = warmup_files(str(tmp_path / "w2"))
    assert open(p1[0]).read() == open(p2[0]).read()
    assert open(p1[1]).read() == open(p2[1]).read()
    # the corpus parses: a cold host run completes on it
    from pwasm_tpu.cli import run as cli_run
    err = io.StringIO()
    rc = cli_run([p1[0], "-r", p1[1],
                  "-o", str(tmp_path / "w.dfa")], stderr=err)
    assert rc == 0, err.getvalue()
    assert (tmp_path / "w.dfa").read_bytes()


def test_router_job_table_bounded_lru(tmp_path):
    """Review hardening: retired routed jobs are evicted past
    --max-results (LRU by access) so a long-lived router's job table
    (and its health-loop scans) stay bounded; evicted fleet ids answer
    unknown_job like the daemon's own eviction."""
    with _fleet(n=1, runner=_stub_runner(),
                router_kw={"max_results": 2}) as f:
        out = str(tmp_path / "e.dfa")
        with ServiceClient(f.sock) as c:
            jids = []
            for _ in range(5):
                s = c.submit(["in.paf", "-o", out], cwd=str(tmp_path))
                assert s["ok"]
                assert c.result(s["job_id"], timeout=30)["rc"] == 0
                jids.append(s["job_id"])
            deadline = time.monotonic() + 15
            while len(f.router.jobs) > 2:
                assert time.monotonic() < deadline, \
                    sorted(f.router.jobs)
                time.sleep(0.05)
            r = c.status(jids[0])
            assert r["error"] == "unknown_job"
            # the most recent job survives the LRU
            assert c.status(jids[-1])["ok"]


def test_router_stream_conn_closed_on_terminal(tmp_path):
    """Review hardening: a stream job's persistent member connection
    is released once the job lands terminal — no fd/thread leak per
    stream."""
    def stream_runner(argv, stdout=None, stderr=None, warm=None,
                      input_stream=None, **kw):
        if input_stream is not None:
            list(input_stream)
        return 0

    with _fleet(n=1, runner=stream_runner) as f:
        out = str(tmp_path / "sc.dfa")
        with ServiceClient(f.sock) as c:
            so = c.stream_open(["-r", "q.fa", "-o", out],
                               cwd=str(tmp_path))
            assert so["ok"], so
            job = f.router.jobs[so["job_id"]]
            assert job.sconn is not None
            c.stream_data(so["job_id"], "r1\tx\n")
            c.stream_end(so["job_id"])
            assert c.result(so["job_id"], timeout=30)["rc"] == 0
            deadline = time.monotonic() + 15
            while job.sconn is not None:
                assert time.monotonic() < deadline
                time.sleep(0.05)
            assert job.retired


def test_poll_death_needs_consecutive_strikes():
    """Review hardening: one failed health poll (a 3s stats RPC can
    time out under member load) must NOT declare a live member dead —
    a spurious failover re-runs jobs a live member still owns.  Two
    consecutive failures do."""
    r = Router(["/nonexistent/ghost.sock"], socket_path=None,
               listen="127.0.0.1:0", stderr=io.StringIO(),
               poll_interval=999)
    m = r.members["ghost.sock"]
    m.alive = m.ever_alive = True
    m.fail_streak = 0
    # a stats-request refresh (count_failures=False) NEVER strikes:
    # only the single-threaded health loop may count, else two
    # concurrent polls double-count one stall into a failover
    r._poll_members()
    assert m.alive and m.fail_streak == 0
    r._poll_members(count_failures=True)
    assert m.alive and m.fail_streak == 1     # one strike: still up
    r._poll_members(count_failures=True)
    assert not m.alive                        # two strikes: down


def test_failover_finished_stream_served_not_resent(tmp_path):
    """Review hardening: a stream job whose FINISH is durably
    journaled before the member died gets its restored verdict, not
    a preempted 're-send the records' — journal rows outrank the
    stream flag, mirroring the member's own restart replay order."""
    with _daemon(runner=_stub_runner()) as sib:
        r, job = _craft_router_with_dead_member(
            tmp_path, sib, [
                {"v": 1, "rec": "admit", "job_id": "job-0001",
                 "argv": ["-r", "q.fa", "-o", "a.dfa"],
                 "client": "cl1", "stream": True, "t": 1.0},
                {"v": 1, "rec": "start", "job_id": "job-0001",
                 "lane": 0},
                {"v": 1, "rec": "finish", "job_id": "job-0001",
                 "state": "done", "rc": 0, "t": 2.0},
            ], stream=True)
        r._member_down("ghost.sock")
        assert job.terminal["job"]["state"] == "done"
        assert job.terminal["rc"] == 0
        assert r.recovered["restored"] == 1
        assert r.recovered["stream_preempted"] == 0


def test_orphan_rescue_resolves_journal_itself(tmp_path):
    """Review hardening: a result-waiter rescuing a job the death
    snapshot missed calls _recover_job with no pre-folded row — the
    method must read the dead member's journal itself, so a durably
    finished (or cancelled) job is served, never blindly re-run with
    --resume."""
    with _daemon(runner=_stub_runner()) as sib:
        r, job = _craft_router_with_dead_member(tmp_path, sib, [
            {"v": 1, "rec": "admit", "job_id": "job-0001",
             "argv": ["a.paf", "-o", "a.dfa"], "client": "cl1",
             "t": 1.0},
            {"v": 1, "rec": "start", "job_id": "job-0001",
             "lane": 0},
            {"v": 1, "rec": "finish", "job_id": "job-0001",
             "state": "done", "rc": 0, "t": 2.0},
        ])
        r.members["ghost.sock"].alive = False   # death already noted
        r._recover_job(job)                     # row resolved inside
        assert job.terminal["job"]["state"] == "done"
        assert r.recovered["restored"] == 1
        assert r.recovered["resumed"] == 0
