"""Vectorized report byte assembly (report/rowbytes.py): byte-exact
parity with the scalar ``format_event_row`` emit loop over adversarial
corpora (IUPAC, oversize events, reverse-strand clips, empty batches),
the ``PWASM_HOST_FORMAT``/``PWASM_HOST_PIPELINE`` escape hatches, the
batched ``-s`` summary writer, and the warm-serve format-buffer reuse."""

import io
import threading

import numpy as np
import pytest

from pwasm_tpu.cli import run
from pwasm_tpu.core.dna import revcomp
from pwasm_tpu.core.events import DiffEvent, extract_alignment
from pwasm_tpu.core.paf import parse_paf_line
from pwasm_tpu.report.columnar import _analyze_batch, emit_batch_rows
from pwasm_tpu.report.diff_report import (Summary, format_event_row,
                                          format_header)
from pwasm_tpu.report.rowbytes import (FormatBuffers, format_batch_block,
                                       get_buffers,
                                       vector_format_enabled)

from helpers import make_paf_line
from test_events import _random_ops


def _alignment(q, line):
    rec = parse_paf_line(line)
    refseq_aln = revcomp(q) if rec.alninfo.reverse else q
    return extract_alignment(rec, refseq_aln), refseq_aln


def _scalar_block(batch, analyzed, summary):
    """The ground-truth scalar emit loop (format_header +
    Summary.add_event + format_event_row, per row)."""
    rows = []
    for aln, rlabel, tlabel, _refseq in batch:
        rows.append(format_header(aln, rlabel, tlabel))
        if summary is not None:
            summary.add_alignment(aln)
        for di in aln.tdiffs:
            aa, aapos, rctx, status, impact = analyzed[id(di)]
            if summary is not None:
                summary.add_event(di, status, impact)
            rows.append(format_event_row(di, aa, aapos, rctx, status,
                                         impact))
    return "".join(rows)


def _assert_block_parity(batch, analyzed):
    s_vec, s_sca = Summary(), Summary()
    vec = format_batch_block(batch, analyzed, s_vec)
    sca = _scalar_block(batch, analyzed, s_sca)
    assert vec == sca
    assert s_vec == s_sca          # dataclass: all counter fields
    # the no-summary arm must produce the same bytes
    assert format_batch_block(batch, analyzed, None) == sca


def _fuzz_batch(rng, n_aln, with_clips=False):
    batch = []
    for k in range(n_aln):
        n = int(rng.integers(40, 200))
        q = "".join(rng.choice(list("ACGT"), size=n))
        strand = "-" if k % 2 else "+"
        q_aln = revcomp(q.encode()).decode() if strand == "-" else q
        kw = {}
        if with_clips and n > 60:
            # reverse-strand clips: aligned window strictly inside the
            # query, so the extraction path sees soft-clipped ends
            kw = {"q_start": 9, "q_end": n - 12}
            q_aln = q_aln[12:n - 9] if strand == "-" \
                else q_aln[9:n - 12]
        ops = _random_ops(rng, q_aln)
        line, _ = make_paf_line("q", q, f"t{k}", strand, ops, **kw)
        aln, _refseq_aln = _alignment(q.encode(), line)
        batch.append((aln, "q", f"t{k}", q.encode().upper()))
    return batch


@pytest.mark.parametrize("skip_codan", [False, True])
@pytest.mark.parametrize("with_clips", [False, True])
def test_fuzz_parity_vectorized_vs_scalar(skip_codan, with_clips):
    rng = np.random.default_rng(17 if with_clips else 23)
    for trial in range(6):
        batch = _fuzz_batch(rng, int(rng.integers(1, 9)), with_clips)
        analyzed = _analyze_batch(batch, skip_codan, ["GGCGG"])
        _assert_block_parity(batch, analyzed)


def test_parity_iupac_and_oversize_events():
    # IUPAC bytes must survive the assembly verbatim, and the three
    # [len] truncation rules (evtbases, evtsub, tctx) must reproduce
    # the scalar path's exact output — analysis tuples are fabricated
    # so every branch is pinned regardless of analyzer routing
    rng = np.random.default_rng(3)
    batch = _fuzz_batch(rng, 2)
    aln = batch[0][0]
    aln.tdiffs = [
        DiffEvent(evt="S", evtlen=1, evtbases=b"R", evtsub=b"N",
                  rloc=4, tloc=4, tctx=b"GGNNC"),
        DiffEvent(evt="I", evtlen=30, evtbases=b"Y" * 30, evtsub=b"",
                  rloc=8, tloc=8, tctx=b"A" * 40),   # both oversize
        DiffEvent(evt="S", evtlen=1, evtbases=b"C" * 13,
                  evtsub=b"G" * 13, rloc=12, tloc=12,
                  tctx=b"ACGTACGTACGTACGTACGTAC"),    # 22 == limit
        DiffEvent(evt="D", evtlen=44, evtbases=b"T" * 44, evtsub=b"",
                  rloc=15, tloc=15, tctx=b"ACGRYSWKMBDHVN" * 4),
        DiffEvent(evt="I", evtlen=12, evtbases=b"A" * 12, evtsub=b"",
                  rloc=18, tloc=18, tctx=b"ACGTACGTACGTACGTACGTACG"),
    ]
    impacts = ["synonymous", "premature stop at AA7",
               "frame shift MK+:M.+", "AA3|K:R", ""]
    statuses = ["homopolymer", "motif GGCGG", "[unknown]",
                "motif AAA", "[unknown]"]
    analyzed = {}
    for di, st, im in zip(aln.tdiffs, statuses, impacts):
        analyzed[id(di)] = ("K", 3, b"ACNRYACGT", st, im)
    for di in batch[1][0].tdiffs:
        analyzed[id(di)] = ("M", 1, b"ATGGCCTGG", "[unknown]", "")
    _assert_block_parity(batch, analyzed)


def test_parity_empty_batches():
    assert format_batch_block([], {}, Summary()) == ""
    # header-only alignment (no events): the summary still counts it
    q = "ACGT" * 30
    line, _ = make_paf_line("q", q, "t0", "+", [("=", len(q))])
    aln, _ = _alignment(q.encode(), line)
    assert aln.tdiffs == []
    batch = [(aln, "q", "t0", q.encode())]
    _assert_block_parity(batch, {})


def test_emit_batch_rows_env_hatch(monkeypatch):
    # PWASM_HOST_FORMAT=0 routes emit_batch_rows through the scalar
    # loop; both routes produce the same bytes and summary
    rng = np.random.default_rng(29)
    batch = _fuzz_batch(rng, 4)
    analyzed = _analyze_batch(batch, False, ["GGCGG"])
    outs = {}
    for flag in ("1", "0"):
        monkeypatch.setenv("PWASM_HOST_FORMAT", flag)
        assert vector_format_enabled() == (flag == "1")
        sink, summ = io.StringIO(), Summary()
        emit_batch_rows(batch, analyzed, sink, summ)
        outs[flag] = (sink.getvalue(), summ)
    assert outs["1"] == outs["0"]


def _cli_corpus(tmp_path, rng, n=14):
    q = "".join(rng.choice(list("ACGT"), size=240))
    lines = []
    for k in range(n):
        strand = "-" if k % 3 == 0 else "+"
        kw = {"q_start": 6, "q_end": 228} if k % 4 == 0 else {}
        q_aln = revcomp(q.encode()).decode() if strand == "-" else q
        if kw:
            q_aln = q_aln[12:234] if strand == "-" else q_aln[6:228]
        ops = _random_ops(rng, q_aln)
        lines.append(make_paf_line("q", q, f"t{k}", strand, ops,
                                   **kw)[0])
    fa = tmp_path / "q.fa"
    fa.write_text(f">q\n{q}\n")
    paf = tmp_path / "in.paf"
    paf.write_text("".join(l + "\n" for l in lines))
    return fa, paf


def test_cli_hatches_byte_identical(tmp_path, monkeypatch):
    # end-to-end A/B/C: vectorized+pipelined (default), scalar format
    # (PWASM_HOST_FORMAT=0), synchronous (PWASM_HOST_PIPELINE=0) —
    # report, -s and -w bytes identical across all arms
    fa, paf = _cli_corpus(tmp_path, np.random.default_rng(31))
    outs = {}
    for tag, fmt, pipe in (("vec", "1", "1"), ("sca", "0", "1"),
                           ("sync", "1", "0"), ("scasync", "0", "0")):
        monkeypatch.setenv("PWASM_HOST_FORMAT", fmt)
        monkeypatch.setenv("PWASM_HOST_PIPELINE", pipe)
        rep = tmp_path / f"{tag}.dfa"
        summ = tmp_path / f"{tag}.sum"
        msa = tmp_path / f"{tag}.mfa"
        rc = run([str(paf), "-r", str(fa), "-o", str(rep),
                  "-s", str(summ), "-w", str(msa), "--batch=5"],
                 stderr=io.StringIO())
        assert rc == 0
        outs[tag] = (rep.read_bytes() + summ.read_bytes()
                     + msa.read_bytes())
    assert len(set(outs.values())) == 1


def test_summary_write_batched_single_call():
    # the -s writer assembles one block and issues ONE write (the same
    # batching contract as the report emit path)
    s = Summary()
    s.fold_event_counts({"S": 3, "I": 1, "D": 2},
                        {"S": 3, "I": 4, "D": 9},
                        {"homopolymer": 2, "motif": 1, "unknown": 3},
                        {"synonymous": 1, "nonsynonymous": 1,
                         "premature_stop": 0, "frame_shift": 1})

    class CountingIO(io.StringIO):
        writes = 0

        def write(self, s_):
            CountingIO.writes += 1
            return super().write(s_)

    sink = CountingIO()
    s.write(sink)
    assert CountingIO.writes == 1
    body = sink.getvalue()
    assert body.startswith("# pwasm-tpu event summary\n")
    assert "events_total\t6\n" in body
    assert "substitutions\t3\t3 bases\n" in body
    assert "deletions\t2\t9 bases\n" in body
    assert "cause_homopolymer\t2\n" in body
    assert "impact_frame_shift\t1\n" in body


def test_format_buffers_thread_local_reuse():
    # the per-thread scratch list persists across batches — steady
    # state does zero list reallocations — and threads never share it
    rng = np.random.default_rng(41)
    batch = _fuzz_batch(rng, 2)
    analyzed = _analyze_batch(batch, False, ["GGCGG"])
    buf = get_buffers()
    assert isinstance(buf, FormatBuffers)
    n0, rows_obj = buf.batches, buf.rows
    format_batch_block(batch, analyzed, None)
    format_batch_block(batch, analyzed, None)
    assert buf.batches == n0 + 2
    assert buf.rows is rows_obj        # same grown list object
    assert buf.rows == []              # transient contents dropped
    other = []
    t = threading.Thread(target=lambda: other.append(get_buffers()))
    t.start()
    t.join()
    assert other[0] is not buf


def test_host_cli_never_imports_jax(tmp_path):
    # the cold host wall's biggest term was an accidental ~1.2 s jax
    # import (report/columnar.py -> pwasm_tpu.ops.__init__ ->
    # ops/consensus.py); the ops re-exports are lazy now and the numpy
    # consensus twin lives in ops/consensus_host.py — gate the full
    # host output set (report, -s, -w, --cons) staying jax-free
    import os
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fa, paf = _cli_corpus(tmp_path, np.random.default_rng(47), n=6)
    code = (
        "import sys, io\n"
        "from pwasm_tpu.cli import run\n"
        f"rc = run([{str(paf)!r}, '-r', {str(fa)!r},"
        f" '-o', {str(tmp_path / 'j.dfa')!r},"
        f" '-s', {str(tmp_path / 'j.sum')!r},"
        f" '-w', {str(tmp_path / 'j.mfa')!r},"
        f" '--cons={tmp_path / 'j.cons'}'], stderr=io.StringIO())\n"
        "assert rc == 0\n"
        "assert 'jax' not in sys.modules, 'host path imported jax'\n")
    env = dict(os.environ, PYTHONPATH=repo)
    r = subprocess.run([_sys.executable, "-c", code],
                       capture_output=True, text=True, env=env)
    assert r.returncode == 0, r.stderr[-2000:]


def test_warm_context_shares_host_executor(tmp_path):
    # consecutive warm jobs reuse ONE host-pipeline worker (and its
    # thread-local FormatBuffers): no per-job thread/buffer allocation
    from pwasm_tpu.service.daemon import WarmContext

    fa, paf = _cli_corpus(tmp_path, np.random.default_rng(43), n=8)
    ctx = WarmContext()
    bodies = []
    for j in (1, 2):
        rep = tmp_path / f"warm{j}.dfa"
        summ = tmp_path / f"warm{j}.sum"
        rc = run([str(paf), "-r", str(fa), "-o", str(rep),
                  "-s", str(summ), "--batch=3"],
                 stderr=io.StringIO(), warm=ctx)
        assert rc == 0
        bodies.append(rep.read_bytes() + summ.read_bytes())
        assert ctx.host_pool is not None
        if j == 1:
            pool = ctx.host_pool
        else:
            assert ctx.host_pool is pool   # job 2 reused job 1's
    assert bodies[0] == bodies[1]
    # the worker's scratch saw both jobs' batches (cross-job reuse)
    seen = pool.submit(lambda: get_buffers().batches).result()
    assert seen >= 2
    ctx.close()
    assert ctx.host_pool is None
    # cold runs own (and retire) their worker — warm state untouched
    rc = run([str(paf), "-r", str(fa), "-o",
              str(tmp_path / "cold.dfa")], stderr=io.StringIO())
    assert rc == 0 and ctx.host_pool is None
