"""Real-signal end-to-end drill (ISSUE 5 satellite, ROADMAP open item
from PR 4): the scripted ``preempt=N`` leg proves the drain machinery
deterministically, but only an ACTUAL ``SIGTERM`` delivered to a live
CLI subprocess proves the handler installation, the signal-safe stderr
path, and the exit-code plumbing end to end.  Timing-tolerant by
design: the drill waits for the first durable batch checkpoint before
signalling (so the signal provably lands mid-run), and retries when
the race is lost to a fast machine.
"""

import io
import os
import signal
import subprocess
import sys
import time

import pytest

from pwasm_tpu.cli import CKPT_VERSION, _load_checkpoint, run

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_corpus(tmp_path, n_aln=200):
    from test_realistic_scale import make_corpus
    qseq, lines = make_corpus(n_aln=n_aln)
    fa = tmp_path / "cds.fa"
    fa.write_text(f">cds1\n{qseq}\n")
    paf = tmp_path / "in.paf"
    paf.write_text("".join(ln + "\n" for ln in lines))
    return str(paf), str(fa)


def test_real_sigterm_mid_report_exit75_valid_ckpt_resume_parity(
        tmp_path):
    """SIGTERM a real CLI subprocess mid-report: exit 75, a verifying
    v2 checkpoint on disk, and a ``--resume`` completion
    byte-identical to the uninterrupted run."""
    paf, fa = _write_corpus(tmp_path)
    # the uninterrupted reference (in-process, default engine — the
    # engines are byte-identical by contract, so the scalar-engine
    # subprocess below must still match)
    ref = tmp_path / "ref.dfa"
    err = io.StringIO()
    assert run([paf, "-r", fa, "-o", str(ref)], stderr=err) == 0, \
        err.getvalue()[:2000]
    ref_bytes = ref.read_bytes()

    old_pp = os.environ.get("PYTHONPATH", "")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PWASM_DEVICE_PROBE="0",
               # the scalar host engine: ~2x slower per alignment, so
               # the post-checkpoint window the signal must hit is
               # wide on any machine
               PWASM_HOST_COLUMNAR="0",
               PYTHONPATH=REPO + (os.pathsep + old_pp if old_pp
                                  else ""))
    caught = False
    for attempt in range(4):
        rep = tmp_path / f"sig{attempt}.dfa"
        ckpt = str(rep) + ".ckpt"
        proc = subprocess.Popen(
            [sys.executable, "-m", "pwasm_tpu.cli", paf, "-r", fa,
             "-o", str(rep), "--batch=4"],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE, text=True)
        try:
            # arm the signal only once the FIRST batch checkpoint is
            # durable: by then the handler is installed and the run is
            # provably mid-report (~50 batch boundaries remain)
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                if os.path.exists(ckpt) or proc.poll() is not None:
                    break
                time.sleep(0.002)
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
            rc = proc.wait(timeout=120)
            tail = proc.stderr.read()[-2000:]
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
            proc.stderr.close()
        if rc == 0:
            # the run beat the signal (fast machine): the output must
            # still be whole — then try again
            assert rep.read_bytes() == ref_bytes
            continue
        assert rc == 75, (rc, tail)
        assert "draining" in tail, tail
        # the final checkpoint verifies whole: version + CRC + record
        # boundary against the actual report
        got = _load_checkpoint(str(rep))
        assert isinstance(got, tuple), got
        nbytes, nrec, _res = got
        assert nrec > 0
        import json
        ck = json.loads(open(ckpt).read())
        assert ck["version"] == CKPT_VERSION == 2
        # and --resume completes it byte-identically
        err = io.StringIO()
        rc = run([paf, "-r", fa, "-o", str(rep), "--resume"],
                 stderr=err)
        assert rc == 0, err.getvalue()[:2000]
        assert rep.read_bytes() == ref_bytes
        caught = True
        break
    if not caught:
        pytest.skip("machine outran SIGTERM delivery on every "
                    "attempt (outputs stayed byte-identical)")


def test_real_sigterm_before_handler_leaves_resumable_state(tmp_path):
    """The ugly window: a SIGTERM racing process startup (before the
    handler is installed) kills the process with the default
    disposition — whatever landed must STILL resume to a
    byte-identical report (the durability contract has no grace
    period)."""
    paf, fa = _write_corpus(tmp_path, n_aln=60)
    ref = tmp_path / "ref.dfa"
    err = io.StringIO()
    assert run([paf, "-r", fa, "-o", str(ref)], stderr=err) == 0, \
        err.getvalue()[:2000]
    old_pp = os.environ.get("PYTHONPATH", "")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PWASM_DEVICE_PROBE="0",
               PYTHONPATH=REPO + (os.pathsep + old_pp if old_pp
                                  else ""))
    rep = tmp_path / "early.dfa"
    proc = subprocess.Popen(
        [sys.executable, "-m", "pwasm_tpu.cli", paf, "-r", fa,
         "-o", str(rep), "--batch=4"],
        env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)
    # no synchronization on purpose: the signal lands wherever startup
    # happens to be — default-killed (-15), drained (75), or done (0)
    time.sleep(0.05)
    proc.send_signal(signal.SIGTERM)
    rc = proc.wait(timeout=120)
    assert rc in (0, 75, -signal.SIGTERM), rc
    err = io.StringIO()
    assert run([paf, "-r", fa, "-o", str(rep), "--resume"],
               stderr=err) == 0, err.getvalue()[:2000]
    assert rep.read_bytes() == ref.read_bytes()
