"""Native C++ host core: extraction parity vs the Python path and the
single-core banded Gotoh baseline."""

import numpy as np
import pytest

from pwasm_tpu.core.dna import revcomp
from pwasm_tpu.core.errors import PwasmError
from pwasm_tpu.core.events import extract_alignment
from pwasm_tpu.core.paf import parse_paf_line
from pwasm_tpu.native import (
    banded_gotoh_batch,
    extract_native,
    native_available,
)
from pwasm_tpu.ops.banded_dp import ScoreParams, band_dlo, full_gotoh_score

from helpers import make_paf_line
from test_events import _random_ops

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native library unavailable")


def _aln_tuple(aln):
    return (aln.tseq, aln.offset, aln.seqlen,
            [(e.evt, e.rloc, e.tloc, e.evtlen, e.evtbases, e.evtsub,
              e.tctx) for e in aln.tdiffs],
            [(g.pos, g.len) for g in aln.rgaps],
            [(g.pos, g.len) for g in aln.tgaps])


@pytest.mark.parametrize("strand", ["+", "-"])
@pytest.mark.parametrize("seed", range(8))
def test_extraction_parity(strand, seed):
    rng = np.random.default_rng(300 + seed)
    q = "".join(rng.choice(list("ACGT"), size=int(rng.integers(60, 160))))
    q_start = int(rng.integers(0, 8))
    q_end = len(q) - int(rng.integers(0, 8))
    if strand == "-":
        q_aln = revcomp(q.encode()).decode()[len(q) - q_end:len(q) - q_start]
    else:
        q_aln = q[q_start:q_end]
    ops = _random_ops(rng, q_aln)
    line, _ = make_paf_line("q", q, "t", strand, ops,
                            q_start=q_start, q_end=q_end)
    rec = parse_paf_line(line)
    refseq_aln = revcomp(q.encode()) if rec.alninfo.reverse else q.encode()
    py = extract_alignment(rec, refseq_aln, use_native=False)
    nat = extract_native(rec, refseq_aln)
    assert _aln_tuple(nat) == _aln_tuple(py)


def test_native_error_base_mismatch():
    q = "ACGTACGTAC"
    line, _ = make_paf_line("q", q, "t", "+",
                            [("=", 3), ("*", "a", "t"), ("=", 6)])
    line = line.replace("*at", "*ag")
    rec = parse_paf_line(line)
    with pytest.raises(PwasmError, match="base mismatch"):
        extract_native(rec, q.encode())


def test_ref_overrun_error_parity():
    """A cs walk that reads past the query end must raise the same
    PwasmError on both the Python and native paths (the PAF fields are
    internally consistent; only the FASTA is shorter than claimed)."""
    q = "ACGTACGTAC"
    line, _ = make_paf_line("q", q, "t", "+", [("=", 10)])
    rec = parse_paf_line(line)
    short_ref = q.encode()[:7]  # FASTA shorter than the claimed r_len
    errs = []
    for fn in (lambda: extract_alignment(rec, short_ref, use_native=False),
               lambda: extract_native(rec, short_ref)):
        with pytest.raises(PwasmError, match="parsing cs string") as ei:
            fn()
        errs.append(str(ei.value))
    assert errs[0] == errs[1]

    # same for a '+' (deleted-bases) run past the end
    line2, _ = make_paf_line("q", q, "t", "+", [("=", 6), ("del", 4)])
    rec2 = parse_paf_line(line2)
    errs2 = []
    for fn in (lambda: extract_alignment(rec2, short_ref, use_native=False),
               lambda: extract_native(rec2, short_ref)):
        with pytest.raises(PwasmError, match="parsing cs string") as ei:
            fn()
        errs2.append(str(ei.value))
    assert errs2[0] == errs2[1]


def test_native_error_splice_and_lengths():
    q = "ACGTACGTAC"
    line, _ = make_paf_line("q", q, "t", "+", [("=", 10)])
    rec = parse_paf_line(line.replace("cs:Z::10", "cs:Z::5~gt4ac:5"))
    with pytest.raises(PwasmError, match="spliced"):
        extract_native(rec, q.encode())
    rec2 = parse_paf_line(line.replace("cg:Z:10M", "cg:Z:9M"))
    with pytest.raises(PwasmError, match="length mismatch"):
        extract_native(rec2, q.encode())


def test_native_buffer_growth_long_insertion():
    # an insertion far larger than the initial arena guess
    rng = np.random.default_rng(1)
    q = "".join(rng.choice(list("ACGT"), size=50))
    ins = "".join(rng.choice(list("acgt"), size=3000))
    line, _ = make_paf_line("q", q, "t", "+",
                            [("=", 25), ("ins", ins), ("=", 25)])
    rec = parse_paf_line(line)
    py = extract_alignment(rec, q.encode(), use_native=False)
    nat = extract_native(rec, q.encode())
    assert _aln_tuple(nat) == _aln_tuple(py)


def test_banded_gotoh_matches_oracle():
    rng = np.random.default_rng(5)
    p = ScoreParams()
    m = 40
    q = rng.integers(0, 4, size=m).astype(np.int8)
    targets, lens = [], []
    n_pad = 56
    for _ in range(8):
        t = list(q)
        for _ in range(int(rng.integers(0, 4))):
            t[int(rng.integers(0, len(t)))] = int(rng.integers(0, 4))
        if rng.random() < 0.5 and len(t) > 10:
            del t[int(rng.integers(1, len(t) - 1))]
        pad = np.full(n_pad, 127, dtype=np.int8)
        pad[:len(t)] = t
        targets.append(pad)
        lens.append(len(t))
    ts = np.stack(targets)
    tl = np.array(lens, dtype=np.int32)
    dlo = band_dlo(m, n_pad, 32)
    got = banded_gotoh_batch(q, ts, tl, 32, dlo, p.match, p.mismatch,
                             p.gap_open, p.gap_extend)
    for k in range(8):
        assert got[k] == full_gotoh_score(q, targets[k][:lens[k]], p)


def test_native_jax_banded_parity():
    import jax.numpy as jnp

    from pwasm_tpu.ops.banded_dp import banded_scores_batch

    rng = np.random.default_rng(9)
    p = ScoreParams()
    m = 48
    q = rng.integers(0, 4, size=m).astype(np.int8)
    n_pad = 64
    ts = np.full((10, n_pad), 127, dtype=np.int8)
    tl = np.zeros(10, dtype=np.int32)
    for k in range(10):
        t = list(q)
        for _ in range(int(rng.integers(0, 5))):
            t[int(rng.integers(0, len(t)))] = int(rng.integers(0, 4))
        ts[k, :len(t)] = t
        tl[k] = len(t)
    dlo = band_dlo(m, n_pad, 32)
    nat = banded_gotoh_batch(q, ts, tl, 32, dlo, p.match, p.mismatch,
                             p.gap_open, p.gap_extend)
    jx = np.asarray(banded_scores_batch(jnp.asarray(q), jnp.asarray(ts),
                                        jnp.asarray(tl), band=32))
    np.testing.assert_array_equal(nat, jx)



def test_cli_uses_native_transparently(tmp_path):
    # end-to-end through the CLI with the native extractor active
    from io import StringIO

    from pwasm_tpu.cli import run
    from pwasm_tpu.core.fasta import write_fasta

    fa = tmp_path / "q.fa"
    write_fasta(str(fa), [("q", b"ACGTACGTAC")])
    line, _ = make_paf_line("q", "ACGTACGTAC", "asm1", "+",
                            [("=", 3), ("*", "a", "t"), ("=", 6)])
    paf = tmp_path / "in.paf"
    paf.write_text(line + "\n")
    out = StringIO()
    assert run([str(paf), "-r", str(fa)], stdout=out,
               stderr=StringIO()) == 0
    assert "S\t4\t" in out.getvalue()
