"""Native C++ host core: extraction parity vs the Python path and the
single-core banded Gotoh baseline."""

import os

import numpy as np
import pytest

from pwasm_tpu.core.dna import revcomp
from pwasm_tpu.core.errors import PwasmError
from pwasm_tpu.core.events import extract_alignment
from pwasm_tpu.core.paf import parse_paf_line
from pwasm_tpu.native import (
    banded_gotoh_batch,
    extract_native,
    native_available,
)
from pwasm_tpu.ops.banded_dp import ScoreParams, band_dlo, full_gotoh_score

from helpers import make_paf_line
from test_events import _random_ops

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native library unavailable")


def _aln_tuple(aln):
    return (aln.tseq, aln.offset, aln.seqlen,
            [(e.evt, e.rloc, e.tloc, e.evtlen, e.evtbases, e.evtsub,
              e.tctx) for e in aln.tdiffs],
            [(g.pos, g.len) for g in aln.rgaps],
            [(g.pos, g.len) for g in aln.tgaps])


@pytest.mark.parametrize("strand", ["+", "-"])
@pytest.mark.parametrize("seed", range(8))
def test_extraction_parity(strand, seed):
    rng = np.random.default_rng(300 + seed)
    q = "".join(rng.choice(list("ACGT"), size=int(rng.integers(60, 160))))
    q_start = int(rng.integers(0, 8))
    q_end = len(q) - int(rng.integers(0, 8))
    if strand == "-":
        q_aln = revcomp(q.encode()).decode()[len(q) - q_end:len(q) - q_start]
    else:
        q_aln = q[q_start:q_end]
    ops = _random_ops(rng, q_aln)
    line, _ = make_paf_line("q", q, "t", strand, ops,
                            q_start=q_start, q_end=q_end)
    rec = parse_paf_line(line)
    refseq_aln = revcomp(q.encode()) if rec.alninfo.reverse else q.encode()
    py = extract_alignment(rec, refseq_aln, use_native=False)
    nat = extract_native(rec, refseq_aln)
    assert _aln_tuple(nat) == _aln_tuple(py)


def test_native_error_base_mismatch():
    q = "ACGTACGTAC"
    line, _ = make_paf_line("q", q, "t", "+",
                            [("=", 3), ("*", "a", "t"), ("=", 6)])
    line = line.replace("*at", "*ag")
    rec = parse_paf_line(line)
    with pytest.raises(PwasmError, match="base mismatch"):
        extract_native(rec, q.encode())


def test_ref_overrun_error_parity():
    """A cs walk that reads past the query end must raise the same
    PwasmError on both the Python and native paths (the PAF fields are
    internally consistent; only the FASTA is shorter than claimed)."""
    q = "ACGTACGTAC"
    line, _ = make_paf_line("q", q, "t", "+", [("=", 10)])
    rec = parse_paf_line(line)
    short_ref = q.encode()[:7]  # FASTA shorter than the claimed r_len
    errs = []
    for fn in (lambda: extract_alignment(rec, short_ref, use_native=False),
               lambda: extract_native(rec, short_ref)):
        with pytest.raises(PwasmError, match="parsing cs string") as ei:
            fn()
        errs.append(str(ei.value))
    assert errs[0] == errs[1]

    # same for a '+' (deleted-bases) run past the end
    line2, _ = make_paf_line("q", q, "t", "+", [("=", 6), ("del", 4)])
    rec2 = parse_paf_line(line2)
    errs2 = []
    for fn in (lambda: extract_alignment(rec2, short_ref, use_native=False),
               lambda: extract_native(rec2, short_ref)):
        with pytest.raises(PwasmError, match="parsing cs string") as ei:
            fn()
        errs2.append(str(ei.value))
    assert errs2[0] == errs2[1]


def test_native_error_splice_and_lengths():
    q = "ACGTACGTAC"
    line, _ = make_paf_line("q", q, "t", "+", [("=", 10)])
    rec = parse_paf_line(line.replace("cs:Z::10", "cs:Z::5~gt4ac:5"))
    with pytest.raises(PwasmError, match="spliced"):
        extract_native(rec, q.encode())
    rec2 = parse_paf_line(line.replace("cg:Z:10M", "cg:Z:9M"))
    with pytest.raises(PwasmError, match="length mismatch"):
        extract_native(rec2, q.encode())


def test_native_buffer_growth_long_insertion():
    # an insertion far larger than the initial arena guess
    rng = np.random.default_rng(1)
    q = "".join(rng.choice(list("ACGT"), size=50))
    ins = "".join(rng.choice(list("acgt"), size=3000))
    line, _ = make_paf_line("q", q, "t", "+",
                            [("=", 25), ("ins", ins), ("=", 25)])
    rec = parse_paf_line(line)
    py = extract_alignment(rec, q.encode(), use_native=False)
    nat = extract_native(rec, q.encode())
    assert _aln_tuple(nat) == _aln_tuple(py)


def test_banded_gotoh_matches_oracle():
    rng = np.random.default_rng(5)
    p = ScoreParams()
    m = 40
    q = rng.integers(0, 4, size=m).astype(np.int8)
    targets, lens = [], []
    n_pad = 56
    for _ in range(8):
        t = list(q)
        for _ in range(int(rng.integers(0, 4))):
            t[int(rng.integers(0, len(t)))] = int(rng.integers(0, 4))
        if rng.random() < 0.5 and len(t) > 10:
            del t[int(rng.integers(1, len(t) - 1))]
        pad = np.full(n_pad, 127, dtype=np.int8)
        pad[:len(t)] = t
        targets.append(pad)
        lens.append(len(t))
    ts = np.stack(targets)
    tl = np.array(lens, dtype=np.int32)
    dlo = band_dlo(m, n_pad, 32)
    got = banded_gotoh_batch(q, ts, tl, 32, dlo, p.match, p.mismatch,
                             p.gap_open, p.gap_extend)
    for k in range(8):
        assert got[k] == full_gotoh_score(q, targets[k][:lens[k]], p)


def test_native_jax_banded_parity():
    import jax.numpy as jnp

    from pwasm_tpu.ops.banded_dp import banded_scores_batch

    rng = np.random.default_rng(9)
    p = ScoreParams()
    m = 48
    q = rng.integers(0, 4, size=m).astype(np.int8)
    n_pad = 64
    ts = np.full((10, n_pad), 127, dtype=np.int8)
    tl = np.zeros(10, dtype=np.int32)
    for k in range(10):
        t = list(q)
        for _ in range(int(rng.integers(0, 5))):
            t[int(rng.integers(0, len(t)))] = int(rng.integers(0, 4))
        ts[k, :len(t)] = t
        tl[k] = len(t)
    dlo = band_dlo(m, n_pad, 32)
    nat = banded_gotoh_batch(q, ts, tl, 32, dlo, p.match, p.mismatch,
                             p.gap_open, p.gap_extend)
    jx = np.asarray(banded_scores_batch(jnp.asarray(q), jnp.asarray(ts),
                                        jnp.asarray(tl), band=32))
    np.testing.assert_array_equal(nat, jx)



def test_cli_uses_native_transparently(tmp_path):
    # end-to-end through the CLI with the native extractor active
    from io import StringIO

    from pwasm_tpu.cli import run
    from pwasm_tpu.core.fasta import write_fasta

    fa = tmp_path / "q.fa"
    write_fasta(str(fa), [("q", b"ACGTACGTAC")])
    line, _ = make_paf_line("q", "ACGTACGTAC", "asm1", "+",
                            [("=", 3), ("*", "a", "t"), ("=", 6)])
    paf = tmp_path / "in.paf"
    paf.write_text(line + "\n")
    out = StringIO()
    assert run([str(paf), "-r", str(fa)], stdout=out,
               stderr=StringIO()) == 0
    assert "S\t4\t" in out.getvalue()


def test_native_consensus_vote_parity():
    from pwasm_tpu.align.msa import best_char_from_counts
    from pwasm_tpu.native import (consensus_vote_counts,
                                  consensus_vote_pileup, native_available)

    if not native_available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(11)
    # codes 0..7: includes pad codes (>=6) that must contribute nothing
    pileup = rng.integers(0, 8, size=(48, 800)).astype(np.int8)
    pileup[:, 10] = 7  # a zero-coverage column
    got = consensus_vote_pileup(pileup)
    counts = np.stack([(pileup == k).sum(0) for k in range(6)],
                      axis=1).astype(np.int32)
    layers = counts.sum(1).astype(np.int32)
    expect = np.array([best_char_from_counts(counts[c], int(layers[c]))
                       for c in range(800)], dtype=np.uint8)
    np.testing.assert_array_equal(got, expect)
    np.testing.assert_array_equal(consensus_vote_counts(counts, layers),
                                  expect)
    assert got[10] == 0  # zero coverage votes 0


def test_refine_msa_native_vote_matches_python():
    # the refine_msa host path (native counts vote) must produce the same
    # consensus as the per-column python vote
    from pwasm_tpu.align.gapseq import GapSeq
    from pwasm_tpu.align.msa import Msa

    def build():
        s1 = GapSeq("a", seq=b"ACGTACGTAA")
        s2 = GapSeq("b", seq=b"ACGAACGTAA")
        m = Msa(s1, s2)
        return m

    m1 = build()
    m1.refine_msa(remove_cons_gaps=False)
    m2 = build()
    m2.build_msa()
    cols = m2.msacolumns
    expect = bytearray()
    for col in range(cols.mincol, cols.maxcol + 1):
        c = cols.best_char(col)
        expect.append(ord("*") if c in (ord("-"), ord("*")) else c)
    assert bytes(m1.consensus) == bytes(expect)


def test_native_fasta_index_parity(tmp_path):
    from pwasm_tpu.native import fasta_fetch, fasta_index, native_available

    if not native_available():
        pytest.skip("native library unavailable")
    fa = tmp_path / "mix.fa"
    # exercises: description after name, blank/whitespace lines inside a
    # record, CRLF endings, duplicate id, empty header, header at EOF
    fa.write_bytes(b">one some description\nACGTAC\nGT AC\n\n"
                   b">two\r\nACG\r\nT\r\n"
                   b">one\nTTTT\n"
                   b">\nGG\n"
                   b">three")
    entries = fasta_index(str(fa))
    names = [e[0] for e in entries]
    assert names == ["one", "two", "one", "", "three"]
    # parity with the pure-Python indexer entry by entry
    import pwasm_tpu.core.fasta as F

    class PyOnly(F.FastaFile):
        def _build_index(self):
            # bypass the native path: copy of the python branch via
            # monkeypatched native indexer
            import pwasm_tpu.native as N
            real = N.fasta_index
            N.fasta_index = lambda p: None
            try:
                super()._build_index()
            finally:
                N.fasta_index = real

    py = PyOnly(str(fa))
    nat = F.FastaFile(str(fa))
    assert py.names == nat.names
    for n in py.names:
        assert py.length(n) == nat.length(n)
        assert py._index[n] == nat._index[n]
        assert py.fetch(n) == nat.fetch(n)
    # direct range fetch strips all whitespace
    e = entries[0]
    assert fasta_fetch(str(fa), e[2], e[3]) == b"ACGTACGTAC"


def test_native_pack_2bit_roundtrip():
    from pwasm_tpu.native import (encode_codes, native_available, pack_2bit,
                                  unpack_2bit)

    if not native_available():
        pytest.skip("native library unavailable")
    from pwasm_tpu.core.dna import encode

    seq = b"ACGTacgtUuNn-*XYacg"
    got = encode_codes(seq)
    np.testing.assert_array_equal(got, encode(seq))
    codes = np.array([0, 1, 2, 3, 3, 2, 1, 0, 2], dtype=np.int8)
    packed = pack_2bit(codes)
    assert packed.shape == (3,)
    np.testing.assert_array_equal(unpack_2bit(packed, len(codes)), codes)


def test_native_sanitizer_selftest():
    """The reference ships ASan/UBSan build targets (Makefile:30-47);
    our equivalent gate is `make memcheck` in pwasm_tpu/native."""
    import subprocess

    d = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "pwasm_tpu", "native")
    # probe: can this toolchain link a sanitized binary at all?
    probe = subprocess.run(
        ["g++", "-fsanitize=address,undefined", "-x", "c++", "-",
         "-o", os.devnull],
        input="int main(){return 0;}", capture_output=True, text=True)
    if probe.returncode != 0:
        pytest.skip("sanitizer toolchain unavailable")
    res = subprocess.run(["make", "-C", d, "memcheck"],
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stderr[-2000:]
    assert "native selftest OK" in res.stdout
    # the full CLI (MSA + consensus engine) must also run clean under
    # ASan/UBSan — the recipe exits nonzero on any sanitizer report
    assert "native CLI sanitizer run OK" in res.stdout


def test_native_gotoh_traceback_matches_python_oracle():
    """pw_gotoh_traceback must reproduce full_gotoh_traceback exactly:
    score AND op string (identical tie-breaks by construction)."""
    from pwasm_tpu.native import gotoh_traceback, native_available
    from pwasm_tpu.ops.banded_dp import ScoreParams
    from pwasm_tpu.ops.realign import full_gotoh_traceback

    if not native_available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(42)
    params = ScoreParams()
    for _ in range(40):
        m = int(rng.integers(5, 120))
        q = rng.integers(0, 4, m).astype(np.int8)
        t = list(q)
        for _ in range(int(rng.integers(0, 10))):
            p = int(rng.integers(0, max(1, len(t) - 1)))
            r = rng.random()
            if r < 0.4:
                t[p] = int(rng.integers(0, 4))
            elif r < 0.7:
                t.insert(p, int(rng.integers(0, 4)))
            elif len(t) > 2:
                del t[p]
        t = np.array(t, dtype=np.int8)
        want_score, want_ops = full_gotoh_traceback(q, t, params)
        got = gotoh_traceback(q, t, params.match, params.mismatch,
                              params.gap_open, params.gap_extend)
        assert got is not None
        score, ops = got
        assert score == want_score
        np.testing.assert_array_equal(ops, want_ops)
    # degenerate shapes
    for q, t in ((np.zeros(0, np.int8), np.array([1, 2], np.int8)),
                 (np.array([1], np.int8), np.zeros(0, np.int8))):
        want = full_gotoh_traceback(q, t, params)
        got = gotoh_traceback(q, t, params.match, params.mismatch,
                              params.gap_open, params.gap_extend)
        assert got[0] == want[0]
        np.testing.assert_array_equal(got[1], want[1])


def test_extract_batch_parity_and_stop_at_failing_item():
    """pw_extract_batch: one crossing for a mixed flush (both strands,
    different queries/lengths) returns alignments identical to the
    per-item native path; a failing mid-batch item stops the batch at
    the items before it and surfaces the SAME per-item error."""
    from pwasm_tpu.native import extract_batch_native
    rng = np.random.default_rng(1234)
    recs, refs = [], []
    for i in range(13):
        strand = "+" if i % 3 else "-"
        q = "".join(rng.choice(list("ACGT"),
                               size=int(rng.integers(60, 160))))
        if strand == "-":
            q_aln = revcomp(q.encode()).decode()
        else:
            q_aln = q
        ops = _random_ops(rng, q_aln)
        line, _ = make_paf_line(f"q{i}", q, f"t{i}", strand, ops)
        rec = parse_paf_line(line)
        recs.append(rec)
        refs.append(revcomp(q.encode()) if rec.alninfo.reverse
                    else q.encode())
    alns, err = extract_batch_native(recs, refs)
    assert err is None and len(alns) == len(recs)
    for rec, ref, aln in zip(recs, refs, alns):
        assert _aln_tuple(aln) == _aln_tuple(extract_native(rec, ref))
    # poison item 7 with an unparsable cs op: items 0..6 extract, the
    # error is byte-identical to the per-item one
    bad_line = recs[7].line.replace("cs:Z:", "cs:Z:~zz")
    bad = parse_paf_line(bad_line)
    broken = recs[:7] + [bad] + recs[8:]
    brefs = refs[:7] + [refs[7]] + refs[8:]
    alns2, err2 = extract_batch_native(broken, brefs)
    assert len(alns2) == 7 and err2 is not None
    with pytest.raises(PwasmError) as ei:
        extract_native(bad, refs[7])
    assert str(err2) == str(ei.value)
    for a, b in zip(alns2, alns):
        assert _aln_tuple(a) == _aln_tuple(b)


def test_cli_extract_batch_hatch_byte_parity(tmp_path):
    """PWASM_NATIVE_EXTRACT_BATCH=0 is the per-item A/B hatch: both
    modes produce byte-identical report AND MSA files (the
    pw_msa_add_batch parity contract, extended to extraction)."""
    from pwasm_tpu.cli import run
    import io
    rng = np.random.default_rng(77)
    seqs, lines = [], []
    for qn in range(2):
        q = "".join(rng.choice(list("ACGT"), size=140 + 20 * qn))
        seqs.append((f"q{qn}", q))
        for i in range(11):     # not a multiple of --batch: tail flush
            strand = "+" if (i + qn) % 3 else "-"
            qa = revcomp(q.encode()).decode() if strand == "-" else q
            ops = _random_ops(rng, qa)
            lines.append(make_paf_line(f"q{qn}", q, f"t{qn}_{i}",
                                       strand, ops)[0])
    fa = tmp_path / "q.fa"
    fa.write_text("".join(f">{n}\n{s}\n" for n, s in seqs))
    paf = tmp_path / "in.paf"
    paf.write_text("".join(ln + "\n" for ln in lines))
    outs = {}
    for hatch in ("1", "0"):
        os.environ["PWASM_NATIVE_EXTRACT_BATCH"] = hatch
        try:
            out = tmp_path / f"h{hatch}.dfa"
            msa = tmp_path / f"h{hatch}.msa"
            err = io.StringIO()
            rc = run([str(paf), "-r", str(fa), "-o", str(out),
                      "-w", str(msa), "--batch=7"], stderr=err)
            assert rc == 0, err.getvalue()
            outs[hatch] = (out.read_bytes(), msa.read_bytes())
        finally:
            del os.environ["PWASM_NATIVE_EXTRACT_BATCH"]
    assert outs["1"] == outs["0"]
