"""Router HA (ISSUE 16): write-ahead journal + warm-standby failover,
epoch-lease fencing for zombie members, SLO-driven member auto-scaling.

Acceptance contracts:

- **router WAL**: a kill -9'd router restarted on the same socket
  replays its routed-job table from ``<socket>.router.journal`` and
  keeps answering ``status``/``result`` for pre-crash jobs;
- **the standby drill**: kill -9 the PRIMARY router mid-job with a
  ``route --standby-of`` warm standby running → the standby takes over
  the primary's socket, the job completes byte-identical to an
  uncrashed run, and the client-minted trace_id survives end-to-end;
- **the zombie drill**: SIGSTOP a member mid-job → the fleet fails the
  job over (epoch bumped), the report stays byte-identical, and the
  revived zombie self-fences instead of double-writing;
- **auto-scaling**: sustained SLO pressure spawns a member, sustained
  calm retires it, within the policy's bounds.
"""

import io
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

import pytest

from pwasm_tpu.core.errors import EXIT_PREEMPTED, EXIT_USAGE
from pwasm_tpu.fleet import transport
from pwasm_tpu.fleet.fencing import EpochLease, readmit_epoch_guard
from pwasm_tpu.fleet.router import (Router, _FleetJob,
                                    fold_route_records, route_main)
from pwasm_tpu.fleet.scaler import load_scale_policy
from pwasm_tpu.fleet.standby import run_standby
from pwasm_tpu.service.client import (ServiceClient, ServiceError,
                                      wait_for_socket)

from test_fleet import (REPO, SLOW, _corpus, _daemon, _fleet,
                        _job_args, _serve_env, _stub_runner)


# ---------------------------------------------------------------------------
# fencing units
# ---------------------------------------------------------------------------
def test_epoch_lease_semantics():
    t = [0.0]
    lease = EpochLease(clock=lambda: t[0])
    # ungoverned: today's standalone-daemon behaviour, no TTL
    assert not lease.governed and not lease.expired()
    assert lease.remaining_s() == float("inf")
    assert not lease.grant(0, 5.0)[0]            # epoch must be >= 1
    assert not lease.grant(True, 5.0)[0]         # bools are not epochs
    assert not lease.grant(2, 0)[0]              # ttl must be > 0
    assert not lease.grant(2, float("inf"))[0]   # ... and finite
    assert not lease.governed
    ok, detail = lease.grant(2, 5.0)
    assert ok and detail == ""
    assert lease.governed and lease.epoch == 2
    # a stale router cannot re-arm a member the fleet moved past
    ok, detail = lease.grant(1, 5.0)
    assert not ok and "stale epoch" in detail
    assert lease.epoch == 2
    t[0] = 4.0
    assert not lease.expired()
    t[0] = 5.1
    assert lease.expired()
    assert lease.fence("ttl expired")
    assert not lease.fence("again")      # only the 0->1 transition
    assert lease.fenced and lease.fences == 1
    d = lease.as_dict()
    assert d["fenced"] and d["reason"] == "ttl expired"
    assert d["epoch"] == 2 and d["governed"]
    # a current-or-newer grant lifts the fence (router re-asserted
    # ownership, so every race the fence guarded is fenced upstream)
    ok, _ = lease.grant(3, 5.0)
    assert ok and not lease.fenced
    assert not lease.expired()
    assert "reason" not in lease.as_dict()


def test_readmit_epoch_guard():
    # the qa-gate choke point: new placements run at the fleet epoch
    assert readmit_epoch_guard(0, 3) == 3
    assert readmit_epoch_guard(3, 3) == 3
    # a job placed under an epoch NEWER than the fleet's own means two
    # routers disagree about ownership — the double-resume race
    with pytest.raises(RuntimeError) as ei:
        readmit_epoch_guard(4, 3)
    assert "fencing violation" in str(ei.value)


def test_router_journal_path(tmp_path):
    # both the primary and the standby compute the path HERE, so they
    # cannot disagree about which file the WAL is
    assert transport.router_journal_path("/run/r.sock", None, None) \
        == "/run/r.sock.router.journal"
    shared = str(tmp_path)
    assert transport.router_journal_path("/run/r.sock", None, shared) \
        == os.path.join(shared, "router-r.sock.journal")
    assert transport.router_journal_path(None, "node7:9211", shared) \
        == os.path.join(shared, "router-node7_9211.journal")
    # TCP-only without shared storage: journal-less (RAM-only), loud
    assert transport.router_journal_path(None, "node7:9211", None) \
        is None


def test_fold_route_records():
    recs = [
        {"rec": "members", "backends": ["/a.sock"]},
        {"rec": "epoch", "epoch": 1},
        {"rec": "route_admit", "job_id": "fleet-0001", "client": "c",
         "frame": {"args": []}},
        {"rec": "route_place", "job_id": "fleet-0001",
         "member": "a.sock", "mjid": "job-0001", "gen": 0, "epoch": 1},
        {"rec": "route_admit", "job_id": "fleet-0002", "client": "c",
         "frame": {"args": []}},
        {"rec": "route_retire", "job_id": "fleet-0002"},
        # a place with no admit is a torn line: the client was never
        # acked, so the job must not resurrect
        {"rec": "route_place", "job_id": "fleet-0009", "member": "x"},
        {"rec": "epoch", "epoch": 4},
        {"rec": "members", "backends": ["/a.sock", "/b.sock"]},
        {"rec": "scale", "action": "spawn", "target": "/s1.sock",
         "pid": 11},
        {"rec": "scale", "action": "spawn", "target": "/s2.sock",
         "pid": 12},
        {"rec": "scale", "action": "retire", "target": "/s1.sock"},
    ]
    f = fold_route_records(recs)
    assert f["epoch"] == 4
    assert f["members"] == ["/a.sock", "/b.sock"]   # last snapshot wins
    assert set(f["jobs"]) == {"fleet-0001", "fleet-0002"}
    assert f["jobs"]["fleet-0001"]["place"]["mjid"] == "job-0001"
    assert f["jobs"]["fleet-0001"]["retire"] is None
    assert f["jobs"]["fleet-0002"]["retire"] is not None
    assert set(f["scaled"]) == {"/s2.sock"}     # spawn minus retire
    assert fold_route_records([]) == {
        "jobs": {}, "epoch": 0, "members": None, "scaled": {}}


# ---------------------------------------------------------------------------
# flag surface: standby exclusivity + HA knob validation
# ---------------------------------------------------------------------------
def test_route_main_standby_and_ha_flag_validation(tmp_path):
    # a flag-supplied fleet view alongside --standby-of is exactly the
    # split-brain the journal exists to prevent: refuse LOUDLY
    err = io.StringIO()
    assert route_main(["--standby-of=/p.sock", "--backends=a.sock"],
                      stderr=err) == EXIT_USAGE
    assert "mutually exclusive" in err.getvalue()
    assert "member set" in err.getvalue()
    err = io.StringIO()
    assert route_main(["--standby-of=/p.sock",
                       "--socket=" + str(tmp_path / "r")],
                      stderr=err) == EXIT_USAGE
    assert "PRIMARY's socket" in err.getvalue()
    err = io.StringIO()
    assert route_main(["--standby-of=/p.sock",
                       "--listen=127.0.0.1:9211"],
                      stderr=err) == EXIT_USAGE
    assert "mutually exclusive" in err.getvalue()
    base = ["--backends=a.sock", "--socket=" + str(tmp_path / "r")]
    for flag, frag in [
            ("--lease-ttl=0", "--lease-ttl"),
            ("--lease-ttl=inf", "--lease-ttl"),
            ("--lease-ttl=abc", "--lease-ttl"),
            ("--stream-replay-bytes=-1", "--stream-replay-bytes"),
            ("--stream-replay-bytes=4MiB", "--stream-replay-bytes"),
            ("--scale-policy=" + str(tmp_path / "nope.json"),
             "cannot read")]:
        err = io.StringIO()
        assert route_main(base + [flag], stderr=err) == EXIT_USAGE, flag
        assert frag in err.getvalue(), (flag, err.getvalue())


def test_standby_refuses_tcp_primary():
    # a takeover binds the primary's socket; a TCP endpoint on another
    # host cannot be bound from here
    err = io.StringIO()
    assert run_standby("host:9211", stderr=err) == EXIT_USAGE
    assert "SOCKET" in err.getvalue()


def test_load_scale_policy(tmp_path):
    p = tmp_path / "pol.json"
    p.write_text(json.dumps({
        "min_members": 1, "max_members": 2, "cooldown_s": 5,
        "hysteresis": 1, "scale_down_after_s": 9,
        "rules": ["queue_pressure"],
        "spawn": {"socket_dir": "/srv", "args": ["--warmup"]}}))
    pol = load_scale_policy(str(p))
    assert pol["max_members"] == 2 and pol["hysteresis"] == 1
    assert pol["spawn"]["args"] == ["--warmup"]
    # defaults: only spawn.socket_dir is mandatory
    p.write_text(json.dumps({"spawn": {"socket_dir": "/srv"}}))
    pol = load_scale_policy(str(p))
    assert pol["min_members"] == 1 and pol["max_members"] == 4
    assert pol["rules"] == ["queue_pressure", "queue_wait_burn",
                            "ledger_saturation"]
    sd = {"socket_dir": "/srv"}
    for bad, frag in [
            ({"spawn": sd, "min_members": 0}, "min_members"),
            ({"spawn": sd, "max_members": True}, "max_members"),
            ({"spawn": sd, "min_members": 3, "max_members": 2},
             "max_members must be >="),
            ({"spawn": sd, "cooldown_s": -1}, "cooldown_s"),
            ({"spawn": sd, "rules": []}, "rules"),
            ({"spawn": sd, "rules": "queue_pressure"}, "rules"),
            ({"spawn": {}}, "socket_dir"),
            ({}, "socket_dir"),
            ({"spawn": {"socket_dir": "/srv", "args": "nope"}},
             "spawn.args"),
            ([1], "JSON object")]:
        p.write_text(json.dumps(bad))
        with pytest.raises(ValueError) as ei:
            load_scale_policy(str(p))
        assert frag in str(ei.value), (bad, str(ei.value))
    p.write_text("{nope")
    with pytest.raises(ValueError) as ei:
        load_scale_policy(str(p))
    assert "not valid JSON" in str(ei.value)
    with pytest.raises(ValueError) as ei:
        load_scale_policy(str(tmp_path / "missing.json"))
    assert "cannot read" in str(ei.value)


# ---------------------------------------------------------------------------
# the bounded mid-stream replay window
# ---------------------------------------------------------------------------
def test_stream_replay_window_bounds():
    r = Router(["/nonexistent/a.sock"], socket_path=None,
               listen="127.0.0.1:0", stderr=io.StringIO(),
               poll_interval=999, stream_replay_bytes=600)
    job = _FleetJob("fleet-0001", "c", "", "", {"args": []},
                    "a.sock", "job-0001", stream=True)
    assert job.rbuf == [] and job.rbytes == 0
    f1 = {"cmd": "stream-data", "data": '{"rec": 1}'}
    r._buffer_stream_frame(job, f1)
    assert job.rbuf == [f1] and job.rbytes == len('{"rec": 1}')
    # unsized payloads charge a flat estimate, never go unaccounted
    r._buffer_stream_frame(job, {"cmd": "stream-data", "data": None})
    assert job.rbytes == len('{"rec": 1}') + 256
    # past the window the buffer is DROPPED, not truncated — a partial
    # prefix would replay a corrupt stream; failover then degrades to
    # the documented preempted-resumable verdict
    r._buffer_stream_frame(job, {"cmd": "stream-data",
                                 "data": "x" * 600})
    assert job.rbuf is None and job.rbytes == 0
    r._buffer_stream_frame(job, {"cmd": "stream-data", "data": "y"})
    assert job.rbuf is None                     # overflow is sticky
    job2 = _FleetJob("fleet-0002", "c", "", "", {"args": []},
                     "a.sock", "job-0002", stream=True)
    r._buffer_stream_frame(job2, {"cmd": "stream-end"})
    assert job2.ended
    # non-stream jobs have no window at all
    job3 = _FleetJob("fleet-0003", "c", "", "", {"args": []},
                     "a.sock", "job-0003")
    assert job3.rbuf is None


# ---------------------------------------------------------------------------
# member-side fencing: the self-fence protocol
# ---------------------------------------------------------------------------
def test_member_self_fences_on_lease_expiry(tmp_path):
    """A governed member whose lease TTL lapses fences itself: queued
    jobs preempt to durable state, new submits answer the ``fenced``
    error, reads keep working, and a fresh grant at a current epoch
    lifts the fence."""
    with _daemon(runner=_stub_runner(sleep=4.0)) as h:
        out = str(tmp_path / "o.dfa")
        with ServiceClient(h.sock) as c:
            running = c.submit(["in.paf", "-o", out],
                               cwd=str(tmp_path))
            queued = c.submit(["in2.paf", "-o", out + "2"],
                              cwd=str(tmp_path))
            assert running["ok"] and queued["ok"]
            # the lease heartbeat rides the stats poll: zero extra RPCs
            st = c.request({"cmd": "stats",
                            "lease": {"epoch": 1, "ttl_s": 0.4}})
            assert st["ok"]
            lb = st["stats"]["lease"]
            assert lb["accepted"] is True
            assert lb["governed"] and lb["epoch"] == 1
            # stop heartbeating: the TTL lapses and the daemon's own
            # tick loop latches the fence
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                lb = c.request({"cmd": "stats"})["stats"]["lease"]
                if lb["fenced"]:
                    break
                time.sleep(0.05)
            assert lb["fenced"], lb
            assert "TTL expired" in lb["reason"]
            # the queued job was preempted at fence time — resumable,
            # never silently dropped
            rq = c.result(queued["job_id"], timeout=15)
            assert rq["rc"] == EXIT_PREEMPTED
            assert "fencing" in rq["job"]["detail"]
            # new work refused with the fenced error...
            ref = c.submit(["x.paf", "-o", out], cwd=str(tmp_path))
            assert not ref.get("ok") and ref["error"] == "fenced"
            assert ref["epoch"] == 1
            # ...but reads and the heartbeat surface still serve (the
            # router must be able to see and un-fence the member)
            assert c.ping()["ok"]
            assert c.status(running["job_id"])["ok"]
            # a fence is a pause, not a kill: the in-flight job drains
            # to completion at its own pace
            rr = c.result(running["job_id"], timeout=30)
            assert rr["rc"] == 0
            # a grant at a NEWER epoch lifts the fence
            g = c.request({"cmd": "lease-grant", "epoch": 2,
                           "ttl_s": 30})
            assert g["ok"]
            assert g["lease"]["fenced"] is False
            assert g["lease"]["epoch"] == 2
            ok2 = c.submit(["y.paf", "-o", out], cwd=str(tmp_path))
            assert ok2["ok"]
            # a STALE grant is refused: this member has seen epoch 2,
            # a router stuck at epoch 1 must not re-arm it
            g2 = c.request({"cmd": "lease-grant", "epoch": 1,
                            "ttl_s": 30})
            assert not g2.get("ok") and g2["error"] == "fenced"
            assert "stale epoch" in g2["detail"]
            # the explicit fence verb latches immediately
            fv = c.request({"cmd": "fence", "reason": "drill"})
            assert fv["ok"] and fv["lease"]["fenced"]


# ---------------------------------------------------------------------------
# the router WAL: kill -9 the router, restart on the same socket
# ---------------------------------------------------------------------------
def test_router_wal_replay_after_kill9(tmp_path):
    """SIGKILL a subprocess router mid-job and restart it on the same
    socket: the WAL replay restores the routed-job table — the live
    job completes, the pre-crash finished job still answers status and
    result, and the epoch is bumped past the dead incarnation's."""
    from test_fleet import _nested
    with _nested(2, _stub_runner(sleep=6.0), {}) as members:
        d = tempfile.mkdtemp(prefix="pwwal")
        rsock = os.path.join(d, "router.sock")
        jpath = rsock + ".router.journal"
        argv = [sys.executable, "-m", "pwasm_tpu.cli", "route",
                "--backends=" + ",".join(m.sock for m in members),
                f"--socket={rsock}", "--poll-interval=0.2"]
        p = subprocess.Popen(argv, env=_serve_env(),
                             stdout=subprocess.DEVNULL,
                             stderr=subprocess.PIPE, text=True)
        p2 = None
        try:
            assert wait_for_socket(rsock, 30)
            with ServiceClient(rsock) as c:
                # stub runners key runtime on --inject-faults-free
                # sleep; give the quick job its own fast runner via
                # the member's default (sleep=6 runner serves both, so
                # "quick" here just means submitted and finished first
                # is not needed — we wait it out)
                quick = c.submit(["q.paf", "-o",
                                  str(tmp_path / "q.dfa")],
                                 cwd=str(tmp_path))
                assert quick["ok"]
                rq = c.result(quick["job_id"], timeout=60)
                assert rq["rc"] == 0
                live = c.submit(["l.paf", "-o",
                                 str(tmp_path / "l.dfa")],
                                cwd=str(tmp_path))
                assert live["ok"]
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    st = c.status(live["job_id"])["job"]["state"]
                    if st == "running":
                        break
                    time.sleep(0.05)
                assert st == "running", st
            assert os.path.exists(jpath)
            p.kill()                      # SIGKILL: no drain, no flush
            p.wait(timeout=30)
            assert os.path.exists(jpath)  # the WAL survived the crash
            p2 = subprocess.Popen(argv, env=_serve_env(),
                                  stdout=subprocess.DEVNULL,
                                  stderr=subprocess.PIPE, text=True)
            assert wait_for_socket(rsock, 30)
            with ServiceClient(rsock) as c:
                # the live job was restored and completes normally
                rl = c.result(live["job_id"], timeout=120)
                assert rl.get("rc") == 0, rl
                # the PRE-CRASH finished job still answers: status
                # from the replayed table, result via the member that
                # still holds the verdict
                sq = c.status(quick["job_id"])
                assert sq["ok"] and sq["job"]["id"] == quick["job_id"]
                rq2 = c.result(quick["job_id"], timeout=60)
                assert rq2.get("rc") == 0, rq2
                st = c.stats()["stats"]
                # every incarnation bumps the epoch: placements made
                # under the dead router are visibly superseded
                assert st["ha"]["epoch"] >= 2
                assert st["ha"]["journal"]["path"] == jpath
                c.drain()
            assert p2.wait(timeout=60) == 0
            p2 = None
            # a clean drain retires the WAL: nothing left to replay
            assert not os.path.exists(jpath)
        finally:
            for proc in (p, p2):
                if proc is not None and proc.poll() is None:
                    proc.kill()
                    proc.wait()
                if proc is not None:
                    proc.stderr.close()
            shutil.rmtree(d, ignore_errors=True)


# ---------------------------------------------------------------------------
# THE drill: kill -9 the PRIMARY router with a warm standby watching
# ---------------------------------------------------------------------------
def test_standby_takeover_kill9_mid_job_byte_identical(tmp_path):
    """ISSUE 16 acceptance: two serve daemons behind a primary router
    with a ``route --standby-of`` warm standby tailing its WAL.
    SIGKILL the primary mid-job → the standby takes over the SAME
    socket, the in-flight job completes byte-identical to an uncrashed
    run, and the client's trace_id survives the takeover."""
    paf, fa = _corpus(tmp_path)
    from pwasm_tpu.cli import run as cli_run
    assert cli_run(_job_args(tmp_path, "cold", paf, fa, [SLOW]),
                   stderr=io.StringIO()) == 0
    expect = (tmp_path / "cold.dfa").read_bytes()

    d = tempfile.mkdtemp(prefix="pwha")
    procs = []
    try:
        socks = []
        for i in range(2):
            s = os.path.join(d, f"m{i}.sock")
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "pwasm_tpu.cli", "serve",
                 f"--socket={s}"],
                env=_serve_env(), stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE, text=True))
            socks.append(s)
        for s in socks:
            assert wait_for_socket(s, 60)
        rsock = os.path.join(d, "router.sock")
        primary = subprocess.Popen(
            [sys.executable, "-m", "pwasm_tpu.cli", "route",
             "--backends=" + ",".join(socks), f"--socket={rsock}",
             "--poll-interval=0.2"],
            env=_serve_env(), stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE, text=True)
        procs.append(primary)
        assert wait_for_socket(rsock, 30)
        standby = subprocess.Popen(
            [sys.executable, "-m", "pwasm_tpu.cli", "route",
             f"--standby-of={rsock}", "--poll-interval=0.2"],
            env=_serve_env(), stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE, text=True)
        procs.append(standby)

        with ServiceClient(rsock, trace_id="ha-drill") as c:
            ja = c.submit(_job_args(tmp_path, "a", paf, fa, [SLOW]),
                          cwd=str(tmp_path))
            assert ja["ok"], ja
            # wait until the job is demonstrably MID-RUN with a ckpt
            ck = str(tmp_path / "a.dfa.ckpt")
            deadline = time.monotonic() + 60
            mid = False
            while time.monotonic() < deadline:
                st = c.status(ja["job_id"])["job"]["state"]
                if st == "running" and os.path.exists(ck):
                    mid = True
                    break
                assert st in ("queued", "running"), st
                time.sleep(0.02)
            assert mid, "job never reached mid-run with a ckpt"
        primary.kill()                 # SIGKILL the submit surface
        primary.wait(timeout=30)
        # the standby notices the missed pings and binds the SAME
        # socket — clients reconnect to the address they already had
        took = False
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                with ServiceClient(rsock, timeout=2.0) as c:
                    if c.request({"cmd": "ping"}).get("ok"):
                        took = True
                        break
            except (ServiceError, OSError):
                pass
            time.sleep(0.1)
        assert took, "standby never took over the primary's socket"
        assert standby.poll() is None
        with ServiceClient(rsock, trace_id="ha-drill") as c:
            ra = c.result(ja["job_id"], timeout=300)
            assert ra.get("rc") == 0, ra
            # identity survived the takeover end-to-end
            assert ra["job"]["trace_id"] == "ha-drill"
            st = c.stats()["stats"]
            assert st["ha"]["takeover"] is True
            # takeover bumps the epoch: the dead primary's placements
            # are fenced even if it were merely stalled
            assert st["ha"]["epoch"] >= 2
            assert len(st["fleet"]["members"]) == 2
            c.drain()
        assert standby.wait(timeout=120) == 0
        # byte parity vs the uncrashed arm: the router died, the work
        # did not, and nothing was double-applied
        assert (tmp_path / "a.dfa").read_bytes() == expect
        for i, s in enumerate(socks):
            with ServiceClient(s) as c:
                c.drain()
            assert procs[i].wait(timeout=120) == EXIT_PREEMPTED
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
            p.stderr.close()
        shutil.rmtree(d, ignore_errors=True)


# ---------------------------------------------------------------------------
# the zombie drill: SIGSTOP a member, fail over, revive — no double-write
# ---------------------------------------------------------------------------
def test_zombie_member_self_fences_no_double_write(tmp_path):
    """SIGSTOP (not SIGKILL) the member running a mid-run job: to the
    router it looks dead, but the process is merely paused — the
    classic zombie.  The fleet fails the job over (epoch bumped, byte
    parity preserved); when the zombie thaws with no router left to
    heartbeat it, its lapsed lease self-fences it — it refuses new
    work instead of writing as if it still owned anything."""
    paf, fa = _corpus(tmp_path)
    from pwasm_tpu.cli import run as cli_run
    assert cli_run(_job_args(tmp_path, "cold", paf, fa, [SLOW]),
                   stderr=io.StringIO()) == 0
    expect = (tmp_path / "cold.dfa").read_bytes()

    d = tempfile.mkdtemp(prefix="pwzmb")
    procs, socks = [], []
    rt = None
    try:
        for i in range(2):
            s = os.path.join(d, f"m{i}.sock")
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "pwasm_tpu.cli", "serve",
                 f"--socket={s}"],
                env=_serve_env(), stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE, text=True))
            socks.append(s)
        for s in socks:
            assert wait_for_socket(s, 60)
        rsock = os.path.join(d, "router.sock")
        # default lease TTL: it must absorb the serial health loop
        # stalling ~3s per hung victim poll without fencing the
        # healthy sibling (that head-of-line stall is exactly what
        # DEFAULT_LEASE_TTL_S is sized for)
        router = Router(socks, socket_path=rsock,
                        stderr=io.StringIO(), poll_interval=0.2)
        rt = threading.Thread(target=router.serve, daemon=True)
        rt.start()
        assert wait_for_socket(rsock, 15)

        with ServiceClient(rsock, trace_id="zombie-drill") as c:
            ja = c.submit(_job_args(tmp_path, "a", paf, fa, [SLOW]),
                          cwd=str(tmp_path))
            assert ja["ok"], ja
            ck = str(tmp_path / "a.dfa.ckpt")
            deadline = time.monotonic() + 60
            mid = False
            while time.monotonic() < deadline:
                st = c.status(ja["job_id"])["job"]["state"]
                if st == "running" and os.path.exists(ck):
                    mid = True
                    break
                assert st in ("queued", "running"), st
                time.sleep(0.02)
            assert mid, "job never reached mid-run with a ckpt"
            victim = ja["member"]
            vi = socks.index(router.members[victim].target)
            os.kill(procs[vi].pid, signal.SIGSTOP)   # zombie, not dead
            ra = c.result(ja["job_id"], timeout=300)
            assert ra.get("rc") == 0, ra
            assert ra["job"]["trace_id"] == "zombie-drill"
            assert ra["job"]["member"] != victim
            assert ra["job"]["failovers"] == 1
            st = c.stats()["stats"]
            # a hung-but-connectable member can strike out on more
            # than one path (health loop + a forwarding RPC timeout),
            # so the fleet-wide counter is >= 1; the JOB failed over
            # exactly once (asserted above) and resumed exactly once
            assert st["fleet"]["failovers"] >= 1
            assert st["fleet"]["jobs_recovered"]["resumed"] == 1
            # the failover re-admission bumped the fleet epoch: the
            # zombie's lease is now unrefreshable history
            assert st["ha"]["epoch"] >= 2
            c.drain()
        rt.join(20)
        rt = None
        # byte parity BEFORE the zombie thaws: whatever it does later
        # can at worst touch its own files, never the fleet's answer
        assert (tmp_path / "a.dfa").read_bytes() == expect
        # thaw the zombie with NO router left to re-arm it: its lease
        # lapsed while frozen, so its own tick loop must self-fence
        os.kill(procs[vi].pid, signal.SIGCONT)
        fenced = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                with ServiceClient(socks[vi], timeout=5.0) as mc:
                    lb = mc.request({"cmd": "stats"})["stats"]["lease"]
            except (ServiceError, OSError):
                time.sleep(0.1)
                continue
            if lb.get("fenced"):
                fenced = lb
                break
            time.sleep(0.1)
        assert fenced is not None, "zombie never self-fenced"
        assert fenced["governed"]
        with ServiceClient(socks[vi], timeout=10.0) as mc:
            ref = mc.submit(["z.paf", "-o", str(tmp_path / "z.dfa")],
                            cwd=str(tmp_path))
            assert not ref.get("ok") and ref["error"] == "fenced"
        # the sibling kept an ordinary life
        with ServiceClient(socks[1 - vi]) as mc:
            mc.drain()
        assert procs[1 - vi].wait(timeout=120) == EXIT_PREEMPTED
    finally:
        if rt is not None:
            try:
                with ServiceClient(rsock) as c:
                    c.drain()
            except (ServiceError, OSError):
                pass
            rt.join(20)
        for p in procs:
            if p.poll() is None:
                try:
                    os.kill(p.pid, signal.SIGCONT)
                except OSError:
                    pass
                p.kill()
                p.wait()
            p.stderr.close()
        shutil.rmtree(d, ignore_errors=True)


# ---------------------------------------------------------------------------
# SLO-driven auto-scaling
# ---------------------------------------------------------------------------
def test_scaler_spawns_and_retires_members(tmp_path, monkeypatch):
    """Sustained SLO pressure spawns a serve member (a real
    subprocess, adopted into the fleet); sustained calm drains it back
    down to min_members with the documented preempted exit."""
    env = _serve_env()
    for k in ("PYTHONPATH", "JAX_PLATFORMS", "PWASM_DEVICE_PROBE"):
        monkeypatch.setenv(k, env[k])
    sdir = str(tmp_path / "scaled")
    os.makedirs(sdir)
    policy = {"min_members": 1, "max_members": 2, "cooldown_s": 0.0,
              "hysteresis": 2, "scale_down_after_s": 0.5,
              "rules": ["queue_pressure"],
              "spawn": {"socket_dir": sdir, "args": []}}
    pressure = {"on": True}
    with _fleet(n=1, runner=_stub_runner(),
                router_kw={"scale_policy": policy}) as f:
        scaler = f.router.scaler
        assert scaler is not None
        # the SLO engine's verdicts are tested on their own (ISSUE
        # 14); here we drive the pressure signal directly and test
        # the scaling mechanics end-to-end
        monkeypatch.setattr(
            scaler, "_firing_rules",
            lambda: {"queue_pressure"} if pressure["on"] else set())

        def ha_stats():
            with ServiceClient(f.sock) as c:
                return c.stats()["stats"]

        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            st = ha_stats()
            if st["ha"]["scaler"]["owned"] == 1:
                break
            time.sleep(0.1)
        assert st["ha"]["scaler"]["owned"] == 1, st["ha"]
        assert st["ha"]["scaler"]["enabled"]
        assert len(st["fleet"]["members"]) == 2
        # bounded: sustained pressure cannot push past max_members
        time.sleep(1.0)
        assert len(ha_stats()["fleet"]["members"]) == 2
        # the grown fleet actually serves (a REAL corpus: the job may
        # land on the scaled member, which runs the real pipeline)
        paf, fa = _corpus(tmp_path, n=8)
        with ServiceClient(f.sock) as c:
            sub = c.submit(_job_args(tmp_path, "s", paf, fa),
                           cwd=str(tmp_path))
            assert sub["ok"]
            assert c.result(sub["job_id"], timeout=120)["rc"] == 0
        # calm past scale_down_after_s: drain back to min_members
        pressure["on"] = False
        # retirement forgets the member FIRST (so its planned exit is
        # never mistaken for a death), then drains it and reaps the
        # child — the counter lands only once the member is gone
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            st = ha_stats()
            if st["ha"]["scaler"]["retired"] == 1:
                break
            time.sleep(0.1)
        assert st["ha"]["scaler"]["retired"] == 1, st["ha"]
        assert st["ha"]["scaler"]["owned"] == 0
        assert st["ha"]["scaler"]["spawned"] == 1
        assert len(st["fleet"]["members"]) == 1


# ---------------------------------------------------------------------------
# the live-traffic drill (ISSUE 18 satellite): takeover is invisible to
# clients that keep submitting through it
# ---------------------------------------------------------------------------
def test_standby_takeover_under_live_client_traffic(tmp_path):
    """The kill9 drill above freezes the client during the takeover;
    real clients do not hold still.  Here a pump thread keeps
    submitting jobs through the whole window — at least two land
    before the SIGKILL and at least two after the standby binds — and
    every job that was ever acknowledged completes rc 0 with output
    byte-identical to a cold run.  The client-side contract: retrying
    the SAME address across OSError/ServiceError is sufficient; no
    job is lost, none is corrupted."""
    paf, fa = _corpus(tmp_path)
    from pwasm_tpu.cli import run as cli_run
    assert cli_run(_job_args(tmp_path, "cold", paf, fa, []),
                   stderr=io.StringIO()) == 0
    expect = (tmp_path / "cold.dfa").read_bytes()

    d = tempfile.mkdtemp(prefix="pwhalv")
    procs = []
    stop = threading.Event()
    done = []                 # [(tag, rc)] — every acknowledged job
    pump_err = []

    def pump(rsock):
        k = 0
        while not stop.is_set():
            # a fresh tag per submit ATTEMPT: a reply lost in the
            # takeover window must not race a retry onto the same
            # output paths
            tag = f"lv{k}"
            k += 1
            jid = None
            try:
                with ServiceClient(rsock, timeout=2.0) as c:
                    s = c.submit(_job_args(tmp_path, tag, paf, fa, []),
                                 cwd=str(tmp_path))
                    if s.get("ok"):
                        jid = s["job_id"]
            except (OSError, ServiceError):
                time.sleep(0.1)
                continue
            if jid is None:
                time.sleep(0.1)
                continue
            # acknowledged: this job may NOT be lost, even if the
            # router that acknowledged it is about to be SIGKILLed
            rc = None
            deadline = time.monotonic() + 120
            while rc is None and time.monotonic() < deadline:
                try:
                    with ServiceClient(rsock, timeout=5.0) as c:
                        rc = c.result(jid, timeout=60).get("rc")
                except (OSError, ServiceError):
                    time.sleep(0.1)
            if rc is None:
                pump_err.append(f"{tag}: result never arrived")
                return
            done.append((tag, rc))
            time.sleep(0.1)

    try:
        socks = []
        for i in range(2):
            s = os.path.join(d, f"m{i}.sock")
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "pwasm_tpu.cli", "serve",
                 f"--socket={s}"],
                env=_serve_env(), stdout=subprocess.DEVNULL,
                stderr=subprocess.PIPE, text=True))
            socks.append(s)
        for s in socks:
            assert wait_for_socket(s, 60)
        rsock = os.path.join(d, "router.sock")
        primary = subprocess.Popen(
            [sys.executable, "-m", "pwasm_tpu.cli", "route",
             "--backends=" + ",".join(socks), f"--socket={rsock}",
             "--poll-interval=0.2"],
            env=_serve_env(), stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE, text=True)
        procs.append(primary)
        assert wait_for_socket(rsock, 30)
        standby = subprocess.Popen(
            [sys.executable, "-m", "pwasm_tpu.cli", "route",
             f"--standby-of={rsock}", "--poll-interval=0.2"],
            env=_serve_env(), stdout=subprocess.DEVNULL,
            stderr=subprocess.PIPE, text=True)
        procs.append(standby)

        t = threading.Thread(target=pump, args=(rsock,), daemon=True)
        t.start()
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and len(done) < 2 \
                and not pump_err:
            time.sleep(0.05)
        assert not pump_err, pump_err
        assert len(done) >= 2, "traffic never established pre-kill"

        primary.kill()
        primary.wait(timeout=30)
        pre = len(done)
        # the pump keeps hammering the SAME address through the gap;
        # two completions past the kill prove the takeover end-to-end
        # from a client that never coordinated with it
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and len(done) < pre + 2 \
                and not pump_err:
            time.sleep(0.05)
        assert not pump_err, pump_err
        assert len(done) >= pre + 2, \
            f"traffic never resumed after takeover ({len(done)}/{pre})"
        stop.set()
        t.join(timeout=180)
        assert not t.is_alive(), "pump wedged"
        assert standby.poll() is None

        bad = [(tag, rc) for tag, rc in done if rc != 0]
        assert not bad, bad
        for tag, _ in done:
            assert (tmp_path / f"{tag}.dfa").read_bytes() == expect, tag
        with ServiceClient(rsock) as c:
            st = c.stats()["stats"]
            assert st["ha"]["takeover"] is True
            assert st["ha"]["epoch"] >= 2
            c.drain()
        assert standby.wait(timeout=120) == 0
        for i, s in enumerate(socks):
            with ServiceClient(s) as c:
                c.drain()
            assert procs[i].wait(timeout=120) == EXIT_PREEMPTED
    finally:
        stop.set()
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
            p.stderr.close()
        shutil.rmtree(d, ignore_errors=True)
