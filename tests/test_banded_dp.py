"""Banded DP kernel: exact score parity vs the full-matrix numpy Gotoh
oracle, batch/vmap behavior, band placement, and the Pallas variant."""

import numpy as np
import pytest

import jax.numpy as jnp

from pwasm_tpu.core.dna import encode
from pwasm_tpu.ops.banded_dp import (
    NEG,
    ScoreParams,
    band_dlo,
    banded_score,
    banded_scores_batch,
    banded_scores_pallas,
    full_gotoh_score,
)


def _mutate(rng, q, n_sub, n_ind):
    t = list(q)
    for _ in range(n_sub):
        p = rng.integers(0, len(t))
        t[p] = rng.integers(0, 4)
    for _ in range(n_ind):
        p = int(rng.integers(1, len(t) - 1))
        if rng.random() < 0.5:
            t.insert(p, rng.integers(0, 4))
        else:
            del t[p]
    return np.array(t, dtype=np.int8)


def test_identical_sequences():
    q = encode(b"ACGTACGTACGTACGT")
    score = int(banded_score(jnp.asarray(q), jnp.asarray(q),
                             jnp.int32(len(q)), band=16))
    assert score == len(q) * ScoreParams().match


def test_single_substitution():
    q = encode(b"ACGTACGTACGTACGT")
    t = q.copy()
    t[5] = (t[5] + 1) % 4
    p = ScoreParams()
    score = int(banded_score(jnp.asarray(q), jnp.asarray(t),
                             jnp.int32(len(t)), band=16))
    assert score == (len(q) - 1) * p.match - p.mismatch


def test_single_gap():
    q = encode(b"ACGTACGTACGTACGT")
    t = np.delete(q, 7)
    p = ScoreParams()
    score = int(banded_score(jnp.asarray(q), jnp.asarray(t),
                             jnp.int32(len(t)), band=16))
    assert score == (len(q) - 1) * p.match - p.go


@pytest.mark.parametrize("seed", range(10))
def test_matches_full_gotoh(seed):
    rng = np.random.default_rng(seed)
    m = int(rng.integers(20, 60))
    q = rng.integers(0, 4, size=m).astype(np.int8)
    t = _mutate(rng, q, n_sub=int(rng.integers(0, 5)),
                n_ind=int(rng.integers(0, 4)))
    n = len(t)
    band = 32
    expect = full_gotoh_score(q, t)
    pad = np.full(n + 8, 127, dtype=np.int8)
    pad[:n] = t
    got = int(banded_score(jnp.asarray(q), jnp.asarray(pad),
                           jnp.int32(n), band=band))
    assert got == expect, (seed, m, n)


def test_batch_vmap_matches_singles():
    rng = np.random.default_rng(42)
    q = rng.integers(0, 4, size=40).astype(np.int8)
    targets = []
    lens = []
    n_max = 56
    for _ in range(9):
        t = _mutate(rng, q, rng.integers(0, 4), rng.integers(0, 3))
        pad = np.full(n_max, 127, dtype=np.int8)
        pad[:len(t)] = t
        targets.append(pad)
        lens.append(len(t))
    ts = jnp.asarray(np.stack(targets))
    tl = jnp.asarray(np.array(lens, dtype=np.int32))
    batch = np.asarray(banded_scores_batch(jnp.asarray(q), ts, tl, band=32))
    for k in range(9):
        single = int(banded_score(jnp.asarray(q), ts[k], tl[k], band=32))
        assert batch[k] == single
        assert batch[k] == full_gotoh_score(q, targets[k][:lens[k]])


def test_band_too_narrow_raises():
    with pytest.raises(ValueError, match="band .* too narrow"):
        band_dlo(10, 100, 8)


def test_target_length_outside_band_is_neg():
    q = jnp.asarray(encode(b"ACGTACGT"))
    t = jnp.asarray(np.full(20, 127, dtype=np.int8))
    # band 16 over (m=8, n=20) covers diagonals [-2, 13];
    # t_len=4 implies end diagonal -4, outside the band -> NEG sentinel
    score = int(banded_score(q, t, jnp.int32(4), band=16))
    assert score == NEG


def test_pallas_matches_jax():
    rng = np.random.default_rng(7)
    q = rng.integers(0, 4, size=48).astype(np.int8)
    n_max = 64
    targets, lens = [], []
    for _ in range(12):
        t = _mutate(rng, q, rng.integers(0, 5), rng.integers(0, 3))
        pad = np.full(n_max, 127, dtype=np.int8)
        pad[:len(t)] = t
        targets.append(pad)
        lens.append(len(t))
    ts = jnp.asarray(np.stack(targets))
    tl = jnp.asarray(np.array(lens, dtype=np.int32))
    ref = np.asarray(banded_scores_batch(jnp.asarray(q), ts, tl, band=32))
    got = np.asarray(banded_scores_pallas(jnp.asarray(q), ts, tl, band=32,
                                          block_t=4))
    np.testing.assert_array_equal(got, ref)


def test_custom_score_params():
    p = ScoreParams(match=1, mismatch=3, gap_open=5, gap_extend=1)
    q = encode(b"ACGTACGTAC")
    t = np.delete(q, 4)
    got = int(banded_score(jnp.asarray(q), jnp.asarray(t),
                           jnp.int32(len(t)), band=16, params=p))
    assert got == full_gotoh_score(q, t, p)
    assert got == 9 * 1 - 6


def test_long_kernel_matches_batch():
    """HBM-streaming long-read kernel vs the scan path, chunk smaller than
    m so multiple DMA windows are exercised (plus the round-up tail)."""
    from pwasm_tpu.ops.banded_dp import banded_scores_long

    rng = np.random.default_rng(11)
    m, n, band = 200, 216, 32
    q = rng.integers(0, 4, size=m).astype(np.int8)
    T = 5
    ts = np.full((T, n), 127, dtype=np.int8)
    t_lens = np.zeros(T, dtype=np.int32)
    for k in range(T):
        t = list(q)
        for _ in range(int(rng.integers(0, 6))):
            t[int(rng.integers(0, len(t)))] = int(rng.integers(0, 4))
        for _ in range(int(rng.integers(0, 3))):
            p = int(rng.integers(1, len(t) - 1))
            if rng.random() < 0.5:
                t.insert(p, int(rng.integers(0, 4)))
            else:
                del t[p]
        ts[k, :len(t)] = t
        t_lens[k] = len(t)
    got = np.asarray(banded_scores_long(
        jnp.asarray(q), jnp.asarray(ts), jnp.asarray(t_lens),
        band=band, block_t=8, chunk=64))
    expect = np.asarray(banded_scores_batch(
        jnp.asarray(q), jnp.asarray(ts), jnp.asarray(t_lens), band=band))
    np.testing.assert_array_equal(got, expect)


def test_long_kernel_interior_pairs():
    """Small chunk so whole DMA pairs fall in the interior phase (the
    statically mask-elided bodies) — the masked/interior/masked pair
    split must be bit-exact across the phase boundaries."""
    from pwasm_tpu.ops.banded_dp import band_dlo, banded_scores_long

    rng = np.random.default_rng(13)
    m, n, band, chunk = 256, 280, 32, 32
    # sanity: this geometry really exercises interior pairs
    dlo = band_dlo(m, n, band)
    head = min(max(0, -dlo), m)
    int_end = max(head, min(m, n - band - dlo + 1))
    n_chunks = (m + chunk - 1) // chunk
    ok = [c * chunk >= head and (c + 1) * chunk <= int_end
          for c in range(n_chunks)]
    assert any(ok[2 * c] and ok[2 * c + 1]
               for c in range((n_chunks + 1) // 2 - 1)), \
        "geometry no longer covers interior pairs; adjust the test"
    q = rng.integers(0, 4, size=m).astype(np.int8)
    T = 7
    ts = np.full((T, n), 127, dtype=np.int8)
    t_lens = np.zeros(T, dtype=np.int32)
    for k in range(T):
        t = list(q)
        for _ in range(int(rng.integers(0, 8))):
            t[int(rng.integers(0, len(t)))] = int(rng.integers(0, 4))
        for _ in range(int(rng.integers(0, 4))):
            p = int(rng.integers(0, len(t)))
            if rng.random() < 0.5:
                t.insert(p, int(rng.integers(0, 4)))
            elif len(t) > 1:
                del t[p]
        t = t[:n]
        ts[k, :len(t)] = t
        t_lens[k] = len(t)
    got = np.asarray(banded_scores_long(
        jnp.asarray(q), jnp.asarray(ts), jnp.asarray(t_lens),
        band=band, block_t=8, chunk=chunk))
    expect = np.asarray(banded_scores_batch(
        jnp.asarray(q), jnp.asarray(ts), jnp.asarray(t_lens), band=band))
    np.testing.assert_array_equal(got, expect)


def test_long_kernel_single_chunk():
    """chunk >= m: one DMA window, still exact."""
    from pwasm_tpu.ops.banded_dp import banded_scores_long

    rng = np.random.default_rng(12)
    m, n, band = 40, 56, 32
    q = rng.integers(0, 4, size=m).astype(np.int8)
    ts = np.full((3, n), 127, dtype=np.int8)
    t_lens = np.array([m, m - 2, m + 4], dtype=np.int32)
    ts[0, :m] = q
    ts[1, :m - 2] = q[:m - 2]
    t2 = list(q)
    for p in (5, 15, 25, 30):
        t2.insert(p, 2)
    ts[2, :len(t2)] = t2
    got = np.asarray(banded_scores_long(
        jnp.asarray(q), jnp.asarray(ts), jnp.asarray(t_lens),
        band=band, block_t=8, chunk=128))
    expect = np.asarray(banded_scores_batch(
        jnp.asarray(q), jnp.asarray(ts), jnp.asarray(t_lens), band=band))
    np.testing.assert_array_equal(got, expect)


def test_numpy_banded_gotoh_bench_fallback_matches():
    # bench.py's nativeless parity reference must agree with the jax path
    import bench as B

    rng = np.random.default_rng(21)
    m, n, band = 40, 48, 16
    params = ScoreParams()
    dlo = band_dlo(m, n, band)
    q = rng.integers(0, 4, size=m).astype(np.int8)
    for _ in range(5):
        t_len = int(rng.integers(m - 4, n + 1))
        t = np.full(n, 127, dtype=np.int8)
        t[:t_len] = rng.integers(0, 4, size=t_len)
        expect = int(np.asarray(banded_score(
            jnp.asarray(q), jnp.asarray(t), jnp.int32(t_len), band=band)))
        got = B._numpy_banded_gotoh(q, t, t_len, band, dlo, params)
        assert got == expect


def test_packed_scores_match_unpacked():
    from pwasm_tpu.ops.pack import (banded_scores_packed, pack_targets,
                                    unpack_targets_device)

    rng = np.random.default_rng(22)
    m, n, band, T = 32, 40, 16, 9
    q = rng.integers(0, 4, size=m).astype(np.int8)
    ts = np.full((T, n), 127, dtype=np.int8)
    t_lens = np.zeros(T, dtype=np.int32)
    for k in range(T):
        t_len = int(rng.integers(m - 4, n + 1))
        ts[k, :t_len] = rng.integers(0, 4, size=t_len)
        t_lens[k] = t_len
    packed = pack_targets(ts)  # 127 pad accepted, packs as 'A'
    assert packed.shape == (T, n // 4)
    # device unpack restores codes (pad positions become 0, harmless)
    codes = np.asarray(unpack_targets_device(jnp.asarray(packed), n))
    np.testing.assert_array_equal(
        codes, np.where(ts == 127, 0, ts))
    got = np.asarray(banded_scores_packed(
        jnp.asarray(q), jnp.asarray(packed), n, jnp.asarray(t_lens),
        band=band))
    expect = np.asarray(banded_scores_batch(
        jnp.asarray(q), jnp.asarray(ts), jnp.asarray(t_lens), band=band))
    np.testing.assert_array_equal(got, expect)


def test_pack_targets_rejects_n_codes():
    from pwasm_tpu.ops.pack import pack_targets

    bad = np.array([[0, 1, 4, 2]], dtype=np.int8)  # an N inside the row
    with pytest.raises(ValueError):
        pack_targets(bad)
