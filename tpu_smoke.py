#!/usr/bin/env python
"""Pallas kernel lowering smoke: run every Pallas kernel ONCE with
``PWASM_DEVICE_INTERPRET=0`` (compiled Mosaic lowering, no interpreter)
on the default backend and print one JSON line of per-kernel pass/fail.

Interpreter-mode tests (the CPU suite) validate kernel *semantics* but
cannot catch a Mosaic lowering break (VERDICT r1 weak #2); this script
exists so a real chip run has an explicit, cheap lowering gate:

    python tpu_smoke.py          # on TPU: compiled lowering of all kernels

Off-TPU it still runs, but Mosaic lowering of Pallas TPU kernels does
not exist on CPU, so there the kernels keep interpreter mode (the JSON
marks ``interpret_forced_off: false``) and the run is only a semantic
check.  Exit code 0 iff every kernel passed.
"""

from __future__ import annotations

import json
import os
import sys
import traceback

import numpy as np


def _workload(T=256, m=192, band=64, seed=0):
    rng = np.random.default_rng(seed)
    q = rng.integers(0, 4, size=m).astype(np.int8)
    n = m + band // 2
    ts = np.full((T, n), 127, dtype=np.int8)
    t_lens = np.zeros(T, dtype=np.int32)
    for k in range(T):
        t = list(q)
        for _ in range(int(rng.integers(0, 10))):
            t[int(rng.integers(0, len(t)))] = int(rng.integers(0, 4))
        ts[k, :len(t)] = t
        t_lens[k] = len(t)
    return q, ts, t_lens


def _probe_backend_bounded() -> tuple[bool, str]:
    """The tunnel backend can hang indefinitely when unhealthy; reuse
    bench.py's bounded subprocess probe (one shared implementation),
    with the same two-attempt retry its _resolve_backend uses because
    tunnel errors are documented as transient.  Returns (healthy,
    diagnostic-from-the-last-attempt)."""
    from bench import _probe_backend

    try:
        t = float(os.environ.get("PWASM_BENCH_PROBE_TIMEOUT", "150"))
    except ValueError:
        t = 150.0
    why = ""
    for _attempt in range(2):
        platform, why = _probe_backend(dict(os.environ), t)
        if platform is not None:
            return True, ""
    return False, why


def main() -> int:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    healthy, why = _probe_backend_bounded()
    if not healthy:
        # backend_down is the STRUCTURED signal bench.py's run-all keys
        # on to pre-pin config children to CPU (don't rely on wording)
        print(json.dumps({"smoke": "pallas_lowering", "ok": False,
                          "backend_down": True,
                          "error": "jax backend unreachable "
                                   f"(tunnel down?): {why}"}))
        return 1

    import jax.numpy as jnp

    from pwasm_tpu.ops import on_tpu_backend

    on_tpu = on_tpu_backend()
    if on_tpu:  # force compiled Mosaic lowering — the point of the smoke
        os.environ["PWASM_DEVICE_INTERPRET"] = "0"
    from pwasm_tpu.ops.banded_dp import (banded_scores_batch,
                                         banded_scores_long,
                                         banded_scores_pallas)
    from pwasm_tpu.ops.consensus import consensus_pallas, consensus_votes
    from pwasm_tpu.ops.pack import banded_scores_packed, pack_targets
    from pwasm_tpu.parallel.many2many import (many2many_scores,
                                              many2many_scores_pallas)

    band = 64
    q, ts, t_lens = _workload(band=band)
    qd, tsd, tld = jnp.asarray(q), jnp.asarray(ts), jnp.asarray(t_lens)
    want = np.asarray(banded_scores_batch(qd, tsd, tld, band=band))

    rng = np.random.default_rng(1)
    # codes -3..8: the compiled kernel must treat every code outside
    # [0, 6) — negatives, PAD_CODE 6 and beyond — as no-contribution
    # exactly like the interpreter (round-3 leftover: this was
    # interpreter-tested only)
    pileup = rng.integers(-3, 9, size=(64, 1024)).astype(np.int8)
    want_votes = np.asarray(consensus_votes(jnp.asarray(pileup)))
    want_counts = np.stack([(pileup == k).sum(0) for k in range(6)], 1)

    qs2 = np.stack([q, np.roll(q, 3)])
    want_m2m = np.asarray(many2many_scores(jnp.asarray(qs2), tsd, tld,
                                           band=band))

    def dp_pallas():
        got = np.asarray(banded_scores_pallas(qd, tsd, tld, band=band))
        assert np.array_equal(got, want), "score mismatch"

    def dp_long():
        # chunk=32 so at least one DMA pair falls in the statically
        # mask-elided interior phase (m=192, n=224, band=64 puts chunks
        # 2-3 inside [head, int_end)) — the interior bodies must both
        # lower AND execute on hardware, not just compile
        got = np.asarray(banded_scores_long(qd, tsd, tld, band=band,
                                            chunk=32))
        assert np.array_equal(got, want), "score mismatch"

    def dp_packed():
        tsp = jnp.asarray(pack_targets(ts))
        got = np.asarray(banded_scores_packed(qd, tsp, ts.shape[1], tld,
                                              band=band))
        assert np.array_equal(got, want), "score mismatch"

    def consensus():
        votes, counts = consensus_pallas(jnp.asarray(pileup))
        assert np.array_equal(np.asarray(votes), want_votes), \
            "vote mismatch"
        assert np.array_equal(np.asarray(counts), want_counts), \
            "count mismatch (out-of-range code handling)"

    def refine_clip():
        # the device X-drop phase program (XLA, not Pallas) end-to-end
        # on the chip vs the host batch pass
        from pwasm_tpu.align.gapseq import GapSeq, refine_clipping_batch
        r = np.random.default_rng(5)
        base = r.choice(list(b"ACGT"), 400).astype(np.uint8)
        def mk():
            out = []
            rr = np.random.default_rng(6)
            for k in range(16):
                arr = base.copy()
                arr[rr.integers(0, 400, 8)] = rr.choice(list(b"ACGT"), 8)
                s = GapSeq(f"r{k}", "", bytes(arr))
                s.clp5 = int(rr.integers(1, 12))
                s.clp3 = int(rr.integers(1, 12))
                for _ in range(3):
                    s.set_gap(int(rr.integers(0, 400)), 1)
                out.append(s)
            return out
        dev, host = mk(), mk()
        assert refine_clipping_batch(dev, bytes(base), [0] * 16,
                                     device=True) == 0, "device demoted"
        refine_clipping_batch(host, bytes(base), [0] * 16)
        for a, b in zip(dev, host):
            assert (a.clp5, a.clp3) == (b.clp5, b.clp3), "clip mismatch"

    def m2m():
        got = np.asarray(many2many_scores_pallas(jnp.asarray(qs2), tsd,
                                                 tld, band=band))
        assert np.array_equal(got, want_m2m), "score mismatch"

    def realign(kernel="pallas"):
        from pwasm_tpu.ops.realign import banded_realign_rows
        qs = np.broadcast_to(q, (ts.shape[0], len(q))).copy()
        qls = np.full(ts.shape[0], len(q), dtype=np.int32)
        ref = banded_realign_rows(qs, ts, qls, t_lens, band=band,
                                  kernel="xla")
        got = banded_realign_rows(qs, ts, qls, t_lens, band=band,
                                  kernel=kernel)
        for name, a, b in zip(("scores", "leads", "iy", "ops", "ok"),
                              ref, got):
            assert np.array_equal(np.asarray(a), np.asarray(b)), \
                f"{name} mismatch"

    kernels = {"banded_scores_pallas": dp_pallas,
               "banded_scores_long": dp_long,
               "banded_scores_packed": dp_packed,
               "consensus_pallas": consensus,
               "many2many_scores_pallas": m2m,
               "realign_fwdptr_walk_pallas": realign,
               "realign_fwdptr_streaming_pallas":
                   lambda: realign("pallas_long"),
               "refine_clip_device": refine_clip}
    results = {}
    for name, fn in kernels.items():
        try:
            fn()
            results[name] = "pass"
        except Exception as e:
            results[name] = f"fail: {type(e).__name__}: {e}"
            traceback.print_exc()
    ok = all(v == "pass" for v in results.values())
    print(json.dumps({"smoke": "pallas_lowering",
                      "backend": "tpu" if on_tpu else "other",
                      "interpret_forced_off": on_tpu,
                      "kernels": results, "ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
